"""Packed-sequence (segment-masked) attention: every implementation —
plain, flash (pallas interpret), ring (8-device cpu mesh) — must agree with
an UNPACKED reference forward pass sequence-by-sequence, which is the whole
point of the segment-id fence: packing is a batching optimization, never a
numerics change."""

import os

import numpy as np
import pytest

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax
import jax.numpy as jnp

from tensorflowonspark_tpu.ops.flash_attention import flash_attention
from tensorflowonspark_tpu.parallel.ring_attention import (
    plain_attention,
    ring_attention_sharded,
)


def _packed_case(b=2, h=2, l=64, d=16, seed=0, segs=(11, 7, 20)):
    """Random q/k/v plus a packed layout: each batch row holds len(segs)
    sequences back-to-back (ids 1..n), zero-padded tail (id 0)."""
    rng = np.random.default_rng(seed)
    q, k, v = (
        jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.float32) for _ in range(3)
    )
    seg = np.zeros((b, l), np.int32)
    off = 0
    spans = []
    for i, n in enumerate(segs, start=1):
        seg[:, off : off + n] = i
        spans.append((off, off + n))
        off += n
    assert off <= l
    return q, k, v, jnp.asarray(seg), spans


def _unpacked_reference(q, k, v, seg, spans, causal):
    """Run plain attention per sequence slice and re-assemble the packed
    layout — the oracle every masked implementation must match on the
    non-pad positions."""
    out = np.zeros(q.shape[:2] + (q.shape[2], v.shape[3]), np.float32)
    for lo, hi in spans:
        piece = plain_attention(
            q[:, :, lo:hi], k[:, :, lo:hi], v[:, :, lo:hi], causal=causal
        )
        out[:, :, lo:hi] = np.asarray(piece)
    return out, np.asarray(seg) > 0


@pytest.mark.parametrize("causal", [False, True])
def test_plain_segment_mask_matches_unpacked(causal):
    q, k, v, seg, spans = _packed_case()
    ref, real = _unpacked_reference(q, k, v, seg, spans, causal)
    out = np.asarray(plain_attention(q, k, v, causal=causal, segment_ids=seg))
    np.testing.assert_allclose(out[:, :, real[0]], ref[:, :, real[0]], atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_segment_mask_matches_unpacked(causal):
    q, k, v, seg, spans = _packed_case(seed=1)
    ref, real = _unpacked_reference(q, k, v, seg, spans, causal)
    out = np.asarray(
        flash_attention(
            q, k, v, causal=causal, segment_ids=seg,
            block_q=16, block_k=16, interpret=True,
        )
    )
    np.testing.assert_allclose(out[:, :, real[0]], ref[:, :, real[0]], atol=2e-5)


def test_flash_segment_gradients_match_masked_plain():
    q, k, v, seg, spans = _packed_case(seed=2)

    def loss_flash(q, k, v):
        o = flash_attention(
            q, k, v, causal=True, segment_ids=seg,
            block_q=16, block_k=16, interpret=True,
        )
        return (o ** 2).sum()

    def loss_plain(q, k, v):
        return (plain_attention(q, k, v, causal=True, segment_ids=seg) ** 2).sum()

    gf = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    gp = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gf, gp):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=5e-4)


def test_flash_unsegmented_path_unchanged():
    # segment_ids=None must stay the exact pre-existing kernel path
    q, k, v, _seg, _spans = _packed_case(seed=3)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16, interpret=True)
    ref = plain_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_segment_mask_matches_unpacked(causal):
    from tensorflowonspark_tpu import parallel

    if jax.device_count() < 8:
        pytest.skip("needs 8 cpu devices (XLA_FLAGS set too late)")
    mesh = parallel.local_mesh({"dp": 2, "sp": 4})
    q, k, v, seg, spans = _packed_case(b=4, seed=4)
    ref, real = _unpacked_reference(q, k, v, seg, spans, causal)
    out = np.asarray(
        ring_attention_sharded(q, k, v, mesh, causal=causal, segment_ids=seg)
    )
    np.testing.assert_allclose(out[:, :, real[0]], ref[:, :, real[0]], atol=2e-5)


class TestRingEdgeGeometry:
    """Ring attention at awkward geometry: ring size ≥ 3, sequence length
    not divisible by the ring, whole trailing shards that are pure padding.
    The pad-to-ring-multiple path must stay exact against the same
    packed-vs-unpacked oracle (and plain attention where nothing is
    packed)."""

    def _mesh(self, axes):
        from tensorflowonspark_tpu import parallel

        if jax.device_count() < 8:
            pytest.skip("needs 8 cpu devices")
        return parallel.local_mesh(axes)

    @pytest.mark.parametrize("causal", [False, True])
    def test_nondivisible_length_matches_plain(self, causal):
        # L=30 on an 8-ring: pad 2, slice back — exact in both mask modes
        mesh = self._mesh({"sp": 8})
        rng = np.random.default_rng(9)
        q, k, v = (
            jnp.asarray(rng.standard_normal((2, 2, 30, 16)), jnp.float32)
            for _ in range(3)
        )
        ref = plain_attention(q, k, v, causal=causal)
        out = ring_attention_sharded(q, k, v, mesh, causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_packed_nondivisible_matches_unpacked(self, causal):
        mesh = self._mesh({"sp": 8})
        q, k, v, seg, spans = _packed_case(b=2, l=30, seed=5, segs=(11, 7, 9))
        ref, real = _unpacked_reference(q, k, v, seg, spans, causal)
        out = np.asarray(
            ring_attention_sharded(q, k, v, mesh, causal=causal, segment_ids=seg)
        )
        assert np.all(np.isfinite(out))
        np.testing.assert_allclose(out[:, :, real[0]], ref[:, :, real[0]], atol=2e-5)

    def test_all_pad_trailing_shards(self):
        # real tokens end at 18 of 32: on an 8-ring the last 3 local blocks
        # are pure padding — outputs stay finite, real positions exact
        mesh = self._mesh({"sp": 8})
        q, k, v, seg, spans = _packed_case(b=2, l=32, seed=6, segs=(11, 7))
        ref, real = _unpacked_reference(q, k, v, seg, spans, True)
        out = np.asarray(
            ring_attention_sharded(q, k, v, mesh, causal=True, segment_ids=seg)
        )
        assert np.all(np.isfinite(out))
        np.testing.assert_allclose(out[:, :, real[0]], ref[:, :, real[0]], atol=2e-5)

    def test_nondivisible_gradients_match_plain(self):
        mesh = self._mesh({"dp": 2, "sp": 4})
        rng = np.random.default_rng(10)
        q, k, v = (
            jnp.asarray(rng.standard_normal((2, 2, 30, 16)), jnp.float32)
            for _ in range(3)
        )

        def ring_loss(q, k, v):
            return (ring_attention_sharded(q, k, v, mesh, causal=True) ** 2).sum()

        def plain_loss(q, k, v):
            return (plain_attention(q, k, v, causal=True) ** 2).sum()

        gr = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
        gp = jax.grad(plain_loss, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, gp):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


class TestTransformerPacked:
    """Model-level equivalence: packed [1 row: s1+s2] logits must equal the
    per-sequence unpacked forward passes, for every attention impl, and the
    segment-masked LM loss must train (finite grads)."""

    CFG = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=2, d_ff=64,
               dtype="float32")

    def _packed_batch(self, rows=2, l=24):
        rng = np.random.default_rng(3)
        s1 = rng.integers(3, 64, 11).astype(np.int32)
        s2 = rng.integers(3, 64, 7).astype(np.int32)
        tokens = np.zeros((rows, l), np.int32)
        seg = np.zeros((rows, l), np.int32)
        pos = np.zeros((rows, l), np.int32)
        tokens[:, :11] = s1
        seg[:, :11] = 1
        pos[:, :11] = np.arange(11)
        tokens[:, 11:18] = s2
        seg[:, 11:18] = 2
        pos[:, 11:18] = np.arange(7)
        return s1, s2, tokens, seg, pos

    @pytest.mark.parametrize("impl", ["plain", "flash", "ring"])
    def test_packed_logits_match_unpacked(self, impl):
        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import transformer

        if impl == "ring" and jax.device_count() < 8:
            pytest.skip("needs 8 cpu devices")
        s1, s2, tokens, seg, pos = self._packed_batch()
        plain = transformer.create_model(attention="plain", **self.CFG)
        params = plain.init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))[
            "params"
        ]
        l1 = plain.apply({"params": params}, jnp.asarray(s1[None]))
        l2 = plain.apply({"params": params}, jnp.asarray(s2[None]))
        mesh = parallel.local_mesh({"dp": 2, "sp": 4}) if impl == "ring" else None
        model = transformer.create_model(mesh=mesh, attention=impl, **self.CFG)
        lp = model.apply(
            {"params": params}, jnp.asarray(tokens),
            positions=jnp.asarray(pos), segment_ids=jnp.asarray(seg),
        )
        np.testing.assert_allclose(
            np.asarray(lp[0, :11]), np.asarray(l1[0]), atol=2e-5
        )
        np.testing.assert_allclose(
            np.asarray(lp[0, 11:18]), np.asarray(l2[0]), atol=2e-5
        )

    def test_packed_loss_masks_pad_and_boundaries(self):
        from tensorflowonspark_tpu.models import transformer

        _s1, _s2, tokens, seg, pos = self._packed_batch()
        model = transformer.create_model(attention="plain", **self.CFG)
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32))[
            "params"
        ]
        loss_fn = transformer.make_loss_fn(model)
        batch = {
            "tokens": jnp.asarray(tokens),
            "segment_ids": jnp.asarray(seg),
            "positions": jnp.asarray(pos),
        }
        (loss, metrics), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, batch
        )
        assert np.isfinite(float(loss))
        assert all(bool(jnp.isfinite(g).all()) for g in jax.tree.leaves(grads))
        # target mask excludes pad AND the cross-sequence boundary position:
        # (seq_len-1) - (intra-segment transitions) of the 23 shifted slots
        # are masked; the loss must not average over them. Proxy check: the
        # same batch with the pad tail re-labeled as real tokens must move
        # the loss (the mask was doing work).
        tokens2 = tokens.copy()
        tokens2[:, 18:] = 5
        seg2 = seg.copy()
        seg2[:, 18:] = 3
        batch2 = {
            "tokens": jnp.asarray(tokens2),
            "segment_ids": jnp.asarray(seg2),
            "positions": jnp.asarray(pos),
        }
        loss2, _ = loss_fn(params, batch2)
        assert abs(float(loss2) - float(loss)) > 1e-6
