"""Shared-memory feed-chunk tests: columnar layout, fallback rules, segment
lifecycle, and the DataFeed integration (VERDICT r2 item 3)."""

import os

import numpy as np
import pytest

from tensorflowonspark_tpu.shm import NAME_PREFIX, ShmChunk, unlink_leaked


def _segments():
    shm_dir = "/dev/shm"
    if not os.path.isdir(shm_dir):
        return set()
    return {f for f in os.listdir(shm_dir) if f.startswith(NAME_PREFIX)}


def test_tuple_rows_roundtrip_and_unlink():
    rows = [([float(i)] * 8, i % 3) for i in range(50)]
    before = _segments()
    chunk = ShmChunk.from_rows(rows)
    assert chunk is not None
    assert len(chunk) == 50
    assert _segments() - before, "segment should exist before materialize"
    out = chunk.rows()
    assert _segments() == before, "segment should be unlinked after materialize"
    assert len(out) == 50
    np.testing.assert_allclose(np.asarray(out[7][0]), [7.0] * 8)
    assert int(out[7][1]) == 1


def test_single_column_vector_rows():
    """784-float rows are ONE logical field, not 784 columns."""
    rows = [[float(i)] * 784 for i in range(20)]
    chunk = ShmChunk.from_rows(rows)
    assert chunk is not None
    assert chunk.single
    assert len(chunk.columns) == 1
    dtype, shape, _off = chunk.columns[0]
    assert shape == (20, 784)
    out = chunk.rows()
    np.testing.assert_allclose(np.asarray(out[3]), [3.0] * 784)


def test_wide_scalar_rows_with_mixed_kinds_stay_multi():
    """A 20-field row of 19 floats + 1 int label must NOT collapse into one
    float64 column (the label dtype must survive the lane)."""
    rows = [tuple([float(i)] * 19 + [i]) for i in range(8)]
    chunk = ShmChunk.from_rows(rows)
    assert chunk is not None
    assert not chunk.single
    assert len(chunk.columns) == 20
    out = chunk.rows()
    assert np.asarray(out[3][19]).dtype.kind == "i"


def test_wide_uniform_scalar_rows_are_single_column():
    rows = [[float(i)] * 784 for i in range(4)]
    chunk = ShmChunk.from_rows(rows)
    assert chunk is not None and chunk.single


def test_scalar_rows():
    chunk = ShmChunk.from_rows(list(range(10)))
    assert chunk is not None and chunk.single
    assert [int(v) for v in chunk.rows()] == list(range(10))


def test_non_numeric_rows_fall_back():
    assert ShmChunk.from_rows(["a", "b"]) is None
    assert ShmChunk.from_rows([("x", 1), ("y", 2)]) is None
    assert ShmChunk.from_rows([(b"raw", 1)]) is None
    # ragged rows
    assert ShmChunk.from_rows([([1, 2], 0), ([1, 2, 3], 1)]) is None
    assert ShmChunk.from_rows([]) is None


def test_discard_unlinks_without_reading():
    chunk = ShmChunk.from_rows([(1.0, 2.0)])
    before = _segments()
    assert any(chunk.name in s for s in before)
    chunk.discard()
    assert chunk.name not in _segments()
    chunk.discard()  # idempotent


def test_unlink_leaked_age_gate(tmp_path, monkeypatch):
    # isolate the janitor's namespace: /dev/shm is shared with concurrent
    # xdist workers' live feed segments and with stale leaks from other
    # (killed) runs — scan/reap only this test's own prefix
    import os

    import tensorflowonspark_tpu.shm as shm_mod

    monkeypatch.setattr(shm_mod, "NAME_PREFIX", "tosfeedtest{}_".format(os.getpid()))
    chunk = ShmChunk.from_rows([(1.0, 2.0)])
    try:
        # too young: janitor must not touch it (membership checked against
        # the raw dir: _segments() filters by the UNPATCHED prefix)
        assert unlink_leaked(max_age_secs=3600) == 0
        assert chunk.name in os.listdir("/dev/shm")
        # old enough: reaped
        assert unlink_leaked(max_age_secs=0) >= 1
        assert chunk.name not in os.listdir("/dev/shm")
    finally:
        chunk.discard()


def test_datafeed_consumes_shm_chunks():
    """DataFeed serves ShmChunk rows with deferred task_done, same as a
    pickled Chunk; as_numpy gives device-put-ready columns."""
    from tensorflowonspark_tpu import TFManager
    from tensorflowonspark_tpu.TFNode import DataFeed

    mgr = TFManager.start(b"shm-test", ["input", "output"], mode="local")
    try:
        q = mgr.get_queue("input")
        rows = [([float(i)] * 4, i) for i in range(6)]
        q.put(ShmChunk.from_rows(rows[:4]))
        q.put(ShmChunk.from_rows(rows[4:]))
        q.put(None)

        feed = DataFeed(mgr, train_mode=False, input_mapping={"a": "x", "b": "y"})
        batch = feed.next_batch(5, as_numpy=True)
        assert set(batch) == {"x", "y"}
        assert batch["x"].shape == (5, 4)
        np.testing.assert_allclose(batch["x"][2], [2.0] * 4)
        rest = feed.next_batch(5, as_numpy=True)  # 1 pending row + end-of-feed
        assert rest["x"].shape == (1, 4)
        assert feed.should_stop()
        assert q.unfinished() == 0, "deferred task_done must fully drain"
    finally:
        mgr.shutdown()


def test_datafeed_columnar_fast_lane_slices_across_boundaries():
    """as_numpy+mapping consumers get column SLICES (no row objects) even
    when batch boundaries cut through shm chunks, with correct ordering and
    values across chunk joins; a pickled Chunk interleaved mid-stream merges
    into the same output columns."""
    from tensorflowonspark_tpu import TFManager
    from tensorflowonspark_tpu.TFNode import DataFeed
    from tensorflowonspark_tpu.marker import Chunk

    mgr = TFManager.start(b"shm-colfast", ["input", "output"], mode="local")
    try:
        q = mgr.get_queue("input")
        rows = [([float(i)] * 3, i) for i in range(10)]
        q.put(ShmChunk.from_rows(rows[:6]))
        q.put(Chunk(rows[6:8]))  # pickled rows interleave
        q.put(ShmChunk.from_rows(rows[8:]))
        q.put(None)
        feed = DataFeed(mgr, train_mode=False, input_mapping={"a": "x", "b": "y"})
        b1 = feed.next_batch(4, as_numpy=True)   # slice of chunk 1
        b2 = feed.next_batch(5, as_numpy=True)   # chunk1 tail + pickled + chunk2 head
        b3 = feed.next_batch(4, as_numpy=True)   # chunk2 tail + end-of-feed
        assert b1["x"].shape == (4, 3) and b2["x"].shape == (5, 3) and b3["x"].shape == (1, 3)
        got = np.concatenate([b["y"] for b in (b1, b2, b3)])
        np.testing.assert_array_equal(got, np.arange(10))
        np.testing.assert_allclose(b2["x"][0], [4.0] * 3)
        assert feed.should_stop()
        assert q.unfinished() == 0
    finally:
        mgr.shutdown()


def test_datafeed_plain_consumer_gets_python_types():
    """Without as_numpy, the shm lane delivers the exact Python types the
    feeder saw — no silent list→ndarray / int→np.int64 changes inside user
    main_fun code."""
    from tensorflowonspark_tpu import TFManager
    from tensorflowonspark_tpu.TFNode import DataFeed

    mgr = TFManager.start(b"shm-test-py", ["input", "output"], mode="local")
    try:
        q = mgr.get_queue("input")
        rows = [([1.0, 2.0, 3.0], 7), ([4.0, 5.0, 6.0], 8)]
        q.put(ShmChunk.from_rows(rows))
        q.put(None)
        feed = DataFeed(mgr, train_mode=False)
        batch = feed.next_batch(4)
        assert len(batch) == 2
        assert isinstance(batch[0][0], list) and batch[0][0] == [1.0, 2.0, 3.0]
        assert type(batch[0][1]) is int and batch[0][1] == 7
        import json as _json

        _json.dumps(batch)  # fully JSON-serializable, as pickled rows were
    finally:
        mgr.shutdown()


def test_datafeed_terminate_discards_unread_segments():
    from tensorflowonspark_tpu import TFManager
    from tensorflowonspark_tpu.TFNode import DataFeed

    mgr = TFManager.start(b"shm-test2", ["input", "output"], mode="local")
    try:
        q = mgr.get_queue("input")
        chunk = ShmChunk.from_rows([(1.0, 2)] * 10)
        q.put(chunk)
        feed = DataFeed(mgr, train_mode=False)
        feed.terminate()
        assert chunk.name not in _segments()
    finally:
        mgr.shutdown()


def test_feeder_tasks_use_shm_lane():
    """_put_rows ships numeric rows via shared memory and falls back for
    non-numeric; the message on the queue proves which lane was taken."""
    from tensorflowonspark_tpu import TFManager, TFSparkNode
    from tensorflowonspark_tpu.marker import Chunk

    mgr = TFManager.start(b"shm-test3", ["input"], mode="local")
    try:
        q = mgr.get_queue("input")
        TFSparkNode._put_rows(q, [(1.0, 2), (3.0, 4)])
        item = q.get()
        q.task_done()
        assert isinstance(item, ShmChunk)
        item.discard()
        TFSparkNode._put_rows(q, [("s", 1)])
        item = q.get()
        q.task_done()
        assert isinstance(item, Chunk)
    finally:
        mgr.shutdown()


def test_no_resource_tracker_keyerror_spam():
    """materialize()/discard() must not double-unregister: CPython registers
    a segment with the resource_tracker on ATTACH too, and ``unlink()``
    already unregisters it — an extra manual unregister after unlink made
    the tracker's ``cache.remove()`` raise KeyError tracebacks into every
    consumer process's stderr (the MULTICHIP_r04 log spam, VERDICT r4)."""
    import subprocess
    import sys

    script = """
import sys
sys.path.insert(0, {repo!r})
import numpy as np
from tensorflowonspark_tpu.shm import ShmChunk

for i in range(5):
    chunk = ShmChunk.from_rows(
        [(np.arange(4, dtype=np.float32) + j, j % 3) for j in range(32)]
    )
    assert chunk is not None
    assert len(chunk.rows()) == 32
chunk = ShmChunk.from_rows([(1.0, 2)] * 8)
chunk.discard()
chunk.discard()  # double-discard: second attach fails cleanly
print("SHM_TRACKER_OK")
""".format(repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    # the tracker process inherits stderr, so run() only returns once the
    # tracker has drained and closed it — any KeyError spam is captured
    proc = subprocess.run(
        [sys.executable, "-c", script], capture_output=True, text=True, timeout=120
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "SHM_TRACKER_OK" in proc.stdout
    assert "KeyError" not in proc.stderr, proc.stderr[-2000:]
    assert "resource_tracker" not in proc.stderr, proc.stderr[-2000:]
