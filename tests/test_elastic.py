"""The recovery ladder: classification, blacklisting, shrink-to-fit.

Unit coverage for :mod:`tensorflowonspark_tpu.elastic` (ledger arithmetic,
failure classification, the min_workers floor, blacklist-aware templates,
reservation-server attribution/refusal, the preflight gate) plus the
end-to-end elasticity story: chaos ``node.kill`` takes a worker down
mid-training twice → the ledger blacklists it → the relaunch shrinks to the
surviving capacity → ``ckpt.reshard_restore`` resumes the trajectory on the
smaller mesh → training completes, with the recovery counters visible in the
merged cluster metrics snapshot."""

import json
import os
import socket
import time

import pytest

from tensorflowonspark_tpu import TFCluster, chaos, control, elastic, reservation
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext
from tensorflowonspark_tpu.reservation import MessageSocket

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


# -- classification ------------------------------------------------------------


class TestClassifyFailure:
    def test_reservation_timeout_carries_missing_ids(self):
        err = reservation.ReservationError("timed out", missing=[2, 3])
        wrapper = RuntimeError("cluster attempt failed")
        wrapper.__cause__ = err
        event = elastic.classify_failure(wrapper)
        assert event.kind == "reservation_timeout"
        assert event.executor_ids == [2, 3]

    def test_heartbeat_loss_attributed_via_role_map(self):
        exc = RuntimeError(
            "cluster failed: node worker:1 stopped heartbeating for 31s "
            "without a final status (child killed?)"
        )
        event = elastic.classify_failure(exc, role_map={"worker:1": 4})
        assert event.kind == "heartbeat_loss"
        assert event.executor_ids == [4]

    def test_signal_exit_is_node_exit(self):
        exc = RuntimeError("node worker:0 failed (exit -9):\n<no output>")
        event = elastic.classify_failure(exc, role_map={"worker:0": 0})
        assert event.kind == "node_exit"
        assert event.executor_ids == [0]

    def test_user_error_exit_is_node_error_not_loss(self):
        exc = RuntimeError("node worker:0 failed (exit 1):\nTraceback ...")
        event = elastic.classify_failure(exc, role_map={"worker:0": 0})
        assert event.kind == "node_error"
        assert event.kind not in elastic.LOSS_KINDS

    def test_lease_expired_attributed_via_executor_tag(self):
        # ISSUE 11: the registry watchdog names the executor inline, so
        # attribution no longer depends on a role_map being threaded through
        exc = RuntimeError(
            "cluster failed: node worker:1 stopped heartbeating: lease "
            "expired after 31s without renewal (executor 4)"
        )
        event = elastic.classify_failure(exc)
        assert event.kind == "lease_expired"
        assert event.executor_ids == [4]
        assert event.kind in elastic.LOSS_KINDS

    def test_lease_expired_counts_toward_suspects(self):
        ledger = elastic.FailureLedger(max_restarts=8, blacklist_after=2)
        event = elastic.FailureEvent("lease_expired", [3], "lease expired (executor 3)")
        ledger.record(event)
        assert ledger.suspects() == []
        ledger.record(event)
        assert ledger.suspects() == [3]

    def test_feed_timeout(self):
        exc = RuntimeError("feed timeout: queue 'input' still has 3 unconsumed items")
        assert elastic.classify_failure(exc).kind == "feed_timeout"

    def test_preempted_is_first_class_and_budget_exempt(self):
        # the child's SIGTERM drain commits a ``preempted`` parting status;
        # the watchdog stamps it into the failure text with the executor id
        exc = RuntimeError("cluster failed: node worker:1 preempted (executor 3)")
        event = elastic.classify_failure(exc)
        assert event.kind == "preemption"
        assert event.executor_ids == [3]
        assert event.kind not in elastic.LOSS_KINDS
        assert event.kind in elastic.BUDGET_EXEMPT_KINDS

    def test_preemption_wins_over_late_expiry_phrasing(self):
        # a drained child's exit can race a late watchdog expiry into the
        # same failure text: the warned signal must win the classification
        exc = RuntimeError(
            "node worker:1 preempted (executor 3)\nnode worker:1 stopped "
            "heartbeating: lease expired after 31s without renewal (executor 3)"
        )
        assert elastic.classify_failure(exc).kind == "preemption"

    def test_unclassifiable_is_unknown(self):
        event = elastic.classify_failure(ValueError("something odd"))
        assert event.kind == "unknown"
        assert event.executor_ids == []


# -- ledger --------------------------------------------------------------------


class FakeClock:
    def __init__(self):
        self.t = 1000.0

    def __call__(self):
        return self.t


class TestFailureLedger:
    def test_restart_budget_is_window_scoped(self):
        clock = FakeClock()
        ledger = elastic.FailureLedger(max_restarts=2, window_secs=600, clock=clock)
        ledger.record(elastic.FailureEvent("unknown"))
        ledger.record(elastic.FailureEvent("unknown"))
        assert ledger.allow_restart()
        ledger.record(elastic.FailureEvent("unknown"))
        assert not ledger.allow_restart()  # 3 failures inside the window
        clock.t += 601  # the window slides past all three
        assert ledger.allow_restart()
        assert ledger.failures_in_window() == 0

    def test_suspects_need_repeated_loss_kind_failures(self):
        ledger = elastic.FailureLedger(blacklist_after=2)
        ledger.record(elastic.FailureEvent("node_exit", [1]))
        assert ledger.suspects() == []  # one transient loss never blacklists
        ledger.record(elastic.FailureEvent("feed_timeout", [1]))
        assert ledger.suspects() == []  # non-loss kinds never count
        ledger.record(elastic.FailureEvent("heartbeat_loss", [1]))
        assert ledger.suspects() == [1]

    def test_clear_forgives_one_executor(self):
        ledger = elastic.FailureLedger(blacklist_after=1)
        ledger.record(elastic.FailureEvent("node_exit", [1]))
        ledger.record(elastic.FailureEvent("node_exit", [2]))
        assert ledger.suspects() == [1, 2]
        ledger.clear(1)
        assert ledger.suspects() == [2]

    def test_preemption_never_consumes_the_restart_budget(self):
        # SIGTERM-then-clean-exit is *warned* downsizing: any number of
        # drained preemptions must leave the whole budget for real failures
        ledger = elastic.FailureLedger(max_restarts=1, blacklist_after=1)
        for _ in range(5):
            ledger.record(elastic.FailureEvent("preemption", [1], "preempted"))
        assert ledger.failures_in_window() == 0
        assert ledger.allow_restart()
        ledger.record(elastic.FailureEvent("node_exit", [2]))
        assert ledger.failures_in_window() == 1
        assert ledger.allow_restart()  # 1 real failure <= max_restarts=1
        ledger.record(elastic.FailureEvent("node_exit", [2]))
        assert not ledger.allow_restart()

    def test_preemption_never_counts_toward_blacklist(self):
        # a preempted-then-returning executor must rejoin without a ledger
        # entry: no suspects, so the next plan stays at full size and the
        # executor is back in the template
        ledger = elastic.FailureLedger(blacklist_after=1)
        ledger.record(elastic.FailureEvent("preemption", [1], "preempted"))
        ledger.record(elastic.FailureEvent("preemption", [1], "preempted"))
        assert ledger.suspects() == []
        assert elastic.plan_size(2, set(ledger.suspects())) == 2
        template = TFCluster.build_cluster_template(
            2, master_node=None, blacklist=set(ledger.suspects())
        )
        assert 1 in template

    def test_preemptions_still_appear_in_events(self):
        # exempt from the budget, not from the record: the trace/result
        # timeline still shows every drained preemption
        ledger = elastic.FailureLedger(max_restarts=0)
        ledger.record(elastic.FailureEvent("preemption", [1], "preempted"))
        assert [e.kind for _, e in ledger.events()] == ["preemption"]

    def test_shrink_never_goes_below_min_workers(self):
        assert elastic.plan_size(4, {3}, min_workers=2) == 3
        assert elastic.plan_size(4, {1, 3}, min_workers=2) == 2
        with pytest.raises(RuntimeError, match="min_workers"):
            elastic.plan_size(4, {1, 2, 3}, min_workers=2)
        # overhead (ps/evaluator) doesn't count toward the worker floor
        with pytest.raises(RuntimeError, match="min_workers"):
            elastic.plan_size(4, {3}, min_workers=3, overhead=1)


# -- blacklist threading -------------------------------------------------------


class TestBlacklistTemplate:
    def test_roles_skip_blacklisted_executors(self):
        template = TFCluster.build_cluster_template(
            3, master_node="chief", blacklist={1}
        )
        assert template == {0: ("chief", 0), 2: ("worker", 0), 3: ("worker", 1)}

    def test_empty_blacklist_is_identical_to_no_blacklist(self):
        assert TFCluster.build_cluster_template(4, num_ps=1) == (
            TFCluster.build_cluster_template(4, num_ps=1, blacklist=set())
        )


def _send_reg(addr, executor_id):
    """One raw REG exchange (no Client: its retry policy would turn the
    deliberate ERROR reply into seconds of backoff)."""
    with socket.create_connection(addr, timeout=10) as sock:
        msock = MessageSocket(sock)
        msock.send({"type": "REG", "data": {"executor_id": executor_id}})
        return msock.recv()


class TestReservationAttribution:
    def test_timeout_lists_never_registered_executors(self):
        server = reservation.Server(2, expected_ids=[0, 1])
        addr = server.start()
        try:
            assert _send_reg(("127.0.0.1", addr[1]), 0)["type"] == "OK"
            with pytest.raises(reservation.ReservationError) as excinfo:
                server.await_reservations(timeout=1.0, poll_interval=0.1)
            assert "never registered: executors [1]" in str(excinfo.value)
            assert excinfo.value.missing == [1]
        finally:
            server.stop()

    def test_blacklisted_registration_is_refused(self):
        server = reservation.Server(1, expected_ids=[0], blacklist={1})
        addr = server.start()
        try:
            reply = _send_reg(("127.0.0.1", addr[1]), 1)
            assert reply["type"] == "ERROR"
            assert "blacklisted" in reply["data"]
            assert server.reservations.remaining() == 1  # nothing stored
            # a healthy executor still registers
            assert _send_reg(("127.0.0.1", addr[1]), 0)["type"] == "OK"
        finally:
            server.stop()


# -- preflight gate ------------------------------------------------------------


def _probe_fail_on_1(executor_id):
    if executor_id == 1:
        raise IOError("scratch disk full")


class TestPreflight:
    def test_healthy_executors_pass(self):
        sc = LocalSparkContext(num_executors=2, task_timeout=120)
        try:
            assert elastic.preflight_executors(sc, [0, 1]) == {}
        finally:
            sc.stop()

    def test_extra_probe_failure_is_attributed(self):
        sc = LocalSparkContext(num_executors=2, task_timeout=120)
        try:
            bad = elastic.preflight_executors(sc, [0, 1], extra_probe=_probe_fail_on_1)
            assert list(bad) == [1]
            assert "disk full" in bad[1]
        finally:
            sc.stop()

    def test_unpinnable_backend_reports_nothing(self):
        class NoPin:
            pass

        assert elastic.preflight_executors(NoPin(), [0]) == {}


# -- final-failure path --------------------------------------------------------


def fn_always_dies(args, ctx):
    raise RuntimeError("synthetic training failure")


def test_final_failure_aborts_and_chains_cause(tmp_path, monkeypatch):
    """When the window budget is spent the ladder must (a) have aborted every
    failed attempt — the caller gets their executors back — and (b) raise a
    RuntimeError chaining the last underlying failure."""
    aborts = []
    real_abort = TFCluster.TFCluster.abort

    def spying_abort(self, reason="aborted by driver", wait_secs=60):
        aborts.append(str(reason))
        return real_abort(self, reason, wait_secs)

    monkeypatch.setattr(TFCluster.TFCluster, "abort", spying_abort)
    sc = LocalSparkContext(num_executors=1, task_timeout=300)
    try:
        with pytest.raises(RuntimeError, match="failed after 1 relaunch") as excinfo:
            TFCluster.run_with_recovery(
                sc, fn_always_dies, {}, num_executors=1,
                input_mode=InputMode.TENSORFLOW, master_node=None,
                env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
                max_relaunches=1, shutdown_timeout=120, preflight=False,
            )
        assert excinfo.value.__cause__ is not None
        assert "synthetic training failure" in str(excinfo.value.__cause__)
    finally:
        sc.stop()
    assert len(aborts) == 2  # both failed attempts were torn down


# -- end to end: kill → blacklist → shrink → resharded resume ------------------


def fn_elastic_train(args, ctx):
    """Trains to ``target_steps`` on a mesh shaped by the CURRENT cluster
    size (2 workers → dp=2 × fsdp=4; 1 worker → dp=1 × fsdp=8 on the 8
    virtual CPU devices), resuming via ``ckpt.reshard_restore`` so a
    checkpoint saved at one size lands on the other. Only task 0 owns the
    shared model_dir. The chaos victim (executor 1) trains without a stop
    condition — it can only ever exit by the injected kill, so the test
    has no completion-vs-kill race, and the late ``after_beats`` gives
    task 0 ample runway to commit mid-training checkpoints first."""
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import ckpt, parallel
    from tensorflowonspark_tpu.ckpt.reshard import reshard_restore
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint

    num_workers = ctx.num_workers
    strategy = SyncDataParallel(
        parallel.local_mesh({"dp": num_workers, "fsdp": -1}),
        fsdp=True, min_weight_size=1,
    )
    model = mnist.create_model("mlp", hidden=8)
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(
        mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0)
    )
    step = strategy.compile_train_step(
        mnist.make_loss_fn(model), optimizer, has_aux=True, donate=False
    )
    rng = np.random.default_rng(7)
    batch = strategy.shard_batch(
        {
            "image": rng.standard_normal((16, 28, 28)).astype(np.float32),
            "label": rng.integers(0, 10, 16),
        }
    )

    if ctx.executor_id == args["victim"]:
        # the designated victim never finishes on its own: its only exits
        # are the injected node.kill (lives at full size) or not being
        # scheduled at all (after the blacklist) — no timing race
        while True:
            state, metrics = step(state, batch)
            jax.block_until_ready(metrics["loss"])
            time.sleep(args["step_pace_secs"])

    model_dir = args["model_dir"]
    resumed_from = 0
    latest = checkpoint.latest_checkpoint(model_dir)
    if latest:
        state = reshard_restore(latest, strategy=strategy, target=state)
        resumed_from = int(jax.device_get(state.step))
    global_step = int(jax.device_get(state.step))

    with ckpt.AsyncCheckpointEngine(model_dir) as eng:
        while global_step < args["target_steps"]:
            state, metrics = step(state, batch)
            jax.block_until_ready(metrics["loss"])
            global_step += 1
            time.sleep(args["step_pace_secs"])
            if global_step % 2 == 0:
                eng.save(state, global_step)
        assert eng.drain(timeout=120)
    with open(os.path.join(model_dir, "done.json"), "w") as f:
        json.dump(
            {
                "final_step": global_step,
                "resumed_from": resumed_from,
                "num_workers": num_workers,
                "mesh": dict(strategy.mesh.shape),
            },
            f,
        )


@pytest.mark.chaos
@pytest.mark.slow
def test_node_kill_blacklist_shrink_resharded_resume(tmp_path, monkeypatch):
    """The elasticity acceptance story: chaos SIGKILLs worker 1 mid-training
    on every life (fresh per-process plan budget), the ledger attributes two
    losses to executor 1 and blacklists it, the third attempt launches at
    N−1 with the 1×8 mesh, reshard-restores the 2×4-mesh checkpoint, and
    finishes — with the ladder's counters visible in the metrics snapshot
    captured from ``cluster.metrics()``."""
    monkeypatch.setenv("TOS_MONITOR_INTERVAL", "1")
    monkeypatch.setenv("TOS_HEARTBEAT_INTERVAL", "0.2")
    chaos_log = str(tmp_path / "chaos.log")
    monkeypatch.setenv(chaos.LOG_ENV_VAR, chaos_log)
    model_dir = str(tmp_path / "model")
    os.makedirs(model_dir)
    args = {
        "model_dir": model_dir,
        "target_steps": 12,
        "step_pace_secs": 0.2,
        "victim": 1,
    }

    # victim-scoped: only executor 1's jax child dies, 50 beats (~10s) into
    # its life — late enough that worker 0 has committed real mid-training
    # checkpoints by then, while the victim (which never stops on its own)
    # is still guaranteed to be mid-training. Every relaunch spawns a fresh
    # child whose plan budget resets, so the victim dies on EVERY life
    # until the ladder stops scheduling it.
    plan = chaos.ChaosPlan(seed=11).site(
        "node.kill", probability=1.0, max_count=1, victim=1, after_beats=50
    )
    chaos.install(plan)
    sc = LocalSparkContext(num_executors=2, task_timeout=900)
    try:
        result = elastic.run_ladder(
            sc, fn_elastic_train, args, num_executors=2,
            max_relaunches=3, min_workers=1, blacklist_after=2,
            input_mode=InputMode.TENSORFLOW, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
            shutdown_timeout=240,
        )
    finally:
        sc.stop()
        chaos.uninstall()

    # the ladder's trajectory: two full-size failures, then shrink to 1
    assert result.relaunches == 2
    assert result.blacklist == {1}
    assert result.num_executors == 1

    # the kills really came from the chaos site, once per victim life
    with open(chaos_log) as f:
        kills = [line for line in f if line.strip() == "node.kill"]
    assert len(kills) >= 2

    # training completed on the SHRUNK mesh, resuming (not restarting):
    # the final life restored a checkpoint saved on the 2×4 mesh onto 1×8
    with open(os.path.join(model_dir, "done.json")) as f:
        done = json.load(f)
    assert done["final_step"] == args["target_steps"]
    assert done["num_workers"] == 1
    assert done["mesh"] == {"dp": 1, "fsdp": 8}
    assert done["resumed_from"] >= 1, "final life must resume from a checkpoint"

    # the recovery counters are in the merged cluster metrics snapshot
    snap = result.metrics
    assert snap is not None
    assert snap["counters"]["recovery_attempts_total"]["value"] >= 2
    assert snap["counters"]["recovery_shrinks_total"]["value"] >= 1
    assert snap["gauges"]["executors_blacklisted"]["value"] >= 1
    assert snap["counters"]["recovery_seconds_total"]["value"] > 0


# -- end to end: kill → shrink → forgive → regrow → full-size resume -----------


def fn_regrow_train(args, ctx):
    """The bidirectional-elasticity workload. Life 1 (full size): the victim
    spins until the once-latched ``node.kill`` lands; the healthy worker
    trains to ``target_steps`` on the 2×4 mesh, checkpointing async. Life 2
    (shrunk to 1): resumes on the 1×8 mesh and trains *without a stop
    condition* — only the driver's regrow preemption warning ends it, and
    the SIGTERM drain is what lands its final checkpoint. Life 3 (regrown
    to full size): both workers reshard-restore the drained checkpoint onto
    the 2×4 mesh; the stop condition (full size AND ``target_steps``) is
    satisfiable again and task 0 records the outcome."""
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import ckpt, parallel
    from tensorflowonspark_tpu.ckpt.reshard import reshard_restore
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint

    num_workers = ctx.num_workers
    strategy = SyncDataParallel(
        parallel.local_mesh({"dp": num_workers, "fsdp": -1}),
        fsdp=True, min_weight_size=1,
    )
    model = mnist.create_model("mlp", hidden=8)
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(
        mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0)
    )
    step = strategy.compile_train_step(
        mnist.make_loss_fn(model), optimizer, has_aux=True, donate=False
    )
    rng = np.random.default_rng(7)
    batch = strategy.shard_batch(
        {
            "image": rng.standard_normal((16, 28, 28)).astype(np.float32),
            "label": rng.integers(0, 10, 16),
        }
    )

    if ctx.executor_id == args["victim"] and not os.path.exists(args["latch"]):
        # life 1 only: the latch file doubles as the chaos site's
        # ``once_path``, so once the kill has fired the respawned victim
        # takes the normal training path below and simply rejoins
        while True:
            state, metrics = step(state, batch)
            jax.block_until_ready(metrics["loss"])
            time.sleep(args["step_pace_secs"])

    model_dir = args["model_dir"]
    resumed_from = 0
    latest = checkpoint.latest_checkpoint(model_dir)
    if latest:
        state = reshard_restore(latest, strategy=strategy, target=state)
        resumed_from = int(jax.device_get(state.step))
    global_step = int(jax.device_get(state.step))

    with ckpt.AsyncCheckpointEngine(model_dir) as eng:
        # the stop condition requires the FULL-size mesh: a shrunk life can
        # only end by the driver's preemption warning, whose drain commits
        # the engine's pending save before the exit
        while not (
            num_workers == args["full_size"] and global_step >= args["target_steps"]
        ):
            state, metrics = step(state, batch)
            jax.block_until_ready(metrics["loss"])
            global_step += 1
            time.sleep(args["step_pace_secs"])
            if ctx.task_index == 0 and global_step % 2 == 0:
                eng.save(state, global_step)
        assert eng.drain(timeout=120)
    if ctx.task_index == 0:
        with open(os.path.join(model_dir, "done.json"), "w") as f:
            json.dump(
                {
                    "final_step": global_step,
                    "resumed_from": resumed_from,
                    "num_workers": num_workers,
                    "mesh": dict(strategy.mesh.shape),
                },
                f,
            )


@pytest.mark.chaos
@pytest.mark.slow
def test_preempt_drain_regrow_full_size_resume(tmp_path, monkeypatch):
    """The bidirectional acceptance story: one latched chaos kill takes the
    victim down → the ledger blacklists it (``blacklist_after=1``) and the
    ladder shrinks to 1 → the mid-run regrow poll re-probes the condemned
    executor, finds it healthy, and the scaler votes to grow → the driver
    posts a preemption warning, the shrunk worker drains its async
    checkpoint and exits clean (budget-exempt: ``max_relaunches=1`` is
    already spent on the kill) → the relaunch forgives the victim and
    regrows to the original world size, reshard-restoring the drained
    checkpoint onto the full mesh."""
    monkeypatch.setenv("TOS_MONITOR_INTERVAL", "1")
    monkeypatch.setenv("TOS_HEARTBEAT_INTERVAL", "0.2")
    chaos_log = str(tmp_path / "chaos.log")
    monkeypatch.setenv(chaos.LOG_ENV_VAR, chaos_log)
    model_dir = str(tmp_path / "model")
    os.makedirs(model_dir)
    latch = str(tmp_path / "kill.latch")
    args = {
        "model_dir": model_dir,
        "target_steps": 12,
        "step_pace_secs": 0.2,
        "victim": 1,
        "latch": latch,
        "full_size": 2,
    }

    # once_path makes the kill a single event across the victim's lives:
    # the respawned (forgiven) child finds the latch and trains normally
    plan = chaos.ChaosPlan(seed=11).site(
        "node.kill", probability=1.0, max_count=1, victim=1, after_beats=50,
        once_path=latch,
    )
    chaos.install(plan)
    sc = LocalSparkContext(num_executors=2, task_timeout=900)
    try:
        result = elastic.run_ladder(
            sc, fn_regrow_train, args, num_executors=2,
            max_relaunches=1, min_workers=1, blacklist_after=1,
            regrow=True, regrow_check_secs=3.0,
            scaler=control.ClusterScaler(2, min_size=1, grow_patience=1),
            input_mode=InputMode.TENSORFLOW, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
            shutdown_timeout=240,
        )
    finally:
        sc.stop()
        chaos.uninstall()

    # the ladder's trajectory: kill → shrink to 1, preempt-drain → regrow
    # to 2 with the blacklist emptied by forgiveness. The preemption rode
    # for free: max_relaunches=1 was already spent on the kill, so the run
    # completing at all proves the budget exemption end to end.
    assert result.relaunches == 2
    assert result.num_executors == 2
    assert result.blacklist == set()
    kinds = [e.kind for _, e in result.events]
    assert "preemption" in kinds

    # exactly one kill ever fired (the latch held across lives)
    with open(chaos_log) as f:
        kills = [line for line in f if line.strip() == "node.kill"]
    assert len(kills) == 1

    # training completed back on the FULL mesh, resuming the trajectory the
    # preempted life drained (its async checkpoint outlived the process)
    with open(os.path.join(model_dir, "done.json")) as f:
        done = json.load(f)
    assert done["num_workers"] == 2
    assert done["mesh"] == {"dp": 2, "fsdp": 4}
    assert done["final_step"] >= args["target_steps"]
    assert done["resumed_from"] >= args["target_steps"], (
        "the regrown life must resume from the shrunk life's progress, "
        "not restart"
    )

    # the bidirectional counters are in the merged snapshot
    snap = result.metrics
    assert snap is not None
    assert snap["counters"]["recovery_shrinks_total"]["value"] >= 1
    assert snap["counters"]["recovery_regrows_total"]["value"] >= 1
    assert snap["counters"]["preemptions_drained_total"]["value"] >= 1
    assert snap["gauges"]["target_world_size"]["value"] == 2
    assert snap["gauges"]["executors_blacklisted"]["value"] == 0
