"""Fixture tests for the chaos-obs-coverage and import-hygiene rules."""

import textwrap

from tosa_testutil import run_rule, run_rule_multi


def _src(s):
    return textwrap.dedent(s).lstrip()


CHAOS_PATH = "tensorflowonspark_tpu/chaos/__init__.py"

#: a minimal chaos module: one documented site + the obs counter bump
CHAOS_MODULE = _src('''
    """Deterministic fault injection.

    Sites:

    ``feed.stall``      delay the feeder before a put
    """

    from tensorflowonspark_tpu import obs

    active = False


    def _record(site):
        obs.counter("chaos_faults_injected_total").inc()


    def fire(site):
        _record(site)


    def delay(site, seconds=0.0):
        _record(site)
''')

FIRING_MODULE = _src("""
    from tensorflowonspark_tpu import chaos


    def feed(q, item):
        if chaos.active:
            chaos.fire("feed.stall")
        q.put(item)
""")


class TestChaosObsCoverage:
    def test_documented_and_fired_is_clean(self):
        findings = run_rule_multi("chaos-obs-coverage", {
            CHAOS_PATH: CHAOS_MODULE,
            "tensorflowonspark_tpu/feeder.py": FIRING_MODULE,
        })
        assert findings == []

    def test_non_literal_site_fires(self):
        findings = run_rule_multi("chaos-obs-coverage", {
            CHAOS_PATH: CHAOS_MODULE,
            "tensorflowonspark_tpu/feeder.py": _src("""
                from tensorflowonspark_tpu import chaos

                SITE = "feed.stall"


                def feed(q, item):
                    chaos.fire(SITE)
                    chaos.delay("feed.stall")
                    q.put(item)
            """),
        })
        assert len(findings) == 1
        assert "non-literal" in findings[0].message

    def test_undocumented_site_fires(self):
        findings = run_rule_multi("chaos-obs-coverage", {
            CHAOS_PATH: CHAOS_MODULE,
            "tensorflowonspark_tpu/feeder.py": _src("""
                from tensorflowonspark_tpu import chaos


                def feed(q, item):
                    chaos.fire("feed.stall")
                    chaos.fire("feed.mystery")
                    q.put(item)
            """),
        })
        assert len(findings) == 1
        assert "feed.mystery" in findings[0].message
        assert "missing from the site table" in findings[0].message

    def test_stale_table_row_fires(self):
        stale = CHAOS_MODULE.replace(
            "``feed.stall``      delay the feeder before a put",
            "``feed.stall``      delay the feeder before a put\n"
            "    ``feed.ghost``      documented but never wired up",
        )
        findings = run_rule_multi("chaos-obs-coverage", {
            CHAOS_PATH: stale,
            "tensorflowonspark_tpu/feeder.py": FIRING_MODULE,
        })
        assert len(findings) == 1
        assert "feed.ghost" in findings[0].message
        assert "never fired" in findings[0].message

    def test_missing_obs_counter_fires(self):
        no_counter = CHAOS_MODULE.replace(
            'obs.counter("chaos_faults_injected_total").inc()', "pass"
        )
        findings = run_rule_multi("chaos-obs-coverage", {
            CHAOS_PATH: no_counter,
            "tensorflowonspark_tpu/feeder.py": FIRING_MODULE,
        })
        assert len(findings) == 1
        assert "chaos_faults_injected_total" in findings[0].message

    def test_no_chaos_module_in_scan_skips_table_checks(self):
        findings = run_rule_multi("chaos-obs-coverage", {
            "tensorflowonspark_tpu/feeder.py": FIRING_MODULE,
        })
        assert findings == []


class TestImportHygiene:
    def test_module_level_basicconfig_fires(self):
        findings = run_rule("import-hygiene", _src("""
            import logging

            logging.basicConfig(level=logging.INFO)
        """))
        assert len(findings) == 1
        assert "setup_logging" in findings[0].message

    def test_class_body_counts_as_import_time(self):
        findings = run_rule("import-hygiene", _src("""
            import jax


            class Topology:
                DEVICES = jax.devices()
        """))
        assert len(findings) == 1
        assert "jax.devices" in findings[0].message

    def test_module_level_jax_distributed_init_fires(self):
        findings = run_rule("import-hygiene", _src("""
            import jax

            jax.distributed.initialize()
        """))
        assert len(findings) == 1

    def test_spark_session_chain_fires(self):
        findings = run_rule("import-hygiene", _src("""
            from pyspark.sql import SparkSession

            spark = SparkSession.builder.appName("x").getOrCreate()
        """))
        assert len(findings) == 1

    def test_spark_context_constructor_fires(self):
        findings = run_rule("import-hygiene", _src("""
            from pyspark import SparkContext

            sc = SparkContext()
        """))
        assert len(findings) == 1

    def test_calls_inside_functions_are_clean(self):
        findings = run_rule("import-hygiene", _src("""
            import logging

            import jax


            def setup_logging(level=logging.INFO):
                logging.basicConfig(level=level)


            def world_size():
                return jax.device_count()
        """))
        assert findings == []

    def test_scripts_are_not_library_scope(self):
        findings = run_rule("import-hygiene", _src("""
            import logging

            logging.basicConfig(level=logging.INFO)
        """), relpath="scripts/bench_helper.py")
        assert findings == []
