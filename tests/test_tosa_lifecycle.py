"""Fixture tests for the thread-lifecycle rule: reachable stop signals,
join discipline, and bounded hand-off queues."""

import textwrap

from tosa_testutil import LIB_PATH, run_project_rule
from tosa import core


def _src(s):
    return textwrap.dedent(s).lstrip()


class TestStopSignal:
    def test_stopless_while_true_on_spawned_thread_fires(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            class Pump:
                def start(self):
                    self._thread = threading.Thread(target=self._run, daemon=True)
                    self._thread.start()

                def _run(self):
                    while True:
                        do_work()

                def stop(self):
                    self._thread.join(timeout=5.0)
        """)})
        assert len(findings) == 1
        assert "checks no stop signal" in findings[0].message

    def test_event_wait_loop_is_clean(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            class Pump:
                def start(self):
                    self._stop = threading.Event()
                    self._thread = threading.Thread(target=self._run, daemon=True)
                    self._thread.start()

                def _run(self):
                    while True:
                        if self._stop.wait(0.1):
                            return
                        do_work()

                def stop(self):
                    self._stop.set()
                    self._thread.join(timeout=5.0)
        """)})
        assert findings == []

    def test_queue_sentinel_exit_is_clean(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import queue
            import threading


            class Pump:
                def start(self):
                    self._q = queue.Queue(maxsize=64)
                    self._thread = threading.Thread(target=self._run, daemon=True)
                    self._thread.start()

                def _run(self):
                    while True:
                        item = self._q.get()
                        if item is None:
                            return
                        handle(item)

                def stop(self):
                    self._q.put(None)
                    self._thread.join(timeout=5.0)
        """)})
        assert findings == []

    def test_stop_flag_guarded_exit_is_clean(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            class Pump:
                def start(self):
                    self._closed = False
                    self._thread = threading.Thread(target=self._run, daemon=True)
                    self._thread.start()

                def _run(self):
                    while True:
                        if self._closed:
                            break
                        do_work()

                def stop(self):
                    self._closed = True
                    self._thread.join(timeout=5.0)
        """)})
        assert findings == []

    def test_stopless_loop_one_call_down_fires(self):
        # the spawn target delegates to a helper; the helper's loop still
        # runs on the spawned thread (targets expand one call level)
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            def _drain_forever(q):
                while True:
                    handle(q.get())


            def launch(q):
                threading.Thread(target=_run, args=(q,), daemon=True).start()


            def _run(q):
                _drain_forever(q)
        """)})
        assert len(findings) == 1
        assert "checks no stop signal" in findings[0].message
        assert "_drain_forever" in findings[0].message

    def test_generator_pull_loop_is_exempt(self):
        # `while True: yield ...` is driven by its consumer; the stop
        # signal lives in the caller, not the loop body
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            def _waves(q):
                while True:
                    yield q.get()


            def _run(q):
                for wave in _waves(q):
                    if wave is None:
                        return
                    handle(wave)


            def launch(q):
                threading.Thread(target=_run, args=(q,), daemon=True).start()
        """)})
        assert findings == []

    def test_submit_target_gets_stop_check_but_not_join_discipline(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            class Pump:
                def start(self, pool):
                    pool.submit(self._run)

                def _run(self):
                    while True:
                        do_work()
        """)})
        # one stop-signal finding; no drop-the-handle finding — executor
        # shutdown owns submit lifetimes
        assert len(findings) == 1
        assert "checks no stop signal" in findings[0].message


class TestJoinDiscipline:
    def test_self_handle_never_joined_fires(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            class Pump:
                def start(self):
                    self._stop = threading.Event()
                    self._thread = threading.Thread(target=self._run, daemon=True)
                    self._thread.start()

                def _run(self):
                    while True:
                        if self._stop.is_set():
                            return
                        do_work()

                def stop(self):
                    self._stop.set()
        """)})
        assert len(findings) == 1
        assert "never joined on any shutdown path" in findings[0].message

    def test_self_handle_untimed_join_fires(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            class Pump:
                def start(self):
                    self._stop = threading.Event()
                    self._thread = threading.Thread(target=self._run, daemon=True)
                    self._thread.start()

                def _run(self):
                    while True:
                        if self._stop.is_set():
                            return
                        do_work()

                def stop(self):
                    self._stop.set()
                    self._thread.join()
        """)})
        assert len(findings) == 1
        assert "only joined without a timeout" in findings[0].message

    def test_timer_cancelled_on_shutdown_is_clean(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            class Rearm:
                def arm(self):
                    self._timer = threading.Timer(5.0, self._fire)
                    self._timer.start()

                def _fire(self):
                    do_work()

                def stop(self):
                    self._timer.cancel()
        """)})
        assert findings == []

    def test_dropped_handle_without_daemon_fires(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            def launch(ev):
                threading.Thread(target=_run, args=(ev,)).start()


            def _run(ev):
                while True:
                    if ev.is_set():
                        return
                    do_work()
        """)})
        assert len(findings) == 1
        assert "drops the handle and is not daemon=True" in findings[0].message

    def test_dropped_handle_with_daemon_is_clean(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            def launch(ev):
                threading.Thread(target=_run, args=(ev,), daemon=True).start()


            def _run(ev):
                while True:
                    if ev.is_set():
                        return
                    do_work()
        """)})
        assert findings == []

    def test_local_handle_untimed_join_fires(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            def run_once(ev):
                t = threading.Thread(target=_work, args=(ev,))
                t.start()
                t.join()


            def _work(ev):
                do_work()
        """)})
        assert len(findings) == 1
        assert "joined without a timeout" in findings[0].message

    def test_local_handle_leaked_without_daemon_fires(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            def run_once(ev):
                t = threading.Thread(target=_work, args=(ev,))
                t.start()


            def _work(ev):
                do_work()
        """)})
        assert len(findings) == 1
        assert "neither joined with a timeout" in findings[0].message

    def test_sliced_timed_join_is_clean(self):
        # `while t.is_alive(): t.join(timeout=...)` keeps wait-forever
        # semantics while satisfying the timed-join rule — the fix pattern
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            def run_once(ev):
                t = threading.Thread(target=_work, args=(ev,))
                t.start()
                while t.is_alive():
                    t.join(timeout=60.0)


            def _work(ev):
                do_work()
        """)})
        assert findings == []

    def test_post_hoc_daemon_set_amends_the_spawn(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import threading


            def run_once(ev):
                t = threading.Thread(target=_work, args=(ev,))
                t.daemon = True
                t.start()


            def _work(ev):
                do_work()
        """)})
        assert findings == []


class TestBoundedHandoff:
    UNBOUNDED = _src("""
        import queue
        import threading


        class Feeder:
            def __init__(self):
                self._q = queue.Queue()
                self._t = threading.Thread(target=self._drain, daemon=True)
                self._t.start()

            def _drain(self):
                while True:
                    item = self._q.get()
                    if item is None:
                        return
                    handle(item)

            def close(self):
                self._q.put(None)
                self._t.join(timeout=5.0)
    """)

    def test_unbounded_queue_with_spawned_consumer_fires(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: self.UNBOUNDED})
        assert len(findings) == 1
        assert "unbounded Queue()" in findings[0].message
        assert "Feeder._drain" in findings[0].message

    def test_bounded_queue_is_clean(self):
        bounded = self.UNBOUNDED.replace("queue.Queue()", "queue.Queue(maxsize=64)")
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: bounded})
        assert findings == []

    def test_multiprocessing_queue_is_exempt(self):
        # mp queues have different bounding semantics; the rule only
        # covers `queue.Queue`
        mp = self.UNBOUNDED.replace("import queue", "import multiprocessing as queue")
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: mp})
        assert findings == []

    def test_unconsumed_unbounded_queue_is_clean(self):
        # no spawned thread drains it — buffering in the owner's own
        # thread is not a hand-off hazard
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: _src("""
            import queue


            class Buffer:
                def __init__(self):
                    self._q = queue.Queue()

                def push(self, item):
                    self._q.put(item)

                def pop(self):
                    return self._q.get()
        """)})
        assert findings == []


class TestSuppressionAndBaseline:
    BAD = _src("""
        import threading


        def launch(ev):
            threading.Thread(target=_run, args=(ev,)).start()


        def _run(ev):
            while True:
                if ev.is_set():
                    return
                do_work()
    """)

    def test_inline_disable_silences_with_reason(self):
        src = self.BAD.replace(
            "threading.Thread(target=_run, args=(ev,)).start()",
            "threading.Thread(target=_run, args=(ev,)).start()"
            "  # tosa: disable=thread-lifecycle -- fixture leaks on purpose",
        )
        findings = run_project_rule(
            "thread-lifecycle", {LIB_PATH: src}, keep_suppressed=True
        )
        assert len(findings) == 1
        assert findings[0].suppressed == "fixture leaks on purpose"
        assert core.gating(findings) == []

    def test_baseline_grandfathers_one_occurrence(self):
        findings = run_project_rule("thread-lifecycle", {LIB_PATH: self.BAD})
        assert len(core.gating(findings)) == 1
        baseline = {findings[0].fingerprint: 1}
        findings = core.apply_baseline(findings, baseline)
        assert core.gating(findings) == []
