"""Inference server (the JVM-inference equivalent) — VERDICT round-1 item 10.

The byte-level test speaks the wire protocol with raw sockets, framing
messages exactly as jvm/.../InferenceClient.java does (4-byte big-endian
length + UTF-8 JSON), so the JVM contract is pinned without a JVM in the
image. Reference analogue: Scala Inference.scala/TFModel.scala batch
inference from Spark executors.
"""

import json
import socket
import struct

import numpy as np
import pytest

from tensorflowonspark_tpu.serving import InferenceClient, InferenceServer
from tensorflowonspark_tpu.train import export


def _bundle(tmp_path):
    """A linear y = x @ w + b bundle, like the pipeline's export."""
    w = np.array([[2.0], [3.0]], np.float32)
    b = np.array([1.0], np.float32)

    def predict_builder():
        def predict(params, model_state, arrays):
            return {"y_": arrays["x"] @ params["w"] + params["b"]}

        return predict

    path = str(tmp_path / "bundle")
    export.export_model(path, predict_builder, {"w": w, "b": b})
    return path


@pytest.fixture
def server(tmp_path):
    srv = InferenceServer(_bundle(tmp_path))
    srv.start()
    yield srv
    srv.stop()


def _jvm_style_request(address, payload_text):
    """Frame and send exactly like the Java client: writeInt + UTF-8 bytes."""
    with socket.create_connection(address, timeout=30) as sock:
        data = payload_text.encode("utf-8")
        sock.sendall(struct.pack(">I", len(data)) + data)
        header = b""
        while len(header) < 4:
            header += sock.recv(4 - len(header))
        (length,) = struct.unpack(">I", header)
        body = b""
        while len(body) < length:
            body += sock.recv(length - len(body))
        return json.loads(body.decode("utf-8"))


def test_raw_socket_protocol(server):
    assert _jvm_style_request(server.address, '{"type": "ping"}') == {"type": "pong"}
    info = _jvm_style_request(server.address, '{"type": "info"}')
    assert info["ready"] is True

    reply = _jvm_style_request(
        server.address,
        '{"type": "predict", "inputs": {"x": [[1.0, 1.0], [0.0, 2.0]]}}',
    )
    assert reply["type"] == "result"
    np.testing.assert_allclose(reply["outputs"]["y_"], [[6.0], [7.0]])


def test_error_reply_for_unknown_type(server):
    reply = _jvm_style_request(server.address, '{"type": "wat"}')
    assert reply["type"] == "error"


def test_python_client_roundtrip(server):
    client = InferenceClient(server.address)
    try:
        assert client.ping()
        out = client.predict(x=np.array([[1.0, 2.0], [3.0, 0.5]], np.float32))
        np.testing.assert_allclose(out["y_"], [[9.0], [8.5]])
        # persistent connection: a second request on the same socket
        out2 = client.predict(x=[[0.0, 0.0]])
        np.testing.assert_allclose(out2["y_"], [[1.0]])
    finally:
        client.close()


def test_predict_failure_surfaces(server):
    client = InferenceClient(server.address)
    try:
        with pytest.raises(RuntimeError):
            client.predict(wrong_column=[[1.0]])
    finally:
        client.close()


def test_binary_tensor_lane_roundtrip(server):
    """predict_binary moves raw little-endian buffers, not JSON text — the
    class-parity answer to the reference's JVM nio-buffer tensors
    (TFModel.scala:121-244)."""
    client = InferenceClient(server.address)
    try:
        x = np.array([[1.0, 2.0], [3.0, 0.5]], np.float32)
        out = client.predict_binary(x=x)
        assert out["y_"].dtype == np.float32
        np.testing.assert_allclose(out["y_"], [[9.0], [8.5]])
        # json and binary lanes interleave on one connection
        out_json = client.predict(x=[[0.0, 0.0]])
        np.testing.assert_allclose(out_json["y_"], [[1.0]])
        out2 = client.predict_binary(x=np.zeros((1, 2), np.float32))
        np.testing.assert_allclose(out2["y_"], [[1.0]])
    finally:
        client.close()


def test_binary_lane_byte_level(server):
    """Pin the binary wire format without the Python client: JSON header
    frame, then one raw frame of concatenated C-order little-endian column
    buffers; reply mirrors it."""
    x = np.array([[1.0, 1.0]], np.float32)
    header = json.dumps(
        {"type": "predict_binary",
         "columns": [{"name": "x", "dtype": "<f4", "shape": [1, 2]}]}
    ).encode("utf-8")
    with socket.create_connection(server.address, timeout=30) as sock:
        sock.sendall(struct.pack(">I", len(header)) + header)
        payload = x.tobytes()
        sock.sendall(struct.pack(">I", len(payload)) + payload)

        def read_frame():
            hdr = b""
            while len(hdr) < 4:
                hdr += sock.recv(4 - len(hdr))
            (length,) = struct.unpack(">I", hdr)
            body = b""
            while len(body) < length:
                body += sock.recv(length - len(body))
            return body

        reply = json.loads(read_frame().decode("utf-8"))
        assert reply["type"] == "result_binary"
        (col,) = reply["columns"]
        assert col["name"] == "y_" and col["dtype"] == "<f4" and col["shape"] == [1, 1]
        out = np.frombuffer(read_frame(), np.float32).reshape(1, 1)
        np.testing.assert_allclose(out, [[6.0]])


def test_binary_lane_error_has_no_raw_frame(server):
    """An error reply is a lone JSON frame (the Java client depends on it)."""
    client = InferenceClient(server.address)
    try:
        with pytest.raises(RuntimeError):
            client.predict_binary(wrong=np.zeros((1, 2), np.float32))
        assert client.ping()  # connection stays usable
    finally:
        client.close()


def test_concurrent_clients_all_served(server):
    """N concurrent clients through the bounded pool + coalescing predictor;
    every client gets its own rows back (VERDICT r2 weak item 6/8)."""
    import threading

    results = {}
    errors = []

    def worker(i):
        try:
            client = InferenceClient(server.address)
            try:
                x = np.full((4, 2), float(i), np.float32)
                for _ in range(5):
                    out = client.predict_binary(x=x)
                    np.testing.assert_allclose(
                        out["y_"], np.full((4, 1), 5.0 * i + 1.0), rtol=1e-6
                    )
                results[i] = True
            finally:
                client.close()
        except Exception as e:  # pragma: no cover - failure reporting
            errors.append((i, e))

    threads = [threading.Thread(target=worker, args=(i,)) for i in range(12)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errors, errors
    assert len(results) == 12


def test_client_final_error_names_server_and_budget(tmp_path):
    """After retry exhaustion the client's error must name the server
    address, attempt count, and elapsed budget — the reservation.Client
    contract — not surface the bare last OSError."""
    from tensorflowonspark_tpu import resilience

    srv = InferenceServer(_bundle(tmp_path))
    host, port = srv.start()
    client = InferenceClient(
        (host, port), timeout=5,
        retry=resilience.RetryPolicy(
            max_attempts=2,
            backoff=resilience.Backoff(base=0.02, factor=2.0, max_delay=0.1,
                                       jitter=0.5, seed=0),
            retry_on=(OSError,),
        ),
    )
    srv.stop()
    try:
        with pytest.raises(ConnectionError) as err:
            client.predict(x=[[1.0, 2.0]])
    finally:
        client.close()
    msg = str(err.value)
    assert "inference server at {}:{}".format(host or "127.0.0.1", port) in msg
    assert "2 attempt(s)" in msg
    assert "unreachable" in msg
    assert err.value.__cause__ is not None  # the bare last error is chained


def test_stop_with_idle_persistent_connection(tmp_path):
    """stop() must complete even while a client holds an idle persistent
    connection (pool threads are non-daemon; the server closes live
    connections to unblock them)."""
    import threading
    import time

    srv = InferenceServer(_bundle(tmp_path))
    srv.start()
    client = InferenceClient(srv.address)
    assert client.ping()
    t0 = time.time()
    done = threading.Event()

    def _stop():
        srv.stop()
        done.set()

    threading.Thread(target=_stop, daemon=True).start()
    assert done.wait(timeout=30), "server.stop() hung on an idle connection"
    assert time.time() - t0 < 30
    client.close()


def test_coalescing_matches_individual_runs(tmp_path):
    """Coalesced concurrent requests return exactly what individual runs
    return (axis-0 concat + split is the only transformation)."""
    from tensorflowonspark_tpu.serving import _Predictor
    from tensorflowonspark_tpu.train import export as export_mod

    path = _bundle(tmp_path)
    predict_fn, params, model_state = export_mod.load_model(path)
    pred = _Predictor(predict_fn, params, model_state)
    try:
        import threading

        outs = {}

        def call(i):
            x = np.full((2, 2), float(i), np.float32)
            outs[i] = pred.submit({"x": x})

        threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        for i in range(8):
            np.testing.assert_allclose(outs[i]["y_"], np.full((2, 1), 5.0 * i + 1.0))
    finally:
        pred.stop()


def test_mixed_signature_requests_all_complete(tmp_path):
    """Minority-signature requests ride the FIFO backlog and complete under
    sustained majority-signature load (no starvation)."""
    import threading

    from tensorflowonspark_tpu.serving import _Predictor
    from tensorflowonspark_tpu.train import export as export_mod

    predict_fn, params, model_state = export_mod.load_model(_bundle(tmp_path))
    pred = _Predictor(predict_fn, params, model_state)
    try:
        outs = {}
        errors = []

        def majority(i):
            try:
                x = np.full((4, 2), float(i), np.float32)
                for _ in range(10):
                    outs[("maj", i)] = pred.submit({"x": x})
            except Exception as e:
                errors.append(e)

        def minority():
            try:
                # different dtype+width signature: never coalesces with the
                # majority stream
                x = np.full((2, 2), 9.0, np.float64)
                for _ in range(5):
                    outs["min"] = pred.submit({"x": x})
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=majority, args=(i,)) for i in range(6)]
        threads.append(threading.Thread(target=minority))
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        np.testing.assert_allclose(outs["min"]["y_"], np.full((2, 1), 46.0))
        np.testing.assert_allclose(outs[("maj", 3)]["y_"], np.full((4, 1), 16.0))
    finally:
        pred.stop()


def test_batch_inference_cli(tmp_path):
    """The Inference.scala:52-79 analogue: TFRecord shards in, prediction
    shards out (VERDICT r2 item 4a)."""
    from tensorflowonspark_tpu import tfrecord
    from tensorflowonspark_tpu.serving import run_batch_inference

    bundle = _bundle(tmp_path)
    data_dir = str(tmp_path / "records")
    import os

    os.makedirs(data_dir)
    rows = [([float(i), float(2 * i)], i) for i in range(10)]
    for s in range(2):
        with tfrecord.TFRecordWriter(os.path.join(data_dir, "part-{:05d}".format(s))) as w:
            for feats, label in rows[s * 5 : (s + 1) * 5]:
                w.write(tfrecord.encode_example({"x": feats, "label": [label]}))

    out_dir = str(tmp_path / "preds")
    total = run_batch_inference(
        data_dir, bundle, out_dir, batch_size=4,
        input_mapping={"x": "x"}, output_mapping={"y_": "prediction"},
    )
    assert total == 10
    shards = sorted(os.listdir(out_dir))
    assert shards == ["part-00000.jsonl", "part-00001.jsonl"]
    preds = []
    for shard in shards:
        with open(os.path.join(out_dir, shard)) as f:
            preds.extend(json.loads(line) for line in f)
    assert len(preds) == 10
    # y = 2*x0 + 3*x1 + 1 = 2i + 6i + 1
    np.testing.assert_allclose(
        [p["prediction"][0] for p in preds], [8.0 * i + 1.0 for i in range(10)]
    )


def test_batch_inference_through_live_server(tmp_path, server):
    """TFRecord shard → RUNNING server (binary tensor lane) → output shard —
    the full JVM-story round trip (VERDICT r2 item 4 done-criterion)."""
    from tensorflowonspark_tpu import tfrecord
    from tensorflowonspark_tpu.serving import run_batch_inference

    data_dir = str(tmp_path / "records")
    import os

    os.makedirs(data_dir)
    with tfrecord.TFRecordWriter(os.path.join(data_dir, "part-00000")) as w:
        for i in range(7):
            w.write(tfrecord.encode_example({"x": [float(i), 1.0]}))
    out_dir = str(tmp_path / "preds")
    total = run_batch_inference(
        data_dir, None, out_dir, batch_size=3, server=server.address,
    )
    assert total == 7
    with open(os.path.join(out_dir, "part-00000.jsonl")) as f:
        preds = [json.loads(line) for line in f]
    np.testing.assert_allclose(
        [p["y_"][0] for p in preds], [2.0 * i + 4.0 for i in range(7)]
    )


def test_batch_inference_cli_tfrecord_output(tmp_path):
    from tensorflowonspark_tpu import tfrecord
    from tensorflowonspark_tpu.serving import run_batch_inference

    bundle = _bundle(tmp_path)
    data_dir = str(tmp_path / "records")
    import os

    os.makedirs(data_dir)
    with tfrecord.TFRecordWriter(os.path.join(data_dir, "part-00000")) as w:
        for i in range(4):
            w.write(tfrecord.encode_example({"x": [float(i), 0.0]}))
    out_dir = str(tmp_path / "preds")
    run_batch_inference(data_dir, bundle, out_dir, out_format="tfrecord")
    (shard,) = sorted(os.listdir(out_dir))
    recs = list(tfrecord.read_records(os.path.join(out_dir, shard)))
    assert len(recs) == 4
    feats = tfrecord.decode_example(recs[2])
    np.testing.assert_allclose(feats["y_"][1], [5.0])


# -- trust model: npz safe lane + trusted builder (VERDICT r3 weak 4) --------


def _linear_builder():
    def predict(params, model_state, arrays):
        return {"y_": arrays["x"] @ params["w"] + params["b"]}

    return predict


def test_export_writes_npz_weights_not_pickle(tmp_path):
    import os

    path = _bundle(tmp_path)
    assert os.path.isfile(os.path.join(path, "weights.npz"))
    assert not os.path.isfile(os.path.join(path, "weights.pkl"))
    # and npz loads with pickle disabled (plain arrays only)
    with np.load(os.path.join(path, "weights.npz"), allow_pickle=False) as z:
        assert "params/w" in z.files


def test_trusted_builder_loads_without_unpickling_anything(tmp_path):
    """With trusted_builder + npz weights, a tampered predict_builder.pkl is
    never even opened — the no-code-execution contract of the safe lane."""
    import os

    from tensorflowonspark_tpu.train import export as export_mod

    path = _bundle(tmp_path)
    with open(os.path.join(path, "predict_builder.pkl"), "wb") as f:
        f.write(b"\x80\x04TAMPERED-NOT-A-PICKLE")
    predict_fn, params, model_state = export_mod.load_model(
        path, trusted_builder=_linear_builder
    )
    out = predict_fn(params, model_state, {"x": np.ones((1, 2), np.float32)})
    np.testing.assert_allclose(out["y_"], [[6.0]])


def test_trusted_builder_refuses_pickled_weights(tmp_path):
    """A non-dict-tree state falls back to pickled weights; the safe lane
    must refuse such a bundle instead of silently unpickling."""
    import pytest

    from tensorflowonspark_tpu.train import export as export_mod

    path = str(tmp_path / "listy")
    # list-valued leaf container -> no npz lane
    export_mod.export_model(
        path, _linear_builder,
        {"w": [np.zeros((2, 1), np.float32)], "b": np.zeros(1, np.float32)},
    )
    import os

    assert os.path.isfile(os.path.join(path, "weights.pkl"))
    with pytest.raises(ValueError, match="pickled weights"):
        export_mod.load_model(path, trusted_builder=_linear_builder)
    # ...but the default (trusted-artifact) path still loads it
    predict_fn, params, _ = export_mod.load_model(path)
    assert isinstance(params["w"], list)


def test_resolve_builder_specs():
    import pytest

    from tensorflowonspark_tpu.train.export import resolve_builder

    assert resolve_builder("os.path:join") is __import__("os.path").path.join
    assert resolve_builder("os.path.join") is __import__("os.path").path.join
    assert resolve_builder(_linear_builder) is _linear_builder
    with pytest.raises(ValueError, match="trusted_builder"):
        resolve_builder("no-colon-no-dot")


def test_server_with_trusted_builder_end_to_end(tmp_path):
    from tensorflowonspark_tpu.serving import InferenceClient, InferenceServer

    srv = InferenceServer(_bundle(tmp_path), trusted_builder=_linear_builder)
    srv.start()
    try:
        client = InferenceClient(srv.address)
        out = client.predict(x=[[1.0, 1.0]])
        np.testing.assert_allclose(out["y_"], [[6.0]])
        client.close()
    finally:
        srv.stop()


def test_npz_lane_preserves_bfloat16(tmp_path):
    """The flagship LM exports bf16 params; npz must round-trip ml_dtypes
    exactly (raw savez would reload them as unusable void arrays)."""
    import ml_dtypes

    from tensorflowonspark_tpu.train import export as export_mod

    w = np.arange(6, dtype=np.float32).reshape(2, 3).astype(ml_dtypes.bfloat16)
    s = np.float32(2.5).astype(ml_dtypes.bfloat16)  # 0-d leaf
    path = str(tmp_path / "bf16")
    export_mod.export_model(path, _linear_builder, {"w": w, "nested": {"s": s}})
    import os

    assert os.path.isfile(os.path.join(path, "weights.npz"))
    _, params, _ = export_mod.load_model(path, trusted_builder=_linear_builder)
    assert params["w"].dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(params["w"], w)
    assert params["nested"]["s"].dtype == ml_dtypes.bfloat16
    assert params["nested"]["s"].shape == ()
    assert float(params["nested"]["s"]) == 2.5


def test_empty_subtree_falls_back_to_pickle(tmp_path):
    """npz can't represent an empty dict subtree; such states take the
    pickle lane so the reloaded structure is identical."""
    import os

    from tensorflowonspark_tpu.train import export as export_mod

    path = str(tmp_path / "emptysub")
    export_mod.export_model(
        path, _linear_builder, {"w": np.zeros((2, 1), np.float32), "extra": {}}
    )
    assert os.path.isfile(os.path.join(path, "weights.pkl"))
    _, params, _ = export_mod.load_model(path)
    assert params["extra"] == {}


def test_reexport_removes_stale_weight_lane(tmp_path):
    """Re-exporting into the same dir with the other weights lane must not
    leave the previous lane's file where load_model would prefer it."""
    import os

    from tensorflowonspark_tpu.train import export as export_mod

    path = str(tmp_path / "reexport")
    export_mod.export_model(path, _linear_builder, {"w": np.full((2, 1), 7.0, np.float32),
                                                    "b": np.zeros(1, np.float32)})
    assert os.path.isfile(os.path.join(path, "weights.npz"))
    # second export: list leaf -> pickle lane; the npz from export 1 must go
    export_mod.export_model(path, _linear_builder,
                            {"w": [np.zeros((2, 1), np.float32)], "b": np.zeros(1, np.float32)})
    assert os.path.isfile(os.path.join(path, "weights.pkl"))
    assert not os.path.isfile(os.path.join(path, "weights.npz"))
    _, params, _ = export_mod.load_model(path)
    assert isinstance(params["w"], list), "must serve the NEW export's params"


def test_trusted_builder_refuses_legacy_checkpoint_bundle(tmp_path):
    """The safe lane must refuse the legacy orbax fallback too — it parses
    bundle-dir bytes, which the lane promises never to do."""
    import os

    import pytest

    from tensorflowonspark_tpu.train import export as export_mod

    path = str(tmp_path / "legacy")
    os.makedirs(os.path.join(path, "checkpoint"))
    with open(os.path.join(path, "predict_builder.pkl"), "wb") as f:
        f.write(b"irrelevant")
    with pytest.raises(ValueError, match="legacy checkpoint"):
        export_mod.load_model(path, trusted_builder=_linear_builder)


def test_binary_lane_mixed_dtype_columns(tmp_path):
    """Python twin of the JVM genericBinaryColumnsMultiDtype JUnit test
    (and of scripts/jvm_crosscheck.py's bundle): an f32 matrix + an i64
    per-row column through the binary lane in one request."""
    from tensorflowonspark_tpu.serving import InferenceClient, InferenceServer
    from tensorflowonspark_tpu.train import export as export_mod

    def builder():
        def predict(params, model_state, arrays):
            y = arrays["x"] @ params["w"] + params["b"]
            if "z" in arrays:
                y = y + arrays["z"].astype(y.dtype)
            return {"y_": y}

        return predict

    path = str(tmp_path / "mixed")
    export_mod.export_model(
        path, builder,
        {"w": np.array([[2.0], [3.0]], np.float32), "b": np.array([1.0], np.float32)},
    )
    srv = InferenceServer(path)
    srv.start()
    try:
        client = InferenceClient(srv.address)
        out = client.predict_binary(
            x=np.array([[1, 1], [0, 0]], np.float32),
            z=np.array([[10], [-4]], np.int64),
        )
        np.testing.assert_allclose(out["y_"], [[16.0], [-3.0]])
        # without z the same bundle serves the plain linear model
        out2 = client.predict_binary(x=np.array([[1, 1]], np.float32))
        np.testing.assert_allclose(out2["y_"], [[6.0]])
        client.close()
    finally:
        srv.stop()


def test_overload_sheds_with_error():
    """A full pending queue sheds NEW requests with Overloaded instead of
    growing an unbounded backlog behind a slow model (VERDICT r4: the
    serving tail needs a queue cap, not hope)."""
    import threading
    import time

    from tensorflowonspark_tpu.serving import Overloaded, _Predictor

    release = threading.Event()

    def slow_fn(params, model_state, arrays):
        release.wait(30)
        return {"y": arrays["x"].sum(axis=1, keepdims=True)}

    # the pending bound is exact — it counts the in-flight request too, so
    # capacity 3 = 1 blocked in dispatch + 2 queued
    pred = _Predictor(slow_fn, None, None, max_pending=3)
    # obs counters are process-global and cumulative across tests: take deltas
    requests_before = pred._requests_c.value
    shed_before = pred._shed_over_c.value
    latency_before = pred._latency_h.count
    try:
        results, errors = [], []

        def call(rows):
            try:
                results.append(pred.submit({"x": np.ones((rows, 2), np.float32)}))
            except Exception as e:
                errors.append(e)

        # first request enters the dispatch and blocks the predictor thread
        threads = [threading.Thread(target=call, args=(4,))]
        threads[0].start()
        time.sleep(0.4)
        # two more fill the bounded queue
        for _ in range(2):
            t = threading.Thread(target=call, args=(4,))
            t.start()
            threads.append(t)
        time.sleep(0.4)
        with pytest.raises(Overloaded):
            pred.submit({"x": np.ones((1, 2), np.float32)})
        release.set()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert len(results) == 3  # everything accepted was served
        # metrics saw what happened: 4 submits, 1 shed, 3 latencies observed
        assert pred._requests_c.value - requests_before == 4
        assert pred._shed_over_c.value - shed_before == 1
        assert pred._latency_h.count - latency_before == 3
    finally:
        release.set()
        pred.stop()


def test_deadline_sheds_stale_queued_requests():
    """A request still queued past its deadline fails fast with
    DeadlineExceeded instead of being served arbitrarily late (VERDICT r4:
    p99 must be bounded by policy, not by the backlog draining)."""
    import threading
    import time

    from tensorflowonspark_tpu.serving import DeadlineExceeded, _Predictor

    release = threading.Event()

    def slow_fn(params, model_state, arrays):
        release.wait(30)
        return {"y": arrays["x"].sum(axis=1, keepdims=True)}

    pred = _Predictor(slow_fn, None, None, deadline_ms=200)
    shed_before = pred._shed_deadline_c.value
    try:
        results, errors = [], []

        def call():
            try:
                results.append(pred.submit({"x": np.ones((2, 2), np.float32)}))
            except Exception as e:
                errors.append(e)

        t0 = threading.Thread(target=call)  # dequeued in time, slow dispatch
        t0.start()
        time.sleep(0.4)
        t1 = threading.Thread(target=call)  # queued; deadline passes waiting
        t1.start()
        time.sleep(0.4)
        release.set()
        t0.join(timeout=60)
        t1.join(timeout=60)
        assert len(results) == 1  # the in-flight one completed
        assert len(errors) == 1 and isinstance(errors[0], DeadlineExceeded), errors
        assert pred._shed_deadline_c.value - shed_before == 1
    finally:
        release.set()
        pred.stop()


def test_coalesce_respects_max_rows_cap():
    """A request that would push the coalesced batch past max_rows is
    deferred to the next dispatch (ADVICE r4): every dispatch shape stays
    within the operator's bound, preserving the padding buckets' XLA
    shape-reuse guarantee."""
    import threading
    import time

    from tensorflowonspark_tpu.serving import _Predictor

    shapes = []
    release = threading.Event()
    first = threading.Event()

    def fn(params, model_state, arrays):
        shapes.append(arrays["x"].shape[0])
        if not first.is_set():
            first.set()
            release.wait(30)
        return {"y": arrays["x"].sum(axis=1, keepdims=True)}

    pred = _Predictor(fn, None, None, max_rows=8)
    try:
        outs, errors = {}, []

        def call(i):
            try:
                outs[i] = pred.submit({"x": np.full((3, 2), float(i), np.float32)})
            except Exception as e:
                errors.append(e)

        blocker = threading.Thread(
            target=lambda: outs.setdefault("b", pred.submit({"x": np.ones((1, 2), np.float32)}))
        )
        blocker.start()
        assert first.wait(30)
        threads = [threading.Thread(target=call, args=(i,)) for i in range(3)]
        for t in threads:
            t.start()
        time.sleep(0.5)  # let all three queue behind the blocked dispatch
        release.set()
        blocker.join(timeout=60)
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        # 3+3+3 must NOT fuse into one 9-row (> max_rows) dispatch
        assert max(shapes) <= 8, shapes
        for i in range(3):
            np.testing.assert_allclose(outs[i]["y"], np.full((3, 1), 2.0 * i))
    finally:
        release.set()
        pred.stop()


def test_predictor_stop_is_idempotent():
    """A second stop() must not block: with the bounded queue, a second
    sentinel could fill the +1 slot and deadlock while holding the submit
    lock (server shutdown paths can reach stop() more than once)."""
    import threading

    from tensorflowonspark_tpu.serving import _Predictor

    pred = _Predictor(lambda p, ms, a: {"y": a["x"]}, None, None, max_pending=1)
    pred.stop()
    second = threading.Thread(target=pred.stop)
    second.start()
    second.join(timeout=10)
    assert not second.is_alive(), "second stop() blocked"
    with pytest.raises(RuntimeError):
        pred.submit({"x": np.ones((1, 2), np.float32)})
