"""Inference server (the JVM-inference equivalent) — VERDICT round-1 item 10.

The byte-level test speaks the wire protocol with raw sockets, framing
messages exactly as jvm/.../InferenceClient.java does (4-byte big-endian
length + UTF-8 JSON), so the JVM contract is pinned without a JVM in the
image. Reference analogue: Scala Inference.scala/TFModel.scala batch
inference from Spark executors.
"""

import json
import socket
import struct

import numpy as np
import pytest

from tensorflowonspark_tpu.serving import InferenceClient, InferenceServer
from tensorflowonspark_tpu.train import export


def _bundle(tmp_path):
    """A linear y = x @ w + b bundle, like the pipeline's export."""
    w = np.array([[2.0], [3.0]], np.float32)
    b = np.array([1.0], np.float32)

    def predict_builder():
        def predict(params, model_state, arrays):
            return {"y_": arrays["x"] @ params["w"] + params["b"]}

        return predict

    path = str(tmp_path / "bundle")
    export.export_model(path, predict_builder, {"w": w, "b": b})
    return path


@pytest.fixture
def server(tmp_path):
    srv = InferenceServer(_bundle(tmp_path))
    srv.start()
    yield srv
    srv.stop()


def _jvm_style_request(address, payload_text):
    """Frame and send exactly like the Java client: writeInt + UTF-8 bytes."""
    with socket.create_connection(address, timeout=30) as sock:
        data = payload_text.encode("utf-8")
        sock.sendall(struct.pack(">I", len(data)) + data)
        header = b""
        while len(header) < 4:
            header += sock.recv(4 - len(header))
        (length,) = struct.unpack(">I", header)
        body = b""
        while len(body) < length:
            body += sock.recv(length - len(body))
        return json.loads(body.decode("utf-8"))


def test_raw_socket_protocol(server):
    assert _jvm_style_request(server.address, '{"type": "ping"}') == {"type": "pong"}
    info = _jvm_style_request(server.address, '{"type": "info"}')
    assert info["ready"] is True

    reply = _jvm_style_request(
        server.address,
        '{"type": "predict", "inputs": {"x": [[1.0, 1.0], [0.0, 2.0]]}}',
    )
    assert reply["type"] == "result"
    np.testing.assert_allclose(reply["outputs"]["y_"], [[6.0], [7.0]])


def test_error_reply_for_unknown_type(server):
    reply = _jvm_style_request(server.address, '{"type": "wat"}')
    assert reply["type"] == "error"


def test_python_client_roundtrip(server):
    client = InferenceClient(server.address)
    try:
        assert client.ping()
        out = client.predict(x=np.array([[1.0, 2.0], [3.0, 0.5]], np.float32))
        np.testing.assert_allclose(out["y_"], [[9.0], [8.5]])
        # persistent connection: a second request on the same socket
        out2 = client.predict(x=[[0.0, 0.0]])
        np.testing.assert_allclose(out2["y_"], [[1.0]])
    finally:
        client.close()


def test_predict_failure_surfaces(server):
    client = InferenceClient(server.address)
    try:
        with pytest.raises(RuntimeError):
            client.predict(wrong_column=[[1.0]])
    finally:
        client.close()
