"""Model-axis parallelism gates: every model-sharding path — dp×tp,
dp×fsdp×tp, 1F1B pipeline, ring attention on real TextPipeline slabs — must
be a pure placement/scheduling change, never a numerics change. Each path is
held to a numeric-parity gate against its single-axis reference, and the
measured accounting (bubble fraction, overlap fraction, sharded-param
gauges) must be live and in range."""

import os

import numpy as np
import pytest

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax
import jax.numpy as jnp

from tensorflowonspark_tpu import obs, parallel, tfrecord
from tensorflowonspark_tpu.data import TextPipeline, Tokenizer
from tensorflowonspark_tpu.models import transformer
from tensorflowonspark_tpu.parallel.pipeline_parallel import (
    Pipeline1F1B,
    schedule_1f1b,
    split_microbatches,
)
from tensorflowonspark_tpu.train.strategy import SyncDataParallel

CFG = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
           dtype="float32")


def _mesh(axes):
    if jax.device_count() < 8:
        pytest.skip("needs 8 cpu devices (XLA_FLAGS set too late)")
    return parallel.local_mesh(axes)


def _packed_batch(rows=8, l=24, seed=3):
    """Packed [rows, l] batch: two sequences (ids 1, 2) plus a pad tail."""
    rng = np.random.default_rng(seed)
    s1 = rng.integers(3, 64, 11).astype(np.int32)
    s2 = rng.integers(3, 64, 7).astype(np.int32)
    tokens = np.zeros((rows, l), np.int32)
    seg = np.zeros((rows, l), np.int32)
    pos = np.zeros((rows, l), np.int32)
    tokens[:, :11] = s1
    seg[:, :11] = 1
    pos[:, :11] = np.arange(11)
    tokens[:, 11:18] = s2
    seg[:, 11:18] = 2
    pos[:, 11:18] = np.arange(7)
    return tokens, seg, pos


def _ref_params():
    model = transformer.create_model(attention="plain", **CFG)
    return model, model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
    )["params"]


class TestTensorParallel:
    """dp×tp (and dp×fsdp×tp) placement through ``transformer.param_specs``
    must reproduce the replicated model's packed logits bit-for-bit up to
    float tolerance — TP is a layout, not a different network."""

    def _parity(self, strategy, atol=2e-5):
        ref_model, params = _ref_params()
        tokens, seg, pos = _packed_batch()
        ref = ref_model.apply(
            {"params": params}, jnp.asarray(tokens),
            positions=jnp.asarray(pos), segment_ids=jnp.asarray(seg),
        )
        sharded = jax.device_put(params, strategy.param_shardings(params))
        model = transformer.create_model(
            mesh=strategy.mesh, attention="plain", **CFG
        )
        got = model.apply(
            {"params": sharded}, jnp.asarray(tokens),
            positions=jnp.asarray(pos), segment_ids=jnp.asarray(seg),
        )
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref), atol=atol)
        return sharded

    def test_dp_tp_logits_match_replicated(self):
        mesh = _mesh({"dp": 2, "tp": 4})
        strategy = SyncDataParallel(mesh, tp=transformer.param_specs)
        sharded = self._parity(strategy)
        axes = {
            a
            for leaf in jax.tree.leaves(sharded)
            for part in leaf.sharding.spec
            if part is not None
            for a in ((part,) if isinstance(part, str) else part)
        }
        assert axes == {"tp"}
        # 2 layers × (q k v o + wi wo) + lm_head all carry a tp dim
        assert obs.gauge("tp_params_sharded").value == 13

    def test_dp_fsdp_tp_overlay_matches_replicated(self):
        mesh = _mesh({"dp": 2, "fsdp": 2, "tp": 2})
        strategy = SyncDataParallel(
            mesh, fsdp=True, min_weight_size=1, tp=transformer.param_specs
        )
        sharded = self._parity(strategy)
        axes = {
            a
            for leaf in jax.tree.leaves(sharded)
            for part in leaf.sharding.spec
            if part is not None
            for a in ((part,) if isinstance(part, str) else part)
        }
        # tp rules place the model dims, the ZeRO-3 overlay shards the rest
        assert "tp" in axes and "fsdp" in axes

    def test_tp_requires_mesh_axis(self):
        mesh = _mesh({"dp": 8})
        with pytest.raises(ValueError, match="'tp' axis"):
            SyncDataParallel(mesh, tp=transformer.param_specs)

    def test_tp_requires_placement_rules(self):
        mesh = _mesh({"dp": 2, "tp": 4})
        with pytest.raises(ValueError, match="placement rules"):
            SyncDataParallel(mesh, tp=True)

    def test_tp_rejects_two_different_rule_fns(self):
        mesh = _mesh({"dp": 2, "tp": 4})
        with pytest.raises(ValueError, match="once"):
            SyncDataParallel(
                mesh, tp=transformer.param_specs,
                param_spec_fn=lambda p, m: p,
            )

    def test_undersized_dims_degrade_to_replicated(self):
        # n_heads=2 cannot shard over tp=4: the head dim must drop its axis
        # (not error), same degrade contract as the fsdp rules
        mesh = _mesh({"dp": 2, "tp": 4})
        cfg = dict(CFG, n_heads=2)
        model = transformer.create_model(attention="plain", **cfg)
        params = model.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 16), jnp.int32)
        )["params"]
        specs = transformer.param_specs(params, mesh)
        flat = jax.tree_util.tree_flatten_with_path(specs)[0]
        for path, spec in flat:
            key = "/".join(p.key for p in path)
            if "attn/q/kernel" in key:
                assert spec[1] is None  # H=2 % 4 != 0 → replicated
            if "mlp/wi/kernel" in key:
                assert "tp" in spec  # d_ff=64 still shards


class TestPipeline1F1B:
    """The 1F1B schedule and host-driven pipeline: exact loss/grad parity
    with the sequential (single-device) reference, measured bubble and
    overlap accounting live and in range."""

    def test_schedule_shape_and_memory_bound(self):
        P, M = 4, 6
        for s in range(P):
            ops = schedule_1f1b(s, P, M)
            assert [m for op, m in ops if op == "F"] == list(range(M))
            assert [m for op, m in ops if op == "B"] == list(range(M))
            # every F precedes its own B
            for m in range(M):
                assert ops.index(("F", m)) < ops.index(("B", m))
            # ≤ P - s activation stashes in flight (the 1F1B contract)
            depth = peak = 0
            for op, _m in ops:
                depth += 1 if op == "F" else -1
                peak = max(peak, depth)
            assert peak == min(P - s, M)

    def _stages(self, n_stages=4, width=16, seed=0):
        rng = np.random.default_rng(seed)
        params = [
            {"w": jnp.asarray(rng.standard_normal((width, width)) / 4.0,
                              jnp.float32)}
            for _ in range(n_stages)
        ]

        def stage_fn(p, x):
            return jnp.tanh(x @ p["w"])

        def loss_fn(y, target):
            return jnp.mean((y - target) ** 2)

        return stage_fn, params, loss_fn

    @pytest.mark.parametrize("overlap", [True, False])
    def test_loss_and_grads_match_sequential(self, overlap):
        if jax.device_count() < 4:
            pytest.skip("needs 4 cpu devices")
        stage_fn, params, loss_fn = self._stages()
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
        t = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)

        def sequential(params_list, x, t):
            y = x
            for p in params_list:
                y = stage_fn(p, y)
            return loss_fn(y, t)

        ref_loss, ref_grads = jax.value_and_grad(sequential)(params, x, t)

        pipe = Pipeline1F1B(stage_fn, params, loss_fn, overlap=overlap)
        try:
            loss, grads = pipe.step(
                split_microbatches(x, 8), split_microbatches(t, 8)
            )
            assert abs(float(loss) - float(ref_loss)) <= 1e-6
            for ref_g, got_g in zip(ref_grads, grads):
                np.testing.assert_allclose(
                    np.asarray(got_g["w"]), np.asarray(ref_g["w"]), atol=1e-5
                )
            stats = pipe.last_stats
            assert stats["n_stages"] == 4 and stats["n_microbatches"] == 8
            assert 0.0 <= stats["bubble_fraction"] <= 1.0
            assert 0.0 <= stats["overlap_fraction"] <= 1.0
            assert stats["comm_busy_s"] > 0.0
            assert obs.gauge("pipeline_bubble_fraction").value == pytest.approx(
                stats["bubble_fraction"]
            )
        finally:
            pipe.close()

    def test_grad_accumulation_weights_microbatches_equally(self):
        # 1 stage, M microbatches: grads must equal grad(mean-of-means loss)
        stage_fn, params, loss_fn = self._stages(n_stages=1)
        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
        t = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)

        def mean_of_micro(p, x, t):
            xs, ts = split_microbatches(x, 4), split_microbatches(t, 4)
            return jnp.mean(
                jnp.stack([loss_fn(stage_fn(p, xs[m]), ts[m]) for m in range(4)])
            )

        ref_loss, ref_grad = jax.value_and_grad(mean_of_micro)(params[0], x, t)
        pipe = Pipeline1F1B(stage_fn, params, loss_fn, overlap=False)
        try:
            loss, grads = pipe.step(
                split_microbatches(x, 4), split_microbatches(t, 4)
            )
            assert abs(float(loss) - float(ref_loss)) <= 1e-6
            np.testing.assert_allclose(
                np.asarray(grads[0]["w"]), np.asarray(ref_grad["w"]), atol=1e-5
            )
        finally:
            pipe.close()


class TestRingOnTextSlabs:
    """Ring attention on real packed [B, L] slabs from TextPipeline — the
    exact tensors the lm workload feeds — at a sequence length that does NOT
    divide the ring, so the pad-to-ring-multiple path runs on real data."""

    def _slab(self, tmp_path, seq_len=46, batch_size=4):
        rng = np.random.default_rng(11)
        words = "ring attention shards long sequence slabs over devices".split()
        texts = [
            " ".join(rng.choice(words, size=max(2, int(rng.lognormal(2.2, 0.7)))))
            for _ in range(96)
        ]
        d = tmp_path / "corpus"
        d.mkdir()
        path = str(d / "part-00000")
        with tfrecord.TFRecordWriter(path) as w:
            for t in texts:
                w.write(t.encode("utf-8"))
        pipe = TextPipeline(
            [path], Tokenizer(kind="word", vocab_size=64),
            seq_len=seq_len, batch_size=batch_size, seed=7,
        )
        batch = next(iter(pipe))
        assert batch["tokens"].shape == (batch_size, seq_len)
        assert (np.asarray(batch["segment_ids"]) > 0).any()
        return batch

    def test_ring_logits_match_plain_on_pipeline_batch(self, tmp_path):
        mesh = _mesh({"dp": 2, "sp": 4})
        batch = self._slab(tmp_path)  # L=46: 46 % 4 != 0 → pad path
        ref_model, params = _ref_params()
        ref = ref_model.apply(
            {"params": params}, jnp.asarray(batch["tokens"]),
            positions=jnp.asarray(batch["positions"]),
            segment_ids=jnp.asarray(batch["segment_ids"]),
        )
        ring = transformer.create_model(mesh=mesh, attention="ring", **CFG)
        got = ring.apply(
            {"params": params}, jnp.asarray(batch["tokens"]),
            positions=jnp.asarray(batch["positions"]),
            segment_ids=jnp.asarray(batch["segment_ids"]),
        )
        real = np.asarray(batch["segment_ids"]) > 0
        np.testing.assert_allclose(
            np.asarray(got)[real], np.asarray(ref)[real], atol=2e-5
        )
