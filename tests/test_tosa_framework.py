"""Framework-level tests for tosa: suppressions, baseline workflow, the
CLI contract, and the self-run gate asserting this repo is clean."""

import json
import os
import subprocess
import sys
import textwrap

from tosa_testutil import REPO_ROOT, run_rule
from tosa import ALL_CHECKERS, analyze_source, core, make_checkers


def _src(s):
    return textwrap.dedent(s).lstrip()


BAD_SLEEP = _src("""
    import time

    def wait(q):
        while q.empty():
            time.sleep(0.1)
""")


class TestSuppressions:
    def test_inline_disable_silences_with_reason(self):
        src = BAD_SLEEP.replace(
            "time.sleep(0.1)",
            "time.sleep(0.1)  # tosa: disable=retry-discipline -- fixture needs a raw sleep",
        )
        findings = analyze_source(src, "mod.py", make_checkers(["retry-discipline"]))
        assert len(findings) == 1
        assert findings[0].suppressed == "fixture needs a raw sleep"
        assert core.gating(findings) == []

    def test_disable_of_other_rule_does_not_silence(self):
        src = BAD_SLEEP.replace(
            "time.sleep(0.1)",
            "time.sleep(0.1)  # tosa: disable=jit-purity -- wrong rule",
        )
        findings = analyze_source(src, "mod.py", make_checkers(["retry-discipline"]))
        assert len(core.gating(findings)) == 1

    def test_disable_all_silences_everything(self):
        src = BAD_SLEEP.replace(
            "time.sleep(0.1)",
            "time.sleep(0.1)  # tosa: disable=all -- kitchen sink",
        )
        findings = analyze_source(src, "mod.py", make_checkers(["retry-discipline"]))
        assert core.gating(findings) == []


class TestBaseline:
    def test_baselined_finding_does_not_gate(self, tmp_path):
        findings = analyze_source(BAD_SLEEP, "mod.py", make_checkers(["retry-discipline"]))
        assert len(core.gating(findings)) == 1
        bl = tmp_path / "baseline.json"
        core.write_baseline(str(bl), findings)
        fresh = analyze_source(BAD_SLEEP, "mod.py", make_checkers(["retry-discipline"]))
        fresh = core.apply_baseline(fresh, core.load_baseline(str(bl)))
        assert core.gating(fresh) == []
        assert all(f.baselined for f in fresh)

    def test_fingerprint_is_line_free(self):
        shifted = "# a leading comment\n# another\n" + BAD_SLEEP
        a = analyze_source(BAD_SLEEP, "mod.py", make_checkers(["retry-discipline"]))
        b = analyze_source(shifted, "mod.py", make_checkers(["retry-discipline"]))
        assert a[0].line != b[0].line
        assert a[0].fingerprint == b[0].fingerprint

    def test_baseline_allowance_is_counted(self):
        # one baseline entry grandfathers ONE occurrence; a second identical
        # finding still gates
        doubled = BAD_SLEEP.replace(
            "time.sleep(0.1)", "time.sleep(0.1)\n        time.sleep(0.1)"
        )
        findings = analyze_source(doubled, "mod.py", make_checkers(["retry-discipline"]))
        assert len(findings) == 2
        baseline = {findings[0].fingerprint: 1}
        findings = core.apply_baseline(findings, baseline)
        assert len(core.gating(findings)) == 1


class TestRegistry:
    def test_all_thirteen_rules_registered(self):
        assert set(ALL_CHECKERS) == {
            "jit-host-sync", "jit-purity", "retry-discipline",
            "lock-discipline", "lock-order", "chaos-obs-coverage",
            "import-hygiene", "donation-safety", "metrics-contract",
            "trace-discipline", "commit-discipline", "thread-lifecycle",
            "env-lane",
        }

    def test_unknown_rule_fails_loudly(self):
        try:
            make_checkers(["no-such-rule"])
        except KeyError as e:
            assert "no-such-rule" in e.args[0]
        else:
            raise AssertionError("expected KeyError")

    def test_parse_error_is_reported_not_raised(self):
        findings = analyze_source("def broken(:\n", "mod.py", make_checkers())
        assert len(findings) == 1
        assert findings[0].rule == "parse-error"


def _run_cli(args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tosa"] + args,
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=120,
    )


class TestCLI:
    def test_json_report_and_exit_code(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SLEEP)
        proc = _run_cli(
            ["--json", "--root", str(tmp_path), "--baseline", str(tmp_path / "bl.json"), str(bad)]
        )
        assert proc.returncode == 1, proc.stderr
        report = json.loads(proc.stdout)
        assert report["gating"] == 1
        assert report["files_analyzed"] == 1
        [finding] = report["findings"]
        assert finding["rule"] == "retry-discipline"
        assert finding["path"] == "bad.py"

    def test_write_baseline_then_clean(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SLEEP)
        bl = tmp_path / "bl.json"
        args = ["--root", str(tmp_path), "--baseline", str(bl), str(bad)]
        proc = _run_cli(["--write-baseline"] + args)
        assert proc.returncode == 0, proc.stderr
        assert json.loads(bl.read_text())["findings"]
        proc = _run_cli(args)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "1 baselined" in proc.stdout

    def test_rules_filter_runs_only_selected(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SLEEP)
        proc = _run_cli(
            ["--rules", "import-hygiene", "--root", str(tmp_path),
             "--baseline", str(tmp_path / "bl.json"), str(bad)]
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_unknown_rule_is_usage_error(self):
        proc = _run_cli(["--rules", "bogus"])
        assert proc.returncode == 2
        assert "unknown rule" in proc.stderr

    def test_list_rules_covers_catalog(self):
        proc = _run_cli(["--list-rules"])
        assert proc.returncode == 0
        for rule in ALL_CHECKERS:
            assert rule in proc.stdout

    def test_sarif_report_shape(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SLEEP)
        out = tmp_path / "report.sarif"
        proc = _run_cli(
            ["--sarif", "--sarif-out", str(out), "--root", str(tmp_path),
             "--baseline", str(tmp_path / "bl.json"), str(bad)]
        )
        assert proc.returncode == 1, proc.stderr
        for payload in (proc.stdout, out.read_text()):
            sarif = json.loads(payload)
            assert sarif["version"] == "2.1.0"
            [run] = sarif["runs"]
            rule_ids = [r["id"] for r in run["tool"]["driver"]["rules"]]
            assert rule_ids == sorted(ALL_CHECKERS)
            [result] = run["results"]
            assert result["ruleId"] == "retry-discipline"
            assert rule_ids[result["ruleIndex"]] == "retry-discipline"
            loc = result["locations"][0]["physicalLocation"]
            assert loc["artifactLocation"]["uri"] == "bad.py"
            assert loc["region"]["startLine"] >= 1
            assert result["partialFingerprints"]["tosa/v1"]

    def test_changed_mode_requires_targets_and_scopes_report(self, tmp_path):
        proc = _run_cli(["--changed", "--root", str(tmp_path),
                         "--baseline", str(tmp_path / "bl.json")])
        assert proc.returncode == 2
        assert "--changed" in proc.stderr
        good = tmp_path / "good.py"
        good.write_text("def fine():\n    return 1\n")
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SLEEP)
        # only the changed file's findings are reported even though the
        # neighbor is also in the corpus being indexed
        proc = _run_cli(
            ["--changed", "--json", "--root", str(tmp_path),
             "--baseline", str(tmp_path / "bl.json"), str(good)]
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert json.loads(proc.stdout)["findings"] == []
        proc = _run_cli(
            ["--changed", "--json", "--root", str(tmp_path),
             "--baseline", str(tmp_path / "bl.json"), str(bad)]
        )
        assert proc.returncode == 1
        [finding] = json.loads(proc.stdout)["findings"]
        assert finding["path"] == "bad.py"

    def test_changed_mode_with_no_python_files_is_noop(self, tmp_path):
        doc = tmp_path / "notes.md"
        doc.write_text("prose only\n")
        proc = _run_cli(["--changed", "--root", str(tmp_path),
                         "--baseline", str(tmp_path / "bl.json"), str(doc)])
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "nothing to do" in proc.stdout


class TestIndexCache:
    def test_warm_run_skips_reparsing_and_is_faster(self, tmp_path):
        import time

        from tosa.index import build_index

        lib = os.path.join(REPO_ROOT, "tensorflowonspark_tpu")
        paths = sorted(
            os.path.join(dirpath, name)
            for dirpath, _, names in os.walk(lib)
            for name in names
            if name.endswith(".py")
        )
        assert len(paths) > 10
        cache_path = str(tmp_path / "cache.json")
        t0 = time.monotonic()
        cold = build_index(paths, root=REPO_ROOT, cache_path=cache_path)
        cold_s = time.monotonic() - t0
        assert os.path.exists(cache_path)
        t0 = time.monotonic()
        warm = build_index(paths, root=REPO_ROOT, cache_path=cache_path)
        warm_s = time.monotonic() - t0
        assert set(warm.modules) == set(cold.modules)
        assert warm.modules == cold.modules
        # the warm pass hashes file contents but never calls ast.parse;
        # generous margin so CI jitter doesn't flake the assertion
        assert warm_s < max(cold_s * 0.6, 0.05), (cold_s, warm_s)

    def test_cache_invalidated_by_content_change(self, tmp_path):
        from tosa.index import build_index

        mod = tmp_path / "mod.py"
        mod.write_text("import threading\n_lk = threading.Lock()\n")
        cache_path = str(tmp_path / "cache.json")
        first = build_index([str(mod)], root=str(tmp_path), cache_path=cache_path)
        assert first.modules["mod.py"]["module_locks"]
        mod.write_text("X = 1\n")
        second = build_index([str(mod)], root=str(tmp_path), cache_path=cache_path)
        assert not second.modules["mod.py"]["module_locks"]

    def test_stale_cache_version_is_ignored(self, tmp_path):
        from tosa import index as tosa_index

        mod = tmp_path / "mod.py"
        mod.write_text("X = 1\n")
        cache_path = str(tmp_path / "cache.json")
        tosa_index.build_index([str(mod)], root=str(tmp_path), cache_path=cache_path)
        with open(cache_path) as f:
            payload = json.load(f)
        payload["cache_version"] = -1
        with open(cache_path, "w") as f:
            json.dump(payload, f)
        cache = tosa_index.load_cache(cache_path, [])
        assert cache.files == {}


class TestSelfRun:
    def test_repo_is_clean_under_all_rules(self):
        """The hard gate: the analyzer over its default targets (library,
        bench.py, scripts) finds nothing to report — every invariant the
        thirteen rules encode holds in this repo, with an empty baseline."""
        proc = _run_cli([])
        assert proc.returncode == 0, "\n" + proc.stdout + proc.stderr

    def test_committed_baseline_is_empty(self):
        with open(os.path.join(REPO_ROOT, "tools", "analyze", "baseline.json")) as f:
            assert json.load(f) == {"findings": []}
