"""Framework-level tests for tosa: suppressions, baseline workflow, the
CLI contract, and the self-run gate asserting this repo is clean."""

import json
import os
import subprocess
import sys
import textwrap

from tosa_testutil import REPO_ROOT, run_rule
from tosa import ALL_CHECKERS, analyze_source, core, make_checkers


def _src(s):
    return textwrap.dedent(s).lstrip()


BAD_SLEEP = _src("""
    import time

    def wait(q):
        while q.empty():
            time.sleep(0.1)
""")


class TestSuppressions:
    def test_inline_disable_silences_with_reason(self):
        src = BAD_SLEEP.replace(
            "time.sleep(0.1)",
            "time.sleep(0.1)  # tosa: disable=retry-discipline -- fixture needs a raw sleep",
        )
        findings = analyze_source(src, "mod.py", make_checkers(["retry-discipline"]))
        assert len(findings) == 1
        assert findings[0].suppressed == "fixture needs a raw sleep"
        assert core.gating(findings) == []

    def test_disable_of_other_rule_does_not_silence(self):
        src = BAD_SLEEP.replace(
            "time.sleep(0.1)",
            "time.sleep(0.1)  # tosa: disable=jit-purity -- wrong rule",
        )
        findings = analyze_source(src, "mod.py", make_checkers(["retry-discipline"]))
        assert len(core.gating(findings)) == 1

    def test_disable_all_silences_everything(self):
        src = BAD_SLEEP.replace(
            "time.sleep(0.1)",
            "time.sleep(0.1)  # tosa: disable=all -- kitchen sink",
        )
        findings = analyze_source(src, "mod.py", make_checkers(["retry-discipline"]))
        assert core.gating(findings) == []


class TestBaseline:
    def test_baselined_finding_does_not_gate(self, tmp_path):
        findings = analyze_source(BAD_SLEEP, "mod.py", make_checkers(["retry-discipline"]))
        assert len(core.gating(findings)) == 1
        bl = tmp_path / "baseline.json"
        core.write_baseline(str(bl), findings)
        fresh = analyze_source(BAD_SLEEP, "mod.py", make_checkers(["retry-discipline"]))
        fresh = core.apply_baseline(fresh, core.load_baseline(str(bl)))
        assert core.gating(fresh) == []
        assert all(f.baselined for f in fresh)

    def test_fingerprint_is_line_free(self):
        shifted = "# a leading comment\n# another\n" + BAD_SLEEP
        a = analyze_source(BAD_SLEEP, "mod.py", make_checkers(["retry-discipline"]))
        b = analyze_source(shifted, "mod.py", make_checkers(["retry-discipline"]))
        assert a[0].line != b[0].line
        assert a[0].fingerprint == b[0].fingerprint

    def test_baseline_allowance_is_counted(self):
        # one baseline entry grandfathers ONE occurrence; a second identical
        # finding still gates
        doubled = BAD_SLEEP.replace(
            "time.sleep(0.1)", "time.sleep(0.1)\n        time.sleep(0.1)"
        )
        findings = analyze_source(doubled, "mod.py", make_checkers(["retry-discipline"]))
        assert len(findings) == 2
        baseline = {findings[0].fingerprint: 1}
        findings = core.apply_baseline(findings, baseline)
        assert len(core.gating(findings)) == 1


class TestRegistry:
    def test_all_six_rules_registered(self):
        assert set(ALL_CHECKERS) == {
            "jit-host-sync", "jit-purity", "retry-discipline",
            "lock-discipline", "chaos-obs-coverage", "import-hygiene",
        }

    def test_unknown_rule_fails_loudly(self):
        try:
            make_checkers(["no-such-rule"])
        except KeyError as e:
            assert "no-such-rule" in e.args[0]
        else:
            raise AssertionError("expected KeyError")

    def test_parse_error_is_reported_not_raised(self):
        findings = analyze_source("def broken(:\n", "mod.py", make_checkers())
        assert len(findings) == 1
        assert findings[0].rule == "parse-error"


def _run_cli(args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tosa"] + args,
        cwd=cwd,
        capture_output=True,
        text=True,
        timeout=120,
    )


class TestCLI:
    def test_json_report_and_exit_code(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SLEEP)
        proc = _run_cli(
            ["--json", "--root", str(tmp_path), "--baseline", str(tmp_path / "bl.json"), str(bad)]
        )
        assert proc.returncode == 1, proc.stderr
        report = json.loads(proc.stdout)
        assert report["gating"] == 1
        assert report["files_analyzed"] == 1
        [finding] = report["findings"]
        assert finding["rule"] == "retry-discipline"
        assert finding["path"] == "bad.py"

    def test_write_baseline_then_clean(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SLEEP)
        bl = tmp_path / "bl.json"
        args = ["--root", str(tmp_path), "--baseline", str(bl), str(bad)]
        proc = _run_cli(["--write-baseline"] + args)
        assert proc.returncode == 0, proc.stderr
        assert json.loads(bl.read_text())["findings"]
        proc = _run_cli(args)
        assert proc.returncode == 0, proc.stdout + proc.stderr
        assert "1 baselined" in proc.stdout

    def test_rules_filter_runs_only_selected(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SLEEP)
        proc = _run_cli(
            ["--rules", "import-hygiene", "--root", str(tmp_path),
             "--baseline", str(tmp_path / "bl.json"), str(bad)]
        )
        assert proc.returncode == 0, proc.stdout + proc.stderr

    def test_unknown_rule_is_usage_error(self):
        proc = _run_cli(["--rules", "bogus"])
        assert proc.returncode == 2
        assert "unknown rule" in proc.stderr

    def test_list_rules_covers_catalog(self):
        proc = _run_cli(["--list-rules"])
        assert proc.returncode == 0
        for rule in ALL_CHECKERS:
            assert rule in proc.stdout


class TestSelfRun:
    def test_repo_is_clean_under_all_rules(self):
        """The hard gate: the analyzer over its default targets (library,
        bench.py, scripts) finds nothing to report — every invariant the
        six rules encode holds in this repo, with an empty baseline."""
        proc = _run_cli([])
        assert proc.returncode == 0, "\n" + proc.stdout + proc.stderr

    def test_committed_baseline_is_empty(self):
        with open(os.path.join(REPO_ROOT, "tools", "analyze", "baseline.json")) as f:
            assert json.load(f) == {"findings": []}
