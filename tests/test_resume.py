"""Checkpoint → crash → resume, end to end through the cluster runtime
(SURVEY §5 "Checkpoint / resume": the reference relied on TF's
latest-checkpoint pickup, reference test_pipeline.py:130
``load_weights_on_restart``; here orbax + ``latest_checkpoint``)."""

import json
import os

import numpy as np
import pytest

from tensorflowonspark_tpu import TFCluster
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


def fn_train_with_resume(args, ctx):
    """Trains ``steps`` MORE steps from the latest checkpoint (if any),
    checkpointing every ``checkpoint_steps``; records its trajectory."""
    import jax
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint

    strategy = SyncDataParallel(parallel.local_mesh({"dp": -1}))
    model = mnist.create_model("mlp", hidden=16)
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(
        mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0)
    )
    latest = checkpoint.latest_checkpoint(args["model_dir"])
    if latest:
        # targeted restore: structure + shardings from the fresh state
        state = checkpoint.restore_checkpoint(latest, target=jax.device_get(state))
    start_step = int(jax.device_get(state.step))

    step = strategy.compile_train_step(
        mnist.make_loss_fn(model), optimizer, has_aux=True, donate=False
    )
    rng = np.random.default_rng(7)  # fixed data: loss must keep decreasing
    batch = strategy.shard_batch(
        {
            "image": rng.standard_normal((32, 28, 28)).astype(np.float32),
            "label": rng.integers(0, 10, 32),
        }
    )
    losses = []
    for i in range(args["steps"]):
        state, metrics = step(state, batch)
        jax.block_until_ready(metrics["loss"])
        losses.append(float(metrics["loss"]))
        global_step = start_step + i + 1
        if global_step % args["checkpoint_steps"] == 0:
            checkpoint.save_checkpoint(
                os.path.join(args["model_dir"], "ckpt_{}".format(global_step)),
                jax.device_get(state),
            )
    with open(os.path.join(args["model_dir"], "run_{}.json".format(start_step)), "w") as f:
        json.dump({"start_step": start_step, "losses": losses}, f)


def _run_once(model_dir):
    sc = LocalSparkContext(num_executors=1, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_train_with_resume,
            {"model_dir": model_dir, "steps": 6, "checkpoint_steps": 3},
            num_executors=1, input_mode=InputMode.TENSORFLOW, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        cluster.shutdown(timeout=240)
    finally:
        sc.stop()


@pytest.mark.slow
def test_train_crash_resume_continues_trajectory(tmp_path):
    model_dir = str(tmp_path)
    _run_once(model_dir)  # "first life": steps 1..6, ckpts at 3 and 6
    _run_once(model_dir)  # "after the crash": resumes at 6, trains 7..12

    with open(os.path.join(model_dir, "run_0.json")) as f:
        first = json.load(f)
    with open(os.path.join(model_dir, "run_6.json")) as f:
        second = json.load(f)
    assert first["start_step"] == 0
    assert second["start_step"] == 6, "second life must resume from the checkpoint"
    # the trajectory CONTINUES: the resumed run starts below where the first
    # ended (same data, restored optimizer state) and keeps improving
    assert second["losses"][0] < first["losses"][0]
    assert second["losses"][-1] < second["losses"][0]
    # checkpoints for both lives exist
    names = sorted(d for d in os.listdir(model_dir) if d.startswith("ckpt_"))
    assert names == ["ckpt_12", "ckpt_3", "ckpt_6", "ckpt_9"]
