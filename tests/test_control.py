"""The audited control core: one estimate→decide→patience→apply engine.

Unit coverage for :mod:`tensorflowonspark_tpu.control` — the shared
hysteresis :class:`Controller` every autotuner rebases onto, its estimator
and rule helpers, the clocked delta gate, and the cluster-level
:class:`ClusterScaler` the recovery ladder's regrow poll consults."""

import pytest

from tensorflowonspark_tpu import obs
from tensorflowonspark_tpu.control import (
    ClusterScaler,
    Controller,
    DeltaTicker,
    EwmaEstimator,
    StallRule,
    classify_stalls,
)


def _decisions():
    counters = obs.snapshot()["counters"]
    return (counters.get("control_decisions_total") or {}).get("value", 0.0)


# -- classification ------------------------------------------------------------


class TestClassifyStalls:
    def test_emit_pressure_means_device_bound(self):
        assert classify_stalls(1.0, 1.0, 5.0, 2.0) == "device_bound"

    def test_no_data_at_all_is_device_bound(self):
        # the regrow gate's common case: TENSORFLOW-mode nodes read their
        # own data, so the cluster counters are all zero — compute is the
        # gate and growing is allowed
        assert classify_stalls(0.0, 0.0, 0.0, 0.0) == "device_bound"

    def test_starved_consumer_splits_by_producer_stage(self):
        assert classify_stalls(5.0, 1.0, 0.1, 2.0) == "io_bound"
        assert classify_stalls(1.0, 5.0, 0.1, 2.0) == "decode_bound"


# -- estimator -----------------------------------------------------------------


class TestEwmaEstimator:
    def test_first_observation_seeds_directly(self):
        est = EwmaEstimator(alpha=0.3)
        assert est.value is None
        assert est.observe(10.0) == 10.0

    def test_blend_weights_newest_by_alpha(self):
        est = EwmaEstimator(alpha=0.5)
        est.observe(10.0)
        assert est.observe(20.0) == pytest.approx(15.0)
        assert est.blend(0.0, 8.0) == pytest.approx(4.0)

    def test_alpha_bounds(self):
        with pytest.raises(ValueError, match="alpha"):
            EwmaEstimator(alpha=0.0)
        with pytest.raises(ValueError, match="alpha"):
            EwmaEstimator(alpha=1.5)
        assert EwmaEstimator(alpha=1.0).blend(3.0, 7.0) == 7.0


# -- stall rule ----------------------------------------------------------------


class TestStallRule:
    def test_starved_and_owned_pressure_grows(self):
        assert StallRule().want(0.10, True) == 1

    def test_starved_but_foreign_pressure_holds(self):
        # the consumer is starving, but the stage this knob owns did not
        # dominate: growing would tune the wrong knob
        assert StallRule().want(0.10, False) == 0

    def test_idle_shrinks_and_midband_holds(self):
        rule = StallRule(starve_ratio=0.05, idle_ratio=0.01)
        assert rule.want(0.001, True) == -1
        assert rule.want(0.03, True) == 0


# -- the controller discipline -------------------------------------------------


class TestController:
    def test_requires_a_ladder(self):
        with pytest.raises(ValueError, match="levels or lo/hi"):
            Controller()
        with pytest.raises(ValueError, match="non-empty"):
            Controller(levels=())
        with pytest.raises(ValueError, match="hi must be >= lo"):
            Controller(lo=4, hi=2)

    def test_up_is_immediate_by_default(self):
        ctl = Controller(lo=1, hi=8)
        assert ctl.step(2, +1) == 3

    def test_down_needs_patience(self):
        ctl = Controller(lo=1, hi=8, down_patience=2)
        assert ctl.step(4, -1) == 4  # first lower verdict: hold
        assert ctl.step(4, -1) == 3  # second consecutive: move

    def test_hold_clears_both_streaks(self):
        ctl = Controller(lo=1, hi=8, up_patience=2, down_patience=2)
        assert ctl.step(4, -1) == 4
        assert ctl.step(4, 0) == 4  # the streak dies here
        assert ctl.step(4, -1) == 4  # ...so this is a fresh first verdict
        assert ctl.step(4, +1) == 4  # and an up verdict also resets down
        assert ctl.step(4, -1) == 4

    def test_floor_hold_clears_streak(self):
        # pinned tuner behavior: idle intervals at the floor never
        # accumulate credit toward a move that can't happen
        ctl = Controller(lo=2, hi=8, down_patience=2)
        assert ctl.step(2, -1) == 2
        assert ctl.step(3, -1) == 3  # one verdict above the floor: patience
        assert ctl.step(3, -1) == 2

    def test_ceiling_clamps_and_levels_ladder_walks_rungs(self):
        ctl = Controller(levels=(1, 2, 4, 8))
        assert ctl.step(8, +1) == 8
        assert ctl.step(4, +1) == 8
        assert ctl.toward(2, 8) == 4  # one rung per verdict, not a jump
        assert ctl.toward(4, 4) == 4

    def test_moves_are_counted_holds_are_not(self):
        ctl = Controller(lo=1, hi=8, down_patience=2)
        before = _decisions()
        ctl.step(4, +1)  # move
        ctl.step(5, -1)  # hold (patience)
        ctl.step(5, 0)   # hold
        assert _decisions() == before + 1

    def test_reset_clears_accumulated_evidence(self):
        ctl = Controller(lo=1, hi=8, up_patience=2)
        assert ctl.step(4, +1) == 4
        ctl.reset()
        assert ctl.step(4, +1) == 4  # patience starts over after the reset
        assert ctl.step(4, +1) == 5


# -- delta ticker --------------------------------------------------------------


class TestDeltaTicker:
    def test_first_tick_seeds_and_interval_gates(self):
        clock = [100.0]
        reads = []

        def read():
            reads.append(clock[0])
            return (clock[0], clock[0] * 2)

        ticker = DeltaTicker(10.0, read, clock=lambda: clock[0])
        assert ticker.tick() is None  # baseline only
        clock[0] += 5.0
        assert ticker.tick() is None  # sub-interval: read not consulted
        assert len(reads) == 1
        clock[0] += 5.0
        deltas, elapsed = ticker.tick()
        assert deltas == (10.0, 20.0)
        assert elapsed == pytest.approx(10.0)


# -- cluster scaler ------------------------------------------------------------


class TestClusterScaler:
    def test_grow_needs_patience_across_intervals(self):
        scaler = ClusterScaler(4, min_size=1, grow_patience=2)
        assert scaler.decide(2, 4) == 2  # first healthy verdict: hold
        assert scaler.decide(2, 4) == 3  # second consecutive: one rung up

    def test_input_bound_defers_grow_and_clears_credit(self):
        scaler = ClusterScaler(4, min_size=1, grow_patience=2)
        assert scaler.decide(2, 4, "device_bound") == 2
        # an input-bound interval not only holds, it invalidates the
        # accumulated healthy verdict: the window starts over
        assert scaler.decide(2, 4, "io_bound") == 2
        assert scaler.decide(2, 4, "device_bound") == 2
        assert scaler.decide(2, 4, "device_bound") == 3

    def test_shrink_is_immediate(self):
        scaler = ClusterScaler(4, min_size=1, grow_patience=2)
        assert scaler.decide(3, 2) == 2
        # ...even when the interval was input-bound: the gate only guards
        # paying for growth
        assert scaler.decide(2, 1, "io_bound") == 1

    def test_bounds_and_gauge(self):
        scaler = ClusterScaler(3, min_size=2, grow_patience=1)
        assert scaler.decide(2, 1) == 2  # floor holds
        assert scaler.decide(3, 5) == 3  # ceiling clamps at full size
        scaler.observe(2)
        assert obs.snapshot()["gauges"]["target_world_size"]["value"] == 2

    def test_observe_resets_the_patience_window(self):
        scaler = ClusterScaler(4, min_size=1, grow_patience=2)
        assert scaler.decide(2, 4) == 2
        scaler.observe(2)  # the ladder imposed a size: regime change
        assert scaler.decide(2, 4) == 2
        assert scaler.decide(2, 4) == 3
