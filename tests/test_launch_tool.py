"""Cluster bring-up planner (the spark_ec2.py analogue, VERDICT r2 missing
item 5): the generated command plan is pinned here; execution (``apply``)
requires gcloud and runs only in the field."""

import argparse

from scripts.launch_tpu_spark import plan_commands
from tensorflowonspark_tpu import tpu_info


def _args(**kw):
    defaults = dict(
        name="tos", zone="us-central2-b", accelerator="v5e-32",
        runtime_version="tpu-ubuntu2204-base", spark_version="3.5.1",
        teardown=False,
    )
    defaults.update(kw)
    return argparse.Namespace(**defaults)


def test_plan_shape_and_order():
    cmds = plan_commands(_args())
    assert len(cmds) == 7
    assert "tpu-vm create tos --zone us-central2-b" in cmds[0]
    assert "--accelerator-type v5e-32" in cmds[0]
    assert "spark-3.5.1-bin-hadoop3" in cmds[1] and "--worker=all" in cmds[1]
    # absolute path anchored at the repo, not the operator's CWD
    assert " scp /" in cmds[2] and cmds[2].split()[5].endswith("examples/mnist/mnist_spark.py")
    assert "start-master.sh" in cmds[3] and "--worker=0" in cmds[3]
    # master IP resolved from host 0, never a hardcoded slice hostname
    assert cmds[4].startswith("MASTER_IP=$(") and "hostname -I" in cmds[4]
    # one worker per host, ONE core each: the task-per-executor invariant
    assert "SPARK_WORKER_CORES=1" in cmds[5] and "--worker=all" in cmds[5]
    assert "spark://$MASTER_IP:7077" in cmds[5]
    assert "--cluster_size 8" in cmds[6]  # v5e-32 = 8 hosts x 4 chips
    assert "mnist_spark.py" in cmds[6]


def test_teardown_plan():
    cmds = plan_commands(_args(teardown=True))
    assert len(cmds) == 1 and "delete tos" in cmds[0]


def test_unknown_accelerator_fails_loudly():
    import pytest

    with pytest.raises(SystemExit, match="unknown accelerator"):
        plan_commands(_args(accelerator="v99-1"))


def test_host_counts_from_topology_rules():
    assert tpu_info.num_hosts_for("v5e-32") == 8
    assert tpu_info.num_hosts_for("v5p-128") == 16
    cmds = plan_commands(_args(accelerator="v5p-128"))
    assert "--cluster_size 16" in cmds[6]
