"""Cluster bring-up planner (the spark_ec2.py analogue, VERDICT r2 missing
item 5): the generated command plan is pinned here; execution (``apply``)
requires gcloud and runs only in the field."""

import argparse

from scripts.launch_tpu_spark import HOSTS, plan_commands


def _args(**kw):
    defaults = dict(
        name="tos", zone="us-central2-b", accelerator="v5e-32",
        runtime_version="tpu-ubuntu2204-base", spark_version="3.5.1",
        teardown=False,
    )
    defaults.update(kw)
    return argparse.Namespace(**defaults)


def test_plan_shape_and_order():
    cmds = plan_commands(_args())
    assert len(cmds) == 5
    assert "tpu-vm create tos --zone us-central2-b" in cmds[0]
    assert "--accelerator-type v5e-32" in cmds[0]
    assert "spark-3.5.1-bin-hadoop3" in cmds[1] and "--worker=all" in cmds[1]
    assert "start-master.sh" in cmds[2] and "--worker=0" in cmds[2]
    # one worker per host, ONE core each: the task-per-executor invariant
    assert "SPARK_WORKER_CORES=1" in cmds[3] and "--worker=all" in cmds[3]
    assert "--cluster_size 4" in cmds[4]  # v5e-32 = 4 TPU hosts


def test_teardown_plan():
    cmds = plan_commands(_args(teardown=True))
    assert len(cmds) == 1 and "delete tos" in cmds[0]


def test_unknown_accelerator_fails_loudly():
    import pytest

    with pytest.raises(SystemExit, match="unknown accelerator"):
        plan_commands(_args(accelerator="v99-1"))


def test_host_table_consistency():
    assert HOSTS["v5e-32"] == 4
    assert all(isinstance(v, int) and v >= 1 for v in HOSTS.values())
