"""Unit tests for the survivable control plane (ISSUE 11 tentpole): lease
lifecycle and expiry, blacklist/role truth, CRC-framed journal + manifest
commits, driver-restart recovery with live-lease re-adoption, epoch fencing
of stale writers, torn-manifest/torn-journal fallback, and the deterministic
heartbeat aggregation tree election."""

import json
import os
import zlib

import pytest

from tensorflowonspark_tpu import chaos, registry


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


class TestLeaseLifecycle:
    def test_join_renew_leave(self):
        clk = FakeClock()
        reg = registry.MembershipRegistry(ttl=10, clock=clk)
        reg.begin_generation({0: ("worker", 0), 1: ("worker", 1)})
        assert reg.epoch == 1
        reg.join(0, "worker", 0)
        reg.join(1, "worker", 1)
        assert reg.live_members() == [0, 1]
        assert reg.leases_active() == 2
        assert reg.role_map() == {"worker:0": 0, "worker:1": 1}
        reg.leave(1, reason="done")
        assert reg.live_members() == [0]

    def test_renew_requires_beat_progress(self):
        """Re-reading a dead child's frozen counter must not renew."""
        reg = registry.MembershipRegistry(ttl=10)
        reg.begin_generation()
        reg.join(0)
        assert reg.renew(0, beat=3) is True
        assert reg.renew(0, beat=3) is False  # same value: no progress
        assert reg.renew(0, beat=4) is True

    def test_expiry_after_ttl_without_renewal(self):
        clk = FakeClock()
        reg = registry.MembershipRegistry(ttl=10, clock=clk)
        reg.begin_generation()
        reg.join(0)
        reg.join(1)
        reg.renew(0, beat=1)
        reg.renew(1, beat=1)
        clk.advance(11)
        reg.renew(0, beat=2)  # only node 0 keeps beating
        expired = reg.expire_stale()
        assert [eid for eid, _ in expired] == [1]
        age = expired[0][1]
        assert age > 10
        assert reg.live_members() == [0]

    def test_expiry_survives_journal_io_failure(self, tmp_path, monkeypatch):
        """Failure detection must not depend on the disk: a journal append
        that raises (disk full, unwritable dir) still returns the in-memory
        expiries — otherwise the members flip to ``expired`` state but are
        never reported, and the loss goes permanently unnoticed."""
        clk = FakeClock()
        reg = registry.MembershipRegistry(ttl=10, journal_dir=str(tmp_path), clock=clk)
        reg.begin_generation()
        reg.join(0)
        reg.renew(0, beat=1)
        clk.advance(11)

        def boom(record):
            raise OSError("disk full")

        monkeypatch.setattr(reg, "_journal_locked", boom)
        expired = reg.expire_stale()
        assert [eid for eid, _ in expired] == [0]
        assert reg.live_members() == []

    def test_member_that_never_beat_is_exempt(self):
        """Slow child startup is the launch timeout's concern, not a lease
        violation (historical watchdog parity)."""
        clk = FakeClock()
        reg = registry.MembershipRegistry(ttl=5, clock=clk)
        reg.begin_generation()
        reg.join(0)
        clk.advance(1000)
        assert reg.expire_stale() == []
        assert reg.live_members() == [0]

    def test_expired_member_readopted_on_new_beat(self):
        clk = FakeClock()
        reg = registry.MembershipRegistry(ttl=5, clock=clk)
        reg.begin_generation()
        reg.join(0)
        reg.renew(0, beat=1)
        clk.advance(6)
        assert [e for e, _ in reg.expire_stale()] == [0]
        assert reg.renew(0, beat=2) is True  # long flap: the node came back
        assert reg.live_members() == [0]

    def test_left_member_does_not_renew(self):
        reg = registry.MembershipRegistry(ttl=5)
        reg.begin_generation()
        reg.join(0)
        reg.leave(0)
        assert reg.renew(0, beat=1) is False

    def test_blacklist_and_forgive(self):
        reg = registry.MembershipRegistry()
        reg.blacklist(3, reason="repeated loss")
        assert reg.is_blacklisted(3)
        assert reg.blacklisted() == [3]
        reg.forgive(3)
        assert not reg.is_blacklisted(3)

    def test_generation_bumps_epoch_and_clears_members(self):
        reg = registry.MembershipRegistry()
        reg.begin_generation({0: ("chief", 0)})
        reg.join(0, "chief", 0)
        reg.begin_generation({0: ("chief", 0), 1: ("worker", 0)})
        assert reg.epoch == 2
        assert reg.live_members() == []  # relaunch: fresh roster
        assert reg.roles() == {0: ("chief", 0), 1: ("worker", 0)}


class TestJournalRecovery:
    def test_recover_readopts_live_leases(self, tmp_path):
        clk = FakeClock()
        d = str(tmp_path)
        reg = registry.MembershipRegistry(ttl=30, journal_dir=d, clock=clk)
        reg.begin_generation({0: ("worker", 0), 1: ("worker", 1)})
        reg.join(0, "worker", 0)
        reg.join(1, "worker", 1)
        reg.renew(0, beat=5)
        reg.renew(1, beat=7)
        reg.blacklist(9, reason="condemned")
        clk.advance(3)  # well inside the TTL
        reg2 = registry.MembershipRegistry.recover(d, ttl=30, clock=clk)
        assert reg2.epoch == reg.epoch + 1
        assert reg2.live_members() == [0, 1]
        assert reg2.blacklisted() == [9]
        assert reg2.roles() == {0: ("worker", 0), 1: ("worker", 1)}

    def test_recover_restores_target_size_through_compaction(self, tmp_path):
        # every epoch record triggers a manifest compaction that truncates
        # the journal, so the target must survive in the manifest snapshot,
        # not just the journaled epoch record
        clk = FakeClock()
        d = str(tmp_path)
        reg = registry.MembershipRegistry(ttl=30, journal_dir=d, clock=clk)
        reg.begin_generation({0: ("worker", 0)}, target_size=4)
        reg.join(0, "worker", 0)
        reg2 = registry.MembershipRegistry.recover(d, ttl=30, clock=clk)
        assert reg2.target_size == 4
        # ...and a second recovery (reading the fencing manifest the first
        # one committed) still carries it
        reg3 = registry.MembershipRegistry.recover(d, ttl=30, clock=clk)
        assert reg3.target_size == 4

    def test_recover_expires_leases_past_ttl(self, tmp_path):
        clk = FakeClock()
        d = str(tmp_path)
        reg = registry.MembershipRegistry(ttl=10, journal_dir=d, clock=clk)
        reg.begin_generation()
        reg.join(0, "worker", 0)
        reg.renew(0, beat=1)
        clk.advance(60)  # the driver outage outlived the lease
        reg2 = registry.MembershipRegistry.recover(d, ttl=10, clock=clk)
        assert reg2.live_members() == []
        assert reg2.members()[0]["state"] == "expired"

    def test_recovery_fences_stale_writer(self, tmp_path):
        d = str(tmp_path)
        reg = registry.MembershipRegistry(ttl=30, journal_dir=d)
        reg.begin_generation()
        reg.join(0)
        reg2 = registry.MembershipRegistry.recover(d, ttl=30)
        assert reg2.epoch > reg.epoch
        with pytest.raises(registry.StaleEpochError):
            reg.join(1)  # the pre-crash writer must not clobber the journal

    def test_torn_manifest_falls_back_to_previous(self, tmp_path):
        d = str(tmp_path)
        reg = registry.MembershipRegistry(ttl=30, journal_dir=d)
        reg.begin_generation({0: ("worker", 0)})
        reg.join(0, "worker", 0)
        reg2 = registry.MembershipRegistry.recover(d, ttl=30)  # commits a manifest
        mpath = os.path.join(d, registry.MANIFEST_NAME)
        text = open(mpath).read()
        with open(mpath, "w") as f:
            f.write(text[: len(text) // 2])  # tear the newest manifest
        reg3 = registry.MembershipRegistry.recover(d, ttl=30)
        assert reg3.epoch > reg2.epoch
        assert 0 in reg3.members()

    def test_crc_mismatch_detected(self, tmp_path):
        d = str(tmp_path)
        reg = registry.MembershipRegistry(ttl=30, journal_dir=d)
        reg.begin_generation()
        mpath = os.path.join(d, registry.MANIFEST_NAME)
        payload = json.load(open(mpath))
        payload["state"]["epoch"] = 99  # bitrot: valid JSON, wrong content
        with open(mpath, "w") as f:
            json.dump(payload, f)
        loaded, reason = registry._read_manifest_file(mpath)
        assert loaded is None and reason == "checksum mismatch"

    def test_torn_journal_line_stops_replay(self, tmp_path):
        d = str(tmp_path)
        reg = registry.MembershipRegistry(ttl=30, journal_dir=d, manifest_every=1000)
        reg.begin_generation()
        reg.join(0, "worker", 0)
        reg.join(1, "worker", 1)
        jpath = os.path.join(d, registry.JOURNAL_NAME)
        with open(jpath, "a") as f:
            f.write("deadbeef {\"op\": \"join\", \"eid\"")  # crash mid-append
        state = registry._load_state(d)
        # the two whole records replayed; the torn tail was dropped
        assert set(state["members"]) == {"0", "1"}

    def test_journal_lines_are_crc_framed(self, tmp_path):
        d = str(tmp_path)
        reg = registry.MembershipRegistry(ttl=30, journal_dir=d, manifest_every=1000)
        reg.begin_generation()
        reg.join(0)
        for line in open(os.path.join(d, registry.JOURNAL_NAME)):
            crc_hex, _, payload = line.rstrip("\n").partition(" ")
            assert int(crc_hex, 16) == zlib.crc32(payload.encode()) & 0xFFFFFFFF

    def test_renew_journaling_is_coalesced(self, tmp_path):
        """Per-beat renew records would grow the journal without bound; only
        ~one per ttl/4 per member goes to disk."""
        clk = FakeClock()
        d = str(tmp_path)
        reg = registry.MembershipRegistry(
            ttl=40, journal_dir=d, clock=clk, manifest_every=100000
        )
        reg.begin_generation()
        reg.join(0)
        for beat in range(50):
            clk.advance(1)
            reg.renew(0, beat=beat)
        renews = [
            line for line in open(os.path.join(d, registry.JOURNAL_NAME))
            if '"op": "renew"' in line
        ]
        # 50s of beats at ttl/4 = 10s coalescing -> ~5 records, never 50
        assert 1 <= len(renews) <= 10

    def test_manifest_compaction_truncates_journal(self, tmp_path):
        d = str(tmp_path)
        reg = registry.MembershipRegistry(ttl=30, journal_dir=d, manifest_every=3)
        reg.begin_generation()
        for eid in range(6):
            reg.join(eid)
        # compaction ran: journal holds at most manifest_every records
        lines = open(os.path.join(d, registry.JOURNAL_NAME)).read().splitlines()
        assert len(lines) < 6
        reg2 = registry.MembershipRegistry.recover(d, ttl=30)
        assert reg2.live_members() == [0, 1, 2, 3, 4, 5]

    def test_recover_from_empty_dir(self, tmp_path):
        reg = registry.MembershipRegistry.recover(str(tmp_path), ttl=30, fallback_epoch=4)
        assert reg.epoch == 5
        assert reg.live_members() == []

    def test_recover_without_journal_dir(self):
        reg = registry.MembershipRegistry.recover(None, ttl=30, fallback_epoch=2)
        assert reg.epoch == 3


class TestChaosSites:
    def test_journal_tear_leaves_recoverable_state(self, tmp_path):
        """control.journal_tear tears the manifest publish; the journal is
        NOT truncated, so prev-manifest + journal reconstruct everything."""
        d = str(tmp_path)
        reg = registry.MembershipRegistry(ttl=30, journal_dir=d, manifest_every=1000)
        reg.begin_generation({0: ("worker", 0), 1: ("worker", 1)})
        reg.join(0, "worker", 0)
        chaos.install(chaos.ChaosPlan(seed=7).site("control.journal_tear", probability=1.0, max_count=1))
        try:
            reg.join(1, "worker", 1)  # this durable append hits the tear
        finally:
            chaos.uninstall()
        payload, reason = registry._read_manifest_file(
            os.path.join(d, registry.MANIFEST_NAME)
        )
        assert payload is None  # the newest manifest really is torn
        reg2 = registry.MembershipRegistry.recover(d, ttl=30)
        # member 0 survived via prev manifest/journal; member 1's join died
        # with the torn write (crash semantics)
        assert 0 in reg2.members()

    def test_lease_delay_site_is_benign(self):
        chaos.install(
            chaos.ChaosPlan(seed=1).site(
                "control.lease_delay", probability=1.0, max_count=2, delay_s=0.001
            )
        )
        try:
            reg = registry.MembershipRegistry(ttl=30)
            reg.begin_generation()
            reg.join(0)
            assert reg.renew(0, beat=1) is True
            assert reg.live_members() == [0]
        finally:
            chaos.uninstall()


class TestAggregationTree:
    def test_tree_is_sqrt_sized_and_deterministic(self):
        rows = [{"executor_id": i, "manager_addr": ("h", i)} for i in range(9)]
        tree = registry.plan_aggregation_tree(rows)
        assert tree == registry.plan_aggregation_tree(list(reversed(rows)))
        assert len(tree) == 3  # isqrt(9) groups
        covered = sorted(eid for members in tree.values() for eid in members)
        assert covered == list(range(9))
        for agg, members in tree.items():
            assert agg == members[0]  # lowest id of the group aggregates it

    def test_tree_skips_unreachable_rows(self):
        rows = [
            {"executor_id": 0, "manager_addr": ("h", 0)},
            {"executor_id": 1, "manager_addr": None},
        ]
        tree = registry.plan_aggregation_tree(rows)
        assert tree == {0: [0]}

    def test_empty_tree(self):
        assert registry.plan_aggregation_tree([]) == {}

    def test_window_coverage_splits_members(self):
        summary = {
            "window": 7,
            "beats": {"0": 5, "2": 9},
            "status": {"1": "done"},
            "errors": [2],
        }
        statuses, beats, flagged = registry.window_coverage(summary, [0, 1, 2])
        assert statuses == {1: "done"}
        assert beats == {0: 5, 2: 9}
        assert flagged == {2}

    def test_window_coverage_excludes_members_absent_from_summary(self):
        """An executor that died entirely (process/machine gone) appears in
        neither beats, status, nor errors — the aggregator could not reach
        its channel. It must NOT count as covered: if the driver renewed its
        lease anyway (a beat-less renew is unconditional), the dead
        executor's lease would never expire and the failure would never
        surface. Uncovered members fall back to direct polls, where the
        unreachable channel stops renewals."""
        summary = {"window": 3, "beats": {"0": 4}, "status": {}, "errors": []}
        statuses, beats, flagged = registry.window_coverage(summary, [0, 1])
        assert beats == {0: 4}
        assert statuses == {}
        assert flagged == set()
        assert 1 not in statuses and 1 not in beats  # → direct-poll path

    def test_window_coverage_ignores_non_members(self):
        # a summary may carry rows for executors no longer in the tree
        # (stale window from a previous generation): only tree members count
        summary = {"window": 1, "beats": {"0": 1, "9": 8}, "errors": [9]}
        statuses, beats, flagged = registry.window_coverage(summary, [0, 1])
        assert beats == {0: 1}
        assert flagged == set()

    def test_enablement_knob(self, monkeypatch):
        monkeypatch.delenv("TOS_HEARTBEAT_AGG", raising=False)
        assert not registry.aggregation_enabled(1)  # auto: too small
        assert registry.aggregation_enabled(2)
        monkeypatch.setenv("TOS_HEARTBEAT_AGG", "0")
        assert not registry.aggregation_enabled(100)
        monkeypatch.setenv("TOS_HEARTBEAT_AGG", "1")
        assert registry.aggregation_enabled(1)
