"""Fixture tests for the project-wide (phase-2) tosa rules.

Each rule family gets bad-fixture-fires / good-fixture-stays-clean pairs,
plus the cross-rule interaction coverage ISSUE 9 asks for: block-scoped
suppressions and baseline fingerprints for project-level findings.
"""

import os
import textwrap
import unittest

from tosa_testutil import LIB_PATH, REPO_ROOT, core, run_project_rule


def _src(body):
    return textwrap.dedent(body).strip() + "\n"


# ---------------------------------------------------------------------------
# donation-safety
# ---------------------------------------------------------------------------

#: the PR 7 ckpt/snapshot.py bug, reduced: jax's cached sharded-array
#: assembly (read-only host memory) pooled as a reusable writable buffer
SNAPSHOT_POOL_BUG = _src(
    """
    import jax
    import numpy as np

    class SnapshotBuffers:
        def __init__(self):
            self._free = []

        def take(self, leaf):
            host = jax.device_get(leaf)
            arr = np.asarray(host)
            self._free.append(arr)
            return arr
    """
)


class TestDonationSafety(unittest.TestCase):
    def test_pr7_snapshot_pool_bug_fires(self):
        findings = run_project_rule("donation-safety", {LIB_PATH: SNAPSHOT_POOL_BUG})
        self.assertEqual(len(findings), 1)
        self.assertEqual(findings[0].rule, "donation-safety")
        self.assertIn("jax.device_get", findings[0].message)
        self.assertIn("_free", findings[0].message)

    def test_owned_copy_stays_clean(self):
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax
            import numpy as np

            class SnapshotBuffers:
                def __init__(self):
                    self._free = []

                def take(self, leaf):
                    host = jax.device_get(leaf)
                    arr = np.array(host, copy=True)
                    self._free.append(arr)
                    return arr
            """
        )})
        self.assertEqual(findings, [])

    def test_flags_check_sanitizes(self):
        # the shape of the in-tree fix: checking .flags before pooling
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax
            import numpy as np

            class SnapshotBuffers:
                def __init__(self):
                    self._free = []

                def take(self, leaf):
                    arr = np.asarray(jax.device_get(leaf))
                    if not arr.flags.owndata or not arr.flags.writeable:
                        arr = np.array(arr, copy=True)
                    self._free.append(arr)
                    return arr
            """
        )})
        self.assertEqual(findings, [])

    def test_owndata_only_guard_does_not_sanitize(self):
        # the exact shape of the PRE-fix PR 7 guard: an early return copies
        # when owndata is false, but jax's cached sharded assembly OWNS its
        # data and is still frozen — the fallthrough returns the raw view,
        # and only a .flags.writeable check counts as handling that case
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax
            import numpy as np

            def _leaf_to_host(leaf):
                arr = np.asarray(jax.device_get(leaf))
                if not arr.flags.owndata:
                    return np.array(arr, copy=True)
                return arr

            class SnapshotBuffers:
                def __init__(self):
                    self._free = []

                def take(self, leaf):
                    arr = _leaf_to_host(leaf)
                    self._free.append(arr)
                    return arr
            """
        )})
        self.assertEqual(len(findings), 1)
        self.assertIn("_free", findings[0].message)

    def test_inplace_write_of_device_view_fires(self):
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax
            import numpy as np

            def refresh(out, leaf):
                view = jax.device_get(leaf)
                view[0] = 0.0
                return view
            """
        )})
        self.assertEqual(len(findings), 1)
        self.assertIn("in place", findings[0].message)

    def test_copyto_into_tainted_destination_fires(self):
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax
            import numpy as np

            def refresh(leaf, fresh):
                dst = np.asarray(jax.device_get(leaf))
                np.copyto(dst, fresh)
                return dst
            """
        )})
        self.assertEqual(len(findings), 1)
        self.assertIn("copyto", findings[0].message)

    def test_taint_flows_through_helper_return(self):
        # cross-function propagation: the helper's return is device-derived
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax
            import numpy as np

            def _to_host(leaf):
                return np.asarray(jax.device_get(leaf))

            class Pool:
                def __init__(self):
                    self._slots = []

                def keep(self, leaf):
                    arr = _to_host(leaf)
                    self._slots.append(arr)
            """
        )})
        self.assertEqual(len(findings), 1)
        self.assertIn("_to_host", findings[0].message)

    def test_read_after_donation_fires(self):
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax

            step = jax.jit(lambda s, b: s, donate_argnums=(0,))

            def run(state, batch):
                out = step(state, batch)
                return state
            """
        )})
        self.assertEqual(len(findings), 1)
        self.assertIn("donated", findings[0].message)
        self.assertIn("step", findings[0].message)

    def test_rebind_idiom_stays_clean(self):
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax

            step = jax.jit(lambda s, b: s, donate_argnums=(0,))

            def run(state, batches):
                for batch in batches:
                    state = step(state, batch)
                return state
            """
        )})
        self.assertEqual(findings, [])

    def test_non_donated_args_stay_readable(self):
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax

            step = jax.jit(lambda s, b: s, donate_argnums=(0,))

            def run(state, batch):
                state = step(state, batch)
                return state, batch
            """
        )})
        self.assertEqual(findings, [])


class TestBucketedOverlapDonation(unittest.TestCase):
    """Pins the BucketedOverlap donation contract: a grad program that
    donated its params would invalidate the buffers every later microbatch
    (and the comm thread's in-flight bucket fetches) still reference."""

    def test_donating_grad_fn_fires(self):
        # the shape BucketedOverlap must never take: donate params to the
        # grad program, then keep handing them out for the next microbatch
        # while the first's grads sit on the comm queue
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax

            def dispatch(loss_fn, params, b1, jobs):
                gfn = jax.jit(jax.value_and_grad(loss_fn), donate_argnums=(0,))
                loss1, g1 = gfn(params, b1)
                jobs.put(g1)
                return params
            """
        )})
        self.assertEqual(len(findings), 1)
        self.assertIn("read after being donated", findings[0].message)

    def test_overlap_shape_stays_clean(self):
        # the in-tree shape: grad program donates nothing; only the apply
        # program donates, after the comm drain, and its result is rebound
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax

            def dispatch(loss_fn, apply, params, opt_state, b1, b2, jobs):
                gfn = jax.jit(jax.value_and_grad(loss_fn), donate_argnums=())
                loss1, g1 = gfn(params, b1)
                jobs.put(g1)
                loss2, g2 = gfn(params, b2)
                jobs.put(g2)
                apply_fn = jax.jit(apply, donate_argnums=(0, 1))
                params, opt_state = apply_fn(params, opt_state, g1)
                return params, opt_state, loss2
            """
        )})
        self.assertEqual(findings, [])

    def test_in_tree_scheduler_stays_clean(self):
        # the rule over the real module: the shipped scheduler never reads
        # a donated buffer (grad fns donate nothing, apply rebinds)
        path = os.path.join(
            REPO_ROOT, "tensorflowonspark_tpu", "train", "strategy.py"
        )
        with open(path) as f:
            src = f.read()
        findings = run_project_rule(
            "donation-safety", {"tensorflowonspark_tpu/train/strategy.py": src}
        )
        self.assertEqual(findings, [])


# ---------------------------------------------------------------------------
# metrics-contract
# ---------------------------------------------------------------------------

GOOD_DOCS = {
    "docs/architecture.md": _src(
        """
        ### Metrics inventory

        | name | kind | meaning |
        | --- | --- | --- |
        | `good_things_total` | counter | things that went well |
        """
    )
}


class TestMetricsContract(unittest.TestCase):
    def test_documented_conforming_counter_is_clean(self):
        findings = run_project_rule("metrics-contract", {LIB_PATH: _src(
            """
            from tensorflowonspark_tpu import obs

            def work():
                obs.counter("good_things_total", help="x").inc()
            """
        )}, docs=GOOD_DOCS)
        self.assertEqual(findings, [])

    def test_counter_without_total_suffix_fires(self):
        findings = run_project_rule("metrics-contract", {LIB_PATH: _src(
            """
            from tensorflowonspark_tpu import obs

            def work():
                obs.counter("good_things", help="x").inc()
            """
        )})
        self.assertEqual(len(findings), 1)
        self.assertIn("_total", findings[0].message)

    def test_gauge_with_total_suffix_fires(self):
        findings = run_project_rule("metrics-contract", {LIB_PATH: _src(
            """
            from tensorflowonspark_tpu import obs

            def work():
                obs.gauge("queue_depth_total", help="x").set(1)
            """
        )})
        self.assertEqual(len(findings), 1)
        self.assertIn("reserved for counters", findings[0].message)

    def test_dynamic_name_outside_obs_fires(self):
        findings = run_project_rule("metrics-contract", {LIB_PATH: _src(
            """
            from tensorflowonspark_tpu import obs

            def work(kind):
                obs.counter("x_{}_total".format(kind), help="x").inc()
            """
        )})
        self.assertEqual(len(findings), 1)
        self.assertIn("non-literal", findings[0].message)

    def test_desynced_docs_fire_both_directions(self):
        # registered-but-undocumented AND documented-but-unregistered
        findings = run_project_rule("metrics-contract", {LIB_PATH: _src(
            """
            from tensorflowonspark_tpu import obs

            def work():
                obs.counter("undocumented_total", help="x").inc()
            """
        )}, docs=GOOD_DOCS)
        messages = sorted(f.message for f in findings)
        self.assertEqual(len(findings), 2)
        self.assertIn("undocumented_total", messages[1])
        self.assertIn("missing from the Metrics inventory", messages[1])
        self.assertIn("good_things_total", messages[0])
        self.assertIn("never registered", messages[0])
        # the stale-row finding anchors at the docs file
        stale = [f for f in findings if "never registered" in f.message][0]
        self.assertEqual(stale.path, "docs/architecture.md")

    def test_kind_mismatch_fires(self):
        findings = run_project_rule("metrics-contract", {LIB_PATH: _src(
            """
            from tensorflowonspark_tpu import obs

            def work():
                obs.gauge("good_things_total").set(1)
            """
        )}, docs=GOOD_DOCS)
        # the gauge-named-_total conformance finding plus the kind mismatch
        mismatch = [f for f in findings if "documented as a" in f.message]
        self.assertEqual(len(mismatch), 1)
        self.assertEqual(mismatch[0].path, "docs/architecture.md")

    def test_unmerged_private_registry_fires(self):
        findings = run_project_rule("metrics-contract", {LIB_PATH: _src(
            """
            from tensorflowonspark_tpu.obs import registry as obs_registry

            def task():
                reg = obs_registry.Registry(enabled=True)
                reg.counter("feed_rows_total", help="x").inc()
            """
        )}, docs={"docs/architecture.md": "| `feed_rows_total` | counter | x |"})
        self.assertEqual(len(findings), 1)
        self.assertIn("never merged", findings[0].message)

    def test_merged_private_registry_is_clean(self):
        findings = run_project_rule("metrics-contract", {LIB_PATH: _src(
            """
            from tensorflowonspark_tpu.obs import aggregate as obs_aggregate
            from tensorflowonspark_tpu.obs import registry as obs_registry

            def task(mgr):
                reg = obs_registry.Registry(enabled=True)
                reg.counter("feed_rows_total", help="x").inc()
                obs_aggregate.accumulate_to_channel(mgr, reg)
            """
        )}, docs={"docs/architecture.md": "| `feed_rows_total` | counter | x |"})
        self.assertEqual(findings, [])

    def test_dynamic_family_row_matches_minted_names(self):
        findings = run_project_rule("metrics-contract", {LIB_PATH: _src(
            """
            from tensorflowonspark_tpu import obs

            def work():
                obs.counter("chaos_fault_feed_stall_total", help="x").inc()
            """
        )}, docs={"docs/architecture.md": "| `chaos_fault_{site}_total` | counter | x |"})
        self.assertEqual(findings, [])


# ---------------------------------------------------------------------------
# lock-order
# ---------------------------------------------------------------------------

TWO_LOCK_CYCLE = _src(
    """
    import threading

    _lock_a = threading.Lock()
    _lock_b = threading.Lock()

    def forward():
        with _lock_a:
            with _lock_b:
                pass

    def backward():
        with _lock_b:
            with _lock_a:
                pass
    """
)


class TestLockOrder(unittest.TestCase):
    def test_two_lock_cycle_fires(self):
        findings = run_project_rule("lock-order", {LIB_PATH: TWO_LOCK_CYCLE})
        self.assertEqual(len(findings), 1)
        self.assertIn("cycle", findings[0].message)
        self.assertIn("_lock_a", findings[0].message)
        self.assertIn("_lock_b", findings[0].message)

    def test_consistent_order_is_clean(self):
        findings = run_project_rule("lock-order", {LIB_PATH: _src(
            """
            import threading

            _lock_a = threading.Lock()
            _lock_b = threading.Lock()

            def forward():
                with _lock_a:
                    with _lock_b:
                        pass

            def also_forward():
                with _lock_a:
                    with _lock_b:
                        pass
            """
        )})
        self.assertEqual(findings, [])

    def test_cross_module_cycle_through_calls_fires(self):
        findings = run_project_rule("lock-order", {
            "tensorflowonspark_tpu/mod_a.py": _src(
                """
                import threading

                from tensorflowonspark_tpu import mod_b

                _lock = threading.Lock()

                def locked_work():
                    with _lock:
                        mod_b.helper()

                def helper():
                    with _lock:
                        pass
                """
            ),
            "tensorflowonspark_tpu/mod_b.py": _src(
                """
                import threading

                from tensorflowonspark_tpu import mod_a

                _lock = threading.Lock()

                def helper():
                    with _lock:
                        pass

                def locked_work():
                    with _lock:
                        mod_a.helper()
                """
            ),
        })
        self.assertEqual(len(findings), 1)
        self.assertIn("cycle", findings[0].message)

    def test_blocking_put_on_bounded_queue_under_consumer_lock_fires(self):
        findings = run_project_rule("lock-order", {LIB_PATH: _src(
            """
            import queue
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue(2)
                    self._thread = threading.Thread(target=self._drain)
                    self._thread.start()

                def _drain(self):
                    while True:
                        item = self._q.get()
                        with self._lock:
                            del item

                def submit(self, item):
                    with self._lock:
                        self._q.put(item)
            """
        )})
        self.assertEqual(len(findings), 1)
        self.assertIn("bounded queue", findings[0].message)

    def test_put_with_timeout_or_unbounded_queue_is_clean(self):
        for variant in ("queue.Queue()", "queue.Queue(2)"):
            put = "self._q.put(item)" if variant == "queue.Queue()" else "self._q.put(item, timeout=1.0)"
            findings = run_project_rule("lock-order", {LIB_PATH: _src(
                """
                import queue
                import threading

                class Worker:
                    def __init__(self):
                        self._lock = threading.Lock()
                        self._q = {}
                        self._thread = threading.Thread(target=self._drain)
                        self._thread.start()

                    def _drain(self):
                        while True:
                            item = self._q.get()
                            with self._lock:
                                del item

                    def submit(self, item):
                        with self._lock:
                            {}
                """.format(variant, put)
            )})
            self.assertEqual(findings, [], variant)

    def test_join_under_consumer_lock_fires_and_timeout_is_clean(self):
        template = _src(
            """
            import threading

            class Worker:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._thread = threading.Thread(target=self._run)
                    self._thread.start()

                def _run(self):
                    with self._lock:
                        pass

                def close(self):
                    with self._lock:
                        self._thread.join({})
            """
        )
        findings = run_project_rule("lock-order", {LIB_PATH: template.format("")})
        self.assertEqual(len(findings), 1)
        self.assertIn("join()", findings[0].message)
        findings = run_project_rule(
            "lock-order", {LIB_PATH: template.format("timeout=5.0")}
        )
        self.assertEqual(findings, [])


# ---------------------------------------------------------------------------
# cross-rule interaction: suppressions + baselines for project findings
# ---------------------------------------------------------------------------


class TestProjectFindingFilters(unittest.TestCase):
    def test_block_scoped_suppression_on_for_header(self):
        # suppression on the for header covers the pooling line inside it
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax
            import numpy as np

            class Pool:
                def __init__(self):
                    self._slots = []

                def keep(self, leaves):
                    for leaf in leaves:  # tosa: disable=donation-safety -- zero-copy pool is intentional here
                        arr = np.asarray(jax.device_get(leaf))
                        self._slots.append(arr)
            """
        )}, keep_suppressed=True)
        self.assertEqual(len(findings), 1)
        self.assertIsNotNone(findings[0].suppressed)
        self.assertIn("zero-copy pool", findings[0].suppressed)

    def test_line_exact_suppression_still_works(self):
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax
            import numpy as np

            class Pool:
                def __init__(self):
                    self._slots = []

                def keep(self, leaf):
                    arr = np.asarray(jax.device_get(leaf))
                    self._slots.append(arr)  # tosa: disable=donation-safety -- fixture
            """
        )})
        self.assertEqual(findings, [])

    def test_suppression_of_other_rule_does_not_silence(self):
        findings = run_project_rule("donation-safety", {LIB_PATH: _src(
            """
            import jax
            import numpy as np

            class Pool:
                def __init__(self):
                    self._slots = []

                def keep(self, leaf):
                    arr = np.asarray(jax.device_get(leaf))
                    self._slots.append(arr)  # tosa: disable=lock-order -- wrong rule
            """
        )})
        self.assertEqual(len(findings), 1)

    def test_baseline_fingerprint_grandfathers_project_finding(self):
        findings = run_project_rule("lock-order", {LIB_PATH: TWO_LOCK_CYCLE})
        self.assertEqual(len(findings), 1)
        baseline = {findings[0].fingerprint: 1}
        # a fresh run of the same fixture produces the same fingerprint:
        # line-free, so unrelated edits elsewhere don't churn it
        again = run_project_rule("lock-order", {LIB_PATH: TWO_LOCK_CYCLE})
        core.apply_baseline(again, baseline)
        self.assertTrue(again[0].baselined)
        self.assertEqual(core.gating(again), [])

    def test_docs_anchored_finding_is_baselinable(self):
        files = {LIB_PATH: "def work():\n    pass\n"}
        docs = GOOD_DOCS  # documents good_things_total, never registered
        findings = run_project_rule("metrics-contract", files, docs=docs)
        self.assertEqual(len(findings), 1)
        self.assertEqual(findings[0].path, "docs/architecture.md")
        baseline = {findings[0].fingerprint: 1}
        again = run_project_rule("metrics-contract", files, docs=docs)
        core.apply_baseline(again, baseline)
        self.assertEqual(core.gating(again), [])


if __name__ == "__main__":
    unittest.main()
