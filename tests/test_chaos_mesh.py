"""Serving-mesh chaos (ISSUE 13): router partitions, torn swap publishes,
and the ``run_tests.sh --chaos`` replica-kill leg — SIGKILL one of three
replicas under sustained load, assert via the merged ``cluster.metrics()``
that failover absorbed it (``serving_failovers_total > 0``) with zero
client-visible request failures, the active-replica gauge dipped and
recovered, and the dead replica's lease expired in the registry."""

import threading
import time

import numpy as np
import pytest

from tensorflowonspark_tpu import TFCluster, chaos, obs, resilience
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext
from tensorflowonspark_tpu.serving import InferenceServer
from tensorflowonspark_tpu.serving_mesh import ModelPointer, ReplicaServer, ServingMesh
from tensorflowonspark_tpu.train import export

pytestmark = pytest.mark.chaos

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _builder():
    def predict(params, model_state, arrays):
        return {"y_": arrays["x"] @ params["w"]}

    return predict


def _params(scale):
    return {"w": np.full((1, 1), float(scale), np.float32)}


def _bundle(path, scale):
    export.export_model(str(path), _builder, _params(scale))
    return str(path)


def _counter(name):
    return obs.snapshot()["counters"].get(name, {}).get("value", 0)


def fn_sleep_forever(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        feed.next_batch(16)


class TestRouterPartition:
    def test_partition_drives_failover_not_an_error(self, tmp_path):
        """``serving.router_partition`` drops the chosen replica's pooled
        connection mid-route; the request must fail over and succeed."""
        a = InferenceServer(_bundle(tmp_path / "a", 3))
        b = InferenceServer(_bundle(tmp_path / "b", 3))
        a.start()
        b.start()
        plan = chaos.ChaosPlan(seed=4).site(
            "serving.router_partition", probability=1.0, max_count=1
        )
        chaos.install(plan, propagate=False)
        failovers = _counter("serving_failovers_total")
        from tensorflowonspark_tpu.serving_mesh import ReplicaRouter

        router = ReplicaRouter(
            {0: a.address, 1: b.address}, deadline=10.0, breaker_threshold=5,
            backoff=resilience.Backoff(base=0.02, factor=2.0, max_delay=0.1,
                                       jitter=0.5, seed=0),
        )
        try:
            out = router.predict_binary(x=np.ones((1, 1), np.float32))
            assert float(np.asarray(out["y_"]).ravel()[0]) == 3.0
            assert plan.fired("serving.router_partition") == 1
            assert _counter("serving_failovers_total") - failovers >= 1
        finally:
            router.close()
            a.stop()
            b.stop()


class TestSwapTorn:
    def test_torn_publish_rejected_mesh_keeps_serving(self, tmp_path):
        """``serving.swap_torn`` tears the manifest of a fresh generation;
        the replica rejects it via cheap-verify and the old model serves."""
        pointer = ModelPointer(str(tmp_path / "ptr"))
        pointer.publish(_builder, _params(2))
        rep = ReplicaServer(pointer.root, poll_interval=999)
        rep.start()
        rejects = _counter("serving_swap_rejects_total")
        plan = chaos.ChaosPlan(seed=6).site(
            "serving.swap_torn", probability=1.0, max_count=1
        )
        chaos.install(plan, propagate=False)
        try:
            pointer.publish(_builder, _params(8))
            assert plan.fired("serving.swap_torn") == 1
            assert rep.check_swap() is False
            assert _counter("serving_swap_rejects_total") - rejects == 1
            assert rep.generation() == "gen-000000"
        finally:
            rep.stop()


@pytest.mark.slow
def test_replica_kill_under_load_no_client_visible_failure(tmp_path, monkeypatch):
    """The ``run_tests.sh --chaos`` mesh leg (ISSUE 13 acceptance): SIGKILL
    one of three process replicas under sustained load. Every request
    completes via failover (zero client-visible errors), the merged
    ``cluster.metrics()`` shows ``serving_failovers_total > 0``,
    ``serving_replicas_active`` dips then recovers on relaunch, and the dead
    replica's lease expires in the mesh registry."""
    chaos_log = str(tmp_path / "chaos.log")
    monkeypatch.setenv(chaos.LOG_ENV_VAR, chaos_log)

    sc = LocalSparkContext(num_executors=2, task_timeout=240)
    mesh = router = None
    stop = threading.Event()
    try:
        cluster = TFCluster.run(
            sc, fn_sleep_forever, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        # the mesh lives driver-side: its metrics ride the driver's
        # process-global registry into the merged cluster.metrics() view
        mesh = ServingMesh(
            _bundle(tmp_path / "bundle", 3), replicas=3, mode="process",
            monitor_interval=0.5, lease_ttl=2.0,
        )
        mesh.start()
        router = mesh.router(deadline=30.0)
        expiries = _counter("registry_lease_expirations_total")
        relaunches = _counter("serving_replica_relaunches_total")
        errors = []
        min_active = [99]

        def load():
            while not stop.is_set():
                try:
                    out = router.predict_binary(x=np.ones((1, 1), np.float32))
                    assert float(np.asarray(out["y_"]).ravel()[0]) == 3.0
                except Exception as e:  # any client-visible failure fails the leg
                    errors.append(e)
                g = obs.snapshot()["gauges"].get("serving_replicas_active")
                if g is not None:
                    min_active[0] = min(min_active[0], g["value"])
                time.sleep(0.01)

        threads = [threading.Thread(target=load) for _ in range(3)]
        for t in threads:
            t.start()
        time.sleep(1.0)  # load is flowing before the fault lands
        chaos.install(
            chaos.ChaosPlan(seed=13).site(
                "serving.replica_kill", probability=1.0, max_count=1
            ),
            propagate=False,
        )
        deadline = time.time() + 90
        while time.time() < deadline:
            if (
                _counter("serving_replica_relaunches_total") - relaunches >= 1
                and len(mesh.endpoints()) == 3
            ):
                break
            time.sleep(0.5)
        time.sleep(1.0)  # settled load on the recovered mesh
        stop.set()
        for t in threads:
            t.join(timeout=60)

        assert not errors, errors[:3]
        assert _counter("serving_replica_relaunches_total") - relaunches >= 1
        assert _counter("registry_lease_expirations_total") - expiries >= 1
        assert min_active[0] <= 2  # the gauge dip was observable
        assert len(mesh.endpoints()) == 3

        snap = cluster.metrics()
        assert snap["counters"]["serving_failovers_total"]["value"] > 0
        assert snap["gauges"]["serving_replicas_active"]["value"] == 3

        cluster.shutdown(timeout=120)
    finally:
        stop.set()
        if router is not None:
            router.close()
        if mesh is not None:
            mesh.stop()
        sc.stop()
        chaos.uninstall()

    with open(chaos_log) as f:
        fired = [line.strip() for line in f]
    assert "serving.replica_kill" in fired
