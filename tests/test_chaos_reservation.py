"""Chaos: the reservation control plane assembles despite injected faults —
dropped registrations (server closes before replying), client-side
connection resets, slow accepts and late registrations — because REG is
idempotent and the client's shared retry policy re-registers."""

import threading

import pytest

from tensorflowonspark_tpu import chaos, obs, reservation, resilience

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    """Keep retry sleeps in the millisecond range for the test."""
    monkeypatch.setattr(
        reservation.Client, "BACKOFF",
        resilience.Backoff(base=0.02, factor=2.0, max_delay=0.1, jitter=0.5, seed=0),
    )


def _counter(name):
    return obs.snapshot()["counters"].get(name, {}).get("value", 0)


class TestReservationChaos:
    def test_cluster_assembles_despite_dropped_registrations(self):
        plan = chaos.ChaosPlan(seed=5).site(
            "reservation.reg_drop", probability=1.0, max_count=2
        )
        chaos.install(plan, propagate=False)
        retries_before = _counter("reservation_client_retries_total")
        server = reservation.Server(3)
        addr = server.start()
        try:
            clients = [reservation.Client(addr, timeout=5) for _ in range(3)]
            threads = [
                threading.Thread(target=c.register, args=({"host": "h", "executor_id": i},))
                for i, c in enumerate(clients)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            info = server.await_reservations(timeout=30)
            assert {r["executor_id"] for r in info} == {0, 1, 2}
        finally:
            server.stop()
        # both faults fired and every one forced a client retry
        assert plan.fired("reservation.reg_drop") == 2
        assert _counter("reservation_client_retries_total") >= retries_before + 2
        assert _counter("chaos_fault_reservation_reg_drop_total") >= 2

    def test_client_survives_injected_connection_resets(self):
        plan = chaos.ChaosPlan(seed=1).site(
            "reservation.client_reset", probability=1.0, max_count=2
        )
        chaos.install(plan, propagate=False)
        server = reservation.Server(1)
        addr = server.start()
        try:
            client = reservation.Client(addr, timeout=5)
            client.register({"host": "a", "executor_id": 0})  # eats both resets
            assert client.await_reservations(timeout=10)
        finally:
            server.stop()
        assert plan.fired("reservation.client_reset") == 2

    def test_reset_budget_beyond_retries_surfaces_reservation_error(self):
        # more resets than the retry budget: the client gives up cleanly
        plan = chaos.ChaosPlan(seed=1).site("reservation.client_reset", probability=1.0)
        chaos.install(plan, propagate=False)
        server = reservation.Server(1)
        addr = server.start()
        try:
            client = reservation.Client(addr, timeout=5)
            with pytest.raises(reservation.ReservationError, match="could not reach"):
                client.register({"host": "a", "executor_id": 0})
        finally:
            server.stop()
        assert plan.fired("reservation.client_reset") == reservation.Client.RETRIES

    def test_slow_accept_and_late_register_only_delay(self):
        plan = (
            chaos.ChaosPlan(seed=2)
            .site("reservation.slow_accept", probability=1.0, max_count=2, delay_s=0.05)
            .site("reservation.late_register", probability=1.0, max_count=1, delay_s=0.05)
        )
        chaos.install(plan, propagate=False)
        server = reservation.Server(1)
        addr = server.start()
        try:
            client = reservation.Client(addr, timeout=5)
            client.register({"host": "a", "executor_id": 0})
            assert client.await_reservations(timeout=10)
        finally:
            server.stop()
        assert plan.fired("reservation.slow_accept") >= 1
        assert plan.fired("reservation.late_register") == 1
