"""TimeHistory / build_stats (the reference's measurement instrumentation,
common.py:177-245, promoted from example code to a framework module)."""

import numpy as np

from tensorflowonspark_tpu.train import TimeHistory, build_stats


def test_time_history_intervals_and_rate(monkeypatch):
    clock = {"t": 100.0}
    monkeypatch.setattr("time.time", lambda: clock["t"])

    th = TimeHistory(batch_size=32, log_steps=4)
    for _ in range(12):  # 3 complete intervals
        th.batch_end()
        clock["t"] += 0.5
    assert th.global_steps == 12
    assert len(th.timestamps) == 3
    # avg_exp_per_second = bs * log_steps * (N-1) / (t_last - t_first):
    # interval ends at t=101.5, 103.5, 105.5 -> 32*4*2/4 = 64
    assert abs(th.avg_examples_per_second - 64.0) < 1e-6


def test_time_history_too_short_run():
    th = TimeHistory(batch_size=8, log_steps=100)
    th.batch_end()
    assert th.avg_examples_per_second == 0.0
    assert th.timestamps == []


def test_build_stats_shapes():
    th = TimeHistory(batch_size=8, log_steps=1)
    th.batch_end()
    th.batch_end()
    stats = build_stats(
        loss=np.float32(1.5),
        metrics={"accuracy": np.float32(0.9), "step": 10},
        time_history=th,
        eval_results={"accuracy": 0.8},
    )
    assert stats["loss"] == 1.5
    assert stats["accuracy"] == np.float32(0.9)
    assert stats["eval_accuracy"] == 0.8
    assert len(stats["step_timestamp_log"]) == 2
    assert stats["train_finish_time"] is not None
    assert stats["avg_exp_per_second"] > 0


def test_build_stats_minimal():
    assert build_stats(None) == {}
    assert build_stats(2.0) == {"loss": 2.0}
