"""Multi-host performance plane: hybrid mesh placement, host-side bucketed
gradient overlap, and multi-process gloo worlds.

Three layers, cheapest first:

* pure placement math — ``_hybrid_factors`` / ``_hybrid_device_grid`` /
  ``build_hybrid_mesh`` driven with fake slice-tagged device objects (the
  ``TestMultiSliceWarning`` idiom), asserting DCN-outer/ICI-inner layout;
* single-process ``BucketedOverlap`` — the overlap-on/off bit-identity
  contract and the measured ``comm_overlap_fraction``;
* real 2- and 4-rank gloo worlds (``util.spawn_process`` +
  ``testing.join_cpu_world``, the test_jax_distributed pattern) proving the
  :class:`HostAllReduceGroup` determinism contract cross-process, and the
  ``comm.link_delay`` chaos straggler leg (graceful degradation, victim
  gating, straggle visible in every rank's step-time distribution — sync
  training is lockstep, so one slow link slows the world).
"""

import json
import os

import numpy as np
import pytest

from tensorflowonspark_tpu import util
from tensorflowonspark_tpu.parallel import mesh as mesh_mod


class _FakeDev:
    """Stands in for a TPU device: identity + slice tag, nothing else."""

    def __init__(self, i, slice_index=None):
        self.id = i
        self.platform = "cpu"
        if slice_index is not None:
            self.slice_index = slice_index

    def __repr__(self):
        return "F{}s{}".format(self.id, getattr(self, "slice_index", "-"))


def _two_slices(per_slice=4):
    return [_FakeDev(i, i // per_slice) for i in range(2 * per_slice)]


class TestHybridFactors:
    def test_sequence_gives_whole_factor_to_first_fit(self):
        f = mesh_mod._hybrid_factors({"dp": 4, "fsdp": 2}, 2, ("dp",))
        assert f == {"dp": 2, "fsdp": 1}

    def test_sequence_skips_non_dividing_axis(self):
        f = mesh_mod._hybrid_factors({"dp": 3, "fsdp": 4}, 2, ("dp", "fsdp"))
        assert f == {"dp": 1, "fsdp": 2}

    def test_no_axis_can_absorb_raises(self):
        with pytest.raises(ValueError, match="absorb the DCN dimension"):
            mesh_mod._hybrid_factors({"tp": 3}, 2, ("dp",))

    def test_dict_split_validated(self):
        f = mesh_mod._hybrid_factors({"dp": 4, "fsdp": 4}, 4, {"dp": 2, "fsdp": 2})
        assert f == {"dp": 2, "fsdp": 2}
        with pytest.raises(ValueError, match="does not divide"):
            mesh_mod._hybrid_factors({"dp": 3}, 2, {"dp": 2})
        with pytest.raises(ValueError, match="multiply to the slice count"):
            mesh_mod._hybrid_factors({"dp": 4, "fsdp": 4}, 4, {"dp": 2})


class TestHybridDeviceGrid:
    def test_slice_major_within_split_axis(self):
        # dp=4 split 2 (DCN) x 2 (ICI): dp rows 0,1 from slice 0, rows 2,3
        # from slice 1 — walking dp crosses the DCN boundary exactly once
        devs = _two_slices(4)
        grid = mesh_mod._hybrid_device_grid(
            {"dp": 4, "fsdp": 2}, {"dp": 2, "fsdp": 1},
            mesh_mod._slice_groups(devs),
        )
        assert grid.shape == (4, 2)
        for j in range(4):
            rows = {d.slice_index for d in grid[j]}
            assert rows == {j // 2}, grid

    def test_unsplit_axis_stays_inside_a_slice(self):
        devs = _two_slices(4)
        grid = mesh_mod._hybrid_device_grid(
            {"dp": 2, "fsdp": 4}, {"dp": 2, "fsdp": 1},
            mesh_mod._slice_groups(devs),
        )
        # fsdp (inner, all-ICI) never leaves a slice; dp crosses slices
        for j in range(2):
            assert {d.slice_index for d in grid[j]} == {j}

    def test_unequal_slices_raise(self):
        devs = [_FakeDev(0, 0), _FakeDev(1, 0), _FakeDev(2, 1)]
        with pytest.raises(ValueError, match="devices; hybrid mesh needs"):
            mesh_mod._hybrid_device_grid(
                {"dp": 3}, {"dp": 1}, mesh_mod._slice_groups(devs)
            )


class TestBuildHybridMesh:
    def test_default_axes_dp_over_slices_fsdp_within(self):
        m = mesh_mod.build_hybrid_mesh(devices=_two_slices(4))
        assert mesh_mod.mesh_shape(m) == {"dp": 2, "fsdp": 4}
        for j in range(2):
            assert {d.slice_index for d in m.devices[j].ravel()} == {j}

    def test_explicit_axes_split_dp(self):
        m = mesh_mod.build_hybrid_mesh({"dp": 4, "fsdp": 2}, devices=_two_slices(4))
        assert mesh_mod.mesh_shape(m) == {"dp": 4, "fsdp": 2}
        for j in range(4):
            assert {d.slice_index for d in m.devices[j].ravel()} == {j // 2}

    def test_single_slice_delegates_to_flat_build(self):
        devs = [_FakeDev(i, 0) for i in range(4)]
        m = mesh_mod.build_hybrid_mesh({"dp": -1}, devices=devs)
        assert mesh_mod.mesh_shape(m) == {"dp": 4}

    def test_drop_trivial_keeps_dcn_axes(self):
        # fsdp==1 is droppable; dp carries the DCN factor and must survive
        m = mesh_mod.build_hybrid_mesh(
            {"dp": 2, "fsdp": 1}, devices=_two_slices(1), drop_trivial=True
        )
        assert mesh_mod.mesh_shape(m) == {"dp": 2}


class TestBuildMeshDelegation:
    """Satellite: build_mesh on a multi-slice world delegates to the hybrid
    placement instead of warning about its own flat reshape."""

    def test_multi_slice_delegates_silently(self, caplog):
        import logging

        with caplog.at_level(logging.WARNING, logger=mesh_mod.__name__):
            m = mesh_mod.build_mesh({"dp": 2, "fsdp": 4}, devices=_two_slices(4))
        assert not caplog.records
        assert mesh_mod.mesh_shape(m) == {"dp": 2, "fsdp": 4}
        for j in range(2):
            assert {d.slice_index for d in m.devices[j].ravel()} == {j}

    def test_unplaceable_falls_back_to_flat_with_warning(self, caplog):
        import logging

        # dp=3 cannot absorb the 2-slice DCN dimension -> hybrid placement
        # fails, the old flat reshape (and its warning) is the fallback
        devs = [_FakeDev(i, i // 3) for i in range(6)]
        with caplog.at_level(logging.WARNING, logger=mesh_mod.__name__):
            m = mesh_mod.build_mesh({"dp": 3, "tp": 2}, devices=devs)
        assert any("flat reshape" in r.getMessage() for r in caplog.records)
        assert mesh_mod.mesh_shape(m) == {"dp": 3, "tp": 2}


# -- single-process overlap scheduler ------------------------------------------


def _mlp_setup(fsdp=False):
    import jax
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.train import SyncDataParallel

    mesh = parallel.local_mesh({"dp": 4, "fsdp": 2} if fsdp else {"dp": -1})
    strategy = SyncDataParallel(mesh, fsdp=fsdp)

    def init_fn(rng):
        k1, k2 = jax.random.split(rng)
        return {
            "w1": jax.random.normal(k1, (64, 64)) * 0.1,
            "w2": jax.random.normal(k2, (64, 8)) * 0.1,
        }

    def loss_fn(params, batch):
        import jax.numpy as jnp

        h = jnp.tanh(batch["x"] @ params["w1"])
        return jnp.mean((h @ params["w2"] - batch["y"]) ** 2)

    return strategy, init_fn, loss_fn, optax.adam(1e-2)


def _microbatches(strategy, rng, n, rows=8):
    return [
        strategy.shard_batch(
            {
                "x": rng.normal(size=(rows, 64)).astype(np.float32),
                "y": rng.normal(size=(rows, 8)).astype(np.float32),
            }
        )
        for _ in range(n)
    ]


class TestBucketedOverlap:
    def _losses(self, overlap, steps=4, bucket_bytes=4096):
        import jax

        from tensorflowonspark_tpu.train import BucketedOverlap

        strategy, init_fn, loss_fn, opt = _mlp_setup()
        state = strategy.create_state(init_fn, opt, jax.random.PRNGKey(0))
        sched = BucketedOverlap(
            strategy, loss_fn, opt, bucket_bytes=bucket_bytes, overlap=overlap
        )
        rng = np.random.default_rng(11)
        mbs = _microbatches(strategy, rng, 3)  # fixed: loss must descend
        losses = []
        for _ in range(steps):
            state, metrics = sched.step(state, mbs)
            losses.append(float(metrics["loss"]))
        stats = dict(sched.last_stats)
        sched.close()
        return losses, stats

    def test_on_off_bit_identical_and_training_progresses(self):
        on, stats_on = self._losses(True)
        off, stats_off = self._losses(False)
        assert on == off, (on, off)  # bitwise: same programs, same order
        assert on[-1] < on[0]
        # overlap=False joins the comm thread before the next dispatch, so
        # by construction no comm second coincides with later device work
        assert stats_off["overlap_fraction"] == 0.0
        assert stats_on["overlap_fraction"] > 0.0, stats_on

    def test_multiple_buckets_partition(self):
        import jax

        from tensorflowonspark_tpu.train import BucketedOverlap

        strategy, init_fn, loss_fn, opt = _mlp_setup()
        state = strategy.create_state(init_fn, opt, jax.random.PRNGKey(0))
        sched = BucketedOverlap(strategy, loss_fn, opt, bucket_bytes=4096)
        rng = np.random.default_rng(1)
        sched.step(state, _microbatches(strategy, rng, 1))
        # w1 (16 KiB) exceeds the 4 KiB bound -> its own bucket; w2 fits
        assert len(sched._buckets) == 2, sched._buckets
        sched.close()

    def test_rejects_fsdp_strategy_naming_axes(self):
        from tensorflowonspark_tpu.train import BucketedOverlap

        strategy, _, loss_fn, opt = _mlp_setup(fsdp=True)
        # the error must name the offending axes AND the supported
        # compositions (satellite contract of the model-axis PR)
        with pytest.raises(ValueError, match=r"axes \('fsdp',\)") as ei:
            BucketedOverlap(strategy, loss_fn, opt)
        assert "dp x tp" in str(ei.value)

    def test_tp_sharded_params_sync_and_stay_sharded(self):
        """dp×tp composition: grads all-reduce over dp only (here: a single
        process, so the step is pure grad accumulation), the apply program
        keeps params tp-sharded, and the trajectory matches an unsharded
        reference exactly."""
        import jax
        import jax.numpy as jnp
        import optax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import transformer
        from tensorflowonspark_tpu.train import BucketedOverlap, SyncDataParallel

        if jax.device_count() < 8:
            pytest.skip("needs 8 cpu devices")
        cfg = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64,
                   dtype="float32", attention="plain")
        mesh = parallel.local_mesh({"dp": 2, "tp": 4})
        model = transformer.create_model(mesh=mesh, **cfg)
        tloss = transformer.make_loss_fn(model)
        opt = optax.sgd(0.1)
        strategy = SyncDataParallel(mesh, tp=transformer.param_specs)
        state = strategy.create_state(
            transformer.make_init_fn(model), opt, jax.random.PRNGKey(0)
        )
        params0 = jax.device_get(state.params)
        spec0 = jax.tree.map(lambda x: x.sharding.spec, state.params)
        flat_axes = {
            ax
            for s in jax.tree.leaves(spec0, is_leaf=lambda n: hasattr(n, "index"))
            for ax in s
            if isinstance(ax, str)
        }
        assert "tp" in flat_axes, flat_axes

        def loss_fn(params, batch):
            return tloss(params, batch)[0]

        rng = np.random.default_rng(7)
        mbs = [
            strategy.shard_batch(
                {"tokens": rng.integers(0, 64, (4, 16)).astype(np.int32)}
            )
            for _ in range(2)
        ]
        sched = BucketedOverlap(strategy, loss_fn, opt)
        state, _ = sched.step(state, mbs)
        state, metrics = sched.step(state, mbs)
        sched.close()
        spec_after = jax.tree.map(lambda x: x.sharding.spec, state.params)
        assert spec0 == spec_after  # the apply program pinned out_shardings

        # unsharded reference: identical grad-accumulation SGD trajectory
        model_u = transformer.create_model(mesh=None, **cfg)
        loss_u = transformer.make_loss_fn(model_u)
        params, opt_state = params0, opt.init(params0)
        host_mbs = [jax.device_get(mb) for mb in mbs]
        for _ in range(2):
            grads = None
            for mb in host_mbs:
                g = jax.grad(lambda p, b: loss_u(p, b)[0])(params, mb)
                grads = g if grads is None else jax.tree.map(jnp.add, grads, g)
            grads = jax.tree.map(lambda g: g / len(host_mbs), grads)
            updates, opt_state = opt.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
        probe = host_mbs[0]
        ref = float(loss_u(params, probe)[0])
        got = float(loss_u(jax.device_get(state.params), probe)[0])
        assert abs(ref - got) <= 2e-5, (ref, got)

    def test_empty_microbatches_raise(self):
        import jax

        from tensorflowonspark_tpu.train import BucketedOverlap

        strategy, init_fn, loss_fn, opt = _mlp_setup()
        state = strategy.create_state(init_fn, opt, jax.random.PRNGKey(0))
        sched = BucketedOverlap(strategy, loss_fn, opt)
        with pytest.raises(ValueError, match="at least one microbatch"):
            sched.step(state, [])


class TestFsdpOverlay:
    def test_gauge_counts_sharded_params(self):
        import jax
        import optax

        from tensorflowonspark_tpu import obs, parallel
        from tensorflowonspark_tpu.train import SyncDataParallel

        strategy = SyncDataParallel(
            parallel.local_mesh({"dp": 4, "fsdp": 2}), fsdp=True,
            min_weight_size=1,
        )

        def init_fn(rng):
            k1, k2 = jax.random.split(rng)
            return {
                "w1": jax.random.normal(k1, (64, 64)),
                "w2": jax.random.normal(k2, (64, 8)),
            }

        state = strategy.create_state(init_fn, optax.sgd(0.1), jax.random.PRNGKey(0))
        # both leaves have a dim divisible by the 2-way fsdp axis
        specs = [leaf.sharding.spec for leaf in jax.tree.leaves(state.params)]
        assert all(
            any("fsdp" in ((ax,) if isinstance(ax, str) else tuple(ax or ()))
                for ax in spec)
            for spec in specs
        ), specs
        snap = obs.snapshot()
        assert snap["gauges"]["fsdp_params_sharded"]["value"] == 2

    def test_overlay_respects_existing_specs_and_threshold(self):
        from jax.sharding import PartitionSpec as P

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.parallel.sharding import overlay_fsdp_specs

        mesh = parallel.local_mesh({"dp": 4, "fsdp": 2})
        params = {
            "big": np.zeros((64, 64), np.float32),
            "tiny": np.zeros((4,), np.float32),
            "taken": np.zeros((64, 64), np.float32),
        }
        specs = {"big": P(), "tiny": P(), "taken": P(None, "fsdp")}
        out = overlay_fsdp_specs(params, specs, mesh, min_weight_size=64)
        assert out["taken"] == P(None, "fsdp")  # already on fsdp: untouched
        assert out["tiny"] == P()  # under the threshold: replicated
        assert "fsdp" in [ax for ax in out["big"] if ax]  # sharded


# -- multi-process gloo worlds -------------------------------------------------


def _world_member(pid, num_procs, coord_port, out_dir, scenario):
    """One gloo world member (module-level: spawn-picklable)."""
    from tensorflowonspark_tpu.testing import join_cpu_world

    join_cpu_world(pid, num_procs, coord_port, local_devices=1)
    import time

    import jax

    from tensorflowonspark_tpu import chaos
    from tensorflowonspark_tpu.parallel.hostreduce import HostAllReduceGroup
    from tensorflowonspark_tpu.train import BucketedOverlap

    out = {"pid": pid}
    with HostAllReduceGroup(pid, num_procs) as group:
        # raw collective determinism: distinct per-rank payloads, exact mean
        buf = np.arange(8, dtype=np.float32) + 10.0 * pid
        reduced = group.allreduce_mean(buf)
        expect = np.mean(
            [np.arange(8, dtype=np.float32) + 10.0 * r for r in range(num_procs)],
            axis=0,
        )
        out["reduce_exact"] = bool(np.array_equal(reduced, expect))

        strategy, init_fn, loss_fn, opt = _mlp_setup()

        if scenario == "chaos":
            # every rank installs the same single-victim plan: rank 0's
            # link straggles; victim gating must leave rank 1's budget at 0
            plan = chaos.ChaosPlan(seed=5).site(
                "comm.link_delay", probability=1.0, delay_s=0.08, victim=0
            )
            chaos.install(plan, propagate=False)

        def run(overlap, steps):
            state = strategy.create_state(init_fn, opt, jax.random.PRNGKey(0))
            sched = BucketedOverlap(
                strategy, loss_fn, opt, group=group, bucket_bytes=1 << 14,
                overlap=overlap,
            )
            rng = np.random.default_rng(100 + pid)  # per-rank data
            mbs = _microbatches(strategy, rng, 2)  # fixed: loss must descend
            losses, times = [], []
            for _ in range(steps):
                t0 = time.perf_counter()
                state, metrics = sched.step(state, mbs)
                times.append(time.perf_counter() - t0)
                losses.append(float(metrics["loss"]))
            sched.close()
            return losses, times

        out["losses_on"], out["times_on"] = run(True, 4)
        out["losses_off"], out["times_off"] = run(False, 4)
        if scenario == "chaos":
            out["fired"] = chaos.plan().fired()
            chaos.uninstall()
            out["losses_clean"], out["times_clean"] = run(True, 4)

    with open(os.path.join(out_dir, "rank{}.json".format(pid)), "w") as f:
        json.dump(out, f)


def _run_world(tmp_path, num_procs, scenario="plain"):
    import functools

    coord_port = util.find_free_port()
    procs = [
        util.spawn_process(
            functools.partial(
                _world_member, pid, num_procs, coord_port, str(tmp_path), scenario
            ),
            name="mc-{}".format(pid),
        )
        for pid in range(num_procs)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=300)
    assert all(p.exitcode == 0 for p in procs), [p.exitcode for p in procs]
    results = []
    for pid in range(num_procs):
        with open(tmp_path / "rank{}.json".format(pid)) as f:
            results.append(json.load(f))
    return results


def _tp_world_member(pid, num_procs, coord_port, out_dir):
    """dp across processes × tp across the member's two local cpu devices."""
    from tensorflowonspark_tpu.testing import join_cpu_world

    join_cpu_world(pid, num_procs, coord_port, local_devices=2)
    import jax
    import jax.numpy as jnp
    import optax
    from jax.sharding import PartitionSpec as P

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.parallel.hostreduce import HostAllReduceGroup
    from tensorflowonspark_tpu.train import BucketedOverlap, SyncDataParallel

    def spec_fn(params, mesh):
        # Megatron column/row pair for the 2-layer MLP
        return {"w1": P(None, "tp"), "w2": P("tp", None)}

    def init_fn(rng):
        k1, k2 = jax.random.split(rng)
        return {
            "w1": jax.random.normal(k1, (64, 64)) * 0.1,
            "w2": jax.random.normal(k2, (64, 8)) * 0.1,
        }

    def loss_fn(params, batch):
        h = jnp.tanh(batch["x"] @ params["w1"])
        return jnp.mean((h @ params["w2"] - batch["y"]) ** 2)

    opt = optax.adam(1e-2)
    mesh = parallel.local_mesh({"tp": 2})
    strategy = SyncDataParallel(mesh, tp=spec_fn)
    out = {"pid": pid}
    with HostAllReduceGroup(pid, num_procs) as group:
        state = strategy.create_state(init_fn, opt, jax.random.PRNGKey(0))
        sched = BucketedOverlap(strategy, loss_fn, opt, group=group)
        rng = np.random.default_rng(100 + pid)  # per-rank data (the dp axis)
        mbs = _microbatches(strategy, rng, 2)
        losses = []
        for _ in range(4):
            state, metrics = sched.step(state, mbs)
            losses.append(float(metrics["loss"]))
        sched.close()
        out["losses"] = losses
        axes = {
            ax
            for leaf in jax.tree.leaves(state.params)
            for ax in leaf.sharding.spec
            if isinstance(ax, str)
        }
        out["tp_sharded_after"] = "tp" in axes
    with open(os.path.join(out_dir, "rank{}.json".format(pid)), "w") as f:
        json.dump(out, f)


@pytest.mark.slow
def test_two_rank_dp_tp_world(tmp_path):
    """dp over 2 gloo processes × tp over 2 local cpu devices each: the
    host all-reduce averages only the (replicated) dp axis, every rank sees
    the same global-mean loss trajectory, training moves, and params stay
    tp-sharded through the apply program."""
    import functools

    coord_port = util.find_free_port()
    procs = [
        util.spawn_process(
            functools.partial(
                _tp_world_member, pid, 2, coord_port, str(tmp_path)
            ),
            name="tp-{}".format(pid),
        )
        for pid in range(2)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=300)
    assert all(p.exitcode == 0 for p in procs), [p.exitcode for p in procs]
    results = []
    for pid in range(2):
        with open(tmp_path / "rank{}.json".format(pid)) as f:
            results.append(json.load(f))
    assert results[0]["losses"] == results[1]["losses"]
    assert results[0]["losses"][-1] < results[0]["losses"][0]
    assert all(r["tp_sharded_after"] for r in results)


@pytest.mark.slow
def test_two_rank_determinism_and_overlap(tmp_path):
    """2-rank gloo world: the host all-reduce is exact and rank-order
    deterministic, every rank sees the same loss trajectory (it is a global
    mean), and the trajectory is bit-identical with overlap on or off."""
    results = _run_world(tmp_path, 2)
    assert all(r["reduce_exact"] for r in results), results
    # loss is reduced across ranks: identical everywhere, in both modes
    assert results[0]["losses_on"] == results[1]["losses_on"]
    assert results[0]["losses_on"] == results[0]["losses_off"]
    assert results[0]["losses_off"] == results[1]["losses_off"]
    # and training moved
    assert results[0]["losses_on"][-1] < results[0]["losses_on"][0]


@pytest.mark.slow
@pytest.mark.skipif(
    (os.cpu_count() or 1) < 4,
    reason="4 lockstep jax worlds need >= 4 cores to measure anything",
)
def test_four_rank_weak_scaling_smoke(tmp_path):
    """4-rank smoke: the group and scheduler hold at the widest CI world."""
    results = _run_world(tmp_path, 4)
    assert all(r["reduce_exact"] for r in results), results
    first = results[0]["losses_on"]
    assert all(r["losses_on"] == first for r in results)


@pytest.mark.chaos
@pytest.mark.slow
def test_comm_link_delay_straggler(tmp_path):
    """comm.link_delay on rank 0: the world degrades gracefully (losses stay
    bit-identical across ranks and modes), the victim's budget is the only
    one spent, and the straggle is visible in every rank's step-time
    distribution — sync data parallelism is lockstep, one slow link slows
    the world; uninstalling the plan brings step times back down."""
    results = _run_world(tmp_path, 2, scenario="chaos")
    # determinism survives the straggler
    assert results[0]["losses_on"] == results[1]["losses_on"]
    assert results[0]["losses_on"] == results[0]["losses_off"]
    # victim gating: rank 0 fired, rank 1's identical plan spent nothing
    assert results[0]["fired"] > 0
    assert results[1]["fired"] == 0
    # straggle shows in the per-rank spread: chaos-window step times sit
    # well above the clean window on BOTH ranks (the delay propagates
    # through the collective), and recover once the plan is gone
    for r in results:
        chaos_p50 = float(np.median(r["times_on"][1:]))
        clean_p50 = float(np.median(r["times_clean"][1:]))
        assert chaos_p50 > clean_p50 + 0.05, (r["pid"], chaos_p50, clean_p50)
