"""store/ subsystem: the shared framing/chunk implementation, the
ShardStore ABI (LocalStore, HTTPStore with range-GETs and the GCS/S3
endpoint adapters against in-process fixtures), the prefetch staging
tier's durable commit / verify-on-read / LRU eviction, deterministic
local-vs-remote shard assignment, the byte-identical stream matrix
(local / HTTP-cold / warm-staged / post-eviction, image and text planes),
and the ``store.*`` chaos sites."""

import functools
import http.server
import json
import os
import threading
import urllib.parse

import numpy as np
import pytest

from tensorflowonspark_tpu import chaos, obs, tfrecord
from tensorflowonspark_tpu.data import ImagePipeline, TextPipeline, Tokenizer
from tensorflowonspark_tpu.data.loader import shard_files
from tensorflowonspark_tpu.store import (
    GCSAdapter,
    HTTPStore,
    LocalStore,
    S3Adapter,
    base,
    framing,
    resolve_store,
    shard_sort_key,
)
from tensorflowonspark_tpu.store import staging


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _counter(name):
    return obs.snapshot()["counters"].get(name, {}).get("value", 0)


# -- corpus + stream helpers (the loader-test idiom) ------------------------


def _write_shards(root, n_shards=3, per=47, name="corpus"):
    d = os.path.join(str(root), name)
    os.makedirs(d, exist_ok=True)
    idx = 0
    paths = []
    for s in range(n_shards):
        p = os.path.join(d, "part-{:05d}".format(s))
        with tfrecord.TFRecordWriter(p) as w:
            for _ in range(per):
                w.write(str(idx).encode())
                idx += 1
        paths.append(p)
    return d, paths


def _parse(rec):
    v = int(rec)
    return np.full((4, 4, 1), v % 251, np.uint8), v


def _stream(pipe):
    out = []
    for b in pipe:
        out.append((np.array(b["image"]).tobytes(), np.array(b["label"]).tobytes()))
    return out


def _records(chunks_iter):
    return [rec for chunk in chunks_iter for rec in chunk]


# -- in-process HTTP fixtures (no cloud creds, no sockets past loopback) ----


class _RangeHandler(http.server.SimpleHTTPRequestHandler):
    """Directory server that honors single byte ranges with 206 — the
    object-store access pattern plain ``http.server`` ignores."""

    def log_message(self, *args):
        pass

    def do_GET(self):
        path = self.translate_path(self.path)
        if os.path.isdir(path):
            return super().do_GET()  # directory-index listing
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            self.send_error(404)
            return
        rng = self.headers.get("Range", "")
        if rng.startswith("bytes="):
            start_s, _, end_s = rng[len("bytes="):].partition("-")
            start = int(start_s)
            if start >= len(data):
                self.send_response(416)
                self.send_header("Content-Range", "bytes */{}".format(len(data)))
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            end = min(int(end_s) if end_s else len(data) - 1, len(data) - 1)
            body = data[start : end + 1]
            self.send_response(206)
            self.send_header("Content-Length", str(len(body)))
            self.send_header(
                "Content-Range", "bytes {}-{}/{}".format(start, end, len(data))
            )
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)


class _PlainHandler(http.server.SimpleHTTPRequestHandler):
    """Stock behavior: the Range header is ignored, every GET answers 200
    with the whole body — the fallback HTTPStore must slice client-side."""

    def log_message(self, *args):
        pass


class _ObjectHandler(http.server.BaseHTTPRequestHandler):
    """Minimal GCS-JSON / S3-ListObjectsV2 object endpoint over one
    ``{"bucket/key": bytes}`` corpus dict (set per-server)."""

    corpus = {}

    def log_message(self, *args):
        pass

    def _resolve(self):
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        path = urllib.parse.unquote(parsed.path).lstrip("/")
        if parsed.path.startswith("/storage/v1/b/"):  # GCS JSON listing
            bucket = parsed.path.split("/")[4]
            prefix = urllib.parse.unquote(qs.get("prefix", [""])[0])
            items = [
                {"name": k.split("/", 1)[1]}
                for k in sorted(self.corpus)
                if k.startswith(bucket + "/" + prefix)
            ]
            return 200, json.dumps({"items": items}).encode()
        if "list-type" in qs:  # S3 ListObjectsV2
            bucket = path.split("?")[0]
            prefix = urllib.parse.unquote(qs.get("prefix", [""])[0])
            keys = [
                k.split("/", 1)[1]
                for k in sorted(self.corpus)
                if k.startswith(bucket + "/" + prefix)
            ]
            xml = "".join("<Key>{}</Key>".format(k) for k in keys)
            return 200, ("<ListBucketResult>" + xml + "</ListBucketResult>").encode()
        data = self.corpus.get(path)
        if data is None:
            return 404, b""
        return 200, data

    def _reply(self, status, body, send_body):
        if status != 200:
            self.send_response(status)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        rng = self.headers.get("Range", "")
        if send_body and rng.startswith("bytes="):
            start_s, _, end_s = rng[len("bytes="):].partition("-")
            start = int(start_s)
            if start >= len(body):
                self.send_response(416)
                self.send_header("Content-Length", "0")
                self.end_headers()
                return
            end = min(int(end_s) if end_s else len(body) - 1, len(body) - 1)
            body = body[start : end + 1]
            status = 206
        self.send_response(status)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        if send_body:
            self.wfile.write(body)

    def do_GET(self):
        status, body = self._resolve()
        self._reply(status, body, send_body=True)

    def do_HEAD(self):
        status, body = self._resolve()
        self._reply(status, body, send_body=False)


def _serve(handler):
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv, "http://127.0.0.1:{}".format(srv.server_address[1])


@pytest.fixture
def http_corpus(tmp_path):
    """(url root, local dir, local paths, url paths) over one corpus served
    by the range-capable in-process server."""
    d, paths = _write_shards(tmp_path)
    handler = functools.partial(_RangeHandler, directory=str(tmp_path))
    srv, root = _serve(handler)
    url_root = root + "/corpus"
    urls = [url_root + "/" + os.path.basename(p) for p in paths]
    yield url_root, d, paths, urls
    srv.shutdown()
    srv.server_close()


@pytest.fixture
def _prefetch_env(tmp_path, monkeypatch):
    """Isolate the staging tier per-test: fresh root, no capacity bound."""
    root = tmp_path / "prefetch"
    monkeypatch.setenv(staging.DIR_ENV, str(root))
    monkeypatch.delenv(staging.BYTES_ENV, raising=False)
    monkeypatch.delenv(staging.DEPTH_ENV, raising=False)
    return str(root)


# -- framing: the one chunk implementation ---------------------------------


class TestFraming:
    def test_read_framed_matches_tfrecord_reader(self, tmp_path):
        _, paths = _write_shards(tmp_path, n_shards=1, per=13)
        with open(paths[0], "rb") as f:
            framed = list(framing.read_framed(f, paths[0]))
        assert framed == list(tfrecord.read_records(paths[0]))
        assert framed == [str(i).encode() for i in range(13)]

    def test_truncation_and_crc_errors_surface(self, tmp_path):
        _, paths = _write_shards(tmp_path, n_shards=1, per=5)
        blob = open(paths[0], "rb").read()
        torn = tmp_path / "torn"
        torn.write_bytes(blob[:-3])
        with pytest.raises(IOError):
            with open(str(torn), "rb") as f:
                list(framing.read_framed(f, "torn"))
        flipped = tmp_path / "flipped"
        flipped.write_bytes(blob[:20] + bytes([blob[20] ^ 0xFF]) + blob[21:])
        with pytest.raises(IOError):
            with open(str(flipped), "rb") as f:
                list(framing.read_framed(f, "flipped"))

    def test_chunk_loop_is_shared_by_both_readers(self, tmp_path):
        """Satellite: tfrecord and native_io both delegate to
        framing.iter_chunks — same chunk boundaries, same records."""
        from tensorflowonspark_tpu import native_io

        _, paths = _write_shards(tmp_path, n_shards=1, per=29)
        py_chunks = [list(c) for c in tfrecord.read_records_chunked(paths[0], chunk_records=8)]
        assert [len(c) for c in py_chunks] == [8, 8, 8, 5]
        assert [r for c in py_chunks for r in c] == [str(i).encode() for i in range(29)]
        if native_io.stream_available():
            nat = [list(c) for c in native_io.read_records_chunked(paths[0], chunk_records=8)]
            assert nat == py_chunks

    def test_iter_chunks_retries_open_not_midstream(self, tmp_path):
        from tensorflowonspark_tpu import resilience

        _, paths = _write_shards(tmp_path, n_shards=1, per=6)
        attempts = [0]

        def flaky_open():
            attempts[0] += 1
            if attempts[0] == 1:
                raise IOError("transient open")
            return framing.FramedChunkReader(open(paths[0], "rb"), paths[0])

        retry = resilience.RetryPolicy(
            max_attempts=3,
            backoff=resilience.Backoff(base=0.0, factor=1.0, max_delay=0.0, jitter=0.0),
            retry_on=(OSError,),
            name="test-open",
        )
        recs = _records(framing.iter_chunks(flaky_open, 4, retry=retry))
        assert attempts[0] == 2
        assert recs == [str(i).encode() for i in range(6)]


# -- LocalStore -------------------------------------------------------------


class TestLocalStore:
    def test_list_stat_read_fetch(self, tmp_path):
        d, paths = _write_shards(tmp_path)
        store = LocalStore()
        assert store.handles(paths[0]) and store.handles("file://" + paths[0])
        assert not store.handles("http://x/y")
        assert store.list_shards(d) == paths
        assert store.stat(paths[0])["size"] == os.path.getsize(paths[0])
        recs = _records(store.read_records_chunked(paths[0], chunk_records=16))
        assert recs == list(tfrecord.read_records(paths[0]))
        import io

        buf = io.BytesIO()
        n = store.fetch(paths[0], buf)
        assert n == os.path.getsize(paths[0])
        assert buf.getvalue() == open(paths[0], "rb").read()


# -- HTTPStore over the in-process fixtures --------------------------------


class TestHTTPStore:
    def test_list_stat_and_chunked_read_match_local(self, http_corpus):
        url_root, d, paths, urls = http_corpus
        store = HTTPStore(range_bytes=512)
        shards = store.list_shards(url_root)
        assert [u.rsplit("/", 1)[-1] for u in shards] == [
            os.path.basename(p) for p in paths
        ]
        assert store.stat(urls[0])["size"] == os.path.getsize(paths[0])
        for url, path in zip(urls, paths):
            assert _records(store.read_records_chunked(url, chunk_records=16)) == list(
                tfrecord.read_records(path)
            )

    def test_fetch_downloads_identical_bytes(self, http_corpus):
        import io

        _, _, paths, urls = http_corpus
        store = HTTPStore(range_bytes=100)  # many ranges per object
        buf = io.BytesIO()
        n = store.fetch(urls[1], buf)
        want = open(paths[1], "rb").read()
        assert n == len(want) and buf.getvalue() == want

    def test_200_fallback_when_server_ignores_range(self, tmp_path):
        """Plain http.server answers 200 + whole body; read_range slices
        client-side so the stream is still byte-identical."""
        d, paths = _write_shards(tmp_path)
        handler = functools.partial(_PlainHandler, directory=str(tmp_path))
        srv, root = _serve(handler)
        try:
            store = HTTPStore(range_bytes=64)
            url = root + "/corpus/" + os.path.basename(paths[0])
            blob = open(paths[0], "rb").read()
            assert store.read_range(url, 10, 29) == blob[10:30]
            assert _records(store.read_records_chunked(url, chunk_records=8)) == list(
                tfrecord.read_records(paths[0])
            )
        finally:
            srv.shutdown()
            srv.server_close()

    def test_remote_read_metrics_count(self, http_corpus):
        _, _, paths, urls = http_corpus
        before = _counter("store_remote_reads_total")
        store = HTTPStore(range_bytes=256)
        _records(store.read_records_chunked(urls[0], chunk_records=16))
        assert _counter("store_remote_reads_total") > before

    def test_resolve_store_schemes(self, tmp_path):
        assert resolve_store(["/a/part-0", "/a/part-1"]) is None
        s = resolve_store(["http://h/a", "https://h/b"])
        assert isinstance(s, HTTPStore)
        assert isinstance(resolve_store(["gs://b/k"]).adapter, GCSAdapter)
        assert isinstance(resolve_store(["s3://b/k"]).adapter, S3Adapter)
        with pytest.raises(ValueError):
            resolve_store(["/a/part-0", "http://h/part-1"])


class TestEndpointAdapters:
    def _serve_corpus(self, paths, bucket="bkt"):
        corpus = {
            "{}/corpus/{}".format(bucket, os.path.basename(p)): open(p, "rb").read()
            for p in paths
        }
        handler = type("_H", (_ObjectHandler,), {"corpus": corpus})
        return _serve(handler)

    def test_gcs_adapter_lists_and_reads(self, tmp_path):
        _, paths = _write_shards(tmp_path)
        srv, endpoint = self._serve_corpus(paths)
        try:
            store = HTTPStore(adapter=GCSAdapter(endpoint=endpoint), range_bytes=256)
            shards = store.list_shards("gs://bkt/corpus")
            assert shards == [
                "gs://bkt/corpus/" + os.path.basename(p) for p in paths
            ]
            assert _records(
                store.read_records_chunked(shards[0], chunk_records=16)
            ) == list(tfrecord.read_records(paths[0]))
        finally:
            srv.shutdown()
            srv.server_close()

    def test_s3_adapter_lists_and_reads(self, tmp_path):
        _, paths = _write_shards(tmp_path)
        srv, endpoint = self._serve_corpus(paths)
        try:
            store = HTTPStore(adapter=S3Adapter(endpoint=endpoint), range_bytes=256)
            shards = store.list_shards("s3://bkt/corpus")
            assert shards == [
                "s3://bkt/corpus/" + os.path.basename(p) for p in paths
            ]
            assert _records(
                store.read_records_chunked(shards[2], chunk_records=16)
            ) == list(tfrecord.read_records(paths[2]))
        finally:
            srv.shutdown()
            srv.server_close()


# -- deterministic shard assignment (local == remote) -----------------------


class TestShardAssignment:
    def test_shard_files_orders_urls_like_local_paths(self, http_corpus):
        """Satellite: identical worker→shard assignment whether the corpus
        is listed from a local glob or a remote store."""
        url_root, d, paths, _ = http_corpus
        local = LocalStore().list_shards(d)
        remote = HTTPStore().list_shards(url_root)
        assert [os.path.basename(p) for p in local] == [
            u.rsplit("/", 1)[-1] for u in remote
        ]
        for num_shards in (1, 2, 3):
            for index in range(num_shards):
                l = shard_files(local, num_shards, index)
                r = shard_files(remote, num_shards, index)
                assert [os.path.basename(p) for p in l] == [
                    u.rsplit("/", 1)[-1] for u in r
                ], (num_shards, index)

    def test_shard_files_sorts_unsorted_listings(self, tmp_path):
        d, paths = _write_shards(tmp_path)
        shuffled = [paths[2], paths[0], paths[1]]
        assert shard_files(shuffled, 1, 0) == paths
        urls = ["http://h/c/" + os.path.basename(p) for p in shuffled]
        assert shard_files(urls, 2, 0) == sorted(urls, key=shard_sort_key)[0::2]

    def test_sort_key_is_basename_first(self):
        # two roots, interleaved basenames: basename ordering wins so a
        # re-rooted corpus (local dir vs URL) assigns identically
        mixed = ["/b/part-00001", "/a/part-00000"]
        assert sorted(mixed, key=shard_sort_key) == ["/a/part-00000", "/b/part-00001"]


# -- prefetch staging tier --------------------------------------------------


class TestPrefetchStager:
    def test_resolve_stager_knobs(self, tmp_path, monkeypatch):
        monkeypatch.setenv(staging.DIR_ENV, str(tmp_path / "p"))
        store = LocalStore()
        assert staging.resolve_stager(store, prefetch="0") is None
        assert staging.resolve_stager(store, prefetch="off") is None
        fixed = staging.resolve_stager(store, prefetch="3")
        try:
            assert fixed.depth == 3 and fixed._tuner is None
        finally:
            fixed.close()
        auto = staging.resolve_stager(store, prefetch="auto")
        try:
            assert auto._tuner is not None
        finally:
            auto.close()

    def test_stage_commit_hit_and_warm_reopen(self, http_corpus, tmp_path):
        _, d, paths, urls = http_corpus
        store = HTTPStore(range_bytes=512)
        root = str(tmp_path / "stage")
        stager = staging.PrefetchStager(store, root=root, depth=2)
        try:
            before_hits = _counter("store_prefetch_hits_total")
            stager.plan(urls)
            local0 = stager.fetch(urls[0])
            assert local0 and open(local0, "rb").read() == open(paths[0], "rb").read()
            # second fetch of the same shard: staged-tier hit, no download
            assert stager.fetch(urls[0]) == local0
            assert _counter("store_prefetch_hits_total") > before_hits
        finally:
            stager.close()
        # a new stager (fresh process) adopts the staged dir and verifies
        # it on first use — bytes still identical
        warm = staging.PrefetchStager(store, root=root, depth=2)
        try:
            again = warm.fetch(urls[0])
            assert again and open(again, "rb").read() == open(paths[0], "rb").read()
        finally:
            warm.close()

    def test_verify_on_read_rejects_corrupt_staged_shard(self, http_corpus, tmp_path):
        _, _, paths, urls = http_corpus
        store = HTTPStore(range_bytes=512)
        root = str(tmp_path / "stage")
        stager = staging.PrefetchStager(store, root=root, depth=1)
        try:
            stager.plan(urls[:1])
            local0 = stager.fetch(urls[0])
            assert local0
        finally:
            stager.close()
        # flip one byte of the staged data file behind the manifest's back
        blob = bytearray(open(local0, "rb").read())
        blob[5] ^= 0xFF
        open(local0, "wb").write(bytes(blob))
        before = _counter("store_prefetch_rejects_total")
        fresh = staging.PrefetchStager(store, root=root, depth=1)
        try:
            fresh.plan(urls[:1])
            refetched = fresh.fetch(urls[0])
            assert _counter("store_prefetch_rejects_total") > before
            # the tear was rejected and the shard re-staged from remote
            assert refetched and open(refetched, "rb").read() == open(
                paths[0], "rb"
            ).read()
        finally:
            fresh.close()

    def test_capacity_bound_evicts_lru(self, http_corpus, tmp_path):
        _, _, paths, urls = http_corpus
        store = HTTPStore(range_bytes=512)
        before = _counter("store_prefetch_evictions_total")
        stager = staging.PrefetchStager(
            store, root=str(tmp_path / "stage"), depth=1, capacity_bytes=1
        )
        try:
            stager.plan(urls)
            for u in urls:
                assert stager.fetch(u) is not None
            assert _counter("store_prefetch_evictions_total") > before
            resident = [
                n for n in os.listdir(stager.root) if n.startswith("obj-")
            ]
            assert len(resident) == 1  # the bound keeps at least one shard
        finally:
            stager.close()


# -- byte-identical stream matrix (the tentpole's contract) -----------------


class TestStreamMatrix:
    def _pipe(self, files, **kw):
        kw.setdefault("batch_size", 8)
        kw.setdefault("seed", 3)
        kw.setdefault("epochs", 2)
        kw.setdefault("num_threads", 2)
        return ImagePipeline(files, _parse, **kw)

    def test_image_stream_identical_local_http_warm_evicted(
        self, http_corpus, _prefetch_env, monkeypatch
    ):
        url_root, d, paths, urls = http_corpus
        local = _stream(self._pipe(paths))
        assert local, "pipeline yielded nothing"
        # cold: every chunk range-GETs straight off the remote store
        cold = _stream(self._pipe(urls, prefetch="0"))
        assert cold == local
        # staged: first pass downloads + commits, second pass is warm
        staged1 = _stream(self._pipe(urls, prefetch="2"))
        assert staged1 == local
        hits_before = _counter("store_prefetch_hits_total")
        staged2 = _stream(self._pipe(urls, prefetch="2"))
        assert staged2 == local
        assert _counter("store_prefetch_hits_total") > hits_before
        # post-eviction: a 1-byte capacity bound evicts behind every fetch,
        # so most shards re-stage cold — bytes must not change
        monkeypatch.setenv(staging.BYTES_ENV, "1")
        evb = _counter("store_prefetch_evictions_total")
        evicted = _stream(self._pipe(urls, prefetch="2"))
        assert evicted == local
        assert _counter("store_prefetch_evictions_total") > evb

    def test_image_stream_autodetects_store_for_urls(self, http_corpus, _prefetch_env):
        _, _, paths, urls = http_corpus
        pipe = self._pipe(urls, prefetch="0")
        assert isinstance(pipe.store, HTTPStore)
        assert _stream(pipe) == _stream(self._pipe(paths))

    def test_explicit_store_and_max_bad_records_contract(self, http_corpus, _prefetch_env):
        _, _, paths, urls = http_corpus
        store = HTTPStore(range_bytes=512)

        def parse_or_raise(rec):
            v = int(rec)
            if v % 17 == 0:
                raise ValueError("undecodable {}".format(v))
            return np.full((4, 4, 1), v % 251, np.uint8), v

        a = _stream(
            ImagePipeline(
                paths, parse_or_raise, batch_size=8, seed=3, epochs=1,
                max_bad_records=100,
            )
        )
        b = _stream(
            ImagePipeline(
                urls, parse_or_raise, batch_size=8, seed=3, epochs=1,
                max_bad_records=100, store=store, prefetch="0",
            )
        )
        assert a and a == b

    def test_text_stream_identical_local_http_warm(self, http_corpus, _prefetch_env, tmp_path):
        rng = np.random.default_rng(11)
        words = "remote shard store streams packed text identically".split()
        texts = [
            " ".join(rng.choice(words, size=int(rng.integers(2, 12))))
            for _ in range(90)
        ]
        d = tmp_path / "text"
        d.mkdir()
        paths = []
        for s in range(2):
            p = str(d / "part-{:05d}".format(s))
            with tfrecord.TFRecordWriter(p) as w:
                for t in texts[s * 45 : (s + 1) * 45]:
                    w.write(t.encode())
            paths.append(p)
        handler = functools.partial(_RangeHandler, directory=str(tmp_path))
        srv, root = _serve(handler)
        try:
            urls = [root + "/text/" + os.path.basename(p) for p in paths]

            def pipe(files, **kw):
                return TextPipeline(
                    files, Tokenizer(kind="word", vocab_size=128), seq_len=48,
                    batch_size=4, seed=7, epochs=2, **kw
                )

            def collect(p):
                return [
                    tuple(np.array(b[k]).tobytes() for k in ("tokens", "segment_ids", "positions"))
                    for b in p
                ]

            local = collect(pipe(paths))
            assert local, "text pipeline yielded nothing"
            assert collect(pipe(urls, prefetch="0")) == local  # cold remote
            assert collect(pipe(urls, prefetch="2")) == local  # stage + commit
            assert collect(pipe(urls, prefetch="2")) == local  # warm tier
        finally:
            srv.shutdown()
            srv.server_close()


# -- chaos sites ------------------------------------------------------------


@pytest.mark.chaos
class TestStoreChaos:
    def test_read_error_is_retried_and_counted(self, http_corpus):
        """store.read_error: bounded injected request failures are absorbed
        by STORE_READ_RETRY — stream identical, faults counted."""
        _, _, paths, urls = http_corpus
        chaos.install(
            chaos.ChaosPlan(
                seed=5,
                sites={"store.read_error": {"probability": 1.0, "max_count": 2}},
            )
        )
        before = _counter("chaos_fault_store_read_error_total")
        store = HTTPStore(range_bytes=512)
        recs = _records(store.read_records_chunked(urls[0], chunk_records=16))
        assert recs == list(tfrecord.read_records(paths[0]))
        assert _counter("chaos_fault_store_read_error_total") == before + 2

    def test_remote_stall_delays_but_streams(self, http_corpus):
        _, _, paths, urls = http_corpus
        chaos.install(
            chaos.ChaosPlan(
                seed=6,
                sites={
                    "store.remote_stall": {
                        "probability": 1.0, "max_count": 3, "delay_s": 0.01,
                    }
                },
            )
        )
        before = _counter("chaos_fault_store_remote_stall_total")
        store = HTTPStore(range_bytes=512)
        recs = _records(store.read_records_chunked(urls[0], chunk_records=16))
        assert recs == list(tfrecord.read_records(paths[0]))
        assert _counter("chaos_fault_store_remote_stall_total") == before + 3

    def test_prefetch_tear_rejected_by_verify(self, http_corpus, tmp_path):
        """store.prefetch_tear publishes a torn MANIFEST.json; the commit's
        own verify rejects it and the shard is served cold — never garbage."""
        _, _, paths, urls = http_corpus
        chaos.install(
            chaos.ChaosPlan(
                seed=7,
                sites={"store.prefetch_tear": {"probability": 1.0, "max_count": 1}},
            )
        )
        before = _counter("store_prefetch_rejects_total")
        store = HTTPStore(range_bytes=512)
        stager = staging.PrefetchStager(store, root=str(tmp_path / "stage"), depth=1)
        try:
            stager.plan(urls[:1])
            data = stager.fetch(urls[0])  # torn publish -> rejected -> None
            assert _counter("store_prefetch_rejects_total") > before
            if data is not None:  # a post-tear re-stage is allowed, but
                # only with verified bytes
                assert open(data, "rb").read() == open(paths[0], "rb").read()
        finally:
            stager.close()

    def test_torn_stage_never_pollutes_the_stream(self, http_corpus, tmp_path, monkeypatch):
        _, _, paths, urls = http_corpus
        monkeypatch.setenv(staging.DIR_ENV, str(tmp_path / "stage"))
        local = _stream(
            ImagePipeline(paths, _parse, batch_size=8, seed=3, epochs=2, num_threads=2)
        )
        chaos.install(
            chaos.ChaosPlan(
                seed=8,
                sites={"store.prefetch_tear": {"probability": 0.5, "max_count": 2}},
            )
        )
        torn = _stream(
            ImagePipeline(
                urls, _parse, batch_size=8, seed=3, epochs=2, num_threads=2,
                prefetch="2",
            )
        )
        assert torn == local


# -- slab-cache tier hierarchy ---------------------------------------------


class TestSlabCacheTiers:
    def _fill(self, cache, n, base=0):
        for i in range(base, base + n):
            cache.put(i, np.full((4, 4, 1), i % 251, np.uint8), i)
        return cache.commit()

    def test_disk_hit_promotes_into_ram(self, tmp_path):
        from tensorflowonspark_tpu.data.slab_cache import SlabCache

        cache = SlabCache(str(tmp_path), "k", (4, 4, 1), np.uint8, ram_bytes=1 << 20)
        try:
            assert self._fill(cache, 8) == 8
            ram_b = _counter("tier_ram_hits_total")
            disk_b = _counter("tier_disk_hits_total")
            promote_b = _counter("tier_promotions_total")
            pixels, label = cache.lookup(3)
            assert label == 3 and pixels[0, 0, 0] == 3
            assert _counter("tier_disk_hits_total") == disk_b + 1
            assert _counter("tier_promotions_total") == promote_b + 1
            pixels2, label2 = cache.lookup(3)  # now RAM-resident
            assert label2 == 3 and np.array_equal(np.array(pixels), np.array(pixels2))
            assert _counter("tier_ram_hits_total") == ram_b + 1
        finally:
            cache.close()

    def test_ram_bound_demotes_lru_rows(self, tmp_path):
        from tensorflowonspark_tpu.data.slab_cache import SlabCache

        # room for exactly 2 rows of 16 bytes in RAM
        cache = SlabCache(str(tmp_path), "k", (4, 4, 1), np.uint8, ram_bytes=32)
        try:
            self._fill(cache, 6)
            demote_b = _counter("tier_demotions_total")
            for i in range(4):
                cache.lookup(i)
            assert _counter("tier_demotions_total") >= demote_b + 2
            # demoted rows still answer from disk, byte-identical
            pixels, label = cache.lookup(0)
            assert label == 0 and pixels[0, 0, 0] == 0
        finally:
            cache.close()

    def test_disk_capacity_evicts_whole_generations(self, tmp_path):
        from tensorflowonspark_tpu.data.slab_cache import SlabCache

        row = 16  # 4*4*1 uint8
        cache = SlabCache(
            str(tmp_path), "k", (4, 4, 1), np.uint8, max_bytes=10 * row, ram_bytes=0
        )
        try:
            evict_b = _counter("tier_evictions_total")
            self._fill(cache, 8, base=0)  # gen 0: 8 rows
            self._fill(cache, 8, base=100)  # gen 1: 8 rows -> over 10-row cap
            assert _counter("tier_evictions_total") > evict_b
            # the oldest generation went; the newest survives
            assert cache.lookup(0) is None
            assert cache.lookup(100) is not None
        finally:
            cache.close()

    def test_lookup_recency_steers_disk_eviction(self, tmp_path):
        from tensorflowonspark_tpu.data.slab_cache import SlabCache

        row = 16
        cache = SlabCache(
            str(tmp_path), "k", (4, 4, 1), np.uint8, max_bytes=17 * row, ram_bytes=0
        )
        try:
            self._fill(cache, 8, base=0)  # gen 0
            self._fill(cache, 8, base=100)  # gen 1 (16 rows: still under cap)
            assert cache.lookup(0) is not None  # touch gen 0: it is now MRU
            self._fill(cache, 8, base=200)  # gen 2 -> evict LRU = gen 1
            assert cache.lookup(100) is None
            assert cache.lookup(1) is not None
            assert cache.lookup(200) is not None
        finally:
            cache.close()

    def test_reopen_respects_capacity(self, tmp_path):
        from tensorflowonspark_tpu.data.slab_cache import SlabCache

        row = 16
        cache = SlabCache(str(tmp_path), "k", (4, 4, 1), np.uint8, ram_bytes=0)
        try:
            self._fill(cache, 8, base=0)
            self._fill(cache, 8, base=100)
        finally:
            cache.close()
        warm = SlabCache(
            str(tmp_path), "k", (4, 4, 1), np.uint8, max_bytes=10 * row, ram_bytes=0
        )
        try:
            # reopen under a tighter bound: older generations are evicted
            # at load, the newest still serves
            assert warm.lookup(100) is not None
            assert warm.lookup(0) is None
        finally:
            warm.close()


# -- backend fingerprint (bench provenance) ---------------------------------


class TestBackendFingerprint:
    def test_note_backend_records_last_read_source(self, tmp_path, http_corpus):
        _, d, paths, urls = http_corpus
        LocalStore().read_records(paths[0])
        assert base.active_fingerprint() == "local"
        store = HTTPStore(range_bytes=512)
        store.read_records(urls[0])
        assert base.active_fingerprint().startswith("http adapter=IndexHtmlAdapter")
