"""ML pipeline tests, mirroring reference test_pipeline.py: param plumbing
units plus the full fit→export→transform loop with a known-weights regressor
(reference test_pipeline.py:89-172, weights 3.14/1.618)."""

import os

import numpy as np
import pytest

from tensorflowonspark_tpu import pipeline
from tensorflowonspark_tpu.backends.local import LocalSparkContext


class TestNamespace:
    def test_from_dict(self):
        ns = pipeline.Namespace({"a": 1, "b": "x"})
        assert ns.a == 1 and "b" in ns

    def test_from_namespace(self):
        ns = pipeline.Namespace(pipeline.Namespace({"a": 2}))
        assert ns.a == 2

    def test_from_argv(self):
        ns = pipeline.Namespace(["--foo", "1"])
        assert ns.argv == ["--foo", "1"]

    def test_bad_type(self):
        with pytest.raises(TypeError):
            pipeline.Namespace(42)


class TestParams:
    def test_defaults_all_mixins_initialized(self):
        est = pipeline.TFEstimator(lambda a, c: None, {})
        m = est.extractParamMap()
        assert m["batch_size"] == 100
        assert m["cluster_size"] == 1
        assert m["epochs"] == 1
        assert m["master_node"] == "chief"
        assert m["protocol"] == "ici"
        assert m["num_ps"] == 0

    def test_setters_override_args(self):
        est = pipeline.TFEstimator(lambda a, c: None, {"batch_size": 7, "other": "keep"})
        est.setBatchSize(32).setClusterSize(2)
        args = est.merge_args_params()
        assert args.batch_size == 32  # param wins over tf_args
        assert args.cluster_size == 2
        assert args.other == "keep"

    def test_input_mode_tensorflow_rejected(self):
        from tensorflowonspark_tpu.TFCluster import InputMode

        est = pipeline.TFEstimator(lambda a, c: None, {})
        with pytest.raises(ValueError):
            est.setInputMode(InputMode.TENSORFLOW)

    def test_unknown_param_rejected(self):
        est = pipeline.TFEstimator(lambda a, c: None, {})
        with pytest.raises(ValueError):
            est._set(nope=1)

    def test_params_copy_to_model(self):
        est = pipeline.TFEstimator(lambda a, c: None, {})
        est.setBatchSize(5)
        model = pipeline.TFModel({})
        est.copyParamsTo(model)
        assert model.getBatchSize() == 5


def _train_fn(args, ctx):
    """Linear regressor y = w.x + b on the feed; chief exports a bundle."""
    import os as _os

    _os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as _np
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.train import SyncDataParallel, export

    mesh = parallel.local_mesh({"dp": -1})
    strategy = SyncDataParallel(mesh)

    def init(rng):
        return {"w": jnp.zeros((2, 1)), "b": jnp.zeros((1,))}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    opt = optax.adam(0.3)
    state = strategy.create_state(init, opt, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(loss_fn, opt)

    feed = ctx.get_data_feed(train_mode=True)
    while not feed.should_stop():
        batch = feed.next_batch(args.batch_size)
        if not batch:
            break
        x = _np.asarray([row[0] for row in batch], _np.float32)
        y = _np.asarray([row[1] for row in batch], _np.float32).reshape(-1, 1)
        state, metrics = step(state, strategy.shard_batch({"x": x, "y": y}))
        jax.block_until_ready(metrics["loss"])

    if ctx.job_name in ("chief", "master"):
        params = jax.device_get(state.params)

        def predict_builder():
            import jax as _jax

            def predict(params, model_state, arrays):
                x = arrays["x"]
                return {"y_": x @ params["w"] + params["b"]}

            return _jax.jit(predict, static_argnames=())

        export.export_model(args.export_dir, predict_builder, params)


@pytest.fixture(scope="module")
def sc():
    ctx = LocalSparkContext(num_executors=2, task_timeout=300)
    yield ctx
    ctx.stop()


def test_fit_and_transform(sc, tmp_path_factory):
    export_dir = str(tmp_path_factory.mktemp("pipeline") / "bundle")
    rng = np.random.default_rng(0)
    w_true = np.array([[3.14], [1.618]], np.float32)
    x = rng.standard_normal((256, 2)).astype(np.float32)
    y = (x @ w_true).ravel() + 0.5
    df = sc.createDataFrame(
        [(x[i].tolist(), float(y[i])) for i in range(len(x))], ["features", "label"], 4
    )

    est = (
        pipeline.TFEstimator(
            _train_fn, {"export_dir": export_dir}, env={"JAX_PLATFORMS": "cpu"}
        )
        .setInputMapping({"features": "x", "label": "y"})
        .setBatchSize(32)
        .setEpochs(25)
        .setClusterSize(2)
        .setGraceSecs(5)
    )
    model = est.fit(df)
    assert os.path.isdir(export_dir)

    model.setInputMapping({"features": "x"}).setExportDir(export_dir)
    model.setOutputMapping({"y_": "prediction"})
    preds_df = model.transform(sc.createDataFrame([(r.tolist(),) for r in x[:10]], ["features"], 2))
    assert preds_df.columns == ["prediction"]
    preds = [row[0] for row in preds_df.collect()]
    expected = (x[:10] @ w_true).ravel() + 0.5
    # workers train independent replicas here (no grad sync on the 1-host CPU
    # cluster) and only the chief exports, so convergence is approximate: the
    # check is that the exported bundle predicts the right function shape
    np.testing.assert_allclose(np.asarray(preds).ravel(), expected, atol=0.5)


def test_tfrecord_dir_materializes_and_reuses(sc, tmp_path):
    """setTFRecordDir materializes the input DataFrame as shards; a DataFrame
    loaded FROM that directory is not re-written (provenance reuse, reference
    dfutil.py:15-26 loadedDF registry)."""
    import time as _time

    from tensorflowonspark_tpu import dfutil

    tfr_dir = str(tmp_path / "tfr")

    def train_noop(args, ctx):
        feed = ctx.get_data_feed(train_mode=True)
        while not feed.should_stop():
            feed.next_batch(16)

    df = sc.createDataFrame([(i, float(i)) for i in range(32)], ["a", "b"], 2)
    est = (
        pipeline.TFEstimator(train_noop, {}, env={"JAX_PLATFORMS": "cpu"})
        .setInputMapping({"a": "a", "b": "b"})
        .setEpochs(1)
        .setClusterSize(2)
        .setMasterNode(None)
        .setTFRecordDir(tfr_dir)
    )
    est.fit(df)
    shards = dfutil.tfrecord.list_shards(tfr_dir)
    assert shards, "tfrecord_dir was not materialized"
    mtimes = {s: os.path.getmtime(s) for s in shards}

    _time.sleep(0.05)
    loaded = dfutil.loadTFRecords(sc, tfr_dir)
    est.fit(loaded)  # provenance hit: must NOT rewrite the shards
    assert {s: os.path.getmtime(s) for s in dfutil.tfrecord.list_shards(tfr_dir)} == mtimes
