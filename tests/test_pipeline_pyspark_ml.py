"""TFEstimator/TFModel pyspark.ml citizenship, provable WITHOUT pyspark.

`tensorflowonspark_tpu.pipeline` subclasses ``pyspark.ml.Estimator/Model``
when pyspark imports (the reference subclassed them too, pipeline.py:349,433).
This image has no pyspark, so these tests run the import in a SUBPROCESS with
a stub ``pyspark.ml`` package that reproduces the real bases' load-bearing
behavior (pyspark 3.x ``ml/param/__init__.py`` + ``ml/base.py``):

* ``Params.__init__`` sets an INSTANCE attribute ``self._params = None``
  (which would shadow a method of that name — why ours is ``_param_index``),
  and ``_copy_params()`` scans ``dir(cls)`` for pyspark ``Param`` descriptors;
* ``Identifiable.__init__`` sets ``self.uid``;
* ``Estimator``/``Transformer`` are ABCs with abstract ``_fit``/``_transform``
  and concrete ``fit``/``transform`` wrappers;
* ``Pipeline._fit`` isinstance-checks every stage against
  ``Estimator``/``Transformer`` (pipeline.py ``_fit`` — the check the r4
  duck-typed classes failed) and builds a ``PipelineModel``.

The CI pyspark job runs the same shape against REAL pyspark on a real
local-cluster (tests/test_real_pyspark.py::test_ml_pipeline_fit_transform).
"""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

STUB = '''
from abc import ABCMeta, abstractmethod
import uuid


class Param:
    """pyspark.ml.param.Param stand-in (parent/name/doc triple)."""

    def __init__(self, parent, name, doc):
        self.parent = parent
        self.name = name
        self.doc = doc

    def _copy_new_parent(self, parent):
        return Param(parent, self.name, self.doc)


class Identifiable:
    def __init__(self):
        super().__init__()
        self.uid = type(self).__name__ + "_" + uuid.uuid4().hex[:12]

    def __repr__(self):
        return self.uid


class Params(Identifiable, metaclass=ABCMeta):
    def __init__(self):
        super().__init__()
        self._paramMap = {}
        self._defaultParamMap = {}
        self._params = None  # the instance attr that shadows same-named methods
        self._copy_params()

    def _copy_params(self):
        cls = type(self)
        for name in dir(cls):
            attr = getattr(cls, name)
            if isinstance(attr, Param):
                setattr(self, name, attr._copy_new_parent(self))

    @property
    def params(self):
        if self._params is None:
            self._params = [
                getattr(self, x) for x in dir(self)
                if x != "params" and isinstance(getattr(type(self), x, None), Param)
            ]
        return self._params


class Estimator(Params, metaclass=ABCMeta):
    @abstractmethod
    def _fit(self, dataset):
        raise NotImplementedError()

    def fit(self, dataset, params=None):
        return self._fit(dataset)


class Transformer(Params, metaclass=ABCMeta):
    @abstractmethod
    def _transform(self, dataset):
        raise NotImplementedError()

    def transform(self, dataset, params=None):
        return self._transform(dataset)


class Model(Transformer, metaclass=ABCMeta):
    pass


class Pipeline(Params):
    def __init__(self, stages):
        super().__init__()
        self.stages = stages

    def fit(self, dataset):
        return self._fit(dataset)

    def _fit(self, dataset):
        stages = self.stages
        for stage in stages:
            if not (isinstance(stage, Estimator) or isinstance(stage, Transformer)):
                raise TypeError(
                    "Cannot recognize a pipeline stage of type %s." % type(stage)
                )
        indexOfLastEstimator = -1
        for i, stage in enumerate(stages):
            if isinstance(stage, Estimator):
                indexOfLastEstimator = i
        transformers = []
        for i, stage in enumerate(stages):
            if i <= indexOfLastEstimator:
                if isinstance(stage, Transformer):
                    transformers.append(stage)
                    dataset = stage.transform(dataset)
                else:
                    model = stage.fit(dataset)
                    transformers.append(model)
                    if i < indexOfLastEstimator:
                        dataset = model.transform(dataset)
            else:
                transformers.append(stage)
        return PipelineModel(transformers)


class PipelineModel(Model):
    def __init__(self, stages):
        super().__init__()
        self.stages = stages

    def _transform(self, dataset):
        for stage in self.stages:
            dataset = stage.transform(dataset)
        return dataset
'''

DRIVER = '''
import os, sys

os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, {repo!r})
import jax  # sitecustomize may have pinned a TPU platform already

jax.config.update("jax_platforms", "cpu")

import pyspark.ml as ml  # the stub
import numpy as np

from tensorflowonspark_tpu import pipeline


def main():
    # -- citizenship: real subclasses, not duck types -----------------------
    assert issubclass(pipeline.TFEstimator, ml.Estimator), pipeline.TFEstimator.__mro__
    assert issubclass(pipeline.TFModel, ml.Model)
    assert issubclass(pipeline.TFModel, ml.Transformer)

    # -- init chain: pyspark Params/Identifiable ran (uid), and its
    #    `self._params = None` did not break the string-keyed param maps ----
    est = pipeline.TFEstimator(lambda a, c: None, {{"other": "keep"}})
    assert getattr(est, "uid", "").startswith("TFEstimator_"), est.uid
    est.setBatchSize(32).setClusterSize(2)
    assert est.getBatchSize() == 32
    assert est.extractParamMap()["epochs"] == 1  # mixin defaults intact
    args = est.merge_args_params()
    assert args.batch_size == 32 and args.other == "keep"

    # -- Pipeline._fit isinstance gate + fit/transform dispatch -------------
    class RecordingEstimator(pipeline.TFEstimator):
        def _fit(self, dataset):
            model = pipeline.TFModel(self.args)
            self.copyParamsTo(model)
            model.fitted_on = dataset
            return model

    est2 = RecordingEstimator(lambda a, c: None, {{}}).setBatchSize(4)
    pm = ml.Pipeline(stages=[est2]).fit("DATASET")
    assert isinstance(pm, ml.PipelineModel)
    tf_model = pm.stages[0]
    assert isinstance(tf_model, pipeline.TFModel) and isinstance(tf_model, ml.Model)
    assert tf_model.fitted_on == "DATASET"
    assert tf_model.getBatchSize() == 4
    assert getattr(tf_model, "uid", "").startswith("TFModel_")

    # a non-stage object is still rejected by the gate
    try:
        ml.Pipeline(stages=[object()]).fit("DATASET")
    except TypeError:
        pass
    else:
        raise AssertionError("Pipeline accepted a non-Estimator stage")

    # -- TFModel.transform through the REAL _transform path (numpy bundle,
    #    local backend DataFrame) inside the PipelineModel ------------------
    from tensorflowonspark_tpu.backends.local import LocalSparkContext
    from tensorflowonspark_tpu.train import export

    sc = LocalSparkContext(num_executors=1)
    try:
        bundle = os.path.join({tmp!r}, "bundle")

        def predict_builder():
            def predict(params, model_state, arrays):
                return {{"y_": arrays["x"] @ params["w"]}}

            return predict

        export.export_model(bundle, predict_builder,
                            {{"w": np.array([[2.0], [1.0]], np.float32)}})
        tf_model.setInputMapping({{"features": "x"}}).setExportDir(bundle)
        tf_model.setOutputMapping({{"y_": "prediction"}})
        df = sc.createDataFrame([([1.0, 2.0],), ([3.0, 4.0],)], ["features"], 1)
        out = pm.transform(df)  # PipelineModel.transform -> TFModel._transform
        preds = [row[0][0] for row in out.collect()]
        assert preds == [4.0, 10.0], preds
    finally:
        sc.stop()

    print("PYSPARK_ML_CITIZENSHIP_OK")


if __name__ == "__main__":  # LocalSparkContext spawns processes that
    main()                  # re-import this module
'''


def test_pyspark_ml_citizenship_via_stub(tmp_path):
    pkg = tmp_path / "stub" / "pyspark"
    (pkg / "ml").mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    (pkg / "ml" / "__init__.py").write_text(STUB)
    driver = tmp_path / "driver.py"
    driver.write_text(DRIVER.format(repo=REPO, tmp=str(tmp_path)))
    env = dict(os.environ)
    env["PYTHONPATH"] = "{}{}{}".format(
        tmp_path / "stub", os.pathsep, env.get("PYTHONPATH", "")
    )
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, str(driver)], env=env, cwd=REPO,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert "PYSPARK_ML_CITIZENSHIP_OK" in proc.stdout
