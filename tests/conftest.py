"""Test environment bootstrap.

Multi-chip sharding is validated on a virtual 8-device CPU mesh (SURVEY.md §4:
the reference tested "multi-node" on a 2-worker local standalone cluster; our
analogue is multi-process local executors + a virtual device mesh).

The environment may have already imported jax and pointed it at a real TPU
(sitecustomize + ``JAX_PLATFORMS``), so plain env vars are not enough: the
platform is forced back to CPU through the config API, which works as long as
no backend has been initialized yet, and child processes get the env vars.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"  # for forked jax child processes
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# keep XLA's CPU thread usage sane on small CI machines
os.environ.setdefault("XLA_CPU_MULTI_THREAD_EIGEN", "false")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
