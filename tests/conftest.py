"""Test environment bootstrap.

Multi-chip sharding is validated on a virtual 8-device CPU mesh (SURVEY.md §4:
the reference tested "multi-node" on a 2-worker local standalone cluster; our
analogue is multi-process local executors + a virtual device mesh). These env
vars must be set before jax is imported anywhere in the test process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
# keep XLA's CPU thread usage sane on small CI machines
os.environ.setdefault("XLA_CPU_MULTI_THREAD_EIGEN", "false")
