"""Mid-run failure watchdog tests (VERDICT r2 item 7): a crashed child
surfaces on the driver within seconds — via the error queue for a clean
traceback, via heartbeat loss for a SIGKILLed child — not only at shutdown."""

import os
import time

import pytest

from tensorflowonspark_tpu import TFCluster, elastic
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


def fn_sleep_forever(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        feed.next_batch(16)


def fn_crash_after_start(args, ctx):
    if ctx.executor_id == args["victim"]:
        time.sleep(1.0)
        raise RuntimeError("deliberate mid-run crash")
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        feed.next_batch(16)


def fn_sigkill_self(args, ctx):
    import signal

    if ctx.executor_id == args["victim"]:
        time.sleep(1.0)
        os.kill(os.getpid(), signal.SIGKILL)  # no traceback, no child_status
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        feed.next_batch(16)


def _wait_for_error(cluster, within_secs):
    deadline = time.time() + within_secs
    while time.time() < deadline:
        if cluster.tf_status.get("error"):
            return cluster.tf_status["error"]
        time.sleep(0.5)
    return None


@pytest.mark.slow
def test_watchdog_surfaces_crash_mid_run(monkeypatch):
    monkeypatch.setenv("TOS_MONITOR_INTERVAL", "1")
    sc = LocalSparkContext(num_executors=2, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_crash_after_start, {"victim": 1}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        err = _wait_for_error(cluster, within_secs=60)
        assert err is not None and "deliberate mid-run crash" in err
        with pytest.raises(RuntimeError, match="deliberate mid-run crash"):
            cluster.check_errors()
        with pytest.raises(RuntimeError, match="deliberate mid-run crash"):
            cluster.shutdown(timeout=60)
    finally:
        sc.stop()


@pytest.mark.slow
def test_watchdog_detects_silent_child_death(monkeypatch):
    """SIGKILL leaves no traceback and no child_status; the heartbeat gap is
    the only signal."""
    monkeypatch.setenv("TOS_MONITOR_INTERVAL", "1")
    monkeypatch.setenv("TOS_HEARTBEAT_STALE", "6")
    sc = LocalSparkContext(num_executors=2, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_sigkill_self, {"victim": 1}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        err = _wait_for_error(cluster, within_secs=90)
        assert err is not None and "stopped heartbeating" in err
        with pytest.raises(RuntimeError, match="stopped heartbeating"):
            cluster.shutdown(timeout=60)
    finally:
        sc.stop()


@pytest.mark.slow
def test_lease_expiry_names_the_executor_for_the_ledger(monkeypatch):
    """ISSUE 11 satellite: a node that stops renewing its lease surfaces as
    a first-class ``lease_expired`` event carrying the executor id inline —
    so ``FailureLedger.suspects()`` attributes it without a role_map — and
    the registry's lease metrics land in the merged ``cluster.metrics()``."""
    monkeypatch.setenv("TOS_MONITOR_INTERVAL", "1")
    monkeypatch.setenv("TOS_HEARTBEAT_STALE", "6")
    sc = LocalSparkContext(num_executors=2, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_sigkill_self, {"victim": 1}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        err = _wait_for_error(cluster, within_secs=90)
        assert err is not None and "lease expired" in err

        event = elastic.classify_failure(RuntimeError(err))
        assert event.kind == "lease_expired"
        assert event.executor_ids == [1]
        assert event.kind in elastic.LOSS_KINDS

        ledger = elastic.FailureLedger(max_restarts=8, blacklist_after=2)
        ledger.record(event)
        ledger.record(event)
        assert ledger.suspects() == [1]

        snap = cluster.metrics()
        assert snap["counters"]["registry_lease_expirations_total"]["value"] >= 1
        assert snap["gauges"]["registry_epoch"]["value"] >= 1

        with pytest.raises(RuntimeError, match="lease expired"):
            cluster.shutdown(timeout=60)
    finally:
        sc.stop()


def test_healthy_cluster_watchdog_stays_quiet():
    sc = LocalSparkContext(num_executors=1, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_sleep_forever, {}, num_executors=1,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        cluster.train(sc.parallelize(range(64), 2), num_epochs=1, feed_timeout=60)
        assert cluster.tf_status.get("error") is None
        cluster.check_errors()  # no-op on a healthy cluster
        cluster.shutdown(timeout=120)
    finally:
        sc.stop()
