"""TFNode unit tests: hdfs_path matrix + DataFeed against a real local IPC
channel (mirrors reference test/test_TFNode.py)."""

import numpy as np
import pytest

from tensorflowonspark_tpu import TFManager, TFNode
from tensorflowonspark_tpu.marker import EndPartition


def mock_ctx(**kwargs):
    return type("MockContext", (), kwargs)


class TestHdfsPath:
    def test_absolute_uri_passthrough(self):
        ctx = mock_ctx(defaultFS="hdfs://namenode:8020")
        for p in (
            "file:///tmp/x",
            "hdfs://nn/data",
            "viewfs://cluster/data",
            "gs://bucket/data",
            "s3a://bucket/data",
            "abfss://c@acct.dfs.core.windows.net/d",
        ):
            assert TFNode.hdfs_path(ctx, p) == p

    def test_absolute_path_gets_default_fs(self):
        ctx = mock_ctx(defaultFS="hdfs://namenode:8020")
        assert TFNode.hdfs_path(ctx, "/data/mnist") == "hdfs://namenode:8020/data/mnist"

    def test_relative_path_hdfs_user_home(self):
        import getpass

        ctx = mock_ctx(defaultFS="hdfs://namenode:8020")
        assert TFNode.hdfs_path(ctx, "mnist") == "hdfs://namenode:8020/user/{}/mnist".format(
            getpass.getuser()
        )

    def test_relative_path_local_fs_working_dir(self):
        ctx = mock_ctx(defaultFS="file://", working_dir="/home/me")
        assert TFNode.hdfs_path(ctx, "mnist") == "file:///home/me/mnist"


@pytest.fixture
def ipc():
    mgr = TFManager.start(authkey=b"test-key", queues=("input", "output", "error"))
    yield mgr
    mgr.shutdown()


class TestDataFeed:
    def test_next_batch_and_end_of_feed(self, ipc):
        q = ipc.get_queue("input")
        for i in range(10):
            q.put(i)
        q.put(None)  # end-of-feed
        feed = TFNode.DataFeed(ipc)
        batch = feed.next_batch(4)
        assert batch == [0, 1, 2, 3]
        assert not feed.should_stop()
        batch = feed.next_batch(100)
        assert batch == [4, 5, 6, 7, 8, 9]
        assert feed.should_stop()
        q.join()  # every item including the marker was task_done'd

    def test_end_partition_breaks_batch(self, ipc):
        q = ipc.get_queue("input")
        q.put(1)
        q.put(2)
        q.put(EndPartition())
        q.put(3)
        q.put(None)
        feed = TFNode.DataFeed(ipc)
        assert feed.next_batch(10) == [1, 2]
        assert feed.next_batch(10) == [3]
        assert feed.should_stop()

    def test_input_mapping_columns(self, ipc):
        q = ipc.get_queue("input")
        q.put((1.0, 10))
        q.put((2.0, 20))
        q.put(None)
        feed = TFNode.DataFeed(ipc, input_mapping={"colA": "x", "colB": "y"})
        batch = feed.next_batch(2)
        assert batch == {"x": [1.0, 2.0], "y": [10, 20]}

    def test_as_numpy(self, ipc):
        q = ipc.get_queue("input")
        q.put((1.0, 10))
        q.put((2.0, 20))
        q.put(None)
        feed = TFNode.DataFeed(ipc, input_mapping={"a": "x", "b": "y"})
        batch = feed.next_batch(16, as_numpy=True)
        np.testing.assert_array_equal(batch["x"], np.array([1.0, 2.0]))
        np.testing.assert_array_equal(batch["y"], np.array([10, 20]))
        assert feed.should_stop()

    def test_batch_results_roundtrip(self, ipc):
        from tensorflowonspark_tpu.marker import Chunk
        from tensorflowonspark_tpu.shm import ShmChunk

        import numpy as _np

        feed = TFNode.DataFeed(ipc)
        # numpy results ride the shared-memory lane (types round-trip as
        # numpy either way)...
        feed.batch_results(list(_np.asarray([42, 43])))
        out = ipc.get_queue("output")
        chunk = out.get()
        assert isinstance(chunk, ShmChunk)
        assert [int(v) for v in chunk.rows()] == [42, 43]

        # ...while plain-Python rows pickle, so collectors see the exact
        # types the worker produced (json.dumps-able ints, not np.int64)
        feed.batch_results([42, 43])
        chunk = out.get()
        assert isinstance(chunk, Chunk) and chunk.items == [42, 43]

        feed.batch_results(["a", "b"])  # non-numeric -> pickled Chunk
        chunk = out.get()
        assert isinstance(chunk, Chunk) and chunk.items == ["a", "b"]

    def test_terminate_sets_state_and_drains(self, ipc):
        q = ipc.get_queue("input")
        for i in range(5):
            q.put(i)
        feed = TFNode.DataFeed(ipc)
        feed.terminate()
        assert ipc.get("state") == "terminating"
        assert q.qsize() == 0


class TestFeedChunking:
    """Feed-plane chunking: >=chunk_size fewer proxied puts per partition
    (VERDICT round-1 item 4), transparent to DataFeed consumers."""

    def test_train_task_chunks_messages(self, tmp_path, monkeypatch):
        import os
        import secrets

        from tensorflowonspark_tpu import TFManager, TFSparkNode, util
        from tensorflowonspark_tpu.TFNode import DataFeed

        monkeypatch.chdir(tmp_path)
        authkey = secrets.token_bytes(8)
        mgr = TFManager.start(authkey=authkey, queues=("input", "output", "error"), mode="remote")
        try:
            mgr.set("state", "running")
            util.write_executor_state(
                {"executor_id": 7, "cluster_id": 1, "address": mgr.address,
                 "authkey": authkey, "job_name": "worker", "task_index": 0},
                cwd=str(tmp_path),
            )
            TFSparkNode._live_channels[7] = mgr
            task = TFSparkNode._TrainPartitionTask({"server_addr": None}, feed_timeout=30, chunk_size=100)

            import threading

            rows = list(range(1000))
            feeder = threading.Thread(target=task, args=(iter(rows),))
            feeder.start()
            # 1000 rows -> exactly 10 chunked messages on the queue
            import time

            q = mgr.get_queue("input")
            deadline = time.time() + 20
            while q.qsize() < 10 and time.time() < deadline:
                time.sleep(0.05)
            assert q.qsize() == 10, q.qsize()

            feed = DataFeed(mgr)
            got = []
            while len(got) < 1000:
                # batch size divides the feed: next_batch blocks (reference
                # semantics) until a batch fills or a marker arrives
                got.extend(feed.next_batch(50))
            assert got == rows
            feeder.join(timeout=30)
            assert not feeder.is_alive()
        finally:
            TFSparkNode._live_channels.pop(7, None)
            mgr.shutdown()
