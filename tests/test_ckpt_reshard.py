"""Resharded restore: a checkpoint saved on one mesh lands on another.

The elastic-recovery contract: save through the async engine on a pure-DP
1×N mesh, restore onto a 2×4 dp/fsdp mesh with a different partition spec
— every value bitwise-equal after gather, placement derived by the NEW
strategy. Runs on the 8 host-platform CPU devices conftest forces."""

import os

import numpy as np
import pytest

from tensorflowonspark_tpu import ckpt
from tensorflowonspark_tpu.ckpt.reshard import reshard_restore, state_shardings


def _specs(tree):
    import jax

    return [leaf.sharding.spec for leaf in jax.tree.leaves(tree)]


class TestReshardTrainState:
    @pytest.fixture
    def saved_on_dp(self, tmp_path):
        """A TrainState trained a step on the full-DP mesh, committed by
        the async engine; returns (path, host copy of the saved state)."""
        import jax
        import optax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import mnist
        from tensorflowonspark_tpu.train import SyncDataParallel

        strategy = SyncDataParallel(parallel.local_mesh({"dp": -1}))
        model = mnist.create_model("mlp", hidden=8)
        optimizer = optax.sgd(0.1)
        state = strategy.create_state(
            mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0)
        )
        step = strategy.compile_train_step(
            mnist.make_loss_fn(model), optimizer, has_aux=True, donate=False
        )
        rng = np.random.default_rng(3)
        batch = strategy.shard_batch(
            {
                "image": rng.standard_normal((16, 28, 28)).astype(np.float32),
                "label": rng.integers(0, 10, 16),
            }
        )
        state, _ = step(state, batch)  # non-trivial opt state + step count
        with ckpt.AsyncCheckpointEngine(str(tmp_path)) as eng:
            eng.save(state, 1)
            assert eng.drain(timeout=120)
        path = os.path.join(str(tmp_path), "ckpt_1")
        assert ckpt.verify(path) == (True, "verified")
        return path, jax.device_get(state)

    def test_restore_onto_fsdp_mesh_bitwise_equal(self, saved_on_dp):
        import jax
        import optax
        from jax.sharding import PartitionSpec

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import mnist
        from tensorflowonspark_tpu.train import SyncDataParallel

        path, host = saved_on_dp
        # the NEW world: 2-way dp × 4-way fsdp, weights actually sharded
        target_strategy = SyncDataParallel(
            parallel.local_mesh({"dp": 2, "fsdp": 4}), fsdp=True,
            min_weight_size=1,
        )
        model = mnist.create_model("mlp", hidden=8)
        fresh = target_strategy.create_state(
            mnist.make_init_fn(model), optax.sgd(0.1), jax.random.PRNGKey(1)
        )

        restored = reshard_restore(path, strategy=target_strategy, target=fresh)

        # placement is the new strategy's: some param dim rides the fsdp axis
        specs = _specs(restored.params)
        assert any("fsdp" in (ax or ()) for spec in specs for ax in spec), specs
        assert restored.params["Dense_0"]["kernel"].sharding.mesh.shape == {
            "dp": 2, "fsdp": 4,
        }
        # resharding moves bytes, never recomputes: bitwise equal after gather
        for saved, back in zip(
            jax.tree.leaves(host.params), jax.tree.leaves(jax.device_get(restored.params))
        ):
            np.testing.assert_array_equal(saved, back)
        for saved, back in zip(
            jax.tree.leaves(host.opt_state),
            jax.tree.leaves(jax.device_get(restored.opt_state)),
        ):
            np.testing.assert_array_equal(saved, back)
        assert int(jax.device_get(restored.step)) == 1

    def test_state_shardings_match_create_state_placement(self, saved_on_dp):
        import jax
        import optax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import mnist
        from tensorflowonspark_tpu.train import SyncDataParallel

        _, host = saved_on_dp
        target_strategy = SyncDataParallel(
            parallel.local_mesh({"dp": 2, "fsdp": 4}), fsdp=True,
            min_weight_size=1,
        )
        model = mnist.create_model("mlp", hidden=8)
        fresh = target_strategy.create_state(
            mnist.make_init_fn(model), optax.sgd(0.1), jax.random.PRNGKey(1)
        )
        derived = state_shardings(target_strategy, host)
        # the derived placement IS what create_state produced on the new mesh
        assert jax.tree.map(lambda s: s.spec, derived.params) == jax.tree.map(
            lambda a: a.sharding.spec, fresh.params
        )
        assert jax.tree.map(lambda s: s.spec, derived.opt_state) == jax.tree.map(
            lambda a: a.sharding.spec, fresh.opt_state
        )


class TestHybridRoundTrip:
    """Mesh-shape round trips: dp -> dp x fsdp -> dp, parameter-exact, plus
    the elastic-ladder shrink landing on a smaller hybrid mesh. The proof
    that a checkpoint is a mesh-independent set of bytes."""

    def _make_state(self, strategy):
        import jax
        import optax

        from tensorflowonspark_tpu.models import mnist

        model = mnist.create_model("mlp", hidden=8)
        optimizer = optax.sgd(0.1)
        state = strategy.create_state(
            mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0)
        )
        step = strategy.compile_train_step(
            mnist.make_loss_fn(model), optimizer, has_aux=True, donate=False
        )
        rng = np.random.default_rng(7)
        batch = strategy.shard_batch(
            {
                "image": rng.standard_normal((8, 28, 28)).astype(np.float32),
                "label": rng.integers(0, 10, 8),
            }
        )
        state, _ = step(state, batch)
        return state

    def _save(self, state, root, step_no):
        with ckpt.AsyncCheckpointEngine(str(root)) as eng:
            eng.save(state, step_no)
            assert eng.drain(timeout=120)
        path = os.path.join(str(root), "ckpt_{}".format(step_no))
        assert ckpt.verify(path) == (True, "verified")
        return path

    def _assert_bitwise(self, host_state, restored):
        import jax

        for saved, back in zip(
            jax.tree.leaves(host_state.params),
            jax.tree.leaves(jax.device_get(restored.params)),
        ):
            np.testing.assert_array_equal(saved, back)
        for saved, back in zip(
            jax.tree.leaves(host_state.opt_state),
            jax.tree.leaves(jax.device_get(restored.opt_state)),
        ):
            np.testing.assert_array_equal(saved, back)

    def test_round_trip_dp_to_hybrid_and_back(self, tmp_path):
        import jax
        import optax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import mnist
        from tensorflowonspark_tpu.train import SyncDataParallel

        devices = jax.local_devices()
        dp4 = SyncDataParallel(parallel.build_mesh({"dp": 4}, devices=devices[:4]))
        state = self._make_state(dp4)
        host = jax.device_get(state)
        path = self._save(state, tmp_path / "a", 1)

        # leg 1: land the dp-mesh checkpoint on a 2x2 hybrid, params sharded
        hybrid = SyncDataParallel(
            parallel.build_mesh({"dp": 2, "fsdp": 2}, devices=devices[:4]),
            fsdp=True, min_weight_size=1,
        )
        model = mnist.create_model("mlp", hidden=8)
        fresh = hybrid.create_state(
            mnist.make_init_fn(model), optax.sgd(0.1), jax.random.PRNGKey(9)
        )
        on_hybrid = reshard_restore(path, strategy=hybrid, target=fresh)
        self._assert_bitwise(host, on_hybrid)
        specs = _specs(on_hybrid.params)
        assert any("fsdp" in (ax or ()) for spec in specs for ax in spec), specs

        # leg 2: save FROM the hybrid placement, land back on the dp mesh —
        # the bytes never change, only the placement does
        path2 = self._save(on_hybrid, tmp_path / "b", 2)
        fresh2 = dp4.create_state(
            mnist.make_init_fn(model), optax.sgd(0.1), jax.random.PRNGKey(11)
        )
        back_on_dp = reshard_restore(path2, strategy=dp4, target=fresh2)
        self._assert_bitwise(host, back_on_dp)
        for spec in _specs(back_on_dp.params):
            assert all("fsdp" not in (ax or ()) for ax in spec), spec

    def test_elastic_shrink_onto_smaller_hybrid_mesh(self, tmp_path):
        import jax
        import optax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import mnist
        from tensorflowonspark_tpu.train import SyncDataParallel

        devices = jax.local_devices()
        # the full world: 2-way dp x 4-way fsdp over all 8 devices
        full = SyncDataParallel(
            parallel.build_mesh({"dp": 2, "fsdp": 4}, devices=devices),
            fsdp=True, min_weight_size=1,
        )
        state = self._make_state(full)
        host = jax.device_get(state)
        path = self._save(state, tmp_path, 3)

        # the shrink-to-fit world after losing half the hosts: 2x2 over the
        # surviving 4 devices (the recovery ladder's resharded resume)
        shrunk = SyncDataParallel(
            parallel.build_mesh({"dp": 2, "fsdp": 2}, devices=devices[:4]),
            fsdp=True, min_weight_size=1,
        )
        model = mnist.create_model("mlp", hidden=8)
        fresh = shrunk.create_state(
            mnist.make_init_fn(model), optax.sgd(0.1), jax.random.PRNGKey(5)
        )
        restored = reshard_restore(path, strategy=shrunk, target=fresh)
        self._assert_bitwise(host, restored)
        k = restored.params["Dense_0"]["kernel"]
        assert k.sharding.mesh.shape == {"dp": 2, "fsdp": 2}
        assert len(k.sharding.device_set) <= 4


class TestReshardBarePytree:
    @pytest.fixture
    def saved_dict(self, tmp_path):
        tree = {"step": np.int64(4), "w": np.arange(32, dtype=np.float32)}
        with ckpt.AsyncCheckpointEngine(str(tmp_path)) as eng:
            eng.save(tree, 4)
            assert eng.drain(timeout=120)
        return os.path.join(str(tmp_path), "ckpt_4"), tree

    def test_explicit_shardings_override(self, saved_dict):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from tensorflowonspark_tpu import parallel

        path, tree = saved_dict
        mesh = parallel.local_mesh({"dp": -1})
        shardings = {
            "step": NamedSharding(mesh, PartitionSpec()),
            "w": NamedSharding(mesh, PartitionSpec("dp")),
        }
        placed = reshard_restore(path, shardings=shardings)
        assert placed["w"].sharding.spec == PartitionSpec("dp")
        np.testing.assert_array_equal(jax.device_get(placed["w"]), tree["w"])
        assert int(jax.device_get(placed["step"])) == 4

    def test_strategy_replicates_bare_pytree(self, saved_dict):
        import jax
        from jax.sharding import PartitionSpec

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.train import SyncDataParallel

        path, tree = saved_dict
        strategy = SyncDataParallel(parallel.local_mesh({"dp": 2, "fsdp": 4}))
        placed = reshard_restore(path, strategy=strategy)
        assert placed["w"].sharding.spec == PartitionSpec()
        np.testing.assert_array_equal(jax.device_get(placed["w"]), tree["w"])

    def test_requires_strategy_or_shardings(self, saved_dict):
        path, _ = saved_dict
        with pytest.raises(ValueError, match="strategy or explicit shardings"):
            reshard_restore(path)
