"""Resharded restore: a checkpoint saved on one mesh lands on another.

The elastic-recovery contract: save through the async engine on a pure-DP
1×N mesh, restore onto a 2×4 dp/fsdp mesh with a different partition spec
— every value bitwise-equal after gather, placement derived by the NEW
strategy. Runs on the 8 host-platform CPU devices conftest forces."""

import os

import numpy as np
import pytest

from tensorflowonspark_tpu import ckpt
from tensorflowonspark_tpu.ckpt.reshard import reshard_restore, state_shardings


def _specs(tree):
    import jax

    return [leaf.sharding.spec for leaf in jax.tree.leaves(tree)]


class TestReshardTrainState:
    @pytest.fixture
    def saved_on_dp(self, tmp_path):
        """A TrainState trained a step on the full-DP mesh, committed by
        the async engine; returns (path, host copy of the saved state)."""
        import jax
        import optax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import mnist
        from tensorflowonspark_tpu.train import SyncDataParallel

        strategy = SyncDataParallel(parallel.local_mesh({"dp": -1}))
        model = mnist.create_model("mlp", hidden=8)
        optimizer = optax.sgd(0.1)
        state = strategy.create_state(
            mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0)
        )
        step = strategy.compile_train_step(
            mnist.make_loss_fn(model), optimizer, has_aux=True, donate=False
        )
        rng = np.random.default_rng(3)
        batch = strategy.shard_batch(
            {
                "image": rng.standard_normal((16, 28, 28)).astype(np.float32),
                "label": rng.integers(0, 10, 16),
            }
        )
        state, _ = step(state, batch)  # non-trivial opt state + step count
        with ckpt.AsyncCheckpointEngine(str(tmp_path)) as eng:
            eng.save(state, 1)
            assert eng.drain(timeout=120)
        path = os.path.join(str(tmp_path), "ckpt_1")
        assert ckpt.verify(path) == (True, "verified")
        return path, jax.device_get(state)

    def test_restore_onto_fsdp_mesh_bitwise_equal(self, saved_on_dp):
        import jax
        import optax
        from jax.sharding import PartitionSpec

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import mnist
        from tensorflowonspark_tpu.train import SyncDataParallel

        path, host = saved_on_dp
        # the NEW world: 2-way dp × 4-way fsdp, weights actually sharded
        target_strategy = SyncDataParallel(
            parallel.local_mesh({"dp": 2, "fsdp": 4}), fsdp=True,
            min_weight_size=1,
        )
        model = mnist.create_model("mlp", hidden=8)
        fresh = target_strategy.create_state(
            mnist.make_init_fn(model), optax.sgd(0.1), jax.random.PRNGKey(1)
        )

        restored = reshard_restore(path, strategy=target_strategy, target=fresh)

        # placement is the new strategy's: some param dim rides the fsdp axis
        specs = _specs(restored.params)
        assert any("fsdp" in (ax or ()) for spec in specs for ax in spec), specs
        assert restored.params["Dense_0"]["kernel"].sharding.mesh.shape == {
            "dp": 2, "fsdp": 4,
        }
        # resharding moves bytes, never recomputes: bitwise equal after gather
        for saved, back in zip(
            jax.tree.leaves(host.params), jax.tree.leaves(jax.device_get(restored.params))
        ):
            np.testing.assert_array_equal(saved, back)
        for saved, back in zip(
            jax.tree.leaves(host.opt_state),
            jax.tree.leaves(jax.device_get(restored.opt_state)),
        ):
            np.testing.assert_array_equal(saved, back)
        assert int(jax.device_get(restored.step)) == 1

    def test_state_shardings_match_create_state_placement(self, saved_on_dp):
        import jax
        import optax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import mnist
        from tensorflowonspark_tpu.train import SyncDataParallel

        _, host = saved_on_dp
        target_strategy = SyncDataParallel(
            parallel.local_mesh({"dp": 2, "fsdp": 4}), fsdp=True,
            min_weight_size=1,
        )
        model = mnist.create_model("mlp", hidden=8)
        fresh = target_strategy.create_state(
            mnist.make_init_fn(model), optax.sgd(0.1), jax.random.PRNGKey(1)
        )
        derived = state_shardings(target_strategy, host)
        # the derived placement IS what create_state produced on the new mesh
        assert jax.tree.map(lambda s: s.spec, derived.params) == jax.tree.map(
            lambda a: a.sharding.spec, fresh.params
        )
        assert jax.tree.map(lambda s: s.spec, derived.opt_state) == jax.tree.map(
            lambda a: a.sharding.spec, fresh.opt_state
        )


class TestReshardBarePytree:
    @pytest.fixture
    def saved_dict(self, tmp_path):
        tree = {"step": np.int64(4), "w": np.arange(32, dtype=np.float32)}
        with ckpt.AsyncCheckpointEngine(str(tmp_path)) as eng:
            eng.save(tree, 4)
            assert eng.drain(timeout=120)
        return os.path.join(str(tmp_path), "ckpt_4"), tree

    def test_explicit_shardings_override(self, saved_dict):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec

        from tensorflowonspark_tpu import parallel

        path, tree = saved_dict
        mesh = parallel.local_mesh({"dp": -1})
        shardings = {
            "step": NamedSharding(mesh, PartitionSpec()),
            "w": NamedSharding(mesh, PartitionSpec("dp")),
        }
        placed = reshard_restore(path, shardings=shardings)
        assert placed["w"].sharding.spec == PartitionSpec("dp")
        np.testing.assert_array_equal(jax.device_get(placed["w"]), tree["w"])
        assert int(jax.device_get(placed["step"])) == 4

    def test_strategy_replicates_bare_pytree(self, saved_dict):
        import jax
        from jax.sharding import PartitionSpec

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.train import SyncDataParallel

        path, tree = saved_dict
        strategy = SyncDataParallel(parallel.local_mesh({"dp": 2, "fsdp": 4}))
        placed = reshard_restore(path, strategy=strategy)
        assert placed["w"].sharding.spec == PartitionSpec()
        np.testing.assert_array_equal(jax.device_get(placed["w"]), tree["w"])

    def test_requires_strategy_or_shardings(self, saved_dict):
        path, _ = saved_dict
        with pytest.raises(ValueError, match="strategy or explicit shardings"):
            reshard_restore(path)
