"""Model-zoo tests: shapes, one real train step per family, sharded flagship."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from tensorflowonspark_tpu import parallel
from tensorflowonspark_tpu.models import get_model, mnist, resnet, segmentation, transformer
from tensorflowonspark_tpu.train import SyncDataParallel


def test_registry():
    assert get_model("mnist_mlp").hidden == 512
    with pytest.raises(KeyError):
        get_model("nope")


class TestMnist:
    def test_train_step_improves(self):
        mesh = parallel.build_mesh({"dp": 8})
        strategy = SyncDataParallel(mesh)
        model = mnist.create_model("mlp", hidden=32)
        opt = optax.adam(1e-3)
        state = strategy.create_state(mnist.make_init_fn(model), opt, jax.random.PRNGKey(0))
        step = strategy.compile_train_step(mnist.make_loss_fn(model), opt, has_aux=True)
        rng = np.random.default_rng(0)
        batch = strategy.shard_batch(
            {
                "image": rng.standard_normal((32, 28, 28)).astype(np.float32),
                "label": rng.integers(0, 10, 32),
            }
        )
        state, m0 = step(state, batch)
        jax.block_until_ready(m0["loss"])
        for _ in range(20):
            state, m = step(state, batch)
            jax.block_until_ready(m["loss"])
        assert float(m["loss"]) < float(m0["loss"])
        assert "accuracy" in m

    def test_predict_shape(self):
        model = mnist.create_model("cnn")
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 28, 28)))["params"]
        preds = mnist.make_predict_fn(model)(params, {"image": jnp.zeros((4, 28, 28))})
        assert preds.shape == (4,)


class TestResNet:
    def test_resnet56_train_step_with_batch_stats(self):
        mesh = parallel.build_mesh({"dp": 8})
        strategy = SyncDataParallel(mesh)
        model = resnet.resnet56(num_classes=10)
        opt = optax.sgd(0.1, momentum=0.9)
        state = strategy.create_state(
            resnet.make_init_fn(model, image_size=32), opt, jax.random.PRNGKey(0)
        )
        assert "batch_stats" in state.model_state
        step = strategy.compile_train_step(
            resnet.make_loss_fn(model, weight_decay=1e-4), opt, mutable=True
        )
        rng = np.random.default_rng(0)
        batch = strategy.shard_batch(
            {
                "image": rng.standard_normal((16, 32, 32, 3)).astype(np.float32),
                "label": rng.integers(0, 10, 16),
            }
        )
        before = np.asarray(
            jax.device_get(
                jax.tree.leaves(state.model_state["batch_stats"])[0]
            )
        ).copy()
        state, metrics = step(state, batch)
        jax.block_until_ready(metrics["loss"])
        assert np.isfinite(float(metrics["loss"]))
        after = np.asarray(
            jax.device_get(jax.tree.leaves(state.model_state["batch_stats"])[0])
        )
        assert not np.array_equal(before, after), "batch_stats must update"

    def test_resnet50_forward_shape(self):
        model = resnet.resnet50(num_classes=1000)
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)), train=False)
        logits = model.apply(variables, jnp.zeros((2, 64, 64, 3)), train=False)
        assert logits.shape == (2, 1000)

    def test_resnet50_s2d_stem_matches_shapes_and_trains(self):
        """The space-to-depth stem (docs/perf.md r4 breakdown) halves the
        spatial dims exactly like the 7x7/2 stem, so every downstream stage
        sees identical shapes; one train step must run and mutate stats."""
        model = resnet.resnet50(num_classes=1000, stem="imagenet_s2d")
        variables = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 64, 64, 3)), train=False)
        # stem kernel consumes the 2x2-block channels: (4, 4, 12, 64)
        assert variables["params"]["stem"]["kernel"].shape == (4, 4, 12, 64)
        logits = model.apply(variables, jnp.zeros((2, 64, 64, 3)), train=False)
        assert logits.shape == (2, 1000)
        logits, new_state = model.apply(
            variables, jnp.zeros((2, 64, 64, 3)), train=True, mutable=["batch_stats"]
        )
        assert logits.shape == (2, 1000) and "batch_stats" in new_state


class TestSegmentation:
    def test_unet_train_step(self):
        mesh = parallel.build_mesh({"dp": 8})
        strategy = SyncDataParallel(mesh)
        model = segmentation.create_model(num_classes=3, base_filters=8, depth=2)
        opt = optax.adam(1e-3)
        state = strategy.create_state(
            segmentation.make_init_fn(model, image_size=32), opt, jax.random.PRNGKey(0)
        )
        step = strategy.compile_train_step(
            segmentation.make_loss_fn(model), opt, has_aux=True
        )
        rng = np.random.default_rng(0)
        batch = strategy.shard_batch(
            {
                "image": rng.standard_normal((8, 32, 32, 3)).astype(np.float32),
                "mask": rng.integers(0, 3, (8, 32, 32)),
            }
        )
        state, metrics = step(state, batch)
        jax.block_until_ready(metrics["loss"])
        assert np.isfinite(float(metrics["loss"]))
        preds = segmentation.make_predict_fn(model)(state.params, jax.device_get(batch))
        assert preds.shape == (8, 32, 32)


class TestTransformer:
    def test_forward_and_loss(self):
        model = transformer.create_model(
            vocab_size=100, d_model=32, n_layers=2, n_heads=4, d_ff=64
        )
        tokens = jnp.asarray(np.random.default_rng(0).integers(0, 100, (2, 17)))
        variables = model.init(jax.random.PRNGKey(0), tokens)
        logits = model.apply(variables, tokens)
        assert logits.shape == (2, 17, 100)
        loss, aux = transformer.make_loss_fn(model)(variables["params"], {"tokens": tokens})
        assert np.isfinite(float(loss))
        assert float(aux["perplexity"]) > 1

    def test_causality(self):
        """Changing a future token must not affect earlier logits."""
        model = transformer.create_model(
            vocab_size=50, d_model=16, n_layers=1, n_heads=2, d_ff=32
        )
        rng = np.random.default_rng(1)
        tokens = jnp.asarray(rng.integers(0, 50, (1, 12)))
        variables = model.init(jax.random.PRNGKey(0), tokens)
        logits_a = model.apply(variables, tokens)
        tokens_b = tokens.at[0, -1].set((int(tokens[0, -1]) + 1) % 50)
        logits_b = model.apply(variables, tokens_b)
        np.testing.assert_allclose(
            np.asarray(logits_a[0, :-1]), np.asarray(logits_b[0, :-1]), atol=1e-5
        )

    def test_sharded_train_with_ring_attention(self):
        """Full train step over a dp×sp mesh: ring attention inside the model,
        gradients through ppermute, params updated."""
        mesh = parallel.build_mesh({"dp": 2, "sp": 4})
        strategy = SyncDataParallel(mesh)
        model = transformer.create_model(
            mesh=mesh, vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64
        )
        opt = optax.adam(1e-2)
        state = strategy.create_state(
            transformer.make_init_fn(model, sample_len=8), opt, jax.random.PRNGKey(0)
        )
        step = strategy.compile_train_step(
            transformer.make_loss_fn(model), opt, has_aux=True
        )
        rng = np.random.default_rng(0)
        # tokens [B, 33]: model sees 32 = 4 sp shards of 8
        batch = strategy.shard_batch({"tokens": rng.integers(0, 64, (4, 33))})
        state, m0 = step(state, batch)
        jax.block_until_ready(m0["loss"])
        for _ in range(10):
            state, m = step(state, batch)
            jax.block_until_ready(m["loss"])
        assert float(m["loss"]) < float(m0["loss"])

    def test_ring_matches_unsharded_model(self):
        """Same params, same tokens: sp-sharded ring-attention forward must
        equal the single-device forward."""
        cfg = dict(vocab_size=64, d_model=32, n_layers=2, n_heads=4, d_ff=64)
        mesh = parallel.build_mesh({"sp": 8})
        model_ring = transformer.create_model(mesh=mesh, **cfg)
        model_plain = transformer.create_model(**cfg)
        tokens = jnp.asarray(np.random.default_rng(2).integers(0, 64, (2, 32)))
        variables = model_plain.init(jax.random.PRNGKey(0), tokens)
        out_plain = model_plain.apply(variables, tokens)
        out_ring = model_ring.apply(variables, tokens)
        np.testing.assert_allclose(
            np.asarray(out_plain), np.asarray(out_ring), atol=3e-5
        )

    def test_flash_attention_impl_matches_plain(self):
        """attention='flash' (interpret on CPU) must match the plain path."""
        cfg = dict(vocab_size=64, d_model=32, n_layers=1, n_heads=4, d_ff=64)
        model_flash = transformer.create_model(attention="flash", **cfg)
        model_plain = transformer.create_model(attention="plain", **cfg)
        tokens = jnp.asarray(np.random.default_rng(3).integers(0, 64, (2, 128)))
        variables = model_plain.init(jax.random.PRNGKey(0), tokens)
        out_plain = model_plain.apply(variables, tokens)
        out_flash = model_flash.apply(variables, tokens)
        np.testing.assert_allclose(
            np.asarray(out_plain), np.asarray(out_flash), atol=3e-5
        )

    def test_flash_pads_odd_training_lengths(self):
        """make_loss_fn slices tokens[:, :-1] producing odd seq lengths; the
        flash path must pad-and-slice, matching plain exactly (causality)."""
        cfg = dict(vocab_size=64, d_model=32, n_layers=1, n_heads=4, d_ff=64)
        model_flash = transformer.create_model(attention="flash", **cfg)
        model_plain = transformer.create_model(attention="plain", **cfg)
        tokens = jnp.asarray(np.random.default_rng(5).integers(0, 64, (1, 515)))
        variables = model_plain.init(jax.random.PRNGKey(0), tokens)
        np.testing.assert_allclose(
            np.asarray(model_plain.apply(variables, tokens)),
            np.asarray(model_flash.apply(variables, tokens)),
            atol=3e-5,
        )

    def test_unknown_attention_impl_raises(self):
        model = transformer.create_model(
            attention="flsh", vocab_size=16, d_model=8, n_layers=1, n_heads=2, d_ff=16
        )
        with pytest.raises(ValueError, match="unknown attention impl"):
            model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))

    def test_forced_plain_on_sp_mesh(self):
        """attention='plain' must win over the mesh's sp axis (debug escape)."""
        mesh = parallel.build_mesh({"sp": 8})
        cfg = dict(vocab_size=32, d_model=16, n_layers=1, n_heads=2, d_ff=32)
        model = transformer.create_model(mesh=mesh, attention="plain", **cfg)
        tokens = jnp.asarray(np.random.default_rng(6).integers(0, 32, (1, 16)))
        variables = model.init(jax.random.PRNGKey(0), tokens)
        out = model.apply(variables, tokens)
        assert np.isfinite(np.asarray(out)).all()

    def test_param_specs_tp_rules(self):
        mesh = parallel.build_mesh({"fsdp": 2, "tp": 4})
        model = transformer.create_model(
            vocab_size=64, d_model=32, n_layers=1, n_heads=4, d_ff=64
        )
        params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
        specs = transformer.param_specs(params, mesh)
        from jax.sharding import PartitionSpec as P

        assert specs["layer_0"]["attn"]["q"]["kernel"] == P("fsdp", "tp", None)
        assert specs["layer_0"]["mlp"]["wo"]["kernel"] == P("tp", "fsdp")
        # vocab-parallel embedding: d_model stays replicated so the gather
        # output lands directly in the activations' layout (no SPMD remat)
        assert specs["embed"]["embedding"] == P("fsdp", None)


class TestMoE:
    """Expert parallelism (SURVEY §2.7 row EP; absent from the reference):
    switch-routed MoE MLP with dense dispatch, experts sharded over ``ep``."""

    def test_single_expert_equals_dense_ffn(self):
        import flax.linen as nn
        import numpy as np

        cfg = transformer.TransformerConfig(
            vocab_size=64, d_model=16, n_layers=1, n_heads=2, d_ff=32,
            moe_experts=1, moe_capacity_factor=4.0,
        )
        m = transformer.MoeMlp(cfg)
        x = jnp.asarray(np.random.default_rng(0).standard_normal((2, 8, 16)), jnp.float32)
        variables = m.init(jax.random.PRNGKey(0), x)
        # init also ran sow: pass params only so "losses" starts fresh
        y, mods = m.apply({"params": variables["params"]}, x, mutable=["losses"])
        wi = variables["params"]["wi"][0]
        wo = variables["params"]["wo"][0]
        dense = nn.gelu(x.reshape(-1, 16) @ wi) @ wo
        np.testing.assert_allclose(
            np.asarray(y).reshape(-1, 16), np.asarray(dense), rtol=2e-5, atol=2e-5
        )
        # one expert takes every token: aux loss is exactly E * 1 * 1 = 1
        (aux,) = jax.tree.leaves(mods["losses"])
        assert float(aux) == pytest.approx(1.0)

    def test_capacity_drops_overflow_tokens(self):
        import numpy as np

        cfg = transformer.TransformerConfig(
            vocab_size=64, d_model=8, n_layers=1, n_heads=2, d_ff=16,
            moe_experts=2, moe_capacity_factor=0.25,
        )
        m = transformer.MoeMlp(cfg)
        x = jnp.asarray(np.random.default_rng(1).standard_normal((1, 16, 8)), jnp.float32)
        variables = m.init(jax.random.PRNGKey(0), x)
        y, _ = m.apply(variables, x, mutable=["losses"])
        # capacity = 0.25 * 16 / 2 = 2 per expert -> at most 4 tokens routed;
        # dropped tokens contribute exactly zero output
        nonzero_rows = np.count_nonzero(np.abs(np.asarray(y).reshape(16, 8)).sum(-1) > 1e-7)
        assert nonzero_rows <= 4

    def test_ep_sharded_train_step(self):
        import numpy as np
        import optax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.train import SyncDataParallel

        mesh = parallel.build_mesh({"dp": 2, "ep": 4})
        model = transformer.create_model(
            mesh=mesh, vocab_size=128, d_model=32, n_layers=2, n_heads=4,
            d_ff=64, max_seq_len=32, moe_experts=4,
        )
        strategy = SyncDataParallel(mesh, param_spec_fn=transformer.param_specs)
        opt = optax.adamw(1e-3)
        state = strategy.create_state(
            transformer.make_init_fn(model, sample_len=8), opt, jax.random.PRNGKey(0)
        )
        # expert weights actually sharded over ep
        specs = transformer.param_specs(
            jax.eval_shape(transformer.make_init_fn(model, 8), jax.random.PRNGKey(0))["params"],
            mesh,
        )
        from jax.sharding import PartitionSpec as P

        assert specs["layer_0"]["moe"]["wi"] == P("ep", None, None)
        step = strategy.compile_train_step(
            transformer.make_loss_fn(model), opt, has_aux=True
        )
        tokens = np.random.default_rng(0).integers(0, 128, (4, 17))
        state, metrics = step(state, strategy.shard_batch({"tokens": tokens}))
        jax.block_until_ready(metrics["loss"])
        assert np.isfinite(float(metrics["loss"]))
        assert "moe_aux" in metrics and np.isfinite(float(metrics["moe_aux"]))
        # aux loss >= 1 by Cauchy-Schwarz (perfectly balanced -> exactly 1)
        assert float(metrics["moe_aux"]) >= 0.99
