"""Multiprocess decode plane (data/decode_plane.py): slab segments, the
slot lease protocol (fills, worker-side failures, respawn with no lost or
duplicated slots), pool resize/teardown hygiene, the worker-count
autotuner's decision rule, and the GIL-release proof (``perf_smoke``:
process pool beats a 1-thread pool on a multi-core box)."""

import glob
import os
import signal
import time

import numpy as np
import pytest

from tensorflowonspark_tpu import obs, shm
from tensorflowonspark_tpu.data import decode_plane
from tensorflowonspark_tpu.data.decode_plane import (
    DecodeAutotuner,
    DecodePlane,
    DecodeWorkerError,
)

pytestmark = pytest.mark.skipif(
    not decode_plane.available(), reason="no fork/shared_memory on this platform"
)


def _parse(rec):
    # module-level: fork-inheritable, deterministic per record bytes
    v = int(rec)
    if v < 0:
        raise ValueError("negative record {}".format(v))
    return np.full((4, 4, 1), v % 251, np.uint8), v


def _slow_parse(rec):
    time.sleep(0.05)
    return _parse(rec)


def _gil_bound_parse(rec):
    # pure-Python arithmetic: holds the GIL the whole time, unlike PIL's
    # C decode loops — a thread pool gains nothing here, processes do
    v = int(rec)
    acc = 0
    for i in range(120_000):
        acc = (acc + i * v) % 1000003
    return np.full((4, 4, 1), (v + acc * 0) % 251, np.uint8), v


def _counter(name):
    return obs.snapshot()["counters"].get(name, {}).get("value", 0)


def _slab_files():
    return glob.glob("/dev/shm/tosslab_*")


@pytest.fixture
def plane():
    p = DecodePlane(_parse, workers=2)
    yield p
    p.close()


def _fill(plane, batch_size=8, records=None):
    images, labels = plane.new_slab(batch_size, (4, 4, 1), np.uint8)
    if records is None:
        records = [str(i).encode() for i in range(batch_size)]
    tasks = list(enumerate(records))
    failures = plane.run_round(images, labels, tasks)
    return images, labels, failures


class TestSlabSegment:
    def test_create_attach_roundtrip(self):
        slab = shm.SlabSegment.create(64)
        try:
            view = slab.ndarray((64,), np.uint8)
            view[:] = np.arange(64, dtype=np.uint8)
            other = shm.SlabSegment.attach(slab.name)
            got = np.array(other.ndarray((64,), np.uint8))
            other.close()
            assert (got == np.arange(64, dtype=np.uint8)).all()
        finally:
            slab.close()
            slab.unlink()
        assert slab.name not in [os.path.basename(f) for f in _slab_files()]

    def test_release_keeps_views_valid(self):
        # SharedMemory.close() unmaps under live views (segfault, not an
        # error) — release() hands the mapping to the views instead
        slab = shm.SlabSegment.create(16)
        view = slab.ndarray((16,), np.uint8)
        view[:] = 7
        name = slab.name
        slab.release()
        assert (view == 7).all()
        view[:] = 9  # still writable: the mapping follows the view
        assert "/dev/shm/" + name not in _slab_files()

    def test_unlink_leaked_covers_slabs(self, tmp_path):
        slab = shm.SlabSegment.create(16)
        name = slab.name
        slab.close()
        try:
            removed = shm.unlink_leaked(max_age_secs=0)
            assert removed >= 1
            assert "/dev/shm/" + name not in _slab_files()
        finally:
            # balance the create-side tracker registration for the segment
            # unlink_leaked removed behind the tracker's back
            shm._unregister_from_tracker(name)


class TestResolveWorkers:
    def test_explicit_count(self):
        assert decode_plane.resolve_workers(3) == (3, False)
        assert decode_plane.resolve_workers(0) == (0, False)
        assert decode_plane.resolve_workers(-2) == (0, False)

    def test_env_default(self, monkeypatch):
        monkeypatch.delenv("TOS_DECODE_WORKERS", raising=False)
        assert decode_plane.resolve_workers(None) == (0, False)
        monkeypatch.setenv("TOS_DECODE_WORKERS", "5")
        assert decode_plane.resolve_workers(None) == (5, False)

    def test_auto_self_sizes(self, monkeypatch):
        workers, auto = decode_plane.resolve_workers("auto")
        assert auto is True
        assert workers == max(1, (os.cpu_count() or 1) // 2)
        monkeypatch.setenv("TOS_DECODE_WORKERS", "auto")
        assert decode_plane.resolve_workers(None)[1] is True


class TestLeaseProtocol:
    def test_round_fills_slots_and_labels(self, plane):
        images, labels, failures = _fill(plane)
        assert failures == []
        for i in range(8):
            assert labels[i] == i
            assert (images[i] == i % 251).all()

    def test_worker_failures_come_back_as_errors(self, plane):
        records = [str(i if i != 3 else -7).encode() for i in range(8)]
        images, labels, failures = _fill(plane, records=records)
        assert len(failures) == 1
        slot, err = failures[0]
        assert slot == 3
        assert isinstance(err, DecodeWorkerError)
        assert "negative record -7" in str(err)
        # the other slots all landed
        for i in range(8):
            if i != 3:
                assert labels[i] == i

    def test_partial_round_leases_only_given_slots(self, plane):
        images, labels = plane.new_slab(8, (4, 4, 1), np.uint8)
        tasks = [(5, b"50"), (2, b"20")]
        assert plane.run_round(images, labels, tasks) == []
        assert labels[5] == 50 and labels[2] == 20

    def test_kill_mid_round_respawns_and_loses_no_slots(self):
        # SIGKILL one worker while it sleeps inside parse: the EOF on its
        # pipe must re-lease exactly its un-acked slots — every slot filled
        # exactly once, pool back at strength, restart counted
        plane = DecodePlane(_slow_parse, workers=2)
        try:
            before = _counter("decode_worker_restarts_total")
            images, labels = plane.new_slab(8, (4, 4, 1), np.uint8)
            victim = plane._workers[0].proc
            killer_done = []

            import threading

            def _kill():
                time.sleep(0.02)  # mid-round: workers are inside parse
                os.kill(victim.pid, signal.SIGKILL)
                killer_done.append(True)

            t = threading.Thread(target=_kill)
            t.start()
            failures = plane.run_round(
                images, labels, list(enumerate(str(i).encode() for i in range(8)))
            )
            t.join()
            assert killer_done and failures == []
            assert list(labels) == list(range(8))
            assert plane.workers == 2
            assert _counter("decode_worker_restarts_total") >= before + 1
        finally:
            plane.close()

    def test_stop_callback_raises_stopped(self, plane):
        images, labels = plane.new_slab(4, (4, 4, 1), np.uint8)
        with pytest.raises(decode_plane.Stopped):
            plane.run_round(images, labels, [(0, b"1")], should_stop=lambda: True)


class TestLifecycle:
    def test_resize_grows_and_shrinks(self, plane):
        plane.resize(4)
        assert plane.workers == 4
        plane.resize(1)
        assert plane.workers == 1
        # the shrunk pool still decodes
        images, labels, failures = _fill(plane, batch_size=4)
        assert failures == [] and list(labels) == [0, 1, 2, 3]

    def test_close_unlinks_slabs_and_reaps_workers(self):
        plane = DecodePlane(_parse, workers=2)
        images, labels, _ = _fill(plane)
        names = set(plane._slabs)
        procs = [w.proc for w in plane._workers]
        plane.close()
        plane.close()  # idempotent
        assert plane.workers == 0
        assert all(not p.is_alive() for p in procs)
        assert not any(
            os.path.basename(f) in names for f in _slab_files()
        )
        gauges = obs.snapshot()["gauges"]
        assert gauges["decode_workers"]["value"] == 0
        assert gauges["decode_slab_bytes"]["value"] == 0

    def test_slab_bytes_gauge_tracks_pool(self, plane):
        plane.new_slab(8, (4, 4, 1), np.uint8)
        plane.new_slab(8, (4, 4, 1), np.uint8)
        assert obs.snapshot()["gauges"]["decode_slab_bytes"]["value"] == 2 * 8 * 16

    def test_note_slab_wait_accumulates(self, plane):
        before = _counter("decode_slab_wait_seconds_total")
        plane.note_slab_wait(0.25)
        assert _counter("decode_slab_wait_seconds_total") == pytest.approx(
            before + 0.25
        )


class TestDecodeAutotuner:
    def test_starved_and_parse_dominated_grows_immediately(self):
        tuner = DecodeAutotuner(max_workers=8)
        assert tuner.decide(2, parse_delta=1.5, wait_delta=1.0, elapsed=2.0) == 3

    def test_starved_but_not_parse_dominated_holds(self):
        # the consumer starves yet parse is cheap: more decode workers
        # cannot help (IO or emit is the gate)
        tuner = DecodeAutotuner(max_workers=8)
        assert tuner.decide(2, parse_delta=0.1, wait_delta=1.0, elapsed=2.0) == 2

    def test_growth_respects_max_workers(self):
        tuner = DecodeAutotuner(max_workers=2)
        assert tuner.decide(2, parse_delta=2.0, wait_delta=1.0, elapsed=2.0) == 2

    def test_idle_shrinks_only_after_patience(self):
        tuner = DecodeAutotuner(max_workers=8, down_patience=2)
        assert tuner.decide(4, parse_delta=0.0, wait_delta=0.0, elapsed=2.0) == 4
        assert tuner.decide(4, parse_delta=0.0, wait_delta=0.0, elapsed=2.0) == 3

    def test_busy_interval_resets_the_down_streak(self):
        tuner = DecodeAutotuner(max_workers=8, down_patience=2)
        assert tuner.decide(4, parse_delta=0.0, wait_delta=0.0, elapsed=2.0) == 4
        # a mid-band interval (neither starved nor idle) clears the streak
        assert tuner.decide(4, parse_delta=0.1, wait_delta=0.06, elapsed=2.0) == 4
        assert tuner.decide(4, parse_delta=0.0, wait_delta=0.0, elapsed=2.0) == 4

    def test_shrink_respects_min_workers(self):
        tuner = DecodeAutotuner(min_workers=2, max_workers=8, down_patience=1)
        assert tuner.decide(2, parse_delta=0.0, wait_delta=0.0, elapsed=2.0) == 2

    def test_tick_is_clocked_and_delta_based(self):
        clock = [0.0]
        reads = [(0.0, 0.0), (3.0, 2.0), (3.0, 2.0)]
        tuner = DecodeAutotuner(
            max_workers=8,
            check_every=2.0,
            clock=lambda: clock[0],
            read_counters=lambda: reads.pop(0),
        )
        assert tuner.tick(2) is None  # first call seeds the baseline
        clock[0] = 1.0
        assert tuner.tick(2) is None  # interval not elapsed: no read burned
        clock[0] = 2.5
        # deltas (3.0, 2.0) over 2.5 s: starved and parse-dominated → grow
        assert tuner.tick(2) == 3
        clock[0] = 5.0
        # zero deltas: idle, but down_patience=2 holds the first time
        assert tuner.tick(3) == 3


@pytest.mark.perf_smoke
class TestGilRelease:
    """The point of the plane, measured: a GIL-bound parse_fn gains nothing
    from threads, so the process pool must beat a 1-thread pool by real
    parallelism. Skipped below 4 cores — with nothing to parallelize onto,
    IPC overhead is all that's left and the comparison proves nothing."""

    def test_process_pool_beats_single_thread_on_gil_bound_parse(self, tmp_path):
        if (os.cpu_count() or 1) < 4:
            pytest.skip("needs >= 4 cores to demonstrate GIL-free decode")
        from tensorflowonspark_tpu import tfrecord
        from tensorflowonspark_tpu.data import ImagePipeline

        p = str(tmp_path / "part-00000")
        with tfrecord.TFRecordWriter(p) as w:
            for i in range(96):
                w.write(str(i).encode())

        def _rate(decode_workers):
            pipe = ImagePipeline(
                [p], _gil_bound_parse, batch_size=8, seed=0, epochs=None,
                num_threads=1, decode_workers=decode_workers,
            )
            it = iter(pipe)
            next(it)  # bootstrap + pool spin-up outside the clock
            t0 = time.monotonic()
            for _ in range(8):
                next(it)
            dt = time.monotonic() - t0
            del it
            return 64 / dt

        thread = _rate(0)
        procs = _rate(4)
        assert procs > 1.5 * thread, (thread, procs)

    def test_process_pool_hits_3x_on_4plus_cores(self, tmp_path):
        """The multi-core demonstration the plane has waited on: with >= 4
        real cores the 4-process pool must clear 3x the 1-thread pool on a
        GIL-bound parse (the ``BENCH_MODE=decode`` gil leg records the same
        ratio). Skipped below 4 cores, where the recorded status quo is the
        single-core ~1x of docs/perf.md."""
        if (os.cpu_count() or 1) < 4:
            pytest.skip("needs >= 4 cores to demonstrate 3x GIL-free decode")
        from tensorflowonspark_tpu import tfrecord
        from tensorflowonspark_tpu.data import ImagePipeline

        p = str(tmp_path / "part-00000")
        with tfrecord.TFRecordWriter(p) as w:
            for i in range(160):
                w.write(str(i).encode())

        def _rate(decode_workers, batches=12):
            pipe = ImagePipeline(
                [p], _gil_bound_parse, batch_size=8, seed=0, epochs=None,
                num_threads=1, decode_workers=decode_workers,
            )
            it = iter(pipe)
            next(it)  # bootstrap + pool spin-up outside the clock
            t0 = time.monotonic()
            for _ in range(batches):
                next(it)
            dt = time.monotonic() - t0
            del it
            return batches * 8 / dt

        thread = _rate(0)
        procs = max(_rate(4), _rate(4))  # best-of-2: absorb scheduler noise
        assert procs >= 3.0 * thread, (thread, procs)
