"""Chaos: checkpoint faults and the resume contract.

``checkpoint.corrupt_write`` leaves the newest checkpoint torn on disk (the
shape a mid-write host crash produces); ``checkpoint.restore_fail`` makes a
restore raise once. :func:`checkpoint.restore_latest` must fall back to the
newest *restorable* checkpoint instead of dying — the "recovery relaunches
past a poisoned checkpoint" half of the chaos acceptance bar. Also covers
the `latest_checkpoint` prefix-mismatch warning satellite."""

import logging
import os

import pytest

from tensorflowonspark_tpu import chaos
from tensorflowonspark_tpu.train import checkpoint

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _save_steps(model_dir, steps):
    for step in steps:
        checkpoint.save_checkpoint(
            os.path.join(model_dir, "ckpt_{}".format(step)),
            {"step": step, "w": [float(step)] * 4},
        )


class TestRestoreLatestFallback:
    def test_corrupt_newest_falls_back_to_previous(self, tmp_path):
        model_dir = str(tmp_path)
        _save_steps(model_dir, [1, 2])
        # corrupt the NEWEST save only
        chaos.install(
            chaos.ChaosPlan(seed=0).site("checkpoint.corrupt_write", probability=1.0,
                                         max_count=1),
            propagate=False,
        )
        _save_steps(model_dir, [3])
        chaos.uninstall()

        state, path = checkpoint.restore_latest(model_dir)
        assert os.path.basename(path) == "ckpt_2"
        assert state["step"] == 2

    def test_restore_fail_once_falls_back_then_heals(self, tmp_path):
        model_dir = str(tmp_path)
        _save_steps(model_dir, [1, 2])
        plan = chaos.ChaosPlan(seed=0).site(
            "checkpoint.restore_fail", probability=1.0, max_count=1
        )
        chaos.install(plan, propagate=False)
        state, path = checkpoint.restore_latest(model_dir)
        # the injected failure hit ckpt_2; the fallback restored ckpt_1
        assert plan.fired("checkpoint.restore_fail") == 1
        assert os.path.basename(path) == "ckpt_1"
        assert state["step"] == 1
        # fault budget spent: the next resume sees the newest again
        state, path = checkpoint.restore_latest(model_dir)
        assert os.path.basename(path) == "ckpt_2"

    def test_every_checkpoint_corrupt_raises(self, tmp_path):
        model_dir = str(tmp_path)
        _save_steps(model_dir, [1])
        chaos.install(
            chaos.ChaosPlan(seed=0).site("checkpoint.restore_fail", probability=1.0),
            propagate=False,
        )
        with pytest.raises(IOError):
            checkpoint.restore_latest(model_dir)

    def test_empty_dir_is_clean_fresh_start(self, tmp_path):
        assert checkpoint.restore_latest(str(tmp_path)) == (None, None)

    def test_restore_latest_with_train_state_target(self, tmp_path):
        """The fallback path preserves the targeted-restore contract used by
        the training examples (structure/shardings from a fresh state)."""
        import jax
        import numpy as np
        import optax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import mnist
        from tensorflowonspark_tpu.train import SyncDataParallel

        model_dir = str(tmp_path)
        strategy = SyncDataParallel(parallel.local_mesh({"dp": -1}))
        model = mnist.create_model("mlp", hidden=8)
        state = strategy.create_state(
            mnist.make_init_fn(model), optax.sgd(0.1), jax.random.PRNGKey(0)
        )
        host_state = jax.device_get(state)
        checkpoint.save_checkpoint(os.path.join(model_dir, "ckpt_5"), host_state)
        chaos.install(
            chaos.ChaosPlan(seed=0).site("checkpoint.corrupt_write", probability=1.0),
            propagate=False,
        )
        checkpoint.save_checkpoint(os.path.join(model_dir, "ckpt_9"), host_state)
        chaos.uninstall()

        restored, path = checkpoint.restore_latest(model_dir, target=host_state)
        assert os.path.basename(path) == "ckpt_5"
        np.testing.assert_array_equal(
            jax.tree.leaves(restored.params)[0], jax.tree.leaves(host_state.params)[0]
        )


class TestPrefixMismatchWarning:
    def test_warns_when_numbered_dirs_miss_the_prefix(self, tmp_path, caplog):
        os.makedirs(str(tmp_path / "model_3"))
        os.makedirs(str(tmp_path / "model_7"))
        with caplog.at_level(logging.WARNING, logger="tensorflowonspark_tpu.train.checkpoint"):
            assert checkpoint.latest_checkpoint(str(tmp_path)) is None
        joined = " ".join(r.getMessage() for r in caplog.records)
        assert "none match" in joined and 'prefix=""' in joined and "model_7" in joined

    def test_no_warning_for_empty_or_matching_dirs(self, tmp_path, caplog):
        with caplog.at_level(logging.WARNING, logger="tensorflowonspark_tpu.train.checkpoint"):
            assert checkpoint.latest_checkpoint(str(tmp_path)) is None  # empty: quiet
            os.makedirs(str(tmp_path / "ckpt_4"))
            assert checkpoint.latest_checkpoint(str(tmp_path)).endswith("ckpt_4")
        assert not caplog.records

    def test_prefix_escape_hatch_accepts_any_layout(self, tmp_path):
        os.makedirs(str(tmp_path / "model_3"))
        assert checkpoint.latest_checkpoint(str(tmp_path), prefix="").endswith("model_3")
