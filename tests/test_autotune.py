"""Adaptive device-feed autotuner (data/autotune.py + train PackedLoopCache):
link-estimator math, the bucket decision rule with hysteresis, byte-identical
delivery for ANY window trajectory, bounded recompiles, the donation-safety
contract of the packed loop, and deterministic adaptation under the
``data.device_link`` chaos site."""

import warnings

import numpy as np
import pytest

import jax
import optax

from tensorflowonspark_tpu import chaos, obs, parallel
from tensorflowonspark_tpu.data import FeedAutotuner, LinkEstimator, autotuned_prefetch
from tensorflowonspark_tpu.data.autotune import (
    batch_nbytes,
    bucket_decomposition,
)
from tensorflowonspark_tpu.data.loader import packed_place
from tensorflowonspark_tpu.train import PackedLoopCache, SyncDataParallel

FEED_METRICS = (
    "feed_link_bytes_per_sec",
    "feed_transfer_fixed_cost_seconds",
    "feed_window_size",
    "feed_recompiles_total",
    "feed_transfer_seconds_total",
)


def _strategy():
    return SyncDataParallel(parallel.build_mesh({"dp": 8}))


def _linear_init(rng):
    k1, _ = jax.random.split(rng)
    return {"w": jax.random.normal(k1, (2, 1)) * 0.01, "b": np.zeros((1,), np.float32)}


def _linear_loss(params, batch):
    pred = batch["x"] @ params["w"] + params["b"]
    return ((pred - batch["y"]) ** 2).mean()


def _xy_batches(n, rows=8):
    rng = np.random.default_rng(0)
    return [
        {
            "x": rng.standard_normal((rows, 2)).astype(np.float32),
            "y": rng.standard_normal((rows, 1)).astype(np.float32),
        }
        for _ in range(n)
    ]


class TestLinkEstimator:
    def test_first_observations_seed_directly(self):
        est = LinkEstimator(alpha=0.3)
        assert not est.ready and est.predict(100) is None
        est.observe_fixed(0.2)
        est.observe(10_000, 0.2 + 0.001)  # stream share: exactly 1 ms
        assert est.ready
        assert est.fixed_s == pytest.approx(0.2)
        assert est.bytes_per_sec == pytest.approx(10_000 / 0.001)
        assert est.predict(20_000) == pytest.approx(0.2 + 0.002)

    def test_ewma_blends_with_alpha(self):
        est = LinkEstimator(alpha=0.3)
        est.observe_fixed(0.2)
        est.observe_fixed(0.1)
        assert est.fixed_s == pytest.approx(0.7 * 0.2 + 0.3 * 0.1)

    def test_fast_transfer_drags_fixed_down(self):
        # a whole transfer faster than the fixed estimate disproves the
        # estimate: the model must recover from a probe that caught a spike
        est = LinkEstimator(alpha=0.3)
        est.observe_fixed(0.2)
        est.observe(1_000, 0.05)
        assert est.fixed_s == pytest.approx(0.7 * 0.2 + 0.3 * 0.05)
        # the whole observation fits inside the (clamped) fixed estimate: it
        # resolves no stream share, so it must NOT poison the bandwidth
        # estimate with a near-infinite sample
        assert est.bytes_per_sec is None and not est.ready

    def test_unresolvable_transfer_leaves_bandwidth_untouched(self):
        est = LinkEstimator(alpha=0.5)
        est.observe_fixed(0.010)
        est.observe(1 << 20, 0.015)  # 5 ms of stream: 1 MiB / 0.005
        bw = est.bytes_per_sec
        assert bw == pytest.approx((1 << 20) / 0.005)
        est.observe(1 << 20, 0.008)  # inside fixed cost: clamps fixed only
        assert est.fixed_s < 0.010
        assert est.bytes_per_sec == pytest.approx(bw)

    def test_fixed_share_decreases_with_bytes(self):
        est = LinkEstimator()
        est.observe_fixed(0.1)
        est.observe(1_000_000, 0.1 + 0.05)
        shares = [est.fixed_share(k * 1_000_000) for k in (1, 2, 4, 8)]
        assert shares == sorted(shares, reverse=True)
        assert shares[0] == pytest.approx(0.1 / 0.15)

    def test_rejects_bad_alpha_and_ignores_bad_samples(self):
        with pytest.raises(ValueError):
            LinkEstimator(alpha=0.0)
        est = LinkEstimator()
        est.observe(0, 1.0)
        est.observe(100, 0.0)
        assert not est.ready


class TestBucketDecomposition:
    def test_binary_decomposition_is_exact_with_unit_bucket(self):
        buckets = (1, 2, 4, 8, 16)
        assert bucket_decomposition(13, buckets) == [8, 4, 1]
        assert bucket_decomposition(16, buckets) == [16]
        for n in range(0, 40):
            sizes = bucket_decomposition(n, buckets)
            assert sum(sizes) == n
            assert all(s in buckets for s in sizes)

    def test_residue_below_smallest_bucket_is_dropped(self):
        assert bucket_decomposition(5, (2, 4)) == [4]


class TestFeedAutotunerDecisions:
    def _tuner(self, **kw):
        kw.setdefault("buckets", (1, 2, 4, 8))
        kw.setdefault("down_patience", 2)
        return FeedAutotuner(**kw)

    def _seed_for_k4(self, tuner, b=1_000_000):
        # fixed 0.02, stream 0.05/batch: share(4b) = .02/.22 <= 0.1 < share(2b)
        tuner.note_fixed_probe(0.02)
        tuner.note_transfer(b, 0.02 + 0.05)
        assert tuner.recommend(b) == 4
        return b

    def test_not_ready_recommends_smallest_bucket(self):
        tuner = self._tuner()
        assert tuner.recommend(1_000_000) == 1

    def test_first_decide_jumps_to_recommendation(self):
        tuner = self._tuner()
        b = self._seed_for_k4(tuner)
        assert tuner.decide(b) == (4, 2)

    def test_upward_move_is_immediate_one_bucket_per_decide(self):
        tuner = self._tuner(alpha=0.9)
        b = self._seed_for_k4(tuner)
        tuner.decide(b)
        for _ in range(4):  # latency spike: fixed cost jumps 20x
            tuner.note_fixed_probe(0.4)
        assert tuner.recommend(b) == 8
        assert tuner.decide(b)[0] == 8  # one bucket up, no patience needed

    def test_downward_move_waits_for_patience(self):
        tuner = self._tuner(alpha=0.9, down_patience=2)
        b = self._seed_for_k4(tuner)
        tuner.decide(b)
        for _ in range(4):  # link recovers: fixed cost collapses
            tuner.note_fixed_probe(0.0005)
            tuner.note_transfer(b, 0.0005 + 0.05)
        assert tuner.recommend(b) == 1
        assert tuner.decide(b)[0] == 4  # streak 1 of 2: hold
        assert tuner.decide(b)[0] == 2  # patience met: one bucket down
        assert tuner.decide(b)[0] == 2  # streak resets after a move
        assert tuner.decide(b)[0] == 1

    def test_depth_shrinks_for_deep_windows(self):
        tuner = self._tuner(deep_window_k=8)
        assert tuner.depth(2) == 2
        assert tuner.depth(8) == 1

    def test_rejects_bad_config(self):
        with pytest.raises(ValueError):
            FeedAutotuner(buckets=())
        with pytest.raises(ValueError):
            FeedAutotuner(buckets=(0, 2))
        with pytest.raises(ValueError):
            FeedAutotuner(overhead_target=1.5)

    def test_all_feed_metrics_registered_and_published(self):
        tuner = self._tuner()
        b = self._seed_for_k4(tuner)
        tuner.decide(b)
        snap = obs.snapshot()
        flat = dict(snap["gauges"])
        flat.update(snap["counters"])
        for name in FEED_METRICS:
            assert name in flat, name
        assert flat["feed_window_size"]["value"] == 4
        assert flat["feed_transfer_fixed_cost_seconds"]["value"] == pytest.approx(0.02)
        assert flat["feed_link_bytes_per_sec"]["value"] == pytest.approx(1_000_000 / 0.05)
        assert flat["feed_transfer_seconds_total"]["value"] > 0


class TestAutotunedPrefetchStream:
    """The delivery contract: byte-identical batch stream for ANY controller
    trajectory — windows in arrival order, the source tail flushed by binary
    decomposition, nothing dropped or duplicated."""

    def _delivered(self, host, strategy, **tuner_kw):
        out, ks = [], []
        tuner = FeedAutotuner(**tuner_kw)
        for w in autotuned_prefetch(iter(host), strategy, tuner=tuner):
            assert w.k in tuner.buckets
            ks.append(w.k)
            data = jax.device_get(w.data)
            for i in range(w.k):
                out.append({k: np.asarray(v)[i] for k, v in data.items()})
        return out, ks

    @pytest.mark.parametrize("n", [1, 7, 11, 16])
    def test_stream_identical_across_bucket_sets(self, n):
        strategy = _strategy()
        host = _xy_batches(n)
        base, base_ks = self._delivered(host, strategy, buckets=(1,))
        assert base_ks == [1] * n
        for buckets in [(1, 2), (1, 4), (1, 2, 4, 8, 16)]:
            got, ks = self._delivered(host, strategy, buckets=buckets)
            assert sum(ks) == n
            assert len(got) == n
            for a, b in zip(got, base):
                for key in ("x", "y"):
                    np.testing.assert_array_equal(a[key], b[key])

    def test_tuner_kwargs_construct_default_tuner(self):
        strategy = _strategy()
        host = _xy_batches(3)
        ws = list(autotuned_prefetch(iter(host), strategy, buckets=(1,)))
        assert [w.k for w in ws] == [1, 1, 1]

    def test_batch_nbytes_counts_all_leaves(self):
        b = _xy_batches(1)[0]
        assert batch_nbytes(b) == b["x"].nbytes + b["y"].nbytes


class TestPackedLoopCache:
    def test_compiles_at_most_once_per_bucket_and_counts(self):
        strategy = _strategy()
        optimizer = optax.sgd(0.05)
        cache = PackedLoopCache(strategy, _linear_loss, optimizer)
        before = obs.snapshot()["counters"]["feed_recompiles_total"]["value"]
        l2 = cache.loop_for(2)
        assert cache.loop_for(2) is l2
        cache.loop_for(4)
        assert cache.compiled_sizes == [2, 4]
        after = obs.snapshot()["counters"]["feed_recompiles_total"]["value"]
        assert after - before == 2

    def test_run_trains_through_autotuned_windows(self):
        strategy = _strategy()
        optimizer = optax.sgd(0.05)
        state = strategy.create_state(_linear_init, optimizer, jax.random.PRNGKey(0))
        cache = PackedLoopCache(strategy, _linear_loss, optimizer)
        n = 11
        for w in autotuned_prefetch(
            iter(_xy_batches(n)), strategy, buckets=(1, 2, 4)
        ):
            state, metrics = cache.run(state, w)
            jax.block_until_ready(metrics["loss"])
        # every batch trained exactly one step, whatever the windowing
        assert int(jax.device_get(state.step)) == n
        assert np.isfinite(float(jax.device_get(metrics["loss"])))


class TestDonationSafety:
    """The packed loop's donation contract (satellite of the autotuner: the
    prefetch buffer retains windows for double-buffering, so the default
    packed path must never donate them)."""

    def _compiled(self, strategy, k, donate):
        optimizer = optax.sgd(0.05)
        state = strategy.create_state(_linear_init, optimizer, jax.random.PRNGKey(0))
        loop = strategy.compile_train_loop(
            _linear_loss, optimizer, k, donate=donate, packed=True
        )
        return state, loop

    def test_packed_default_donation_emits_no_unusable_warning(self):
        strategy = _strategy()
        k = 4
        state, loop = self._compiled(strategy, k, donate=True)
        window = packed_place(_xy_batches(k), strategy)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            for _ in range(2):  # window re-fed: it must survive the dispatch
                state, metrics = loop(state, window)
                jax.block_until_ready(metrics["loss"])
        donated = [w for w in caught if "donated buffers" in str(w.message).lower()]
        assert donated == [], [str(w.message) for w in donated]
        assert int(jax.device_get(state.step)) == 2 * k

    def test_packed_default_donates_state_not_batches(self):
        # the contract itself, read off the lowered IR: packed donate=True
        # means "state" — the [K,B,...] stack is NOT marked as a buffer
        # donor; donate="batches" forces it (and marks exactly the window's
        # leaves on top of the state's)
        strategy = _strategy()
        k = 4
        window = packed_place(_xy_batches(k), strategy)

        def donors(donate):
            state, loop = self._compiled(strategy, k, donate=donate)
            return loop.lower(state, window).as_text().count("jax.buffer_donor")

        default, state_only, forced = donors(True), donors("state"), donors("batches")
        assert donors(False) == 0
        assert default == state_only > 0
        n_window_leaves = len(jax.tree.leaves(window))
        assert forced == state_only + n_window_leaves

    def test_unpacked_default_donates_state_not_batches(self):
        # same contract for the NON-packed loop (the examples' real-data
        # path): donate=True marks state leaves only — the batch-list
        # donation was what kept the "Some donated buffers were not
        # usable: uint8[...]" warning alive in the bench tail
        strategy = _strategy()
        k = 4
        optimizer = optax.sgd(0.05)
        batches = [strategy.shard_batch(b) for b in _xy_batches(k)]

        def donors(donate):
            state = strategy.create_state(
                _linear_init, optimizer, jax.random.PRNGKey(0)
            )
            loop = strategy.compile_train_loop(
                _linear_loss, optimizer, k, donate=donate, packed=False
            )
            return loop.lower(state, batches).as_text().count("jax.buffer_donor")

        default, state_only, forced = donors(True), donors("state"), donors("batches")
        assert donors(False) == 0
        assert default == state_only > 0
        n_batch_leaves = len(jax.tree.leaves(batches))
        assert forced == state_only + n_batch_leaves


@pytest.mark.chaos
@pytest.mark.perf_smoke
class TestChaosDeviceLink:
    """Deterministic end-to-end adaptation: ``data.device_link`` injects a
    per-transfer delay INSIDE the autotuner's timed region, so injected
    latency flows straight into the link estimate. Sleep-staged like the
    other perf_smoke legs — the assertions are structural (which bucket the
    controller picked), never absolute throughput."""

    @pytest.fixture(autouse=True)
    def _clean_chaos(self):
        chaos.uninstall()
        yield
        chaos.uninstall()

    def _drain(self, host, strategy, tuner):
        """Run one stream through autotuned_prefetch; return (delivered
        per-batch host arrays, window sizes)."""
        out, ks = [], []
        for w in autotuned_prefetch(iter(host), strategy, tuner=tuner):
            ks.append(w.k)
            data = np.asarray(jax.device_get(w.data["x"]))
            out.extend(data[i] for i in range(w.k))
        return out, ks

    def test_latency_up_moves_k_up_then_recovery_moves_k_down(self):
        strategy = _strategy()
        # alpha/reprobe tuned for a short test: the estimator forgets the
        # spike within a few windows once the injected latency is gone
        tuner = FeedAutotuner(
            buckets=(1, 2, 4), alpha=0.7, reprobe_every=1, down_patience=1
        )

        # -- phase 1: 60 ms injected per-transfer latency dwarfs the real
        # CPU transfer time, so the fixed-cost share is ~1 at every bucket
        # and the controller must ratchet to the top bucket; 1 MiB batches
        # keep the window transfers long enough beyond the probes that the
        # bandwidth term resolves (sub-probe transfers feed only the
        # fixed-cost clamp)
        plan = chaos.ChaosPlan(seed=0).site("data.device_link", probability=1.0, delay_s=0.06)
        chaos.install(plan, propagate=False)
        spike = [{"x": np.full((8, 128, 256), i, np.float32)} for i in range(10)]
        got, ks = self._drain(spike, strategy, tuner)
        assert plan.fired("data.device_link") > 0
        assert max(ks) == 4
        assert tuner._k == 4
        assert sum(ks) == len(spike)
        for i, arr in enumerate(got):  # byte-identical delivery under chaos
            np.testing.assert_array_equal(arr, spike[i]["x"])

        # -- phase 2: latency gone; 8 MiB batches put the per-batch stream
        # time (~10 ms on any host) far above what a noisy sub-millisecond
        # probe can re-inflate the fixed estimate to, so once the spike
        # decays the recommendation falls and K must come back down — and
        # stay down through the end of the stream
        chaos.uninstall()
        calm = [{"x": np.full((8, 512, 512), i, np.float32)} for i in range(24)]
        got, ks = self._drain(calm, strategy, tuner)
        assert sum(ks) == len(calm)
        assert tuner._k < 4, ks
        assert ks[-1] < 4, ks
        for i, arr in enumerate(got):
            np.testing.assert_array_equal(arr, calm[i]["x"])


class TestReadaheadAutotuner:
    """The third controller: shard read-ahead depth steered by the same
    stall accounting ``bench.classify_stalls`` reads — deepen only when the
    interval was io_bound, never when decode is the bottleneck."""

    def _tuner(self, **kw):
        from tensorflowonspark_tpu.data.autotune import ReadaheadAutotuner

        kw.setdefault("min_depth", 1)
        kw.setdefault("max_depth", 6)
        kw.setdefault("down_patience", 2)
        return ReadaheadAutotuner(**kw)

    def test_starved_and_io_bound_deepens_immediately(self):
        t = self._tuner()
        # consumer starved 40% of the interval, shard IO >= parse: deepen
        assert t.decide(2, read_delta=3.0, parse_delta=1.0, wait_delta=0.8,
                        elapsed=2.0) == 3

    def test_starved_but_decode_bound_is_not_its_problem(self):
        t = self._tuner()
        # same starvation but parse dominates IO: the decode autotuner's
        # territory — deepening read-ahead cannot fix it, depth holds
        assert t.decide(2, read_delta=1.0, parse_delta=3.0, wait_delta=0.8,
                        elapsed=2.0) == 2

    def test_idle_shrinks_only_after_down_patience(self):
        t = self._tuner(down_patience=2)
        assert t.decide(4, 0.1, 0.1, 0.0, 2.0) == 4  # streak 1 of 2: hold
        assert t.decide(4, 0.1, 0.1, 0.0, 2.0) == 3  # patience met
        assert t.decide(3, 0.1, 0.1, 0.0, 2.0) == 3  # streak reset by move

    def test_busy_interval_resets_the_down_streak(self):
        t = self._tuner(down_patience=2)
        assert t.decide(4, 0.1, 0.1, 0.0, 2.0) == 4   # idle: streak 1
        # a moderately-waiting interval (neither idle nor starved+io_bound)
        assert t.decide(4, 1.0, 3.0, 0.5, 2.0) == 4   # streak cleared
        assert t.decide(4, 0.1, 0.1, 0.0, 2.0) == 4   # idle again: streak 1

    def test_bounds_are_respected(self):
        t = self._tuner(min_depth=2, max_depth=3, down_patience=1)
        assert t.decide(3, 3.0, 1.0, 1.0, 2.0) == 3  # at max: no deeper
        assert t.decide(2, 0.0, 0.0, 0.0, 2.0) == 2  # at min: no shallower

    def test_zero_elapsed_is_a_noop(self):
        t = self._tuner()
        assert t.decide(2, 1.0, 0.0, 1.0, 0.0) == 2

    def test_rejects_inverted_bounds(self):
        from tensorflowonspark_tpu.data.autotune import ReadaheadAutotuner

        with pytest.raises(ValueError):
            ReadaheadAutotuner(min_depth=4, max_depth=2)

    def test_tick_gates_on_check_every_and_publishes_gauge(self):
        clock = iter([0.0, 1.0, 2.5, 5.0]).__next__
        reads = iter([
            (0.0, 0.0, 0.0),   # first tick: baseline only
            (3.0, 1.0, 1.0),   # io_bound + starved over 2.5 s
            (3.1, 1.1, 1.0),   # idle interval
        ]).__next__
        t = self._tuner(check_every=2.0, clock=clock, read_counters=reads)
        assert t.tick(2) is None        # t=0: baseline
        assert t.tick(2) is None        # t=1: interval not elapsed
        assert t.tick(2) == 3           # t=2.5: starved + io_bound
        assert obs.snapshot()["gauges"]["readahead_depth"]["value"] == 3
        assert t.tick(3) == 3           # t=5: idle, streak 1 of 2: hold

    def test_publish_seeds_the_gauge_before_first_interval(self):
        t = self._tuner()
        t.publish(5)
        assert obs.snapshot()["gauges"]["readahead_depth"]["value"] == 5

    def test_default_counter_source_reads_the_obs_registry(self):
        t = self._tuner(check_every=0.0, clock=iter([0.0, 1.0]).__next__)
        read_c = obs.counter("data_producer_read_seconds_total")
        wait_c = obs.counter("data_consumer_wait_seconds_total")
        assert t.tick(1) is None        # baseline snapshot of real counters
        read_c.inc(2.0)
        wait_c.inc(0.5)                 # 50% starved, io dominates parse
        assert t.tick(1) == 2


class TestBenchLoopDonationPin:
    """The donation-warning pin, on the bench's exact loop configuration
    (``compile_train_loop(loss_fn, optimizer, K, mutable=True,
    donate="state", packed=...)`` with a batch-stats ResNet loss over raw
    uint8 images + int labels): "Some donated buffers were not usable:
    uint8[...], int32[...]" must stay dead. Pinned at the IR level — no
    uint8 image stack or int32 label leaf may carry ``jax.buffer_donor`` —
    and at dispatch, re-feeding the same window warning-free."""

    K = 4

    def _bench_loop(self, packed, hw=8, b=8):
        from tensorflowonspark_tpu.data import imagenet
        from tensorflowonspark_tpu.models import resnet

        strategy = _strategy()
        model = resnet.ResNet(stage_sizes=(1,), filters=(8,), num_classes=10,
                              bottleneck=False, stem="cifar")
        optimizer = optax.sgd(0.1, momentum=0.9)
        state = strategy.create_state(
            resnet.make_init_fn(model, image_size=hw), optimizer,
            jax.random.PRNGKey(0))
        loss_fn = resnet.make_loss_fn(
            model, weight_decay=1e-4, normalize=imagenet.device_normalize)
        loop = strategy.compile_train_loop(
            loss_fn, optimizer, self.K, mutable=True, donate="state",
            packed=packed)
        rng = np.random.default_rng(0)
        host = [
            {"image": rng.integers(0, 256, (b, hw, hw, 3), dtype=np.uint8),
             "label": rng.integers(0, 10, b).astype(np.int32)}
            for _ in range(self.K)
        ]
        if packed:
            window = packed_place(host, strategy)
        else:
            window = [strategy.shard_batch(x) for x in host]
        return state, loop, window

    @pytest.mark.parametrize("packed", [True, False])
    def test_lowered_ir_never_marks_batch_leaves_as_donors(self, packed):
        import re

        state, loop, window = self._bench_loop(packed)
        text = loop.lower(state, window).as_text()
        donors = re.findall(r"tensor<([^>]*)>[^,)]*jax\.buffer_donor", text)
        assert donors, "donation disappeared entirely — state must donate"
        for d in donors:
            # uint8 image stacks lower as ...xui8, label vectors as ...xi32
            # (the state's scalar step is tensor<i32>: no 'x')
            assert "ui8" not in d and "xi32" not in d, donors

    @pytest.mark.parametrize("packed", [True, False])
    def test_double_dispatch_refeeding_the_window_is_warning_free(self, packed):
        state, loop, window = self._bench_loop(packed)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            for _ in range(2):  # the bench re-feeds live windows: no copies
                state, metrics = loop(state, window)
                jax.block_until_ready(metrics["loss"])
        bad = [str(w.message) for w in caught
               if "donated buffers" in str(w.message).lower()]
        assert bad == []
        assert int(jax.device_get(state.step)) == 2 * self.K
