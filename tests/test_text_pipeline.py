"""Text plane: tokenizer validation, FFD packing bounds, the TextPipeline
determinism contract (byte-identical [B, L] streams across pack modes,
knobs, and cache states), the text chaos sites, the TFEstimator LM
fine-tune wiring, and the perf-smoke lm leg."""

import importlib.util
import os

import numpy as np
import pytest

from tensorflowonspark_tpu import chaos, obs, tfrecord
from tensorflowonspark_tpu.data import TextPipeline, TokenizeError, Tokenizer, pack_bins
from tensorflowonspark_tpu.data.tokenizer import BOS_ID, EOS_ID, PAD_ID, RESERVED_IDS


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _write_corpus(tmp_path, texts, shards=2, name="corpus"):
    d = tmp_path / name
    d.mkdir(exist_ok=True)
    per = (len(texts) + shards - 1) // shards
    paths = []
    for s in range(shards):
        p = str(d / "part-{:05d}".format(s))
        with tfrecord.TFRecordWriter(p) as w:
            for t in texts[s * per : (s + 1) * per]:
                w.write(t if isinstance(t, bytes) else t.encode("utf-8"))
        paths.append(p)
    return paths


def _sample_texts(n=120, seed=0):
    rng = np.random.default_rng(seed)
    words = "spark text plane packs variable length sequences tightly".split()
    return [
        " ".join(rng.choice(words, size=max(2, int(rng.lognormal(2.2, 0.7)))))
        for _ in range(n)
    ]


def _collect(pipe):
    return [{k: np.array(v) for k, v in b.items()} for b in pipe]


def _streams_equal(a, b):
    if len(a) != len(b):
        return False
    for x, y in zip(a, b):
        for k in ("tokens", "segment_ids", "positions"):
            if not np.array_equal(x[k], y[k]):
                return False
    return True


class TestTokenizer:
    def test_byte_roundtrip_shape(self):
        tok = Tokenizer(kind="byte")
        ids = tok.encode(b"hi")
        assert list(ids) == [BOS_ID, ord("h") + RESERVED_IDS, ord("i") + RESERVED_IDS, EOS_ID]
        assert tok.token_length(b"hi") == len(ids)

    def test_word_hashing_is_deterministic(self):
        tok = Tokenizer(kind="word", vocab_size=64)
        a, b = tok.encode(b"alpha beta alpha"), tok.encode(b"alpha beta alpha")
        assert np.array_equal(a, b)
        assert a[1] == a[3]  # same word, same bucket
        assert all(RESERVED_IDS <= t < 64 for t in a[1:-1])

    def test_truncation_keeps_terminal_eos(self):
        tok = Tokenizer(kind="byte")
        ids = tok.encode(b"abcdefgh", max_tokens=5)
        assert len(ids) == 5 and ids[0] == BOS_ID and ids[-1] == EOS_ID

    def test_rejects_invalid_utf8_and_empty(self):
        tok = Tokenizer()
        with pytest.raises(TokenizeError):
            tok.token_length(b"\xff\xfe")
        with pytest.raises(TokenizeError):
            tok.token_length(b"   ")

    def test_example_field_extraction(self):
        tok = Tokenizer(kind="word", field="text")
        rec = tfrecord.encode_example({"text": [b"hello world"]})
        assert tok.token_length(rec) == 4
        with pytest.raises(TokenizeError):
            tok.token_length(tfrecord.encode_example({"other": [b"x"]}))

    def test_cache_key_covers_config(self):
        keys = {
            Tokenizer().cache_key,
            Tokenizer(kind="word").cache_key,
            Tokenizer(kind="word", vocab_size=64).cache_key,
            Tokenizer(kind="word", field="text").cache_key,
        }
        assert len(keys) == 4


class TestPackBins:
    def test_partition_is_exact_and_within_capacity(self):
        rng = np.random.default_rng(0)
        lengths = rng.integers(1, 101, 500).tolist()
        bins = pack_bins(lengths, 100)
        flat = sorted(i for b in bins for i in b)
        assert flat == list(range(len(lengths)))
        assert all(sum(lengths[i] for i in b) <= 100 for b in bins)

    @pytest.mark.parametrize(
        "name,lengths",
        [
            # classic FFD adversary: halves + quarters + slack
            ("halves", [51] * 20 + [26] * 20 + [23] * 20),
            # heavy head, long tail of crumbs
            ("zipf", [90] * 5 + [40] * 10 + [7] * 200),
            # all just over a third: exactly 2 per bin, 1/3 wasted
            ("thirds", [34] * 30),
            ("uniform", list(np.random.default_rng(1).integers(1, 101, 400))),
        ],
    )
    def test_ffd_bound_on_adversarial_distributions(self, name, lengths):
        # FFD <= 11/9 OPT + 6/9 (Dósa); OPT >= ceil(total/capacity)
        capacity = 100
        bins = pack_bins(lengths, capacity)
        lb = -(-sum(lengths) // capacity)
        assert len(bins) <= (11 * lb + 6) // 9 + 1, name

    def test_determinism_and_creation_order(self):
        lengths = [10, 3, 7, 5, 2]
        assert pack_bins(lengths, 12) == pack_bins(lengths, 12) == [[0, 4], [2, 3], [1]]


class TestDeterminism:
    """The delivered [B, L] stream is byte-identical across pack worker
    counts, pipeline knobs, and packed-slab cache states."""

    def _pipe(self, files, tmp_path, **kw):
        kw.setdefault("seq_len", 48)
        kw.setdefault("batch_size", 4)
        kw.setdefault("seed", 7)
        kw.setdefault("epochs", 2)
        return TextPipeline(files, Tokenizer(kind="word", vocab_size=128), **kw)

    def test_stream_invariant_across_pack_modes_and_knobs(self, tmp_path):
        files = _write_corpus(tmp_path, _sample_texts())
        base = _collect(self._pipe(files, tmp_path))
        assert base, "pipeline yielded nothing"
        assert _streams_equal(base, _collect(self._pipe(files, tmp_path, pack_workers=2)))
        assert _streams_equal(
            base,
            _collect(
                self._pipe(
                    files, tmp_path, readahead=0, chunk_records=8, num_threads=1
                )
            ),
        )

    def test_stream_invariant_across_cache_states(self, tmp_path):
        files = _write_corpus(tmp_path, _sample_texts(seed=3))
        cache_dir = str(tmp_path / "slabs")
        base = _collect(self._pipe(files, tmp_path))
        cold = _collect(self._pipe(files, tmp_path, slab_cache_dir=cache_dir))
        warm = _collect(self._pipe(files, tmp_path, slab_cache_dir=cache_dir))
        assert _streams_equal(base, cold)
        assert _streams_equal(base, warm)

    def test_batches_are_packed_and_position_fenced(self, tmp_path):
        files = _write_corpus(tmp_path, _sample_texts(seed=5))
        for batch in _collect(self._pipe(files, tmp_path)):
            tokens, seg, pos = batch["tokens"], batch["segment_ids"], batch["positions"]
            assert tokens.shape == seg.shape == pos.shape == (4, 48)
            # pad iff segment 0; positions restart at 0 per segment
            assert np.array_equal(seg == 0, tokens == PAD_ID) or (tokens[seg == 0] == PAD_ID).all()
            for row_seg, row_pos in zip(seg, pos):
                for s in np.unique(row_seg[row_seg > 0]):
                    span = row_pos[row_seg == s]
                    assert list(span) == list(range(len(span)))


class TestBadRecords:
    def test_budget_charged_identically_in_every_mode(self, tmp_path):
        texts = _sample_texts(40)
        texts[5] = b"\xff\xfe broken"
        texts[21] = b"\x80\x80 also broken"
        files = _write_corpus(tmp_path, texts)

        def run(**kw):
            before = obs.counter("text_tokenize_errors_total").value
            pipe = TextPipeline(
                files, Tokenizer(), seq_len=64, batch_size=2, seed=1,
                max_bad_records=2, **kw
            )
            batches = _collect(pipe)
            return batches, obs.counter("text_tokenize_errors_total").value - before

        b0, skipped0 = run()
        b2, skipped2 = run(pack_workers=2)
        assert skipped0 == skipped2 == 2
        assert _streams_equal(b0, b2)

    def test_budget_exhaustion_raises(self, tmp_path):
        texts = _sample_texts(20)
        texts[3] = b"\xff\xfe broken"
        files = _write_corpus(tmp_path, texts)
        pipe = TextPipeline(
            files, Tokenizer(), seq_len=64, batch_size=2, seed=1, max_bad_records=0
        )
        with pytest.raises(TokenizeError):
            _collect(pipe)


class TestChaosSites:
    def test_tokenize_error_charged_to_budget_mode_invariant(self, tmp_path):
        files = _write_corpus(tmp_path, _sample_texts(60, seed=9))

        def run(**kw):
            chaos.uninstall()
            chaos.install(
                chaos.ChaosPlan(seed=11).site(
                    "data.tokenize_error", probability=1.0, max_count=3
                )
            )
            before = obs.counter("text_tokenize_errors_total").value
            pipe = TextPipeline(
                files, Tokenizer(), seq_len=64, batch_size=2, seed=1,
                max_bad_records=3, **kw
            )
            batches = _collect(pipe)
            return batches, obs.counter("text_tokenize_errors_total").value - before

        b0, s0 = run()
        b2, s2 = run(pack_workers=2)
        assert s0 == s2 == 3
        assert _streams_equal(b0, b2)
        assert obs.counter("chaos_fault_data_tokenize_error_total").value >= 6

    def test_pack_stall_is_charged_input_bound(self, tmp_path):
        files = _write_corpus(tmp_path, _sample_texts(80, seed=4))
        chaos.install(
            chaos.ChaosPlan(seed=2).site(
                "data.pack_stall", probability=1.0, max_count=None, delay_s=0.02
            )
        )
        snap0 = obs.snapshot()["counters"]

        def _d(name):
            return (
                obs.snapshot()["counters"].get(name, {}).get("value", 0.0)
                - snap0.get(name, {}).get("value", 0.0)
            )

        pipe = TextPipeline(
            files, Tokenizer(), seq_len=48, batch_size=2, seed=1, readahead=0
        )
        assert _collect(pipe)
        stall = _d("text_pack_stall_seconds_total")
        assert stall > 0, "pack_stall delay was not charged"
        bench = _load_bench()
        # the injected delay lands in parse time: the classifier must call
        # the run input-bound (decode_bound), not io/device bound
        assert (
            bench.classify_stalls(
                _d("data_producer_read_seconds_total"),
                _d("data_producer_parse_seconds_total"),
                0.0,  # producer never blocked on the queue in this drain
                _d("data_consumer_wait_seconds_total") + stall,
            )
            == "decode_bound"
        )
        assert _d("chaos_fault_data_pack_stall_total") > 0


def _load_bench():
    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("bench", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


class TestEstimatorLMFinetune:
    """The pipeline-API wiring: a DataFrame of text rows materialized via
    setTFRecordDir, a train_fn that fine-tunes a tiny LM by reading those
    shards through TextPipeline with a field-extracting Tokenizer, and the
    text_* metrics surfacing in the estimator's captured cluster metrics."""

    def test_finetune_through_tfrecord_dir(self, tmp_path):
        from tensorflowonspark_tpu import dfutil, pipeline
        from tensorflowonspark_tpu.backends.local import LocalSparkContext

        tfr_dir = str(tmp_path / "tfr")
        sc = LocalSparkContext(num_executors=2, task_timeout=300)
        try:
            texts = _sample_texts(64, seed=13)
            df = sc.createDataFrame([(t,) for t in texts], ["text"], 2)
            est = (
                pipeline.TFEstimator(
                    _lm_finetune_fn, {"steps": 4}, env={"JAX_PLATFORMS": "cpu"}
                )
                .setInputMapping({"text": "text"})
                .setEpochs(1)
                .setClusterSize(2)
                .setMasterNode(None)
                .setTFRecordDir(tfr_dir)
            )
            est.fit(df)
            assert dfutil.tfrecord.list_shards(tfr_dir), "shards not materialized"
            counters = est.cluster_metrics_["counters"]
            assert counters["text_sequences_packed_total"]["value"] > 0
            assert counters["text_tokens_packed_total"]["value"] > 0
            # the cluster-level gauge is a SUM across sources (aggregate.py
            # semantic) and include_driver=True folds in the driver's own
            # registry — which mid-suite carries whatever earlier in-process
            # tests left there. The per-node views are spawn-clean: each
            # executor's efficiency must be a real ratio in (0, 1].
            effs = [
                node["gauges"]["text_pack_efficiency"]["value"]
                for node in est.cluster_metrics_["nodes"].values()
                if "text_pack_efficiency" in node["gauges"]
            ]
            assert effs and all(0.0 < e <= 1.0 for e in effs), effs
        finally:
            sc.stop()


def _lm_finetune_fn(args, ctx):
    # module-level: must be picklable into the executor processes
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel, tfrecord
    from tensorflowonspark_tpu.data import TextPipeline, Tokenizer, shard_files
    from tensorflowonspark_tpu.models import transformer
    from tensorflowonspark_tpu.train import SyncDataParallel

    # drain the spark feed (InputMode.SPARK contract) while the real input
    # comes from the materialized TFRecord shards
    feed = ctx.get_data_feed(train_mode=True)

    batch = jax.device_count()  # dp=-1 mesh below: batch divides the mesh
    files = shard_files(
        tfrecord.list_shards(args.tfrecord_dir), ctx.num_workers, ctx.executor_id
    )
    pipe = TextPipeline(
        files, Tokenizer(kind="word", vocab_size=128, field="text"),
        seq_len=33, batch_size=batch, seed=ctx.executor_id, epochs=None,
        drop_remainder=True,
    )
    stream = iter(pipe)

    mesh = parallel.local_mesh({"dp": -1})
    model = transformer.create_model(
        mesh=mesh, vocab_size=128, d_model=16, n_layers=1, n_heads=2, d_ff=32,
        dtype="float32",
    )
    strategy = SyncDataParallel(mesh)
    optimizer = optax.adamw(1e-3)
    state = strategy.create_state(
        transformer.make_init_fn(model, sample_len=8), optimizer,
        jax.random.PRNGKey(0),
    )
    step = strategy.compile_train_step(
        transformer.make_loss_fn(model), optimizer, has_aux=True
    )
    losses = []
    for _ in range(int(args.steps)):
        state, metrics = step(state, strategy.shard_batch(next(stream)))
        losses.append(float(np.asarray(jax.device_get(metrics["loss"]))))
    stream.close()
    assert all(np.isfinite(losses)), losses
    while not feed.should_stop():
        feed.next_batch(16)


@pytest.mark.perf_smoke
class TestPerfSmokeLM:
    """The BENCH_MODE=lm shape in miniature: a tiny transformer fine-tunes
    through the packed loader and the train-vs-input-only pair must
    validate under the regime-aware band (train can never beat its own
    input path)."""

    def test_pair_validates(self, tmp_path):
        import time

        import jax
        import optax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.models import transformer
        from tensorflowonspark_tpu.train import SyncDataParallel

        bench = _load_bench()
        batch = jax.device_count()  # dp=-1 mesh: batch divides the mesh
        files = _write_corpus(tmp_path, _sample_texts(400, seed=21), shards=4)
        pipe = TextPipeline(
            files, Tokenizer(kind="word", vocab_size=256), seq_len=33,
            batch_size=batch, seed=0, epochs=None, prefetch_batches=4,
        )
        stream = iter(pipe)
        mesh = parallel.local_mesh({"dp": -1})
        strategy = SyncDataParallel(mesh)
        model = transformer.create_model(
            mesh=mesh, vocab_size=256, d_model=32, n_layers=2, n_heads=2,
            d_ff=64, dtype="float32",
        )
        optimizer = optax.adamw(1e-3)
        state = strategy.create_state(
            transformer.make_init_fn(model, sample_len=8), optimizer,
            jax.random.PRNGKey(0),
        )
        step = strategy.compile_train_step(
            transformer.make_loss_fn(model), optimizer, has_aux=True
        )
        batches = (strategy.shard_batch(b) for b in stream)
        state, metrics = step(state, next(batches))  # compile
        float(np.asarray(jax.device_get(metrics["loss"])))
        d = 6

        def no_compute():
            jax.block_until_ready(next(batches)["tokens"])
            t0 = time.perf_counter()
            buf = None
            for _ in range(d):
                buf = next(batches)
            jax.block_until_ready(buf["tokens"])
            return d / (time.perf_counter() - t0)

        def train():
            nonlocal state, metrics
            state, metrics = step(state, next(batches))
            float(np.asarray(jax.device_get(metrics["loss"])))
            t0 = time.perf_counter()
            for _ in range(d):
                state, metrics = step(state, next(batches))
            float(np.asarray(jax.device_get(metrics["loss"])))
            return d / (time.perf_counter() - t0)

        no_compute(), train()  # warm-up pair, discarded
        nc, tr = no_compute(), train()
        stream.close()
        # regime-aware validity: train <= 1.10 * input-path always holds
        valid, _invalid = bench.partition_pairs(
            [nc], [tr], min_ratio=0.0
        )
        assert valid, "train block ({:.1f}/s) beat its own input path ({:.1f}/s)".format(tr, nc)
        assert np.isfinite(float(np.asarray(jax.device_get(metrics["loss"]))))
