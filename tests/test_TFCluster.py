"""End-to-end cluster lifecycle tests on the local multi-process backend
(mirrors reference test/test_TFCluster.py: single-node fn, InputMode.SPARK
inference round trip, feed-error surfacing, late-error surfacing)."""

import os

import pytest

from tensorflowonspark_tpu import TFCluster
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext, TaskError

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


@pytest.fixture
def sc():
    ctx = LocalSparkContext(num_executors=2, task_timeout=120)
    yield ctx
    ctx.stop()


def fn_write_marker(args, ctx):
    # runs in the jax child of each node; proves InputMode.TENSORFLOW dispatch
    path = os.path.join(args["out_dir"], "node-{}-{}.txt".format(ctx.job_name, ctx.task_index))
    with open(path, "w") as f:
        f.write("worker_num={} num_workers={}".format(ctx.executor_id, ctx.num_workers))


def fn_square_feed(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(16)
        if batch:
            feed.batch_results([x * x for x in batch])


def fn_square_feed_jax(args, ctx):
    import jax.numpy as jnp

    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(16, as_numpy=True)
        if batch.size:
            feed.batch_results([int(v) for v in jnp.square(batch)])


def fn_immediate_error(args, ctx):
    raise RuntimeError("deliberate failure before consuming feed")


def fn_late_error(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        feed.next_batch(16)
    raise RuntimeError("deliberate failure after feeding finished")


def fn_consume_all(args, ctx):
    feed = ctx.get_data_feed()
    while not feed.should_stop():
        feed.next_batch(16)


class TestTFCluster:
    def test_single_node_tensorflow_mode(self, sc, tmp_path):
        cluster = TFCluster.run(
            sc, fn_write_marker, {"out_dir": str(tmp_path)}, num_executors=2,
            input_mode=InputMode.TENSORFLOW, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        cluster.shutdown(timeout=120)
        files = sorted(os.listdir(str(tmp_path)))
        assert files == ["node-worker-0.txt", "node-worker-1.txt"]

    def test_inference_roundtrip(self, sc):
        cluster = TFCluster.run(
            sc, fn_square_feed, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        data = sc.parallelize(range(100), 4)
        results = cluster.inference(data).collect()
        cluster.shutdown(timeout=120)
        assert len(results) == 100
        assert sorted(results) == sorted(x * x for x in range(100))

    def test_inference_roundtrip_jax(self, sc):
        cluster = TFCluster.run(
            sc, fn_square_feed_jax, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        data = sc.parallelize(range(40), 2)
        results = cluster.inference(data, feed_timeout=300).collect()
        cluster.shutdown(timeout=300)
        assert sorted(results) == sorted(x * x for x in range(40))

    def test_feed_error_surfaces(self, sc):
        cluster = TFCluster.run(
            sc, fn_immediate_error, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        with pytest.raises(TaskError, match="deliberate failure before"):
            cluster.train(sc.parallelize(range(1000), 4), feed_timeout=30)
        with pytest.raises(RuntimeError):
            cluster.shutdown(timeout=120)

    def test_late_error_surfaces_at_shutdown(self, sc):
        cluster = TFCluster.run(
            sc, fn_late_error, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        cluster.train(sc.parallelize(range(64), 2), feed_timeout=60)
        with pytest.raises((TaskError, RuntimeError), match="after feeding finished"):
            cluster.shutdown(timeout=120)

    def test_train_and_clean_shutdown(self, sc):
        cluster = TFCluster.run(
            sc, fn_consume_all, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        cluster.train(sc.parallelize(range(200), 4), num_epochs=2, feed_timeout=60)
        cluster.shutdown(timeout=120)

    def test_shutdown_falls_back_to_spark_tasks(self, sc):
        """With the driver->executor TCP route severed (NAT'd clusters), the
        end-of-feed markers arrive via scattered Spark shutdown tasks over
        the executor-LOCAL channels (VERDICT r2 item 5; reference
        TFCluster.py:174-176)."""
        cluster = TFCluster.run(
            sc, fn_consume_all, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        cluster.train(sc.parallelize(range(100), 2), num_epochs=1, feed_timeout=60)
        # sever the TCP route: port 1 refuses instantly on loopback
        for row in cluster.cluster_info:
            row["manager_addr"] = ("127.0.0.1", 1)
        cluster.shutdown(grace_secs=1, timeout=120)


class TestClusterTemplate:
    def test_role_order(self):
        t = TFCluster.build_cluster_template(5, num_ps=1, master_node="chief", eval_node=True)
        assert t[0] == ("ps", 0)
        assert t[1] == ("chief", 0)
        assert t[2] == ("evaluator", 0)
        assert t[3] == ("worker", 0)
        assert t[4] == ("worker", 1)

    def test_too_small(self):
        with pytest.raises(ValueError):
            TFCluster.build_cluster_template(1, num_ps=1, master_node=None)

    def test_bogus_master_node_rejected(self):
        with pytest.raises(ValueError, match="master_node"):
            TFCluster.build_cluster_template(2, master_node="None")
