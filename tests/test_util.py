import os
import subprocess
import sys

from tensorflowonspark_tpu import util


def test_import_configures_no_logging():
    """Importing the library must not touch the root logger (the import-time
    basicConfig this repo used to ship hijacked logging from every host
    application). Run in a fresh interpreter: this process imported the
    package long ago."""
    code = (
        "import logging\n"
        "before = list(logging.getLogger().handlers)\n"
        "level = logging.getLogger().level\n"
        "import tensorflowonspark_tpu\n"
        "import tensorflowonspark_tpu.util\n"
        "assert list(logging.getLogger().handlers) == before, 'import added handlers'\n"
        "assert logging.getLogger().level == level, 'import changed root level'\n"
        "print('clean')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert out.returncode == 0, out.stderr
    assert "clean" in out.stdout


def test_setup_logging_configures_root():
    # basicConfig is a no-op on an already-configured root, so check in a
    # subprocess where the root is pristine
    code = (
        "import logging\n"
        "from tensorflowonspark_tpu import util\n"
        "util.setup_logging(level=logging.DEBUG)\n"
        "root = logging.getLogger()\n"
        "assert root.level == logging.DEBUG\n"
        "assert root.handlers, 'setup_logging installed no handler'\n"
        "fmt = root.handlers[0].formatter._fmt\n"
        "assert fmt == util.LOG_FORMAT, fmt\n"
        "print('configured')\n"
    )
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=120,
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
    )
    assert out.returncode == 0, out.stderr
    assert "configured" in out.stdout


def test_ip_address_is_string():
    ip = util.get_ip_address()
    assert isinstance(ip, str) and ip.count(".") == 3


def test_find_in_path(tmp_path):
    f = tmp_path / "tool"
    f.write_text("x")
    path = os.pathsep.join(["/nonexistent", str(tmp_path)])
    assert util.find_in_path(path, "tool") == str(f)
    assert util.find_in_path(path, "missing") is False


def test_executor_state_roundtrip(tmp_path):
    state = {"executor_id": 3, "address": ["10.0.0.1", 4000], "authkey": b"\x01\x02"}
    util.write_executor_state(state, cwd=str(tmp_path))
    got = util.read_executor_state(cwd=str(tmp_path))
    assert got["executor_id"] == 3
    assert got["address"] == ["10.0.0.1", 4000]
    assert got["authkey"] == b"\x01\x02"


def test_read_executor_state_missing(tmp_path):
    assert util.read_executor_state(cwd=str(tmp_path)) is None


def test_find_free_port():
    p = util.find_free_port()
    assert 0 < p < 65536
