import os

from tensorflowonspark_tpu import util


def test_ip_address_is_string():
    ip = util.get_ip_address()
    assert isinstance(ip, str) and ip.count(".") == 3


def test_find_in_path(tmp_path):
    f = tmp_path / "tool"
    f.write_text("x")
    path = os.pathsep.join(["/nonexistent", str(tmp_path)])
    assert util.find_in_path(path, "tool") == str(f)
    assert util.find_in_path(path, "missing") is False


def test_executor_state_roundtrip(tmp_path):
    state = {"executor_id": 3, "address": ["10.0.0.1", 4000], "authkey": b"\x01\x02"}
    util.write_executor_state(state, cwd=str(tmp_path))
    got = util.read_executor_state(cwd=str(tmp_path))
    assert got["executor_id"] == 3
    assert got["address"] == ["10.0.0.1", 4000]
    assert got["authkey"] == b"\x01\x02"


def test_read_executor_state_missing(tmp_path):
    assert util.read_executor_state(cwd=str(tmp_path)) is None


def test_find_free_port():
    p = util.find_free_port()
    assert 0 < p < 65536
