"""Fixture tests for the jit-host-sync and jit-purity rules: each bad
snippet must fire, each good twin must stay clean — proving the rule is
live, not vacuously passing on the repo."""

import textwrap

from tosa_testutil import run_rule


def _src(s):
    return textwrap.dedent(s).lstrip()


class TestJitHostSync:
    def test_item_inside_jit_fires(self):
        findings = run_rule("jit-host-sync", _src("""
            import jax

            @jax.jit
            def step(x):
                y = x * 2
                return y.item()
        """))
        assert len(findings) == 1
        assert "item" in findings[0].message
        assert findings[0].line == 6

    def test_float_builtin_inside_pjit_fires(self):
        findings = run_rule("jit-host-sync", _src("""
            from jax.experimental.pjit import pjit

            @pjit
            def step(x):
                loss = x.sum()
                return float(loss)
        """))
        assert len(findings) == 1

    def test_block_until_ready_in_wrapped_fn_fires(self):
        findings = run_rule("jit-host-sync", _src("""
            import jax

            def step(x):
                return (x + 1).block_until_ready()

            fast_step = jax.jit(step)
        """))
        assert len(findings) == 1

    def test_sync_outside_traced_code_is_clean(self):
        findings = run_rule("jit-host-sync", _src("""
            import jax

            @jax.jit
            def step(x):
                return x * 2

            def host_loop(x):
                out = step(x)
                return float(out.item())
        """))
        assert findings == []

    def test_pure_shard_map_body_is_clean(self):
        findings = run_rule("jit-host-sync", _src("""
            import functools
            import jax
            from jax.experimental.shard_map import shard_map

            def body(x):
                return jax.lax.psum(x, "i")

            mapped = shard_map(functools.partial(body), mesh=None, in_specs=(), out_specs=())
        """))
        assert findings == []


class TestJitPurity:
    def test_obs_counter_inside_jit_fires(self):
        findings = run_rule("jit-purity", _src("""
            import jax
            from tensorflowonspark_tpu import obs

            @jax.jit
            def step(state, x):
                obs.counter("steps_total").inc()
                return state + x
        """))
        assert len(findings) == 1
        assert "obs.counter" in findings[0].message

    def test_closure_mutation_inside_jit_fires(self):
        findings = run_rule("jit-purity", _src("""
            import jax

            stats = {}

            @jax.jit
            def step(x):
                stats["last"] = x
                return x
        """))
        assert len(findings) == 1
        assert "stats" in findings[0].message

    def test_wall_clock_inside_jit_fires(self):
        findings = run_rule("jit-purity", _src("""
            import jax
            import time

            @jax.jit
            def step(x):
                t0 = time.time()
                return x + t0
        """))
        assert len(findings) == 1
        assert "time.time" in findings[0].message

    def test_global_statement_inside_jit_fires(self):
        findings = run_rule("jit-purity", _src("""
            import jax

            count = 0

            @jax.jit
            def step(x):
                global count
                count = count + 1
                return x
        """))
        assert any("global" in f.message for f in findings)

    def test_pure_step_with_local_mutation_is_clean(self):
        # mutating values the function itself binds is fine: that's not
        # closed-over state, it's how jaxprs are built up
        findings = run_rule("jit-purity", _src("""
            import jax

            @jax.jit
            def step(state, batch):
                acc = {}
                acc["loss"] = (state - batch).sum()
                new_state = state - 0.1 * batch
                return new_state, acc
        """))
        assert findings == []

    def test_effects_in_host_loop_are_clean(self):
        findings = run_rule("jit-purity", _src("""
            import jax
            import time
            from tensorflowonspark_tpu import obs

            @jax.jit
            def step(x):
                return x * 2

            def train(xs):
                t0 = time.time()
                for x in xs:
                    step(x)
                    obs.counter("steps_total").inc()
                return time.time() - t0
        """))
        assert findings == []
