"""Tracing plane: flight-recorder ring, trace propagation, shard merging.

Covers the PR 15 contract end to end in one process: crash-safe CRC
framing (torn-tail prefix recovery, the registry-journal idiom), segment
rotation and ring pruning, fork-safe shard reopening, span identity
threading through ``obs.span``, NTP-style clock observation, and the
tracemerge Chrome-trace output — including the two-host skewed-clock
merge the whole plane exists for.
"""

import json
import os
import urllib.request

import pytest

from tensorflowonspark_tpu import chaos, obs
from tensorflowonspark_tpu.obs import exporter, flight, registry, tracemerge, tracing


@pytest.fixture
def trace_root(tmp_path, monkeypatch):
    root = str(tmp_path / "traces")
    tracing.reset()
    monkeypatch.setenv(flight.TRACE_DIR_ENV, root)
    yield root
    tracing.reset()


def _shard_records(root):
    """All records across all shards under ``root``, with their shard dir."""
    out = []
    for shard in flight.list_shards(root):
        records, torn = flight.read_shard(shard)
        out.append((shard, records, torn))
    return out


class TestFlightRecorder:
    def test_append_roundtrip_with_meta_header(self, tmp_path):
        rec = flight.FlightRecorder(str(tmp_path), "unit")
        rec.append({"kind": "event", "name": "hello", "ts": 1.0})
        rec.close()
        records, torn = flight.read_shard(rec.shard_dir)
        assert torn == 0
        assert records[0]["kind"] == "meta"
        assert records[0]["proc"] == "unit"
        assert records[-1] == {"kind": "event", "name": "hello", "ts": 1.0}

    def test_rotation_seals_and_prunes_oldest(self, tmp_path):
        rec = flight.FlightRecorder(
            str(tmp_path), "unit", max_segment_bytes=256, max_segments=2
        )
        for i in range(100):
            rec.append({"kind": "event", "name": "e{}".format(i), "ts": float(i)})
        rec.close()
        names = sorted(os.listdir(rec.shard_dir))
        sealed = [n for n in names if n.endswith(".jsonl")]
        assert len(sealed) <= 2  # ring bound holds
        assert sum(1 for n in names if n.endswith(".open")) == 1
        records, torn = flight.read_shard(rec.shard_dir)
        assert torn == 0
        # the *newest* history survives pruning
        kept = [r["name"] for r in records if r.get("kind") == "event"]
        assert kept[-1] == "e99"
        assert "e0" not in kept

    def test_torn_open_tail_keeps_intact_prefix(self, tmp_path):
        rec = flight.FlightRecorder(str(tmp_path), "unit")
        rec.append({"kind": "event", "name": "kept", "ts": 1.0})
        rec.append({"kind": "event", "name": "also-kept", "ts": 2.0})
        rec.close()
        (open_seg,) = [
            n for n in os.listdir(rec.shard_dir) if n.endswith(".open")
        ]
        path = os.path.join(rec.shard_dir, open_seg)
        with open(path, "a", encoding="utf-8") as f:
            f.write('deadbeef {"kind":"event","name":"torn"')  # no newline, bad crc
        records, torn = flight.read_shard(rec.shard_dir)
        assert torn == 1
        assert [r["name"] for r in records if r.get("kind") == "event"] == [
            "kept", "also-kept",
        ]

    def test_corrupt_mid_segment_line_discards_suffix(self, tmp_path):
        # After a framing failure, alignment can't be trusted: prefix only.
        rec = flight.FlightRecorder(str(tmp_path), "unit")
        rec.append({"kind": "event", "name": "a", "ts": 1.0})
        rec.close()
        (open_seg,) = [n for n in os.listdir(rec.shard_dir) if n.endswith(".open")]
        path = os.path.join(rec.shard_dir, open_seg)
        with open(path, "a", encoding="utf-8") as f:
            f.write("garbage line\n")
            f.write(flight._frame(json.dumps({"kind": "event", "name": "b"})))
        records, torn = flight.read_shard(rec.shard_dir)
        assert torn == 2
        assert [r.get("name") for r in records if r.get("kind") == "event"] == ["a"]

    def test_dump_appends_marker(self, tmp_path):
        rec = flight.FlightRecorder(str(tmp_path), "unit")
        rec.dump("chaos:feed.stall")
        rec.close()
        records, _ = flight.read_shard(rec.shard_dir)
        dumps = [r for r in records if r.get("kind") == "dump"]
        assert len(dumps) == 1
        assert dumps[0]["reason"] == "chaos:feed.stall"

    def test_forked_child_opens_own_shard_without_double_flush(self, tmp_path):
        rec = flight.FlightRecorder(str(tmp_path), "unit")
        rec.append({"kind": "event", "name": "parent-before", "ts": 1.0})
        pid = os.fork()
        if pid == 0:
            # child: the inherited recorder must re-home to a new shard
            try:
                rec.append({"kind": "event", "name": "child", "ts": 2.0})
                rec.close()
                os._exit(0)
            except BaseException:
                os._exit(1)
        _, status = os.waitpid(pid, 0)
        assert os.waitstatus_to_exitcode(status) == 0
        rec.append({"kind": "event", "name": "parent-after", "ts": 3.0})
        rec.close()
        shards = {os.path.basename(s): s for s in flight.list_shards(str(tmp_path))}
        assert len(shards) == 2  # parent shard + child shard
        names_by_shard = {
            base: [r.get("name") for r in flight.read_shard(path)[0]
                   if r.get("kind") == "event"]
            for base, path in shards.items()
        }
        parent_base = "{}-{}-unit".format(
            __import__("socket").gethostname(), os.getpid()
        )
        assert names_by_shard[parent_base] == ["parent-before", "parent-after"]
        (child_base,) = [b for b in shards if b != parent_base]
        # child's shard holds ONLY its own write — the parent's buffered
        # bytes were abandoned, not flushed into either file
        assert names_by_shard[child_base] == ["child"]


class TestTraceContext:
    def test_mint_is_idempotent_and_returns_env(self, trace_root):
        env1 = tracing.mint(proc="driver")
        env2 = tracing.mint(proc="driver")
        assert env1[tracing.TRACE_ENV] == env2[tracing.TRACE_ENV] == tracing.trace_id()
        assert env1[tracing.DIR_ENV] == trace_root
        assert len(env1[tracing.TRACE_ENV]) == 32

    def test_nested_spans_record_parent_chain(self, trace_root):
        tracing.mint(proc="driver")
        with obs.span("step_fetch"):
            with obs.span("step_compute"):
                pass
        flight.current().close()
        ((_, records, _),) = _shard_records(trace_root)
        spans = {r["name"]: r for r in records if r.get("kind") == "span"}
        assert set(spans) == {"step_fetch", "step_compute"}
        assert spans["step_compute"]["parent"] == spans["step_fetch"]["span"]
        assert spans["step_fetch"]["trace"] == tracing.trace_id()
        # the outer span's parent is the propagated root span
        assert spans["step_fetch"]["parent"] == tracing.current_span_id()

    def test_install_from_env_adopts_propagated_context(self, trace_root):
        env = {
            tracing.TRACE_ENV: "ab" * 16,
            tracing.PARENT_ENV: "cd" * 8,
            tracing.DIR_ENV: trace_root,
        }
        assert tracing.install_from_env("executor0", env=env)
        assert tracing.trace_id() == "ab" * 16
        assert os.environ[tracing.TRACE_ENV] == "ab" * 16
        tracing.event("lease_expired", executor=0)
        flight.current().close()
        ((shard, records, _),) = _shard_records(trace_root)
        assert "executor0" in os.path.basename(shard)
        (evt,) = [r for r in records if r.get("kind") == "event"]
        assert evt["trace"] == "ab" * 16
        assert evt["parent"] == "cd" * 8

    def test_observe_clock_keeps_min_rtt_sample(self, trace_root):
        tracing.mint(proc="executor")
        assert tracing.observe_clock(105.0, t0=100.0, t1=100.4) is not None
        first = tracing.clock_offset()
        # higher-RTT sample is rejected, offset unchanged
        assert tracing.observe_clock(200.0, t0=100.0, t1=101.0) is None
        assert tracing.clock_offset() == first
        # tighter RTT wins
        assert tracing.observe_clock(105.0, t0=100.0, t1=100.1) is not None
        assert abs(tracing.clock_offset() - (105.0 - 100.05)) < 1e-9
        flight.current().close()
        ((_, records, _),) = _shard_records(trace_root)
        clocks = [r for r in records if r.get("kind") == "clock"]
        assert len(clocks) == 2  # the rejected sample was never journaled

    def test_record_span_lands_on_named_track(self, trace_root):
        tracing.mint(proc="driver")
        tracing.record_span("comm_allreduce", ts=10.0, dur_s=0.5, track="comm")
        flight.current().close()
        ((_, records, _),) = _shard_records(trace_root)
        (span,) = [r for r in records if r.get("kind") == "span"]
        assert span["track"] == "comm"
        assert span["ts"] == 10.0 and span["dur_s"] == 0.5

    def test_chaos_record_dumps_flight_ring(self, trace_root):
        tracing.mint(proc="driver")
        chaos._record("feed.stall")
        flight.current().close()
        ((_, records, _),) = _shard_records(trace_root)
        dumps = [r for r in records if r.get("kind") == "dump"]
        assert any(d["reason"] == "chaos:feed.stall" for d in dumps)


class TestTraceMerge:
    def _make_two_skewed_shards(self, root):
        """A driver shard and an executor shard whose local clock runs 5 s
        behind the driver's; causal order is driver a -> executor b -> driver c."""
        drv = flight.FlightRecorder(root, "driver", trace_id="t" * 32)
        drv.append({"kind": "span", "name": "reservation_roundtrip",
                    "trace": "t" * 32, "span": "s1", "parent": None,
                    "ts": 1000.0, "dur_s": 0.5, "ok": True, "tid": 1})
        drv.append({"kind": "event", "name": "lease_expired",
                    "trace": "t" * 32, "span": "e1", "parent": "s1", "ts": 1002.0})
        drv.close()
        exe = flight.FlightRecorder(root, "executor0", trace_id="t" * 32)
        exe.set_clock_offset(5.0, rtt=0.01)  # local + 5.0 == driver time
        # locally 996.0 == 1001.0 driver time: between the two driver marks
        exe.append({"kind": "span", "name": "node_launch",
                    "trace": "t" * 32, "span": "s2", "parent": "s1",
                    "ts": 996.0, "dur_s": 0.25, "ok": True, "tid": 2})
        exe.close()
        return drv, exe

    def test_skewed_clocks_merge_into_ordered_timeline(self, tmp_path):
        root = str(tmp_path)
        self._make_two_skewed_shards(root)
        trace, summary = tracemerge.merge_directory(root)
        assert tracemerge.validate_chrome_trace(trace) == []
        assert summary["trace_ids"] == ["t" * 32]
        offsets = {s["shard"].split("-")[-1]: s["clock_offset_s"]
                   for s in summary["shards"]}
        assert offsets["driver"] == 0.0
        assert offsets["executor0"] == 5.0
        begins = [(e["ts"], e["name"]) for e in trace["traceEvents"]
                  if e.get("ph") in ("B", "i") and e.get("cat") != "dump"]
        begins.sort()
        assert [n for _, n in begins] == [
            "reservation_roundtrip", "node_launch", "lease_expired",
        ]
        # the executor span landed at driver time 1001.0
        assert begins[1][0] == pytest.approx(1001.0 * 1e6)

    def test_cli_check_and_requirements(self, tmp_path, capsys):
        root = str(tmp_path)
        self._make_two_skewed_shards(root)
        rc = tracemerge.main([
            "--dir", root, "--check",
            "--require-span", "node_launch",
            "--require-event", "lease_expired",
            "--require-same-trace",
        ])
        assert rc == 0
        assert os.path.isfile(os.path.join(root, "trace.json"))
        rc = tracemerge.main(["--dir", root, "--require-event", "never_happened"])
        assert rc == 1
        assert "never_happened" in capsys.readouterr().err

    def test_validate_rejects_unmatched_pairs(self):
        bad = {"traceEvents": [
            {"ph": "B", "name": "a", "pid": 1, "tid": 1, "ts": 1.0},
            {"ph": "E", "name": "b", "pid": 1, "tid": 1, "ts": 2.0},
        ]}
        problems = tracemerge.validate_chrome_trace(bad)
        assert any("does not match open B" in p for p in problems)
        dangling = {"traceEvents": [
            {"ph": "B", "name": "a", "pid": 1, "tid": 1, "ts": 1.0},
        ]}
        assert any(
            "unclosed B" in p
            for p in tracemerge.validate_chrome_trace(dangling)
        )

    def test_overlap_fraction_from_drawn_geometry(self):
        events = [
            {"ph": "X", "name": "comm_allreduce", "ts": 0.0, "dur": 10.0},
            {"ph": "X", "name": "comm_window", "ts": 2.0, "dur": 4.0},
            {"ph": "X", "name": "comm_window", "ts": 4.0, "dur": 4.0},
        ]
        # windows [2,6] and [4,8] merge to [2,8]: 6 of 10 units hidden
        assert tracemerge.overlap_fraction(events) == pytest.approx(0.6)
        assert tracemerge.overlap_fraction([]) is None


class TestRegistryAndExporter:
    def test_event_eviction_is_counted(self, monkeypatch):
        monkeypatch.setattr(registry, "MAX_EVENTS", 3)
        reg = registry.Registry(enabled=True)
        for i in range(5):
            reg.add_event({"i": i})
        snap = reg.snapshot()
        assert snap["counters"]["obs_events_dropped_total"]["value"] == 2
        assert [e["i"] for e in snap["events"]] == [2, 3, 4]

    def test_quantile_endpoint_and_trace_endpoint(self, trace_root):
        tracing.mint(proc="driver")
        with obs.span("step_compute"):
            pass
        reg = registry.Registry(enabled=True)
        h = reg.histogram("toy_seconds", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 3.0, 3.5):
            h.observe(v)
        srv = exporter.MetricsHTTPServer(
            reg.snapshot, host="127.0.0.1", port=0
        ).start()
        try:
            base = "http://127.0.0.1:{}".format(srv.address[1])
            body = json.loads(
                urllib.request.urlopen(base + "/histograms.json", timeout=10).read()
            )
            assert body["toy_seconds"]["count"] == 4
            assert 0.0 < body["toy_seconds"]["p50"] <= 2.0
            assert 2.0 < body["toy_seconds"]["p99"] <= 4.0
            trace_body = json.loads(
                urllib.request.urlopen(base + "/trace", timeout=10).read()
            )
            assert trace_body["torn"] == 0
            assert any(
                r.get("kind") == "span" and r.get("name") == "step_compute"
                for r in trace_body["records"]
            )
        finally:
            srv.stop()

    def test_histogram_quantile_interpolates(self):
        snap = {"count": 10, "sum": 0.0,
                "buckets": [[1.0, 5], [2.0, 5]]}
        assert exporter.histogram_quantile(snap, 0.5) == pytest.approx(1.0)
        assert exporter.histogram_quantile(snap, 0.75) == pytest.approx(1.5)
        assert exporter.histogram_quantile({"count": 0, "buckets": []}, 0.5) is None
