"""TFRecord codec + dfutil round-trip tests (reference test_dfutil.py:30-73
round-tripped a 6-type row through the hadoop jar; same semantics here, plus
cross-validation of the hand-rolled Example codec against real TF protos)."""

import numpy as np
import pytest

from tensorflowonspark_tpu import dfutil, tfrecord
from tensorflowonspark_tpu.backends.local import LocalSparkContext


@pytest.fixture(scope="module")
def sc():
    ctx = LocalSparkContext(num_executors=2, task_timeout=120)
    yield ctx
    ctx.stop()


class TestTFRecordCodec:
    def test_example_roundtrip(self):
        features = {
            "an_int": [42],
            "floats": [1.5, -2.25],
            "a_string": ["hello"],
            "raw": [b"\x00\x01\xff"],
        }
        buf = tfrecord.encode_example(features)
        decoded = tfrecord.decode_example(buf)
        assert decoded["an_int"] == ("int64", [42])
        assert decoded["floats"][0] == "float"
        np.testing.assert_allclose(decoded["floats"][1], [1.5, -2.25])
        assert decoded["a_string"] == ("bytes", [b"hello"])
        assert decoded["raw"] == ("bytes", [b"\x00\x01\xff"])

    def test_negative_int64(self):
        buf = tfrecord.encode_example({"x": [-7, 0, 123456789012345]})
        assert tfrecord.decode_example(buf)["x"] == ("int64", [-7, 0, 123456789012345])

    def test_record_file_roundtrip(self, tmp_path):
        path = str(tmp_path / "part-r-00000")
        records = [b"first", b"second record", b""]
        with tfrecord.TFRecordWriter(path) as w:
            for r in records:
                w.write(r)
        assert list(tfrecord.read_records(path)) == records

    def test_corrupt_crc_detected(self, tmp_path):
        path = str(tmp_path / "part-r-00000")
        with tfrecord.TFRecordWriter(path) as w:
            w.write(b"payload-bytes")
        raw = bytearray(open(path, "rb").read())
        raw[14] ^= 0xFF  # flip a payload byte
        open(path, "wb").write(bytes(raw))
        with pytest.raises(IOError, match="corrupt"):
            list(tfrecord.read_records(path))

    def test_cross_validate_against_tensorflow(self):
        """Our wire bytes must parse with TF's own proto class, and vice
        versa (TF is available in this image for validation only)."""
        tf = pytest.importorskip("tensorflow")
        features = {"i": [1, -2], "f": [0.5], "s": [b"abc"]}
        ours = tfrecord.encode_example(features)
        ex = tf.train.Example.FromString(ours)
        assert list(ex.features.feature["i"].int64_list.value) == [1, -2]
        assert list(ex.features.feature["s"].bytes_list.value) == [b"abc"]
        np.testing.assert_allclose(list(ex.features.feature["f"].float_list.value), [0.5])

        theirs = tf.train.Example(
            features=tf.train.Features(
                feature={
                    "i": tf.train.Feature(int64_list=tf.train.Int64List(value=[9, -9])),
                    "s": tf.train.Feature(bytes_list=tf.train.BytesList(value=[b"xyz"])),
                    "f": tf.train.Feature(float_list=tf.train.FloatList(value=[2.5, 3.5])),
                }
            )
        ).SerializeToString()
        decoded = tfrecord.decode_example(theirs)
        assert decoded["i"] == ("int64", [9, -9])
        assert decoded["s"] == ("bytes", [b"xyz"])
        np.testing.assert_allclose(decoded["f"][1], [2.5, 3.5])


class TestDFUtil:
    def test_dataframe_roundtrip(self, sc, tmp_path):
        out = str(tmp_path / "tfr")
        rows = [
            (i, float(i) * 1.5, "name-{}".format(i), [float(i), float(i + 1)], b"\x01\x02")
            for i in range(20)
        ]
        df = sc.createDataFrame(rows, ["idx", "score", "name", "vec", "blob"], 4)
        dfutil.saveAsTFRecords(df, out, binary_features=["blob"])

        df2 = dfutil.loadTFRecords(sc, out, binary_features=["blob"])
        assert dfutil.isLoadedDF(df2)
        assert sorted(df2.columns) == ["blob", "idx", "name", "score", "vec"]
        got = sorted(df2.collect(), key=lambda r: r[df2.columns.index("idx")])
        ci = {c: i for i, c in enumerate(df2.columns)}
        for i, row in enumerate(got):
            assert row[ci["idx"]] == i
            assert abs(row[ci["score"]] - i * 1.5) < 1e-6
            assert row[ci["name"]] == "name-{}".format(i)
            np.testing.assert_allclose(row[ci["vec"]], [i, i + 1])
            assert row[ci["blob"]] == b"\x01\x02"

    def test_infer_schema(self):
        example = tfrecord.decode_example(
            tfrecord.encode_example({"a": [1], "b": [1.0, 2.0], "c": ["s"]})
        )
        schema = dfutil.infer_schema(example)
        assert schema["a"] == {"kind": "int64", "multi": False}
        assert schema["b"] == {"kind": "float", "multi": True}
        assert schema["c"] == {"kind": "string", "multi": False}


class TestTFParallel:
    def test_independent_instances(self, sc, tmp_path):
        from tensorflowonspark_tpu import TFParallel

        marker_dir = str(tmp_path)

        def fn(args, ctx):
            with open("{}/done-{}".format(args["dir"], ctx.executor_id), "w") as f:
                f.write(str(ctx.num_workers))

        done = TFParallel.run(sc, fn, {"dir": marker_dir}, 2, env={"JAX_PLATFORMS": "cpu"})
        assert sorted(done) == [0, 1]
        import os

        assert sorted(os.listdir(marker_dir)) == ["done-0", "done-1"]


class TestCompat:
    def test_shims(self, tmp_path):
        from tensorflowonspark_tpu import compat

        compat.disable_auto_shard(None)
        # every process participates in export (orbax collective save), chief
        # or not — is_chief is source-compat only
        path = compat.export_saved_model(
            {"w": np.zeros((2,))}, str(tmp_path / "exp"), is_chief=False
        )
        assert path and (tmp_path / "exp").exists()
        assert isinstance(compat.is_tpu_available(), bool)

    def test_shard_overwrite_is_idempotent(self, tmp_path):
        """Retried partition writes must overwrite, not duplicate."""
        sc2 = LocalSparkContext(num_executors=1, task_timeout=60)
        try:
            out = str(tmp_path / "t")
            df = sc2.createDataFrame([(1,), (2,)], ["v"], 1)
            dfutil.saveAsTFRecords(df, out)
            dfutil.saveAsTFRecords(df, out)  # simulate a retry
            assert len(tfrecord.list_shards(out)) == 1
            df2 = dfutil.loadTFRecords(sc2, out)
            assert df2.count() == 2
        finally:
            sc2.stop()


class TestRemoteFS:
    """fsspec-routed TFRecord IO (VERDICT round-1 item 9): the reference
    reached HDFS through the hadoop InputFormat jar (dfutil.py:39-65); here
    any fsspec scheme works. memory:// proves the URI plumbing in-process
    (it is per-process, so the executor-distributed dfutil path is proven
    over a file:// URI instead)."""

    def test_tfrecord_roundtrip_memory_fs(self):
        from tensorflowonspark_tpu import tfrecord

        base = "memory://tos-test/shards"
        tfrecord.write_shard(base + "/part-00000", [{"x": [1, 2]}, {"x": [3]}])
        tfrecord.write_shard(base + "/part-00001", [{"x": [4]}])
        shards = tfrecord.list_shards(base)
        assert [s.rsplit("/", 1)[-1] for s in shards] == ["part-00000", "part-00001"]
        rows = [ex["x"][1] for s in shards for ex in tfrecord.read_examples(s)]
        assert rows == [[1, 2], [3], [4]]

    def test_tfrecord_rename_commit_memory_fs(self):
        from tensorflowonspark_tpu import tfrecord

        tmp = "memory://tos-test/commit/part-00000.abc.tmp"
        tfrecord.write_shard(tmp, [{"y": [7]}])
        tfrecord.rename(tmp, "memory://tos-test/commit/part-00000")
        shards = tfrecord.list_shards("memory://tos-test/commit")
        assert len(shards) == 1 and shards[0].endswith("part-00000")

    def test_dfutil_roundtrip_file_uri(self, sc, tmp_path):
        from tensorflowonspark_tpu import dfutil

        out = "file://" + str(tmp_path / "uri_shards")
        df = sc.createDataFrame([(i, float(i) / 2) for i in range(20)], ["a", "b"], 2)
        dfutil.saveAsTFRecords(df, out)
        loaded = dfutil.loadTFRecords(sc, out)
        assert sorted(loaded.collect()) == [(i, float(i) / 2) for i in range(20)]
        assert dfutil.isLoadedDF(loaded)
