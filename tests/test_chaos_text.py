"""Text-plane chaos sites exercised through a live cluster: the seeded plan
propagates into the spawned jax children, ``data.tokenize_error`` skips are
charged against ``max_bad_records`` without corrupting the stream, a
``data.pack_stall`` delay lands in the pack stage's timed region, and every
fault plus the ``text_*`` accounting travels back through the merged
``TFCluster.metrics()`` snapshot."""

import time

import pytest

from tensorflowonspark_tpu import TFCluster, chaos
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext

pytestmark = pytest.mark.chaos

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


@pytest.fixture
def sc():
    ctx = LocalSparkContext(num_executors=2, task_timeout=120)
    yield ctx
    ctx.stop()


def fn_text_pipeline_under_chaos(args, ctx):
    # runs inside the spawned jax child: the plan must have propagated, the
    # pipeline must absorb the injected tokenize errors within its budget
    # and deliver every surviving record exactly once
    from tensorflowonspark_tpu import chaos as _chaos
    from tensorflowonspark_tpu.data import TextPipeline, Tokenizer

    assert _chaos.active, "chaos plan did not reach the jax child"

    pipe = TextPipeline(
        [args["shard"]], Tokenizer(kind="word", vocab_size=64),
        seq_len=32, batch_size=2, shuffle=False, epochs=1,
        max_bad_records=8, drop_remainder=False,
    )
    # segment ids are 1..n per row: the per-row max IS the sequence count
    n_seqs = sum(int(b["segment_ids"].max(axis=1).sum()) for b in pipe)
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(16)
        if batch:
            feed.batch_results([n_seqs for _ in batch])


def _poll_counter(cluster, name, want, deadline_s=60):
    # include_driver=False: mid-suite the driver registry carries counters
    # from earlier in-process tests (spawned children start clean); every
    # assertion below must hold from the two children alone
    deadline = time.monotonic() + deadline_s
    while True:
        snap = cluster.metrics(include_driver=False)
        got = snap["counters"].get(name, {}).get("value", 0)
        if got >= want or time.monotonic() > deadline:
            return snap, got


class TestTextChaosCluster:
    def test_tokenize_error_and_pack_stall_surface_in_cluster_metrics(
        self, sc, tmp_path
    ):
        from tensorflowonspark_tpu import tfrecord

        shard = str(tmp_path / "part-00000")
        with tfrecord.TFRecordWriter(shard) as w:
            for i in range(48):
                w.write("record number {} with a few words".format(i).encode())

        plan = (
            chaos.ChaosPlan(seed=3)
            # child side: three records swapped for invalid UTF-8 — charged
            # to the pipeline's max_bad_records, stream otherwise intact
            .site("data.tokenize_error", probability=1.0, max_count=3)
            # child side: the packer sleeps inside the timed pack region
            .site("data.pack_stall", probability=1.0, max_count=2, delay_s=0.02)
        )
        chaos.install(plan)  # propagate=True: children inherit via env
        cluster = TFCluster.run(
            sc, fn_text_pipeline_under_chaos, {"shard": shard}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        try:
            results = cluster.inference(sc.parallelize(range(40), 4)).collect()
            # both children packed the stream: 48 records - 3 chaos-poisoned
            # skips survived in each (the answer is per-child, rows echo it)
            assert results and all(r == 45 for r in results), results

            snap, faults = _poll_counter(
                cluster, "chaos_fault_data_tokenize_error_total", 6
            )
            counters = snap["counters"]
            # both sites fired in both children and surfaced in the merge
            assert counters["chaos_fault_data_tokenize_error_total"]["value"] >= 6
            assert counters["chaos_fault_data_pack_stall_total"]["value"] >= 4
            # the text accounting traveled the same lane: the skips were
            # charged to the budget (and to the data-plane skip counter)...
            assert counters["text_tokenize_errors_total"]["value"] >= 6
            assert counters["data_records_skipped_total"]["value"] >= 6
            # ...and the injected delay is visible as pack-stall seconds,
            # charged into parse time so the stall classifier reads the job
            # as input-bound (decode_bound: parse >= read)
            assert counters["text_pack_stall_seconds_total"]["value"] >= 0.04
            assert (
                counters["data_producer_parse_seconds_total"]["value"]
                >= counters["data_producer_read_seconds_total"]["value"]
            )
            assert counters["text_sequences_packed_total"]["value"] >= 90
        finally:
            cluster.shutdown(timeout=120)
