"""ImageNet input-pipeline tests (VERDICT r2 item 2): the decode /
distorted-crop / flip / normalize train path and the aspect-preserving
resize + central-crop eval path, against the reference's
imagenet_preprocessing.py:326-501 semantics."""

import numpy as np
import pytest

from tensorflowonspark_tpu import tfrecord
from tensorflowonspark_tpu.data import ImagePipeline, imagenet


def _jpeg_record(rng, h=96, w=96, label=7):
    img = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
    return img, imagenet.encode_example(img, label)


def test_encode_parse_roundtrip_train_shapes():
    rng = np.random.default_rng(0)
    _, record = _jpeg_record(rng, 96, 128, label=42)
    parse = imagenet.make_parse_fn(True, image_size=64)
    image, label = parse(record)
    assert image.shape == (64, 64, 3)
    assert image.dtype == np.float32
    assert label == 42
    # mean-subtracted: values can be negative; raw uint8 range impossible
    assert image.min() < 0


def test_parse_train_deterministic_under_seed():
    """Same (seed, record) -> same crop/flip regardless of thread order."""
    rng = np.random.default_rng(1)
    _, record = _jpeg_record(rng)
    parse = imagenet.make_parse_fn(True, image_size=64, seed=5)
    a, _ = parse(record)
    b, _ = parse(record)
    np.testing.assert_array_equal(a, b)
    other_seed, _ = imagenet.make_parse_fn(True, image_size=64, seed=6)(record), None
    assert not np.array_equal(a, other_seed[0])


def test_parse_label_offset():
    rng = np.random.default_rng(2)
    _, record = _jpeg_record(rng, label=1)  # 1-based ImageNet label
    _, label = imagenet.make_parse_fn(True, image_size=32, label_offset=-1)(record)
    assert label == 0


def test_eval_resize_preserves_aspect_and_central_crops():
    """A wide image resizes so the SHORT side hits RESIZE_MIN, then the
    center image_size x image_size crop is taken
    (imagenet_preprocessing.py:375-501)."""
    # gradient along width so the central crop is detectable
    h, w = 200, 400
    col = np.linspace(0, 255, w, dtype=np.float32)
    img = np.broadcast_to(col[None, :, None], (h, w, 3)).astype(np.uint8)
    import io

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(img).save(buf, "JPEG", quality=95)
    out = imagenet.preprocess_eval(buf.getvalue(), image_size=224, resize_min=256)
    assert out.shape == (224, 224, 3)
    # scale = 256/200 -> resized w = 512; central 224 of 512 is centered:
    # the mean of the cropped gradient ~= the full gradient's center value
    mid = (out[:, :, 0] + imagenet.CHANNEL_MEANS[0]).mean()
    assert abs(mid - 127.5) < 8.0, mid


def test_eval_tall_image_resizes_short_side():
    h, w = 400, 200
    img = np.full((h, w, 3), 128, np.uint8)
    import io

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(img).save(buf, "JPEG", quality=95)
    out = imagenet.preprocess_eval(buf.getvalue(), image_size=224, resize_min=256)
    assert out.shape == (224, 224, 3)


def test_raw_uint8_parse_plus_device_normalize_matches_float_parse():
    """The slim feed path (uint8 over the wire, normalize on device) is
    numerically the float path."""
    rng = np.random.default_rng(3)
    _, record = _jpeg_record(rng)
    f32, _ = imagenet.make_parse_fn(True, image_size=64, seed=9)(record)
    u8, _ = imagenet.make_parse_fn(True, image_size=64, seed=9, raw_uint8=True)(record)
    assert u8.dtype == np.uint8
    np.testing.assert_allclose(
        np.asarray(imagenet.device_normalize(u8)), f32, atol=1e-5
    )


def test_random_crop_box_respects_ranges():
    rng = np.random.default_rng(4)
    for _ in range(50):
        x, y, w, h = imagenet._random_crop_box(320, 240, rng)
        assert 0 <= x and x + w <= 320
        assert 0 <= y and y + h <= 240
        assert w > 0 and h > 0


def test_image_pipeline_over_imagenet_shards(tmp_path):
    """TFRecord shards -> ImagePipeline -> fixed-shape uint8 batches; short
    remainder dropped (static shapes for XLA)."""
    rng = np.random.default_rng(5)
    shard = str(tmp_path / "part-00000")
    with tfrecord.TFRecordWriter(shard) as w:
        for i in range(10):
            _, rec = _jpeg_record(rng, label=i % 3)
            w.write(rec)
    pipe = ImagePipeline(
        [shard],
        imagenet.make_parse_fn(True, image_size=32, raw_uint8=True),
        batch_size=4, shuffle=False, epochs=1, num_threads=2,
    )
    batches = list(pipe)
    assert len(batches) == 2  # 10 -> 2 full batches of 4, remainder dropped
    for b in batches:
        assert b["image"].shape == (4, 32, 32, 3)
        assert b["image"].dtype == np.uint8
        assert b["label"].dtype == np.int32
