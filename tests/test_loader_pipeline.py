"""Pipelined input path (data/loader.py): determinism across every
pipelining knob, bounded shuffle-buffer behaviour, stall metrics, recycled
zero-copy batch buffers, the multiprocess decode-plane mode (byte-identical
to the thread pool, caches/budget across the process boundary), chaos
``data.shard_read`` faults, and the structural IO/parse overlap proof
(``perf_smoke``)."""

import time

import numpy as np
import pytest

from tensorflowonspark_tpu import chaos, native_io, obs, tfrecord
from tensorflowonspark_tpu.data import ImagePipeline


def _counter(name):
    return obs.snapshot()["counters"].get(name, {}).get("value", 0)


def _parse(rec):
    v = int(rec)
    return np.full((4, 4, 1), v % 251, np.uint8), v


@pytest.fixture
def shards(tmp_path):
    """Three shards of 137 records each; labels are the global record index
    0..410, so a batch stream identifies records exactly."""
    paths, n = [], 0
    for s in range(3):
        p = str(tmp_path / "part-{:05d}".format(s))
        with tfrecord.TFRecordWriter(p) as w:
            for _ in range(137):
                w.write(str(n).encode())
                n += 1
        paths.append(p)
    return paths


def _stream(paths, **kw):
    kw.setdefault("batch_size", 8)
    kw.setdefault("seed", 3)
    kw.setdefault("epochs", 2)
    pipe = ImagePipeline(paths, _parse, **kw)
    return [(b["image"].tobytes(), b["label"].tobytes()) for b in pipe]


class TestDeterminism:
    def test_stream_invariant_to_pipelining_knobs(self, shards):
        """Same seed ⇒ byte-identical batches: read-ahead on/off, chunked vs
        bulk reads, 1 vs 8 parse threads — none may reorder the stream."""
        base = _stream(shards, readahead=0, chunk_records=0, num_threads=1)
        assert len(base) == 2 * (411 // 8)  # 2 epochs, remainder dropped
        variants = [
            dict(readahead=2, chunk_records=0, num_threads=1),
            dict(readahead=0, chunk_records=16, num_threads=1),
            dict(readahead=0, chunk_records=0, num_threads=8),
            dict(readahead=2, chunk_records=16, num_threads=8),
            dict(readahead=3, chunk_records=7, num_threads=8),
        ]
        for kw in variants:
            assert _stream(shards, **kw) == base, kw

    def test_python_codec_fallback_matches_native(self, shards, monkeypatch):
        base = _stream(shards, readahead=2, chunk_records=16)
        monkeypatch.setattr(native_io, "stream_available", lambda: False)
        assert _stream(shards, readahead=2, chunk_records=16) == base

    def test_caches_replay_identically(self, shards):
        # epoch 2 is served from memory (raw bytes / decoded arrays) but must
        # be byte-identical to the uncached stream
        base = _stream(shards, readahead=2, chunk_records=16)
        for mode in ("raw", "decoded"):
            assert _stream(shards, readahead=2, chunk_records=16, cache=mode) == base

    def test_cache_persists_across_iterations(self, shards):
        pipe = ImagePipeline(
            shards, _parse, batch_size=8, seed=3, epochs=1, cache="raw",
            readahead=2, chunk_records=16,
        )
        first = [(b["image"].tobytes(), b["label"].tobytes()) for b in pipe]
        assert len(pipe._raw_complete) == 3
        second = [(b["image"].tobytes(), b["label"].tobytes()) for b in pipe]
        assert second == first

    def test_seed_changes_the_stream(self, shards):
        assert _stream(shards, seed=1) != _stream(shards, seed=2)

    def test_autotuned_delivery_invariant_to_buckets_and_threads(self, shards):
        """The adaptive feed composes with the pipeline without touching the
        record stream: whatever window sizes the controller picks and however
        many parse threads feed it, the delivered batches are identical."""
        import jax

        from tensorflowonspark_tpu import parallel
        from tensorflowonspark_tpu.data import FeedAutotuner, autotuned_prefetch
        from tensorflowonspark_tpu.train import SyncDataParallel

        strategy = SyncDataParallel(parallel.build_mesh({"dp": 8}))

        def delivered(num_threads, buckets):
            pipe = ImagePipeline(
                shards, _parse, batch_size=8, seed=3, epochs=1,
                num_threads=num_threads,
            )
            tuner = FeedAutotuner(buckets=buckets)
            out = []
            for w in autotuned_prefetch(iter(pipe), strategy, tuner=tuner):
                assert w.k in tuner.buckets
                data = jax.device_get(w.data)
                for i in range(w.k):
                    out.append(
                        (
                            np.asarray(data["image"])[i].tobytes(),
                            np.asarray(data["label"])[i].tolist(),
                        )
                    )
            return out

        base = delivered(1, (1,))
        assert len(base) == 411 // 8
        # the K=1 reference matches the raw host stream record for record
        host = _stream(shards, epochs=1, num_threads=1)
        assert [img for img, _ in base] == [img for img, _ in host]
        for threads, buckets in [(1, (1, 2, 4)), (8, (1,)), (8, (1, 2, 4)), (8, (1, 4, 16))]:
            assert delivered(threads, buckets) == base, (threads, buckets)

    def test_invalid_cache_mode_rejected(self, shards):
        with pytest.raises(ValueError):
            ImagePipeline(shards, _parse, batch_size=8, cache="disk")


class TestShuffleBuffer:
    def _labels(self, paths, seed, **kw):
        kw.setdefault("batch_size", 8)
        kw.setdefault("epochs", 1)
        kw.setdefault("drop_remainder", False)
        pipe = ImagePipeline(paths, _parse, seed=seed, **kw)
        return [v for b in pipe for v in b["label"].tolist()]

    def test_bounded_displacement_and_multiset(self, tmp_path):
        # single shard: input order == label value, so displacement is exact
        p = str(tmp_path / "part-00000")
        with tfrecord.TFRecordWriter(p) as w:
            for i in range(200):
                w.write(str(i).encode())
        buffer = 32
        out = self._labels([p], seed=0, shuffle_buffer=buffer)
        assert sorted(out) == list(range(200))  # nothing lost or duplicated
        for j, v in enumerate(out):
            # a record cannot be emitted before it has entered the buffer:
            # by output position j only j + buffer inputs have been read, so
            # no record can jump ahead more than the buffer size (it CAN lag
            # arbitrarily — an unlucky record may survive draws to the end)
            assert v <= j + buffer - 1, (j, v)
        # the stream is actually shuffled, and differently per seed
        assert out != list(range(200))
        assert out[:16] != self._labels([p], seed=1, shuffle_buffer=buffer)[:16]

    def test_buffer_of_one_disables_record_shuffle(self, tmp_path):
        p = str(tmp_path / "part-00000")
        with tfrecord.TFRecordWriter(p) as w:
            for i in range(40):
                w.write(str(i).encode())
        out = self._labels([p], seed=0, shuffle_buffer=1)
        assert out == list(range(40))  # shard order shuffles; records don't

    def test_multi_shard_multiset(self, shards):
        out = self._labels(shards, seed=5, shuffle_buffer=64)
        assert sorted(out) == list(range(411))


class TestStallMetrics:
    def test_producer_and_consumer_counters_advance(self, shards):
        names = (
            "data_producer_read_seconds_total",
            "data_producer_parse_seconds_total",
            "data_producer_emit_seconds_total",
            "data_consumer_wait_seconds_total",
        )
        before = {n: _counter(n) for n in names}
        _stream(shards, readahead=2, chunk_records=16)
        snap = obs.snapshot()["counters"]
        for n in names:
            assert n in snap, n
        # IO and parse genuinely happened; emit/wait only accrue when a side
        # blocks, so they are merely monotone
        assert _counter("data_producer_read_seconds_total") > before[
            "data_producer_read_seconds_total"
        ]
        assert _counter("data_producer_parse_seconds_total") > before[
            "data_producer_parse_seconds_total"
        ]
        for n in names[2:]:
            assert _counter(n) >= before[n]


class TestRecycledBuffers:
    def test_recycled_stream_matches_when_copied(self, shards):
        base = _stream(shards, readahead=2, chunk_records=16)
        pipe = ImagePipeline(
            shards, _parse, batch_size=8, seed=3, epochs=2,
            readahead=2, chunk_records=16, recycle_buffers=True,
        )
        got = [(b["image"].copy().tobytes(), b["label"].copy().tobytes()) for b in pipe]
        assert got == base

    def test_buffers_actually_recycle(self, shards):
        pipe = ImagePipeline(
            shards, _parse, batch_size=8, seed=3, epochs=2,
            readahead=2, chunk_records=16, recycle_buffers=True,
            prefetch_batches=1,
        )
        ids, n_batches = set(), 0
        for b in pipe:
            ids.add(id(b["image"]))
            n_batches += 1
        # pool cap is prefetch_batches + 2: far fewer distinct buffers than
        # batches proves reuse (fresh np.empty per batch would churn ids)
        assert n_batches == 2 * (411 // 8)
        assert len(ids) <= 3


class TestDecodePlaneMode:
    """``decode_workers > 0``: the parse stage runs in worker processes
    writing into shared-memory slabs — the delivered stream must stay
    byte-identical to the thread pool's, across every pipelining knob, and
    the caches/budget/fallback contracts must hold either side of the
    process boundary."""

    def test_stream_invariant_across_decode_workers(self, shards):
        base = _stream(shards, readahead=0, chunk_records=0, num_threads=1)
        variants = [
            dict(decode_workers=1, readahead=0, chunk_records=0),
            dict(decode_workers=1, readahead=2, chunk_records=16),
            dict(decode_workers=4, readahead=0, chunk_records=0),
            dict(decode_workers=4, readahead=2, chunk_records=16),
            dict(decode_workers=4, readahead=3, chunk_records=7),
        ]
        for kw in variants:
            assert _stream(shards, **kw) == base, kw

    def test_env_knob_engages_the_plane(self, shards, monkeypatch):
        from tensorflowonspark_tpu import obs

        base = _stream(shards)
        monkeypatch.setenv("TOS_DECODE_WORKERS", "2")
        assert _stream(shards) == base
        # the plane ran: its gauge got registered (back at 0 after close)
        assert "decode_workers" in obs.snapshot()["gauges"]
        assert obs.snapshot()["gauges"]["decode_workers"]["value"] == 0

    def test_thread_fallback_when_plane_unavailable(self, shards, monkeypatch):
        from tensorflowonspark_tpu.data import decode_plane

        base = _stream(shards)
        monkeypatch.setattr(decode_plane, "available", lambda: False)
        assert _stream(shards, decode_workers=4) == base

    def test_decoded_cache_populated_from_process_workers(self, shards):
        # decoded pixels flow back through the slab (never pickle) into the
        # parent's cache; epoch 2 replays from it byte-identically
        base = _stream(shards, readahead=2, chunk_records=16)
        pipe = ImagePipeline(
            shards, _parse, batch_size=8, seed=3, epochs=2,
            readahead=2, chunk_records=16, cache="decoded", decode_workers=2,
        )
        got = [(b["image"].tobytes(), b["label"].tobytes()) for b in pipe]
        assert got == base
        assert len(pipe._decoded) == 411
        # replay is served from the parent-side cache, process mode again
        second = [(b["image"].tobytes(), b["label"].tobytes()) for b in pipe]
        assert second == got

    def test_recycled_slabs_match_when_copied(self, shards):
        base = _stream(shards, readahead=2, chunk_records=16)
        pipe = ImagePipeline(
            shards, _parse, batch_size=8, seed=3, epochs=2,
            readahead=2, chunk_records=16, recycle_buffers=True,
            decode_workers=2,
        )
        got = [(b["image"].copy().tobytes(), b["label"].copy().tobytes()) for b in pipe]
        assert got == base

    def test_max_bad_records_budget_spans_the_process_boundary(self, tmp_path):
        # the poisoned record fails INSIDE a worker; the budget and the
        # skip counter must behave exactly as in-thread (holes backfilled,
        # batches stay full-size)
        p = str(tmp_path / "part-00000")
        with tfrecord.TFRecordWriter(p) as w:
            for i in range(20):
                w.write(str(i).encode() if i != 7 else b"poison")

        def run(max_bad):
            pipe = ImagePipeline(
                [p], _parse, batch_size=4, seed=0, epochs=1, shuffle=False,
                max_bad_records=max_bad, decode_workers=2,
            )
            return [int(x) for b in pipe for x in b["label"]]

        before = _counter("data_records_skipped_total")
        assert run(1) == [i for i in range(20) if i != 7][:16]
        assert _counter("data_records_skipped_total") == before + 1
        with pytest.raises(Exception, match="poison"):
            run(0)

    def test_slab_metrics_registered(self, shards):
        from tensorflowonspark_tpu import obs

        _stream(shards, decode_workers=2, recycle_buffers=True)
        snap = obs.snapshot()
        assert "decode_slab_bytes" in snap["gauges"]
        assert "decode_worker_restarts_total" in snap["counters"]
        assert "decode_slab_wait_seconds_total" in snap["counters"]


class TestChaosShardRead:
    pytestmark = pytest.mark.chaos

    @pytest.fixture(autouse=True)
    def _clean_chaos(self):
        chaos.uninstall()
        yield
        chaos.uninstall()

    def test_error_faults_absorbed_by_retry(self, shards):
        # two injected IOErrors on shard open: SHARD_READ_RETRY (3 attempts)
        # absorbs both; the epoch completes with every record intact
        plan = chaos.ChaosPlan(seed=0).site(
            "data.shard_read", probability=1.0, max_count=2, error=True
        )
        chaos.install(plan, propagate=False)
        faults_before = _counter("chaos_fault_data_shard_read_total")
        pipe = ImagePipeline(
            shards, _parse, batch_size=8, seed=3, epochs=1,
            drop_remainder=False, readahead=2, chunk_records=16,
        )
        labels = sorted(v for b in pipe for v in b["label"].tolist())
        assert labels == list(range(411))
        assert plan.fired("data.shard_read") == 2
        assert _counter("chaos_fault_data_shard_read_total") - faults_before == 2

    def test_delay_faults_only_slow_the_stream(self, shards):
        base = _stream(shards, readahead=2, chunk_records=16)
        plan = chaos.ChaosPlan(seed=0).site(
            "data.shard_read", probability=1.0, max_count=3, delay_s=0.01
        )
        chaos.install(plan, propagate=False)
        assert _stream(shards, readahead=2, chunk_records=16) == base
        assert plan.fired("data.shard_read") == 3

    def test_exhausted_retry_surfaces_the_error(self, shards):
        # more consecutive faults than the retry budget: the IOError reaches
        # the consumer instead of hanging the pipeline
        plan = chaos.ChaosPlan(seed=0).site(
            "data.shard_read", probability=1.0, max_count=None, error=True
        )
        chaos.install(plan, propagate=False)
        pipe = ImagePipeline(
            shards, _parse, batch_size=8, seed=3, epochs=1, readahead=2,
        )
        with pytest.raises(IOError):
            list(pipe)


@pytest.mark.perf_smoke
class TestOverlapSmoke:
    """Structural proof that read-ahead overlaps IO with parse: both stages
    are sleep-dominated (chaos shard-open delay, sleepy parse_fn), so wall
    time below the serial sum can only come from genuine overlap — no
    absolute-throughput assertion to flake on a loaded box."""

    @pytest.fixture(autouse=True)
    def _clean_chaos(self):
        chaos.uninstall()
        yield
        chaos.uninstall()

    def test_readahead_overlaps_io_and_parse(self, tmp_path):
        paths = []
        for s in range(4):
            p = str(tmp_path / "part-{:05d}".format(s))
            with tfrecord.TFRecordWriter(p) as w:
                for i in range(12):
                    w.write(str(s * 12 + i).encode())
            paths.append(p)

        def sleepy_parse(rec):
            time.sleep(0.005)
            v = int(rec)
            return np.full((2, 2, 1), v % 251, np.uint8), v

        chaos.install(
            chaos.ChaosPlan(seed=0).site(
                "data.shard_read", probability=1.0, delay_s=0.1
            ),
            propagate=False,
        )
        read_before = _counter("data_producer_read_seconds_total")
        parse_before = _counter("data_producer_parse_seconds_total")
        t0 = time.monotonic()
        pipe = ImagePipeline(
            paths, sleepy_parse, batch_size=4, shuffle=False, epochs=1,
            num_threads=1, readahead=2, chunk_records=4,
        )
        n_batches = sum(1 for _ in pipe)
        wall = time.monotonic() - t0
        read_s = _counter("data_producer_read_seconds_total") - read_before
        parse_s = _counter("data_producer_parse_seconds_total") - parse_before

        assert n_batches == 12
        # both stages really slept: 4 shard opens x 0.1s, 48 records x 5ms
        assert read_s > 0.3, read_s
        assert parse_s > 0.2, parse_s
        # the pipelining claim itself: wall beats the serial sum
        assert wall < 0.9 * (read_s + parse_s), (wall, read_s, parse_s)


def _make_jpeg_parse():
    from tensorflowonspark_tpu.data import imagenet

    return imagenet.make_parse_fn(True, image_size=16, seed=5, raw_uint8=True)


@pytest.fixture
def jpeg_shards(tmp_path):
    """Two shards of real JPEG Examples (labels = global index 0..59), the
    decode-mode matrix's substrate: every decode path must produce the same
    pixels from these bytes."""
    from tensorflowonspark_tpu.data import imagenet

    rng = np.random.default_rng(0)
    paths, n = [], 0
    for s in range(2):
        p = str(tmp_path / "img-{:05d}".format(s))
        with tfrecord.TFRecordWriter(p) as w:
            for _ in range(30):
                img = rng.integers(
                    0, 256, (24 + n % 5, 24 + n % 3, 3), dtype=np.uint8
                )
                w.write(imagenet.encode_example(img, n))
                n += 1
        paths.append(p)
    return paths


def _jstream(paths, slab_cache_dir=None, **kw):
    kw.setdefault("batch_size", 4)
    kw.setdefault("seed", 3)
    kw.setdefault("epochs", 1)
    kw.setdefault("readahead", 2)
    kw.setdefault("chunk_records", 16)
    pipe = ImagePipeline(
        paths, _make_jpeg_parse(), slab_cache_dir=slab_cache_dir, **kw
    )
    return [(b["image"].tobytes(), b["label"].tobytes()) for b in pipe]


class TestNativeDecodeAndSlabCache:
    """The byte-identical-stream contract across decode implementations:
    PIL threads, native threads, native worker processes, and the
    cross-epoch decoded-slab cache must all deliver the same batches — and
    charge a corrupt JPEG against ``max_bad_records`` identically."""

    def test_stream_invariant_across_decode_modes(self, jpeg_shards, tmp_path, monkeypatch):
        from tensorflowonspark_tpu.data import decode_plane

        base = _jstream(jpeg_shards)  # thread pool, native when available
        if native_io.jpg_available():
            native = _counter("decode_native_total")
            assert _jstream(jpeg_shards) == base
            assert _counter("decode_native_total") > native
        # PIL-forced threads
        monkeypatch.setenv(native_io.DECODE_ENV_VAR, "0")
        assert _jstream(jpeg_shards) == base
        monkeypatch.delenv(native_io.DECODE_ENV_VAR)
        # worker processes (native inside the workers)
        if decode_plane.available():
            assert _jstream(jpeg_shards, decode_workers=2) == base
        # cold cache, then a warm run served from committed generations
        cache = str(tmp_path / "slab-cache")
        assert _jstream(jpeg_shards, slab_cache_dir=cache) == base
        hits = _counter("decode_cache_hits_total")
        assert _jstream(jpeg_shards, slab_cache_dir=cache) == base
        # 59 of 60: the bootstrap record is decoded parent-side to learn
        # the slab geometry BEFORE the cache can open (it needs the shape)
        assert _counter("decode_cache_hits_total") - hits == 59
        # and a warm PROCESS run: hits lease slots without touching a worker
        if decode_plane.available():
            assert _jstream(jpeg_shards, slab_cache_dir=cache, decode_workers=2) == base

    def test_epoch_two_is_served_from_the_cache(self, jpeg_shards, tmp_path):
        cache = str(tmp_path / "slab-cache")
        base = _jstream(jpeg_shards, epochs=2)
        hits = _counter("decode_cache_hits_total")
        assert _jstream(jpeg_shards, epochs=2, slab_cache_dir=cache) == base
        # epoch 1 decoded and committed; epoch 2 hit for every record
        assert _counter("decode_cache_hits_total") - hits == 60
        assert obs.snapshot()["gauges"]["decode_cache_bytes"]["value"] > 0

    def test_cache_survives_pipeline_objects(self, jpeg_shards, tmp_path):
        # the elastic-relaunch shape: a NEW pipeline (fresh process in real
        # life) over the same shards + params adopts the committed
        # generations and skips decode entirely
        cache = str(tmp_path / "slab-cache")
        base = _jstream(jpeg_shards, slab_cache_dir=cache)
        hits = _counter("decode_cache_hits_total")
        native = _counter("decode_native_total")
        assert _jstream(jpeg_shards, slab_cache_dir=cache) == base
        assert _counter("decode_cache_hits_total") - hits == 59  # 60 - bootstrap
        assert _counter("decode_native_total") == native  # no native decode at all

    def test_cache_is_scoped_by_decode_params(self, jpeg_shards, tmp_path):
        from tensorflowonspark_tpu.data import imagenet

        cache = str(tmp_path / "slab-cache")
        _jstream(jpeg_shards, slab_cache_dir=cache)
        hits = _counter("decode_cache_hits_total")
        # a different augmentation seed is a different cache_key: the
        # committed generation must NOT serve it
        parse = imagenet.make_parse_fn(True, image_size=16, seed=6, raw_uint8=True)
        pipe = ImagePipeline(
            jpeg_shards, parse, batch_size=4, seed=3, epochs=1,
            slab_cache_dir=cache,
        )
        for _ in pipe:
            pass
        assert _counter("decode_cache_hits_total") == hits

    def test_env_knob_engages_the_cache(self, jpeg_shards, tmp_path, monkeypatch):
        base = _jstream(jpeg_shards)
        monkeypatch.setenv("TOS_SLAB_CACHE_DIR", str(tmp_path / "env-cache"))
        assert _jstream(jpeg_shards) == base
        hits = _counter("decode_cache_hits_total")
        assert _jstream(jpeg_shards) == base
        assert _counter("decode_cache_hits_total") - hits == 59  # 60 - bootstrap

    def test_corrupt_jpeg_charged_identically_in_all_modes(self, tmp_path, monkeypatch):
        from tensorflowonspark_tpu import tfrecord as tfr
        from tensorflowonspark_tpu.data import decode_plane, imagenet

        rng = np.random.default_rng(1)
        p = str(tmp_path / "poisoned-00000")
        with tfrecord.TFRecordWriter(p) as w:
            for i in range(12):
                if i == 7:  # valid Example, garbage JPEG bytes (last
                    # slot of round 2, so the backfill keeps label order)
                    w.write(tfr.encode_example({
                        "image/encoded": [b"\xff\xd8 not a jpeg"],
                        "image/class/label": [7],
                    }))
                else:
                    img = rng.integers(0, 256, (24, 24, 3), dtype=np.uint8)
                    w.write(imagenet.encode_example(img, i))

        def labels(max_bad, **kw):
            pipe = ImagePipeline(
                [p], _make_jpeg_parse(), batch_size=4, seed=0, epochs=1,
                shuffle=False, max_bad_records=max_bad, **kw)
            return [int(x) for b in pipe for x in b["label"]]

        good = [i for i in range(12) if i != 7][:8]
        modes = [dict(), dict(slab_cache_dir=str(tmp_path / "c"))]
        if decode_plane.available():
            modes.append(dict(decode_workers=2))
        for kw in modes:
            before = _counter("data_records_skipped_total")
            assert labels(1, **kw) == good, kw
            assert _counter("data_records_skipped_total") == before + 1, kw
            with pytest.raises(Exception):
                labels(0, **kw)
        # and PIL-forced threads charge the same record
        monkeypatch.setenv(native_io.DECODE_ENV_VAR, "0")
        before = _counter("data_records_skipped_total")
        assert labels(1) == good
        assert _counter("data_records_skipped_total") == before + 1

    def test_readahead_auto_stream_is_identical(self, jpeg_shards):
        base = _jstream(jpeg_shards)
        assert _jstream(jpeg_shards, readahead="auto") == base
        assert "readahead_depth" in obs.snapshot()["gauges"]


class TestChaosCacheAndReadahead:
    pytestmark = pytest.mark.chaos

    @pytest.fixture(autouse=True)
    def _clean_chaos(self):
        chaos.uninstall()
        yield
        chaos.uninstall()

    def test_cache_tear_is_rejected_and_stream_survives(self, jpeg_shards, tmp_path):
        # a torn commit (crash between manifest write and fsync) must be
        # rejected by verify-on-publish — the records decode again, the
        # stream never sees garbage
        base = _jstream(jpeg_shards, epochs=2)
        cache = str(tmp_path / "slab-cache")
        plan = chaos.ChaosPlan(seed=0).site(
            "data.cache_tear", probability=1.0, max_count=1
        )
        chaos.install(plan, propagate=False)
        rejects = _counter("decode_cache_rejects_total")
        hits = _counter("decode_cache_hits_total")
        assert _jstream(jpeg_shards, epochs=2, slab_cache_dir=cache) == base
        assert plan.fired("data.cache_tear") == 1
        assert _counter("decode_cache_rejects_total") - rejects == 1
        # epoch 1's torn generation served nothing: epoch 2 re-decoded
        assert _counter("decode_cache_hits_total") == hits
        # the epoch-2 commit was past the chaos budget: a fresh run hits
        chaos.uninstall()
        assert _jstream(jpeg_shards, slab_cache_dir=cache) == base[: len(base) // 2]
        assert _counter("decode_cache_hits_total") - hits == 59  # 60 - bootstrap

    def test_readahead_stall_only_slows_the_stream(self, jpeg_shards):
        base = _jstream(jpeg_shards)
        plan = chaos.ChaosPlan(seed=0).site(
            "data.readahead_stall", probability=1.0, max_count=3, delay_s=0.01
        )
        chaos.install(plan, propagate=False)
        read_before = _counter("data_producer_read_seconds_total")
        assert _jstream(jpeg_shards, readahead="auto") == base
        assert plan.fired("data.readahead_stall") == 3
        # the stall is charged to shard-read time, where the readahead
        # autotuner and classify_stalls can see it
        assert _counter("data_producer_read_seconds_total") - read_before >= 0.03
