"""Fixture tests for the commit-discipline and env-lane rules.

Each finding class gets a bad fixture that fires and a good twin that
stays clean; the docs-drift halves inject a ``docs/architecture.md``
snippet through ``run_project_rule``'s ``docs`` mapping (without docs
text those halves are skipped, which is itself asserted).
"""

import textwrap

from tosa_testutil import LIB_PATH, run_project_rule
from tosa import core


def _src(s):
    return textwrap.dedent(s).lstrip()


# -- commit-discipline --------------------------------------------------------

#: the full idiom: tmp write, file fsync, rename, parent-dir fsync
GOOD_PUBLISH = _src("""
    import os


    def publish(path):
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("data")
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        dirfd = os.open(os.path.dirname(path), os.O_RDONLY)
        os.fsync(dirfd)
        os.close(dirfd)
""")

#: docs row naming the good fixture's publish site with a verify consumer
GOOD_PUBLISH_DOCS = _src("""
    ### Durable commit points

    | commit point | publishes | verified by |
    |---|---|---|
    | `tensorflowonspark_tpu/fixture_mod.py:publish` | the data file | reader re-parses and length-checks it |
""")


class TestCommitDiscipline:
    def test_full_idiom_is_clean(self):
        findings = run_project_rule("commit-discipline", {LIB_PATH: GOOD_PUBLISH})
        assert findings == []

    def test_rename_without_file_fsync_fires(self):
        findings = run_project_rule("commit-discipline", {LIB_PATH: _src("""
            import os


            def publish(path):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write("data")
                os.replace(tmp, path)
                dirfd = os.open(os.path.dirname(path), os.O_RDONLY)
                os.fsync(dirfd)
                os.close(dirfd)
        """)})
        assert len(findings) == 1
        assert "without an fsync of the written file first" in findings[0].message

    def test_rename_without_parent_dir_fsync_fires(self):
        findings = run_project_rule("commit-discipline", {LIB_PATH: _src("""
            import os


            def publish(path):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write("data")
                    os.fsync(f.fileno())
                os.replace(tmp, path)
        """)})
        assert len(findings) == 1
        assert "without fsyncing the parent directory" in findings[0].message

    def test_fsync_through_called_helper_counts(self):
        # provision flows through the call closure: the publish site calls
        # a helper that does the file fsync / dir fsync on its behalf
        findings = run_project_rule("commit-discipline", {LIB_PATH: _src("""
            import os


            def _flush(f):
                f.flush()
                os.fsync(f.fileno())


            def _fsync_dir(path):
                dirfd = os.open(path, os.O_RDONLY)
                os.fsync(dirfd)
                os.close(dirfd)


            def publish(path):
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    f.write("data")
                    _flush(f)
                os.replace(tmp, path)
                _fsync_dir(os.path.dirname(path))
        """)})
        assert findings == []

    def test_manifest_not_written_last_fires(self):
        findings = run_project_rule("commit-discipline", {LIB_PATH: _src("""
            import os

            from tensorflowonspark_tpu.ckpt import manifest


            def commit(root):
                manifest.write_manifest(root)
                with open(root + "/data.tmp", "w") as f:
                    f.write("data")
                    os.fsync(f.fileno())
                os.replace(root + "/data.tmp", root + "/data")
                dirfd = os.open(root, os.O_RDONLY)
                os.fsync(dirfd)
                os.close(dirfd)
        """)})
        assert len(findings) == 1
        assert "must be written last" in findings[0].message

    def test_manifest_written_last_is_clean(self):
        findings = run_project_rule("commit-discipline", {LIB_PATH: _src("""
            import os

            from tensorflowonspark_tpu.ckpt import manifest


            def commit(root):
                with open(root + "/data.tmp", "w") as f:
                    f.write("data")
                    os.fsync(f.fileno())
                manifest.write_manifest(root)
                os.replace(root + "/data.tmp", root + "/data")
                dirfd = os.open(root, os.O_RDONLY)
                os.fsync(dirfd)
                os.close(dirfd)
        """)})
        assert findings == []

    def test_retention_rename_is_not_a_publish_site(self):
        # a rename with no staging hint and no write intent before it is a
        # retention shuffle, not a commit point — no findings even though
        # it never fsyncs anything
        findings = run_project_rule("commit-discipline", {LIB_PATH: _src("""
            import os


            def rotate(old, new):
                os.rename(old, new)
        """)})
        assert findings == []

    def test_chaos_guarded_torn_write_is_exempt(self):
        # the deliberately-torn branch under an `if chaos...` test is the
        # fault injection itself, not a durability bug
        findings = run_project_rule("commit-discipline", {LIB_PATH: _src("""
            import os

            from tensorflowonspark_tpu import chaos


            def publish(path):
                tmp = path + ".tmp"
                if chaos.should_tear("publish"):
                    os.replace(tmp, path)
                    return
                with open(tmp, "w") as f:
                    f.write("data")
                    os.fsync(f.fileno())
                os.replace(tmp, path)
                dirfd = os.open(os.path.dirname(path), os.O_RDONLY)
                os.fsync(dirfd)
                os.close(dirfd)
        """)})
        assert findings == []


class TestCommitDisciplineDocs:
    def test_documented_site_with_verify_consumer_is_clean(self):
        findings = run_project_rule(
            "commit-discipline",
            {LIB_PATH: GOOD_PUBLISH},
            docs={"docs/architecture.md": GOOD_PUBLISH_DOCS},
        )
        assert findings == []

    def test_undocumented_publish_site_fires(self):
        findings = run_project_rule(
            "commit-discipline",
            {LIB_PATH: GOOD_PUBLISH},
            docs={"docs/architecture.md": "### Durable commit points\n\n(no rows)\n"},
        )
        assert len(findings) == 1
        assert "missing from the Durable commit points table" in findings[0].message
        assert findings[0].path == LIB_PATH

    def test_stale_docs_row_fires_on_the_docs_file(self):
        stale = GOOD_PUBLISH_DOCS + (
            "| `tensorflowonspark_tpu/gone.py:publish` | nothing | nobody |\n"
        )
        findings = run_project_rule(
            "commit-discipline",
            {LIB_PATH: GOOD_PUBLISH},
            docs={"docs/architecture.md": stale},
        )
        assert len(findings) == 1
        assert "matches no publish site" in findings[0].message
        assert findings[0].path == "docs/architecture.md"

    def test_empty_verify_cell_fires(self):
        no_verify = GOOD_PUBLISH_DOCS.replace(
            "reader re-parses and length-checks it", "—"
        )
        findings = run_project_rule(
            "commit-discipline",
            {LIB_PATH: GOOD_PUBLISH},
            docs={"docs/architecture.md": no_verify},
        )
        assert len(findings) == 1
        assert "no verify-on-read consumer" in findings[0].message

    def test_docs_half_skipped_without_docs_text(self):
        # fixture runs with no docs mapping only get the code-side checks
        findings = run_project_rule("commit-discipline", {LIB_PATH: GOOD_PUBLISH})
        assert findings == []


# -- env-lane -----------------------------------------------------------------

WRITER = _src("""
    import os


    def launch(executor_id):
        os.environ["TOS_FIXTURE_LANE"] = str(executor_id)
""")

READER = _src("""
    import os


    def attach():
        return os.environ.get("TOS_FIXTURE_LANE")
""")

ENV_DOCS = _src("""
    ### Env lanes

    | name | kind | meaning |
    |---|---|---|
    | `TOS_FIXTURE_LANE` | lane | launch() → attach() |
""")


class TestEnvLane:
    def test_wired_lane_is_clean(self):
        findings = run_project_rule("env-lane", {
            LIB_PATH: WRITER,
            "tensorflowonspark_tpu/attach_mod.py": READER,
        })
        assert findings == []

    def test_orphan_producer_fires(self):
        findings = run_project_rule("env-lane", {LIB_PATH: WRITER})
        assert len(findings) == 1
        assert "never read anywhere" in findings[0].message

    def test_off_lane_names_are_ignored(self):
        findings = run_project_rule("env-lane", {LIB_PATH: _src("""
            import os


            def launch():
                os.environ["SOME_OTHER_VAR"] = "1"
        """)})
        assert findings == []

    def test_constant_name_resolves_across_modules(self):
        # producer writes through a module constant; consumer from-imports
        # the constant — both resolve to the same literal lane name
        findings = run_project_rule("env-lane", {
            LIB_PATH: _src("""
                import os

                LANE = "TOS_FIXTURE_LANE"


                def launch(executor_id):
                    os.environ[LANE] = str(executor_id)
            """),
            "tensorflowonspark_tpu/attach_mod.py": _src("""
                import os

                from tensorflowonspark_tpu.fixture_mod import LANE


                def attach():
                    return os.environ.get(LANE)
            """),
        })
        assert findings == []

    def test_module_level_read_counts_as_consumer(self):
        # import-time defaults (`X = float(os.environ.get(...))`) are
        # consumers too; without module-level scanning the writer would
        # look like an orphan producer
        findings = run_project_rule("env-lane", {
            LIB_PATH: WRITER,
            "tensorflowonspark_tpu/attach_mod.py": _src("""
                import os

                FIXTURE_LANE = os.environ.get("TOS_FIXTURE_LANE", "0")
            """),
        })
        assert findings == []


class TestEnvLaneDocs:
    def test_documented_wired_lane_is_clean(self):
        findings = run_project_rule(
            "env-lane",
            {LIB_PATH: WRITER, "tensorflowonspark_tpu/attach_mod.py": READER},
            docs={"docs/architecture.md": ENV_DOCS},
        )
        assert findings == []

    def test_undocumented_read_fires(self):
        findings = run_project_rule(
            "env-lane",
            {LIB_PATH: WRITER, "tensorflowonspark_tpu/attach_mod.py": READER},
            docs={"docs/architecture.md": "### Env lanes\n\n(no rows)\n"},
        )
        assert len(findings) == 1
        assert "missing from the Env lanes table" in findings[0].message

    def test_stale_docs_row_fires_on_the_docs_file(self):
        stale = ENV_DOCS + "| `TOS_GONE_LANE` | knob | nothing uses this |\n"
        findings = run_project_rule(
            "env-lane",
            {LIB_PATH: WRITER, "tensorflowonspark_tpu/attach_mod.py": READER},
            docs={"docs/architecture.md": stale},
        )
        assert len(findings) == 1
        assert "matches no read or write" in findings[0].message
        assert findings[0].path == "docs/architecture.md"

    def test_documented_lane_without_producer_fires(self):
        # kind `lane` promises an in-code producer; a read-only name must
        # be reclassified as a knob instead
        findings = run_project_rule(
            "env-lane",
            {"tensorflowonspark_tpu/attach_mod.py": READER},
            docs={"docs/architecture.md": ENV_DOCS},
        )
        assert len(findings) == 1
        assert "documented as a produced lane but nothing" in findings[0].message

    def test_knob_kind_needs_no_producer(self):
        knob_docs = ENV_DOCS.replace("| lane |", "| knob |")
        findings = run_project_rule(
            "env-lane",
            {"tensorflowonspark_tpu/attach_mod.py": READER},
            docs={"docs/architecture.md": knob_docs},
        )
        assert findings == []


class TestNewRulesSuppressionAndBaseline:
    def test_inline_disable_silences_project_finding(self):
        src = WRITER.replace(
            'os.environ["TOS_FIXTURE_LANE"] = str(executor_id)',
            'os.environ["TOS_FIXTURE_LANE"] = str(executor_id)'
            "  # tosa: disable=env-lane -- fixture lane has no reader yet",
        )
        findings = run_project_rule("env-lane", {LIB_PATH: src}, keep_suppressed=True)
        assert len(findings) == 1
        assert findings[0].suppressed == "fixture lane has no reader yet"
        assert core.gating(findings) == []

    def test_baseline_grandfathers_project_finding(self):
        findings = run_project_rule("env-lane", {LIB_PATH: WRITER})
        assert len(core.gating(findings)) == 1
        baseline = {findings[0].fingerprint: 1}
        findings = core.apply_baseline(findings, baseline)
        assert core.gating(findings) == []
