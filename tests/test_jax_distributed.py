"""Multi-process jax.distributed execution — the heart of the TPU-native
claim (reference analogue: TF_CONFIG/ClusterSpec assembly + gRPC cluster,
/root/reference/tensorflowonspark/TFSparkNode.py:277-299, which every
reference test exercised through a 2-worker standalone Spark cluster).

Here ≥2 OS processes each ``jax.distributed.initialize`` via the
reservation-derived world, federate their CPU devices over gloo, build ONE
global mesh, and run sharded train steps whose loss must agree across
processes (it is a global collective) and match a single-process run on the
same global batch. Covers the ``make_array_from_process_local_data`` branch
of ``shard_batch`` (parallel/sharding.py).
"""

import json
import os

import numpy as np
import pytest

from tensorflowonspark_tpu import TFCluster, util
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext

CPU_ENV = {"JAX_PLATFORMS": "cpu", "TOS_NUM_CPU_DEVICES": "2"}


def _deterministic_batch(n):
    """(images, labels) fixed by row index — identical in every process."""
    images = (np.arange(n * 28 * 28, dtype=np.float32).reshape(n, 28, 28) % 255.0) / 255.0
    labels = np.arange(n) % 10
    return images, labels


def _train_losses(ctx_args):
    """Body shared by the direct two-process world and the reference
    single-process run: 3 mnist-MLP train steps on a fixed global batch of
    16 rows; this process contributes rows [lo:hi). Returns the loss list.
    """
    import jax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel
    import optax

    lo, hi = ctx_args["rows"]
    mesh = parallel.build_mesh({"dp": -1})  # over ALL global devices
    strategy = SyncDataParallel(mesh)
    model = mnist.create_model("mlp")
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(mnist.make_loss_fn(model), optimizer, has_aux=True)

    images, labels = _deterministic_batch(16)
    local = {"image": images[lo:hi], "label": labels[lo:hi]}
    losses = []
    for _ in range(3):
        state, metrics = step(state, strategy.shard_batch(local))
        jax.block_until_ready(metrics["loss"])
        losses.append(float(metrics["loss"]))
    return losses


def _world_child(pid, num_procs, coord_port, rows, out_dir):
    """Entry of one spawned world member (module-level: spawn-picklable)."""
    from tensorflowonspark_tpu.testing import join_cpu_world

    join_cpu_world(pid, num_procs, coord_port, local_devices=2)
    import jax

    assert jax.process_count() == num_procs, jax.process_count()
    assert jax.device_count() == 2 * num_procs, jax.device_count()
    losses = _train_losses({"rows": rows})
    with open(os.path.join(out_dir, "proc{}.json".format(pid)), "w") as f:
        json.dump({"pid": pid, "losses": losses}, f)


@pytest.mark.parametrize("num_procs", [2])
def test_two_process_world_matches_single_process(tmp_path, num_procs):
    """2 OS processes × 2 CPU devices = one 4-device world; per-step losses
    agree across processes and with a single-process run on the full batch."""
    import functools

    coord_port = util.find_free_port()
    per = 16 // num_procs
    procs = [
        util.spawn_process(
            functools.partial(
                _world_child, pid, num_procs, coord_port, (pid * per, (pid + 1) * per), str(tmp_path)
            ),
            name="world-{}".format(pid),
        )
        for pid in range(num_procs)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=240)
    assert all(p.exitcode == 0 for p in procs), [p.exitcode for p in procs]

    results = []
    for pid in range(num_procs):
        with open(tmp_path / "proc{}.json".format(pid)) as f:
            results.append(json.load(f)["losses"])
    # the loss is a global collective: every process must report the same value
    for other in results[1:]:
        assert np.allclose(results[0], other, rtol=1e-5), results

    # and it must equal the single-process result on the same global batch
    reference = _train_losses({"rows": (0, 16)})
    assert np.allclose(results[0], reference, rtol=1e-4, atol=1e-5), (results[0], reference)
    # training actually progressed
    assert reference[-1] < reference[0]


def fn_distributed_train(args, ctx):
    """main_fun for the cluster-level test: the jax child was already
    initialized into the distributed world by the node runtime."""
    import jax

    out = {
        "executor_id": ctx.executor_id,
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        "losses": _train_losses({"rows": (ctx.process_id * 8, ctx.process_id * 8 + 8)}),
    }
    with open(os.path.join(args["out_dir"], "node{}.json".format(ctx.executor_id)), "w") as f:
        json.dump(out, f)


def test_cluster_forms_distributed_world(tmp_path):
    """TFCluster.run with jax_distributed=True (no CPU auto-disable): the two
    jax children join one world derived from the reservations and train on a
    global mesh; their collective losses agree."""
    sc = LocalSparkContext(num_executors=2, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_distributed_train, {"out_dir": str(tmp_path)}, num_executors=2,
            input_mode=InputMode.TENSORFLOW, master_node=None,
            env=CPU_ENV, jax_distributed=True, reservation_timeout=180,
        )
        cluster.shutdown(timeout=300)
    finally:
        sc.stop()
    reports = []
    for eid in range(2):
        with open(tmp_path / "node{}.json".format(eid)) as f:
            reports.append(json.load(f))
    assert all(r["process_count"] == 2 for r in reports), reports
    assert all(r["device_count"] == 4 for r in reports), reports
    assert np.allclose(reports[0]["losses"], reports[1]["losses"], rtol=1e-5), reports
