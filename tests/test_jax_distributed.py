"""Multi-process jax.distributed execution — the heart of the TPU-native
claim (reference analogue: TF_CONFIG/ClusterSpec assembly + gRPC cluster,
/root/reference/tensorflowonspark/TFSparkNode.py:277-299, which every
reference test exercised through a 2-worker standalone Spark cluster).

Here ≥2 OS processes each ``jax.distributed.initialize`` via the
reservation-derived world, federate their CPU devices over gloo, build ONE
global mesh, and run sharded train steps whose loss must agree across
processes (it is a global collective) and match a single-process run on the
same global batch. Covers the ``make_array_from_process_local_data`` branch
of ``shard_batch`` (parallel/sharding.py).
"""

import json
import os

import numpy as np
import pytest

from tensorflowonspark_tpu import TFCluster, util
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext

CPU_ENV = {"JAX_PLATFORMS": "cpu", "TOS_NUM_CPU_DEVICES": "2"}


def _deterministic_batch(n):
    """(images, labels) fixed by row index — identical in every process."""
    images = (np.arange(n * 28 * 28, dtype=np.float32).reshape(n, 28, 28) % 255.0) / 255.0
    labels = np.arange(n) % 10
    return images, labels


def _train_losses(ctx_args):
    """Body shared by the direct two-process world and the reference
    single-process run: 3 mnist-MLP train steps on a fixed global batch of
    16 rows; this process contributes rows [lo:hi). Returns the loss list.
    """
    import jax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel
    import optax

    lo, hi = ctx_args["rows"]
    mesh = parallel.build_mesh({"dp": -1})  # over ALL global devices
    strategy = SyncDataParallel(mesh)
    model = mnist.create_model("mlp")
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(mnist.make_loss_fn(model), optimizer, has_aux=True)

    images, labels = _deterministic_batch(16)
    local = {"image": images[lo:hi], "label": labels[lo:hi]}
    losses = []
    for _ in range(3):
        state, metrics = step(state, strategy.shard_batch(local))
        jax.block_until_ready(metrics["loss"])
        losses.append(float(metrics["loss"]))
    return losses


def _world_child(pid, num_procs, coord_port, rows, out_dir):
    """Entry of one spawned world member (module-level: spawn-picklable)."""
    from tensorflowonspark_tpu.testing import join_cpu_world

    join_cpu_world(pid, num_procs, coord_port, local_devices=2)
    import jax

    assert jax.process_count() == num_procs, jax.process_count()
    assert jax.device_count() == 2 * num_procs, jax.device_count()
    losses = _train_losses({"rows": rows})
    with open(os.path.join(out_dir, "proc{}.json".format(pid)), "w") as f:
        json.dump({"pid": pid, "losses": losses}, f)


@pytest.mark.parametrize("num_procs", [2])
def test_two_process_world_matches_single_process(tmp_path, num_procs):
    """2 OS processes × 2 CPU devices = one 4-device world; per-step losses
    agree across processes and with a single-process run on the full batch."""
    import functools

    coord_port = util.find_free_port()
    per = 16 // num_procs
    procs = [
        util.spawn_process(
            functools.partial(
                _world_child, pid, num_procs, coord_port, (pid * per, (pid + 1) * per), str(tmp_path)
            ),
            name="world-{}".format(pid),
        )
        for pid in range(num_procs)
    ]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=240)
    assert all(p.exitcode == 0 for p in procs), [p.exitcode for p in procs]

    results = []
    for pid in range(num_procs):
        with open(tmp_path / "proc{}.json".format(pid)) as f:
            results.append(json.load(f)["losses"])
    # the loss is a global collective: every process must report the same value
    for other in results[1:]:
        assert np.allclose(results[0], other, rtol=1e-5), results

    # and it must equal the single-process result on the same global batch
    reference = _train_losses({"rows": (0, 16)})
    assert np.allclose(results[0], reference, rtol=1e-4, atol=1e-5), (results[0], reference)
    # training actually progressed
    assert reference[-1] < reference[0]


def fn_distributed_train(args, ctx):
    """main_fun for the cluster-level test: the jax child was already
    initialized into the distributed world by the node runtime."""
    import jax

    out = {
        "executor_id": ctx.executor_id,
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        "losses": _train_losses({"rows": (ctx.process_id * 8, ctx.process_id * 8 + 8)}),
    }
    with open(os.path.join(args["out_dir"], "node{}.json".format(ctx.executor_id)), "w") as f:
        json.dump(out, f)


def fn_spark_feed_distributed(args, ctx):
    """SPARK-mode distributed consumer: each process trains from its OWN
    feed queue, contributing its local rows to the global batch via
    ``make_array_from_process_local_data`` (inside ``shard_batch``)."""
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel

    mesh = parallel.build_mesh({"dp": -1})
    strategy = SyncDataParallel(mesh)
    model = mnist.create_model("mlp")
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(mnist.make_loss_fn(model), optimizer, has_aux=True)
    feed = ctx.get_data_feed(train_mode=True)
    losses = []
    for _ in range(args["steps"]):
        batch = feed.next_batch(args["batch_size"])
        images = np.asarray([b[0] for b in batch], np.float32).reshape(-1, 28, 28)
        labels = np.asarray([int(b[1]) for b in batch])
        state, metrics = step(
            state, strategy.shard_batch({"image": images, "label": labels})
        )
        jax.block_until_ready(metrics["loss"])
        losses.append(float(metrics["loss"]))
    # uneven partitions leave unconsumed rows; terminate drains them so the
    # blocked feed tasks can finish (the steps_per_worker safeguard story)
    feed.terminate()
    out = {
        "executor_id": ctx.executor_id,
        "process_count": jax.process_count(),
        "device_count": jax.device_count(),
        "losses": losses,
    }
    with open(os.path.join(args["out_dir"], "node{}.json".format(ctx.executor_id)), "w") as f:
        json.dump(out, f)


@pytest.mark.slow
def test_spark_mode_distributed_training_with_uneven_partitions(tmp_path):
    """SURVEY §7 hard-parts 3/4 in one test (VERDICT r2 item 6): a 2-worker
    InputMode.SPARK cluster whose jax children join ONE collective world and
    train from their own feed queues, fed from deliberately uneven RDD
    partitions; the per-step loss is a global collective and must agree."""
    from tensorflowonspark_tpu.train import steps_per_worker

    rows = []
    images, labels = _deterministic_batch(40)
    for i in range(40):
        rows.append((images[i].reshape(-1).tolist(), int(labels[i])))
    # uneven partitions: sizes 16/12/8/4, pinned so each executor gets 20 rows
    parts = [rows[:16], rows[16:28], rows[28:36], rows[36:40]]
    flat = [r for part in parts for r in part]
    batch_size = 4
    steps = steps_per_worker(len(rows), batch_size, 2)  # floor(5)*0.9 = 4

    sc = LocalSparkContext(num_executors=2, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_spark_feed_distributed,
            {"out_dir": str(tmp_path), "steps": steps, "batch_size": batch_size},
            num_executors=2, input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=True, reservation_timeout=180,
        )
        rdd = sc.parallelize(flat, 4, pin_to_executors=[0, 1, 1, 0])
        # re-slice into the original uneven partitions (local backend RDD
        # partitions are (data, transform_chain) pairs)
        rdd._parts = [(p, ()) for p in parts]
        cluster.train(rdd, num_epochs=1, feed_timeout=120)
        cluster.shutdown(grace_secs=2, timeout=300)
    finally:
        sc.stop()

    reports = []
    for eid in range(2):
        with open(tmp_path / "node{}.json".format(eid)) as f:
            reports.append(json.load(f))
    assert all(r["process_count"] == 2 for r in reports), reports
    assert all(r["device_count"] == 4 for r in reports), reports
    assert all(len(r["losses"]) == steps for r in reports), reports
    # collective loss: both processes must report identical values
    assert np.allclose(reports[0]["losses"], reports[1]["losses"], rtol=1e-5), reports


def test_cluster_forms_distributed_world(tmp_path):
    """TFCluster.run with jax_distributed=True (no CPU auto-disable): the two
    jax children join one world derived from the reservations and train on a
    global mesh; their collective losses agree."""
    sc = LocalSparkContext(num_executors=2, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_distributed_train, {"out_dir": str(tmp_path)}, num_executors=2,
            input_mode=InputMode.TENSORFLOW, master_node=None,
            env=CPU_ENV, jax_distributed=True, reservation_timeout=180,
        )
        cluster.shutdown(timeout=300)
    finally:
        sc.stop()
    reports = []
    for eid in range(2):
        with open(tmp_path / "node{}.json".format(eid)) as f:
            reports.append(json.load(f))
    assert all(r["process_count"] == 2 for r in reports), reports
    assert all(r["device_count"] == 4 for r in reports), reports
    assert np.allclose(reports[0]["losses"], reports[1]["losses"], rtol=1e-5), reports
