"""Unit tests for the shared resilience policies (ISSUE 2 satellite):
backoff schedule determinism under a fixed seed, deadline expiry, and
circuit-breaker open/half-open/close transitions — independent of any
injection site."""

import itertools

import pytest

from tensorflowonspark_tpu import resilience


class TestBackoff:
    def test_deterministic_schedule_without_jitter(self):
        b = resilience.Backoff(base=1.0, factor=2.0, max_delay=5.0, jitter=0.0)
        assert list(itertools.islice(b.delays(), 5)) == [1.0, 2.0, 4.0, 5.0, 5.0]

    def test_seeded_jitter_is_reproducible(self):
        b = resilience.Backoff(base=1.0, factor=2.0, max_delay=30.0, jitter=1.0, seed=42)
        first = list(itertools.islice(b.delays(), 6))
        second = list(itertools.islice(b.delays(), 6))
        assert first == second  # re-seeded per delays() call
        other = resilience.Backoff(base=1.0, factor=2.0, max_delay=30.0, jitter=1.0, seed=43)
        assert first != list(itertools.islice(other.delays(), 6))

    def test_jitter_bounds(self):
        b = resilience.Backoff(base=2.0, factor=2.0, max_delay=16.0, jitter=0.5, seed=7)
        expected_caps = [2.0, 4.0, 8.0, 16.0, 16.0]
        for delay, cap in zip(itertools.islice(b.delays(), 5), expected_caps):
            assert cap * 0.5 <= delay <= cap  # floor = (1 - jitter) * cap

    def test_full_jitter_stays_under_cap(self):
        b = resilience.Backoff(base=1.0, factor=10.0, max_delay=3.0, jitter=1.0, seed=0)
        assert all(0.0 <= d <= 3.0 for d in itertools.islice(b.delays(), 20))

    def test_rejects_bad_parameters(self):
        with pytest.raises(ValueError):
            resilience.Backoff(base=-1)
        with pytest.raises(ValueError):
            resilience.Backoff(factor=0.5)
        with pytest.raises(ValueError):
            resilience.Backoff(jitter=2.0)


class TestTicker:
    """ISSUE 11 satellite: the heartbeat schedule is anchored to the
    monotonic clock (drift-free) and jittered (no lockstep fleets)."""

    def _run(self, ticker, n_ticks, work=0.0):
        """Drive a ticker on a fake clock; returns the sleep durations."""
        now = [100.0]
        sleeps = []

        def clock():
            return now[0]

        def sleep(s):
            sleeps.append(s)
            now[0] += s

        t = resilience.Ticker(
            ticker.interval, jitter=ticker.jitter, seed=ticker.seed,
            clock=clock, sleep=sleep,
        )
        for i in t.ticks():
            now[0] += work  # simulate the tick body taking time
            if i >= n_ticks - 1:
                break
        return sleeps

    def test_drift_free_schedule_without_jitter(self):
        spec = resilience.Ticker(2.0, jitter=0.0)
        assert self._run(spec, 4) == [2.0, 2.0, 2.0]

    def test_tick_body_time_is_absorbed_not_accumulated(self):
        """Each tick is scheduled at t0 + n*interval: a 0.5s body shortens
        the sleep instead of pushing every later tick back (the Backoff
        ticker it replaces slept a full interval after the body)."""
        spec = resilience.Ticker(2.0, jitter=0.0)
        sleeps = self._run(spec, 4, work=0.5)
        assert sleeps == [1.5, 1.5, 1.5]

    def test_overrun_skips_sleep(self):
        spec = resilience.Ticker(1.0, jitter=0.0)
        sleeps = self._run(spec, 3, work=5.0)
        assert sleeps == []  # behind schedule: never sleeps negative

    def test_jitter_bounds_and_reproducibility(self):
        spec = resilience.Ticker(2.0, jitter=0.25, seed=11)
        sleeps = self._run(spec, 20)
        # each due time is n*interval ± jitter*interval around the anchor
        assert all(0.0 <= s <= 3.0 for s in sleeps)
        assert any(s != 2.0 for s in sleeps)
        assert sleeps == self._run(spec, 20)  # seeded: reproducible
        other = resilience.Ticker(2.0, jitter=0.25, seed=12)
        assert sleeps != self._run(other, 20)  # different seed: different phase

    def test_deadline_stops_the_generator(self):
        now = [0.0]
        clock = lambda: now[0]

        def sleep(s):
            now[0] += s

        t = resilience.Ticker(1.0, jitter=0.0, clock=clock, sleep=sleep)
        d = resilience.Deadline(3.5, clock=clock)
        ticks = list(t.ticks(deadline=d))
        # the last sleep is clamped to the deadline edge and that tick still
        # fires — same contract as Backoff.attempts — then the generator ends
        assert ticks == [0, 1, 2, 3, 4]
        assert now[0] == pytest.approx(3.5)

    def test_rejects_bad_parameters(self):
        with pytest.raises(ValueError):
            resilience.Ticker(0.0)
        with pytest.raises(ValueError):
            resilience.Ticker(1.0, jitter=1.0)


class TestDeadline:
    def test_expiry_with_fake_clock(self):
        now = [0.0]
        d = resilience.Deadline(10.0, clock=lambda: now[0])
        assert d.remaining() == 10.0
        assert not d.expired()
        now[0] = 9.0
        assert d.remaining() == pytest.approx(1.0)
        d.check()  # still inside the budget
        now[0] = 10.0
        assert d.expired()
        assert d.remaining() == 0.0
        with pytest.raises(resilience.DeadlineExceeded):
            d.check()

    def test_unbounded(self):
        d = resilience.Deadline(None)
        assert d.remaining() is None
        assert not d.expired()
        d.check()
        assert d.clamp(123.0) == 123.0

    def test_clamp_never_overshoots(self):
        now = [0.0]
        d = resilience.Deadline(5.0, clock=lambda: now[0])
        assert d.clamp(60.0) == 5.0
        now[0] = 4.5
        assert d.clamp(60.0) == pytest.approx(0.5)


class TestRetryPolicy:
    def _policy(self, **kw):
        kw.setdefault("backoff", resilience.Backoff(base=0.0, jitter=0.0))
        kw.setdefault("sleep", lambda s: None)
        return resilience.RetryPolicy(**kw)

    def test_succeeds_after_transient_failures(self):
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        assert self._policy(max_attempts=3).call(flaky) == "ok"
        assert len(calls) == 3

    def test_budget_exhaustion_raises_last_error(self):
        def always():
            raise OSError("down")

        with pytest.raises(OSError, match="down"):
            self._policy(max_attempts=2).call(always)

    def test_non_retryable_errors_propagate_immediately(self):
        calls = []

        def boom():
            calls.append(1)
            raise ValueError("fatal")

        with pytest.raises(ValueError):
            self._policy(max_attempts=5, retry_on=(OSError,)).call(boom)
        assert len(calls) == 1

    def test_on_retry_hook_sees_attempt_error_and_delay(self):
        seen = []

        def hook(attempt, exc, delay):
            seen.append((attempt, str(exc), delay))

        def always():
            raise OSError("x")

        with pytest.raises(OSError):
            self._policy(max_attempts=3, on_retry=hook).call(always)
        # hook fires before each backoff sleep: attempts 0 and 1, never the last
        assert [s[0] for s in seen] == [0, 1]

    def test_sleeps_follow_backoff_schedule(self):
        slept = []
        policy = resilience.RetryPolicy(
            max_attempts=4,
            backoff=resilience.Backoff(base=1.0, factor=2.0, max_delay=30.0, jitter=0.0),
            sleep=slept.append,
        )

        def always():
            raise OSError("x")

        with pytest.raises(OSError):
            policy.call(always)
        assert slept == [1.0, 2.0, 4.0]

    def test_deadline_bounds_the_burst(self):
        # a deadline of 0 expires before the first retry sleep
        policy = resilience.RetryPolicy(
            max_attempts=10,
            backoff=resilience.Backoff(base=0.0, jitter=0.0),
            timeout=0.0,
            sleep=lambda s: None,
        )
        calls = []

        def always():
            calls.append(1)
            raise OSError("x")

        with pytest.raises(resilience.DeadlineExceeded):
            policy.call(always)
        assert len(calls) == 1  # no second attempt past the deadline

    def test_decorator_form(self):
        calls = []

        @self._policy(max_attempts=2)
        def flaky():
            calls.append(1)
            if len(calls) < 2:
                raise OSError("t")
            return 7

        assert flaky() == 7

    def test_rejects_zero_attempts(self):
        with pytest.raises(ValueError):
            resilience.RetryPolicy(max_attempts=0)


class TestCircuitBreaker:
    def _breaker(self, now, **kw):
        kw.setdefault("failure_threshold", 2)
        kw.setdefault("reset_timeout", 10.0)
        return resilience.CircuitBreaker(clock=lambda: now[0], **kw)

    def test_closed_to_open_to_half_open_to_closed(self):
        now = [0.0]
        cb = self._breaker(now)
        assert cb.state == resilience.CLOSED
        cb.record_failure()
        assert cb.state == resilience.CLOSED  # below threshold
        cb.record_failure()
        assert cb.state == resilience.OPEN
        assert not cb.allow()
        now[0] = 10.0  # reset timeout elapsed -> half-open probe admitted
        assert cb.state == resilience.HALF_OPEN
        assert cb.allow()
        cb.record_success()
        assert cb.state == resilience.CLOSED

    def test_half_open_failure_reopens_and_restarts_timer(self):
        now = [0.0]
        cb = self._breaker(now)
        cb.record_failure()
        cb.record_failure()
        now[0] = 10.0
        assert cb.state == resilience.HALF_OPEN
        cb.record_failure()  # the probe failed
        assert cb.state == resilience.OPEN
        now[0] = 19.0  # timer restarted at t=10: still open
        assert not cb.allow()
        now[0] = 20.0
        assert cb.allow()

    def test_success_resets_failure_streak(self):
        now = [0.0]
        cb = self._breaker(now, failure_threshold=3)
        cb.record_failure()
        cb.record_failure()
        cb.record_success()  # streak broken
        cb.record_failure()
        cb.record_failure()
        assert cb.state == resilience.CLOSED

    def test_call_fails_fast_when_open(self):
        now = [0.0]
        cb = self._breaker(now)
        calls = []

        def boom():
            calls.append(1)
            raise OSError("down")

        for _ in range(2):
            with pytest.raises(OSError):
                cb.call(boom)
        with pytest.raises(resilience.CircuitOpenError):
            cb.call(boom)
        assert len(calls) == 2  # the open circuit never invoked the function

    def test_call_closes_on_success(self):
        now = [0.0]
        cb = self._breaker(now)
        assert cb.call(lambda: "ok") == "ok"
        assert cb.state == resilience.CLOSED

    def _open_then_half_open(self, now):
        cb = self._breaker(now)
        cb.record_failure()
        cb.record_failure()
        now[0] = 10.0
        assert cb.state == resilience.HALF_OPEN
        return cb

    def test_half_open_admits_exactly_one_probe(self):
        now = [0.0]
        cb = self._open_then_half_open(now)
        assert cb.allow()          # the single trial request
        assert not cb.allow()      # a concurrent caller is refused
        assert not cb.allow()
        # reading the state must NOT consume or grant probe tokens
        assert cb.state == resilience.HALF_OPEN
        assert not cb.allow()
        cb.record_success()
        assert cb.state == resilience.CLOSED
        assert cb.allow()

    def test_half_open_concurrent_probes_race_one_winner(self):
        import threading

        now = [0.0]
        cb = self._open_then_half_open(now)
        admitted = []
        barrier = threading.Barrier(8)

        def probe():
            barrier.wait()
            if cb.allow():
                admitted.append(1)

        threads = [threading.Thread(target=probe) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert len(admitted) == 1  # exactly one trial passed

    def test_failed_probe_reopens_without_double_counting_trips(self):
        from tensorflowonspark_tpu import obs

        def trips():
            return obs.snapshot()["counters"].get(
                "circuit_open_total", {}
            ).get("value", 0)

        now = [0.0]
        before = trips()
        cb = self._open_then_half_open(now)
        assert trips() - before == 1  # the original trip
        assert cb.allow()
        cb.record_failure()  # the trial failed: re-open, count ONE more trip
        assert cb.state == resilience.OPEN
        assert trips() - before == 2
        # stragglers reporting after the re-open (a losing hedge sibling, a
        # refused concurrent probe's caller) must not re-trip
        cb.record_failure()
        cb.record_failure()
        assert trips() - before == 2
        # and the restarted timer admits a fresh single probe
        now[0] = 20.0
        assert cb.allow()
        assert not cb.allow()
        cb.record_success()
        assert cb.state == resilience.CLOSED
