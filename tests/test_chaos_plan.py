"""The chaos subsystem itself: plan determinism under a seed, per-site
probability/count budgets, env-var propagation, obs visibility of injected
faults, and the zero-overhead disabled path."""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tensorflowonspark_tpu import chaos, obs


@pytest.fixture(autouse=True)
def _clean_chaos():
    """Every test starts and ends with no plan installed (chaos state is
    process-global, like the obs registry)."""
    chaos.uninstall()
    yield
    chaos.uninstall()


class TestChaosPlan:
    def test_same_seed_same_schedule(self):
        def schedule(seed):
            plan = chaos.ChaosPlan(seed=seed).site("x.y", probability=0.5)
            return [plan.should_fire("x.y") is not None for _ in range(50)]

        assert schedule(7) == schedule(7)
        assert schedule(7) != schedule(8)

    def test_sites_draw_independent_rngs(self):
        # site A's schedule must not depend on how often site B is polled
        plan1 = chaos.ChaosPlan(seed=3).site("a", probability=0.5).site("b", probability=0.5)
        plan2 = chaos.ChaosPlan(seed=3).site("a", probability=0.5).site("b", probability=0.5)
        seq1 = []
        for i in range(30):
            plan1.should_fire("b")  # interleaved polls of the other site
            seq1.append(plan1.should_fire("a") is not None)
        seq2 = [plan2.should_fire("a") is not None for _ in range(30)]
        assert seq1 == seq2

    def test_max_count_budget(self):
        plan = chaos.ChaosPlan(seed=0).site("s", probability=1.0, max_count=3)
        fires = [plan.should_fire("s") for _ in range(10)]
        assert sum(1 for f in fires if f) == 3
        assert plan.fired("s") == 3
        assert plan.fired() == 3

    def test_probability_zero_never_fires(self):
        plan = chaos.ChaosPlan(seed=0).site("s", probability=0.0)
        assert all(plan.should_fire("s") is None for _ in range(100))

    def test_unknown_site_never_fires(self):
        plan = chaos.ChaosPlan(seed=0).site("s", probability=1.0)
        assert plan.should_fire("other") is None

    def test_json_roundtrip_preserves_schedule(self):
        plan = chaos.ChaosPlan(seed=11).site("s", probability=0.4, max_count=5, delay_s=0.2)
        clone = chaos.ChaosPlan.from_json(plan.to_json())
        assert clone.seed == plan.seed
        assert clone.sites == plan.sites
        a = [plan.should_fire("s") is not None for _ in range(40)]
        b = [clone.should_fire("s") is not None for _ in range(40)]
        assert a == b

    def test_rejects_bad_probability(self):
        with pytest.raises(ValueError):
            chaos.ChaosPlan().site("s", probability=1.5)


class TestInstall:
    def test_install_sets_active_and_env(self):
        assert not chaos.active
        plan = chaos.ChaosPlan(seed=1).site("s", probability=1.0)
        chaos.install(plan)
        assert chaos.active
        assert chaos.plan() is plan
        assert json.loads(os.environ[chaos.ENV_VAR])["seed"] == 1
        chaos.uninstall()
        assert not chaos.active
        assert chaos.ENV_VAR not in os.environ

    def test_install_without_propagation(self):
        chaos.install(chaos.ChaosPlan(seed=2), propagate=False)
        assert chaos.active
        assert chaos.ENV_VAR not in os.environ

    def test_child_process_inherits_plan_from_env(self):
        """The subprocess-propagation lane: a spawned interpreter re-installs
        the plan at import and fires the same deterministic schedule."""
        plan = chaos.ChaosPlan(seed=9).site("s", probability=0.5)
        parent = [plan.should_fire("s") is not None for _ in range(20)]
        code = textwrap.dedent(
            """
            from tensorflowonspark_tpu import chaos
            assert chaos.active, "plan not installed from env"
            p = chaos.plan()
            print([p.should_fire("s") is not None for _ in range(20)])
            """
        )
        pkg_dir = os.path.dirname(os.path.dirname(os.path.abspath(chaos.__file__)))
        repo_root = os.path.dirname(pkg_dir)
        env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo_root)
        env[chaos.ENV_VAR] = plan.to_json()
        out = subprocess.run(
            [sys.executable, "-c", code],
            env=env, cwd=repo_root, capture_output=True, text=True,
        )
        assert out.returncode == 0, out.stderr
        assert eval(out.stdout.strip()) == parent

    def test_malformed_env_plan_is_ignored(self):
        os.environ[chaos.ENV_VAR] = "{not json"
        try:
            chaos._install_from_env()  # must not raise
            assert not chaos.active
        finally:
            os.environ.pop(chaos.ENV_VAR, None)


class TestFire:
    def test_fire_records_obs_counters_and_span(self):
        chaos.install(chaos.ChaosPlan(seed=0).site("unit.test_site", probability=1.0))
        before = obs.snapshot()["counters"].get("chaos_faults_injected_total", {}).get("value", 0)
        assert chaos.fire("unit.test_site") is not None
        snap = obs.snapshot()
        assert snap["counters"]["chaos_faults_injected_total"]["value"] == before + 1
        assert snap["counters"]["chaos_fault_unit_test_site_total"]["value"] >= 1
        assert any(
            e.get("span") == "chaos_fault" and e.get("site") == "unit.test_site"
            for e in snap["events"]
        )

    def test_fire_disabled_returns_none(self):
        assert chaos.fire("anything") is None

    def test_delay_sleeps_only_when_triggered(self):
        chaos.install(chaos.ChaosPlan(seed=0).site("d", probability=1.0, delay_s=0.0))
        assert chaos.delay("d") is True
        assert chaos.delay("not_a_site") is False

    def test_fire_appends_to_chaos_log(self, tmp_path, monkeypatch):
        log = tmp_path / "chaos.log"
        monkeypatch.setenv(chaos.LOG_ENV_VAR, str(log))
        chaos.install(chaos.ChaosPlan(seed=0).site("logged.site", probability=1.0))
        chaos.fire("logged.site")
        chaos.fire("logged.site")
        assert log.read_text().splitlines() == ["logged.site", "logged.site"]


class TestDisabledOverhead:
    def test_disabled_guard_allocates_nothing(self):
        """The acceptance bar: with chaos disabled a site costs one cached
        boolean check — no allocation, no call into the plan machinery."""
        assert not chaos.active
        for _ in range(10):  # warm attribute caches
            if chaos.active:
                chaos.fire("never")
        before = sys.getallocatedblocks()
        for _ in range(1000):
            if chaos.active:
                chaos.fire("never")
        grown = sys.getallocatedblocks() - before
        # zero in practice; tolerate interpreter-internal noise (same bound
        # as the disabled obs-registry test) — 1000 iterations of real
        # allocation would show thousands of blocks
        assert grown < 50, "disabled chaos guard allocated {} blocks".format(grown)

    def test_disabled_guard_never_reaches_fire(self, monkeypatch):
        def explode(site):
            raise AssertionError("fire() reached with chaos disabled")

        monkeypatch.setattr(chaos, "fire", explode)
        if chaos.active:  # the exact guard every injection site uses
            chaos.fire("never")
