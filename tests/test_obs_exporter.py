"""Parser-level validation of the Prometheus exposition output + the HTTP
endpoint. The parser below implements the text-format 0.0.4 grammar the repo
emits (HELP/TYPE comment lines, `name{labels} value` samples) so the tests
fail on any malformed line, not just on missing substrings."""

import json
import re
import urllib.request

import pytest

from tensorflowonspark_tpu.obs import exporter
from tensorflowonspark_tpu.obs.registry import Registry

SAMPLE_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?"
    r" (?P<value>[^ ]+)$"
)
HELP_RE = re.compile(r"^# HELP (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*) (?P<text>.*)$")
TYPE_RE = re.compile(
    r"^# TYPE (?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*) (?P<kind>counter|gauge|histogram|summary|untyped)$"
)


def parse_exposition(text):
    """Parse exposition text into {family: {"type","help","samples":[(name, labels, value)]}}.
    Raises AssertionError on any line that is not valid format 0.0.4."""
    assert text.endswith("\n"), "exposition text must end with a newline"
    families = {}
    current = None
    for line in text.splitlines():
        if not line.strip():
            continue
        m = HELP_RE.match(line)
        if m:
            families.setdefault(m.group("name"), {"samples": []})["help"] = m.group("text")
            continue
        m = TYPE_RE.match(line)
        if m:
            fam = families.setdefault(m.group("name"), {"samples": []})
            fam["type"] = m.group("kind")
            current = m.group("name")
            continue
        assert not line.startswith("#"), "unrecognized comment line: {!r}".format(line)
        m = SAMPLE_RE.match(line)
        assert m, "malformed sample line: {!r}".format(line)
        name, labels_raw, value = m.group("name", "labels", "value")
        labels = {}
        if labels_raw:
            for pair in labels_raw.split(","):
                lm = re.match(r'^([a-zA-Z_][a-zA-Z0-9_]*)="(.*)"$', pair)
                assert lm, "malformed label pair: {!r}".format(pair)
                labels[lm.group(1)] = lm.group(2)
        if value == "+Inf":
            val = float("inf")
        else:
            val = float(value)
        # samples belong to the family whose name is a prefix (histogram
        # children are name_bucket/name_sum/name_count)
        fam_name = current if current and name.startswith(current) else name
        families.setdefault(fam_name, {"samples": []})["samples"].append((name, labels, val))
    return families


@pytest.fixture
def snap():
    reg = Registry()
    reg.counter("requests_total", help="total requests").inc(3)
    reg.gauge("queue_depth", help="pending").set(2.5)
    h = reg.histogram("latency_seconds", help="latency", buckets=(0.1, 0.5, 1.0))
    for v in (0.05, 0.05, 0.3, 2.0):
        h.observe(v)
    return reg.snapshot()


def test_counter_and_gauge_render(snap):
    fams = parse_exposition(exporter.render_prometheus(snap))
    assert fams["requests_total"]["type"] == "counter"
    assert fams["requests_total"]["help"] == "total requests"
    assert fams["requests_total"]["samples"] == [("requests_total", {}, 3.0)]
    assert fams["queue_depth"]["type"] == "gauge"
    assert fams["queue_depth"]["samples"] == [("queue_depth", {}, 2.5)]


def test_histogram_buckets_are_cumulative_and_inf_equals_count(snap):
    fams = parse_exposition(exporter.render_prometheus(snap))
    fam = fams["latency_seconds"]
    assert fam["type"] == "histogram"
    buckets = {s[1]["le"]: s[2] for s in fam["samples"] if s[0] == "latency_seconds_bucket"}
    # non-cumulative input was [2, 1, 0]; output must be cumulative
    assert buckets == {"0.1": 2.0, "0.5": 3.0, "1": 3.0, "+Inf": 4.0}
    # cumulative monotone, +Inf == _count sample
    count = [s for s in fam["samples"] if s[0] == "latency_seconds_count"][0][2]
    assert buckets["+Inf"] == count == 4.0
    total = [s for s in fam["samples"] if s[0] == "latency_seconds_sum"][0][2]
    assert total == pytest.approx(2.4)


def test_every_sample_line_is_well_formed(snap):
    # parse_exposition asserts line-by-line; a malformed line raises
    fams = parse_exposition(exporter.render_prometheus(snap))
    for fam in fams.values():
        assert "type" in fam, "sample emitted without a TYPE header"


def test_metric_names_are_sanitized():
    snap = {"counters": {"bad-name.with spaces": {"value": 1, "help": ""}}}
    text = exporter.render_prometheus(snap)
    fams = parse_exposition(text)
    assert "bad_name_with_spaces" in fams


def test_help_text_escapes_newlines():
    snap = {"counters": {"c": {"value": 1, "help": "line1\nline2"}}}
    text = exporter.render_prometheus(snap)
    parse_exposition(text)  # still one HELP line, still parseable
    assert "# HELP c line1\\nline2" in text


def test_integer_values_render_bare():
    snap = {"counters": {"c": {"value": 5.0, "help": ""}}}
    assert "c 5\n" in exporter.render_prometheus(snap)


def test_render_json_round_trips(snap):
    assert json.loads(exporter.render_json(snap)) == json.loads(json.dumps(snap))


def test_http_server_serves_metrics_and_json():
    reg = Registry()
    reg.counter("hits_total").inc(2)
    srv = exporter.MetricsHTTPServer(reg.snapshot, host="127.0.0.1", port=0).start()
    try:
        base = "http://127.0.0.1:{}".format(srv.address[1])
        resp = urllib.request.urlopen(base + "/metrics", timeout=10)
        assert resp.status == 200
        assert resp.headers["Content-Type"] == exporter.CONTENT_TYPE
        fams = parse_exposition(resp.read().decode("utf-8"))
        assert fams["hits_total"]["samples"] == [("hits_total", {}, 2.0)]

        resp = urllib.request.urlopen(base + "/metrics.json", timeout=10)
        assert resp.status == 200
        assert resp.headers["Content-Type"] == "application/json"
        snap = json.loads(resp.read().decode("utf-8"))
        assert snap["counters"]["hits_total"]["value"] == 2

        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(base + "/nope", timeout=10)
        assert exc_info.value.code == 404
    finally:
        srv.stop()


def test_http_server_survives_broken_snapshot_fn():
    def broken():
        raise RuntimeError("snapshot exploded")

    srv = exporter.MetricsHTTPServer(broken, host="127.0.0.1", port=0).start()
    try:
        base = "http://127.0.0.1:{}".format(srv.address[1])
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(base + "/metrics", timeout=10)
        assert exc_info.value.code == 500
    finally:
        srv.stop()
