"""Control-plane tests, cluster-free (mirrors reference test/test_reservation.py)."""

import os
import threading
import time
from unittest import mock

import pytest

from tensorflowonspark_tpu import reservation, resilience


class TestReservations:
    def test_counting(self):
        store = reservation.Reservations(3)
        assert store.remaining() == 3
        assert not store.done
        store.add({"node": 0})
        store.add({"node": 1})
        assert store.remaining() == 1
        store.add({"node": 2})
        assert store.done
        assert len(store.get()) == 3

    def test_wait_timeout(self):
        store = reservation.Reservations(1)
        assert not store.wait(timeout=0.1)
        store.add({"node": 0})
        assert store.wait(timeout=0.1)


class TestServerClient:
    def test_register_query_info_stop(self):
        server = reservation.Server(2)
        addr = server.start()
        try:
            client = reservation.Client(addr)
            assert client.get_reservations() == []
            client.register({"host": "a", "executor_id": 0})
            client.register({"host": "b", "executor_id": 1})
            info = client.await_reservations(timeout=5)
            assert {r["host"] for r in info} == {"a", "b"}
            assert not client.stop_requested()
            client.request_stop()
            assert client.stop_requested()
            assert server.stop_requested
        finally:
            server.stop()

    def test_driver_await_aborts_on_node_error(self):
        server = reservation.Server(2)
        server.start()
        try:
            status = {}

            def fail_soon():
                time.sleep(0.2)
                status["error"] = "boom on executor 1"

            threading.Thread(target=fail_soon, daemon=True).start()
            with pytest.raises(reservation.ReservationError, match="boom"):
                server.await_reservations(status=status, timeout=10, poll_interval=0.05)
        finally:
            server.stop()

    def test_driver_await_times_out(self):
        server = reservation.Server(2)
        server.start()
        try:
            with pytest.raises(reservation.ReservationError, match="timed out"):
                server.await_reservations(timeout=0.3, poll_interval=0.05)
        finally:
            server.stop()

    def test_env_overrides(self):
        with mock.patch.dict(os.environ, {reservation.ENV_SERVER_HOST: "visible.example"}):
            server = reservation.Server(1)
            host, port = server.start()
            try:
                assert host == "visible.example"
                assert port > 0
            finally:
                server.stop()

    def test_concurrent_clients(self):
        n = 4
        server = reservation.Server(n)
        addr = server.start()
        try:
            def reserve(i):
                c = reservation.Client(addr)
                c.register({"executor_id": i})
                c.await_reservations(timeout=10, poll_interval=0.05)

            threads = [threading.Thread(target=reserve, args=(i,)) for i in range(n)]
            for t in threads:
                t.start()
            got = server.await_reservations(timeout=10, poll_interval=0.05)
            for t in threads:
                t.join(timeout=10)
            assert sorted(r["executor_id"] for r in got) == list(range(n))
        finally:
            server.stop()


class TestDriverRestartWindow:
    """ISSUE 11 satellite: connection-refused during a driver restart is
    retried under a deadline-bounded policy instead of failing fast."""

    FAST = resilience.Backoff(base=0.05, factor=1.0, max_delay=0.05, jitter=0.0)

    @staticmethod
    def _free_port():
        import socket as _socket

        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def test_client_rides_out_a_driver_restart(self):
        port = self._free_port()  # nothing listening yet: connection refused
        client = reservation.Client(
            ("127.0.0.1", port), restart_window=20, backoff=self.FAST
        )
        result = {}

        def register():
            client.register({"executor_id": 0})
            result["reservations"] = client.await_reservations(
                timeout=20, poll_interval=0.05
            )

        t = threading.Thread(target=register, daemon=True)
        t.start()
        time.sleep(0.4)  # let the client knock on the closed port a few times
        with mock.patch.dict(os.environ, {reservation.ENV_SERVER_PORT: str(port)}):
            server = reservation.Server(1)
            server.start()  # the "restarted driver" comes back on the same addr
        try:
            t.join(timeout=20)
            assert not t.is_alive()
            assert result["reservations"][0]["executor_id"] == 0
        finally:
            server.stop()

    def test_window_exhaustion_names_address_and_budget(self):
        port = self._free_port()
        client = reservation.Client(
            ("127.0.0.1", port), restart_window=0.3, backoff=self.FAST
        )
        started = time.monotonic()
        with pytest.raises(reservation.ReservationError) as exc_info:
            client.register({"executor_id": 0})
        msg = str(exc_info.value)
        assert "127.0.0.1:{}".format(port) in msg
        assert "connection-refused retries" in msg
        assert "driver restart window 0s" in msg or "restart window" in msg
        assert time.monotonic() - started < 10  # bounded by the window, not RETRIES*backoff


class TestIdempotentRegister:
    def test_duplicate_executor_id_replaces(self):
        store = reservation.Reservations(2)
        store.add({"executor_id": 0, "v": 1})
        store.add({"executor_id": 0, "v": 2})  # retried REG
        assert not store.done
        assert store.get() == [{"executor_id": 0, "v": 2}]
        store.add({"executor_id": 1, "v": 1})
        assert store.done

    def test_non_object_json_does_not_kill_server(self):
        import socket as _socket
        import struct as _struct

        server = reservation.Server(1)
        _host, port = server.start()
        try:
            payload = b"123"
            with _socket.create_connection(("127.0.0.1", port)) as s:
                s.sendall(_struct.pack(">I", len(payload)) + payload)
            c = reservation.Client(("127.0.0.1", port))
            c.register({"executor_id": 0})
            assert server.reservations.done
        finally:
            server.stop()
