"""Integration: a seeded chaos plan exercised through a live 2-node cluster.

The acceptance bar for the chaos subsystem: faults injected in every
process — the driver's reservation server and the spawned jax children —
are absorbed by the recovery machinery (the cluster assembles, inference
returns correct results) and each one is visible as a counter in the merged
``TFCluster.metrics()`` snapshot."""

import time

import pytest

from tensorflowonspark_tpu import TFCluster, chaos
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext

pytestmark = pytest.mark.chaos

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


@pytest.fixture
def sc():
    ctx = LocalSparkContext(num_executors=2, task_timeout=120)
    yield ctx
    ctx.stop()


def fn_square_feed_under_chaos(args, ctx):
    # the plan must have propagated into the spawned jax child (env lane)
    from tensorflowonspark_tpu import chaos as _chaos

    assert _chaos.active, "chaos plan did not reach the jax child"
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(16)
        if batch:
            feed.batch_results([x * x for x in batch])


def fn_pipeline_under_chaos(args, ctx):
    # the read-ahead reader must hit the data.shard_read site inside the
    # spawned child, and the fault counter must travel back through the
    # metrics merge lane
    import numpy as np

    from tensorflowonspark_tpu import chaos as _chaos
    from tensorflowonspark_tpu.data import ImagePipeline

    assert _chaos.active, "chaos plan did not reach the jax child"

    def parse(rec):
        v = int(rec)
        return np.full((2, 2, 1), v, np.float32), v

    pipe = ImagePipeline(
        [args["shard"]], parse, batch_size=4, shuffle=False, epochs=1,
        readahead=2, chunk_records=8,
    )
    n = sum(b["label"].shape[0] for b in pipe)
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(16)
        if batch:
            feed.batch_results([n for _ in batch])


def _parse_2x2(rec):
    # module-level: decode-plane workers are forked, the parse fn must be
    # importable/fork-inheritable
    import numpy as np

    v = int(rec)
    return np.full((2, 2, 1), v, np.float32), v


def fn_decode_plane_under_chaos(args, ctx):
    # the decode plane runs inside the spawned jax child; the chaos kill
    # SIGKILLs one worker mid-round and the respawned pool must deliver
    # every record exactly once — the child proves the stream intact and
    # the fault/restart counters travel back through the metrics merge
    from tensorflowonspark_tpu import chaos as _chaos
    from tensorflowonspark_tpu.data import ImagePipeline

    assert _chaos.active, "chaos plan did not reach the jax child"

    pipe = ImagePipeline(
        [args["shard"]], _parse_2x2, batch_size=4, shuffle=False, epochs=1,
        decode_workers=2,
    )
    labels = [int(x) for b in pipe for x in b["label"]]
    ok = labels == list(range(16))
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(16)
        if batch:
            feed.batch_results([int(ok) for _ in batch])


class TestClusterChaos:
    def test_faults_injected_and_recovered_across_the_cluster(self, sc):
        plan = (
            chaos.ChaosPlan(seed=7)
            # driver side: the reservation server drops one registration;
            # the client's shared retry policy re-registers
            .site("reservation.reg_drop", probability=1.0, max_count=1)
            # child side: the DataFeed sleeps before dequeueing
            .site("feed.slow_consumer", probability=1.0, max_count=2, delay_s=0.01)
        )
        chaos.install(plan)  # propagate=True: children inherit via env
        cluster = TFCluster.run(
            sc, fn_square_feed_under_chaos, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        try:
            # recovery completed: every row fed and answered correctly
            results = cluster.inference(sc.parallelize(range(100), 4)).collect()
            assert sorted(results) == sorted(x * x for x in range(100))

            # the driver-side fault fired in this process
            assert plan.fired("reservation.reg_drop") == 1

            # child counters arrive on the SnapshotPublisher interval — poll
            # the merged snapshot until the children's faults land
            deadline = time.monotonic() + 60
            while True:
                snap = cluster.metrics()
                child_faults = (
                    snap["counters"]
                    .get("chaos_fault_feed_slow_consumer_total", {})
                    .get("value", 0)
                )
                if child_faults >= 2 or time.monotonic() > deadline:
                    break
                time.sleep(0.5)

            counters = snap["counters"]
            # every fault class visible through cluster.metrics()
            assert counters["chaos_fault_reservation_reg_drop_total"]["value"] >= 1
            assert counters["chaos_fault_feed_slow_consumer_total"]["value"] >= 2
            assert counters["chaos_faults_injected_total"]["value"] >= 3
            # (the forced client retry is counted in the executor process's
            # registry, which has no merge lane — test_chaos_reservation
            # asserts reservation_client_retries_total in-process)
        finally:
            cluster.shutdown(timeout=120)

    def test_shard_read_faults_surface_in_cluster_metrics(self, sc, tmp_path):
        from tensorflowonspark_tpu import tfrecord

        shard = str(tmp_path / "part-00000")
        with tfrecord.TFRecordWriter(shard) as w:
            for i in range(16):
                w.write(str(i).encode())

        # delay faults on every shard open: absorbed invisibly by the
        # read-ahead reader, visible only as counters
        plan = chaos.ChaosPlan(seed=3).site(
            "data.shard_read", probability=1.0, max_count=2, delay_s=0.01
        )
        chaos.install(plan)  # propagate=True: children inherit via env
        cluster = TFCluster.run(
            sc, fn_pipeline_under_chaos, {"shard": shard}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        try:
            # every child consumed all 16 records through the chaos-delayed
            # read-ahead path
            results = cluster.inference(sc.parallelize(range(8), 4)).collect()
            assert results == [16] * 8

            # child counters arrive on the SnapshotPublisher interval
            deadline = time.monotonic() + 60
            while True:
                snap = cluster.metrics()
                faults = (
                    snap["counters"]
                    .get("chaos_fault_data_shard_read_total", {})
                    .get("value", 0)
                )
                if faults >= 1 or time.monotonic() > deadline:
                    break
                time.sleep(0.5)
            assert faults >= 1
        finally:
            cluster.shutdown(timeout=120)

    def test_decode_kill_respawns_without_losing_rows(self, sc, tmp_path):
        import importlib.util

        if importlib.util.find_spec("multiprocessing.shared_memory") is None:
            pytest.skip("no shared_memory on this platform")
        from tensorflowonspark_tpu import tfrecord

        shard = str(tmp_path / "part-00000")
        with tfrecord.TFRecordWriter(shard) as w:
            for i in range(16):
                w.write(str(i).encode())

        plan = chaos.ChaosPlan(seed=11).site(
            "data.decode_kill", probability=1.0, max_count=1
        )
        chaos.install(plan)  # propagate=True: children inherit via env
        cluster = TFCluster.run(
            sc, fn_decode_plane_under_chaos, {"shard": shard}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        try:
            # every child's stream survived the worker kill intact
            results = cluster.inference(sc.parallelize(range(8), 4)).collect()
            assert results == [1] * 8

            # child counters arrive on the SnapshotPublisher interval
            deadline = time.monotonic() + 60
            while True:
                snap = cluster.metrics()
                counters = snap["counters"]
                kills = (
                    counters.get("chaos_fault_data_decode_kill_total", {})
                    .get("value", 0)
                )
                restarts = (
                    counters.get("decode_worker_restarts_total", {})
                    .get("value", 0)
                )
                if (kills >= 1 and restarts >= 1) or time.monotonic() > deadline:
                    break
                time.sleep(0.5)
            assert kills >= 1
            assert restarts >= 1
        finally:
            cluster.shutdown(timeout=120)
