"""End-to-end tracing-plane smoke: one small train on the local backend must
leave flight shards from every process tier (driver, Spark executor, jax
child) that merge into a schema-valid, single-trace Chrome timeline.

Driven by ``./run_tests.sh --trace-smoke``, which exports ``TOS_TRACE_DIR``
(so the shards survive for the CLI-side ``tracemerge --check`` assertions)
and a benign one-shot chaos plan (so the automatic ring dump on fault
injection is exercised too).  Standalone runs record into a tmp dir.
"""

import os

import pytest

from tensorflowonspark_tpu import TFCluster
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext
from tensorflowonspark_tpu.obs import flight, tracemerge, tracing

pytestmark = pytest.mark.slow


def fn_consume_all(args, ctx):
    feed = ctx.get_data_feed()
    while not feed.should_stop():
        feed.next_batch(16)


class TestTraceSmoke:
    def test_train_leaves_mergeable_flight_recording(self, tmp_path, monkeypatch):
        root = os.environ.get(flight.TRACE_DIR_ENV) or str(tmp_path / "traces")
        tracing.reset()
        monkeypatch.setenv(flight.TRACE_DIR_ENV, root)
        sc = LocalSparkContext(num_executors=1, task_timeout=120)
        try:
            cluster = TFCluster.run(
                sc, fn_consume_all, {}, num_executors=1,
                input_mode=InputMode.SPARK, master_node=None,
                env={"JAX_PLATFORMS": "cpu"}, jax_distributed=False,
                reservation_timeout=180,
            )
            cluster.train(sc.parallelize(range(200), 2), feed_timeout=60)
            cluster.shutdown(timeout=120)
        finally:
            sc.stop()

        # every tier recorded its own shard
        procs = set()
        for shard in flight.list_shards(root):
            records, _ = flight.read_shard(shard)
            meta = next((r for r in records if r.get("kind") == "meta"), {})
            procs.add(meta.get("proc", "?"))
        assert "driver" in procs
        assert any(p.startswith("executor") for p in procs)
        assert any(p.startswith("jax-") for p in procs)

        trace, summary = tracemerge.merge_directory(root)
        assert tracemerge.validate_chrome_trace(trace) == []
        assert len(summary["trace_ids"]) == 1
        span_names = {
            e["name"] for e in trace["traceEvents"] if e.get("ph") in ("B", "X")
        }
        assert {"reservation_roundtrip", "node_launch", "node_main",
                "feed_wave"} <= span_names
        if os.environ.get("TOS_CHAOS_PLAN"):
            # the benign fault must have force-dumped someone's ring
            assert any(
                e.get("ph") == "i" and e.get("name") == "flight_dump"
                for e in trace["traceEvents"]
            )
