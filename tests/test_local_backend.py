"""Tests for the local multi-process execution backend."""

import os
import time

import pytest

from tensorflowonspark_tpu.backends.local import LocalSparkContext, TaskError


@pytest.fixture(scope="module")
def sc():
    ctx = LocalSparkContext(num_executors=2, task_timeout=60)
    yield ctx
    ctx.stop()


def _square_partition(it):
    return [x * x for x in it]


def test_parallelize_collect(sc):
    rdd = sc.parallelize(range(10), 4)
    assert rdd.getNumPartitions() == 4
    assert sorted(rdd.collect()) == list(range(10))


def test_map_partitions_and_sum(sc):
    rdd = sc.parallelize(range(5), 2).mapPartitions(_square_partition)
    assert rdd.sum() == sum(x * x for x in range(5))


def test_map_and_count(sc):
    rdd = sc.parallelize(range(7), 2).map(lambda x: x + 1)
    assert rdd.count() == 7
    assert sorted(rdd.collect()) == list(range(1, 8))


def test_union_epochs(sc):
    rdd = sc.parallelize(range(3), 1)
    unioned = sc.union([rdd] * 3)
    assert unioned.getNumPartitions() == 3
    assert sorted(unioned.collect()) == sorted(list(range(3)) * 3)


def test_union_of_transformed_rdds(sc):
    """The epochs-via-union trick must work on an already-mapped RDD
    (TFCluster.train unions a user RDD that typically has map chains)."""
    rdd = sc.parallelize(range(3), 1).map(lambda x: x * 10)
    other = sc.parallelize(range(2), 1).mapPartitions(_square_partition)
    unioned = sc.union([rdd, rdd, other])
    assert sorted(unioned.collect()) == sorted([0, 10, 20] * 2 + [0, 1])


def test_error_propagates_with_remote_traceback(sc):
    def boom(it):
        raise ValueError("deliberate failure in task")

    with pytest.raises(TaskError, match="deliberate failure"):
        sc.parallelize(range(4), 2).mapPartitions(boom).collect()


def test_pinned_tasks_run_on_distinct_executors(sc):
    def report_executor(it):
        list(it)
        return [int(os.environ["TOS_LOCAL_EXECUTOR_ID"])]

    rdd = sc.parallelize(range(2), 2, pin_to_executors=True)
    eids = rdd.mapPartitions(report_executor).collect()
    assert sorted(eids) == [0, 1]


def test_executor_state_persists_across_tasks(sc):
    """One task writes a file in the executor CWD; a pinned follow-up task on
    the same executor sees it (the SPARK_REUSE_WORKER analogue)."""

    def write_marker(it):
        list(it)
        with open("marker.txt", "w") as f:
            f.write(os.environ["TOS_LOCAL_EXECUTOR_ID"])
        return [1]

    def read_marker(it):
        list(it)
        return [os.path.exists("marker.txt")]

    sc.parallelize(range(2), 2, pin_to_executors=True).mapPartitions(write_marker).collect()
    got = sc.parallelize(range(2), 2, pin_to_executors=True).mapPartitions(read_marker).collect()
    assert got == [True, True]


def test_concurrent_jobs(sc):
    """A blocking job on pinned slots must not starve a second job — executors
    pull shared-queue tasks as they free up."""
    import threading

    def slowish(it):
        time.sleep(0.3)
        return [sum(it)]

    results = {}

    def run(name, pin):
        rdd = sc.parallelize(range(4), 2, pin_to_executors=pin)
        results[name] = rdd.mapPartitions(slowish).sum()

    t1 = threading.Thread(target=run, args=("a", True))
    t2 = threading.Thread(target=run, args=("b", False))
    t1.start(), t2.start()
    t1.join(30), t2.join(30)
    assert results["a"] == results["b"] == sum(range(4))
