"""Fixture tests for the retry-discipline and lock-discipline rules."""

import textwrap

from tosa_testutil import run_rule


def _src(s):
    return textwrap.dedent(s).lstrip()


class TestRetryDiscipline:
    def test_sleep_in_while_loop_fires(self):
        findings = run_rule("retry-discipline", _src("""
            import time

            def wait(q):
                while q.empty():
                    time.sleep(0.1)
        """))
        assert len(findings) == 1
        assert "resilience" in findings[0].message

    def test_aliased_import_fires(self):
        findings = run_rule("retry-discipline", _src("""
            import time as _time

            def wait(n):
                for _ in range(n):
                    _time.sleep(0.5)
        """))
        assert len(findings) == 1

    def test_from_import_sleep_fires(self):
        findings = run_rule("retry-discipline", _src("""
            from time import sleep as snooze

            def wait(n):
                for _ in range(n):
                    snooze(1)
        """))
        assert len(findings) == 1

    def test_sleep_outside_loop_is_clean(self):
        findings = run_rule("retry-discipline", _src("""
            import time

            def settle():
                time.sleep(0.2)
        """))
        assert findings == []

    def test_resilience_module_is_exempt(self):
        findings = run_rule("retry-discipline", _src("""
            import time

            def attempts():
                while True:
                    time.sleep(0.1)
        """), relpath="tensorflowonspark_tpu/resilience.py")
        assert findings == []

    def test_backoff_attempts_loop_is_clean(self):
        findings = run_rule("retry-discipline", _src("""
            from tensorflowonspark_tpu import resilience

            def wait(ready):
                tick = resilience.Backoff(base=0.1, jitter=0.0)
                for _ in tick.attempts(deadline=resilience.Deadline(30)):
                    if ready():
                        break
                else:
                    raise TimeoutError("not ready")
        """))
        assert findings == []

    def test_function_defined_in_loop_is_clean(self):
        # the def boundary resets loop ancestry: the sleep runs when the
        # callback is invoked, not per loop iteration
        findings = run_rule("retry-discipline", _src("""
            import time

            def make_callbacks(n):
                out = []
                for _ in range(n):
                    def cb():
                        time.sleep(0.1)
                    out.append(cb)
                return out
        """))
        assert findings == []


class TestLockDiscipline:
    def test_unlocked_cross_thread_write_fires(self):
        findings = run_rule("lock-discipline", _src("""
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._lock = threading.Lock()

                def start(self):
                    threading.Thread(target=self._run, daemon=True).start()

                def _run(self):
                    self.count = 1

                def bump(self):
                    self.count = 2
        """))
        assert len(findings) == 2  # both unlocked writes are reported
        assert all("self.count" in f.message for f in findings)

    def test_locked_writes_are_clean(self):
        findings = run_rule("lock-discipline", _src("""
            import threading

            class Worker:
                def __init__(self):
                    self.count = 0
                    self._lock = threading.Lock()

                def start(self):
                    threading.Thread(target=self._run, daemon=True).start()

                def _run(self):
                    with self._lock:
                        self.count = 1

                def bump(self):
                    with self._lock:
                        self.count = 2
        """))
        assert findings == []

    def test_single_thread_ownership_is_clean(self):
        # only the spawned thread writes the attr after __init__: no race
        findings = run_rule("lock-discipline", _src("""
            import threading

            class Ticker:
                def __init__(self):
                    self.ticks = 0

                def start(self):
                    threading.Thread(target=self._run, daemon=True).start()

                def _run(self):
                    self.ticks = self.ticks + 1
        """))
        assert findings == []

    def test_transitive_thread_reachability_fires(self):
        # _run calls _step; _step's write races with the main-group write
        findings = run_rule("lock-discipline", _src("""
            import threading

            class Worker:
                def __init__(self):
                    self.state = "new"

                def start(self):
                    threading.Thread(target=self._run).start()

                def _run(self):
                    self._step()

                def _step(self):
                    self.state = "running"

                def stop(self):
                    self.state = "stopped"
        """))
        assert len(findings) == 2

    def test_executor_submit_counts_as_thread_entry(self):
        findings = run_rule("lock-discipline", _src("""
            class Pool:
                def __init__(self, ex):
                    self._ex = ex
                    self.done = 0

                def kick(self):
                    self._ex.submit(self._work)

                def _work(self):
                    self.done = 1

                def reset(self):
                    self.done = 0
        """))
        assert len(findings) == 2

    def test_dict_store_is_exempt(self):
        # self.d[k] = v is a single GIL-atomic store; no read-modify-write
        findings = run_rule("lock-discipline", _src("""
            import threading

            class Cache:
                def __init__(self):
                    self.data = {}

                def start(self):
                    threading.Thread(target=self._fill).start()

                def _fill(self):
                    self.data["a"] = 1

                def put(self, k, v):
                    self.data[k] = v
        """))
        assert findings == []

    def test_subscript_augassign_fires(self):
        # self.d[k] += 1 IS a read-modify-write and needs the lock
        findings = run_rule("lock-discipline", _src("""
            import threading

            class Counter:
                def __init__(self):
                    self.counts = {}

                def start(self):
                    threading.Thread(target=self._drain).start()

                def _drain(self):
                    self.counts["n"] += 1

                def bump(self):
                    self.counts["n"] += 1
        """))
        assert len(findings) == 2
