"""Failure → relaunch → auto-resume, end to end (VERDICT r3 item 8).

The reference stopped at failure *detection* (node error → SystemExit on the
feed path, reference TFCluster.py:178-183) and told operators to resubmit.
Here :func:`TFCluster.run_with_recovery` closes the loop driver-side:
watchdog/launch-error detection → :meth:`TFCluster.abort` (executor-side
abort watchers kill surviving jax children, freeing the pinned executor
slots) → relaunch → ``map_fun`` resumes from its latest checkpoint (the
``tests/test_resume.py`` contract)."""

import json
import os

import pytest

from tensorflowonspark_tpu import TFCluster
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


def fn_train_resume_or_die(args, ctx):
    """Trains to ``target_steps`` total, checkpointing every
    ``checkpoint_steps``; the victim executor SIGKILLs itself at
    ``kill_at`` — once (a marker file makes the second life survive)."""
    import signal

    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint

    model_dir = os.path.join(args["model_dir"], "worker_{}".format(ctx.executor_id))
    os.makedirs(model_dir, exist_ok=True)
    strategy = SyncDataParallel(parallel.local_mesh({"dp": -1}))
    model = mnist.create_model("mlp", hidden=16)
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(
        mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0)
    )
    latest = checkpoint.latest_checkpoint(model_dir)
    if latest:
        state = checkpoint.restore_checkpoint(latest, target=jax.device_get(state))
    global_step = int(jax.device_get(state.step))

    step = strategy.compile_train_step(
        mnist.make_loss_fn(model), optimizer, has_aux=True, donate=False
    )
    rng = np.random.default_rng(7)
    batch = strategy.shard_batch(
        {
            "image": rng.standard_normal((32, 28, 28)).astype(np.float32),
            "label": rng.integers(0, 10, 32),
        }
    )
    marker = os.path.join(args["model_dir"], "killed.marker")
    while global_step < args["target_steps"]:
        state, metrics = step(state, batch)
        jax.block_until_ready(metrics["loss"])
        global_step += 1
        if global_step % args["checkpoint_steps"] == 0:
            checkpoint.save_checkpoint(
                os.path.join(model_dir, "ckpt_{}".format(global_step)),
                jax.device_get(state),
            )
        if (
            ctx.executor_id == args["victim"]
            and global_step == args["kill_at"]
            and not os.path.exists(marker)
        ):
            with open(marker, "w") as f:
                f.write("first life died here")
            os.kill(os.getpid(), signal.SIGKILL)  # no traceback, no cleanup
    with open(os.path.join(model_dir, "done.json"), "w") as f:
        json.dump({"final_step": global_step}, f)


@pytest.mark.slow
def test_sigkilled_child_training_finishes_anyway(tmp_path, monkeypatch):
    monkeypatch.setenv("TOS_MONITOR_INTERVAL", "1")
    model_dir = str(tmp_path)
    args = {
        "model_dir": model_dir,
        "target_steps": 8,
        "checkpoint_steps": 2,
        "kill_at": 5,  # after the step-4 checkpoint, before step-6
        "victim": 1,
    }
    sc = LocalSparkContext(num_executors=2, task_timeout=600)
    try:
        relaunches = TFCluster.run_with_recovery(
            sc, fn_train_resume_or_die, args, num_executors=2,
            input_mode=InputMode.TENSORFLOW, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
            max_relaunches=2, shutdown_timeout=240,
        )
    finally:
        sc.stop()
    assert relaunches == 1, "exactly one relaunch should recover this run"
    # the victim really died mid-train ...
    assert os.path.exists(os.path.join(model_dir, "killed.marker"))
    # ... yet BOTH workers finished the full training
    for eid in (0, 1):
        with open(os.path.join(model_dir, "worker_{}".format(eid), "done.json")) as f:
            assert json.load(f)["final_step"] == args["target_steps"]
    # the victim resumed from its step-4 checkpoint (not from scratch): its
    # second life added the 6 and 8 checkpoints on top of 2 and 4
    victim_ckpts = sorted(
        d for d in os.listdir(os.path.join(model_dir, "worker_1")) if d.startswith("ckpt_")
    )
    assert victim_ckpts == ["ckpt_2", "ckpt_4", "ckpt_6", "ckpt_8"]


def fn_touch_and_exit(args, ctx):
    with open(os.path.join(args["dir"], "ran_{}".format(ctx.executor_id)), "w") as f:
        f.write(ctx.job_name)


def test_run_with_recovery_completes_with_parked_ps_role(tmp_path):
    """A ps task parks on its control queue until shutdown, so the launch job
    outlives training by design — completion must key off worker channel
    state, not launch-thread death (this hung before wait_for_completion)."""
    d = str(tmp_path)
    sc = LocalSparkContext(num_executors=2, task_timeout=300)
    try:
        relaunches = TFCluster.run_with_recovery(
            sc, fn_touch_and_exit, {"dir": d}, num_executors=2, num_ps=1,
            input_mode=InputMode.TENSORFLOW, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
            max_relaunches=0, shutdown_timeout=120,
        )
    finally:
        sc.stop()
    assert relaunches == 0
    # the WORKER ran to completion. (No assertion on the ps node's file: ps
    # is a service role — shutdown terminates its child the moment the
    # workers finish, which can be before a slow-booting ps child even
    # reaches user code; the reference's ps sat in server.join() and was
    # killed the same way, TFSparkNode.py:373-390.)
    assert "ran_1" in os.listdir(d)


def test_run_with_recovery_rejects_spark_mode():
    with pytest.raises(ValueError, match="InputMode.TENSORFLOW"):
        TFCluster.run_with_recovery(
            None, lambda a, c: None, {}, num_executors=1,
            input_mode=InputMode.SPARK,
        )
