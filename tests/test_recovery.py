"""Failure → relaunch → auto-resume, end to end (VERDICT r3 item 8).

The reference stopped at failure *detection* (node error → SystemExit on the
feed path, reference TFCluster.py:178-183) and told operators to resubmit.
Here :func:`TFCluster.run_with_recovery` closes the loop driver-side:
watchdog/launch-error detection → :meth:`TFCluster.abort` (executor-side
abort watchers kill surviving jax children, freeing the pinned executor
slots) → relaunch → ``map_fun`` resumes from its latest checkpoint (the
``tests/test_resume.py`` contract)."""

import json
import os

import pytest

from tensorflowonspark_tpu import TFCluster
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


def fn_train_resume_or_die(args, ctx):
    """Trains to ``target_steps`` total, checkpointing every
    ``checkpoint_steps``; the victim executor SIGKILLs itself at
    ``kill_at`` — once (a marker file makes the second life survive)."""
    import signal

    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint

    model_dir = os.path.join(args["model_dir"], "worker_{}".format(ctx.executor_id))
    os.makedirs(model_dir, exist_ok=True)
    strategy = SyncDataParallel(parallel.local_mesh({"dp": -1}))
    model = mnist.create_model("mlp", hidden=16)
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(
        mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0)
    )
    latest = checkpoint.latest_checkpoint(model_dir)
    if latest:
        state = checkpoint.restore_checkpoint(latest, target=jax.device_get(state))
    global_step = int(jax.device_get(state.step))

    step = strategy.compile_train_step(
        mnist.make_loss_fn(model), optimizer, has_aux=True, donate=False
    )
    rng = np.random.default_rng(7)
    batch = strategy.shard_batch(
        {
            "image": rng.standard_normal((32, 28, 28)).astype(np.float32),
            "label": rng.integers(0, 10, 32),
        }
    )
    marker = os.path.join(args["model_dir"], "killed.marker")
    while global_step < args["target_steps"]:
        state, metrics = step(state, batch)
        jax.block_until_ready(metrics["loss"])
        global_step += 1
        if global_step % args["checkpoint_steps"] == 0:
            checkpoint.save_checkpoint(
                os.path.join(model_dir, "ckpt_{}".format(global_step)),
                jax.device_get(state),
            )
        if (
            ctx.executor_id == args["victim"]
            and global_step == args["kill_at"]
            and not os.path.exists(marker)
        ):
            with open(marker, "w") as f:
                f.write("first life died here")
            os.kill(os.getpid(), signal.SIGKILL)  # no traceback, no cleanup
    with open(os.path.join(model_dir, "done.json"), "w") as f:
        json.dump({"final_step": global_step}, f)


@pytest.mark.slow
def test_sigkilled_child_training_finishes_anyway(tmp_path, monkeypatch):
    monkeypatch.setenv("TOS_MONITOR_INTERVAL", "1")
    model_dir = str(tmp_path)
    args = {
        "model_dir": model_dir,
        "target_steps": 8,
        "checkpoint_steps": 2,
        "kill_at": 5,  # after the step-4 checkpoint, before step-6
        "victim": 1,
    }
    sc = LocalSparkContext(num_executors=2, task_timeout=600)
    try:
        relaunches = TFCluster.run_with_recovery(
            sc, fn_train_resume_or_die, args, num_executors=2,
            input_mode=InputMode.TENSORFLOW, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
            max_relaunches=2, shutdown_timeout=240,
        )
    finally:
        sc.stop()
    assert relaunches == 1, "exactly one relaunch should recover this run"
    # the victim really died mid-train ...
    assert os.path.exists(os.path.join(model_dir, "killed.marker"))
    # ... yet BOTH workers finished the full training
    for eid in (0, 1):
        with open(os.path.join(model_dir, "worker_{}".format(eid), "done.json")) as f:
            assert json.load(f)["final_step"] == args["target_steps"]
    # the victim resumed from its step-4 checkpoint (not from scratch): its
    # second life added the 6 and 8 checkpoints on top of 2 and 4
    victim_ckpts = sorted(
        d for d in os.listdir(os.path.join(model_dir, "worker_1")) if d.startswith("ckpt_")
    )
    assert victim_ckpts == ["ckpt_2", "ckpt_4", "ckpt_6", "ckpt_8"]


def fn_touch_and_exit(args, ctx):
    with open(os.path.join(args["dir"], "ran_{}".format(ctx.executor_id)), "w") as f:
        f.write(ctx.job_name)


def test_run_with_recovery_completes_with_parked_ps_role(tmp_path):
    """A ps task parks on its control queue until shutdown, so the launch job
    outlives training by design — completion must key off worker channel
    state, not launch-thread death (this hung before wait_for_completion)."""
    d = str(tmp_path)
    sc = LocalSparkContext(num_executors=2, task_timeout=300)
    try:
        relaunches = TFCluster.run_with_recovery(
            sc, fn_touch_and_exit, {"dir": d}, num_executors=2, num_ps=1,
            input_mode=InputMode.TENSORFLOW, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
            max_relaunches=0, shutdown_timeout=120,
        )
    finally:
        sc.stop()
    assert relaunches == 0
    # the WORKER ran to completion. (No assertion on the ps node's file: ps
    # is a service role — shutdown terminates its child the moment the
    # workers finish, which can be before a slow-booting ps child even
    # reaches user code; the reference's ps sat in server.join() and was
    # killed the same way, TFSparkNode.py:373-390.)
    assert "ran_1" in os.listdir(d)


def test_run_with_recovery_rejects_spark_mode_without_feed_fn():
    with pytest.raises(ValueError, match="feed_fn"):
        TFCluster.run_with_recovery(
            None, lambda a, c: None, {}, num_executors=1,
            input_mode=InputMode.SPARK,
        )


def test_run_with_recovery_rejects_feed_fn_in_tensorflow_mode():
    with pytest.raises(ValueError, match="InputMode.SPARK"):
        TFCluster.run_with_recovery(
            None, lambda a, c: None, {}, num_executors=1,
            input_mode=InputMode.TENSORFLOW, feed_fn=lambda cluster: None,
        )


def fn_spark_feed_resume_or_die(args, ctx):
    """SPARK-mode twin of :func:`fn_train_resume_or_die`: trains one step per
    fed batch to ``target_steps`` total across lives, checkpointing every
    ``checkpoint_steps``; the victim SIGKILLs itself at ``kill_at`` — once."""
    import signal

    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint

    model_dir = os.path.join(args["model_dir"], "worker_{}".format(ctx.executor_id))
    os.makedirs(model_dir, exist_ok=True)
    strategy = SyncDataParallel(parallel.local_mesh({"dp": -1}))
    model = mnist.create_model("mlp", hidden=16)
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(
        mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0)
    )
    latest = checkpoint.latest_checkpoint(model_dir)
    if latest:
        state = checkpoint.restore_checkpoint(latest, target=jax.device_get(state))
    global_step = int(jax.device_get(state.step))

    step = strategy.compile_train_step(
        mnist.make_loss_fn(model), optimizer, has_aux=True, donate=False
    )
    marker = os.path.join(args["model_dir"], "killed.marker")
    feed = ctx.get_data_feed(train_mode=True)
    while not feed.should_stop() and global_step < args["target_steps"]:
        rows = feed.next_batch(16)
        if not rows:
            continue
        images = np.asarray([r[0] for r in rows], np.float32).reshape(-1, 28, 28)
        labels = np.asarray([r[1] for r in rows])
        state, metrics = step(
            state, strategy.shard_batch({"image": images, "label": labels})
        )
        jax.block_until_ready(metrics["loss"])
        global_step += 1
        if global_step % args["checkpoint_steps"] == 0:
            checkpoint.save_checkpoint(
                os.path.join(model_dir, "ckpt_{}".format(global_step)),
                jax.device_get(state),
            )
        if (
            ctx.executor_id == args["victim"]
            and global_step == args["kill_at"]
            and not os.path.exists(marker)
        ):
            with open(marker, "w") as f:
                f.write("first life died here")
            os.kill(os.getpid(), signal.SIGKILL)  # no traceback, no cleanup
    feed.terminate()  # drain the rest of the feed so feeders can finish
    with open(os.path.join(model_dir, "done.json"), "w") as f:
        json.dump({"final_step": global_step}, f)


@pytest.mark.slow
def test_spark_feed_killed_node_training_finishes_anyway(tmp_path, monkeypatch):
    """VERDICT r4 item 7: kill a node mid-SPARK-feed; run_with_recovery
    re-invokes the caller's feed_fn against the relaunched cluster and both
    workers finish training, the victim resuming from its checkpoint."""
    import numpy as np

    monkeypatch.setenv("TOS_MONITOR_INTERVAL", "1")
    model_dir = str(tmp_path)
    args = {
        "model_dir": model_dir,
        "target_steps": 8,
        "checkpoint_steps": 2,
        "kill_at": 5,  # after the step-4 checkpoint, before step-6
        "victim": 1,
    }
    rng = np.random.default_rng(3)
    rows = [
        (rng.standard_normal(784).astype(np.float32).tolist(), int(i % 10))
        for i in range(128)
    ]
    feeds = []

    sc = LocalSparkContext(num_executors=2, task_timeout=900)

    def all_done():
        return all(
            os.path.exists(os.path.join(model_dir, "worker_{}".format(e), "done.json"))
            for e in (0, 1)
        )

    def feed_fn(cluster):
        """The caller's feed loop: waves until every worker reports done.
        A single big feed would under-serve the victim's second life — a
        worker that reaches its target terminates its node, and later feed
        tasks landing on that executor discard their partitions by design
        ('training said enough'), so the data a straggler still needs must
        keep coming from the CALLER. This re-feed-until-done shape is
        exactly why SPARK-mode recovery needs feed_fn (the RDD lineage and
        the stop condition both belong to the caller)."""
        feeds.append(1)  # prove the helper re-invoked the caller's loop
        while not all_done():
            cluster.check_errors()
            cluster.train(sc.parallelize(rows, 4), num_epochs=1, feed_timeout=120)

    try:
        relaunches = TFCluster.run_with_recovery(
            sc, fn_spark_feed_resume_or_die, args, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
            max_relaunches=2, shutdown_timeout=240, feed_fn=feed_fn,
        )
    finally:
        sc.stop()
    assert relaunches == 1, "exactly one relaunch should recover this run"
    assert len(feeds) == 2  # the feed loop ran once per attempt
    assert os.path.exists(os.path.join(model_dir, "killed.marker"))
    for eid in (0, 1):
        with open(os.path.join(model_dir, "worker_{}".format(eid), "done.json")) as f:
            assert json.load(f)["final_step"] == args["target_steps"]
    # the victim resumed from its step-4 checkpoint, not from scratch
    victim_ckpts = sorted(
        d for d in os.listdir(os.path.join(model_dir, "worker_1")) if d.startswith("ckpt_")
    )
    assert victim_ckpts == ["ckpt_2", "ckpt_4", "ckpt_6", "ckpt_8"]
