"""Parallel phase-1 tests: ``--jobs`` output parity with the serial
path, cache interaction (cold parallel run populates it, warm run spawns
no workers), and the pre-commit wrapper's ``--jobs`` forwarding."""

import json
import os
import subprocess
import sys
import textwrap
import time

from tosa_testutil import REPO_ROOT
from tosa import core, make_checkers


def _src(s):
    return textwrap.dedent(s).lstrip()


def _library_paths():
    lib = os.path.join(REPO_ROOT, "tensorflowonspark_tpu")
    return sorted(
        os.path.join(dirpath, name)
        for dirpath, _, names in os.walk(lib)
        for name in names
        if name.endswith(".py")
    )


def _dicts(findings):
    return [f.to_dict() for f in findings]


class TestJobsParity:
    def test_parallel_output_matches_serial_on_the_library(self):
        paths = _library_paths()
        assert len(paths) > 10
        serial = core.analyze_project(paths, make_checkers(), root=REPO_ROOT, jobs=1)
        parallel = core.analyze_project(paths, make_checkers(), root=REPO_ROOT, jobs=4)
        # byte-identical merge: same findings in the same order
        assert _dicts(parallel) == _dicts(serial)

    def test_cold_parallel_run_populates_cache_warm_spawns_no_workers(
        self, tmp_path, monkeypatch
    ):
        paths = _library_paths()
        cache_path = str(tmp_path / "cache.json")
        t0 = time.monotonic()
        cold = core.analyze_project(
            paths, make_checkers(), root=REPO_ROOT, cache_path=cache_path, jobs=4
        )
        cold_s = time.monotonic() - t0
        assert os.path.exists(cache_path)

        # a warm run must not touch the pool at all: every file is a cache
        # hit, so a booby-trapped executor proves no workers are spawned
        import concurrent.futures

        def _boom(*a, **kw):
            raise AssertionError("warm run spawned a process pool")

        monkeypatch.setattr(concurrent.futures, "ProcessPoolExecutor", _boom)
        t0 = time.monotonic()
        warm = core.analyze_project(
            paths, make_checkers(), root=REPO_ROOT, cache_path=cache_path, jobs=4
        )
        warm_s = time.monotonic() - t0
        assert _dicts(warm) == _dicts(cold)
        # warm replays cached summaries: no parse, no fork; generous
        # margin so CI jitter doesn't flake the assertion
        assert warm_s < max(cold_s * 0.6, 0.25), (cold_s, warm_s)

    def test_cache_written_by_parallel_run_serves_a_serial_run(self, tmp_path):
        paths = _library_paths()
        cache_path = str(tmp_path / "cache.json")
        cold = core.analyze_project(
            paths, make_checkers(), root=REPO_ROOT, cache_path=cache_path, jobs=4
        )
        warm = core.analyze_project(
            paths, make_checkers(), root=REPO_ROOT, cache_path=cache_path, jobs=1
        )
        assert _dicts(warm) == _dicts(cold)


BAD_SLEEP = _src("""
    import time

    def wait(q):
        while q.empty():
            time.sleep(0.1)
""")


class TestJobsCLI:
    def _corpus(self, tmp_path):
        (tmp_path / "bad.py").write_text(BAD_SLEEP)
        for i in range(6):
            (tmp_path / "mod{}.py".format(i)).write_text(
                "def f{}():\n    return {}\n".format(i, i)
            )
        return tmp_path

    def _run(self, tmp_path, extra):
        return subprocess.run(
            [sys.executable, "-m", "tosa", "--json", "--root", str(tmp_path),
             "--baseline", str(tmp_path / "bl.json"), str(tmp_path)] + extra,
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=120,
        )

    def test_jobs_flag_is_output_invariant(self, tmp_path):
        self._corpus(tmp_path)
        serial = self._run(tmp_path, ["--jobs", "1"])
        parallel = self._run(tmp_path, ["--jobs", "3"])
        assert serial.returncode == 1, serial.stderr
        assert parallel.returncode == 1, parallel.stderr
        assert json.loads(parallel.stdout) == json.loads(serial.stdout)

    def test_precommit_forwards_jobs(self, tmp_path):
        # the wrapper strips `--jobs N` from its own argv and re-emits it
        # on the `python -m tosa --changed` command line
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SLEEP)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "scripts", "tosa_precommit.py"),
             "--jobs", "2", str(bad)],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=180,
        )
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "retry-discipline" in proc.stdout

    def test_precommit_rejects_malformed_jobs(self, tmp_path):
        bad = tmp_path / "bad.py"
        bad.write_text(BAD_SLEEP)
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "scripts", "tosa_precommit.py"),
             "--jobs", "lots", str(bad)],
            cwd=REPO_ROOT,
            capture_output=True,
            text=True,
            timeout=60,
        )
        assert proc.returncode == 2
        assert "--jobs needs an integer" in proc.stderr
