"""Integration against REAL pyspark (VERDICT r3 items 1 + 7).

Active only when pyspark is importable AND ``TOS_TEST_PYSPARK=1`` (the
CI pyspark job; ``run_tests.sh`` sets it when pyspark is present —
reference test/run_tests.sh:16-19 booted the same local-cluster shape).
Everything here runs on a real ``local-cluster[2,1,1024]``: separate
executor JVMs with separate python workers, real task scheduling/pickling,
real ``_jsc`` Hadoop conf, real barrier RDDs, real DStreams.
"""

import json
import os
import sys
import time

import pytest

pyspark = pytest.importorskip("pyspark")
pytestmark = pytest.mark.skipif(
    os.environ.get("TOS_TEST_PYSPARK") != "1",
    reason="TOS_TEST_PYSPARK=1 not set (real-Spark leg runs in CI)",
)

# this module is not importable on executors (tests/ is not a package);
# both pyspark's vendored cloudpickle (task closures) and the standalone
# cloudpickle (the framework's jax-child spawn) must ship its functions
# by value
import cloudpickle

cloudpickle.register_pickle_by_value(sys.modules[__name__])
try:
    from pyspark import cloudpickle as _pyspark_cloudpickle

    _pyspark_cloudpickle.register_pickle_by_value(sys.modules[__name__])
except Exception:
    pass

from tensorflowonspark_tpu import TFCluster, TFParallel
from tensorflowonspark_tpu.TFCluster import InputMode

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


@pytest.fixture(scope="module")
def sc():
    os.environ.setdefault("PYSPARK_PYTHON", sys.executable)
    os.environ.setdefault("PYSPARK_DRIVER_PYTHON", sys.executable)
    conf = (
        pyspark.SparkConf()
        .setMaster(os.environ.get("MASTER", "local-cluster[2,1,1024]"))
        .setAppName("tos-tpu-real-spark")
        .set("spark.task.maxFailures", "1")
        .set("spark.executorEnv.JAX_PLATFORMS", "cpu")
        .set("spark.python.worker.reuse", "true")
    )
    context = pyspark.SparkContext(conf=conf)
    context.setLogLevel("WARN")
    yield context
    context.stop()


def test_default_fs_through_real_jvm_hadoop_conf(sc):
    fs = TFCluster.resolve_default_fs(sc)
    assert fs is not None and fs.startswith("file:"), fs


def fn_write_marker(args, ctx):
    with open(os.path.join(args["out_dir"], "node{}.json".format(ctx.executor_id)), "w") as f:
        json.dump({"job": ctx.job_name, "index": ctx.task_index,
                   "workers": ctx.num_workers}, f)


def test_cluster_lifecycle_tensorflow_mode(sc, tmp_path):
    """run → assemble over real executors → map_fun in jax children →
    shutdown; the full reference launch path (TFSparkNode.py:240-333) on
    actual Spark task scheduling and pickling."""
    cluster = TFCluster.run(
        sc, fn_write_marker, {"out_dir": str(tmp_path)}, num_executors=2,
        input_mode=InputMode.TENSORFLOW, master_node=None,
        env=CPU_ENV, jax_distributed=False, reservation_timeout=300,
    )
    cluster.shutdown(timeout=300)
    nodes = sorted(os.listdir(str(tmp_path)))
    assert nodes == ["node0.json", "node1.json"], nodes
    with open(tmp_path / "node0.json") as f:
        assert json.load(f)["workers"] == 2


def fn_count_feed(args, ctx):
    out = os.path.join(args["out_dir"], "sum{}.txt".format(ctx.executor_id))
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    while not feed.should_stop():
        rows = feed.next_batch(16)
        total += sum(int(r[1]) for r in rows if r is not None)
        with open(out, "w") as f:  # running total: the driver polls this
            f.write(str(total))


def test_cluster_spark_mode_feed(sc, tmp_path):
    """InputMode.SPARK on real Spark: foreachPartition feed tasks land on
    real executors and reach the executor-local channel of whichever node
    lives there."""
    cluster = TFCluster.run(
        sc, fn_count_feed, {"out_dir": str(tmp_path)}, num_executors=2,
        input_mode=InputMode.SPARK, master_node=None,
        env=CPU_ENV, jax_distributed=False, reservation_timeout=300,
    )
    rows = [("r{}".format(i), 1) for i in range(64)]
    cluster.train(sc.parallelize(rows, 4), num_epochs=1, feed_timeout=300)
    cluster.shutdown(grace_secs=2, timeout=300)
    sums = []
    for name in sorted(os.listdir(str(tmp_path))):
        with open(tmp_path / name) as f:
            sums.append(int(f.read()))
    assert sum(sums) == 64, sums  # every row consumed exactly once


def test_streaming_foreachrdd_single_arg(sc, tmp_path):
    """Micro-batch feeding through a REAL DStream (VERDICT r3 item 7): pins
    the foreachRDD arity subtlety — pyspark inspects co_argcount and passes
    (batch_time, rdd) to 2-arg functions, so TFCluster.train's callback must
    take exactly one positional arg (TFCluster.py train(); reference
    mnist_spark_streaming.py:84-144)."""
    streaming = pytest.importorskip(
        "pyspark.streaming", reason="DStreams removed in Spark 4; CI pins pyspark<4"
    )
    cluster = TFCluster.run(
        sc, fn_count_feed, {"out_dir": str(tmp_path)}, num_executors=2,
        input_mode=InputMode.SPARK, master_node=None,
        env=CPU_ENV, jax_distributed=False, reservation_timeout=300,
    )
    ssc = streaming.StreamingContext(sc, 1)
    waves = [sc.parallelize([("w{}".format(w), 1) for _ in range(8)], 2) for w in range(3)]
    cluster.train(ssc.queueStream(waves), feed_timeout=300)
    ssc.start()
    deadline = time.time() + 120
    while time.time() < deadline:
        done = [f for f in os.listdir(str(tmp_path)) if f.startswith("sum")]
        if len(done) == 2 and _sum_files(tmp_path) >= 24:
            break
        time.sleep(1)
    cluster.shutdown(ssc=ssc, grace_secs=2, timeout=300)
    assert _sum_files(tmp_path) == 24  # 3 waves x 8 rows, each consumed once


def _sum_files(tmp_path):
    total = 0
    for name in os.listdir(str(tmp_path)):
        if name.startswith("sum"):
            with open(os.path.join(str(tmp_path), name)) as f:
                text = f.read().strip()
                total += int(text) if text else 0
    return total


def fn_instance(args, ctx):
    with open(os.path.join(args["out_dir"], "inst{}.txt".format(ctx.executor_id)), "w") as f:
        f.write("{}/{}".format(ctx.executor_id, ctx.num_workers))


def test_tfparallel_barrier_on_real_spark(sc, tmp_path):
    """TFParallel.run on real pyspark uses barrier-mode scheduling
    (reference TFParallel.py:63-64); local-cluster has exactly the 2 slots
    the 2 barrier tasks need."""
    done = TFParallel.run(sc, fn_instance, {"out_dir": str(tmp_path)}, 2, env=CPU_ENV)
    assert sorted(done) == [0, 1]
    assert sorted(os.listdir(str(tmp_path))) == ["inst0.txt", "inst1.txt"]
