"""Integration against REAL pyspark (VERDICT r3 items 1 + 7).

Active only when pyspark is importable AND ``TOS_TEST_PYSPARK=1`` (the
CI pyspark job; ``run_tests.sh`` sets it when pyspark is present —
reference test/run_tests.sh:16-19 booted the same local-cluster shape).
Everything here runs on a real ``local-cluster[2,1,1024]``: separate
executor JVMs with separate python workers, real task scheduling/pickling,
real ``_jsc`` Hadoop conf, real barrier RDDs, real DStreams.
"""

import json
import os
import sys
import time

import pytest

pyspark = pytest.importorskip("pyspark")
pytestmark = pytest.mark.skipif(
    os.environ.get("TOS_TEST_PYSPARK") != "1",
    reason="TOS_TEST_PYSPARK=1 not set (real-Spark leg runs in CI)",
)

# this module is not importable on executors (tests/ is not a package);
# both pyspark's vendored cloudpickle (task closures) and the standalone
# cloudpickle (the framework's jax-child spawn) must ship its functions
# by value
import cloudpickle

cloudpickle.register_pickle_by_value(sys.modules[__name__])
try:
    from pyspark import cloudpickle as _pyspark_cloudpickle

    _pyspark_cloudpickle.register_pickle_by_value(sys.modules[__name__])
except Exception:
    pass

from tensorflowonspark_tpu import TFCluster, TFParallel
from tensorflowonspark_tpu.TFCluster import InputMode

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


@pytest.fixture(scope="module")
def sc():
    os.environ.setdefault("PYSPARK_PYTHON", sys.executable)
    os.environ.setdefault("PYSPARK_DRIVER_PYTHON", sys.executable)
    conf = (
        pyspark.SparkConf()
        .setMaster(os.environ.get("MASTER", "local-cluster[2,1,1024]"))
        .setAppName("tos-tpu-real-spark")
        .set("spark.task.maxFailures", "1")
        .set("spark.executorEnv.JAX_PLATFORMS", "cpu")
        .set("spark.python.worker.reuse", "true")
    )
    context = pyspark.SparkContext(conf=conf)
    context.setLogLevel("WARN")
    yield context
    context.stop()


def test_default_fs_through_real_jvm_hadoop_conf(sc):
    fs = TFCluster.resolve_default_fs(sc)
    assert fs is not None and fs.startswith("file:"), fs


def fn_write_marker(args, ctx):
    with open(os.path.join(args["out_dir"], "node{}.json".format(ctx.executor_id)), "w") as f:
        json.dump({"job": ctx.job_name, "index": ctx.task_index,
                   "workers": ctx.num_workers}, f)


def test_cluster_lifecycle_tensorflow_mode(sc, tmp_path):
    """run → assemble over real executors → map_fun in jax children →
    shutdown; the full reference launch path (TFSparkNode.py:240-333) on
    actual Spark task scheduling and pickling."""
    cluster = TFCluster.run(
        sc, fn_write_marker, {"out_dir": str(tmp_path)}, num_executors=2,
        input_mode=InputMode.TENSORFLOW, master_node=None,
        env=CPU_ENV, jax_distributed=False, reservation_timeout=300,
    )
    cluster.shutdown(timeout=300)
    nodes = sorted(os.listdir(str(tmp_path)))
    assert nodes == ["node0.json", "node1.json"], nodes
    with open(tmp_path / "node0.json") as f:
        assert json.load(f)["workers"] == 2


def fn_count_feed(args, ctx):
    out = os.path.join(args["out_dir"], "sum{}.txt".format(ctx.executor_id))
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    while not feed.should_stop():
        rows = feed.next_batch(16)
        total += sum(int(r[1]) for r in rows if r is not None)
        with open(out, "w") as f:  # running total: the driver polls this
            f.write(str(total))


def test_cluster_spark_mode_feed(sc, tmp_path):
    """InputMode.SPARK on real Spark: foreachPartition feed tasks land on
    real executors and reach the executor-local channel of whichever node
    lives there."""
    cluster = TFCluster.run(
        sc, fn_count_feed, {"out_dir": str(tmp_path)}, num_executors=2,
        input_mode=InputMode.SPARK, master_node=None,
        env=CPU_ENV, jax_distributed=False, reservation_timeout=300,
    )
    rows = [("r{}".format(i), 1) for i in range(64)]
    cluster.train(sc.parallelize(rows, 4), num_epochs=1, feed_timeout=300)
    cluster.shutdown(grace_secs=2, timeout=300)
    sums = []
    for name in sorted(os.listdir(str(tmp_path))):
        with open(tmp_path / name) as f:
            sums.append(int(f.read()))
    assert sum(sums) == 64, sums  # every row consumed exactly once


def test_streaming_foreachrdd_single_arg(sc, tmp_path):
    """Micro-batch feeding through a REAL DStream (VERDICT r3 item 7): pins
    the foreachRDD arity subtlety — pyspark inspects co_argcount and passes
    (batch_time, rdd) to 2-arg functions, so TFCluster.train's callback must
    take exactly one positional arg (TFCluster.py train(); reference
    mnist_spark_streaming.py:84-144)."""
    streaming = pytest.importorskip(
        "pyspark.streaming", reason="DStreams removed in Spark 4; CI pins pyspark<4"
    )
    cluster = TFCluster.run(
        sc, fn_count_feed, {"out_dir": str(tmp_path)}, num_executors=2,
        input_mode=InputMode.SPARK, master_node=None,
        env=CPU_ENV, jax_distributed=False, reservation_timeout=300,
    )
    ssc = streaming.StreamingContext(sc, 1)
    waves = [sc.parallelize([("w{}".format(w), 1) for _ in range(8)], 2) for w in range(3)]
    cluster.train(ssc.queueStream(waves), feed_timeout=300)
    ssc.start()
    deadline = time.time() + 120
    while time.time() < deadline:
        done = [f for f in os.listdir(str(tmp_path)) if f.startswith("sum")]
        if len(done) == 2 and _sum_files(tmp_path) >= 24:
            break
        time.sleep(1)
    cluster.shutdown(ssc=ssc, grace_secs=2, timeout=300)
    assert _sum_files(tmp_path) == 24  # 3 waves x 8 rows, each consumed once


def _sum_files(tmp_path):
    total = 0
    for name in os.listdir(str(tmp_path)):
        if name.startswith("sum"):
            with open(os.path.join(str(tmp_path), name)) as f:
                text = f.read().strip()
                total += int(text) if text else 0
    return total


def fn_square_batches(args, ctx):
    """Inference map_fun: square each single-element row and return results
    1:1 (the reference's flagship integration shape, test_TFCluster.py:29-48:
    its failure modes — result chunking, EndPartition alignment, the 1:1
    row:result contract — are all executor-side)."""
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(10)
        if not batch:
            break
        feed.batch_results([float(row[0]) ** 2 for row in batch])


def test_cluster_inference_square_sum(sc):
    """cluster.inference() on real executors: feed 1000 ints through the
    cluster, square in the jax children, collect results back through Spark
    and sum (reference test_TFCluster.py:29-48)."""
    cluster = TFCluster.run(
        sc, fn_square_batches, {}, num_executors=2,
        input_mode=InputMode.SPARK, master_node=None,
        env=CPU_ENV, jax_distributed=False, reservation_timeout=300,
    )
    rdd = sc.parallelize([[x] for x in range(1000)], 10)
    rdd_out = cluster.inference(rdd, feed_timeout=300)
    total = rdd_out.sum()
    cluster.shutdown(grace_secs=2, timeout=300)
    assert total == sum(x * x for x in range(1000))


def test_dfutil_roundtrip_real_dataframe(sc, tmp_path):
    """6-type DataFrame → saveAsTFRecords → loadTFRecords on real pyspark
    Rows/DataFrames, plus the loaded-DF provenance registry
    (reference test_dfutil.py:30-73)."""
    from pyspark.sql import SparkSession

    from tensorflowonspark_tpu import dfutil

    spark = SparkSession(sc)
    tfr_dir = str(tmp_path / "tfr")
    row1 = ("text string", 1, [2, 3, 4, 5], -1.1, [-2.2, -3.3, -4.4, -5.5],
            bytearray(b"\xff\xfe\xfd\xfc"))
    df1 = spark.createDataFrame(sc.parallelize([row1]), ["a", "b", "c", "d", "e", "f"])
    dfutil.saveAsTFRecords(df1, tfr_dir)
    assert os.path.isdir(tfr_dir)

    df2 = dfutil.loadTFRecords(sc, tfr_dir, binary_features=["f"])
    row2 = df2.take(1)[0]
    assert row2["a"] == row1[0]
    assert row2["b"] == row1[1]
    assert list(row2["c"]) == row1[2]
    assert abs(row2["d"] - row1[3]) < 1e-6
    assert all(abs(x - y) < 1e-6 for x, y in zip(row2["e"], row1[4]))
    assert bytes(row2["f"]) == bytes(row1[5])

    assert not dfutil.isLoadedDF(df1)
    assert dfutil.isLoadedDF(df2)
    assert not dfutil.isLoadedDF(df2.filter(df2.a == "x"))  # mutated DF


def fn_train_linear(args, ctx):
    """Linear regressor on the SPARK feed; chief exports a model bundle
    (the reference proof's train_fn shape, test_pipeline.py:89-131)."""
    import os as _os

    _os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as _np
    import jax.numpy as jnp
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.train import SyncDataParallel, export

    strategy = SyncDataParallel(parallel.local_mesh({"dp": -1}))

    def init(rng):
        return {"w": jnp.zeros((2, 1)), "b": jnp.zeros((1,))}

    def loss_fn(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        return jnp.mean((pred - batch["y"]) ** 2)

    opt = optax.adam(0.3)
    state = strategy.create_state(init, opt, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(loss_fn, opt)
    feed = ctx.get_data_feed(train_mode=True)
    while not feed.should_stop():
        batch = feed.next_batch(args.batch_size)
        if not batch:
            break
        x = _np.asarray([row[0] for row in batch], _np.float32)
        y = _np.asarray([row[1] for row in batch], _np.float32).reshape(-1, 1)
        state, metrics = step(state, strategy.shard_batch({"x": x, "y": y}))
        jax.block_until_ready(metrics["loss"])

    if ctx.job_name in ("chief", "master"):
        params = jax.device_get(state.params)

        def predict_builder():
            def predict(params, model_state, arrays):
                return {"y_": arrays["x"] @ params["w"] + params["b"]}

            return predict

        export.export_model(args.export_dir, predict_builder, params)


def test_ml_pipeline_fit_transform(sc, tmp_path):
    """TFEstimator/TFModel as REAL pyspark.ml citizens (VERDICT r4 item 1):
    the classes subclass Estimator/Model, pass pyspark.ml.Pipeline's
    isinstance checks, fit a known-weights linear model on the real
    local-cluster, and the PipelineModel's transform predicts it back
    (reference pipeline.py:349,433; proof shape test_pipeline.py:89-172)."""
    import numpy as np
    from pyspark.ml import Estimator, Model, Pipeline
    from pyspark.sql import SparkSession

    from tensorflowonspark_tpu import pipeline as tos_pipeline

    spark = SparkSession(sc)
    export_dir = str(tmp_path / "bundle")
    rng = np.random.default_rng(0)
    w_true = np.array([[3.14], [1.618]], np.float32)
    x = rng.standard_normal((256, 2)).astype(np.float32)
    y = (x @ w_true).ravel() + 0.5
    train_df = spark.createDataFrame(
        [(x[i].tolist(), float(y[i])) for i in range(len(x))], ["features", "label"]
    )

    est = (
        tos_pipeline.TFEstimator(
            fn_train_linear, {"export_dir": export_dir}, env=CPU_ENV,
            jax_distributed=False,
        )
        .setInputMapping({"features": "x", "label": "y"})
        .setBatchSize(32)
        .setEpochs(25)
        .setClusterSize(2)
        .setMasterNode("chief")
        .setGraceSecs(5)
    )
    assert isinstance(est, Estimator)  # the real pyspark.ml base

    pipeline_model = Pipeline(stages=[est]).fit(train_df)
    tf_model = pipeline_model.stages[0]
    assert isinstance(tf_model, Model)
    assert os.path.isdir(export_dir)

    tf_model.setInputMapping({"features": "x"}).setExportDir(export_dir)
    tf_model.setOutputMapping({"y_": "prediction"})
    test_df = spark.createDataFrame([(r.tolist(),) for r in x[:10]], ["features"])
    preds_df = pipeline_model.transform(test_df)
    preds = [row[0] for row in preds_df.collect()]
    expected = (x[:10] @ w_true).ravel() + 0.5
    # executors train independent replicas here (no cross-process grad sync
    # on the CPU local-cluster); the check is that the exported bundle
    # predicts the learned linear function through the real ML Pipeline
    np.testing.assert_allclose(np.asarray(preds).ravel(), expected, atol=0.5)


def test_get_spark_context_reuses_active_context(sc):
    """Under spark-submit (an active SparkContext exists) the examples'
    context factory must REUSE it, never construct a second one, and must
    follow the documented executor-count resolution: an explicit request
    always wins (warned when the conf disagrees), else submitted
    spark.executor.instances, else defaultParallelism."""
    from tensorflowonspark_tpu.backends import create_dataframe, get_spark_context

    instances = sc.getConf().get("spark.executor.instances")
    got, n, owned = get_spark_context("reuse-test", 7)
    assert got is sc
    assert not owned  # caller must not stop a context it did not create
    assert n == 7  # explicit request is never silently overridden

    got2, n2, owned2 = get_spark_context("reuse-test", None)
    assert got2 is sc and not owned2
    assert n2 == (int(instances) if instances else (sc.defaultParallelism or 1))

    injected, n3, owned3 = get_spark_context("reuse-test", 3, sc=sc)
    assert injected is sc and n3 == 3 and not owned3
    # injected real context without an explicit size: same conf/parallelism
    # resolution as the active-context path, never a local default
    _, n4, _ = get_spark_context("reuse-test", None, sc=sc, local_default=99)
    assert n4 == (int(instances) if instances else (sc.defaultParallelism or 99))

    df = create_dataframe(sc, [(1, "a"), (2, "b")], ["x", "y"], 2)
    assert sorted(r["x"] for r in df.collect()) == [1, 2]


def test_example_mnist_spark_under_real_spark(sc, tmp_path):
    """The mnist_spark example end-to-end on the REAL local-cluster: the
    north-star deployment shape is 'launched purely via spark-submit', so
    the example itself (not just the framework) must run on real Spark."""
    example_dir = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples", "mnist",
    )
    sys.path.insert(0, example_dir)
    try:
        import mnist_data_setup
        import mnist_spark

        # example modules are not importable on executors: ship by value
        # through BOTH picklers (pyspark task closures + the jax-child spawn)
        for mod in (mnist_spark, mnist_data_setup):
            cloudpickle.register_pickle_by_value(mod)
            try:
                _pyspark_cloudpickle.register_pickle_by_value(mod)
            except NameError:
                pass

        export_dir = str(tmp_path / "bundle")
        mnist_spark.main(
            [
                "--cluster_size", "2", "--epochs", "1",
                "--num_examples", "256", "--batch_size", "32",
                "--export_dir", export_dir, "--platform", "cpu",
                "--jax_distributed", "0",
            ],
            sc=sc,  # the module-scoped context: one SparkContext per JVM
        )
        assert os.path.isdir(export_dir)
    finally:
        sys.path.remove(example_dir)


def fn_instance(args, ctx):
    with open(os.path.join(args["out_dir"], "inst{}.txt".format(ctx.executor_id)), "w") as f:
        f.write("{}/{}".format(ctx.executor_id, ctx.num_workers))


def test_tfparallel_barrier_on_real_spark(sc, tmp_path):
    """TFParallel.run on real pyspark uses barrier-mode scheduling
    (reference TFParallel.py:63-64); local-cluster has exactly the 2 slots
    the 2 barrier tasks need."""
    done = TFParallel.run(sc, fn_instance, {"out_dir": str(tmp_path)}, 2, env=CPU_ENV)
    assert sorted(done) == [0, 1]
    assert sorted(os.listdir(str(tmp_path))) == ["inst0.txt", "inst1.txt"]
