"""parallel/ package tests on the virtual 8-device CPU mesh (conftest.py)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu import parallel
from tensorflowonspark_tpu.parallel import collectives, mesh as mesh_lib
from tensorflowonspark_tpu.parallel.ring_attention import (
    plain_attention,
    ring_attention_sharded,
)


def test_virtual_device_count():
    assert jax.device_count() == 8


class TestMesh:
    def test_default_is_pure_dp(self):
        m = parallel.build_mesh()
        assert mesh_lib.mesh_shape(m) == {"dp": 8}

    def test_fill_axis(self):
        m = parallel.build_mesh({"dp": -1, "tp": 2})
        assert mesh_lib.mesh_shape(m) == {"dp": 4, "tp": 2}

    def test_axis_order_is_canonical(self):
        m = parallel.build_mesh({"sp": 2, "dp": 2, "tp": 2})
        assert m.axis_names == ("dp", "tp", "sp")

    def test_custom_axis_appended(self):
        m = parallel.build_mesh({"dp": 4, "stage": 2})
        assert m.axis_names == ("dp", "stage")

    def test_bad_product_raises(self):
        with pytest.raises(ValueError):
            parallel.build_mesh({"dp": 3})

    def test_two_fills_raise(self):
        with pytest.raises(ValueError):
            parallel.build_mesh({"dp": -1, "tp": -1})


class TestSharding:
    def test_batch_spec_dp_only(self):
        m = parallel.build_mesh({"dp": 8})
        assert parallel.batch_spec(m) == P("dp")

    def test_batch_spec_dp_fsdp(self):
        m = parallel.build_mesh({"dp": 2, "fsdp": 4})
        assert parallel.batch_spec(m) == P(("dp", "fsdp"))

    def test_fsdp_param_specs(self):
        m = parallel.build_mesh({"fsdp": 8})
        params = {
            "dense": {"kernel": jnp.zeros((256, 128)), "bias": jnp.zeros((128,))},
            "tiny": jnp.zeros((4, 4)),
        }
        specs = parallel.fsdp_param_specs(params, m, min_weight_size=1024)
        assert specs["dense"]["kernel"] == P("fsdp", None)
        assert specs["dense"]["bias"] == P()  # too small
        assert specs["tiny"] == P()

    def test_fsdp_spec_picks_divisible_dim(self):
        m = parallel.build_mesh({"fsdp": 8})
        # first dim (129) not divisible by 8; second (256) is
        specs = parallel.fsdp_param_specs({"w": jnp.zeros((129, 256))}, m, min_weight_size=16)
        assert specs["w"] == P(None, "fsdp")

    def test_shard_batch_and_params_roundtrip(self):
        m = parallel.build_mesh({"dp": 8})
        batch = {"x": np.arange(64, dtype=np.float32).reshape(16, 4)}
        sharded = parallel.shard_batch(batch, m)
        assert sharded["x"].sharding.spec == P("dp")
        np.testing.assert_array_equal(np.asarray(sharded["x"]), batch["x"])

        params = parallel.shard_params({"w": jnp.ones((64, 8))}, m)
        np.testing.assert_array_equal(np.asarray(params["w"]), np.ones((64, 8)))


class TestCollectives:
    def test_psum_pmean_under_shard_map(self):
        m = parallel.build_mesh({"dp": 8})

        def f(x):
            return collectives.psum(x, "dp"), collectives.pmean(x, "dp")

        x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
        s, mu = parallel.shard_map(f, mesh=m, in_specs=P("dp"), out_specs=P("dp"))(x)
        np.testing.assert_allclose(np.asarray(s), np.full((8, 1), 28.0))
        np.testing.assert_allclose(np.asarray(mu), np.full((8, 1), 3.5))

    def test_ring_shift(self):
        m = parallel.build_mesh({"dp": 8})

        def f(x):
            return collectives.ring_shift(x, "dp")

        x = jnp.arange(8, dtype=jnp.float32).reshape(8, 1)
        out = np.asarray(parallel.shard_map(f, mesh=m, in_specs=P("dp"), out_specs=P("dp"))(x))
        np.testing.assert_array_equal(out[:, 0], np.roll(np.arange(8), 1))

    def test_reduce_scatter(self):
        m = parallel.build_mesh({"dp": 8})

        def f(x):
            return collectives.reduce_scatter(x, "dp")

        # every member holds the full vector; each ends up with its summed slice
        x = jnp.arange(8, dtype=jnp.float32)
        out = np.asarray(parallel.shard_map(f, mesh=m, in_specs=P(), out_specs=P("dp"))(x))
        np.testing.assert_allclose(out, np.arange(8, dtype=np.float32) * 8.0)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_plain_attention(self, causal):
        m = parallel.build_mesh({"dp": 2, "sp": 4})
        rng = np.random.default_rng(0)
        b, h, l, d = 4, 2, 32, 16
        q, k, v = (
            jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.float32) for _ in range(3)
        )
        expected = plain_attention(q, k, v, causal=causal)
        got = ring_attention_sharded(q, k, v, m, causal=causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def test_no_sp_axis_falls_back(self):
        m = parallel.build_mesh({"dp": 8})
        rng = np.random.default_rng(1)
        q, k, v = (
            jnp.asarray(rng.standard_normal((2, 2, 8, 4)), jnp.float32) for _ in range(3)
        )
        got = ring_attention_sharded(q, k, v, m, causal=True)
        expected = plain_attention(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(expected), atol=2e-5)

    def test_gradients_flow(self):
        m = parallel.build_mesh({"sp": 8})
        rng = np.random.default_rng(2)
        b, h, l, d = 2, 2, 32, 8
        q, k, v = (
            jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.float32) for _ in range(3)
        )

        def loss_ring(q, k, v):
            return ring_attention_sharded(q, k, v, m, causal=True).sum()

        def loss_plain(q, k, v):
            return plain_attention(q, k, v, causal=True).sum()

        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_plain = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
        for gr, gp in zip(g_ring, g_plain):
            np.testing.assert_allclose(np.asarray(gr), np.asarray(gp), atol=1e-4)


class TestPipelineParallel:
    """GPipe over the ``pp`` axis (beyond-parity; SURVEY §2.7 row PP):
    pipelined forward/backward must equal the sequential stage composition."""

    def _setup(self):
        import numpy as np

        from tensorflowonspark_tpu import parallel

        mesh = parallel.build_mesh({"pp": 4}, devices=jax.devices()[:4])
        rng = np.random.default_rng(0)
        d = 8
        stage_weights = [
            jnp.asarray(rng.standard_normal((d, d)) / np.sqrt(d), jnp.float32)
            for _ in range(4)
        ]
        stacked = parallel.stack_stage_params(
            [{"w": w} for w in stage_weights]
        )
        x = jnp.asarray(rng.standard_normal((16, d)), jnp.float32)
        return parallel, mesh, stage_weights, stacked, x

    @staticmethod
    def _stage_fn(params, x):
        return jnp.tanh(x @ params["w"])

    def _sequential(self, stage_weights, x):
        for w in stage_weights:
            x = self._stage_fn({"w": w}, x)
        return x

    def test_forward_matches_sequential(self):
        import numpy as np

        parallel, mesh, weights, stacked, x = self._setup()
        mb = parallel.split_microbatches(x, 8)
        out = parallel.pipeline_apply(self._stage_fn, stacked, mb, mesh)
        got = parallel.merge_microbatches(out)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(self._sequential(weights, x)), atol=1e-6
        )

    def test_gradients_match_sequential(self):
        import numpy as np

        parallel, mesh, weights, stacked, x = self._setup()
        mb = parallel.split_microbatches(x, 8)

        def loss_pp(stacked_params):
            out = parallel.pipeline_apply(self._stage_fn, stacked_params, mb, mesh)
            return jnp.sum(out ** 2)

        def loss_seq(stacked_params):
            y = x
            for i in range(4):
                y = self._stage_fn(jax.tree.map(lambda a: a[i], stacked_params), y)
            return jnp.sum(y ** 2)

        g_pp = jax.grad(loss_pp)(stacked)
        g_seq = jax.grad(loss_seq)(stacked)
        np.testing.assert_allclose(
            np.asarray(g_pp["w"]), np.asarray(g_seq["w"]), atol=1e-5
        )

    def test_jit_with_sharded_stage_params(self):
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        parallel, mesh, weights, stacked, x = self._setup()
        stacked = jax.device_put(stacked, NamedSharding(mesh, P("pp")))
        mb = parallel.split_microbatches(x, 8)

        @jax.jit
        def run(params, mb):
            return parallel.pipeline_apply(self._stage_fn, params, mb, mesh)

        out = parallel.merge_microbatches(run(stacked, mb))
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(self._sequential(weights, x)), atol=1e-6
        )
