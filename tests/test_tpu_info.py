"""tpu_info topology derivation + visibility env (the gpu_info analogue;
VERDICT r2 weak item 8: rule-based so any slice size resolves, validated
against the runtime's own device count)."""

import pytest

from tensorflowonspark_tpu import tpu_info


@pytest.mark.parametrize(
    "accel,expected",
    [
        # chip-counted generations: N = chips; single-host up to 8
        ("v5e-1", (1, 1)),
        ("v5e-4", (4, 4)),
        ("v5e-8", (8, 8)),
        ("v5e-16", (4, 16)),
        ("v5e-32", (4, 32)),
        ("v5e-256", (4, 256)),
        ("v6e-8", (8, 8)),
        ("v6e-64", (4, 64)),
        # core-counted generations: N = TensorCores = 2 per chip; 4-chip hosts
        ("v4-8", (4, 4)),
        ("v4-16", (4, 8)),
        ("v4-32", (4, 16)),
        ("v5p-8", (4, 4)),
        ("v5p-16", (4, 8)),
        ("v5p-128", (4, 64)),   # beyond the old fixed table
        ("v5p-1024", (4, 512)),
        ("v3-8", (4, 4)),
    ],
)
def test_topology_rules(accel, expected):
    assert tpu_info.topology_for(accel) == expected


def test_unknown_types_are_none():
    assert tpu_info.topology_for("tpu9000-4") is None
    assert tpu_info.topology_for("v5e") is None
    assert tpu_info.topology_for("v5e-x") is None
    assert tpu_info.topology_for(None) is None


def test_num_hosts():
    assert tpu_info.num_hosts_for("v5e-32") == 8
    assert tpu_info.num_hosts_for("v5e-8") == 1
    assert tpu_info.num_hosts_for("v4-32") == 4
    assert tpu_info.num_hosts_for("bogus") is None


def test_detect_override_env(monkeypatch):
    monkeypatch.setenv(tpu_info.ENV_CHIP_COUNT, "4")
    assert tpu_info.detect_local_chips() == 4
    assert tpu_info.is_tpu_available()


def test_detect_bounds_env(monkeypatch):
    monkeypatch.delenv(tpu_info.ENV_CHIP_COUNT, raising=False)
    monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,4,1")
    assert tpu_info.detect_local_chips() == 8


def test_local_topology_falls_back_to_accel_rule(monkeypatch):
    monkeypatch.delenv(tpu_info.ENV_CHIP_COUNT, raising=False)
    monkeypatch.delenv("TPU_CHIPS_PER_HOST_BOUNDS", raising=False)
    monkeypatch.delenv("TPU_CHIPS_PER_PROCESS_BOUNDS", raising=False)
    monkeypatch.setenv(tpu_info.ENV_ACCEL_TYPE, "v5p-64")
    topo = tpu_info.local_topology()
    # no /dev/accel files in this image -> derived from the type rule
    if topo["num_chips"]:
        assert topo["num_chips"] == 4


def test_visibility_env_grid_bounds(monkeypatch):
    monkeypatch.delenv("TPU_CHIPS_PER_HOST_BOUNDS", raising=False)
    env = tpu_info.visibility_env(chip_ids=[0, 1])
    assert env["TPU_VISIBLE_CHIPS"] == "0,1"
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "1,2,1"
    # host grid mirrored exactly when all chips visible
    monkeypatch.setenv("TPU_CHIPS_PER_HOST_BOUNDS", "2,4,1")
    env = tpu_info.visibility_env(chip_ids=list(range(8)))
    assert env["TPU_CHIPS_PER_PROCESS_BOUNDS"] == "2,4,1"


def test_validate_against_runtime(monkeypatch, caplog):
    monkeypatch.setenv(tpu_info.ENV_CHIP_COUNT, "4")
    assert tpu_info.validate_against_runtime(4)
    # v2/v3 runtimes report 2 TensorCores per chip: 2x detected is a match
    assert tpu_info.validate_against_runtime(8)
    assert not tpu_info.validate_against_runtime(12)
    monkeypatch.setenv(tpu_info.ENV_CHIP_COUNT, "0")
    assert tpu_info.validate_against_runtime(8)  # no detection -> trust runtime
