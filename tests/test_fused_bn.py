"""Fused pallas BatchNorm numerics vs flax.linen.BatchNorm (interpret mode).

The kernels are the r5 BN-slice experiment (docs/perf.md): whatever the
on-chip timing says, the math must be exactly training-mode batch norm —
forward, batch statistics, and the full custom VJP (dx folds the statistics'
dependency on x; dgamma/dbeta are the usual reductions)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import linen as nn

from tensorflowonspark_tpu.ops.fused_bn import FusedBatchNorm, fused_batch_norm


@pytest.mark.parametrize("n_ch", [64, 128])
@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_forward_matches_reference_math(n_ch, dtype):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 4, 4, n_ch)) * 2 + 1, dtype)
    gamma = jnp.asarray(rng.standard_normal(n_ch), jnp.float32)
    beta = jnp.asarray(rng.standard_normal(n_ch), jnp.float32)

    # block_r=16 forces multi-step grid accumulation (rows=64)
    y, mean, var = fused_batch_norm(x, gamma, beta, block_r=16, interpret=True)
    assert y.dtype == dtype

    xf = np.asarray(x, np.float64).reshape(-1, n_ch)
    ref_mean = xf.mean(axis=0)
    ref_var = xf.var(axis=0)
    ref_y = (xf - ref_mean) / np.sqrt(ref_var + 1e-5) * np.asarray(gamma) + np.asarray(beta)
    tol = 5e-5 if dtype == jnp.float32 else 5e-2
    np.testing.assert_allclose(np.asarray(mean), ref_mean, atol=tol)
    np.testing.assert_allclose(np.asarray(var), ref_var, atol=tol)
    np.testing.assert_allclose(
        np.asarray(y, np.float64).reshape(-1, n_ch), ref_y, atol=tol * 100
    )


def test_gradients_match_flax_batchnorm():
    """d(loss)/d(x, gamma, beta) must equal flax's training-mode BN grads —
    including the batch-statistics terms in dx."""
    rng = np.random.default_rng(1)
    n_ch = 64
    x = jnp.asarray(rng.standard_normal((2, 4, 4, n_ch)), jnp.float32)
    gamma = jnp.asarray(rng.standard_normal(n_ch), jnp.float32)
    beta = jnp.asarray(rng.standard_normal(n_ch), jnp.float32)
    w = jnp.asarray(rng.standard_normal((2, 4, 4, n_ch)), jnp.float32)  # loss weights

    def fused_loss(x, gamma, beta):
        y, _, _ = fused_batch_norm(x, gamma, beta, block_r=16, interpret=True)
        return jnp.sum(y * w)

    bn = nn.BatchNorm(use_running_average=False, momentum=0.9, epsilon=1e-5)
    variables = bn.init(jax.random.PRNGKey(0), x)

    def flax_loss(x, gamma, beta):
        params = {"params": {"scale": gamma, "bias": beta},
                  "batch_stats": variables["batch_stats"]}
        y, _ = bn.apply(params, x, mutable=["batch_stats"])
        return jnp.sum(y * w)

    got = jax.grad(fused_loss, argnums=(0, 1, 2))(x, gamma, beta)
    want = jax.grad(flax_loss, argnums=(0, 1, 2))(x, gamma, beta)
    for g, r, name in zip(got, want, ("dx", "dgamma", "dbeta")):
        np.testing.assert_allclose(np.asarray(g), np.asarray(r), atol=1e-4, err_msg=name)


def test_module_matches_flax_module_and_updates_running_stats():
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((2, 8, 8, 64)) + 0.5, jnp.float32)

    fused = FusedBatchNorm(momentum=0.9, interpret=True, block_r=32)
    ref = nn.BatchNorm(momentum=0.9, epsilon=1e-5)
    fvars = fused.init(jax.random.PRNGKey(0), x, use_running_average=False)
    rvars = ref.init(jax.random.PRNGKey(0), x, use_running_average=False)
    # identical variable structure: checkpoints interchange
    assert jax.tree.structure(fvars) == jax.tree.structure(rvars)

    fy, fmut = fused.apply(fvars, x, use_running_average=False, mutable=["batch_stats"])
    ry, rmut = ref.apply(rvars, x, use_running_average=False, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(fy), np.asarray(ry), atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(fmut["batch_stats"]["mean"]),
        np.asarray(rmut["batch_stats"]["mean"]), atol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(fmut["batch_stats"]["var"]),
        np.asarray(rmut["batch_stats"]["var"]), atol=1e-4,
    )

    # eval mode uses the (updated) running stats, same as flax
    fe = fused.apply(
        {"params": fvars["params"], "batch_stats": fmut["batch_stats"]},
        x, use_running_average=True,
    )
    re = ref.apply(
        {"params": rvars["params"], "batch_stats": rmut["batch_stats"]},
        x, use_running_average=True,
    )
    np.testing.assert_allclose(np.asarray(fe), np.asarray(re), atol=1e-4)


def test_odd_rows_fall_back_instead_of_raising(caplog):
    """An odd per-shard batch (rows=7*5*5=175: no 8..block_r power-of-two
    divisor) must not crash the module at trace time: the train path logs a
    warning and falls back to the plain XLA spelling, matching flax BN in
    forward, running stats, and gradients. Direct ``fused_batch_norm``
    callers still get the loud error."""
    import logging

    rng = np.random.default_rng(4)
    x = jnp.asarray(rng.standard_normal((7, 5, 5, 32)) * 1.5 + 0.25, jnp.float32)
    w = jnp.asarray(rng.standard_normal(x.shape), jnp.float32)

    fused = FusedBatchNorm(momentum=0.9, interpret=True, block_r=16)
    ref = nn.BatchNorm(momentum=0.9, epsilon=1e-5)
    fvars = fused.init(jax.random.PRNGKey(0), x, use_running_average=False)
    rvars = ref.init(jax.random.PRNGKey(0), x, use_running_average=False)

    with caplog.at_level(logging.WARNING, logger="tensorflowonspark_tpu.ops.fused_bn"):
        fy, fmut = fused.apply(fvars, x, use_running_average=False, mutable=["batch_stats"])
    assert any("falling back" in r.getMessage() for r in caplog.records)

    ry, rmut = ref.apply(rvars, x, use_running_average=False, mutable=["batch_stats"])
    np.testing.assert_allclose(np.asarray(fy), np.asarray(ry), atol=1e-4)
    for stat, tol in (("mean", 1e-5), ("var", 1e-4)):
        np.testing.assert_allclose(
            np.asarray(fmut["batch_stats"][stat]),
            np.asarray(rmut["batch_stats"][stat]), atol=tol,
        )

    # gradients flow like flax's (batch-statistics terms included)
    def make_loss(model, variables):
        def f(params):
            y, _ = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x, use_running_average=False, mutable=["batch_stats"],
            )
            return jnp.sum(y * w)

        return f

    got = jax.grad(make_loss(fused, fvars))(fvars["params"])
    want = jax.grad(make_loss(ref, rvars))(rvars["params"])
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-3),
        got, want,
    )

    gamma = jnp.ones(32, jnp.float32)
    beta = jnp.zeros(32, jnp.float32)
    with pytest.raises(ValueError, match="block divisor"):
        fused_batch_norm(x, gamma, beta, block_r=16, interpret=True)


def test_resnet_bn_impl_pallas_trains():
    """resnet56(bn_impl='pallas') runs a forward+backward on CPU (interpret
    mode via the model's backend check) and matches the flax-BN model's loss
    at identical params."""
    from tensorflowonspark_tpu.models import resnet

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((4, 8, 8, 3)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 10, 4))

    flax_model = resnet.ResNet(
        stage_sizes=(1,), filters=(16,), num_classes=10, bottleneck=False,
        stem="cifar", bn_impl="flax",
    )
    pallas_model = resnet.ResNet(
        stage_sizes=(1,), filters=(16,), num_classes=10, bottleneck=False,
        stem="cifar", bn_impl="pallas",
    )
    variables = flax_model.init(jax.random.PRNGKey(0), x, train=False)

    def loss(model, variables):
        def f(params):
            logits, _ = model.apply(
                {"params": params, "batch_stats": variables["batch_stats"]},
                x, train=True, mutable=["batch_stats"],
            )
            return optax_ce(logits, labels)

        return jax.value_and_grad(f)(variables["params"])

    import optax

    def optax_ce(logits, labels):
        return optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()

    flax_loss, flax_grads = loss(flax_model, variables)
    pallas_loss, pallas_grads = loss(pallas_model, variables)
    np.testing.assert_allclose(float(pallas_loss), float(flax_loss), atol=1e-4)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=2e-3),
        flax_grads, pallas_grads,
    )
