"""Serving mesh (ISSUE 13): replica leases, routed failover, hedging, and
zero-downtime hot swap.

Router tests run against static endpoint dicts and plain
``InferenceServer``s so each behavior (round-robin, failover, circuit shed,
hedging, final-error naming) is isolated; the mesh lifecycle and hot-swap
tests run a real thread-mode :class:`ServingMesh` with short lease TTLs so
kill → lease expiry → relaunch happens inside a few monitor ticks."""

import os
import socket
import threading
import time
import urllib.request

import numpy as np
import pytest

from tensorflowonspark_tpu import chaos, obs, resilience, serving
from tensorflowonspark_tpu.ckpt import manifest
from tensorflowonspark_tpu.serving import InferenceClient, InferenceServer, Overloaded
from tensorflowonspark_tpu.serving_mesh import (
    MeshFrontend,
    ModelPointer,
    ReplicaRouter,
    ReplicaServer,
    ServingMesh,
)
from tensorflowonspark_tpu.train import export


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _builder():
    def predict(params, model_state, arrays):
        return {"y_": arrays["x"] @ params["w"]}

    return predict


def _params(scale):
    return {"w": np.full((1, 1), float(scale), np.float32)}


def _bundle(path, scale):
    export.export_model(str(path), _builder, _params(scale))
    return str(path)


def _counter(name):
    return obs.snapshot()["counters"].get(name, {}).get("value", 0)


def _gauge(name):
    return obs.snapshot()["gauges"].get(name, {}).get("value", 0)


def _value(out):
    return float(np.asarray(out["y_"]).ravel()[0])


def _fast_router(endpoints, **kw):
    kw.setdefault("deadline", 10.0)
    kw.setdefault("backoff", resilience.Backoff(base=0.02, factor=2.0,
                                                max_delay=0.1, jitter=0.5, seed=0))
    return ReplicaRouter(endpoints, **kw)


def _dead_port():
    """A port nothing listens on (bound once, then released)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class TestModelPointer:
    def test_publish_flips_pointer_atomically(self, tmp_path):
        pointer = ModelPointer(str(tmp_path / "ptr"))
        assert pointer.current() is None
        gen0 = pointer.publish(_builder, _params(2))
        assert pointer.current() == ("gen-000000", gen0)
        ok, reason = manifest.verify(gen0)
        assert ok, reason
        gen1 = pointer.publish(_builder, _params(5))
        assert pointer.generations() == ["gen-000000", "gen-000001"]
        assert pointer.current() == ("gen-000001", gen1)

    def test_publish_bundle_adopts_and_restamps(self, tmp_path):
        src = _bundle(tmp_path / "src", 3)
        manifest.write_manifest(src, step=7)  # stale source manifest
        pointer = ModelPointer(str(tmp_path / "ptr"))
        gen0 = pointer.publish_bundle(src, step=9)
        ok, _ = manifest.verify(gen0)
        assert ok
        assert manifest.read_manifest(gen0)["extra"]["generation"] == "gen-000000"

    def test_torn_publish_fails_cheap_verify(self, tmp_path):
        pointer = ModelPointer(str(tmp_path / "ptr"))
        plan = chaos.ChaosPlan(seed=0).site(
            "serving.swap_torn", probability=1.0, max_count=1
        )
        chaos.install(plan, propagate=False)
        gen0 = pointer.publish(_builder, _params(2))
        assert plan.fired("serving.swap_torn") == 1
        ok, reason = manifest.verify(gen0)
        assert not ok and reason


class TestReplicaServer:
    def test_hot_swap_serves_new_generation(self, tmp_path):
        pointer = ModelPointer(str(tmp_path / "ptr"))
        pointer.publish(_builder, _params(2))
        rep = ReplicaServer(pointer.root, poll_interval=999)
        rep.start()
        client = InferenceClient(
            rep.address, timeout=30, retry=resilience.RetryPolicy(max_attempts=1)
        )
        try:
            assert _value(client.predict_binary(x=np.ones((1, 1), np.float32))) == 2.0
            swaps = _counter("serving_swaps_total")
            pointer.publish(_builder, _params(5))
            assert rep.check_swap() is True
            assert _counter("serving_swaps_total") - swaps == 1
            assert rep.generation() == "gen-000001"
            assert _value(client.predict_binary(x=np.ones((1, 1), np.float32))) == 5.0
            # same pointer again: no second swap, no second compile
            assert rep.check_swap() is False
            assert _counter("serving_swaps_total") - swaps == 1
        finally:
            client.close()
            rep.stop()

    def test_torn_swap_rejected_old_model_keeps_serving(self, tmp_path):
        pointer = ModelPointer(str(tmp_path / "ptr"))
        pointer.publish(_builder, _params(2))
        rep = ReplicaServer(pointer.root, poll_interval=999)
        rep.start()
        client = InferenceClient(
            rep.address, timeout=30, retry=resilience.RetryPolicy(max_attempts=1)
        )
        try:
            rejects = _counter("serving_swap_rejects_total")
            chaos.install(
                chaos.ChaosPlan(seed=1).site(
                    "serving.swap_torn", probability=1.0, max_count=1
                ),
                propagate=False,
            )
            pointer.publish(_builder, _params(9))  # torn on disk
            assert rep.check_swap() is False
            assert _counter("serving_swap_rejects_total") - rejects == 1
            assert rep.generation() == "gen-000000"
            assert _value(client.predict_binary(x=np.ones((1, 1), np.float32))) == 2.0
            # the rejected generation is remembered: no re-verify, no recount
            assert rep.check_swap() is False
            assert _counter("serving_swap_rejects_total") - rejects == 1
            chaos.uninstall()
            pointer.publish(_builder, _params(7))  # a good publish recovers
            assert rep.check_swap() is True
            assert _value(client.predict_binary(x=np.ones((1, 1), np.float32))) == 7.0
        finally:
            client.close()
            rep.stop()


class _SlowEcho(serving.ProtocolServer):
    """A protocol-speaking replica stand-in whose answers take ``delay``
    seconds — the hedging target."""

    def __init__(self, delay):
        self.delay = delay
        serving.ProtocolServer.__init__(self, host="127.0.0.1", port=0,
                                        name="tos-test-slow")

    def _submit(self, arrays):
        time.sleep(self.delay)
        return {"y_": np.full_like(np.asarray(arrays["x"]), 99.0)}


class TestReplicaRouter:
    @pytest.fixture
    def pair(self, tmp_path):
        a = InferenceServer(_bundle(tmp_path / "a", 1))
        b = InferenceServer(_bundle(tmp_path / "b", 2))
        a.start()
        b.start()
        yield a, b
        a.stop()
        b.stop()

    def test_round_robin_spreads_requests(self, pair):
        a, b = pair
        router = _fast_router({0: a.address, 1: b.address})
        try:
            seen = {
                _value(router.predict_binary(x=np.ones((1, 1), np.float32)))
                for _ in range(4)
            }
            assert seen == {1.0, 2.0}
        finally:
            router.close()

    def test_failover_reroutes_around_dead_replica(self, pair):
        a, b = pair
        a.kill()  # abrupt socket death; rid 0 is picked first every cycle
        failovers = _counter("serving_failovers_total")
        router = _fast_router({0: a.address, 1: b.address}, breaker_threshold=50)
        try:
            for _ in range(3):
                out = router.predict_binary(x=np.ones((1, 1), np.float32))
                assert _value(out) == 2.0
            assert _counter("serving_failovers_total") - failovers >= 3
        finally:
            router.close()

    def test_all_circuits_open_sheds_with_distinct_reason(self):
        eps = {0: ("127.0.0.1", _dead_port()), 1: ("127.0.0.1", _dead_port())}
        shed = _counter("serving_mesh_shed_total")
        trips = _counter("serving_circuit_open_total")
        router = _fast_router(eps, breaker_threshold=1, breaker_reset=60.0)
        try:
            with pytest.raises(Overloaded, match="circuits open"):
                router.predict_binary(x=np.ones((1, 1), np.float32))
            assert _counter("serving_mesh_shed_total") - shed == 1
            assert _counter("serving_circuit_open_total") - trips == 2
        finally:
            router.close()

    def test_empty_mesh_sheds_immediately(self):
        shed = _counter("serving_mesh_shed_total")
        router = _fast_router({})
        try:
            with pytest.raises(Overloaded, match="no live replicas"):
                router.predict(x=[[1.0]])
            assert _counter("serving_mesh_shed_total") - shed == 1
        finally:
            router.close()

    def test_final_error_names_replicas_elapsed_and_budget(self):
        eps = {0: ("127.0.0.1", _dead_port())}
        router = _fast_router(eps, deadline=1.0, breaker_threshold=100)
        try:
            with pytest.raises(ConnectionError) as err:
                router.predict_binary(x=np.ones((1, 1), np.float32))
            msg = str(err.value)
            assert "replica(s) [0]" in msg
            assert "1s budget" in msg
            assert "after" in msg
        finally:
            router.close()

    def test_hedge_to_second_replica_wins(self, tmp_path):
        slow = _SlowEcho(delay=1.5)
        slow.start()
        fast = InferenceServer(_bundle(tmp_path / "fast", 4))
        fast.start()
        hedges = _counter("serving_hedges_total")
        router = _fast_router(
            {0: slow.address, 1: fast.address}, hedge_after=0.15
        )
        try:
            out = router.predict_binary(x=np.ones((1, 1), np.float32))
            assert _value(out) == 4.0  # the hedge answered first
            assert _counter("serving_hedges_total") - hedges == 1
        finally:
            router.close()
            fast.stop()
            slow.stop()


class TestMeshLifecycle:
    def test_start_route_and_frontend(self, tmp_path):
        mesh = ServingMesh(
            _bundle(tmp_path / "bundle", 3), replicas=2, mode="thread",
            monitor_interval=0.5,
        )
        mesh.start()
        router = mesh.router(deadline=10.0)
        front = MeshFrontend(router, host="127.0.0.1")
        front.start()
        client = InferenceClient(front.address, timeout=30)
        try:
            assert len(mesh.endpoints()) == 2
            assert _value(router.predict_binary(x=np.ones((1, 1), np.float32))) == 3.0
            # the frontend speaks the plain InferenceServer protocol
            out = client.predict_binary(x=np.ones((1, 1), np.float32))
            assert _value(out) == 3.0
            assert client.info().get("mesh") is True
        finally:
            client.close()
            front.stop()
            router.close()
            mesh.stop()

    def test_kill_expires_lease_relaunches_and_requests_survive(self, tmp_path):
        """ISSUE 13 e2e (thread mode): hard-kill 1 of 2 replicas under load —
        every request completes via failover, the dead lease expires, the
        active gauge dips, and the slot relaunches on a fresh port."""
        mesh = ServingMesh(
            _bundle(tmp_path / "bundle", 3), replicas=2, mode="thread",
            monitor_interval=0.2, lease_ttl=0.8,
        )
        mesh.start()
        router = mesh.router(deadline=15.0)
        relaunches = _counter("serving_replica_relaunches_total")
        expiries = _counter("registry_lease_expirations_total")
        errors = []
        min_active = [99]
        stop = threading.Event()

        def load():
            while not stop.is_set():
                try:
                    out = router.predict_binary(x=np.ones((1, 1), np.float32))
                    assert _value(out) == 3.0
                except Exception as e:  # any client-visible failure is a bug
                    errors.append(e)
                min_active[0] = min(min_active[0], _gauge("serving_replicas_active"))
                time.sleep(0.01)

        threads = [threading.Thread(target=load) for _ in range(3)]
        try:
            # wait for at least one renewed beat so the victim's lease is
            # expirable (never-beat leases are expiry-exempt by contract)
            deadline = time.time() + 10
            while time.time() < deadline and mesh._beats.get(0, 0) < 1:
                time.sleep(0.05)
            assert mesh._beats.get(0, 0) >= 1
            old_addr = mesh.endpoints()[0]
            for t in threads:
                t.start()
            assert mesh.kill_replica(0) == 0
            deadline = time.time() + 30
            while time.time() < deadline:
                if (
                    _counter("serving_replica_relaunches_total") - relaunches >= 1
                    and len(mesh.endpoints()) == 2
                ):
                    break
                time.sleep(0.1)
            time.sleep(0.3)  # a little settled load on the recovered mesh
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert not errors, errors[:3]
            assert _counter("serving_replica_relaunches_total") - relaunches >= 1
            assert _counter("registry_lease_expirations_total") - expiries >= 1
            assert len(mesh.endpoints()) == 2
            assert mesh.endpoints()[0] != old_addr  # fresh port after relaunch
            assert min_active[0] <= 1  # the gauge dip was observable
            assert _gauge("serving_replicas_active") == 2
        finally:
            stop.set()
            router.close()
            mesh.stop()

    def test_hot_swap_under_load_zero_failures(self, tmp_path):
        """ISSUE 13 e2e: publish a new generation mid-load — responses flip,
        zero dropped/failed requests, exactly one swap (compile) per
        replica, and no rejects."""
        pointer = ModelPointer(str(tmp_path / "ptr"))
        pointer.publish(_builder, _params(2))
        mesh = ServingMesh(
            pointer.root, replicas=2, mode="thread",
            monitor_interval=0.5, swap_poll=0.1,
        )
        mesh.start()
        router = mesh.router(deadline=15.0)
        swaps = _counter("serving_swaps_total")
        rejects = _counter("serving_swap_rejects_total")
        values, errors = [], []
        stop = threading.Event()

        def load():
            while not stop.is_set():
                try:
                    values.append(
                        _value(router.predict_binary(x=np.ones((1, 1), np.float32)))
                    )
                except Exception as e:
                    errors.append(e)
                time.sleep(0.005)

        threads = [threading.Thread(target=load) for _ in range(4)]
        try:
            for t in threads:
                t.start()
            time.sleep(0.3)
            pointer.publish(_builder, _params(6))
            deadline = time.time() + 20
            while time.time() < deadline:
                with mesh._lock:
                    gens = [rec.server.generation() for rec in mesh._replicas.values()]
                if all(g == "gen-000001" for g in gens):
                    break
                time.sleep(0.05)
            time.sleep(0.3)
            stop.set()
            for t in threads:
                t.join(timeout=30)
            assert not errors, errors[:3]
            assert set(values) <= {2.0, 6.0}
            assert values[-1] == 6.0  # responses flipped to the new model
            assert _counter("serving_swaps_total") - swaps == 2
            assert _counter("serving_swap_rejects_total") - rejects == 0
        finally:
            stop.set()
            router.close()
            mesh.stop()

    def test_cli_mesh_mode_scrape_shows_replica_gauge(self, tmp_path):
        """Satellite: ``serving mesh --metrics_port`` publishes the mesh
        gauges, so a scrape shows ``serving_replicas_active``."""
        bundle = _bundle(tmp_path / "bundle", 3)
        front_port, metrics_port = _dead_port(), _dead_port()
        t = threading.Thread(
            target=serving.main,
            args=(
                [
                    "mesh", "--export_dir", bundle, "--replicas", "2",
                    "--host", "127.0.0.1", "--port", str(front_port),
                    "--metrics_port", str(metrics_port),
                ],
            ),
            daemon=True,
        )
        t.start()
        try:
            body = None
            deadline = time.time() + 60
            while time.time() < deadline:
                try:
                    with urllib.request.urlopen(
                        "http://127.0.0.1:{}/metrics".format(metrics_port), timeout=5
                    ) as resp:
                        body = resp.read().decode("utf-8")
                    break
                except OSError:
                    time.sleep(0.2)
            assert body is not None, "metrics endpoint never came up"
            assert "serving_replicas_active" in body
            client = InferenceClient(("127.0.0.1", front_port), timeout=30)
            try:
                out = client.predict_binary(x=np.ones((1, 1), np.float32))
                assert _value(out) == 3.0
            finally:
                client.close()
        finally:
            deadline = time.time() + 10
            while serving._exit_event is None and time.time() < deadline:
                time.sleep(0.05)
            if serving._exit_event is not None:
                serving._exit_event.set()
            t.join(timeout=60)
        assert not t.is_alive(), "mesh CLI did not shut down"


class TestMeshProcessMode:
    @pytest.mark.slow
    def test_process_replicas_serve_and_survive_sigkill(self, tmp_path):
        """Process-mode smoke: forked replicas serve; a SIGKILL'd child is
        discovered, its lease expires, and the slot relaunches."""
        mesh = ServingMesh(
            _bundle(tmp_path / "bundle", 5), replicas=2, mode="process",
            monitor_interval=0.3, lease_ttl=1.0,
        )
        mesh.start()
        router = mesh.router(deadline=20.0)
        relaunches = _counter("serving_replica_relaunches_total")
        try:
            assert _value(router.predict_binary(x=np.ones((1, 1), np.float32))) == 5.0
            assert mesh.kill_replica(0) == 0
            deadline = time.time() + 60
            while time.time() < deadline:
                if _counter("serving_replica_relaunches_total") - relaunches >= 1:
                    break
                out = router.predict_binary(x=np.ones((1, 1), np.float32))
                assert _value(out) == 5.0
                time.sleep(0.2)
            assert _counter("serving_replica_relaunches_total") - relaunches >= 1
            assert _value(router.predict_binary(x=np.ones((1, 1), np.float32))) == 5.0
        finally:
            router.close()
            mesh.stop()
