"""Streaming (DStream-equivalent) micro-batch feeding — VERDICT round-1
item 5. Batches arrive in waves, training proceeds between them, external
STOP works, and shutdown drains without deadlock (reference analogues:
TFCluster.py:83-85 DStream branch, mnist_spark_streaming.py,
utils/stop_streaming.py).
"""

import json
import os
import time

import pytest

from tensorflowonspark_tpu import TFCluster, reservation
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext, LocalStreamingContext

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


@pytest.fixture
def sc():
    ctx = LocalSparkContext(num_executors=2, task_timeout=240)
    yield ctx
    ctx.stop()


def fn_count_rows(args, ctx):
    """Consumes the stream until end-of-feed; records its row total."""
    feed = ctx.get_data_feed(train_mode=True)
    total = 0
    while not feed.should_stop():
        batch = feed.next_batch(16)
        total += len(batch)
    with open(os.path.join(args["out_dir"], "node{}.json".format(ctx.executor_id)), "w") as f:
        json.dump({"rows": total}, f)


def _totals(out_dir, n):
    total = 0
    for eid in range(n):
        with open(os.path.join(out_dir, "node{}.json".format(eid))) as f:
            total += json.load(f)["rows"]
    return total


def test_waves_then_clean_shutdown(sc, tmp_path):
    """Micro-batches arriving in waves are all consumed; shutdown drains."""
    cluster = TFCluster.run(
        sc, fn_count_rows, {"out_dir": str(tmp_path)}, num_executors=2,
        input_mode=InputMode.SPARK, master_node=None,
        env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
    )
    ssc = LocalStreamingContext(sc, batch_interval=0.2)
    stream = ssc.queueStream()
    cluster.train(stream)
    ssc.start()
    for wave in range(3):
        ssc.feed(sc.parallelize(range(wave * 64, (wave + 1) * 64), 2))
        time.sleep(0.3)
    cluster.shutdown(ssc=ssc, grace_secs=2, timeout=240)
    assert _totals(str(tmp_path), 2) == 3 * 64


def test_generator_of_rdds(sc, tmp_path):
    """cluster.train also accepts a plain iterable of RDDs."""
    cluster = TFCluster.run(
        sc, fn_count_rows, {"out_dir": str(tmp_path)}, num_executors=2,
        input_mode=InputMode.SPARK, master_node=None,
        env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
    )

    def waves():
        for wave in range(4):
            yield sc.parallelize(range(32), 2)

    cluster.train(waves())
    cluster.shutdown(grace_secs=2, timeout=240)
    assert _totals(str(tmp_path), 2) == 4 * 32


def test_external_stop_ends_stream(sc, tmp_path):
    """utils/stop_cluster-style STOP on the control plane halts the feed."""
    cluster = TFCluster.run(
        sc, fn_count_rows, {"out_dir": str(tmp_path)}, num_executors=2,
        input_mode=InputMode.SPARK, master_node=None,
        env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
    )
    ssc = LocalStreamingContext(sc, batch_interval=0.2)
    stream = ssc.queueStream()
    cluster.train(stream)
    ssc.start()
    ssc.feed(sc.parallelize(range(64), 2))
    time.sleep(0.5)

    # external stop (the reference's utils/stop_streaming.py flow)
    reservation.Client(cluster.cluster_meta["server_addr"]).request_stop()
    assert cluster.stop_requested
    # micro-batches after the stop are NOT fed
    ssc.feed(sc.parallelize(range(64), 2))
    time.sleep(0.5)

    cluster.shutdown(ssc=ssc, grace_secs=2, timeout=240)
    assert _totals(str(tmp_path), 2) == 64
