"""Shared helpers for the tosa analyzer tests.

``tosa`` lives at ``tools/analyze/tosa`` with a repo-root symlink, so
putting the repo root on ``sys.path`` makes ``import tosa`` work the same
way ``python -m tosa`` does from a checkout.
"""

import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from tosa import analyze_source, core, make_checkers  # noqa: E402

#: default fixture path — inside the library so library-scoped rules apply
LIB_PATH = "tensorflowonspark_tpu/fixture_mod.py"


def run_rule(rule, source, relpath=LIB_PATH):
    """Analyze one in-memory file under a single rule; unsuppressed
    findings only (what would gate)."""
    findings = analyze_source(source, relpath, make_checkers([rule]))
    return [f for f in findings if f.suppressed is None]


def run_rule_multi(rule, files):
    """Analyze several in-memory files (``{relpath: source}``) under one
    rule, including the cross-file ``end_run`` pass."""
    checkers = make_checkers([rule])
    run = core.RunContext()
    findings = []
    for relpath, source in files.items():
        findings.extend(analyze_source(source, relpath, checkers, run=run))
    for checker in checkers:
        checker.end_run(run)
    findings.extend(run.findings)
    return [f for f in findings if f.suppressed is None]


def run_project_rule(rule, files, docs=None, keep_suppressed=False):
    """Run a project-wide rule over in-memory files (``{relpath: source}``)
    through the two-phase engine: phase-1 index + per-file walks, then
    ``check_project``. ``docs`` injects documentation text (e.g. a Metrics
    inventory) keyed by relpath. Returns unsuppressed findings unless
    ``keep_suppressed``."""
    from tosa.index import ProjectIndex

    checkers = make_checkers([rule])
    run = core.RunContext()
    proj = ProjectIndex(docs=dict(docs or {}))
    findings = []
    for relpath, source in files.items():
        findings.extend(
            analyze_source(source, relpath, checkers, run=run, project=proj)
        )
    for checker in checkers:
        check_project = getattr(checker, "check_project", None)
        if check_project is not None:
            check_project(proj, run)
        else:
            checker.end_run(run)
    for f in run.findings:
        core._apply_suppressions([f], run.suppressions.get(f.path, {}))
    findings.extend(run.findings)
    if keep_suppressed:
        return findings
    return [f for f in findings if f.suppressed is None]
