"""The manifest commit marker: write-last, cheap-verify, reject-with-reason.

Unit coverage for :mod:`tensorflowonspark_tpu.ckpt.manifest` — the
integrity half of the async engine's atomic commit protocol. Every
rejection reason asserted here is a string ``restore_latest`` surfaces in
its skip log, so the shapes are pinned."""

import json
import os

from tensorflowonspark_tpu.ckpt import manifest


def _make_ckpt(root, files):
    os.makedirs(root, exist_ok=True)
    for rel, payload in files.items():
        sub = os.path.join(root, rel)
        os.makedirs(os.path.dirname(sub), exist_ok=True)
        with open(sub, "wb") as f:
            f.write(payload)


class TestWriteManifest:
    def test_roundtrip_verifies(self, tmp_path):
        root = str(tmp_path / "ckpt_1")
        _make_ckpt(root, {"a.bin": b"hello", "sub/b.bin": b"world" * 100})
        m = manifest.write_manifest(root, step=1)
        assert set(m["files"]) == {"a.bin", os.path.join("sub", "b.bin")}
        assert m["files"]["a.bin"]["size"] == 5
        assert manifest.verify(root) == (True, "verified")

    def test_manifest_excludes_itself_and_leaves_no_temp(self, tmp_path):
        root = str(tmp_path / "ckpt_2")
        _make_ckpt(root, {"a.bin": b"x"})
        manifest.write_manifest(root, step=2)
        manifest.write_manifest(root, step=2)  # idempotent rewrite
        names = os.listdir(root)
        assert manifest.MANIFEST_NAME in names
        assert not any(n.endswith(".tmp") for n in names)
        assert set(manifest.read_manifest(root)["files"]) == {"a.bin"}

    def test_step_and_extra_recorded(self, tmp_path):
        root = str(tmp_path / "ckpt_3")
        _make_ckpt(root, {"a.bin": b"x"})
        manifest.write_manifest(root, step=3, extra={"mesh": "dp=8"})
        m = manifest.read_manifest(root)
        assert m["step"] == 3 and m["extra"] == {"mesh": "dp=8"}


class TestVerifyRejections:
    def _committed(self, tmp_path):
        root = str(tmp_path / "ckpt_9")
        _make_ckpt(root, {"a.bin": b"A" * 64, "b.bin": b"B" * 64})
        manifest.write_manifest(root, step=9)
        return root

    def test_no_manifest_is_legacy_ok(self, tmp_path):
        root = str(tmp_path / "old")
        _make_ckpt(root, {"a.bin": b"x"})
        assert manifest.verify(root) == (True, "no manifest")
        assert manifest.read_manifest(root) is None

    def test_missing_file(self, tmp_path):
        root = self._committed(tmp_path)
        os.remove(os.path.join(root, "b.bin"))
        ok, reason = manifest.verify(root)
        assert not ok and "missing file b.bin" in reason

    def test_size_mismatch(self, tmp_path):
        root = self._committed(tmp_path)
        with open(os.path.join(root, "a.bin"), "ab") as f:
            f.write(b"tail")
        ok, reason = manifest.verify(root)
        assert not ok and "size mismatch on a.bin" in reason

    def test_checksum_mismatch_same_size(self, tmp_path):
        root = self._committed(tmp_path)
        with open(os.path.join(root, "a.bin"), "r+b") as f:
            f.write(b"Z")  # flip bytes, keep the size
        ok, reason = manifest.verify(root)
        assert not ok and "checksum mismatch on a.bin" in reason

    def test_torn_manifest_json(self, tmp_path):
        root = self._committed(tmp_path)
        mpath = os.path.join(root, manifest.MANIFEST_NAME)
        with open(mpath, "r+b") as f:
            f.truncate(os.path.getsize(mpath) // 2)
        ok, reason = manifest.verify(root)
        assert not ok and "torn manifest" in reason

    def test_manifest_without_file_table(self, tmp_path):
        root = self._committed(tmp_path)
        with open(os.path.join(root, manifest.MANIFEST_NAME), "w") as f:
            json.dump({"version": 1}, f)
        ok, reason = manifest.verify(root)
        assert not ok and "no file table" in reason
