"""The async checkpoint engine: snapshot, background commit, loop hook.

Chaos-free unit coverage (the fault-injection legs live in
tests/test_ckpt_chaos.py): commits publish manifest-verified checkpoints,
``run_steps`` drives the ``save_every_n`` cadence and drains on exit, the
snapshot pool double-buffers, and pruning honors the in-flight registry."""

import os

import numpy as np
import pytest

from tensorflowonspark_tpu import ckpt, obs
from tensorflowonspark_tpu.ckpt.snapshot import SnapshotBuffers, snapshot_to_host
from tensorflowonspark_tpu.train import checkpoint
from tensorflowonspark_tpu.train.strategy import run_steps


def _state(step):
    return {"step": np.int64(step), "w": np.full(16, float(step), np.float32)}


class TestEngineCommit:
    def test_save_publishes_manifest_verified_checkpoint(self, tmp_path):
        d = str(tmp_path)
        with ckpt.AsyncCheckpointEngine(d) as eng:
            eng.save(_state(3), 3)
            assert eng.drain(timeout=60)
        assert sorted(os.listdir(d)) == ["ckpt_3"]
        assert ckpt.verify(os.path.join(d, "ckpt_3")) == (True, "verified")
        state, path = checkpoint.restore_latest(d)
        assert os.path.basename(path) == "ckpt_3"
        np.testing.assert_array_equal(state["w"], np.full(16, 3.0, np.float32))
        assert eng.error is None

    def test_sequential_saves_keep_prune_budget(self, tmp_path):
        d = str(tmp_path)
        with ckpt.AsyncCheckpointEngine(d, keep=2) as eng:
            for step in (1, 2, 3, 4):
                eng.save(_state(step), step)
                assert eng.drain(timeout=60)
        assert sorted(os.listdir(d)) == ["ckpt_3", "ckpt_4"]

    def test_resave_same_step_replaces(self, tmp_path):
        d = str(tmp_path)
        with ckpt.AsyncCheckpointEngine(d) as eng:
            eng.save(_state(7), 7)
            assert eng.drain(timeout=60)
            eng.save({"step": np.int64(7), "w": np.full(16, 99.0, np.float32)}, 7)
        state, _ = checkpoint.restore_latest(d)
        np.testing.assert_array_equal(state["w"], np.full(16, 99.0, np.float32))

    def test_save_after_close_raises(self, tmp_path):
        eng = ckpt.AsyncCheckpointEngine(str(tmp_path))
        eng.close()
        with pytest.raises(RuntimeError):
            eng.save(_state(1), 1)
        eng.close()  # idempotent

    def test_counters_flow(self, tmp_path):
        before_bytes = obs.counter("ckpt_bytes_total").value
        before_commits = obs.counter("ckpt_commits_total").value
        with ckpt.AsyncCheckpointEngine(str(tmp_path)) as eng:
            eng.save(_state(1), 1)
        assert obs.counter("ckpt_bytes_total").value > before_bytes
        assert obs.counter("ckpt_commits_total").value == before_commits + 1
        assert obs.counter("ckpt_snapshot_seconds_total").value >= 0
        assert obs.gauge("ckpt_pending").value == 0  # drained by close()


class TestRunStepsHook:
    def test_save_every_n_cadence_and_drain_on_exit(self, tmp_path):
        d = str(tmp_path)

        def step_fn(state, batch):
            new = {"step": state["step"] + 1, "w": state["w"] + batch}
            return new, {"loss": float(new["w"][0])}

        eng = ckpt.AsyncCheckpointEngine(d, save_every_n=2)
        state, metrics = run_steps(
            step_fn, _state(0), [np.float32(1.0)] * 5, engine=eng
        )
        # cadence queued saves at steps 2 and 4; drain-on-exit guarantees the
        # NEWEST one is published (step 2's may be superseded if the toy loop
        # outruns the writer — that is the newest-wins contract, not a loss)
        assert eng.saves_accepted == 2
        assert "ckpt_4" in os.listdir(d)
        assert set(os.listdir(d)) <= {"ckpt_2", "ckpt_4"}
        assert metrics["loss"] == 5.0
        restored, path = checkpoint.restore_latest(d)
        assert os.path.basename(path) == "ckpt_4"
        np.testing.assert_array_equal(restored["w"], np.full(16, 4.0, np.float32))
        eng.close()

    def test_explicit_cadence_overrides_engine(self, tmp_path):
        d = str(tmp_path)

        def step_fn(state, batch):
            return {"step": state["step"] + 1, "w": state["w"]}, {}

        with ckpt.AsyncCheckpointEngine(d, save_every_n=1) as eng:
            run_steps(step_fn, _state(0), [None] * 4, engine=eng, save_every_n=4)
        assert sorted(os.listdir(d)) == ["ckpt_4"]

    def test_hooks_see_global_step(self, tmp_path):
        seen = []

        def step_fn(state, batch):
            return {"step": state["step"] + 1, "w": state["w"]}, {"loss": 0.0}

        run_steps(
            step_fn, _state(10), [None] * 3,
            hooks=[lambda s, step, m: seen.append(step)],
        )
        assert seen == [11, 12, 13]

    def test_drain_on_error_exit(self, tmp_path):
        d = str(tmp_path)

        def step_fn(state, batch):
            if batch == "boom":
                raise ValueError("boom")
            return {"step": state["step"] + 1, "w": state["w"]}, {}

        with ckpt.AsyncCheckpointEngine(d, save_every_n=1) as eng:
            with pytest.raises(ValueError):
                run_steps(step_fn, _state(0), [None, "boom"], engine=eng)
        # the step-1 save landed even though the loop died on step 2
        assert sorted(os.listdir(d)) == ["ckpt_1"]


class TestSnapshotBuffers:
    def test_snapshot_owns_its_memory(self):
        src = {"w": np.arange(8, dtype=np.float32)}
        snap = snapshot_to_host(src, step=1)
        src["w"][:] = -1.0  # donation-equivalent: source reused immediately
        np.testing.assert_array_equal(
            snap.tree["w"], np.arange(8, dtype=np.float32)
        )

    def test_slot_reuse_after_release(self):
        pool = SnapshotBuffers(depth=2)
        a = pool.take(_state(1))
        buf_a = a.tree["w"]
        pool.release(a)
        b = pool.take(_state(2))
        assert b.tree["w"] is buf_a  # pooled buffer reused, no realloc
        np.testing.assert_array_equal(b.tree["w"], np.full(16, 2.0, np.float32))

    def test_overflow_beyond_depth_is_unpooled(self):
        pool = SnapshotBuffers(depth=2)
        held = [pool.take(_state(i)) for i in range(3)]
        assert held[0].slot is not None and held[1].slot is not None
        assert held[2].slot is None  # overflow: fresh unpooled buffers
        for snap in held:
            pool.release(snap)

    def test_shape_change_evicts_stale_slots(self):
        pool = SnapshotBuffers(depth=1)
        a = pool.take({"w": np.zeros(4, np.float32)})
        pool.release(a)
        b = pool.take({"w": np.zeros(8, np.float32)})  # new signature
        assert b.slot is not None  # stale slot evicted, pooled slot granted
        assert b.tree["w"].shape == (8,)
        pool.release(b)


class TestPruneInFlightGuard:
    def test_explicit_in_flight_survives_prune(self, tmp_path):
        d = str(tmp_path)
        for step in (1, 2, 3):
            checkpoint.save_checkpoint(os.path.join(d, "ckpt_{}".format(step)),
                                       {"step": step, "w": [float(step)] * 4})
        removed = checkpoint.prune_checkpoints(
            d, keep=1, in_flight={os.path.join(d, "ckpt_1")}
        )
        assert removed == 1  # only ckpt_2: ckpt_1 is mid-commit, ckpt_3 kept
        assert sorted(os.listdir(d)) == ["ckpt_1", "ckpt_3"]

    def test_tmp_staging_dirs_invisible_everywhere(self, tmp_path):
        d = str(tmp_path)
        checkpoint.save_checkpoint(os.path.join(d, "ckpt_2"),
                                   {"step": 2, "w": [2.0] * 4})
        os.makedirs(os.path.join(d, "tmp.ckpt_5"))  # torn commit leftover
        assert checkpoint.latest_checkpoint(d).endswith("ckpt_2")
        # even the any-layout escape hatch must not resurrect staging dirs
        assert checkpoint.latest_checkpoint(d, prefix="").endswith("ckpt_2")
        assert checkpoint.prune_checkpoints(d, keep=1) == 0
        assert os.path.isdir(os.path.join(d, "tmp.ckpt_5"))

    def test_engine_registry_feeds_default_guard(self, tmp_path):
        eng = ckpt.AsyncCheckpointEngine(str(tmp_path))
        try:
            assert eng.busy_paths() == set()
            assert ckpt.in_flight_paths() == set()
            eng.save(_state(1), 1)
            eng.drain(timeout=60)
            assert ckpt.in_flight_paths() == set()
        finally:
            eng.close()
