"""pyspark-surface compatibility branches, exercised with duck-typed fakes.

pyspark is not installable in this environment (VERDICT round-1 item 6), so
the pyspark-shaped code paths — JVM Hadoop conf lookup, ``rdd.context``,
barrier-mode RDDs — are pinned by objects exposing exactly the attribute
surface pyspark exposes. ``run_tests.sh`` runs the suite against real Spark
when pyspark IS available (reference test/run_tests.sh:16-19).
"""

import os

from tensorflowonspark_tpu import TFCluster, TFParallel


class _FakeHadoopConf:
    def get(self, key):
        assert key == "fs.defaultFS"
        return "hdfs://namenode:8020"


class _FakeJsc:
    def hadoopConfiguration(self):
        return _FakeHadoopConf()


class _FakePysparkContext:
    """What TFCluster sees of a real pyspark SparkContext: no defaultFS
    attribute, a _jsc JVM handle (reference TFCluster.py:271-274)."""

    _jsc = _FakeJsc()


def test_default_fs_from_jvm_hadoop_conf():
    assert TFCluster.resolve_default_fs(_FakePysparkContext()) == "hdfs://namenode:8020"


def test_default_fs_fallback_without_jvm():
    class _Bare:
        pass

    assert TFCluster.resolve_default_fs(_Bare()) == "file://"


def test_default_fs_local_backend_wins():
    class _Local:
        defaultFS = "file://"
        _jsc = _FakeJsc()  # must NOT be consulted

    assert TFCluster.resolve_default_fs(_Local()) == "file://"


class _FakeBarrierRDD:
    """pyspark RDD surface used by TFParallel.run: barrier() + mapPartitions
    + collect (reference TFParallel.py:63-64 nodeRDD.barrier().mapPartitions).
    Executes partitions inline, like a 1-task local Spark job."""

    def __init__(self, partitions):
        self._partitions = partitions
        self.barrier_called = False

    def barrier(self):
        self.barrier_called = True
        return self

    def mapPartitions(self, fn):
        self._fn = fn
        return self

    def collect(self):
        out = []
        for part in self._partitions:
            out.extend(self._fn(iter(part)))
        return out


class _FakeBarrierSC:
    """SparkContext surface TFParallel.run touches (no PIN_SUPPORTED attr on
    real pyspark, parallelize(range, n))."""

    def __init__(self):
        self.rdd = None

    def parallelize(self, data, num_slices):
        data = list(data)
        per = max(1, len(data) // num_slices)
        parts = [data[i : i + per] for i in range(0, len(data), per)]
        self.rdd = _FakeBarrierRDD(parts)
        return self.rdd


def _record_instance(args, ctx):
    with open(os.path.join(args["out_dir"], "instance-{}.txt".format(ctx.executor_id)), "w") as f:
        f.write("{} of {}".format(ctx.executor_id, ctx.num_workers))


def test_tfparallel_uses_barrier_rdd(tmp_path):
    """TFParallel over a pyspark-shaped barrier RDD runs every instance."""
    sc = _FakeBarrierSC()
    done = TFParallel.run(
        sc, _record_instance, {"out_dir": str(tmp_path)}, 2,
        env={"JAX_PLATFORMS": "cpu"},
    )
    assert sc.rdd.barrier_called, "barrier execution mode was not requested"
    assert sorted(done) == [0, 1]
    assert sorted(os.listdir(str(tmp_path))) == ["instance-0.txt", "instance-1.txt"]
