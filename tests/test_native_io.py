"""Native C++ TFRecord IO (native/tfrecord_io.cc via ctypes) vs the pure
Python codec — byte-for-byte interchange and corruption detection.

The reference's native IO layer was borrowed (tensorflow-hadoop jar +
TensorFlow's C++ record_reader); ours is in-repo, so it gets the test the
reference never had.
"""

import os

import pytest

from tensorflowonspark_tpu import native_io, tfrecord

pytestmark = pytest.mark.skipif(
    not native_io.available(), reason="native toolchain unavailable"
)


def test_masked_crc_matches_python():
    for data in [b"", b"x", b"hello world", os.urandom(7), os.urandom(8), os.urandom(1000)]:
        assert native_io.masked_crc32c(data) == tfrecord._masked_crc(data)


def test_native_write_python_read(tmp_path):
    recs = [os.urandom(i * 13 + 1) for i in range(40)] + [b""]
    path = str(tmp_path / "native.tfrecord")
    assert native_io.write_records(path, recs) == len(recs)
    assert list(tfrecord.read_records(path)) == recs


def test_python_write_native_read(tmp_path):
    recs = [os.urandom(i * 13 + 1) for i in range(40)]
    path = str(tmp_path / "python.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
        for r in recs:
            w.write(r)
    assert native_io.read_records(path) == recs


def test_corruption_detected(tmp_path):
    path = str(tmp_path / "good.tfrecord")
    native_io.write_records(path, [b"payload-one", b"payload-two"])
    blob = bytearray(open(path, "rb").read())
    blob[14] ^= 0xFF  # flip a payload byte of record 0
    bad = str(tmp_path / "bad.tfrecord")
    open(bad, "wb").write(bytes(blob))
    with pytest.raises(IOError):
        native_io.read_records(bad)
    # verify_crc=False skips the check and returns the (corrupt) payloads
    assert len(native_io.read_records(bad, verify_crc=False)) == 2


def test_empty_file(tmp_path):
    path = str(tmp_path / "empty.tfrecord")
    open(path, "wb").close()
    assert native_io.read_records(path) == []


def test_tf_interop(tmp_path):
    """The native framing must be readable by TensorFlow itself."""
    tf = pytest.importorskip("tensorflow")
    recs = [b"alpha", b"beta", os.urandom(100)]
    path = str(tmp_path / "interop.tfrecord")
    native_io.write_records(path, recs)
    got = [bytes(x.numpy()) for x in tf.data.TFRecordDataset(path)]
    assert got == recs
    # and the other direction
    path2 = str(tmp_path / "tfwrote.tfrecord")
    with tf.io.TFRecordWriter(path2) as w:
        for r in recs:
            w.write(r)
    assert native_io.read_records(path2) == recs


def test_huge_length_field_rejected(tmp_path):
    """A corrupt 8-byte length near UINT64_MAX must produce a clean error,
    not an out-of-bounds read (the `pos + len` sum would wrap)."""
    import struct

    path = str(tmp_path / "huge.tfrecord")
    payload = b"x" * 10
    header = struct.pack("<Q", 0xFFFFFFFFFFFFFFF0)
    open(path, "wb").write(header + b"\x00" * 4 + payload + b"\x00" * 4)
    with pytest.raises(IOError):
        native_io.read_records(path, verify_crc=True)
    with pytest.raises(IOError):
        native_io.read_records(path, verify_crc=False)
