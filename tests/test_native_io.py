"""Native C++ TFRecord IO (native/tfrecord_io.cc via ctypes) vs the pure
Python codec — byte-for-byte interchange and corruption detection.

The reference's native IO layer was borrowed (tensorflow-hadoop jar +
TensorFlow's C++ record_reader); ours is in-repo, so it gets the test the
reference never had.
"""

import os

import pytest

from tensorflowonspark_tpu import native_io, tfrecord

pytestmark = pytest.mark.skipif(
    not native_io.available(), reason="native toolchain unavailable"
)


def test_masked_crc_matches_python():
    for data in [b"", b"x", b"hello world", os.urandom(7), os.urandom(8), os.urandom(1000)]:
        assert native_io.masked_crc32c(data) == tfrecord._masked_crc(data)


def test_native_write_python_read(tmp_path):
    recs = [os.urandom(i * 13 + 1) for i in range(40)] + [b""]
    path = str(tmp_path / "native.tfrecord")
    assert native_io.write_records(path, recs) == len(recs)
    assert list(tfrecord.read_records(path)) == recs


def test_python_write_native_read(tmp_path):
    recs = [os.urandom(i * 13 + 1) for i in range(40)]
    path = str(tmp_path / "python.tfrecord")
    with tfrecord.TFRecordWriter(path) as w:
        for r in recs:
            w.write(r)
    assert native_io.read_records(path) == recs


def test_corruption_detected(tmp_path):
    path = str(tmp_path / "good.tfrecord")
    native_io.write_records(path, [b"payload-one", b"payload-two"])
    blob = bytearray(open(path, "rb").read())
    blob[14] ^= 0xFF  # flip a payload byte of record 0
    bad = str(tmp_path / "bad.tfrecord")
    open(bad, "wb").write(bytes(blob))
    with pytest.raises(IOError):
        native_io.read_records(bad)
    # verify_crc=False skips the check and returns the (corrupt) payloads
    assert len(native_io.read_records(bad, verify_crc=False)) == 2


def test_empty_file(tmp_path):
    path = str(tmp_path / "empty.tfrecord")
    open(path, "wb").close()
    assert native_io.read_records(path) == []


def test_tf_interop(tmp_path):
    """The native framing must be readable by TensorFlow itself."""
    tf = pytest.importorskip("tensorflow")
    recs = [b"alpha", b"beta", os.urandom(100)]
    path = str(tmp_path / "interop.tfrecord")
    native_io.write_records(path, recs)
    got = [bytes(x.numpy()) for x in tf.data.TFRecordDataset(path)]
    assert got == recs
    # and the other direction
    path2 = str(tmp_path / "tfwrote.tfrecord")
    with tf.io.TFRecordWriter(path2) as w:
        for r in recs:
            w.write(r)
    assert native_io.read_records(path2) == recs


def test_huge_length_field_rejected(tmp_path):
    """A corrupt 8-byte length near UINT64_MAX must produce a clean error,
    not an out-of-bounds read (the `pos + len` sum would wrap)."""
    import struct

    path = str(tmp_path / "huge.tfrecord")
    payload = b"x" * 10
    header = struct.pack("<Q", 0xFFFFFFFFFFFFFFF0)
    open(path, "wb").write(header + b"\x00" * 4 + payload + b"\x00" * 4)
    with pytest.raises(IOError):
        native_io.read_records(path, verify_crc=True)
    with pytest.raises(IOError):
        native_io.read_records(path, verify_crc=False)


# --------------------------------------------------------------------------
# Native JPEG decode (jpg_* entry points): Pillow is the bit-exactness
# oracle — every geometry the imagenet pipeline uses must produce the exact
# bytes PIL produces, or the byte-identical-stream contract across decode
# modes is broken.

_JPG = pytest.mark.skipif(
    not native_io.jpg_available(), reason="native JPEG decode unavailable"
)


def _checker(w, h, mode="RGB", seed=0):
    """A deterministic test image with enough structure to catch upsampling
    and resampling off-by-ones (gradients + hard edges)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    yy, xx = np.mgrid[0:h, 0:w]
    r = (xx * 255 // max(w - 1, 1)).astype(np.uint8)
    g = (yy * 255 // max(h - 1, 1)).astype(np.uint8)
    b = ((xx // 4 + yy // 4) % 2 * 255).astype(np.uint8)
    arr = np.stack([r, g, b], axis=-1)
    arr ^= rng.integers(0, 32, arr.shape, dtype=np.uint8)
    if mode == "L":
        return arr[..., 0]
    return arr


def _encode_jpg(arr, quality=90, subsampling=-1):
    import io

    from PIL import Image

    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, "JPEG", quality=quality, subsampling=subsampling)
    return buf.getvalue()


def _pil_window(data, box, resize, origin=(0, 0), size=None, flip=False):
    """The PIL oracle for jpg_decode_window's decode→resize→window→flip."""
    import io

    import numpy as np
    from PIL import Image

    img = Image.open(io.BytesIO(data))
    if img.mode != "RGB":
        img = img.convert("RGB")
    r = img.resize(resize, Image.BILINEAR, box=box)
    if size is None:
        size = (resize[1], resize[0])
    ox, oy = origin
    arr = np.asarray(r.crop((ox, oy, ox + size[1], oy + size[0])))
    if flip:
        arr = arr[:, ::-1]
    return arr


@_JPG
def test_jpg_info_matches_pil():
    import io

    from PIL import Image

    for w, h, mode in [(64, 48, "RGB"), (17, 11, "RGB"), (2, 2, "RGB"),
                       (33, 40, "L"), (1, 7, "RGB")]:
        data = _encode_jpg(_checker(w, h, mode))
        assert native_io.jpg_info(data) == Image.open(io.BytesIO(data)).size == (w, h)


@_JPG
def test_jpg_decode_matrix_matches_pil_exactly():
    """Raster decode across codings PIL emits: quality × subsampling ×
    geometry (odd dims, tiny images where libjpeg switches from fancy
    upsampling to replication, grayscale). Identity resize compares the
    raw decode; a torn tolerance here means the two IDCT/upsample paths
    diverged."""
    import numpy as np

    cases = [(64, 48, "RGB"), (17, 11, "RGB"), (5, 3, "RGB"), (2, 2, "RGB"),
             (1, 1, "RGB"), (24, 24, "L"), (7, 16, "L")]
    for quality in (50, 90, 100):
        for subsampling in (0, 1, 2):
            for w, h, mode in cases:
                data = _encode_jpg(_checker(w, h, mode), quality, subsampling)
                out = np.empty((h, w, 3), np.uint8)
                native_io.jpg_decode_window(data, out, (0, 0, w, h), (w, h))
                ref = _pil_window(data, (0, 0, w, h), (w, h))
                assert np.array_equal(out, ref), (
                    "decode mismatch at q={} ss={} {}x{} {}".format(
                        quality, subsampling, w, h, mode))


@_JPG
def test_jpg_decode_window_geometry_matches_pil():
    """The three geometries the imagenet pipeline drives: train fractional
    crop-box + resize + flip, eval full-frame resize + centered window, and
    an off-origin window of an upscale."""
    import numpy as np

    data = _encode_jpg(_checker(61, 43))
    for box, resize, origin, size, flip in [
        ((3.25, 2.5, 50.75, 40.0), (32, 32), (0, 0), None, True),
        ((3.25, 2.5, 50.75, 40.0), (32, 32), (0, 0), None, False),
        ((0, 0, 61, 43), (91, 64), (33, 10), (48, 48), False),
        ((0, 0, 61, 43), (122, 86), (5, 7), (40, 60), True),
    ]:
        if size is None:
            size = (resize[1], resize[0])
        out = np.empty(size + (3,), np.uint8)
        native_io.jpg_decode_window(data, out, box, resize, origin, flip)
        ref = _pil_window(data, box, resize, origin, size, flip)
        assert np.array_equal(out, ref)


@_JPG
def test_jpg_decode_into_strided_slab_rows():
    """A slab slot is a view with padded row stride; the decoder writes
    through strides[0] and must not touch the padding."""
    import numpy as np

    data = _encode_jpg(_checker(30, 20))
    backing = np.full((16, 16 * 3 + 13), 0xAB, np.uint8)
    out = backing[:, :16 * 3].reshape(16, 16, 3)[:12, :10]
    assert out.strides[1] == 3 and out.strides[2] == 1
    native_io.jpg_decode_window(data, out, (0, 0, 30, 20), (14, 16), (2, 3))
    ref = _pil_window(data, (0, 0, 30, 20), (14, 16), (2, 3), (12, 10))
    assert np.array_equal(out, ref)
    assert (backing[:, 16 * 3:] == 0xAB).all()  # padding untouched


@_JPG
def test_jpg_parse_into_matches_pil_parse():
    """End-to-end rng protocol: make_parse_fn's native ``into`` must land
    byte-identical pixels to the PIL ``parse`` for the same record — train
    (crop-box draws then flip draw) and eval (aspect resize + center crop)."""
    import numpy as np

    from tensorflowonspark_tpu.data import imagenet

    for is_training in (True, False):
        parse = imagenet.make_parse_fn(
            is_training, image_size=32, seed=7, raw_uint8=True)
        for i in range(6):
            rec = imagenet.encode_example(_checker(57 + 3 * i, 49 + 2 * i, seed=i), i)
            ref_img, ref_lbl = parse(rec)
            out = np.empty((32, 32, 3), np.uint8)
            lbl, used_native = parse.into(rec, out)
            assert used_native, "native path unexpectedly fell back"
            assert lbl == ref_lbl
            assert np.array_equal(out, ref_img)


@_JPG
def test_jpg_corrupt_and_truncated_raise_jpegerror():
    import numpy as np

    data = _encode_jpg(_checker(32, 24))
    out = np.empty((24, 32, 3), np.uint8)
    for bad in [b"", b"\xff\xd8", data[: len(data) // 2], b"not a jpeg at all",
                data[:2] + b"\x00" * 64]:
        with pytest.raises((native_io.JpegError, ValueError)):
            native_io.jpg_info(bad)
        with pytest.raises((native_io.JpegError, ValueError)):
            native_io.jpg_decode_window(bad, out, (0, 0, 32, 24), (32, 24))


@_JPG
def test_jpg_header_fuzz_never_crashes():
    """The sanitizer-leg workload: truncations at every prefix, trailing
    garbage, and lying segment-length fields must either decode cleanly or
    raise JpegError — never read out of bounds (ASan would abort)."""
    import numpy as np

    data = _encode_jpg(_checker(40, 30), quality=75)
    out = np.empty((30, 40, 3), np.uint8)

    def attempt(blob):
        try:
            native_io.jpg_info(blob)
            native_io.jpg_decode_window(blob, out, (0, 0, 40, 30), (40, 30))
        except native_io.JpegError:
            pass

    for cut in range(0, len(data), 3):      # truncated streams
        attempt(data[:cut])
    attempt(data + b"\xde\xad" * 32)        # overlong: trailing garbage
    mutated = 0
    for i in range(len(data) - 4):          # lying segment lengths
        if data[i] == 0xFF and data[i + 1] not in (0x00, 0xD8, 0xD9):
            for fake in (b"\x00\x00", b"\x00\x01", b"\xff\xff"):
                attempt(data[: i + 2] + fake + data[i + 4:])
            mutated += 1
    assert mutated > 0


def test_build_info_reports_jpeg_variant():
    """tfr_build_info() pins which backend the Makefile probe selected; the
    string is surfaced in BENCH JSON so perf numbers carry their decoder."""
    import re

    info = native_io.build_info()
    if not native_io.load_library().tfr_has_jpeg:
        assert info is None
        return
    assert re.fullmatch(r"tfrecord_io jpeg=(libjpeg-turbo api=\d+|scalar)", info)


def test_decode_env_var_vetoes_native_path(monkeypatch):
    monkeypatch.setenv(native_io.DECODE_ENV_VAR, "0")
    assert not native_io.jpg_available()
    monkeypatch.delenv(native_io.DECODE_ENV_VAR)
    assert native_io.jpg_available() == bool(native_io.load_library().tfr_has_jpeg)


def test_stale_library_without_jpeg_falls_back(tmp_path):
    """A prebuilt .so that predates the jpg_* entry points (-DTFR_OMIT_JPEG)
    must keep serving record IO while image decode falls back to PIL with
    identical pixels — the stale-.so half of the fallback contract."""
    import shutil
    import subprocess
    import sys
    import textwrap

    if shutil.which("g++") is None:
        pytest.skip("no compiler to build the stale variant")
    src = os.path.join(os.path.dirname(__file__), "..", "native", "tfrecord_io.cc")
    stale = str(tmp_path / "libtfrecord_io_stale.so")
    subprocess.run(
        ["g++", "-O1", "-fPIC", "-shared", "-std=c++17", "-DTFR_OMIT_JPEG",
         "-o", stale, src],
        check=True, capture_output=True, timeout=120)
    prog = textwrap.dedent("""
        import numpy as np
        from tensorflowonspark_tpu import native_io
        from tensorflowonspark_tpu.data import imagenet
        assert native_io.available()
        assert not native_io.load_library().tfr_has_jpeg
        assert not native_io.jpg_available()
        assert native_io.build_info() is None
        parse = imagenet.make_parse_fn(True, image_size=16, seed=3, raw_uint8=True)
        rec = imagenet.encode_example(
            np.arange(31 * 27 * 3, dtype=np.uint8).reshape(27, 31, 3), 5)
        ref_img, ref_lbl = parse(rec)
        out = np.empty((16, 16, 3), np.uint8)
        lbl, used_native = parse.into(rec, out)
        assert not used_native and lbl == ref_lbl
        assert np.array_equal(out, ref_img)
        import tempfile, os as _os
        shard = _os.path.join(tempfile.mkdtemp(), "s.tfrecord")
        native_io.write_records(shard, [rec])
        assert native_io.read_records(shard) == [rec]
        print("STALE-OK")
    """)
    env = dict(os.environ, TOS_NATIVE_LIB=stale)
    env.pop("TOS_NATIVE_DECODE", None)
    r = subprocess.run(
        [sys.executable, "-c", prog], env=env, capture_output=True,
        text=True, timeout=120, cwd=os.path.join(os.path.dirname(__file__), ".."))
    assert r.returncode == 0, r.stderr
    assert "STALE-OK" in r.stdout
