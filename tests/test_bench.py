"""Unit tests for bench.py's result-annotation helpers (the heavy benchmark
paths themselves run under BENCH_* env switches, not pytest)."""

import importlib.util
import os

_BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")
_spec = importlib.util.spec_from_file_location("bench", _BENCH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def test_confidence_fields_full_budget():
    # all requested pairs recorded and valid: no low-confidence flag
    assert bench.confidence_fields(6, 6) == {"pairs": 6, "pairs_requested": 6}
    assert bench.confidence_fields(7, 6) == {"pairs": 7, "pairs_requested": 6}


def test_confidence_fields_budget_exhausted():
    out = bench.confidence_fields(3, 6)
    assert out == {"pairs": 3, "pairs_requested": 6, "low_confidence": True}


def test_confidence_fields_zero_pairs():
    out = bench.confidence_fields(0, 6)
    assert out["pairs"] == 0 and out["low_confidence"] is True


def test_confidence_fields_invalid_pairs_lower_confidence():
    # 6 pairs ran but one was discarded: the median rests on 5 samples
    out = bench.confidence_fields(6, 6, invalid_pairs=1)
    assert out["pairs"] == 6
    assert out["invalid_pairs"] == 1
    assert out["low_confidence"] is True


def test_partition_pairs_flags_impossible_ratios():
    # train cannot beat its own input path: the 3.30 pair is noise
    nc = [100.0, 100.0, 100.0]
    tr = [95.0, 330.0, 102.0]
    valid, invalid = bench.partition_pairs(nc, tr)
    assert valid == [(100.0, 95.0), (100.0, 102.0)]
    assert invalid == [(100.0, 330.0)]


def test_partition_pairs_boundary_is_inclusive():
    valid, invalid = bench.partition_pairs([100.0], [110.0])
    assert valid and not invalid  # ratio == 1.10 exactly: still valid
    valid, invalid = bench.partition_pairs([100.0], [111.0])
    assert invalid and not valid


def test_partition_pairs_all_valid():
    valid, invalid = bench.partition_pairs([100.0, 90.0], [99.0, 91.0])
    assert len(valid) == 2 and not invalid
