"""Unit tests for bench.py's result-annotation helpers (the heavy benchmark
paths themselves run under BENCH_* env switches, not pytest)."""

import importlib.util
import os

_BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")
_spec = importlib.util.spec_from_file_location("bench", _BENCH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def test_confidence_fields_full_budget():
    # all requested pairs recorded: no low-confidence flag in the JSON
    assert bench.confidence_fields(6, 6) == {"pairs": 6}
    assert bench.confidence_fields(7, 6) == {"pairs": 7}


def test_confidence_fields_budget_exhausted():
    out = bench.confidence_fields(3, 6)
    assert out == {"pairs": 3, "low_confidence": True}


def test_confidence_fields_zero_pairs():
    out = bench.confidence_fields(0, 6)
    assert out["pairs"] == 0 and out["low_confidence"] is True
