"""Unit tests for bench.py's result-annotation helpers (the heavy benchmark
paths themselves run under BENCH_* env switches, not pytest)."""

import importlib.util
import os

import pytest

_BENCH = os.path.join(os.path.dirname(__file__), "..", "bench.py")
_spec = importlib.util.spec_from_file_location("bench", _BENCH)
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def test_confidence_fields_full_budget():
    # all requested pairs recorded and valid: no low-confidence flag
    assert bench.confidence_fields(6, 6) == {
        "pairs": 6, "pairs_requested": 6, "pairs_completed": 6,
    }
    assert bench.confidence_fields(7, 6) == {
        "pairs": 7, "pairs_requested": 6, "pairs_completed": 7,
    }


def test_confidence_fields_short_run_flags_low_confidence():
    out = bench.confidence_fields(3, 6)
    assert out == {
        "pairs": 3, "pairs_requested": 6, "pairs_completed": 3,
        "low_confidence": True,
    }


def test_confidence_fields_budget_exhausted_is_reported():
    # the budget (not the rep count) ended the run: say so explicitly, on
    # top of the sample-count accounting
    out = bench.confidence_fields(3, 6, budget_exhausted=True)
    assert out == {
        "pairs": 3, "pairs_requested": 6, "pairs_completed": 3,
        "budget_exhausted": True, "low_confidence": True,
    }
    # a full run never carries the flag
    assert "budget_exhausted" not in bench.confidence_fields(6, 6)


def test_confidence_fields_zero_pairs():
    out = bench.confidence_fields(0, 6)
    assert out["pairs"] == 0 and out["low_confidence"] is True


def test_confidence_fields_invalid_pairs_lower_confidence():
    # 6 pairs ran but one was discarded: the median rests on 5 samples
    out = bench.confidence_fields(6, 6, invalid_pairs=1)
    assert out["pairs"] == 6
    assert out["invalid_pairs"] == 1
    assert out["pairs_completed"] == 5
    assert out["low_confidence"] is True


def test_partition_pairs_flags_impossible_ratios():
    # train cannot beat its own input path: the 3.30 pair is noise
    nc = [100.0, 100.0, 100.0]
    tr = [95.0, 330.0, 102.0]
    valid, invalid = bench.partition_pairs(nc, tr)
    assert valid == [(100.0, 95.0), (100.0, 102.0)]
    assert invalid == [(100.0, 330.0)]


def test_partition_pairs_boundary_is_inclusive():
    valid, invalid = bench.partition_pairs([100.0], [110.0])
    assert valid and not invalid  # ratio == 1.10 exactly: still valid
    valid, invalid = bench.partition_pairs([100.0], [111.0])
    assert invalid and not valid


def test_partition_pairs_all_valid():
    valid, invalid = bench.partition_pairs([100.0, 90.0], [99.0, 91.0])
    assert len(valid) == 2 and not invalid


def test_partition_pairs_band_is_symmetric():
    # a train block 12% SLOWER than its paired input-path block is just as
    # impossible under the pairing model as 12% faster (the r05 0.881 pair:
    # a relay mood swing landed between the two half-blocks) — both sides
    # of the band discard
    valid, invalid = bench.partition_pairs([100.0, 100.0], [88.1, 95.0])
    assert valid == [(100.0, 95.0)]
    assert invalid == [(100.0, 88.1)]


def test_partition_pairs_low_boundary_is_inclusive():
    # ratio == 1/1.10 exactly: still valid, mirroring the high boundary
    valid, invalid = bench.partition_pairs([110.0], [100.0])
    assert valid and not invalid
    valid, invalid = bench.partition_pairs([113.0], [100.0])
    assert invalid and not valid


def test_seed_autotuner_solves_the_two_probe_system():
    """fixed=(K*t_pb - t_win)/(K-1), bw from the residual stream time: a
    synthetic link with known parameters must round-trip through the probe
    rates exactly."""
    from tensorflowonspark_tpu.data import FeedAutotuner

    fixed, bw = 0.25, 20e6
    # the real bench batch: 64 uint8 images at 224x224x3 (~9.6 MB)
    batch_imgs, win = 64, 8
    batch_bytes = 64 * 224 * 224 * 3
    t_pb = fixed + batch_bytes / bw            # seconds per per-batch transfer
    t_win = fixed + win * batch_bytes / bw     # seconds per packed window
    per_batch_rate = batch_imgs / t_pb
    packed_rate = win * batch_imgs / t_win

    tuner = FeedAutotuner()
    assert bench.seed_autotuner(
        tuner, per_batch_rate, packed_rate, win, batch_imgs, batch_bytes
    )
    assert tuner.estimator.ready
    assert tuner.estimator.fixed_s == pytest.approx(fixed, rel=1e-6)
    assert tuner.estimator.bytes_per_sec == pytest.approx(bw, rel=1e-6)
    # at these parameters the controller recommends the hand-tuned K=8
    assert tuner.recommend(batch_bytes) == 8


def test_seed_autotuner_refuses_unusable_probes():
    from tensorflowonspark_tpu.data import FeedAutotuner

    tuner = FeedAutotuner()
    assert not bench.seed_autotuner(tuner, 0.0, 100.0, 8, 64, 1 << 20)
    assert not bench.seed_autotuner(tuner, 100.0, 100.0, 1, 64, 1 << 20)
    assert not tuner.estimator.ready


def test_feed_fields_reports_link_estimate_and_stalls():
    from tensorflowonspark_tpu.data import FeedAutotuner

    tuner = FeedAutotuner()
    out = bench.feed_fields(tuner, window_k=1, batch_bytes=1 << 20)
    assert out["window_k"] == 1
    assert "autotuned_k" not in out  # estimator unseeded: no link estimate
    assert set(out["stalls"]) == {
        "producer_read_seconds", "producer_parse_seconds",
        "producer_emit_seconds", "consumer_wait_seconds",
        "classification", "store",
    }
    assert out["stalls"]["classification"] in {
        "device_bound", "decode_bound", "io_bound",
    }
    # store provenance rides in the stalls block: backend fingerprint plus
    # the per-tier hit/miss/promotion counters
    store = out["stalls"]["store"]
    assert isinstance(store["backend"], str) and store["backend"]
    for k in ("remote_reads", "prefetch_hits", "tier_ram_hits",
              "tier_disk_hits", "tier_promotions"):
        assert isinstance(store[k], int)

    tuner.note_fixed_probe(0.25)
    tuner.note_transfer(1 << 20, 0.25 + (1 << 20) / 20e6)
    out = bench.feed_fields(tuner, window_k=8, batch_bytes=1 << 20)
    assert out["window_k"] == 8
    assert out["autotuned_k"] in tuner.buckets
    assert out["link_fixed_cost_seconds"] == pytest.approx(0.25, abs=1e-3)
    assert out["link_bytes_per_sec"] == pytest.approx(20e6, rel=1e-2)


def test_classify_stalls_covers_all_three_bottlenecks():
    # producer blocked on the full queue >= consumer starvation: device gates
    assert bench.classify_stalls(1.0, 1.0, 5.0, 2.0) == "device_bound"
    # input path gates, parse dominates shard IO: the decode stage
    assert bench.classify_stalls(1.0, 3.0, 0.0, 2.0) == "decode_bound"
    # input path gates, shard IO dominates parse
    assert bench.classify_stalls(3.0, 1.0, 0.0, 2.0) == "io_bound"


def test_least_implausible_pair_picks_log_symmetric_winner():
    # ratios 3.30, 0.5, 2.0 — |log| says 2.0 and 0.5 tie at log 2, 3.30
    # loses; min() resolves the tie to the first, but the outlier must
    # never win
    nc = [100.0, 100.0, 100.0]
    tr = [330.0, 50.0, 200.0]
    assert bench.least_implausible_pair(nc, tr) in {(100.0, 50.0), (100.0, 200.0)}

    # an actual near-1.0 ratio beats both halves of the band
    tr2 = [330.0, 50.0, 108.0]
    assert bench.least_implausible_pair(nc, tr2) == (100.0, 108.0)

    # symmetric: 0.9 and 1/0.9 are equally plausible, both beat 3.30
    assert bench.least_implausible_pair([100.0, 100.0], [90.0, 330.0]) == (100.0, 90.0)


def test_all_invalid_fallback_admits_one_pair_not_the_raw_set():
    # the r05 regression: every pair out of band used to readmit the whole
    # raw set, letting a 3.30 outlier into the headline median — the
    # fallback must now surface exactly one least-implausible pair
    nc = [100.0, 100.0]
    tr = [330.0, 250.0]
    valid, invalid = bench.partition_pairs(nc, tr)
    assert valid == []
    assert len(invalid) == 2
    best = bench.least_implausible_pair(nc, tr)
    assert best == (100.0, 250.0)
