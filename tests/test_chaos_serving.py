"""Chaos: the serving path under injected latency, dropped connections and
transient overload. The client half of the load-shedding contract — a shared
RetryPolicy that re-dials dropped connections (prediction is stateless, so
replay is safe) and backs off on ``Overloaded`` — must absorb every
transient fault class end-to-end."""

import numpy as np
import pytest

from tensorflowonspark_tpu import chaos, obs, resilience
from tensorflowonspark_tpu.serving import (
    InferenceClient,
    InferenceServer,
    Overloaded,
    _Predictor,
)
from tensorflowonspark_tpu.train import export

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


@pytest.fixture
def server(tmp_path):
    w = np.array([[2.0], [3.0]], np.float32)
    b = np.array([1.0], np.float32)

    def predict_builder():
        def predict(params, model_state, arrays):
            return {"y_": arrays["x"] @ params["w"] + params["b"]}

        return predict

    path = str(tmp_path / "bundle")
    export.export_model(path, predict_builder, {"w": w, "b": b})
    srv = InferenceServer(path)
    srv.start()
    yield srv
    srv.stop()


def _fast_client(server, attempts=3):
    return InferenceClient(
        server.address,
        timeout=30,
        retry=resilience.RetryPolicy(
            max_attempts=attempts,
            backoff=resilience.Backoff(base=0.02, factor=2.0, max_delay=0.1,
                                       jitter=0.5, seed=0),
            retry_on=(OSError, Overloaded),
            name="inference-client",
        ),
    )


def _counter(name):
    return obs.snapshot()["counters"].get(name, {}).get("value", 0)


class TestServingChaos:
    def test_injected_latency_only_slows_the_answer(self, server):
        plan = chaos.ChaosPlan(seed=0).site(
            "serving.latency", probability=1.0, max_count=2, delay_s=0.05
        )
        chaos.install(plan, propagate=False)
        client = _fast_client(server)
        try:
            out = client.predict(x=[[1.0, 2.0]])
            np.testing.assert_allclose(out["y_"], [[9.0]])
        finally:
            client.close()
        assert plan.fired("serving.latency") >= 1

    def test_client_redials_through_dropped_connections(self, server):
        plan = chaos.ChaosPlan(seed=1).site(
            "serving.conn_drop", probability=1.0, max_count=2
        )
        chaos.install(plan, propagate=False)
        client = _fast_client(server)
        try:
            # each drop closes the connection mid-request; the retry policy
            # re-dials and replays
            out = client.predict(x=[[1.0, 2.0]])
            np.testing.assert_allclose(out["y_"], [[9.0]])
            out = client.predict(x=[[0.0, 0.0]])
            np.testing.assert_allclose(out["y_"], [[1.0]])
        finally:
            client.close()
        assert plan.fired("serving.conn_drop") == 2
        assert _counter("chaos_fault_serving_conn_drop_total") >= 2

    def test_binary_lane_redials_through_dropped_connection(self, server):
        plan = chaos.ChaosPlan(seed=2).site(
            "serving.conn_drop", probability=1.0, max_count=1
        )
        chaos.install(plan, propagate=False)
        client = _fast_client(server)
        try:
            out = client.predict_binary(x=np.array([[1.0, 2.0]], np.float32))
            np.testing.assert_allclose(out["y_"], [[9.0]])
        finally:
            client.close()
        assert plan.fired("serving.conn_drop") == 1

    def test_client_backs_off_through_transient_overload(self, server):
        plan = chaos.ChaosPlan(seed=3).site(
            "serving.overload", probability=1.0, max_count=2
        )
        chaos.install(plan, propagate=False)
        shed_before = _counter("serving_shed_overloaded_total")
        client = _fast_client(server)
        try:
            # attempts 1 and 2 come back as Overloaded error replies; the
            # third lands
            out = client.predict(x=[[1.0, 2.0]])
            np.testing.assert_allclose(out["y_"], [[9.0]])
        finally:
            client.close()
        assert plan.fired("serving.overload") == 2
        assert _counter("serving_shed_overloaded_total") - shed_before == 2

    def test_fail_fast_client_surfaces_overload(self, server):
        chaos.install(
            chaos.ChaosPlan(seed=3).site("serving.overload", probability=1.0),
            propagate=False,
        )
        client = _fast_client(server, attempts=1)
        try:
            with pytest.raises(Overloaded):
                client.predict(x=[[1.0, 2.0]])
        finally:
            client.close()


class TestExactPendingBound:
    def test_pending_counter_returns_to_zero(self):
        pred = _Predictor(lambda p, ms, a: {"y": a["x"]}, None, None, max_pending=4)
        try:
            for _ in range(3):
                pred.submit({"x": np.ones((2, 2), np.float32)})
            assert pred._pending == 0  # every future resolved -> fully released
        finally:
            pred.stop()

    def test_single_slot_rejects_concurrent_second_request(self):
        import threading
        import time

        release = threading.Event()

        def slow_fn(params, model_state, arrays):
            release.wait(30)
            return {"y": arrays["x"]}

        # max_pending=1 is exact: the in-flight request fills the only slot
        pred = _Predictor(slow_fn, None, None, max_pending=1)
        try:
            results = []
            t = threading.Thread(
                target=lambda: results.append(pred.submit({"x": np.ones((1, 2), np.float32)}))
            )
            t.start()
            time.sleep(0.4)
            with pytest.raises(Overloaded):
                pred.submit({"x": np.ones((1, 2), np.float32)})
            release.set()
            t.join(timeout=30)
            assert len(results) == 1
            assert pred._pending == 0
        finally:
            release.set()
            pred.stop()
