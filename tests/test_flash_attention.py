"""Flash-attention kernel numerics vs plain attention (pallas interpret mode)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from tensorflowonspark_tpu.ops.flash_attention import flash_attention
from tensorflowonspark_tpu.parallel.ring_attention import plain_attention


def _qkv(b=2, h=2, l=256, d=64, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(
        jnp.asarray(rng.standard_normal((b, h, l, d)), jnp.float32) for _ in range(3)
    )


@pytest.mark.parametrize("causal", [False, True])
def test_forward_matches_plain(causal):
    q, k, v = _qkv()
    out = flash_attention(q, k, v, causal=causal, interpret=True)
    expected = plain_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


def test_multi_block_grid():
    # seq 256 with 64-blocks → 4x4 kv/q grid, exercises accumulator reuse
    q, k, v = _qkv(l=256, seed=1)
    out = flash_attention(q, k, v, causal=True, block_q=64, block_k=64, interpret=True)
    expected = plain_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_gradients_match_plain(causal):
    q, k, v = _qkv(l=128, seed=2)

    def loss_flash(q, k, v):
        return (flash_attention(q, k, v, causal=causal, block_q=64, block_k=64, interpret=True) ** 2).sum()

    def loss_plain(q, k, v):
        return (plain_attention(q, k, v, causal=causal) ** 2).sum()

    g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_plain = jax.grad(loss_plain, argnums=(0, 1, 2))(q, k, v)
    for gf, gp, name in zip(g_flash, g_plain, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gp), atol=5e-4,
            err_msg="d{} mismatch".format(name),
        )


def test_bfloat16_forward():
    q, k, v = _qkv(l=128, seed=3)
    q, k, v = (t.astype(jnp.bfloat16) for t in (q, k, v))
    out = flash_attention(q, k, v, causal=True, interpret=True)
    assert out.dtype == jnp.bfloat16
    expected = plain_attention(q, k, v, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(expected, np.float32), atol=0.05
    )
