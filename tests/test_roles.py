"""ps/evaluator runtime paths exercised end-to-end — VERDICT round-1 item 7.

A cluster with ``num_ps=1, eval_node=True``: the chief trains from the feed
and writes checkpoints, the evaluator continuously evaluates the latest
checkpoint (reference mnist/estimator/mnist_tf.py:109 eval_node usage), the
ps parks (API-compat role, no PS on TPU — SURVEY.md §2.6), and driver
shutdown releases both parked roles (reference ps control-queue wait loop,
TFSparkNode.py:373-390 + driver-side role stop TFCluster.py:188-194).
"""

import json
import os
import time

import pytest

from tensorflowonspark_tpu import TFCluster
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


def fn_role_dispatch(args, ctx):
    """main_fun for every role, dispatching like reference user programs."""
    out_dir = args["out_dir"]
    marker = os.path.join(out_dir, "{}-{}.started".format(ctx.job_name, ctx.task_index))
    with open(marker, "w") as f:
        f.write(str(os.getpid()))

    if ctx.job_name == "ps":
        # no PS on TPU: park until the driver releases the role
        while True:
            time.sleep(0.2)

    if ctx.job_name == "evaluator":
        _evaluator_loop(args, ctx)
        return

    _chief_train(args, ctx)


def _evaluator_loop(args, ctx):
    """Evaluate every new checkpoint as it appears (runs until terminated)."""
    import numpy as np

    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import checkpoint

    model = mnist.create_model("mlp")
    rng = np.random.default_rng(0)
    images = rng.standard_normal((64, 28, 28)).astype(np.float32)
    labels = rng.integers(0, 10, 64)
    seen = set()
    while True:
        latest = checkpoint.latest_checkpoint(args["model_dir"])
        if latest and latest not in seen:
            seen.add(latest)
            state = checkpoint.restore_checkpoint(latest)
            logits = model.apply({"params": state.params}, images)
            acc = float(np.mean(np.argmax(np.asarray(logits), -1) == labels))
            record = {"checkpoint": os.path.basename(latest), "accuracy": acc,
                      "step": int(np.asarray(state.step))}
            with open(os.path.join(args["out_dir"], "eval-{}.json".format(len(seen))), "w") as f:
                json.dump(record, f)
        time.sleep(0.2)


def _chief_train(args, ctx):
    import jax
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel, checkpoint

    strategy = SyncDataParallel(parallel.local_mesh({"dp": -1}))
    model = mnist.create_model("mlp")
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(mnist.make_init_fn(model), optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(mnist.make_loss_fn(model), optimizer, has_aux=True)
    feed = ctx.get_data_feed(train_mode=True)
    steps = 0
    while not feed.should_stop():
        batch = feed.next_batch(32)
        if not batch:
            break
        images = np.asarray([b[0] for b in batch], np.float32).reshape(-1, 28, 28)
        labels = np.asarray([b[1] for b in batch])
        state, _ = step(state, strategy.shard_batch({"image": images, "label": labels}))
        steps += 1
        if steps % 4 == 0:
            checkpoint.save_checkpoint(
                os.path.join(args["model_dir"], "ckpt_{}".format(steps)),
                jax.device_get(state),
            )


@pytest.mark.slow
def test_ps_and_evaluator_roles(tmp_path):
    out_dir = str(tmp_path / "out")
    model_dir = str(tmp_path / "model")
    os.makedirs(out_dir)
    sc = LocalSparkContext(num_executors=3, task_timeout=300)
    try:
        cluster = TFCluster.run(
            sc, fn_role_dispatch, {"out_dir": out_dir, "model_dir": model_dir},
            num_executors=3, num_ps=1, master_node="chief", eval_node=True,
            input_mode=InputMode.SPARK, env=CPU_ENV, jax_distributed=False,
            reservation_timeout=120,
        )
        # template: executor 0 = ps, 1 = chief, 2 = evaluator
        roles = {(r["job_name"], r["task_index"]) for r in cluster.cluster_info}
        assert roles == {("ps", 0), ("chief", 0), ("evaluator", 0)}

        rng_rows = [([0.01 * (i % 100)] * 784, i % 10) for i in range(512)]
        cluster.train(sc.parallelize(rng_rows, 4), num_epochs=1, feed_timeout=240)

        # evaluator must observe at least one checkpoint before teardown
        deadline = time.time() + 120
        while time.time() < deadline:
            if any(n.startswith("eval-") for n in os.listdir(out_dir)):
                break
            time.sleep(0.5)

        t0 = time.time()
        cluster.shutdown(grace_secs=2, timeout=240)
        teardown = time.time() - t0
    finally:
        sc.stop()

    started = sorted(n for n in os.listdir(out_dir) if n.endswith(".started"))
    assert started == ["chief-0.started", "evaluator-0.started", "ps-0.started"]
    evals = [n for n in os.listdir(out_dir) if n.startswith("eval-")]
    assert evals, "evaluator produced no eval results"
    with open(os.path.join(out_dir, sorted(evals)[0])) as f:
        record = json.load(f)
    assert record["checkpoint"].startswith("ckpt_")
    assert 0.0 <= record["accuracy"] <= 1.0
    assert record["step"] >= 4
    # parked ps/evaluator roles were released promptly, not via the 3-day
    # watchdog (reference TFCluster.py:136-144)
    assert teardown < 120, teardown


def fn_evaluator_crashes(args, ctx):
    if ctx.job_name == "evaluator":
        raise RuntimeError("deliberate evaluator failure")
    feed = ctx.get_data_feed(train_mode=True)
    while not feed.should_stop():
        feed.next_batch(16)


def test_evaluator_error_surfaces_at_shutdown(tmp_path):
    """A crashed driver-managed role must fail shutdown, not be swallowed
    (its error queue has no feed task to surface it through)."""
    sc = LocalSparkContext(num_executors=2, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_evaluator_crashes, {}, num_executors=2,
            master_node="chief", eval_node=True,
            input_mode=InputMode.SPARK, env=CPU_ENV, jax_distributed=False,
            reservation_timeout=120,
        )
        cluster.train(sc.parallelize(range(64), 2), num_epochs=1, feed_timeout=120)
        # deterministic: wait until the evaluator child has actually crashed
        # (posted its traceback) before shutdown peeks the error queues —
        # under load the spawned child may still be importing
        from tensorflowonspark_tpu import TFManager

        row = next(r for r in cluster.cluster_info if r["job_name"] == "evaluator")
        mgr = TFManager.connect(tuple(row["manager_addr"]), cluster.cluster_meta["authkey"])
        deadline = time.time() + 120
        while mgr.get("child_status") != "failed" and time.time() < deadline:
            time.sleep(0.2)
        assert mgr.get("child_status") == "failed"
        with pytest.raises(RuntimeError, match="deliberate evaluator failure"):
            cluster.shutdown(grace_secs=1, timeout=240)
    finally:
        sc.stop()
