"""get_spark_context / create_dataframe: the examples' backend selection.

The real-pyspark legs (reuse of an active SparkContext, executor-count
resolution from the submitted conf, an example end-to-end on local-cluster)
live in tests/test_real_pyspark.py; here the local side and the forcing
knobs are pinned."""

import importlib.util

import pytest

from tensorflowonspark_tpu.backends import create_dataframe, get_spark_context
from tensorflowonspark_tpu.backends.local import LocalSparkContext

HAVE_PYSPARK = importlib.util.find_spec("pyspark") is not None


@pytest.mark.skipif(HAVE_PYSPARK, reason="selection with pyspark present is CI-leg territory")
def test_local_fallback_without_pyspark(monkeypatch):
    monkeypatch.delenv("TOS_SPARK", raising=False)
    monkeypatch.delenv("MASTER", raising=False)
    sc, n, owned = get_spark_context("ctx-test", 3)
    try:
        assert isinstance(sc, LocalSparkContext)
        assert n == 3 and owned
    finally:
        sc.stop()


def test_tos_spark_0_forces_local(monkeypatch):
    monkeypatch.setenv("TOS_SPARK", "0")
    monkeypatch.setenv("MASTER", "local-cluster[2,1,1024]")  # must be ignored
    sc, n, owned = get_spark_context("ctx-test", 2)
    try:
        assert isinstance(sc, LocalSparkContext)
        assert n == 2 and owned
    finally:
        sc.stop()


@pytest.mark.skipif(HAVE_PYSPARK, reason="with pyspark installed TOS_SPARK=1 is legitimate")
def test_tos_spark_1_without_pyspark_raises(monkeypatch):
    monkeypatch.setenv("TOS_SPARK", "1")
    with pytest.raises(ImportError):
        get_spark_context("ctx-test", 1)


def test_local_default_used_when_no_explicit_size(monkeypatch):
    """Examples pass --cluster_size default=None; locally the per-example
    local_default applies (under Spark the cluster's conf/parallelism
    would — pinned in the CI real-pyspark leg)."""
    monkeypatch.setenv("TOS_SPARK", "0")
    sc, n, owned = get_spark_context("ctx-test", None, local_default=2)
    try:
        assert n == 2 and owned
    finally:
        sc.stop()


def test_create_dataframe_local_backend():
    sc = LocalSparkContext(num_executors=1)
    try:
        df = create_dataframe(sc, [(1, 2.0), (3, 4.0)], ["a", "b"], 1)
        assert df.columns == ["a", "b"]
        assert sorted(row[0] for row in df.collect()) == [1, 3]
    finally:
        sc.stop()


def test_injected_local_context_uses_local_default():
    sc = LocalSparkContext(num_executors=2)
    try:
        got, n, owned = get_spark_context("ctx-test", None, sc=sc, local_default=2)
        assert got is sc and n == 2 and not owned
        got, n, owned = get_spark_context("ctx-test", 5, sc=sc)
        assert n == 5 and not owned  # explicit request always wins
    finally:
        sc.stop()
