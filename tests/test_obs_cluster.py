"""Integration: a 2-node cluster's metrics flow — child registries published
over the TFManager channel, feed tasks accumulated on the feeder lane, all
merged by ``TFCluster.metrics()`` into one cluster snapshot."""

import time

import pytest

from tensorflowonspark_tpu import TFCluster
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


@pytest.fixture
def sc():
    ctx = LocalSparkContext(num_executors=2, task_timeout=120)
    yield ctx
    ctx.stop()


def fn_square_feed_with_metric(args, ctx):
    # the jax child's process-global registry: published periodically by the
    # SnapshotPublisher the node runtime starts
    from tensorflowonspark_tpu import obs
    from tensorflowonspark_tpu.data import FeedAutotuner

    obs.counter("child_marks_total", help="one per node main_fun entry").inc()
    # the feed autotuner publishes its link estimate and window choice into
    # the same registry (pure controller API: no device traffic needed)
    tuner = FeedAutotuner()
    tuner.note_fixed_probe(0.25)
    tuner.note_transfer(1 << 20, 0.25 + 0.05)
    tuner.decide(1 << 20)
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(16)
        if batch:
            feed.batch_results([x * x for x in batch])


class TestClusterMetrics:
    def test_metrics_returns_merged_cluster_snapshot(self, sc):
        cluster = TFCluster.run(
            sc, fn_square_feed_with_metric, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        try:
            results = cluster.inference(sc.parallelize(range(100), 4)).collect()
            assert sorted(results) == sorted(x * x for x in range(100))

            # the feeder lane is accumulated synchronously at task end, but the
            # child lane is published on an interval — poll until both nodes'
            # child registries have landed
            deadline = time.monotonic() + 60
            while True:
                snap = cluster.metrics()
                marks = snap["counters"].get("child_marks_total", {}).get("value", 0)
                if marks >= 2 or time.monotonic() > deadline:
                    break
                time.sleep(0.5)

            # cluster-level sums: one mark per node, every row fed + returned
            assert snap["counters"]["child_marks_total"]["value"] == 2
            assert snap["counters"]["feed_rows_total"]["value"] == 100
            assert snap["counters"]["inference_results_total"]["value"] == 100
            # driver registry rides along: the reservation server counted both
            # node registrations (process-global, so >= in case other tests ran)
            assert snap["counters"]["reservation_registrations_total"]["value"] >= 2
            # per-node detail survives the merge
            assert set(snap["nodes"]) == {"worker:0", "worker:1"}
            for node_snap in snap["nodes"].values():
                assert node_snap["counters"]["child_marks_total"]["value"] == 1
            # the adaptive feed's five metrics cross the channel: gauges and
            # counters published by the node-side FeedAutotuner land in the
            # cluster view
            for name in (
                "feed_link_bytes_per_sec",
                "feed_transfer_fixed_cost_seconds",
                "feed_window_size",
                "feed_recompiles_total",
                "feed_transfer_seconds_total",
            ):
                assert (
                    name in snap["gauges"] or name in snap["counters"]
                ), name
            # cross-node gauge semantic is SUM: two nodes x 0.25s fixed cost.
            # Exact sums are asserted on a driver-free snapshot — the driver
            # registry is process-global, and a tuner created by an earlier
            # test in this process would otherwise ride into the sum.
            nodrv = cluster.metrics(include_driver=False)
            assert nodrv["gauges"]["feed_transfer_fixed_cost_seconds"]["value"] == pytest.approx(0.5)
            assert nodrv["counters"]["feed_transfer_seconds_total"]["value"] == pytest.approx(0.6)
            for node_snap in snap["nodes"].values():
                assert node_snap["gauges"]["feed_transfer_fixed_cost_seconds"]["value"] == pytest.approx(0.25)
            # lifecycle spans crossed the channel as events
            assert any(e.get("span") == "inference_wave" for e in snap["events"])
            # snapshot is JSON-able end to end (the exporter contract)
            import json

            json.dumps(snap)
        finally:
            cluster.shutdown(timeout=120)

    def test_metrics_without_driver_registry(self, sc):
        cluster = TFCluster.run(
            sc, fn_square_feed_with_metric, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        try:
            cluster.inference(sc.parallelize(range(20), 2)).collect()
            snap = cluster.metrics(include_driver=False)
            # node-side feed counters present; driver-only counters absent
            assert snap["counters"]["feed_rows_total"]["value"] == 20
            assert "reservation_registrations_total" not in snap["counters"]
        finally:
            cluster.shutdown(timeout=120)
