"""Control-plane chaos tests (ISSUE 11 acceptance): a mid-train driver
crash recovers the membership registry from its journal — live executors
re-adopted, zero relaunches, epoch bumped — and benign lease-renewal
latency never expires a healthy lease. All asserted from the merged
``TFCluster.metrics()`` snapshot."""

import os
import time

import pytest

from tensorflowonspark_tpu import TFCluster, chaos
from tensorflowonspark_tpu import registry as membership
from tensorflowonspark_tpu.TFCluster import InputMode
from tensorflowonspark_tpu.backends.local import LocalSparkContext
from tensorflowonspark_tpu.obs import registry as obs_registry

CPU_ENV = {"JAX_PLATFORMS": "cpu"}


def fn_sleep_forever(args, ctx):
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        feed.next_batch(16)


def _wait_for_counter(cluster, name, at_least, within_secs):
    deadline = time.time() + within_secs
    snap = None
    while time.time() < deadline:
        snap = cluster.metrics()
        c = (snap.get("counters") or {}).get(name)
        if c is not None and c["value"] >= at_least:
            return snap
        time.sleep(1.0)
    return snap


@pytest.mark.chaos
@pytest.mark.slow
def test_driver_crash_recovers_registry_without_relaunch(tmp_path, monkeypatch):
    """``control.driver_crash`` drops the registry mid-watch with no parting
    commit — and ``control.journal_tear`` has already torn the manifest
    publish, so recovery must detect the CRC mismatch and rebuild from the
    journal. The restarted registry re-adopts every live lease (no
    relaunch, no recovery-ladder rung), fences the old epoch, and the
    cluster keeps feeding and shuts down cleanly."""
    monkeypatch.setenv("TOS_MONITOR_INTERVAL", "1")
    chaos_log = str(tmp_path / "chaos.log")
    monkeypatch.setenv(chaos.LOG_ENV_VAR, chaos_log)
    registry_dir = str(tmp_path / "registry")

    plan = (
        chaos.ChaosPlan(seed=3)
        .site("control.journal_tear", probability=1.0, max_count=1)
        .site("control.driver_crash", probability=1.0, max_count=1)
    )
    chaos.install(plan)
    sc = LocalSparkContext(num_executors=2, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_sleep_forever, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
            registry_dir=registry_dir,
        )
        snap = _wait_for_counter(
            cluster, "registry_driver_restarts_total", at_least=1, within_secs=60
        )
        assert snap["counters"]["registry_driver_restarts_total"]["value"] == 1

        # the crash was survivable: every lease re-adopted, nothing relaunched
        assert cluster.tf_status.get("error") is None
        assert snap["gauges"]["registry_leases_active"]["value"] == 2
        assert snap["counters"].get("recovery_attempts_total") is None
        assert snap["counters"].get("recovery_shrinks_total") is None
        # a recovered registry always runs at a HIGHER epoch than the
        # generation it replaced (begin_generation -> 1, recover -> >= 2)
        assert snap["gauges"]["registry_epoch"]["value"] >= 2
        assert cluster.registry.epoch >= 2

        # the journal on disk is the recovered truth: a fresh replay agrees
        replayed = membership.MembershipRegistry.recover(registry_dir)
        assert sorted(replayed.members()) == [0, 1]

        # still a working cluster after the restart: feed a wave through it
        cluster.train(sc.parallelize(range(64), 2), num_epochs=1, feed_timeout=60)
        assert cluster.tf_status.get("error") is None
        cluster.shutdown(timeout=120)
    finally:
        sc.stop()
        chaos.uninstall()

    with open(chaos_log) as f:
        fired = [line.strip() for line in f]
    assert "control.driver_crash" in fired
    assert "control.journal_tear" in fired


@pytest.mark.chaos
@pytest.mark.slow
def test_lease_delay_is_benign(tmp_path, monkeypatch):
    """``control.lease_delay`` injects latency into lease renewal; healthy
    leases must ride it out — no expiries, no watchdog error."""
    monkeypatch.setenv("TOS_MONITOR_INTERVAL", "1")
    chaos_log = str(tmp_path / "chaos.log")
    monkeypatch.setenv(chaos.LOG_ENV_VAR, chaos_log)

    plan = chaos.ChaosPlan(seed=5).site(
        "control.lease_delay", probability=0.5, max_count=None, delay_s=0.01
    )
    chaos.install(plan)
    # the expiration counter lives in the process-global obs registry, so
    # earlier tests in the same process may already have bumped it: assert
    # the DELTA over this cluster's lifetime, not the absolute value
    expirations_before = obs_registry.counter("registry_lease_expirations_total").value
    sc = LocalSparkContext(num_executors=2, task_timeout=240)
    try:
        cluster = TFCluster.run(
            sc, fn_sleep_forever, {}, num_executors=2,
            input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        cluster.train(sc.parallelize(range(64), 2), num_epochs=1, feed_timeout=60)
        time.sleep(5)  # a few watchdog ticks under injected renewal latency
        snap = cluster.metrics()
        assert cluster.tf_status.get("error") is None
        expirations = (snap["counters"].get("registry_lease_expirations_total") or {}).get(
            "value", 0
        )
        assert expirations == expirations_before
        assert snap["gauges"]["registry_leases_active"]["value"] == 2
        cluster.shutdown(timeout=120)
    finally:
        sc.stop()
        chaos.uninstall()

    assert plan.fired("control.lease_delay") >= 1
    with open(chaos_log) as f:
        assert any(line.strip() == "control.lease_delay" for line in f)
