"""train/strategy tests: sync DP and FSDP training on the 8-device CPU mesh."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P

from tensorflowonspark_tpu import parallel
from tensorflowonspark_tpu.train import SyncDataParallel, TrainState, steps_per_worker


def _linear_init(rng):
    k1, k2 = jax.random.split(rng)
    return {
        "w": jax.random.normal(k1, (2, 1)) * 0.01,
        "b": jnp.zeros((1,)),
    }


def _linear_loss(params, batch):
    pred = batch["x"] @ params["w"] + params["b"]
    return jnp.mean((pred - batch["y"]) ** 2)


def _make_data(n=256, seed=0):
    rng = np.random.default_rng(seed)
    x = rng.standard_normal((n, 2)).astype(np.float32)
    y = x @ np.array([[3.14], [1.618]], np.float32) + 0.5
    return {"x": x, "y": y}


@pytest.mark.parametrize("axes,fsdp", [({"dp": 8}, False), ({"dp": 2, "fsdp": 4}, True)])
def test_training_converges(axes, fsdp):
    mesh = parallel.build_mesh(axes)
    strategy = SyncDataParallel(mesh, fsdp=fsdp)
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(_linear_init, optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(_linear_loss, optimizer)
    batch = strategy.shard_batch(_make_data())
    for _ in range(150):
        state, metrics = step(state, batch)
        # the virtual-device CPU backend aborts on collective rendezvous
        # timeouts if the async dispatch queue gets deep — block every step
        # (harmless on CPU; real TPU loops want the async pipeline)
        jax.block_until_ready(metrics["loss"])
    assert float(metrics["loss"]) < 1e-3
    assert int(metrics["step"]) == 150
    w = np.asarray(jax.device_get(state.params["w"]))
    np.testing.assert_allclose(w.ravel(), [3.14, 1.618], atol=0.05)


def test_fsdp_params_actually_sharded():
    mesh = parallel.build_mesh({"fsdp": 8})
    strategy = SyncDataParallel(mesh, fsdp=True, min_weight_size=8)

    def init(rng):
        return {"big": jax.random.normal(rng, (64, 16)), "bias": jnp.zeros((3,))}

    optimizer = optax.adam(1e-3)
    state = strategy.create_state(init, optimizer, jax.random.PRNGKey(0))
    assert state.params["big"].sharding.spec == P("fsdp", None)
    assert state.params["bias"].sharding.spec == P()
    # adam moments mirror the param shardings
    mu = state.opt_state[0].mu
    assert mu["big"].sharding.spec == P("fsdp", None)


def test_train_step_with_aux_metrics():
    mesh = parallel.build_mesh({"dp": 8})
    strategy = SyncDataParallel(mesh)

    def loss_with_acc(params, batch):
        pred = batch["x"] @ params["w"] + params["b"]
        loss = jnp.mean((pred - batch["y"]) ** 2)
        return loss, {"mae": jnp.mean(jnp.abs(pred - batch["y"]))}

    optimizer = optax.sgd(0.05)
    state = strategy.create_state(_linear_init, optimizer, jax.random.PRNGKey(1))
    step = strategy.compile_train_step(loss_with_acc, optimizer, has_aux=True)
    state, metrics = step(state, strategy.shard_batch(_make_data()))
    assert set(metrics) == {"loss", "step", "mae"}


def test_predict_step_outputs_replicated():
    mesh = parallel.build_mesh({"dp": 8})
    strategy = SyncDataParallel(mesh)
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(_linear_init, optimizer, jax.random.PRNGKey(0))
    predict = strategy.compile_predict_step(
        lambda params, batch: batch["x"] @ params["w"] + params["b"]
    )
    batch = strategy.shard_batch(_make_data(n=32))
    out = predict(state.params, batch)
    assert out.shape == (32, 1)
    assert out.sharding.is_fully_replicated


def test_state_checkpoint_roundtrip(tmp_path):
    from tensorflowonspark_tpu.train import checkpoint

    mesh = parallel.build_mesh({"dp": 8})
    strategy = SyncDataParallel(mesh)
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(_linear_init, optimizer, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(_linear_loss, optimizer)
    state, _ = step(state, strategy.shard_batch(_make_data()))

    path = checkpoint.save_checkpoint(str(tmp_path / "ckpt_1"), state)
    restored = checkpoint.restore_checkpoint(path, target=jax.device_get(state))
    np.testing.assert_allclose(
        np.asarray(restored.params["w"]), np.asarray(jax.device_get(state.params["w"]))
    )
    assert checkpoint.latest_checkpoint(str(tmp_path)) == path


def test_steps_per_worker():
    # 60000 MNIST examples, batch 64, 3 workers -> int(312 * 0.9) = 280
    assert steps_per_worker(60000, 64, 3) == 280
    assert steps_per_worker(10, 64, 3) == 1  # never zero


def test_compile_train_loop_matches_sequential_steps():
    """K scanned steps inside one jit == K sequential step() calls."""
    import numpy as np
    import optax

    from tensorflowonspark_tpu import parallel
    from tensorflowonspark_tpu.models import mnist
    from tensorflowonspark_tpu.train import SyncDataParallel

    mesh = parallel.build_mesh({"dp": 8})
    strategy = SyncDataParallel(mesh)
    model = mnist.create_model("mlp", hidden=16)
    opt = optax.sgd(0.1)
    rng = np.random.default_rng(0)
    K = 4
    host_batches = [
        {
            "image": rng.standard_normal((16, 28, 28)).astype(np.float32),
            "label": rng.integers(0, 10, 16),
        }
        for _ in range(K)
    ]

    state_a = strategy.create_state(mnist.make_init_fn(model), opt, jax.random.PRNGKey(0))
    loop = strategy.compile_train_loop(mnist.make_loss_fn(model), opt, K, has_aux=True, donate=False)
    device_batches = [strategy.shard_batch(b) for b in host_batches]
    state_a, metrics = loop(state_a, device_batches)
    jax.block_until_ready(metrics["loss"])
    # batch-count mismatch is a loud error, not a silent shorter run
    import pytest as _pytest

    with _pytest.raises(ValueError, match="batches"):
        loop(state_a, device_batches[:2])

    state_b = strategy.create_state(mnist.make_init_fn(model), opt, jax.random.PRNGKey(0))
    step = strategy.compile_train_step(mnist.make_loss_fn(model), opt, has_aux=True, donate=False)
    for batch in host_batches:
        state_b, m = step(state_b, strategy.shard_batch(batch))
        jax.block_until_ready(m["loss"])

    np.testing.assert_allclose(float(metrics["loss"]), float(m["loss"]), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(state_a.params), jax.tree.leaves(state_b.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_loop_prefetch_windows_and_drops_remainder():
    from tensorflowonspark_tpu.data import loop_prefetch

    mesh = parallel.build_mesh({"dp": 8})
    strategy = SyncDataParallel(mesh)
    rng = np.random.default_rng(0)
    host = [{"x": rng.standard_normal((8, 2)).astype(np.float32)} for _ in range(10)]
    windows = list(loop_prefetch(iter(host), strategy, num_steps=4))
    # 10 batches -> two full windows of 4; the short remainder is dropped
    assert [len(w) for w in windows] == [4, 4]
    flat = [b for w in windows for b in w]
    for got, want in zip(flat, host[:8]):
        np.testing.assert_allclose(np.asarray(got["x"]), want["x"])


def test_packed_prefetch_stacks_and_shards_windows():
    """packed_place (shared by packed_prefetch and bench.py's packed link
    probe): K host batches -> ONE [K, B, ...] device tree, batch dim sharded
    over the data axes; short final windows are dropped."""
    from tensorflowonspark_tpu.data import packed_prefetch

    mesh = parallel.build_mesh({"dp": 8})
    strategy = SyncDataParallel(mesh)
    host = [{"x": np.full((8, 3), i, np.float32)} for i in range(5)]
    windows = list(packed_prefetch(iter(host), strategy, num_steps=2, depth=1))
    assert [w["x"].shape for w in windows] == [(2, 8, 3), (2, 8, 3)]
    # contents: window w holds batches 2w and 2w+1, in order
    np.testing.assert_allclose(np.asarray(windows[1]["x"][1]), host[3]["x"])
    # the batch (second) dim is sharded over dp
    assert "dp" in str(windows[0]["x"].sharding.spec)


def test_restore_checkpoint_tolerates_missing_model_state(tmp_path):
    """A checkpoint saved WITHOUT model_state (pre-r2 layout) still restores
    into a TrainState target (falls back to a target-less restore)."""
    import orbax.checkpoint as ocp

    from tensorflowonspark_tpu.train import checkpoint

    mesh = parallel.build_mesh({"dp": 8})
    strategy = SyncDataParallel(mesh)
    optimizer = optax.sgd(0.1)
    state = strategy.create_state(_linear_init, optimizer, jax.random.PRNGKey(0))

    old_layout = {
        "__train_state__": 1,
        "step": np.asarray(jax.device_get(state.step)),
        "params": jax.device_get(state.params),
        "opt_state": jax.device_get(state.opt_state),
    }
    path = str(tmp_path / "old_ckpt")
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, old_layout)
    ckptr.wait_until_finished()

    restored = checkpoint.restore_checkpoint(path, target=jax.device_get(state))
    assert isinstance(restored, TrainState)
    assert restored.model_state == {}
    np.testing.assert_allclose(
        np.asarray(restored.params["w"]), np.asarray(jax.device_get(state.params["w"]))
    )


def test_prune_checkpoints_keeps_newest(tmp_path):
    import os

    from tensorflowonspark_tpu.train import checkpoint

    for step in (2, 4, 6, 10):
        (tmp_path / "ckpt_{}".format(step)).mkdir()
    (tmp_path / "export").mkdir()  # non-numbered dirs are untouched
    (tmp_path / "run_1").mkdir()  # numbered but NOT ckpt_: deletion must
    # never touch user-owned siblings (latest_checkpoint may read them)
    removed = checkpoint.prune_checkpoints(str(tmp_path), keep=2)
    assert removed == 2
    assert sorted(os.listdir(tmp_path)) == ["ckpt_10", "ckpt_6", "export", "run_1"]
    assert checkpoint.latest_checkpoint(str(tmp_path)).endswith("ckpt_10")
    # a user-owned numbered sibling sorting above every ckpt_ dir must not
    # be returned as the resume point (ADVICE r4: it would break the
    # run_with_recovery resume contract)
    (tmp_path / "run_99").mkdir()
    assert checkpoint.latest_checkpoint(str(tmp_path)).endswith("ckpt_10")
    assert checkpoint.latest_checkpoint(str(tmp_path), prefix="").endswith("run_99")
    assert checkpoint.prune_checkpoints(str(tmp_path), keep=0) == 0  # disabled


class _FakeDevice:
    def __init__(self, slice_index=None):
        if slice_index is not None:
            self.slice_index = slice_index


class TestMultiSliceWarning:
    def test_distinct_slice_indices_warn(self, caplog):
        from tensorflowonspark_tpu.parallel import mesh

        devs = [_FakeDevice(0), _FakeDevice(0), _FakeDevice(1), _FakeDevice(1)]
        with caplog.at_level("WARNING", logger="tensorflowonspark_tpu.parallel.mesh"):
            slices = mesh._warn_if_multi_slice(devs)
        assert slices == {0, 1}
        assert any("build_hybrid_mesh" in r.message for r in caplog.records)

    def test_single_slice_is_silent(self, caplog):
        from tensorflowonspark_tpu.parallel import mesh

        with caplog.at_level("WARNING", logger="tensorflowonspark_tpu.parallel.mesh"):
            assert mesh._warn_if_multi_slice([_FakeDevice(0), _FakeDevice(0)]) == {0}
        assert not caplog.records

    def test_devices_without_slice_index_are_silent(self, caplog):
        # CPU/virtual devices have no slice_index at all
        from tensorflowonspark_tpu.parallel import mesh

        with caplog.at_level("WARNING", logger="tensorflowonspark_tpu.parallel.mesh"):
            assert mesh._warn_if_multi_slice([_FakeDevice(), _FakeDevice()]) == set()
        assert not caplog.records
