"""Chaos against the async checkpoint engine.

The acceptance bar for the tentpole: with a chaos-delayed write in flight
the training loop keeps stepping (overlap leg, also a ``perf_smoke``
marker), and a ``ckpt.commit_tear`` mid-commit never corrupts what
``restore_latest`` returns — either the staging dir is left unpublished or
the published dir fails cheap-verify and is skipped with a logged reason.
The cluster leg reruns the tear inside a spawned jax child and asserts the
fault is visible in the merged ``TFCluster.metrics()`` snapshot."""

import logging
import os
import random
import time

import numpy as np
import pytest

from tensorflowonspark_tpu import chaos, ckpt, obs
from tensorflowonspark_tpu.ckpt.snapshot import snapshot_to_host
from tensorflowonspark_tpu.train import checkpoint

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


def _state(step):
    return {"step": np.int64(step), "w": np.full(8, float(step), np.float32)}


def _seed_firing_on_nth(site, n, probability):
    """Find a plan seed whose RNG for ``site`` stays quiet for the first
    ``n - 1`` arrivals and fires on the n-th — the same
    ``random.Random("{seed}:{site}")`` stream ChaosPlan rolls, so the
    schedule reproduces in any process the plan propagates to."""
    for seed in range(10000):
        rng = random.Random("{}:{}".format(seed, site))
        draws = [rng.random() for _ in range(n)]
        if all(d >= probability for d in draws[:-1]) and draws[-1] < probability:
            return seed
    raise AssertionError("no seed fires {} on arrival {}".format(site, n))


def _save_async(model_dir, steps, **engine_kw):
    with ckpt.AsyncCheckpointEngine(model_dir, **engine_kw) as eng:
        for step in steps:
            eng.save(_state(step), step)
            assert eng.drain(timeout=60)


class TestCorruptWriteAsync:
    def test_bitrot_after_manifest_is_caught_by_cheap_verify(self, tmp_path, caplog):
        model_dir = str(tmp_path)
        _save_async(model_dir, [1])
        chaos.install(
            chaos.ChaosPlan(seed=0).site("checkpoint.corrupt_write",
                                         probability=1.0, max_count=1),
            propagate=False,
        )
        _save_async(model_dir, [2])
        chaos.uninstall()

        # the torn checkpoint PUBLISHED (bitrot hit after the manifest) but
        # cheap-verify rejects it without attempting a restore
        assert os.path.isdir(os.path.join(model_dir, "ckpt_2"))
        ok, reason = ckpt.verify(os.path.join(model_dir, "ckpt_2"))
        assert not ok and ("mismatch" in reason or "torn" in reason or
                           "missing" in reason)
        with caplog.at_level(logging.WARNING,
                             logger="tensorflowonspark_tpu.train.checkpoint"):
            state, path = checkpoint.restore_latest(model_dir)
        assert os.path.basename(path) == "ckpt_1"
        np.testing.assert_array_equal(state["w"], np.full(8, 1.0, np.float32))
        joined = " ".join(r.getMessage() for r in caplog.records)
        assert "skipping checkpoint" in joined and "ckpt_2" in joined


class TestCommitTear:
    def test_tear_leaves_staging_unpublished(self, tmp_path):
        model_dir = str(tmp_path)
        _save_async(model_dir, [1])
        chaos.install(
            chaos.ChaosPlan(seed=0).site("ckpt.commit_tear",
                                         probability=1.0, max_count=1),
            propagate=False,
        )
        _save_async(model_dir, [2])
        chaos.uninstall()

        # crash-before-rename shape: staging left behind, never published
        assert os.path.isdir(os.path.join(model_dir, "tmp.ckpt_2"))
        assert not os.path.isdir(os.path.join(model_dir, "ckpt_2"))
        state, path = checkpoint.restore_latest(model_dir)
        assert os.path.basename(path) == "ckpt_1"

        # a retried save for the same step sweeps the stale staging dir
        _save_async(model_dir, [2])
        assert not os.path.isdir(os.path.join(model_dir, "tmp.ckpt_2"))
        state, path = checkpoint.restore_latest(model_dir)
        assert os.path.basename(path) == "ckpt_2"
        np.testing.assert_array_equal(state["w"], np.full(8, 2.0, np.float32))

    def test_publish_torn_manifest_is_skipped_with_reason(self, tmp_path, caplog):
        model_dir = str(tmp_path)
        _save_async(model_dir, [1])
        chaos.install(
            chaos.ChaosPlan(seed=0).site("ckpt.commit_tear", probability=1.0,
                                         max_count=1, publish_torn=True),
            propagate=False,
        )
        _save_async(model_dir, [2])
        chaos.uninstall()

        # the rename happened over a half-written manifest
        assert os.path.isdir(os.path.join(model_dir, "ckpt_2"))
        ok, reason = ckpt.verify(os.path.join(model_dir, "ckpt_2"))
        assert not ok and "torn manifest" in reason
        with caplog.at_level(logging.WARNING,
                             logger="tensorflowonspark_tpu.train.checkpoint"):
            state, path = checkpoint.restore_latest(model_dir)
        assert os.path.basename(path) == "ckpt_1"
        joined = " ".join(r.getMessage() for r in caplog.records)
        assert "torn manifest" in joined
        assert "after skipping 1 newer checkpoint" in joined


class TestSupersede:
    def test_newer_snapshot_replaces_queued_one(self, tmp_path):
        model_dir = str(tmp_path)
        before = obs.counter("ckpt_superseded_total").value
        # one slow write pins the writer; saves 2 and 3 arrive while it is
        # busy, so 2 waits in the hand-off slot and 3 replaces it
        plan = chaos.ChaosPlan(seed=0).site("ckpt.write_slow", probability=1.0,
                                            max_count=1, delay_s=0.5)
        chaos.install(plan, propagate=False)
        with ckpt.AsyncCheckpointEngine(model_dir) as eng:
            eng.save(_state(1), 1)
            # the fault fires inside the writer's timed region, so fired()
            # flipping proves step 1 was dequeued (not just pending) and the
            # writer is sitting in its 0.5 s stall
            deadline = time.monotonic() + 30
            while not plan.fired("ckpt.write_slow") and time.monotonic() < deadline:
                time.sleep(0.005)
            assert plan.fired("ckpt.write_slow") == 1
            eng.save(_state(2), 2)
            eng.save(_state(3), 3)
            assert eng.drain(timeout=60)
        chaos.uninstall()

        assert sorted(os.listdir(model_dir)) == ["ckpt_1", "ckpt_3"]
        assert obs.counter("ckpt_superseded_total").value == before + 1
        state, path = checkpoint.restore_latest(model_dir)
        assert os.path.basename(path) == "ckpt_3"


class TestSnapshotStall:
    def test_stall_is_charged_to_the_snapshot_counter(self):
        plan = chaos.ChaosPlan(seed=0).site("ckpt.snapshot_stall",
                                            probability=1.0, max_count=1,
                                            delay_s=0.05)
        chaos.install(plan, propagate=False)
        before = obs.counter("ckpt_snapshot_seconds_total").value
        snap = snapshot_to_host(_state(1), step=1)
        chaos.uninstall()
        assert plan.fired("ckpt.snapshot_stall") == 1
        np.testing.assert_array_equal(snap.tree["w"], np.full(8, 1.0, np.float32))
        # the injected stall lands inside the timed snapshot region
        assert obs.counter("ckpt_snapshot_seconds_total").value - before >= 0.05


@pytest.mark.perf_smoke
class TestOverlap:
    def test_training_steps_continue_while_write_is_in_flight(self, tmp_path):
        model_dir = str(tmp_path)
        delay_s = 1.0
        chaos.install(
            chaos.ChaosPlan(seed=0).site("ckpt.write_slow", probability=1.0,
                                         max_count=1, delay_s=delay_s),
            propagate=False,
        )
        with ckpt.AsyncCheckpointEngine(model_dir) as eng:
            state = _state(0)
            eng.save(state, 1)
            t0 = time.monotonic()
            for _ in range(20):  # the training loop keeps stepping
                state = {"step": state["step"] + 1, "w": state["w"] + 1.0}
            stepped = time.monotonic() - t0
            # the save is still in flight (the writer is inside its chaos
            # delay) yet 20 steps cost nowhere near the write stall
            assert eng.drain(timeout=0.05) is False
            assert stepped < delay_s / 2
            assert eng.drain(timeout=60)
            assert eng.error is None
        chaos.uninstall()
        assert ckpt.verify(os.path.join(model_dir, "ckpt_1")) == (True, "verified")


# -- cluster leg --------------------------------------------------------------

CPU_ENV = {"JAX_PLATFORMS": "cpu"}
TEAR_PROBABILITY = 0.5


def fn_train_with_async_ckpt(args, ctx):
    """Runs in the spawned jax child: two async saves under the propagated
    plan (the second commit tears), then serves the feed so the metrics
    publisher has time to ship the child's counters to the driver."""
    import numpy as np

    from tensorflowonspark_tpu import chaos as _chaos
    from tensorflowonspark_tpu import ckpt as _ckpt

    assert _chaos.active, "chaos plan did not reach the jax child"
    with _ckpt.AsyncCheckpointEngine(args["model_dir"]) as eng:
        for step in (1, 2):
            eng.save(
                {"step": np.int64(step), "w": np.full(8, float(step), np.float32)},
                step,
            )
            assert eng.drain(timeout=120)
    feed = ctx.get_data_feed(train_mode=False)
    while not feed.should_stop():
        batch = feed.next_batch(16)
        if batch:
            feed.batch_results([x + 1 for x in batch])


class TestClusterCommitTear:
    def test_tear_in_child_surfaces_in_metrics_and_restore_prefers_good(
        self, tmp_path
    ):
        from tensorflowonspark_tpu import TFCluster
        from tensorflowonspark_tpu.TFCluster import InputMode
        from tensorflowonspark_tpu.backends.local import LocalSparkContext

        model_dir = str(tmp_path / "model")
        # seed-searched so the tear skips the step-1 commit and hits the
        # step-2 commit — deterministic across processes because each site
        # draws from random.Random("{seed}:{site}")
        seed = _seed_firing_on_nth("ckpt.commit_tear", 2, TEAR_PROBABILITY)
        plan = chaos.ChaosPlan(seed=seed).site(
            "ckpt.commit_tear", probability=TEAR_PROBABILITY, max_count=1
        )
        chaos.install(plan)  # propagate=True: the child inherits via env

        sc = LocalSparkContext(num_executors=1, task_timeout=120)
        cluster = TFCluster.run(
            sc, fn_train_with_async_ckpt, {"model_dir": model_dir},
            num_executors=1, input_mode=InputMode.SPARK, master_node=None,
            env=CPU_ENV, jax_distributed=False, reservation_timeout=180,
        )
        try:
            # the child finished its saves and answers the feed
            results = cluster.inference(sc.parallelize(range(20), 2)).collect()
            assert sorted(results) == list(range(1, 21))

            # the child's fault + commit counters cross the merge lane on
            # the SnapshotPublisher interval
            deadline = time.monotonic() + 60
            while True:
                snap = cluster.metrics()
                counters = snap["counters"]
                tears = counters.get(
                    "chaos_fault_ckpt_commit_tear_total", {}).get("value", 0)
                if tears >= 1 or time.monotonic() > deadline:
                    break
                time.sleep(0.5)
            assert counters["chaos_fault_ckpt_commit_tear_total"]["value"] >= 1
            assert counters["ckpt_commits_total"]["value"] >= 1
            assert counters["ckpt_bytes_total"]["value"] > 0
        finally:
            cluster.shutdown(timeout=120)
            sc.stop()

        # driver-side resume: step 2's commit tore before publish, so the
        # newest restorable checkpoint is the step-1 one
        assert os.path.isdir(os.path.join(model_dir, "tmp.ckpt_2"))
        state, path = checkpoint.restore_latest(model_dir)
        assert os.path.basename(path) == "ckpt_1"
        assert int(state["step"]) == 1
