"""Chaos: the feed layer (TFManager queues, DataFeed, data loader) under
injected stalls, truncated chunks and poisoned records. Delay faults must
only slow delivery; a poisoned record is absorbed by the loader's
``max_bad_records`` budget with full-size batches preserved, and surfaces
as the parse error once the budget is spent."""

import numpy as np
import pytest

from tensorflowonspark_tpu import TFManager, TFNode, chaos, obs, tfrecord
from tensorflowonspark_tpu.TFSparkNode import _chaos_trim
from tensorflowonspark_tpu.data import ImagePipeline

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _clean_chaos():
    chaos.uninstall()
    yield
    chaos.uninstall()


@pytest.fixture
def ipc():
    mgr = TFManager.start(authkey=b"chaos-key", queues=("input", "output", "error"))
    yield mgr
    mgr.shutdown()


def _counter(name):
    return obs.snapshot()["counters"].get(name, {}).get("value", 0)


class TestFeedStalls:
    def test_stalled_puts_and_slow_consumer_still_deliver(self, ipc):
        plan = (
            chaos.ChaosPlan(seed=0)
            .site("feed.stall", probability=1.0, max_count=3, delay_s=0.01)
            .site("feed.slow_consumer", probability=1.0, max_count=2, delay_s=0.01)
        )
        chaos.install(plan, propagate=False)
        q = ipc.get_queue("input")
        for i in range(6):
            q.put(i)
        q.put(None)  # end-of-feed
        feed = TFNode.DataFeed(ipc)
        assert feed.next_batch(4) == [0, 1, 2, 3]
        assert feed.next_batch(100) == [4, 5]
        assert feed.should_stop()
        assert plan.fired("feed.stall") == 3
        assert plan.fired("feed.slow_consumer") == 2


class TestTruncatedChunk:
    def test_chaos_trim_halves_a_train_chunk(self):
        chaos.install(
            chaos.ChaosPlan(seed=0).site("feed.truncate_chunk", probability=1.0,
                                         max_count=1),
            propagate=False,
        )
        buf = list(range(10))
        assert _chaos_trim(buf) == [0, 1, 2, 3, 4]  # tail dropped
        assert _chaos_trim(buf) == buf  # budget spent: pass-through
        assert _counter("chaos_fault_feed_truncate_chunk_total") >= 1

    def test_chaos_trim_never_empties_the_chunk(self):
        chaos.install(
            chaos.ChaosPlan(seed=0).site("feed.truncate_chunk", probability=1.0),
            propagate=False,
        )
        assert _chaos_trim([7]) == [7]  # at least one row always survives


def _int_shard(tmp_path, values):
    shard = str(tmp_path / "part-00000")
    with tfrecord.TFRecordWriter(shard) as w:
        for v in values:
            w.write(str(v).encode("ascii"))
    return shard


def _int_parse(rec):
    v = int(rec)  # raises ValueError on a poisoned record
    return np.full((2, 2, 1), v, np.float32), v


class TestPoisonedRecords:
    def test_budget_absorbs_poison_with_full_batches(self, tmp_path):
        plan = chaos.ChaosPlan(seed=0).site("data.poison", probability=1.0, max_count=2)
        chaos.install(plan, propagate=False)
        skipped_before = _counter("data_records_skipped_total")
        pipe = ImagePipeline(
            [_int_shard(tmp_path, range(8))], _int_parse,
            batch_size=2, shuffle=False, epochs=1, num_threads=2,
            max_bad_records=2,
        )
        batches = list(pipe)
        # 2 of 8 records poisoned -> 6 good ones -> 3 FULL batches (good
        # records backfill across chunk boundaries)
        assert len(batches) == 3
        assert all(b["image"].shape == (2, 2, 2, 1) for b in batches)
        assert [v for b in batches for v in b["label"].tolist()] == [2, 3, 4, 5, 6, 7]
        assert plan.fired("data.poison") == 2
        assert _counter("data_records_skipped_total") - skipped_before == 2

    def test_exhausted_budget_surfaces_the_parse_error(self, tmp_path):
        chaos.install(
            chaos.ChaosPlan(seed=0).site("data.poison", probability=1.0, max_count=2),
            propagate=False,
        )
        pipe = ImagePipeline(
            [_int_shard(tmp_path, range(8))], _int_parse,
            batch_size=2, shuffle=False, epochs=1, num_threads=2,
            max_bad_records=1,
        )
        with pytest.raises(ValueError):
            list(pipe)

    def test_default_budget_is_strict_fail_fast(self, tmp_path):
        chaos.install(
            chaos.ChaosPlan(seed=0).site("data.poison", probability=1.0, max_count=1),
            propagate=False,
        )
        pipe = ImagePipeline(
            [_int_shard(tmp_path, range(4))], _int_parse,
            batch_size=2, shuffle=False, epochs=1, num_threads=2,
        )
        with pytest.raises(ValueError):
            list(pipe)


class TestProducerDelay:
    def test_delay_only_slows_the_pipeline(self, tmp_path):
        plan = chaos.ChaosPlan(seed=0).site(
            "data.producer_delay", probability=1.0, max_count=2, delay_s=0.01
        )
        chaos.install(plan, propagate=False)
        pipe = ImagePipeline(
            [_int_shard(tmp_path, range(8))], _int_parse,
            batch_size=2, shuffle=False, epochs=1, num_threads=2,
        )
        batches = list(pipe)
        assert len(batches) == 4
        assert [v for b in batches for v in b["label"].tolist()] == list(range(8))
        assert plan.fired("data.producer_delay") == 2
