"""Fixture tests for the trace-discipline rule."""

import textwrap

from tosa_testutil import run_project_rule, run_rule_multi


def _src(s):
    return textwrap.dedent(s).lstrip()


TRACING_PATH = "tensorflowonspark_tpu/obs/tracing.py"

#: a minimal tracing module: a one-row span-site table
TRACING_MODULE = _src('''
    """Cluster-wide trace context.

    Span sites
    ----------

    ``feed_wave``      one executor feed wave
    """


    def record_span(name, ts, dur_s, **attrs):
        pass
''')

FIRING_MODULE = _src("""
    from tensorflowonspark_tpu import obs


    def feed(q, item):
        with obs.span("feed_wave"):
            q.put(item)
""")


class TestTraceDiscipline:
    def test_documented_and_fired_is_clean(self):
        findings = run_rule_multi("trace-discipline", {
            TRACING_PATH: TRACING_MODULE,
            "tensorflowonspark_tpu/feeder.py": FIRING_MODULE,
        })
        assert findings == []

    def test_non_literal_span_name_fires(self):
        findings = run_rule_multi("trace-discipline", {
            TRACING_PATH: TRACING_MODULE,
            "tensorflowonspark_tpu/feeder.py": _src("""
                from tensorflowonspark_tpu import obs

                NAME = "feed_wave"


                def feed(q, item):
                    with obs.span(NAME):
                        with obs.span("feed_wave"):
                            q.put(item)
            """),
        })
        assert len(findings) == 1
        assert "non-literal" in findings[0].message

    def test_span_outside_with_fires(self):
        findings = run_rule_multi("trace-discipline", {
            TRACING_PATH: TRACING_MODULE,
            "tensorflowonspark_tpu/feeder.py": _src("""
                from tensorflowonspark_tpu import obs


                def feed(q, item):
                    sp = obs.span("feed_wave")
                    sp.__enter__()
                    q.put(item)
                    sp.__exit__(None, None, None)
            """),
        })
        assert len(findings) == 1
        assert "context manager" in findings[0].message

    def test_record_span_is_with_exempt(self):
        findings = run_rule_multi("trace-discipline", {
            TRACING_PATH: TRACING_MODULE,
            "tensorflowonspark_tpu/feeder.py": _src("""
                from tensorflowonspark_tpu.obs import tracing


                def publish(spans):
                    for s, e in spans:
                        tracing.record_span("feed_wave", ts=s, dur_s=e - s)
            """),
        })
        assert findings == []

    def test_undocumented_span_fires(self):
        findings = run_rule_multi("trace-discipline", {
            TRACING_PATH: TRACING_MODULE,
            "tensorflowonspark_tpu/feeder.py": _src("""
                from tensorflowonspark_tpu import obs


                def feed(q, item):
                    with obs.span("feed_wave"):
                        with obs.span("mystery_phase"):
                            q.put(item)
            """),
        })
        assert len(findings) == 1
        assert "mystery_phase" in findings[0].message
        assert "missing from the span-site table" in findings[0].message

    def test_stale_table_row_fires(self):
        stale = TRACING_MODULE.replace(
            "``feed_wave``      one executor feed wave",
            "``feed_wave``      one executor feed wave\n"
            "    ``ghost_phase``    documented but never opened",
        )
        findings = run_rule_multi("trace-discipline", {
            TRACING_PATH: stale,
            "tensorflowonspark_tpu/feeder.py": FIRING_MODULE,
        })
        assert len(findings) == 1
        assert "ghost_phase" in findings[0].message
        assert "never opened" in findings[0].message

    def test_no_tracing_module_in_scan_skips_table_checks(self):
        findings = run_rule_multi("trace-discipline", {
            "tensorflowonspark_tpu/feeder.py": FIRING_MODULE,
        })
        assert findings == []

    def test_obs_package_internals_are_exempt(self):
        findings = run_rule_multi("trace-discipline", {
            TRACING_PATH: TRACING_MODULE,
            "tensorflowonspark_tpu/feeder.py": FIRING_MODULE,
            "tensorflowonspark_tpu/obs/trace.py": _src("""
                def span(name, **attrs):
                    return Span(name, attrs)


                class Span:
                    def __init__(self, name, attrs):
                        self._handle = trace.span(name)
            """),
        })
        assert findings == []

    def test_check_project_path_detects_drift(self):
        # The index-driven variant (cache-hit path) sees the same drift.
        findings = run_project_rule("trace-discipline", {
            TRACING_PATH: TRACING_MODULE,
            "tensorflowonspark_tpu/feeder.py": _src("""
                from tensorflowonspark_tpu import obs


                def feed(q, item):
                    with obs.span("mystery_phase"):
                        q.put(item)
            """),
        })
        messages = "\n".join(f.message for f in findings)
        assert "mystery_phase" in messages
        assert "feed_wave" in messages  # documented but never opened
