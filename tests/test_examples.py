"""Example-level smoke tests (reference ran its resnet examples with
synthetic data and train_steps=1, resnet_cifar_test.py:36-40; same spirit)."""

import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")
ENV = dict(
    os.environ,
    JAX_PLATFORMS="cpu",
    XLA_FLAGS="--xla_force_host_platform_device_count=1",
)


def _run(script, *args, timeout=420):
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script), *args],
        capture_output=True, text=True, timeout=timeout, env=ENV,
        cwd=os.path.join(EXAMPLES, ".."),
    )
    assert proc.returncode == 0, "{} failed:\n{}\n{}".format(script, proc.stdout[-2000:], proc.stderr[-2000:])
    return proc.stdout


@pytest.mark.slow
def test_mnist_data_setup_and_tf_mode(tmp_path):
    data = str(tmp_path / "tfr")
    _run("mnist/mnist_data_setup.py", "--output", data, "--num_examples", "512")
    out = _run(
        "mnist/mnist_tf.py", "--data_dir", data, "--cluster_size", "1",
        "--epochs", "1", "--batch_size", "64", "--platform", "cpu",
    )
    assert "training complete" in out


def test_mnist_spark_mode(tmp_path):
    export_dir = str(tmp_path / "bundle")
    out = _run(
        "mnist/mnist_spark.py", "--cluster_size", "1", "--epochs", "1",
        "--num_examples", "512", "--batch_size", "64",
        "--export_dir", export_dir, "--platform", "cpu",
    )
    assert "training complete" in out
    assert os.path.isdir(export_dir)


def test_mnist_spark_mode_auto_recover(tmp_path):
    """--auto_recover routes the SPARK feed through run_with_recovery's
    feed_fn path (clean run here; the kill-mid-feed path is proven in
    tests/test_recovery.py)."""
    model_dir = str(tmp_path / "model")
    out = _run(
        "mnist/mnist_spark.py", "--cluster_size", "1", "--epochs", "1",
        "--num_examples", "256", "--batch_size", "64",
        "--model_dir", model_dir, "--checkpoint_steps", "2",
        "--auto_recover", "1", "--platform", "cpu",
    )
    assert "training complete (0 relaunch(es))" in out
    assert any(d.startswith("ckpt_") for d in os.listdir(model_dir))


@pytest.mark.slow
def test_mnist_estimator_with_evaluator(tmp_path):
    model_dir = str(tmp_path / "est")
    out = _run(
        "mnist/mnist_estimator.py", "--cluster_size", "2", "--epochs", "1",
        "--num_examples", "512", "--batch_size", "64", "--checkpoint_steps", "4",
        "--model_dir", model_dir, "--platform", "cpu", timeout=420,
    )
    assert "estimator training complete" in out
    results = os.path.join(model_dir, "eval_results.jsonl")
    assert os.path.exists(results), out[-2000:]
    assert "accuracy" in open(results).read()


def test_mnist_streaming(tmp_path):
    out = _run(
        "mnist/mnist_spark_streaming.py", "--cluster_size", "1",
        "--num_waves", "3", "--wave_rows", "128", "--batch_size", "32",
        "--platform", "cpu",
    )
    assert "streaming training complete" in out


@pytest.mark.slow
def test_segmentation_spark(tmp_path):
    export_dir = str(tmp_path / "seg_bundle")
    out = _run(
        "segmentation/segmentation_spark.py", "--cluster_size", "1",
        "--train_steps", "4", "--image_size", "32", "--depth", "2",
        "--base_filters", "8", "--batch_size", "4", "--platform", "cpu",
        "--export_dir", export_dir, "--inference_count", "8",
    )
    assert "segmentation training complete" in out
    # multi-worker (independent instance) inference over the exported bundle
    assert "segmentation inference complete" in out
    assert os.path.isfile(os.path.join(export_dir, "inference-0.txt"))


@pytest.mark.slow
def test_resnet_cifar_synthetic(tmp_path):
    model_dir = str(tmp_path / "prof")
    out = _run(
        "resnet/resnet_spark.py", "--dataset", "cifar", "--train_steps", "3",
        "--batch_size", "8", "--log_steps", "1", "--dtype", "fp32",
        "--platform", "cpu", "--model_dir", model_dir,
        "--profile_steps", "1,2",
    )
    assert "resnet training complete" in out
    # the profiler trace landed (reference --profile_steps parity)
    assert "profiler trace written" in out
    prof = os.path.join(model_dir, "profile")
    assert os.path.isdir(prof) and os.listdir(prof)


@pytest.mark.slow
def test_resnet_real_data_end_to_end(tmp_path):
    """ResNet trains from TFRecords through the framework input pipeline
    (decode/crop/flip/normalize), VERDICT round-1 item 3."""
    data = str(tmp_path / "cifar_tfr")
    model_dir = str(tmp_path / "model")
    _run(
        "resnet/resnet_data_setup.py", "--output", data, "--dataset", "cifar",
        "--num_examples", "128", "--num_shards", "2",
    )
    out = _run(
        "resnet/resnet_spark.py", "--dataset", "cifar", "--data_dir", data,
        "--train_steps", "3", "--batch_size", "8", "--log_steps", "1",
        "--dtype", "fp32", "--model_dir", model_dir, "--platform", "cpu",
    )
    assert "resnet training complete" in out
    assert os.path.isdir(os.path.join(model_dir, "ckpt_3"))


@pytest.mark.slow
def test_resnet_imagenet_real_data_end_to_end(tmp_path):
    """The BASELINE north-star leg: ImageNet-schema JPEG TFRecords ->
    resnet_spark --dataset imagenet through decode/distorted-crop/flip/
    normalize (uint8 feed + on-device normalize) and the fused train loop
    (VERDICT r2 item 2). image_size shrinks ResNet-50 to CI scale; the
    code path is the 224 one."""
    data = str(tmp_path / "imagenet_tfr")
    model_dir = str(tmp_path / "model")
    _run(
        "resnet/resnet_data_setup.py", "--output", data, "--dataset", "imagenet",
        "--num_examples", "96", "--num_shards", "2", "--image_size", "72",
    )
    out = _run(
        "resnet/resnet_spark.py", "--dataset", "imagenet", "--data_dir", data,
        "--eval_dir", data,
        "--train_steps", "4", "--batch_size", "8", "--log_steps", "2",
        "--steps_per_loop", "2", "--image_size", "48", "--dtype", "fp32",
        "--model_dir", model_dir, "--platform", "cpu", timeout=600,
    )
    assert "resnet training complete" in out
    assert "eval accuracy" in out  # the eval input path ran end to end
    assert os.path.isdir(os.path.join(model_dir, "ckpt_4"))


@pytest.mark.slow
def test_transformer_example_sharded(tmp_path):
    """The flagship example: LM training over a dp x tp x sp mesh (tensor
    parallelism + ring attention) with the fused train loop, then a
    checkpoint lands."""
    model_dir = str(tmp_path / "lm")
    out = _run(
        "transformer/transformer_spark.py", "--cluster_size", "1",
        "--train_steps", "4", "--steps_per_loop", "2", "--log_steps", "2",
        "--batch_size", "4", "--seq_len", "64", "--d_model", "64",
        "--n_layers", "2", "--n_heads", "4", "--d_ff", "128",
        "--dtype", "float32", "--mesh", "dp=2,tp=2,sp=2",
        "--model_dir", model_dir, "--platform", "cpu", timeout=600,
    )
    assert "transformer training complete" in out
    assert "'tp': 2" in out and "'sp': 2" in out
    assert os.path.isdir(os.path.join(model_dir, "ckpt_4"))


@pytest.mark.slow
def test_mnist_pipeline_then_parallel_inference(tmp_path):
    """The remaining two BASELINE mnist configs at example level: the
    Spark-ML pipeline (TFEstimator fit -> bundle -> TFModel transform) and
    TFParallel independent-instance inference over the exported bundle."""
    export_dir = str(tmp_path / "bundle")
    out = _run(
        "mnist/mnist_pipeline.py", "--cluster_size", "1", "--epochs", "1",
        "--num_examples", "256", "--batch_size", "32",
        "--export_dir", export_dir, "--platform", "cpu",
    )
    assert "pipeline inference accuracy" in out
    assert os.path.isdir(export_dir)

    pred_out = str(tmp_path / "preds")
    out2 = _run(
        "mnist/mnist_inference.py", "--cluster_size", "2",
        "--num_examples", "128", "--batch_size", "64",
        "--export_dir", export_dir, "--output", pred_out, "--platform", "cpu",
    )
    assert "inference shards in" in out2
    assert os.listdir(pred_out)


@pytest.mark.slow
def test_resnet_checkpoint_resume_and_auto_recover(tmp_path):
    """The crash→resubmit story at the example level: run 1 checkpoints
    every 2 steps and stops at 4; run 2 (--auto_recover engages
    TFCluster.run_with_recovery) resumes at step 4 and finishes 6."""
    model_dir = str(tmp_path / "ckpts")
    common = [
        "resnet/resnet_spark.py", "--dataset", "cifar", "--batch_size", "8",
        "--log_steps", "1", "--dtype", "fp32", "--platform", "cpu",
        "--model_dir", model_dir, "--checkpoint_steps", "2",
    ]
    out1 = _run(*common, "--train_steps", "4")
    assert "resnet training complete" in out1
    assert sorted(os.listdir(model_dir)) == ["ckpt_2", "ckpt_4"]
    out2 = _run(*common, "--train_steps", "6", "--auto_recover", "1")
    assert "resuming from" in out2 and "at step 4" in out2
    assert "resnet training complete (0 relaunch(es))" in out2
    assert "ckpt_6" in os.listdir(model_dir)
