"""Unit tests for the obs registry, trace spans, and snapshot merging."""

import json
import sys
import threading

import pytest

from tensorflowonspark_tpu.obs import aggregate, registry, trace
from tensorflowonspark_tpu.obs.registry import Registry


def test_counter_gauge_histogram_basics():
    reg = Registry()
    c = reg.counter("rows_total", help="rows")
    c.inc()
    c.inc(4)
    assert c.value == 5
    g = reg.gauge("depth")
    g.set(3)
    g.inc()
    g.dec(2)
    assert g.value == 2
    h = reg.histogram("lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)  # above the last bound: count/sum only
    assert h.count == 3
    assert h.sum == pytest.approx(5.55)
    snap = h._snapshot()
    assert snap["buckets"] == [[0.1, 1], [1.0, 1]]


def test_get_or_create_returns_same_instrument_and_rejects_kind_clash():
    reg = Registry()
    assert reg.counter("x") is reg.counter("x")
    with pytest.raises(ValueError):
        reg.gauge("x")


def test_snapshot_is_json_able_and_round_trips():
    reg = Registry()
    reg.counter("a").inc()
    reg.gauge("b").set(2.5)
    reg.histogram("c").observe(0.01)
    reg.add_event({"span": "s", "ts": 1.0, "dur_s": 0.1, "ok": True})
    snap = json.loads(json.dumps(reg.snapshot()))
    assert snap["counters"]["a"]["value"] == 1
    assert snap["gauges"]["b"]["value"] == 2.5
    assert snap["histograms"]["c"]["count"] == 1
    assert snap["events"][0]["span"] == "s"


def test_disabled_registry_records_nothing():
    reg = Registry(enabled=False)
    c = reg.counter("n")
    c.inc()
    reg.gauge("g").set(9)
    reg.histogram("h").observe(1)
    reg.add_event({"e": 1})
    snap = reg.snapshot()
    assert snap["counters"]["n"]["value"] == 0
    assert snap["gauges"]["g"]["value"] == 0
    assert snap["histograms"]["h"]["count"] == 0
    assert snap["events"] == []


def test_disabled_inc_allocates_nothing_per_step():
    """The off-the-hot-path guarantee: with the registry disabled, per-step
    instrument calls allocate no objects at all."""
    reg = Registry(enabled=False)
    c = reg.counter("steps_total")
    h = reg.histogram("step_seconds")
    span = trace.span("step", registry=reg)  # shared _NULL singleton
    # warm up any lazy attribute caches before measuring
    for _ in range(10):
        c.inc()
        h.observe(0.1)
        with span:
            pass
    before = sys.getallocatedblocks()
    for _ in range(1000):
        c.inc()
        h.observe(0.1)
        with trace.span("step", registry=reg):
            pass
    grown = sys.getallocatedblocks() - before
    # zero in practice; tolerate interpreter-internal noise, but 1000
    # iterations of real allocation would show thousands of blocks
    assert grown < 50, "disabled instruments allocated {} blocks".format(grown)


def test_span_records_event_and_histogram():
    reg = Registry()
    with trace.span("launch", registry=reg, node=3) as sp:
        sp.set(extra="yes")
    events = reg.events()
    assert len(events) == 1
    ev = events[0]
    assert ev["span"] == "launch" and ev["ok"] and ev["node"] == 3 and ev["extra"] == "yes"
    assert ev["dur_s"] >= 0
    assert reg.histogram("launch_seconds").count == 1


def test_span_marks_failure_and_propagates():
    reg = Registry()
    with pytest.raises(RuntimeError):
        with trace.span("boom", registry=reg):
            raise RuntimeError("x")
    assert reg.events()[0]["ok"] is False


def test_event_buffer_is_bounded():
    reg = Registry()
    for i in range(registry.MAX_EVENTS + 10):
        reg.add_event({"i": i})
    events = reg.events()
    assert len(events) == registry.MAX_EVENTS
    assert events[-1]["i"] == registry.MAX_EVENTS + 9


def test_thread_safety_of_counters():
    reg = Registry()
    c = reg.counter("n")

    def work():
        for _ in range(10000):
            c.inc()

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value == 40000


def test_merge_snapshots_sums_counters_and_buckets():
    a, b = Registry(), Registry()
    a.counter("n").inc(2)
    b.counter("n").inc(3)
    a.gauge("depth").set(4)
    b.gauge("depth").set(6)
    a.histogram("lat", buckets=(1.0, 2.0)).observe(0.5)
    b.histogram("lat", buckets=(1.0, 2.0)).observe(1.5)
    merged = aggregate.merge_snapshots([a.snapshot(), b.snapshot()])
    assert merged["counters"]["n"]["value"] == 5
    assert merged["gauges"]["depth"]["value"] == 10  # cross-node: summed
    assert merged["histograms"]["lat"]["count"] == 2
    assert merged["histograms"]["lat"]["buckets"] == [[1.0, 1], [2.0, 1]]


def test_merge_snapshots_gauges_last_for_time_accumulation():
    older, newer = Registry(), Registry()
    older.gauge("depth").set(10)
    newer.gauge("depth").set(2)
    merged = aggregate.merge_snapshots([older.snapshot(), newer.snapshot()], gauges="last")
    assert merged["gauges"]["depth"]["value"] == 2


def test_merge_snapshots_orders_and_bounds_events():
    a, b = Registry(), Registry()
    a.add_event({"span": "x", "ts": 2.0})
    b.add_event({"span": "y", "ts": 1.0})
    merged = aggregate.merge_snapshots([a.snapshot(), b.snapshot()])
    assert [e["span"] for e in merged["events"]] == ["y", "x"]


class _FakeMgr:
    """Duck-typed TFManager k/v surface for channel publication tests."""

    def __init__(self):
        self.kv = {}

    def set(self, key, value):
        self.kv[key] = value

    def get(self, key):
        return self.kv.get(key)


def test_publish_and_read_channel_round_trip():
    mgr = _FakeMgr()
    reg = Registry()
    reg.counter("n").inc(7)
    aggregate.publish_to_channel(mgr, reg)
    snaps = aggregate.read_channel_snapshots(mgr)
    assert len(snaps) == 1
    assert snaps[0]["counters"]["n"]["value"] == 7


def test_accumulate_to_channel_merges_successive_tasks():
    mgr = _FakeMgr()
    for rows in (5, 7):
        task_reg = Registry()  # private per-task registry, as the feed tasks use
        task_reg.counter("feed_rows_total").inc(rows)
        task_reg.gauge("feed_queue_depth").set(rows)
        aggregate.accumulate_to_channel(mgr, task_reg)
    (snap,) = aggregate.read_channel_snapshots(mgr, keys=(aggregate.FEEDER_KEY,))
    assert snap["counters"]["feed_rows_total"]["value"] == 12
    # same-node over time: depth is the LAST wave's, not the sum
    assert snap["gauges"]["feed_queue_depth"]["value"] == 7


def test_snapshot_publisher_publishes_and_flushes_on_stop():
    mgr = _FakeMgr()
    reg = Registry()
    reg.counter("beats").inc()
    pub = aggregate.SnapshotPublisher(mgr, reg, interval=0.05).start()
    pub.stop()
    (snap,) = aggregate.read_channel_snapshots(mgr, keys=(aggregate.CHANNEL_KEY,))
    assert snap["counters"]["beats"]["value"] == 1


def test_snapshot_publisher_disabled_registry_spins_nothing():
    mgr = _FakeMgr()
    pub = aggregate.SnapshotPublisher(mgr, Registry(enabled=False), interval=0.01).start()
    assert pub._thread is None
    pub.stop()
    assert mgr.kv == {}
