// TFRecord bulk IO — the native hot path for TPU-host data ingest.
//
// The reference delegated TFRecord IO to a prebuilt Hadoop InputFormat jar
// (/root/reference/lib/tensorflow-hadoop-1.0-SNAPSHOT.jar, driven by
// dfutil.py:39,63); its actual record codec lived in TensorFlow's C++ core
// (tensorflow/core/lib/io/record_reader.cc). This is the TPU-native
// equivalent: a dependency-free C++ reader/writer for the TFRecord framing
// (8-byte LE length, masked-crc32c of the length, payload, masked-crc32c of
// the payload) exposed through a plain C ABI so Python binds it with ctypes
// (no pybind11 in this environment).
//
// Bulk contract: one call loads/indexes a whole shard file. The Python side
// then slices records out of a single contiguous buffer — one FFI round trip
// per file instead of per record, which is what makes feeding a TPU host at
// ResNet rates possible from Python.
//
// Build: `make` in this directory (produces libtfrecord_io.so); loaded by
// tensorflowonspark_tpu/native_io.py, which falls back to the pure-Python
// codec in tensorflowonspark_tpu/tfrecord.py when the library is absent.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

// ---------------------------------------------------------------------------
// crc32c (Castagnoli), slicing-by-8: table-driven, no SSE4.2 dependency so
// the same source builds on any TPU-host CPU image.
// ---------------------------------------------------------------------------

uint32_t kCrcTable[8][256];

// Eager, synchronized table build: ctypes releases the GIL, so two threads
// (e.g. two ImagePipeline producers) may enter tfr_load concurrently — a lazy
// unsynchronized flag would race. Running once at library load removes the
// window entirely.
int crc_init() {
  const uint32_t poly = 0x82f63b78u;  // reflected CRC-32C polynomial
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    kCrcTable[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = kCrcTable[0][i];
    for (int t = 1; t < 8; t++) {
      crc = (crc >> 8) ^ kCrcTable[0][crc & 0xff];
      kCrcTable[t][i] = crc;
    }
  }
  return 0;
}

const int kCrcInitToken = crc_init();  // static initializer, pre-main

uint32_t crc32c(const uint8_t* data, size_t n) {
  uint32_t crc = 0xffffffffu;
  while (n >= 8) {
    uint64_t word;
    memcpy(&word, data, 8);
    word ^= crc;  // little-endian host assumed (x86/arm TPU hosts)
    crc = kCrcTable[7][word & 0xff] ^ kCrcTable[6][(word >> 8) & 0xff] ^
          kCrcTable[5][(word >> 16) & 0xff] ^ kCrcTable[4][(word >> 24) & 0xff] ^
          kCrcTable[3][(word >> 32) & 0xff] ^ kCrcTable[2][(word >> 40) & 0xff] ^
          kCrcTable[1][(word >> 48) & 0xff] ^ kCrcTable[0][(word >> 56) & 0xff];
    data += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ kCrcTable[0][(crc ^ *data++) & 0xff];
  return crc ^ 0xffffffffu;
}

const uint32_t kMaskDelta = 0xa282ead8u;

uint32_t masked_crc(const uint8_t* data, size_t n) {
  uint32_t crc = crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + kMaskDelta;
}

uint32_t read_u32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

uint64_t read_u64(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// A fully-loaded shard: the raw file bytes plus an index of payload spans.
struct TfrFile {
  uint8_t* buf;        // whole file
  uint64_t buf_len;
  uint64_t* offsets;   // payload start offsets into buf
  uint64_t* lengths;   // payload lengths
  uint64_t count;      // number of records
};

// Load + index + (optionally) CRC-verify a TFRecord file in one call.
// Returns NULL on IO/corruption error (error text via tfr_last_error).
static thread_local char g_err[256];

const char* tfr_last_error() { return g_err; }

static void set_err(const char* fmt, const char* a, uint64_t b) {
  snprintf(g_err, sizeof(g_err), fmt, a, (unsigned long long)b);
}

void tfr_free(TfrFile* f) {
  if (!f) return;
  free(f->buf);
  free(f->offsets);
  free(f->lengths);
  free(f);
}

TfrFile* tfr_load(const char* path, int verify_crc) {
  g_err[0] = 0;
  FILE* fp = fopen(path, "rb");
  if (!fp) {
    set_err("cannot open %s (record %llu)", path, 0);
    return nullptr;
  }
  fseek(fp, 0, SEEK_END);
  long sz = ftell(fp);
  fseek(fp, 0, SEEK_SET);
  uint8_t* buf = (uint8_t*)malloc(sz > 0 ? sz : 1);
  if (!buf || (sz > 0 && fread(buf, 1, sz, fp) != (size_t)sz)) {
    set_err("short read on %s (record %llu)", path, 0);
    free(buf);
    fclose(fp);
    return nullptr;
  }
  fclose(fp);

  uint64_t cap = 1024, count = 0;
  uint64_t* offsets = (uint64_t*)malloc(cap * sizeof(uint64_t));
  uint64_t* lengths = (uint64_t*)malloc(cap * sizeof(uint64_t));
  if (!offsets || !lengths) {
    set_err("out of memory allocating record index for %s (record %llu)", path, 0);
    free(buf);
    free(offsets);
    free(lengths);
    return nullptr;
  }
  uint64_t pos = 0, n = (uint64_t)sz;
  while (pos < n) {
    if (pos + 12 > n) {
      set_err("truncated length header in %s (record %llu)", path, count);
      goto fail;
    }
    {
      uint64_t len = read_u64(buf + pos);
      uint32_t len_crc = read_u32(buf + pos + 8);
      if (verify_crc && masked_crc(buf + pos, 8) != len_crc) {
        set_err("corrupt length crc in %s (record %llu)", path, count);
        goto fail;
      }
      // overflow-safe: `pos + 12 + len + 4 > n` wraps for a corrupt huge
      // len; compare against the remaining bytes instead
      uint64_t remaining = n - pos;  // >= 12 per the header check above
      if (remaining < 16 || len > remaining - 16) {
        set_err("truncated payload in %s (record %llu)", path, count);
        goto fail;
      }
      if (verify_crc &&
          masked_crc(buf + pos + 12, len) != read_u32(buf + pos + 12 + len)) {
        set_err("corrupt payload crc in %s (record %llu)", path, count);
        goto fail;
      }
      if (count == cap) {
        cap *= 2;
        uint64_t* new_offsets = (uint64_t*)realloc(offsets, cap * sizeof(uint64_t));
        uint64_t* new_lengths = (uint64_t*)realloc(lengths, cap * sizeof(uint64_t));
        if (new_offsets) offsets = new_offsets;
        if (new_lengths) lengths = new_lengths;
        if (!new_offsets || !new_lengths) {
          set_err("out of memory growing record index for %s (record %llu)",
                  path, count);
          goto fail;
        }
      }
      offsets[count] = pos + 12;
      lengths[count] = len;
      count++;
      pos += 12 + len + 4;
    }
  }
  {
    TfrFile* f = (TfrFile*)malloc(sizeof(TfrFile));
    if (!f) {
      set_err("out of memory for handle on %s (record %llu)", path, count);
      goto fail;
    }
    f->buf = buf;
    f->buf_len = n;
    f->offsets = offsets;
    f->lengths = lengths;
    f->count = count;
    return f;
  }
fail:
  free(buf);
  free(offsets);
  free(lengths);
  return nullptr;
}

uint64_t tfr_count(const TfrFile* f) { return f->count; }
const uint8_t* tfr_buffer(const TfrFile* f) { return f->buf; }
uint64_t tfr_buffer_len(const TfrFile* f) { return f->buf_len; }
const uint64_t* tfr_offsets(const TfrFile* f) { return f->offsets; }
const uint64_t* tfr_lengths(const TfrFile* f) { return f->lengths; }

// ---------------------------------------------------------------------------
// Streaming reader: open once, pull bounded chunks. The chunked twin of
// tfr_load for the pipelined input path — a shard no longer has to be fully
// materialized before the first record flows, and the Python side bounds
// peak memory at (chunk records) instead of (shard records). Each chunk is
// returned as a TfrFile (same contiguous buffer + span index contract as
// tfr_load; freed with tfr_free), so the binding slices records identically
// in both modes.
// ---------------------------------------------------------------------------

struct TfrStream {
  FILE* fp;
  int verify_crc;
  uint64_t record_index;  // records consumed so far (error messages)
  char* path;             // owned copy for error messages
};

TfrStream* tfr_stream_open(const char* path, int verify_crc) {
  g_err[0] = 0;
  FILE* fp = fopen(path, "rb");
  if (!fp) {
    set_err("cannot open %s (record %llu)", path, 0);
    return nullptr;
  }
  TfrStream* s = (TfrStream*)malloc(sizeof(TfrStream));
  char* path_copy = (char*)malloc(strlen(path) + 1);
  if (!s || !path_copy) {
    set_err("out of memory opening stream on %s (record %llu)", path, 0);
    free(s);
    free(path_copy);
    fclose(fp);
    return nullptr;
  }
  strcpy(path_copy, path);
  s->fp = fp;
  s->verify_crc = verify_crc;
  s->record_index = 0;
  s->path = path_copy;
  return s;
}

void tfr_stream_close(TfrStream* s) {
  if (!s) return;
  if (s->fp) fclose(s->fp);
  free(s->path);
  free(s);
}

// Read up to max_records sequentially from the stream position. Returns a
// TfrFile chunk, or NULL at clean EOF (tfr_last_error empty) or on error
// (tfr_last_error set). A short chunk is only returned at end of file.
TfrFile* tfr_stream_next(TfrStream* s, uint64_t max_records) {
  g_err[0] = 0;
  if (!s || !s->fp || max_records == 0) return nullptr;
  uint64_t buf_cap = 1 << 20, buf_len = 0;
  uint64_t idx_cap = max_records < 1024 ? max_records : 1024;
  uint64_t count = 0;
  uint8_t* buf = (uint8_t*)malloc(buf_cap);
  uint64_t* offsets = (uint64_t*)malloc(idx_cap * sizeof(uint64_t));
  uint64_t* lengths = (uint64_t*)malloc(idx_cap * sizeof(uint64_t));
  if (!buf || !offsets || !lengths) {
    set_err("out of memory for chunk on %s (record %llu)", s->path,
            s->record_index);
    goto fail;
  }
  while (count < max_records) {
    uint8_t header[12];
    size_t got = fread(header, 1, 12, s->fp);
    if (got == 0) break;  // clean EOF at a record boundary
    if (got != 12) {
      set_err("truncated length header in %s (record %llu)", s->path,
              s->record_index);
      goto fail;
    }
    {
      uint64_t len = read_u64(header);
      uint32_t len_crc = read_u32(header + 8);
      if (s->verify_crc && masked_crc(header, 8) != len_crc) {
        set_err("corrupt length crc in %s (record %llu)", s->path,
                s->record_index);
        goto fail;
      }
      // reject a corrupt huge len before trying to allocate it: the payload
      // plus its crc cannot exceed what is left of the file
      long cur = ftell(s->fp);
      fseek(s->fp, 0, SEEK_END);
      long end = ftell(s->fp);
      fseek(s->fp, cur, SEEK_SET);
      if (end < cur || len > (uint64_t)(end - cur) ||
          (uint64_t)(end - cur) - len < 4) {
        set_err("truncated payload in %s (record %llu)", s->path,
                s->record_index);
        goto fail;
      }
      while (buf_len + len > buf_cap) {
        buf_cap *= 2;
        uint8_t* new_buf = (uint8_t*)realloc(buf, buf_cap);
        if (!new_buf) {
          set_err("out of memory growing chunk on %s (record %llu)", s->path,
                  s->record_index);
          goto fail;
        }
        buf = new_buf;
      }
      uint8_t crc_bytes[4];
      if (fread(buf + buf_len, 1, len, s->fp) != len ||
          fread(crc_bytes, 1, 4, s->fp) != 4) {
        set_err("truncated payload in %s (record %llu)", s->path,
                s->record_index);
        goto fail;
      }
      if (s->verify_crc &&
          masked_crc(buf + buf_len, len) != read_u32(crc_bytes)) {
        set_err("corrupt payload crc in %s (record %llu)", s->path,
                s->record_index);
        goto fail;
      }
      if (count == idx_cap) {
        idx_cap *= 2;
        uint64_t* new_offsets =
            (uint64_t*)realloc(offsets, idx_cap * sizeof(uint64_t));
        uint64_t* new_lengths =
            (uint64_t*)realloc(lengths, idx_cap * sizeof(uint64_t));
        if (new_offsets) offsets = new_offsets;
        if (new_lengths) lengths = new_lengths;
        if (!new_offsets || !new_lengths) {
          set_err("out of memory growing chunk index on %s (record %llu)",
                  s->path, s->record_index);
          goto fail;
        }
      }
      offsets[count] = buf_len;
      lengths[count] = len;
      buf_len += len;
      count++;
      s->record_index++;
    }
  }
  if (count == 0) {  // clean EOF with nothing read
    free(buf);
    free(offsets);
    free(lengths);
    return nullptr;
  }
  {
    TfrFile* f = (TfrFile*)malloc(sizeof(TfrFile));
    if (!f) {
      set_err("out of memory for chunk handle on %s (record %llu)", s->path,
              s->record_index);
      goto fail;
    }
    f->buf = buf;
    f->buf_len = buf_len;
    f->offsets = offsets;
    f->lengths = lengths;
    f->count = count;
    return f;
  }
fail:
  free(buf);
  free(offsets);
  free(lengths);
  return nullptr;
}

// ---------------------------------------------------------------------------
// Writer: frame `count` records (concatenated in `payloads`, spans given by
// offsets/lengths) into `path` in one call.
// ---------------------------------------------------------------------------

int tfr_write(const char* path, const uint8_t* payloads, const uint64_t* offsets,
              const uint64_t* lengths, uint64_t count) {
  g_err[0] = 0;
  FILE* fp = fopen(path, "wb");
  if (!fp) {
    set_err("cannot open %s for write (record %llu)", path, 0);
    return -1;
  }
  for (uint64_t i = 0; i < count; i++) {
    uint8_t header[12];
    uint64_t len = lengths[i];
    memcpy(header, &len, 8);
    uint32_t hcrc = masked_crc(header, 8);
    memcpy(header + 8, &hcrc, 4);
    uint32_t pcrc = masked_crc(payloads + offsets[i], len);
    if (fwrite(header, 1, 12, fp) != 12 ||
        fwrite(payloads + offsets[i], 1, len, fp) != len ||
        fwrite(&pcrc, 1, 4, fp) != 4) {
      set_err("short write on %s (record %llu)", path, i);
      fclose(fp);
      return -1;
    }
  }
  if (fclose(fp) != 0) {
    set_err("close failed on %s (record %llu)", path, count);
    return -1;
  }
  return 0;
}

// Standalone crc for tests / cross-validation with the Python codec.
uint32_t tfr_masked_crc32c(const uint8_t* data, uint64_t n) {
  return masked_crc(data, n);
}

}  // extern "C"
