// TFRecord bulk IO — the native hot path for TPU-host data ingest.
//
// The reference delegated TFRecord IO to a prebuilt Hadoop InputFormat jar
// (/root/reference/lib/tensorflow-hadoop-1.0-SNAPSHOT.jar, driven by
// dfutil.py:39,63); its actual record codec lived in TensorFlow's C++ core
// (tensorflow/core/lib/io/record_reader.cc). This is the TPU-native
// equivalent: a dependency-free C++ reader/writer for the TFRecord framing
// (8-byte LE length, masked-crc32c of the length, payload, masked-crc32c of
// the payload) exposed through a plain C ABI so Python binds it with ctypes
// (no pybind11 in this environment).
//
// Bulk contract: one call loads/indexes a whole shard file. The Python side
// then slices records out of a single contiguous buffer — one FFI round trip
// per file instead of per record, which is what makes feeding a TPU host at
// ResNet rates possible from Python.
//
// Build: `make` in this directory (produces libtfrecord_io.so); loaded by
// tensorflowonspark_tpu/native_io.py, which falls back to the pure-Python
// codec in tensorflowonspark_tpu/tfrecord.py when the library is absent.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

namespace {

// ---------------------------------------------------------------------------
// crc32c (Castagnoli), slicing-by-8: table-driven, no SSE4.2 dependency so
// the same source builds on any TPU-host CPU image.
// ---------------------------------------------------------------------------

uint32_t kCrcTable[8][256];

// Eager, synchronized table build: ctypes releases the GIL, so two threads
// (e.g. two ImagePipeline producers) may enter tfr_load concurrently — a lazy
// unsynchronized flag would race. Running once at library load removes the
// window entirely.
int crc_init() {
  const uint32_t poly = 0x82f63b78u;  // reflected CRC-32C polynomial
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = i;
    for (int j = 0; j < 8; j++) crc = (crc >> 1) ^ ((crc & 1) ? poly : 0);
    kCrcTable[0][i] = crc;
  }
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t crc = kCrcTable[0][i];
    for (int t = 1; t < 8; t++) {
      crc = (crc >> 8) ^ kCrcTable[0][crc & 0xff];
      kCrcTable[t][i] = crc;
    }
  }
  return 0;
}

const int kCrcInitToken = crc_init();  // static initializer, pre-main

uint32_t crc32c(const uint8_t* data, size_t n) {
  uint32_t crc = 0xffffffffu;
  while (n >= 8) {
    uint64_t word;
    memcpy(&word, data, 8);
    word ^= crc;  // little-endian host assumed (x86/arm TPU hosts)
    crc = kCrcTable[7][word & 0xff] ^ kCrcTable[6][(word >> 8) & 0xff] ^
          kCrcTable[5][(word >> 16) & 0xff] ^ kCrcTable[4][(word >> 24) & 0xff] ^
          kCrcTable[3][(word >> 32) & 0xff] ^ kCrcTable[2][(word >> 40) & 0xff] ^
          kCrcTable[1][(word >> 48) & 0xff] ^ kCrcTable[0][(word >> 56) & 0xff];
    data += 8;
    n -= 8;
  }
  while (n--) crc = (crc >> 8) ^ kCrcTable[0][(crc ^ *data++) & 0xff];
  return crc ^ 0xffffffffu;
}

const uint32_t kMaskDelta = 0xa282ead8u;

uint32_t masked_crc(const uint8_t* data, size_t n) {
  uint32_t crc = crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + kMaskDelta;
}

uint32_t read_u32(const uint8_t* p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

uint64_t read_u64(const uint8_t* p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v;
}

}  // namespace

// ---------------------------------------------------------------------------
// C ABI
// ---------------------------------------------------------------------------

extern "C" {

// A fully-loaded shard: the raw file bytes plus an index of payload spans.
struct TfrFile {
  uint8_t* buf;        // whole file
  uint64_t buf_len;
  uint64_t* offsets;   // payload start offsets into buf
  uint64_t* lengths;   // payload lengths
  uint64_t count;      // number of records
};

// Load + index + (optionally) CRC-verify a TFRecord file in one call.
// Returns NULL on IO/corruption error (error text via tfr_last_error).
static thread_local char g_err[256];

const char* tfr_last_error() { return g_err; }

static void set_err(const char* fmt, const char* a, uint64_t b) {
  snprintf(g_err, sizeof(g_err), fmt, a, (unsigned long long)b);
}

void tfr_free(TfrFile* f) {
  if (!f) return;
  free(f->buf);
  free(f->offsets);
  free(f->lengths);
  free(f);
}

TfrFile* tfr_load(const char* path, int verify_crc) {
  g_err[0] = 0;
  FILE* fp = fopen(path, "rb");
  if (!fp) {
    set_err("cannot open %s (record %llu)", path, 0);
    return nullptr;
  }
  fseek(fp, 0, SEEK_END);
  long sz = ftell(fp);
  fseek(fp, 0, SEEK_SET);
  uint8_t* buf = (uint8_t*)malloc(sz > 0 ? sz : 1);
  if (!buf || (sz > 0 && fread(buf, 1, sz, fp) != (size_t)sz)) {
    set_err("short read on %s (record %llu)", path, 0);
    free(buf);
    fclose(fp);
    return nullptr;
  }
  fclose(fp);

  uint64_t cap = 1024, count = 0;
  uint64_t* offsets = (uint64_t*)malloc(cap * sizeof(uint64_t));
  uint64_t* lengths = (uint64_t*)malloc(cap * sizeof(uint64_t));
  if (!offsets || !lengths) {
    set_err("out of memory allocating record index for %s (record %llu)", path, 0);
    free(buf);
    free(offsets);
    free(lengths);
    return nullptr;
  }
  uint64_t pos = 0, n = (uint64_t)sz;
  while (pos < n) {
    if (pos + 12 > n) {
      set_err("truncated length header in %s (record %llu)", path, count);
      goto fail;
    }
    {
      uint64_t len = read_u64(buf + pos);
      uint32_t len_crc = read_u32(buf + pos + 8);
      if (verify_crc && masked_crc(buf + pos, 8) != len_crc) {
        set_err("corrupt length crc in %s (record %llu)", path, count);
        goto fail;
      }
      // overflow-safe: `pos + 12 + len + 4 > n` wraps for a corrupt huge
      // len; compare against the remaining bytes instead
      uint64_t remaining = n - pos;  // >= 12 per the header check above
      if (remaining < 16 || len > remaining - 16) {
        set_err("truncated payload in %s (record %llu)", path, count);
        goto fail;
      }
      if (verify_crc &&
          masked_crc(buf + pos + 12, len) != read_u32(buf + pos + 12 + len)) {
        set_err("corrupt payload crc in %s (record %llu)", path, count);
        goto fail;
      }
      if (count == cap) {
        cap *= 2;
        uint64_t* new_offsets = (uint64_t*)realloc(offsets, cap * sizeof(uint64_t));
        uint64_t* new_lengths = (uint64_t*)realloc(lengths, cap * sizeof(uint64_t));
        if (new_offsets) offsets = new_offsets;
        if (new_lengths) lengths = new_lengths;
        if (!new_offsets || !new_lengths) {
          set_err("out of memory growing record index for %s (record %llu)",
                  path, count);
          goto fail;
        }
      }
      offsets[count] = pos + 12;
      lengths[count] = len;
      count++;
      pos += 12 + len + 4;
    }
  }
  {
    TfrFile* f = (TfrFile*)malloc(sizeof(TfrFile));
    if (!f) {
      set_err("out of memory for handle on %s (record %llu)", path, count);
      goto fail;
    }
    f->buf = buf;
    f->buf_len = n;
    f->offsets = offsets;
    f->lengths = lengths;
    f->count = count;
    return f;
  }
fail:
  free(buf);
  free(offsets);
  free(lengths);
  return nullptr;
}

uint64_t tfr_count(const TfrFile* f) { return f->count; }
const uint8_t* tfr_buffer(const TfrFile* f) { return f->buf; }
uint64_t tfr_buffer_len(const TfrFile* f) { return f->buf_len; }
const uint64_t* tfr_offsets(const TfrFile* f) { return f->offsets; }
const uint64_t* tfr_lengths(const TfrFile* f) { return f->lengths; }

// ---------------------------------------------------------------------------
// Streaming reader: open once, pull bounded chunks. The chunked twin of
// tfr_load for the pipelined input path — a shard no longer has to be fully
// materialized before the first record flows, and the Python side bounds
// peak memory at (chunk records) instead of (shard records). Each chunk is
// returned as a TfrFile (same contiguous buffer + span index contract as
// tfr_load; freed with tfr_free), so the binding slices records identically
// in both modes.
// ---------------------------------------------------------------------------

struct TfrStream {
  FILE* fp;
  int verify_crc;
  uint64_t record_index;  // records consumed so far (error messages)
  char* path;             // owned copy for error messages
};

TfrStream* tfr_stream_open(const char* path, int verify_crc) {
  g_err[0] = 0;
  FILE* fp = fopen(path, "rb");
  if (!fp) {
    set_err("cannot open %s (record %llu)", path, 0);
    return nullptr;
  }
  TfrStream* s = (TfrStream*)malloc(sizeof(TfrStream));
  char* path_copy = (char*)malloc(strlen(path) + 1);
  if (!s || !path_copy) {
    set_err("out of memory opening stream on %s (record %llu)", path, 0);
    free(s);
    free(path_copy);
    fclose(fp);
    return nullptr;
  }
  strcpy(path_copy, path);
  s->fp = fp;
  s->verify_crc = verify_crc;
  s->record_index = 0;
  s->path = path_copy;
  return s;
}

void tfr_stream_close(TfrStream* s) {
  if (!s) return;
  if (s->fp) fclose(s->fp);
  free(s->path);
  free(s);
}

// Read up to max_records sequentially from the stream position. Returns a
// TfrFile chunk, or NULL at clean EOF (tfr_last_error empty) or on error
// (tfr_last_error set). A short chunk is only returned at end of file.
TfrFile* tfr_stream_next(TfrStream* s, uint64_t max_records) {
  g_err[0] = 0;
  if (!s || !s->fp || max_records == 0) return nullptr;
  uint64_t buf_cap = 1 << 20, buf_len = 0;
  uint64_t idx_cap = max_records < 1024 ? max_records : 1024;
  uint64_t count = 0;
  uint8_t* buf = (uint8_t*)malloc(buf_cap);
  uint64_t* offsets = (uint64_t*)malloc(idx_cap * sizeof(uint64_t));
  uint64_t* lengths = (uint64_t*)malloc(idx_cap * sizeof(uint64_t));
  if (!buf || !offsets || !lengths) {
    set_err("out of memory for chunk on %s (record %llu)", s->path,
            s->record_index);
    goto fail;
  }
  while (count < max_records) {
    uint8_t header[12];
    size_t got = fread(header, 1, 12, s->fp);
    if (got == 0) break;  // clean EOF at a record boundary
    if (got != 12) {
      set_err("truncated length header in %s (record %llu)", s->path,
              s->record_index);
      goto fail;
    }
    {
      uint64_t len = read_u64(header);
      uint32_t len_crc = read_u32(header + 8);
      if (s->verify_crc && masked_crc(header, 8) != len_crc) {
        set_err("corrupt length crc in %s (record %llu)", s->path,
                s->record_index);
        goto fail;
      }
      // reject a corrupt huge len before trying to allocate it: the payload
      // plus its crc cannot exceed what is left of the file
      long cur = ftell(s->fp);
      fseek(s->fp, 0, SEEK_END);
      long end = ftell(s->fp);
      fseek(s->fp, cur, SEEK_SET);
      if (end < cur || len > (uint64_t)(end - cur) ||
          (uint64_t)(end - cur) - len < 4) {
        set_err("truncated payload in %s (record %llu)", s->path,
                s->record_index);
        goto fail;
      }
      while (buf_len + len > buf_cap) {
        buf_cap *= 2;
        uint8_t* new_buf = (uint8_t*)realloc(buf, buf_cap);
        if (!new_buf) {
          set_err("out of memory growing chunk on %s (record %llu)", s->path,
                  s->record_index);
          goto fail;
        }
        buf = new_buf;
      }
      uint8_t crc_bytes[4];
      if (fread(buf + buf_len, 1, len, s->fp) != len ||
          fread(crc_bytes, 1, 4, s->fp) != 4) {
        set_err("truncated payload in %s (record %llu)", s->path,
                s->record_index);
        goto fail;
      }
      if (s->verify_crc &&
          masked_crc(buf + buf_len, len) != read_u32(crc_bytes)) {
        set_err("corrupt payload crc in %s (record %llu)", s->path,
                s->record_index);
        goto fail;
      }
      if (count == idx_cap) {
        idx_cap *= 2;
        uint64_t* new_offsets =
            (uint64_t*)realloc(offsets, idx_cap * sizeof(uint64_t));
        uint64_t* new_lengths =
            (uint64_t*)realloc(lengths, idx_cap * sizeof(uint64_t));
        if (new_offsets) offsets = new_offsets;
        if (new_lengths) lengths = new_lengths;
        if (!new_offsets || !new_lengths) {
          set_err("out of memory growing chunk index on %s (record %llu)",
                  s->path, s->record_index);
          goto fail;
        }
      }
      offsets[count] = buf_len;
      lengths[count] = len;
      buf_len += len;
      count++;
      s->record_index++;
    }
  }
  if (count == 0) {  // clean EOF with nothing read
    free(buf);
    free(offsets);
    free(lengths);
    return nullptr;
  }
  {
    TfrFile* f = (TfrFile*)malloc(sizeof(TfrFile));
    if (!f) {
      set_err("out of memory for chunk handle on %s (record %llu)", s->path,
              s->record_index);
      goto fail;
    }
    f->buf = buf;
    f->buf_len = buf_len;
    f->offsets = offsets;
    f->lengths = lengths;
    f->count = count;
    return f;
  }
fail:
  free(buf);
  free(offsets);
  free(lengths);
  return nullptr;
}

// ---------------------------------------------------------------------------
// Writer: frame `count` records (concatenated in `payloads`, spans given by
// offsets/lengths) into `path` in one call.
// ---------------------------------------------------------------------------

int tfr_write(const char* path, const uint8_t* payloads, const uint64_t* offsets,
              const uint64_t* lengths, uint64_t count) {
  g_err[0] = 0;
  FILE* fp = fopen(path, "wb");
  if (!fp) {
    set_err("cannot open %s for write (record %llu)", path, 0);
    return -1;
  }
  for (uint64_t i = 0; i < count; i++) {
    uint8_t header[12];
    uint64_t len = lengths[i];
    memcpy(header, &len, 8);
    uint32_t hcrc = masked_crc(header, 8);
    memcpy(header + 8, &hcrc, 4);
    uint32_t pcrc = masked_crc(payloads + offsets[i], len);
    if (fwrite(header, 1, 12, fp) != 12 ||
        fwrite(payloads + offsets[i], 1, len, fp) != len ||
        fwrite(&pcrc, 1, 4, fp) != 4) {
      set_err("short write on %s (record %llu)", path, i);
      fclose(fp);
      return -1;
    }
  }
  if (fclose(fp) != 0) {
    set_err("close failed on %s (record %llu)", path, count);
    return -1;
  }
  return 0;
}

// Standalone crc for tests / cross-validation with the Python codec.
uint32_t tfr_masked_crc32c(const uint8_t* data, uint64_t n) {
  return masked_crc(data, n);
}

}  // extern "C"

// ---------------------------------------------------------------------------
// Baseline JPEG decode + Pillow-exact crop/resize, straight into a caller
// buffer (a shared-memory slab slot). Two decode backends behind one entry
// point:
//
//   * TFR_USE_LIBJPEG (set by the Makefile when jpeglib.h is present): the
//     system libjpeg-turbo — SIMD Huffman/IDCT/upsample/color paths.
//   * otherwise: the portable scalar decoder below — baseline sequential
//     8-bit, Huffman, grayscale/YCbCr with 1x1/2x1/2x2 subsampling. It
//     replicates libjpeg's integer pipeline *exactly* (islow IDCT, fancy
//     triangular chroma upsampling, the fixed-point YCbCr tables), so the
//     two backends are bit-identical on every file they both accept.
//
// Both backends are strict: any corruption libjpeg would only *warn* about
// (truncated entropy data, bad Huffman codes) is a hard error here, so a
// corrupt record is charged against the loader's max_bad_records budget
// identically whether the decode ran natively or through PIL.
//
// The resize stage replicates Pillow's two-pass fixed-point bilinear
// resampler (triangle filter, PRECISION_BITS=22, the `box=` source-rect
// contract) coefficient-for-coefficient: pixels produced here are
// byte-identical to `Image.resize(size, BILINEAR, box=...)` on the same
// raster, which is what lets the Python layer keep PIL as the bit-exactness
// oracle and runtime fallback. TFR_OMIT_JPEG reproduces a pre-JPEG build of
// this library (no jpg_* exports) for the stale-.so fallback tests.

#ifndef TFR_OMIT_JPEG

#include <cmath>

#ifdef TFR_USE_LIBJPEG
#include <csetjmp>
#include <jpeglib.h>
#endif

namespace jpg {

// decoded images are capped well above ImageNet scale but low enough that a
// fuzzed 65k x 65k header cannot drive a multi-GB allocation
const uint64_t kMaxPixels = 1ull << 24;  // 16.7 Mpx (4096 x 4096)

void set_jerr(const char* msg) {
  snprintf(g_err, sizeof(g_err), "jpeg: %s", msg);
}

#ifdef TFR_USE_LIBJPEG

struct ErrMgr {
  jpeg_error_mgr mgr;
  jmp_buf jb;
};

void err_exit(j_common_ptr cinfo) {
  longjmp(((ErrMgr*)cinfo->err)->jb, 1);
}

// corruption warnings (truncated stream, bad Huffman code) become hard
// errors: PIL raises on the same inputs, and the loader's max_bad_records
// budget must charge the record identically in native and PIL modes
void err_emit(j_common_ptr cinfo, int msg_level) {
  if (msg_level == -1) longjmp(((ErrMgr*)cinfo->err)->jb, 1);
}

// malloc'd W*H*3 RGB raster, or nullptr with g_err set
uint8_t* decode_rgb(const uint8_t* data, size_t len, int* W, int* H) {
  jpeg_decompress_struct c;
  ErrMgr err;
  uint8_t* out = nullptr;
  c.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = err_exit;
  err.mgr.emit_message = err_emit;
  if (setjmp(err.jb)) {
    char buf[JMSG_LENGTH_MAX];
    (*c.err->format_message)((j_common_ptr)&c, buf);
    set_jerr(buf);
    jpeg_destroy_decompress(&c);
    free(out);
    return nullptr;
  }
  jpeg_create_decompress(&c);
  jpeg_mem_src(&c, data, (unsigned long)len);
  jpeg_read_header(&c, TRUE);
  if ((uint64_t)c.image_width * c.image_height > kMaxPixels) {
    set_jerr("image too large");
    jpeg_destroy_decompress(&c);
    return nullptr;
  }
  c.out_color_space = JCS_RGB;
  jpeg_start_decompress(&c);
  *W = (int)c.output_width;
  *H = (int)c.output_height;
  out = (uint8_t*)malloc((size_t)*W * *H * 3);
  if (!out) {
    set_jerr("out of memory for raster");
    jpeg_destroy_decompress(&c);
    return nullptr;
  }
  while (c.output_scanline < c.output_height) {
    JSAMPROW row = out + (size_t)c.output_scanline * *W * 3;
    jpeg_read_scanlines(&c, &row, 1);
  }
  jpeg_finish_decompress(&c);
  jpeg_destroy_decompress(&c);
  return out;
}

#else  // scalar fallback decoder

// libjpeg's post-IDCT range limit table, as a function: index the wrapped
// 10-bit value exactly the way prepare_range_limit_table lays it out, so
// even wild out-of-range IDCT outputs clamp identically
inline uint8_t idct_range(int64_t v) {
  int x = (int)(v & 1023);
  if (x < 128) return (uint8_t)(x + 128);
  if (x < 512) return 255;
  if (x < 896) return 0;
  return (uint8_t)(x - 896);
}

inline uint8_t clamp255(int v) {
  return (uint8_t)(v < 0 ? 0 : (v > 255 ? 255 : v));
}

const uint8_t kZigzag[64] = {
    0,  1,  8,  16, 9,  2,  3,  10, 17, 24, 32, 25, 18, 11, 4,  5,
    12, 19, 26, 33, 40, 48, 41, 34, 27, 20, 13, 6,  7,  14, 21, 28,
    35, 42, 49, 56, 57, 50, 43, 36, 29, 22, 15, 23, 30, 37, 44, 51,
    58, 59, 52, 45, 38, 31, 39, 46, 53, 60, 61, 54, 47, 55, 62, 63};

// jpeg_idct_islow's fixed-point constants (CONST_BITS=13)
const int64_t kFix_0_298631336 = 2446, kFix_0_390180644 = 3196,
              kFix_0_541196100 = 4433, kFix_0_765366865 = 6270,
              kFix_0_899976223 = 7373, kFix_1_175875602 = 9633,
              kFix_1_501321110 = 12299, kFix_1_847759065 = 15137,
              kFix_1_961570560 = 16069, kFix_2_053119869 = 16819,
              kFix_2_562915447 = 20995, kFix_3_072711026 = 25172;

inline int64_t descale(int64_t x, int n) {
  return (x + ((int64_t)1 << (n - 1))) >> n;
}

// libjpeg jidctint.c jpeg_idct_islow, verbatim math: coef (natural order) x
// quant -> 8x8 samples at out/stride. 64-bit accumulators match libjpeg's
// JLONG on LP64 hosts (and sidestep signed overflow on fuzzed garbage).
void idct_islow(const int16_t* coef, const uint16_t* quant, uint8_t* out,
                size_t stride) {
  const int kConstBits = 13, kPass1Bits = 2;
  int64_t ws[64];
  for (int ctr = 0; ctr < 8; ctr++) {  // pass 1: columns
    const int16_t* in = coef + ctr;
    const uint16_t* q = quant + ctr;
    int64_t* w = ws + ctr;
    if (!(in[8] | in[16] | in[24] | in[32] | in[40] | in[48] | in[56])) {
      // multiplications, not <<: left-shifting a negative signed value is UB
      int64_t dc = (int64_t)in[0] * q[0] * ((int64_t)1 << kPass1Bits);
      for (int i = 0; i < 8; i++) w[i * 8] = dc;
      continue;
    }
    int64_t z2 = (int64_t)in[16] * q[16], z3 = (int64_t)in[48] * q[48];
    int64_t z1 = (z2 + z3) * kFix_0_541196100;
    int64_t tmp2 = z1 + z3 * (-kFix_1_847759065);
    int64_t tmp3 = z1 + z2 * kFix_0_765366865;
    z2 = (int64_t)in[0] * q[0];
    z3 = (int64_t)in[32] * q[32];
    int64_t tmp0 = (z2 + z3) * ((int64_t)1 << kConstBits);
    int64_t tmp1 = (z2 - z3) * ((int64_t)1 << kConstBits);
    int64_t tmp10 = tmp0 + tmp3, tmp13 = tmp0 - tmp3;
    int64_t tmp11 = tmp1 + tmp2, tmp12 = tmp1 - tmp2;
    tmp0 = (int64_t)in[56] * q[56];
    tmp1 = (int64_t)in[40] * q[40];
    tmp2 = (int64_t)in[24] * q[24];
    tmp3 = (int64_t)in[8] * q[8];
    z1 = tmp0 + tmp3;
    z2 = tmp1 + tmp2;
    z3 = tmp0 + tmp2;
    int64_t z4 = tmp1 + tmp3;
    int64_t z5 = (z3 + z4) * kFix_1_175875602;
    tmp0 *= kFix_0_298631336;
    tmp1 *= kFix_2_053119869;
    tmp2 *= kFix_3_072711026;
    tmp3 *= kFix_1_501321110;
    z1 *= -kFix_0_899976223;
    z2 *= -kFix_2_562915447;
    z3 = z3 * (-kFix_1_961570560) + z5;
    z4 = z4 * (-kFix_0_390180644) + z5;
    tmp0 += z1 + z3;
    tmp1 += z2 + z4;
    tmp2 += z2 + z3;
    tmp3 += z1 + z4;
    w[8 * 0] = descale(tmp10 + tmp3, kConstBits - kPass1Bits);
    w[8 * 7] = descale(tmp10 - tmp3, kConstBits - kPass1Bits);
    w[8 * 1] = descale(tmp11 + tmp2, kConstBits - kPass1Bits);
    w[8 * 6] = descale(tmp11 - tmp2, kConstBits - kPass1Bits);
    w[8 * 2] = descale(tmp12 + tmp1, kConstBits - kPass1Bits);
    w[8 * 5] = descale(tmp12 - tmp1, kConstBits - kPass1Bits);
    w[8 * 3] = descale(tmp13 + tmp0, kConstBits - kPass1Bits);
    w[8 * 4] = descale(tmp13 - tmp0, kConstBits - kPass1Bits);
  }
  for (int ctr = 0; ctr < 8; ctr++) {  // pass 2: rows
    const int64_t* w = ws + ctr * 8;
    uint8_t* o = out + ctr * stride;
    if (!(w[1] | w[2] | w[3] | w[4] | w[5] | w[6] | w[7])) {
      uint8_t dc = idct_range(descale(w[0], kPass1Bits + 3));
      for (int i = 0; i < 8; i++) o[i] = dc;
      continue;
    }
    int64_t z2 = w[2], z3 = w[6];
    int64_t z1 = (z2 + z3) * kFix_0_541196100;
    int64_t tmp2 = z1 + z3 * (-kFix_1_847759065);
    int64_t tmp3 = z1 + z2 * kFix_0_765366865;
    int64_t tmp0 = (w[0] + w[4]) * ((int64_t)1 << kConstBits);
    int64_t tmp1 = (w[0] - w[4]) * ((int64_t)1 << kConstBits);
    int64_t tmp10 = tmp0 + tmp3, tmp13 = tmp0 - tmp3;
    int64_t tmp11 = tmp1 + tmp2, tmp12 = tmp1 - tmp2;
    tmp0 = w[7];
    tmp1 = w[5];
    tmp2 = w[3];
    tmp3 = w[1];
    z1 = tmp0 + tmp3;
    z2 = tmp1 + tmp2;
    z3 = tmp0 + tmp2;
    int64_t z4 = tmp1 + tmp3;
    int64_t z5 = (z3 + z4) * kFix_1_175875602;
    tmp0 *= kFix_0_298631336;
    tmp1 *= kFix_2_053119869;
    tmp2 *= kFix_3_072711026;
    tmp3 *= kFix_1_501321110;
    z1 *= -kFix_0_899976223;
    z2 *= -kFix_2_562915447;
    z3 = z3 * (-kFix_1_961570560) + z5;
    z4 = z4 * (-kFix_0_390180644) + z5;
    tmp0 += z1 + z3;
    tmp1 += z2 + z4;
    tmp2 += z2 + z3;
    tmp3 += z1 + z4;
    const int kShift = kConstBits + kPass1Bits + 3;
    o[0] = idct_range(descale(tmp10 + tmp3, kShift));
    o[7] = idct_range(descale(tmp10 - tmp3, kShift));
    o[1] = idct_range(descale(tmp11 + tmp2, kShift));
    o[6] = idct_range(descale(tmp11 - tmp2, kShift));
    o[2] = idct_range(descale(tmp12 + tmp1, kShift));
    o[5] = idct_range(descale(tmp12 - tmp1, kShift));
    o[3] = idct_range(descale(tmp13 + tmp0, kShift));
    o[4] = idct_range(descale(tmp13 - tmp0, kShift));
  }
}

struct Huff {
  bool present = false;
  uint8_t vals[256];
  int32_t mincode[17], maxcode[18], valptr[17];
  uint8_t look_nbits[256], look_val[256];

  bool build(const uint8_t* counts, const uint8_t* symbols, int nsym) {
    present = true;
    memcpy(vals, symbols, nsym);
    // canonical code assignment (JPEG spec DECODE tables)
    int code = 0, k = 0;
    for (int l = 1; l <= 16; l++) {
      valptr[l] = k;
      mincode[l] = code;
      code += counts[l - 1];
      k += counts[l - 1];
      maxcode[l] = code - 1;
      if (counts[l - 1] == 0) maxcode[l] = -1;
      if (code - 1 >= (1 << l)) return false;  // oversubscribed table
      code <<= 1;
    }
    maxcode[17] = 0x7fffffff;  // sentinel: length-17 lookups always fail
    // 8-bit lookahead table (libjpeg's jpeg_make_d_derived_tbl fast path)
    memset(look_nbits, 0, sizeof(look_nbits));
    int p = 0;
    code = 0;
    for (int l = 1; l <= 8; l++) {
      code = mincode[l];
      for (int i = 0; i < counts[l - 1]; i++, code++, p++) {
        int lookbits = code << (8 - l);
        for (int ctr = 1 << (8 - l); ctr > 0; ctr--, lookbits++) {
          look_nbits[lookbits] = (uint8_t)l;
          look_val[lookbits] = vals[p];
        }
      }
    }
    return true;
  }
};

struct Comp {
  int id = 0, h = 1, v = 1, tq = 0, td = 0, ta = 0;
  int dw = 0, dh = 0;  // downsampled sample dims (pre-upsample)
  int pw = 0, ph = 0;  // padded plane dims (whole MCUs)
  uint8_t* plane = nullptr;
  int pred = 0;  // DC predictor
};

struct Decoder {
  const uint8_t* d;
  size_t n, pos = 0;
  uint16_t qt[4][64];  // natural order
  bool qt_ok[4] = {false, false, false, false};
  Huff hdc[4], hac[4];
  int W = 0, H = 0, ncomp = 0, hmax = 1, vmax = 1, restart_interval = 0;
  Comp comp[3];
  uint32_t bitbuf = 0;
  int bitcnt = 0;
  bool hit_marker = false;  // entropy reader ran into an unexpected marker

  Decoder(const uint8_t* data, size_t len) : d(data), n(len) {}
  ~Decoder() {
    for (int i = 0; i < 3; i++) free(comp[i].plane);
  }

  bool fail(const char* msg) {
    set_jerr(msg);
    return false;
  }

  bool need(size_t k) { return pos + k <= n; }

  int u8() { return d[pos++]; }
  int u16() {
    int v = (d[pos] << 8) | d[pos + 1];
    pos += 2;
    return v;
  }

  // -- entropy-coded bit reader (0xFF00 unstuffing, markers stop the feed) --

  bool fill_bits() {
    while (bitcnt <= 24) {
      if (pos >= n) return false;
      int b = d[pos];
      if (b == 0xff) {
        if (pos + 1 >= n) return false;
        if (d[pos + 1] != 0x00) {
          hit_marker = true;  // restart or premature end-of-scan
          return false;
        }
        pos += 2;
      } else {
        pos += 1;
      }
      bitbuf = (bitbuf << 8) | (uint32_t)b;
      bitcnt += 8;
    }
    return true;
  }

  int get_bits(int s) {  // -1 on truncation
    if (s == 0) return 0;
    if (bitcnt < s && !fill_bits() && bitcnt < s) return -1;
    int v = (int)((bitbuf >> (bitcnt - s)) & ((1u << s) - 1));
    bitcnt -= s;
    return v;
  }

  static int extend(int v, int s) {
    return v < (1 << (s - 1)) ? v - (1 << s) + 1 : v;
  }

  int huff_decode(const Huff& h) {  // -1 on error
    if (bitcnt < 16) fill_bits();
    if (bitcnt >= 8) {
      int look = (int)((bitbuf >> (bitcnt - 8)) & 0xff);
      int nb = h.look_nbits[look];
      if (nb) {
        bitcnt -= nb;
        return h.look_val[look];
      }
    }
    int code = 0, l = 0;
    while (l < 17) {
      l++;
      int bit = get_bits(1);
      if (bit < 0) return -1;
      code = (code << 1) | bit;
      if (l <= 16 && h.maxcode[l] >= 0 && code <= h.maxcode[l])
        return h.vals[h.valptr[l] + code - h.mincode[l]];
    }
    return -1;  // code longer than any table entry: corrupt stream
  }

  bool decode_block(Comp& c, int16_t* coef) {
    memset(coef, 0, 64 * sizeof(int16_t));
    if (!hdc[c.td].present || !hac[c.ta].present) return fail("missing Huffman table");
    int t = huff_decode(hdc[c.td]);
    if (t < 0 || t > 15) return fail("bad DC code");
    if (t) {
      int v = get_bits(t);
      if (v < 0) return fail("truncated entropy data");
      c.pred += extend(v, t);
    }
    coef[0] = (int16_t)c.pred;
    for (int k = 1; k < 64;) {
      int rs = huff_decode(hac[c.ta]);
      if (rs < 0) return fail("bad AC code");
      int r = rs >> 4, s = rs & 15;
      if (s == 0) {
        if (r != 15) break;  // EOB
        k += 16;             // ZRL
        continue;
      }
      k += r;
      if (k > 63) return fail("AC run past block end");
      int v = get_bits(s);
      if (v < 0) return fail("truncated entropy data");
      coef[kZigzag[k]] = (int16_t)extend(v, s);
      k++;
    }
    return true;
  }

  // -- marker parsing -------------------------------------------------------

  bool parse_dqt() {
    if (!need(2)) return fail("truncated DQT");
    int len = u16() - 2;
    while (len > 0) {
      if (!need(1)) return fail("truncated DQT");
      int pq_tq = u8();
      int pq = pq_tq >> 4, tq = pq_tq & 15;
      len -= 1;
      if (pq > 1 || tq > 3) return fail("bad DQT header");
      int nbytes = pq ? 128 : 64;
      if (!need(nbytes) || len < nbytes) return fail("truncated DQT");
      for (int i = 0; i < 64; i++) {
        int v = pq ? u16() : u8();
        if (v == 0) return fail("zero quantizer");
        qt[tq][kZigzag[i]] = (uint16_t)v;
      }
      qt_ok[tq] = true;
      len -= nbytes;
    }
    return true;
  }

  bool parse_dht() {
    if (!need(2)) return fail("truncated DHT");
    int len = u16() - 2;
    while (len > 0) {
      if (len < 17 || !need(17)) return fail("truncated DHT");
      int tc_th = u8();
      int tc = tc_th >> 4, th = tc_th & 15;
      if (tc > 1 || th > 3) return fail("bad DHT header");
      uint8_t counts[16];
      int nsym = 0;
      for (int i = 0; i < 16; i++) {
        counts[i] = (uint8_t)u8();
        nsym += counts[i];
      }
      len -= 17;
      if (nsym > 256 || len < nsym || !need(nsym)) return fail("truncated DHT");
      Huff& h = tc ? hac[th] : hdc[th];
      if (!h.build(counts, d + pos, nsym)) return fail("oversubscribed Huffman table");
      pos += nsym;
      len -= nsym;
    }
    return true;
  }

  bool parse_sof(int marker) {
    if (marker == 0xc2) return fail("progressive JPEG unsupported by scalar decoder");
    if (marker != 0xc0 && marker != 0xc1)
      return fail("unsupported SOF type");
    if (!need(8)) return fail("truncated SOF");
    int len = u16();
    int prec = u8();
    H = u16();
    W = u16();
    ncomp = u8();
    if (prec != 8) return fail("only 8-bit precision supported");
    if (W < 1 || H < 1) return fail("bad dimensions");
    if ((uint64_t)W * H > kMaxPixels) return fail("image too large");
    if (ncomp != 1 && ncomp != 3) return fail("unsupported component count");
    if (len != 8 + 3 * ncomp || !need(3 * (size_t)ncomp)) return fail("bad SOF length");
    for (int i = 0; i < ncomp; i++) {
      comp[i].id = u8();
      int hv = u8();
      comp[i].h = hv >> 4;
      comp[i].v = hv & 15;
      comp[i].tq = u8();
      if (comp[i].h < 1 || comp[i].v < 1 || comp[i].tq > 3)
        return fail("bad component spec");
      if (comp[i].h > hmax) hmax = comp[i].h;
      if (comp[i].v > vmax) vmax = comp[i].v;
    }
    if (ncomp == 1) {
      // single-component scans ignore sampling factors (spec B.2.3; libjpeg
      // normalizes them too) — PIL writes 2x2 here when subsampling is forced
      comp[0].h = comp[0].v = hmax = vmax = 1;
    } else {
      // luma h2v2 / h2v1 / h1v1 with 1x1 chroma: the layouts PIL and every
      // mainstream encoder emit; anything else falls back to PIL
      if (comp[1].h != 1 || comp[1].v != 1 || comp[2].h != 1 || comp[2].v != 1 ||
          comp[0].h > 2 || comp[0].v > 2 || comp[0].v > comp[0].h)
        return fail("unsupported chroma sampling");
    }
    int mcux = (W + hmax * 8 - 1) / (hmax * 8);
    int mcuy = (H + vmax * 8 - 1) / (vmax * 8);
    for (int i = 0; i < ncomp; i++) {
      Comp& c = comp[i];
      c.dw = (W * c.h + hmax - 1) / hmax;
      c.dh = (H * c.v + vmax - 1) / vmax;
      c.pw = mcux * c.h * 8;
      c.ph = mcuy * c.v * 8;
      c.plane = (uint8_t*)malloc((size_t)c.pw * c.ph);
      if (!c.plane) return fail("out of memory for plane");
    }
    return true;
  }

  bool skip_segment() {
    if (!need(2)) return fail("truncated segment");
    int len = u16();
    if (len < 2 || !need((size_t)len - 2)) return fail("truncated segment");
    pos += len - 2;
    return true;
  }

  bool parse_sos_header() {
    if (!need(3)) return fail("truncated SOS");
    u16();  // length
    int ns = u8();
    if (ns != ncomp) return fail("non-interleaved scan unsupported");
    if (!need(2 * (size_t)ns + 3)) return fail("truncated SOS");
    for (int i = 0; i < ns; i++) {
      int cs = u8(), tdta = u8();
      Comp* c = nullptr;
      for (int j = 0; j < ncomp; j++)
        if (comp[j].id == cs) c = &comp[j];
      if (!c) return fail("SOS references unknown component");
      c->td = tdta >> 4;
      c->ta = tdta & 15;
      if (c->td > 3 || c->ta > 3) return fail("bad SOS table selector");
    }
    int ss = u8(), se = u8(), ahal = u8();
    if (ss != 0 || se != 63 || ahal != 0) return fail("non-baseline scan parameters");
    return true;
  }

  bool decode_scan() {
    for (int i = 0; i < ncomp; i++) {
      if (!qt_ok[comp[i].tq]) return fail("missing quant table");
      comp[i].pred = 0;
    }
    int mcux = comp[0].pw / (comp[0].h * 8);
    int mcuy = comp[0].ph / (comp[0].v * 8);
    int16_t coef[64];
    int mcus_to_restart = restart_interval;
    int next_rst = 0;
    for (int my = 0; my < mcuy; my++) {
      for (int mx = 0; mx < mcux; mx++) {
        if (restart_interval && mcus_to_restart == 0) {
          // byte-align, then consume the RSTn marker the feeder stopped at
          bitcnt = 0;
          bitbuf = 0;
          hit_marker = false;
          if (!need(2) || d[pos] != 0xff || d[pos + 1] != (0xd0 | next_rst))
            return fail("missing restart marker");
          pos += 2;
          next_rst = (next_rst + 1) & 7;
          mcus_to_restart = restart_interval;
          for (int i = 0; i < ncomp; i++) comp[i].pred = 0;
        }
        for (int i = 0; i < ncomp; i++) {
          Comp& c = comp[i];
          for (int by = 0; by < c.v; by++) {
            for (int bx = 0; bx < c.h; bx++) {
              if (!decode_block(c, coef)) return false;
              size_t ox = ((size_t)mx * c.h + bx) * 8;
              size_t oy = ((size_t)my * c.v + by) * 8;
              idct_islow(coef, qt[c.tq], c.plane + oy * c.pw + ox, c.pw);
            }
          }
        }
        if (restart_interval) mcus_to_restart--;
      }
    }
    return true;
  }

  bool parse() {
    if (n < 2 || d[0] != 0xff || d[1] != 0xd8) return fail("not a JPEG (no SOI)");
    pos = 2;
    bool have_sof = false;
    while (true) {
      // scan to the next marker, skipping fill bytes
      if (!need(2)) return fail("truncated stream");
      if (d[pos] != 0xff) return fail("garbage between segments");
      while (need(1) && d[pos] == 0xff) pos++;
      if (!need(1)) return fail("truncated stream");
      int marker = u8();
      if (marker == 0xd9) return fail("EOI before image data");
      if (marker == 0xda) {  // SOS
        if (!have_sof) return fail("SOS before SOF");
        if (!parse_sos_header()) return false;
        bitbuf = 0;
        bitcnt = 0;
        hit_marker = false;
        if (!decode_scan()) return false;
        // the stream must close cleanly: byte-align and require EOI (after
        // optional fill bytes) — matching the strict-warning libjpeg path
        bitcnt = 0;
        if (!need(2)) return fail("truncated after scan");
        if (d[pos] != 0xff) return fail("garbage after scan");
        while (need(1) && d[pos] == 0xff) pos++;
        if (!need(1) || u8() != 0xd9) return fail("missing EOI");
        return true;
      }
      switch (marker) {
        case 0xc4:
          if (!parse_dht()) return false;
          break;
        case 0xdb:
          if (!parse_dqt()) return false;
          break;
        case 0xdd:
          if (!need(4)) return fail("truncated DRI");
          u16();
          restart_interval = u16();
          break;
        case 0xc0:
        case 0xc1:
        case 0xc2:
        case 0xc3:
        case 0xc5:
        case 0xc6:
        case 0xc7:
        case 0xc9:
        case 0xca:
        case 0xcb:
        case 0xcd:
        case 0xce:
        case 0xcf:
          if (have_sof) return fail("multiple SOF markers");
          if (!parse_sof(marker)) return false;
          have_sof = true;
          break;
        default:
          if (marker == 0x01 || (marker >= 0xd0 && marker <= 0xd7))
            break;  // standalone markers: no length field
          if (!skip_segment()) return false;
      }
    }
  }
};

// libjpeg jdsample.c h2v1_fancy_upsample, one row: dw input samples (from a
// padded plane row, so the dw<=2 pointer walk reads decoded bytes exactly
// like libjpeg's padded sample buffers) to 2*dw output samples
void h2v1_fancy_row(const uint8_t* in, int dw, uint8_t* out) {
  const uint8_t* inptr = in;
  uint8_t* outptr = out;
  int invalue = *inptr++;
  *outptr++ = (uint8_t)invalue;
  *outptr++ = (uint8_t)((invalue * 3 + *inptr + 2) >> 2);
  for (int colctr = dw - 2; colctr > 0; colctr--) {
    invalue = *inptr++ * 3;
    *outptr++ = (uint8_t)((invalue + inptr[-2] + 1) >> 2);
    *outptr++ = (uint8_t)((invalue + *inptr + 2) >> 2);
  }
  invalue = *inptr;
  *outptr++ = (uint8_t)((invalue * 3 + inptr[-1] + 1) >> 2);
  *outptr++ = (uint8_t)invalue;
}

// libjpeg jdsample.c h2v2_fancy_upsample, one output row: the vertical
// triangle (3*nearer + farther) then the horizontal one, biases 8/7
void h2v2_fancy_row(const uint8_t* near_row, const uint8_t* far_row, int dw,
                    uint8_t* out) {
  const uint8_t *inptr0 = near_row, *inptr1 = far_row;
  uint8_t* outptr = out;
  int thiscolsum = (*inptr0++) * 3 + (*inptr1++);
  int nextcolsum = (*inptr0++) * 3 + (*inptr1++);
  *outptr++ = (uint8_t)((thiscolsum * 4 + 8) >> 4);
  *outptr++ = (uint8_t)((thiscolsum * 3 + nextcolsum + 7) >> 4);
  int lastcolsum = thiscolsum;
  thiscolsum = nextcolsum;
  for (int colctr = dw - 2; colctr > 0; colctr--) {
    nextcolsum = (*inptr0++) * 3 + (*inptr1++);
    *outptr++ = (uint8_t)((thiscolsum * 3 + lastcolsum + 8) >> 4);
    *outptr++ = (uint8_t)((thiscolsum * 3 + nextcolsum + 7) >> 4);
    lastcolsum = thiscolsum;
    thiscolsum = nextcolsum;
  }
  *outptr++ = (uint8_t)((thiscolsum * 3 + lastcolsum + 8) >> 4);
  *outptr++ = (uint8_t)((thiscolsum * 4 + 7) >> 4);
}

// libjpeg jdcolor.c build_ycc_rgb_table + ycc_rgb_convert, SCALEBITS=16
struct YccTables {
  int crr[256], cbb[256], crg[256], cbg[256];
  YccTables() {
    const int64_t kScale = 1 << 16, kHalf = 1 << 15;
    for (int i = 0; i < 256; i++) {
      int x = i - 128;
      crr[i] = (int)(((int64_t)(1.40200 * kScale + 0.5) * x + kHalf) >> 16);
      cbb[i] = (int)(((int64_t)(1.77200 * kScale + 0.5) * x + kHalf) >> 16);
      crg[i] = (int)(-(int64_t)(0.71414 * kScale + 0.5) * x);
      cbg[i] = (int)(-(int64_t)(0.34414 * kScale + 0.5) * x + kHalf);
    }
  }
};

uint8_t* decode_rgb(const uint8_t* data, size_t len, int* W, int* H) {
  Decoder dec(data, len);
  if (!dec.parse()) return nullptr;
  *W = dec.W;
  *H = dec.H;
  size_t w = dec.W, h = dec.H;
  uint8_t* rgb = (uint8_t*)malloc(w * h * 3);
  if (!rgb) {
    set_jerr("out of memory for raster");
    return nullptr;
  }
  if (dec.ncomp == 1) {  // gray_rgb_convert: replicate Y
    const Comp& y = dec.comp[0];
    for (size_t r = 0; r < h; r++) {
      const uint8_t* yr = y.plane + r * y.pw;
      uint8_t* o = rgb + r * w * 3;
      for (size_t c = 0; c < w; c++) {
        o[c * 3] = o[c * 3 + 1] = o[c * 3 + 2] = yr[c];
      }
    }
    return rgb;
  }
  static const YccTables kYcc;
  const Comp& y = dec.comp[0];
  const Comp& cb = dec.comp[1];
  const Comp& cr = dec.comp[2];
  int hexp = y.h, vexp = y.v;  // chroma expansion factors (1 or 2)
  // upsampled chroma row buffers; +2 columns absorb the 4-sample write the
  // first/last special cases emit when dw <= 2 (libjpeg writes into padded
  // row buffers the same way)
  uint8_t* cbrow = (uint8_t*)malloc((size_t)cb.dw * 2 + 2);
  uint8_t* crrow = (uint8_t*)malloc((size_t)cr.dw * 2 + 2);
  if (!cbrow || !crrow) {
    free(cbrow);
    free(crrow);
    free(rgb);
    set_jerr("out of memory for chroma rows");
    return nullptr;
  }
  // libjpeg-turbo only selects the fancy (triangle) upsamplers when
  // downsampled_width > 2; tiny widths take the plain replication
  // upsampler instead (jdsample.c start_pass) — mirror that exactly
  bool fancy = cb.dw > 2;
  for (size_t r = 0; r < h; r++) {
    const uint8_t *cbr, *crr;
    if (hexp == 2 && !fancy) {  // h2v2_upsample / h2v1_upsample: replicate
      size_t inrow = (vexp == 2) ? (r >> 1) : r;
      const uint8_t* cbp = cb.plane + inrow * cb.pw;
      const uint8_t* crp = cr.plane + inrow * cr.pw;
      for (int x = 0; x < cb.dw; x++) {
        cbrow[x * 2] = cbrow[x * 2 + 1] = cbp[x];
        crrow[x * 2] = crrow[x * 2 + 1] = crp[x];
      }
      cbr = cbrow;
      crr = crrow;
    } else if (hexp == 2 && vexp == 2) {
      size_t inrow = r >> 1;
      // context row with edge duplication (jdmainct's duplicated rows)
      size_t other = (r & 1) ? (inrow + 1 < (size_t)cb.dh ? inrow + 1 : inrow)
                             : (inrow > 0 ? inrow - 1 : inrow);
      h2v2_fancy_row(cb.plane + inrow * cb.pw, cb.plane + other * cb.pw, cb.dw, cbrow);
      h2v2_fancy_row(cr.plane + inrow * cr.pw, cr.plane + other * cr.pw, cr.dw, crrow);
      cbr = cbrow;
      crr = crrow;
    } else if (hexp == 2) {  // h2v1
      h2v1_fancy_row(cb.plane + r * cb.pw, cb.dw, cbrow);
      h2v1_fancy_row(cr.plane + r * cr.pw, cr.dw, crrow);
      cbr = cbrow;
      crr = crrow;
    } else {  // h1v1: direct
      cbr = cb.plane + r * cb.pw;
      crr = cr.plane + r * cr.pw;
    }
    const uint8_t* yr = y.plane + r * y.pw;
    uint8_t* o = rgb + r * w * 3;
    for (size_t c = 0; c < w; c++) {
      int yy = yr[c], vcb = cbr[c], vcr = crr[c];
      o[c * 3 + 0] = clamp255(yy + kYcc.crr[vcr]);
      o[c * 3 + 1] = clamp255(yy + ((kYcc.cbg[vcb] + kYcc.crg[vcr]) >> 16));
      o[c * 3 + 2] = clamp255(yy + kYcc.cbb[vcb]);
    }
  }
  free(cbrow);
  free(crrow);
  return rgb;
}

#endif  // TFR_USE_LIBJPEG

// ---------------------------------------------------------------------------
// Pillow-exact bilinear resample (Resample.c, the 8bpc fixed-point path):
// precompute_coeffs + normalize_coeffs_8bpc reproduced bit-for-bit, with the
// `box=` source-rect contract and an output *window* so an eval-style
// "resize then center crop" evaluates only the cropped rows/columns (each
// output pixel depends only on its own coefficients, so the window is
// byte-identical to resize-then-crop).
// ---------------------------------------------------------------------------

const int kPrecisionBits = 32 - 8 - 2;

inline uint8_t resample_clip8(int v) {
  if (v >= (1 << kPrecisionBits << 8)) return 255;
  if (v <= 0) return 0;
  return (uint8_t)(v >> kPrecisionBits);
}

double bilinear_filter(double x) {
  if (x < 0.0) x = -x;
  if (x < 1.0) return 1.0 - x;
  return 0.0;
}

// Pillow precompute_coeffs for the bilinear filter (support 1.0), already
// normalized to the fixed-point integers of normalize_coeffs_8bpc. Returns
// ksize (coeffs per output pixel), or 0 on allocation failure.
int precompute_coeffs(int in_size, double in0, double in1, int out_size,
                      int* bounds, int** kk_out) {
  double filterscale, scale;
  filterscale = scale = (in1 - in0) / out_size;
  if (filterscale < 1.0) filterscale = 1.0;
  double support = 1.0 * filterscale;
  int ksize = (int)ceil(support) * 2 + 1;
  double* prekk = (double*)malloc(sizeof(double) * out_size * ksize);
  int* kk = (int*)malloc(sizeof(int) * out_size * ksize);
  if (!prekk || !kk) {
    free(prekk);
    free(kk);
    return 0;
  }
  for (int xx = 0; xx < out_size; xx++) {
    double center = in0 + (xx + 0.5) * scale;
    double ww = 0.0;
    double ss = 1.0 / filterscale;
    int xmin = (int)(center - support + 0.5);
    if (xmin < 0) xmin = 0;
    int xmax = (int)(center + support + 0.5);
    if (xmax > in_size) xmax = in_size;
    xmax -= xmin;
    double* k = prekk + (size_t)xx * ksize;
    int x;
    for (x = 0; x < xmax; x++) {
      double w = bilinear_filter((x + xmin - center + 0.5) * ss) * ss;
      k[x] = w;
      ww += w;
    }
    for (x = 0; x < xmax; x++) {
      if (ww != 0.0) k[x] /= ww;
    }
    for (; x < ksize; x++) k[x] = 0;
    bounds[xx * 2 + 0] = xmin;
    bounds[xx * 2 + 1] = xmax;
  }
  for (int i = 0; i < out_size * ksize; i++) {
    if (prekk[i] < 0) {
      kk[i] = (int)(-0.5 + prekk[i] * (1 << kPrecisionBits));
    } else {
      kk[i] = (int)(0.5 + prekk[i] * (1 << kPrecisionBits));
    }
  }
  free(prekk);
  *kk_out = kk;
  return ksize;
}

// in: [in_h, in_w, 3] RGB. Resize box (bx0..by1) to (rw, rh), emit the
// (ox, oy, ow, oh) window of that resize — optionally mirrored — into out
// (out_stride bytes between rows). Returns 0, or -1 with g_err set.
int resample_window(const uint8_t* in, int in_w, int in_h, double bx0,
                    double by0, double bx1, double by1, int rw, int rh,
                    int ox, int oy, int ow, int oh, int flip, uint8_t* out,
                    int64_t out_stride) {
  int* hb_full = (int*)malloc(sizeof(int) * 2 * rw);
  int* vb_full = (int*)malloc(sizeof(int) * 2 * rh);
  int *kkh_full = nullptr, *kkv_full = nullptr;
  uint8_t* tmp = nullptr;
  int rc = -1;
  if (!hb_full || !vb_full) {
    set_jerr("out of memory for resample bounds");
    goto done;
  }
  {
    int hks = precompute_coeffs(in_w, bx0, bx1, rw, hb_full, &kkh_full);
    int vks = precompute_coeffs(in_h, by0, by1, rh, vb_full, &kkv_full);
    if (!hks || !vks) {
      set_jerr("out of memory for resample coeffs");
      goto done;
    }
    const int* hb = hb_full + 2 * (size_t)ox;
    const int* kkh = kkh_full + (size_t)hks * ox;
    const int* vb = vb_full + 2 * (size_t)oy;
    const int* kkv = kkv_full + (size_t)vks * oy;
    // source rows the window's vertical pass touches
    int ybox_first = vb[0], ybox_last = 0;
    for (int y = 0; y < oh; y++) {
      if (vb[y * 2] < ybox_first) ybox_first = vb[y * 2];
      if (vb[y * 2] + vb[y * 2 + 1] > ybox_last) ybox_last = vb[y * 2] + vb[y * 2 + 1];
    }
    int tmp_h = ybox_last - ybox_first;
    tmp = (uint8_t*)malloc((size_t)tmp_h * ow * 3);
    if (!tmp) {
      set_jerr("out of memory for resample temp");
      goto done;
    }
    for (int yy = 0; yy < tmp_h; yy++) {  // horizontal pass
      const uint8_t* row = in + (size_t)(yy + ybox_first) * in_w * 3;
      uint8_t* trow = tmp + (size_t)yy * ow * 3;
      for (int xx = 0; xx < ow; xx++) {
        int xmin = hb[xx * 2], xmax = hb[xx * 2 + 1];
        const int* k = kkh + (size_t)xx * hks;
        int s0 = 1 << (kPrecisionBits - 1), s1 = s0, s2 = s0;
        for (int x = 0; x < xmax; x++) {
          const uint8_t* p = row + (size_t)(x + xmin) * 3;
          s0 += p[0] * k[x];
          s1 += p[1] * k[x];
          s2 += p[2] * k[x];
        }
        trow[xx * 3 + 0] = resample_clip8(s0);
        trow[xx * 3 + 1] = resample_clip8(s1);
        trow[xx * 3 + 2] = resample_clip8(s2);
      }
    }
    for (int yy = 0; yy < oh; yy++) {  // vertical pass (+ optional mirror)
      int ymin = vb[yy * 2] - ybox_first, ymax = vb[yy * 2 + 1];
      const int* k = kkv + (size_t)yy * vks;
      uint8_t* orow = out + (size_t)yy * out_stride;
      for (int xx = 0; xx < ow; xx++) {
        int s0 = 1 << (kPrecisionBits - 1), s1 = s0, s2 = s0;
        for (int y = 0; y < ymax; y++) {
          const uint8_t* p = tmp + ((size_t)(y + ymin) * ow + xx) * 3;
          s0 += p[0] * k[y];
          s1 += p[1] * k[y];
          s2 += p[2] * k[y];
        }
        int dx = flip ? (ow - 1 - xx) : xx;
        orow[(size_t)dx * 3 + 0] = resample_clip8(s0);
        orow[(size_t)dx * 3 + 1] = resample_clip8(s1);
        orow[(size_t)dx * 3 + 2] = resample_clip8(s2);
      }
    }
    rc = 0;
  }
done:
  free(tmp);
  free(hb_full);
  free(vb_full);
  free(kkh_full);
  free(kkv_full);
  return rc;
}

}  // namespace jpg

#define TFR_STRINGIZE_(x) #x
#define TFR_STRINGIZE(x) TFR_STRINGIZE_(x)

extern "C" {

// Compile-time build fingerprint: which decode backend this .so carries.
// Asserted by tests so a stale scalar build on a libjpeg host is visible.
const char* tfr_build_info() {
#ifdef TFR_USE_LIBJPEG
  return "tfrecord_io jpeg=libjpeg-turbo api=" TFR_STRINGIZE(JPEG_LIB_VERSION);
#else
  return "tfrecord_io jpeg=scalar";
#endif
}

// Header-only probe: image dimensions without a full decode. Returns 0, or
// -1 with tfr_last_error set.
int32_t jpg_info(const uint8_t* data, int64_t len, int32_t* w, int32_t* h) {
  g_err[0] = 0;
#ifdef TFR_USE_LIBJPEG
  jpeg_decompress_struct c;
  jpg::ErrMgr err;
  c.err = jpeg_std_error(&err.mgr);
  err.mgr.error_exit = jpg::err_exit;
  err.mgr.emit_message = jpg::err_emit;
  if (setjmp(err.jb)) {
    char buf[JMSG_LENGTH_MAX];
    (*c.err->format_message)((j_common_ptr)&c, buf);
    jpg::set_jerr(buf);
    jpeg_destroy_decompress(&c);
    return -1;
  }
  jpeg_create_decompress(&c);
  jpeg_mem_src(&c, data, (unsigned long)len);
  jpeg_read_header(&c, TRUE);
  *w = (int32_t)c.image_width;
  *h = (int32_t)c.image_height;
  jpeg_destroy_decompress(&c);
  return 0;
#else
  // walk markers through the whole header, the way jpeg_read_header does:
  // dims come from SOF, but success requires reaching SOS with every segment
  // intact — a stream truncated inside its tables errors in BOTH variants
  if (len < 2 || data[0] != 0xff || data[1] != 0xd8) {
    jpg::set_jerr("not a JPEG (no SOI)");
    return -1;
  }
  size_t pos = 2;
  bool have_dims = false;
  while (true) {
    if (pos >= (size_t)len) {
      jpg::set_jerr("truncated stream");
      return -1;
    }
    if (data[pos] != 0xff) {
      jpg::set_jerr("garbage between segments");
      return -1;
    }
    while (pos < (size_t)len && data[pos] == 0xff) pos++;
    if (pos >= (size_t)len) {
      jpg::set_jerr("truncated stream");
      return -1;
    }
    int marker = data[pos++];
    if (marker == 0xd9) {
      jpg::set_jerr("EOI before image data");
      return -1;
    }
    if (marker == 0xda) {
      if (!have_dims) {
        jpg::set_jerr("SOS before SOF");
        return -1;
      }
      return 0;
    }
    if (marker == 0x01 || (marker >= 0xd0 && marker <= 0xd7)) continue;
    if (pos + 2 > (size_t)len) {
      jpg::set_jerr("truncated segment");
      return -1;
    }
    int seglen = (data[pos] << 8) | data[pos + 1];
    if (seglen < 2 || pos + (size_t)seglen > (size_t)len) {
      jpg::set_jerr("truncated segment");
      return -1;
    }
    if ((marker >= 0xc0 && marker <= 0xcf) && marker != 0xc4 && marker != 0xc8 &&
        marker != 0xcc) {
      if (seglen < 8) {
        jpg::set_jerr("bad SOF length");
        return -1;
      }
      *h = (int32_t)((data[pos + 3] << 8) | data[pos + 4]);
      *w = (int32_t)((data[pos + 5] << 8) | data[pos + 6]);
      if (*w < 1 || *h < 1) {
        jpg::set_jerr("bad dimensions");
        return -1;
      }
      have_dims = true;
    }
    pos += (size_t)seglen;
  }
#endif
}

// Decode `data`, resize the source rect (bx0,by0)-(bx1,by1) to (rw, rh)
// with Pillow's bilinear resampler, and write the (ox, oy, ow, oh) window
// of that resize — h-mirrored when flip — into `out` (uint8 RGB rows,
// `out_stride` bytes apart: a shared-memory slab slot). Returns 0, or -1
// with tfr_last_error set (corrupt stream, unsupported coding, bad params).
int32_t jpg_decode_window(const uint8_t* data, int64_t len, double bx0,
                          double by0, double bx1, double by1, int32_t rw,
                          int32_t rh, int32_t ox, int32_t oy, int32_t ow,
                          int32_t oh, int32_t flip, uint8_t* out,
                          int64_t out_stride) {
  g_err[0] = 0;
  int W = 0, H = 0;
  if (rw < 1 || rh < 1 || ow < 1 || oh < 1 || ox < 0 || oy < 0 ||
      ox + ow > rw || oy + oh > rh) {
    jpg::set_jerr("bad resize/window geometry");
    return -1;
  }
  uint8_t* rgb = jpg::decode_rgb(data, (size_t)len, &W, &H);
  if (!rgb) return -1;
  int rc = -1;
  if (!(bx0 >= 0 && by0 >= 0 && bx1 <= W && by1 <= H && bx0 < bx1 && by0 < by1)) {
    jpg::set_jerr("resize box outside the decoded image");
  } else {
    rc = jpg::resample_window(rgb, W, H, bx0, by0, bx1, by1, rw, rh, ox, oy,
                              ow, oh, flip, out, out_stride);
  }
  free(rgb);
  return rc;
}

}  // extern "C"

#endif  // TFR_OMIT_JPEG
