#!/usr/bin/env bash
# One-shot test harness (the reference's test/run_tests.sh analogue, which
# booted a 2-worker local Spark Standalone cluster around unittest discover).
#
# Without pyspark: the suite runs against the bundled local multi-process
# backend (the Spark stand-in; same executor-process semantics).
# With pyspark installed: additionally boots a local-cluster master so the
# integration tests can target real Spark executors.
#
# Usage: ./run_tests.sh [--quick] [--chaos] [--perf-smoke] [--trace-smoke]
#                       [--analyze] [--native-sanitize] [--multichip]
#                       [extra pytest args]
#   --quick       run the quick tier only (pytest -m 'not slow')
#   --chaos       run the quick tier under a fixed low-probability ChaosPlan and
#                 assert that at least one fault was actually injected
#   --trace-smoke run the tracing-plane end-to-end leg: a 1-executor train
#                 with TOS_TRACE_DIR set (flight shards from driver, executor,
#                 and jax child) under a benign one-shot chaos fault, then
#                 merge the shards and validate the Chrome trace schema
#                 (required keys, monotone ts per track, matched B/E pairs)
#                 and that the fault force-dumped a flight ring
#   --multichip   run only the multi-process gloo legs: 2-rank host all-reduce
#                 determinism + bucketed-overlap smoke (always), and the 4-rank
#                 weak-scaling smoke (skips cleanly on hosts under 4 cores
#                 where four lockstep jax processes just timeshare one core)
#   --perf-smoke  run only the perf_smoke marker leg: structural pipelining
#                 assertions (sleep-staged IO/parse overlap — proves the
#                 read-ahead actually overlaps, no absolute-throughput flake)
#                 plus the adaptive-feed leg (sleep-staged data.device_link
#                 latency: the autotuner must ratchet K up under injected
#                 latency and bring it back down when the latency clears)
#                 plus the async-checkpoint overlap leg (a ckpt.write_slow
#                 stall holds the background writer while the training loop
#                 keeps stepping — tests/test_ckpt_chaos.py::TestOverlap)
#   --analyze     write the full tosa static-analysis report to
#                 tosa-report.json and tosa-report.sarif (SARIF 2.1.0 for
#                 code-scanning upload), print the JSON, and exit
#   --native-sanitize  rebuild native/tfrecord_io.cc with ASan+UBSan and run
#                 the native IO / streaming-chunk / JPEG-decode tests against
#                 it — including the header-fuzz loop (truncated and overlong
#                 JPEG streams, lying segment lengths) over the in-tree scalar
#                 decoder, which the sanitize build selects by not defining
#                 TFR_USE_LIBJPEG (skips cleanly when no g++ toolchain is
#                 present)
set -euo pipefail
cd "$(dirname "$0")"

CHAOS=0
PERF_SMOKE=0
TRACE_SMOKE=0
NATIVE_SANITIZE=0
MULTICHIP=0
EXTRA=()
for arg in "$@"; do
  if [[ "$arg" == "--quick" ]]; then
    EXTRA+=(-m "not slow")
  elif [[ "$arg" == "--chaos" ]]; then
    CHAOS=1
    EXTRA+=(-m "not slow")
  elif [[ "$arg" == "--perf-smoke" ]]; then
    PERF_SMOKE=1
  elif [[ "$arg" == "--trace-smoke" ]]; then
    TRACE_SMOKE=1
  elif [[ "$arg" == "--analyze" ]]; then
    exec python -m tosa --json --out tosa-report.json --sarif-out tosa-report.sarif
  elif [[ "$arg" == "--native-sanitize" ]]; then
    NATIVE_SANITIZE=1
  elif [[ "$arg" == "--multichip" ]]; then
    MULTICHIP=1
  else
    EXTRA+=("$arg")
  fi
done

# static-analysis gate, two-phase (per-file walks + project-wide index,
# phase 1 parallel over min(4, cpu) workers): jit purity/host-sync, retry
# & lock discipline, lock-order deadlock detection, chaos-obs coverage,
# import hygiene, donation safety, the metrics contract, trace discipline,
# commit discipline (crash consistency), thread lifecycle, and the env-lane
# wiring (rule catalog: docs/analysis.md)
python -m tosa

export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi

if [[ "$NATIVE_SANITIZE" == "1" ]]; then
  CXX="${CXX:-g++}"
  if ! command -v "$CXX" >/dev/null 2>&1; then
    echo "native-sanitize leg SKIPPED: no C++ toolchain ($CXX not found)"
    exit 0
  fi
  SAN_DIR="$(mktemp -d /tmp/tos_native_san.XXXXXX)"
  trap 'rm -rf "$SAN_DIR"' EXIT
  echo "native-sanitize leg: building ASan+UBSan libtfrecord_io.so in $SAN_DIR"
  "$CXX" -O1 -g -fPIC -std=c++17 -shared \
    -fsanitize=address,undefined -fno-sanitize-recover=all \
    -o "$SAN_DIR/libtfrecord_io.so" native/tfrecord_io.cc
  export TOS_NATIVE_LIB="$SAN_DIR/libtfrecord_io.so"
  # python itself is not ASan-instrumented, so the runtime must be preloaded;
  # leak checking is off because the interpreter "leaks" by design at exit
  ASAN_RT="$("$CXX" -print-file-name=libasan.so)"
  UBSAN_RT="$("$CXX" -print-file-name=libubsan.so)"
  export LD_PRELOAD="$ASAN_RT $UBSAN_RT"
  export ASAN_OPTIONS="detect_leaks=0:abort_on_error=1"
  exec python -m pytest tests/test_native_io.py tests/test_loader_pipeline.py -q \
    ${EXTRA[@]+"${EXTRA[@]}"}
fi

if python -c "import pyspark" 2>/dev/null; then
  echo "pyspark available: running with TOS_TEST_PYSPARK=1 (local-cluster[2,1,1024])"
  export TOS_TEST_PYSPARK=1
  export MASTER="local-cluster[2,1,1024]"
else
  echo "pyspark not installed: using the bundled local multi-process backend"
fi

if [[ "$MULTICHIP" == "1" ]]; then
  # multi-process gloo legs (tests/test_multichip.py): 2-rank host
  # all-reduce determinism + bucketed-overlap bit-identity smoke runs
  # everywhere; the 4-rank weak-scaling smoke marks itself skipped below
  # 4 cores (four lockstep jax worlds on one core prove nothing). The
  # model-axis legs (tests/test_model_axes.py) ride along: fast dp×tp and
  # 1F1B-pipeline numeric-parity gates on forced cpu devices, plus the
  # 2-rank dp×tp gloo world
  exec python -m pytest tests/test_multichip.py tests/test_model_axes.py -q \
    -m "not chaos" ${EXTRA[@]+"${EXTRA[@]}"}
fi

if [[ "$PERF_SMOKE" == "1" ]]; then
  # covers the IO/parse overlap proof, the autotune adaptation leg
  # (tests/test_autotune.py::TestChaosDeviceLink) — both sleep-staged, no
  # real accelerator or absolute-throughput assertion involved — the
  # decode-plane GIL-release leg (tests/test_decode_plane.py::TestGilRelease:
  # process workers must beat one thread on a CPU-bound parse; skips
  # cleanly on hosts with fewer than 4 cores where the race is meaningless),
  # and the lm leg (tests/test_text_pipeline.py::TestPerfSmokeLM: a tiny
  # transformer fine-tunes through the packed TextPipeline and the
  # train-vs-input-only pair methodology must yield a valid, non-discarded
  # pair — the BENCH_MODE=lm shape in miniature)
  exec python -m pytest tests/ -q -m perf_smoke ${EXTRA[@]+"${EXTRA[@]}"}
fi

if [[ "$TRACE_SMOKE" == "1" ]]; then
  # tracing-plane end-to-end proof: a 1-executor train records flight shards
  # from every tier (driver, Spark executor, jax child), a benign one-shot
  # chaos fault forces a ring dump, and the merged Chrome trace must pass
  # schema validation with the lifecycle spans and the dump marker present
  # on one trace id.
  export TOS_TRACE_DIR="$(mktemp -d /tmp/tos_trace_smoke.XXXXXX)"
  export TOS_CHAOS_PLAN='{"seed": 7, "sites": {"feed.stall": {"probability": 1.0, "max_count": 1, "delay_s": 0.01}}}'
  echo "trace-smoke leg: recording under $TOS_TRACE_DIR"
  python -m pytest tests/test_trace_smoke.py -q
  python -m tensorflowonspark_tpu.obs.tracemerge --dir "$TOS_TRACE_DIR" \
    --check --summary \
    --require-span node_main --require-span feed_wave \
    --require-event flight_dump --require-same-trace
  echo "trace-smoke leg: merged Chrome trace at $TOS_TRACE_DIR/trace.json"
  exit 0
fi

if [[ "$CHAOS" == "1" ]]; then
  # recovery-ladder legs (first, before the benign env plan is exported —
  # each test installs its own single-victim plan): node.kill drives the
  # shrink direction (blacklist after repeated loss, shrink-to-fit
  # relaunch, resharded resume), and the once-latched preempt→drain→regrow
  # run drives the grow direction (mid-run regrow poll re-probes the
  # recovered victim, posts a preemption warning, the drained workers part
  # cleanly and the ladder relaunches at full size) — recovery counters
  # asserted from the merged cluster metrics in both.
  #
  # All ladder legs and the watchdog lease-expiry leg record into one
  # flight root on one pinned trace id (tracing.mint adopts TOS_TRACE_ID),
  # so the victim child's last spans, the watchdog's lease_expired verdict,
  # the regrow poll's elastic_regrow span, the children's preempt_drain
  # events, and the ladder's relaunch spans land on ONE causally-ordered
  # timeline — asserted post-hoc by tracemerge --check below.
  export TOS_TRACE_DIR="$(mktemp -d /tmp/tos_trace_chaos.XXXXXX)"
  export TOS_TRACE_ID="$(python -c 'import secrets; print(secrets.token_hex(16))')"
  echo "chaos leg: recovery-ladder runs: node.kill shrink + preempt-drain regrow (flight recording at $TOS_TRACE_DIR)"
  python -m pytest tests/test_elastic.py -q -m "chaos and slow"
  echo "chaos leg: watchdog lease-expiry run (same trace id)"
  python -m pytest "tests/test_watchdog.py::test_lease_expiry_names_the_executor_for_the_ledger" -q
  python -m tensorflowonspark_tpu.obs.tracemerge --dir "$TOS_TRACE_DIR" --check \
    --require-span node_main --require-span elastic_relaunch \
    --require-span elastic_regrow --require-event preempt_drain \
    --require-event lease_expired --require-same-trace
  echo "chaos leg: flight recording merged clean ($TOS_TRACE_DIR/trace.json)"
  unset TOS_TRACE_DIR TOS_TRACE_ID
  # control-plane leg (also self-installed plans): control.driver_crash
  # drops the membership registry mid-watch (after control.journal_tear
  # tore the manifest publish) — recovery replays the journal, re-adopts
  # every live lease with zero relaunches and a bumped epoch; plus the
  # benign control.lease_delay run. Asserted from merged cluster metrics.
  echo "chaos leg: control.driver_crash registry-recovery run"
  python -m pytest tests/test_chaos_control.py -q -m "chaos and slow"
  # serving-mesh leg (self-installed plan): serving.replica_kill SIGKILLs
  # one of three replicas under sustained client load — the router must
  # fail every affected request over (cluster.metrics() shows
  # serving_failovers_total > 0) with zero client-visible errors, the
  # replicas_active gauge dips and recovers, and the dead lease expires.
  echo "chaos leg: serving.replica_kill mesh-failover run"
  python -m pytest tests/test_chaos_mesh.py -q -m "chaos and slow"
  # comm-plane leg (self-installed plan): comm.link_delay makes one rank's
  # host all-reduces straggle — the 2-rank world must degrade gracefully
  # (bit-identical losses, steps complete) and the straggler must be
  # visible in the per-rank step-time spread bucketed overlap reports.
  echo "chaos leg: comm.link_delay straggler run"
  python -m pytest tests/test_multichip.py -q -m "chaos and slow"
  # text-plane leg (self-installed plans): data.tokenize_error swaps records
  # for invalid UTF-8 on a live cluster — the skips must be charged against
  # max_bad_records and surface as chaos_fault_data_tokenize_error_total /
  # text_tokenize_errors_total in the merged cluster metrics; data.pack_stall
  # delays inside packing and the stall classifier must call the job
  # input-bound.
  echo "chaos leg: text-plane tokenize_error/pack_stall run"
  python -m pytest tests/test_chaos_text.py -q -m chaos
  # store leg (self-installed plans): store.read_error must be absorbed by
  # the store retry budget with the stream byte-identical, store.remote_stall
  # must land in shard-read time (io_bound classification), and a
  # store.prefetch_tear'd staged shard must be rejected by verify-on-read
  # and re-fetched cold — all against the in-process HTTP fixture.
  echo "chaos leg: store read_error/remote_stall/prefetch_tear run"
  python -m pytest tests/test_store.py -q -m chaos
  # Benign-in-outcome sites at low probability: the suite's assertions
  # must keep passing — most sites only perturb timing; data.decode_kill
  # SIGKILLs a decode worker, which the plane's respawn-and-release
  # protocol must absorb without losing or duplicating a row. Error
  # faults get exercised deterministically by tests/test_chaos_*.py.
  export TOS_CHAOS_PLAN='{"seed": 2024, "sites": {
    "feed.stall":           {"probability": 0.02, "max_count": null, "delay_s": 0.01},
    "feed.slow_consumer":   {"probability": 0.02, "max_count": null, "delay_s": 0.01},
    "data.producer_delay":  {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "data.shard_read":      {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "data.decode_kill":     {"probability": 0.05, "max_count": null},
    "data.cache_tear":      {"probability": 0.05, "max_count": null},
    "data.readahead_stall": {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "data.pack_stall":      {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "store.read_error":     {"probability": 0.02, "max_count": null},
    "store.remote_stall":   {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "store.prefetch_tear":  {"probability": 0.05, "max_count": null},
    "serving.latency":      {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "reservation.slow_accept": {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "control.lease_delay":  {"probability": 0.05, "max_count": null, "delay_s": 0.005},
    "comm.link_delay":      {"probability": 0.05, "max_count": null, "delay_s": 0.005, "victim": 0},
    "ckpt.snapshot_stall":  {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "ckpt.write_slow":      {"probability": 0.05, "max_count": null, "delay_s": 0.01}
  }}'
  export TOS_CHAOS_LOG="$(mktemp /tmp/tos_chaos_log.XXXXXX)"
  echo "chaos leg: plan active, fault log at $TOS_CHAOS_LOG"
  python -m pytest tests/ -q ${EXTRA[@]+"${EXTRA[@]}"}
  if [[ ! -s "$TOS_CHAOS_LOG" ]]; then
    echo "chaos leg FAILED: no faults were injected (empty $TOS_CHAOS_LOG)" >&2
    exit 1
  fi
  echo "chaos leg: $(wc -l < "$TOS_CHAOS_LOG") fault(s) injected"
  exit 0
fi

exec python -m pytest tests/ -q ${EXTRA[@]+"${EXTRA[@]}"}
