#!/usr/bin/env bash
# One-shot test harness (the reference's test/run_tests.sh analogue, which
# booted a 2-worker local Spark Standalone cluster around unittest discover).
#
# Without pyspark: the suite runs against the bundled local multi-process
# backend (the Spark stand-in; same executor-process semantics).
# With pyspark installed: additionally boots a local-cluster master so the
# integration tests can target real Spark executors.
#
# Usage: ./run_tests.sh [--quick] [--chaos] [--perf-smoke] [extra pytest args]
#   --quick       run the quick tier only (pytest -m 'not slow')
#   --chaos       run the quick tier under a fixed low-probability ChaosPlan and
#                 assert that at least one fault was actually injected
#   --perf-smoke  run only the perf_smoke marker leg: structural pipelining
#                 assertions (sleep-staged IO/parse overlap — proves the
#                 read-ahead actually overlaps, no absolute-throughput flake)
set -euo pipefail
cd "$(dirname "$0")"

CHAOS=0
PERF_SMOKE=0
EXTRA=()
for arg in "$@"; do
  if [[ "$arg" == "--quick" ]]; then
    EXTRA+=(-m "not slow")
  elif [[ "$arg" == "--chaos" ]]; then
    CHAOS=1
    EXTRA+=(-m "not slow")
  elif [[ "$arg" == "--perf-smoke" ]]; then
    PERF_SMOKE=1
  else
    EXTRA+=("$arg")
  fi
done

# lint gate: library modules must not configure logging at import time
python scripts/check_no_basicconfig.py

export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi

if python -c "import pyspark" 2>/dev/null; then
  echo "pyspark available: running with TOS_TEST_PYSPARK=1 (local-cluster[2,1,1024])"
  export TOS_TEST_PYSPARK=1
  export MASTER="local-cluster[2,1,1024]"
else
  echo "pyspark not installed: using the bundled local multi-process backend"
fi

if [[ "$PERF_SMOKE" == "1" ]]; then
  exec python -m pytest tests/ -q -m perf_smoke ${EXTRA[@]+"${EXTRA[@]}"}
fi

if [[ "$CHAOS" == "1" ]]; then
  # Benign (delay-only) sites at low probability: the suite's assertions
  # must keep passing — chaos here perturbs timing, not outcomes. Error
  # faults get exercised deterministically by tests/test_chaos_*.py.
  export TOS_CHAOS_PLAN='{"seed": 2024, "sites": {
    "feed.stall":           {"probability": 0.02, "max_count": null, "delay_s": 0.01},
    "feed.slow_consumer":   {"probability": 0.02, "max_count": null, "delay_s": 0.01},
    "data.producer_delay":  {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "data.shard_read":      {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "serving.latency":      {"probability": 0.05, "max_count": null, "delay_s": 0.01},
    "reservation.slow_accept": {"probability": 0.05, "max_count": null, "delay_s": 0.01}
  }}'
  export TOS_CHAOS_LOG="$(mktemp /tmp/tos_chaos_log.XXXXXX)"
  echo "chaos leg: plan active, fault log at $TOS_CHAOS_LOG"
  python -m pytest tests/ -q ${EXTRA[@]+"${EXTRA[@]}"}
  if [[ ! -s "$TOS_CHAOS_LOG" ]]; then
    echo "chaos leg FAILED: no faults were injected (empty $TOS_CHAOS_LOG)" >&2
    exit 1
  fi
  echo "chaos leg: $(wc -l < "$TOS_CHAOS_LOG") fault(s) injected"
  exit 0
fi

exec python -m pytest tests/ -q ${EXTRA[@]+"${EXTRA[@]}"}
