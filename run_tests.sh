#!/usr/bin/env bash
# One-shot test harness (the reference's test/run_tests.sh analogue, which
# booted a 2-worker local Spark Standalone cluster around unittest discover).
#
# Without pyspark: the suite runs against the bundled local multi-process
# backend (the Spark stand-in; same executor-process semantics).
# With pyspark installed: additionally boots a local-cluster master so the
# integration tests can target real Spark executors.
#
# Usage: ./run_tests.sh [--quick] [extra pytest args]
#   --quick  run the quick tier only (pytest -m 'not slow')
set -euo pipefail
cd "$(dirname "$0")"

EXTRA=()
for arg in "$@"; do
  if [[ "$arg" == "--quick" ]]; then
    EXTRA+=(-m "not slow")
  else
    EXTRA+=("$arg")
  fi
done

# lint gate: library modules must not configure logging at import time
python scripts/check_no_basicconfig.py

export JAX_PLATFORMS=cpu
if [[ "${XLA_FLAGS:-}" != *xla_force_host_platform_device_count* ]]; then
  export XLA_FLAGS="${XLA_FLAGS:-} --xla_force_host_platform_device_count=8"
fi

if python -c "import pyspark" 2>/dev/null; then
  echo "pyspark available: running with TOS_TEST_PYSPARK=1 (local-cluster[2,1,1024])"
  export TOS_TEST_PYSPARK=1
  export MASTER="local-cluster[2,1,1024]"
else
  echo "pyspark not installed: using the bundled local multi-process backend"
fi

exec python -m pytest tests/ -q ${EXTRA[@]+"${EXTRA[@]}"}
