"""Executor-side node runtime.

Capability-parity with /root/reference/tensorflowonspark/TFSparkNode.py, built
for the TPU process model. Per executor, the launch task:

1. maps its executor id to a (job_name, task_index) from the cluster template,
2. starts the per-executor IPC channel (local unix socket; TCP for
   driver-managed roles) and persists the reconnect record to the executor CWD,
3. registers with the driver's reservation server (host, coordinator port, TPU
   topology) and blocks until the whole cluster is assembled,
4. derives the jax.distributed world — coordinator address, process count,
   process id — from the assembled cluster info (the ClusterSpec/TF_CONFIG
   analogue, reference TFSparkNode.py:277-299),
5. spawns the **jax child process** that owns this host's TPU chips and runs
   the user's ``main_fun(args, ctx)``; the executor process itself never
   imports jax, so it stays light and reusable across Spark tasks (the
   reference's bg-process dispatch, TFSparkNode.py:339-395, generalized: on
   TPU *every* role runs in a child so libtpu's process-owns-chips rule is
   respected and chips are freed when the child exits).

Feeding/inference/shutdown closures are picklable task objects (Spark and the
local backend both ship them to executors by serialization).
"""

import logging
import os
import signal
import threading
import time
import traceback

from tensorflowonspark_tpu import TFManager, TFNode, chaos, reservation, resilience, tpu_info, util
from tensorflowonspark_tpu.marker import Chunk, EndPartition
from tensorflowonspark_tpu.obs import aggregate as obs_aggregate
from tensorflowonspark_tpu.obs import flight as obs_flight
from tensorflowonspark_tpu.obs import registry as obs_registry
from tensorflowonspark_tpu.obs import trace as obs_trace
from tensorflowonspark_tpu.obs import tracing as obs_tracing

#: rows per proxied queue message on the feed plane (amortizes the Manager
#: round trip that was the reference's hot-loop bottleneck; overridable for
#: huge rows via env)
FEED_CHUNK_SIZE = int(os.environ.get("TOS_FEED_CHUNK", "100"))

#: ship chunk payloads through shared memory (columnar numpy segments; the
#: Manager carries only descriptors) — rows without a uniform numeric shape
#: fall back to pickled Chunks per chunk; TOS_FEED_SHM=0 disables the lane
FEED_SHM = os.environ.get("TOS_FEED_SHM", "1") == "1"


def _put_rows(q, rows, use_shm=None):
    """One feed-plane message: shared-memory columnar segment when the rows
    allow it, pickled Chunk otherwise."""
    if FEED_SHM if use_shm is None else use_shm:
        from tensorflowonspark_tpu.shm import ShmChunk

        chunk = ShmChunk.from_rows(rows)
        if chunk is not None:
            q.put(chunk, block=True)
            return
    q.put(Chunk(rows), block=True)

logger = logging.getLogger(__name__)

#: Executor-process-global registry of live IPC channels, keyed by executor id.
#: Keeps the manager server process alive after the launch task returns (its
#: BaseManager finalizer would otherwise tear the channel down) and lets tasks
#: that land on this executor later reuse the handle — the reference's
#: module-global manager singleton (TFSparkNode.py:97-123).
_live_channels = {}

#: Executor-process-global registry of running heartbeat aggregators, keyed by
#: executor id. The aggregator thread outlives the launch task alongside its
#: channel; a Spark task retry (or a relaunch generation) on the same executor
#: must stop the previous one before electing anew — two aggregators publishing
#: independently-numbered windows on one channel would make the driver's
#: window-freshness check flap.
_live_aggregators = {}
_live_aggregators_lock = threading.Lock()


class TFNodeContext:
    """Context object handed to user ``main_fun(args, ctx)``.

    Field-parity with the reference's ctx (TFSparkNode.py:37-60: job_name,
    task_index, cluster_spec, defaultFS, working_dir, mgr, num_workers) plus
    the TPU world: coordinator address / process id / process count for
    ``jax.distributed``, and the local chip topology.
    """

    def __init__(
        self,
        executor_id,
        job_name,
        task_index,
        cluster_spec,
        defaultFS,
        working_dir,
        mgr=None,
        coordinator_address=None,
        num_processes=1,
        process_id=0,
        topology=None,
        cluster_meta=None,
    ):
        self.executor_id = executor_id
        self.worker_num = executor_id  # reference-compat alias
        self.job_name = job_name
        self.task_index = task_index
        self.cluster_spec = cluster_spec
        self.defaultFS = defaultFS
        self.working_dir = working_dir
        self.mgr = mgr
        self.coordinator_address = coordinator_address
        self.num_processes = num_processes
        self.process_id = process_id
        self.topology = topology or {}
        self.cluster_meta = cluster_meta or {}

    @property
    def num_workers(self):
        """Number of training participants (chief/master + workers), reference
        TFSparkNode.py:58."""
        spec = self.cluster_spec or {}
        return (
            len(spec.get("chief", []))
            + len(spec.get("master", []))
            + len(spec.get("worker", []))
        )

    @property
    def distributed(self):
        return self.num_processes > 1

    def get_data_feed(self, train_mode=True, qname_in="input", qname_out="output", input_mapping=None):
        """The InputMode.SPARK consumer (reference TFNode.py:221)."""
        return TFNode.DataFeed(
            self.mgr, train_mode, qname_in, qname_out, input_mapping,
            use_shm=self.cluster_meta.get("feed_shm"),
        )

    def absolute_path(self, path):
        return TFNode.hdfs_path(self, path)

    def initialize_distributed(self):
        """Join the jax.distributed world derived from the reservations.

        Call before any other jax API in multi-host runs; no-op single-host.
        This is the TF_CONFIG/ClusterSpec replacement (SURVEY.md §2.8).
        """
        if self.num_processes <= 1:
            return
        import jax

        platforms = str(getattr(jax.config, "jax_platforms", None) or "")
        if platforms.split(",")[0] == "cpu":
            # CPU multi-process worlds (tests, dev boxes) federate their
            # devices through gloo collectives; on TPU the ICI/DCN transport
            # is native and needs no selection
            try:
                jax.config.update("jax_cpu_collectives_implementation", "gloo")
            except Exception:  # older jax: single implementation only
                pass
        jax.distributed.initialize(
            coordinator_address=self.coordinator_address,
            num_processes=self.num_processes,
            process_id=self.process_id,
        )
        logger.info(
            "jax.distributed world up: %d processes, this is %d, %d global device(s)",
            self.num_processes, self.process_id, jax.device_count(),
        )

    def mesh(self, axes=None):
        """Construct the device mesh for this cluster (convenience wrapper
        around :mod:`tensorflowonspark_tpu.parallel.mesh`)."""
        from tensorflowonspark_tpu.parallel import mesh as mesh_lib

        return mesh_lib.build_mesh(axes)


def _role_rank(job_name):
    # template order mirrors the reference: ps → chief → evaluator → worker
    return {"ps": 0, "chief": 1, "master": 1, "evaluator": 2, "worker": 3}.get(job_name, 3)


def _participants(cluster_info):
    """Training participants (chief first, then workers by task_index)."""
    rows = [r for r in cluster_info if r["job_name"] in ("chief", "master", "worker")]
    return sorted(rows, key=lambda r: (0 if r["job_name"] in ("chief", "master") else 1, r["task_index"]))


def _derive_world(cluster_info, me):
    """coordinator address + (num_processes, process_id) for this node.

    ps/evaluator roles are outside the collective world (no PS on TPU —
    SURVEY.md §2.6: capability met by sync DP over ICI); they get a
    single-process world so ``initialize_distributed`` no-ops.
    """
    parts = _participants(cluster_info)
    if not parts:
        return None, 1, 0
    coord = "{}:{}".format(parts[0]["host"], parts[0]["port"])
    for i, row in enumerate(parts):
        if row["executor_id"] == me["executor_id"]:
            return coord, len(parts), i
    return None, 1, 0


def _child_entry(fn, tf_args, ctx, cluster_meta, error_queue_spec):
    """Entry point of the jax child process: applies env, joins the
    distributed world, runs the user fn; failures land on the 'error' queue
    (reference wrapper_fn_background, TFSparkNode.py:355-361)."""
    publisher = None
    try:
        util.setup_logging()  # spawned interpreter: no handlers configured yet
        env = cluster_meta.get("env") or {}
        os.environ.update(env)
        # the env lane can carry a chaos plan for cross-host executors, but
        # the chaos module already ran its import-time env check in this
        # interpreter — re-check now that the lane has landed
        chaos._install_from_env()
        # adopt the cluster trace context the same way: spans below (and in
        # forked decode workers, which inherit this environ) carry the
        # driver-minted trace_id, and this child gets its own flight shard
        obs_tracing.install_from_env(
            "jax-{}-{}".format(ctx.job_name, ctx.task_index)
        )
        os.environ.update(tpu_info.visibility_env(platform=env.get("JAX_PLATFORMS")))
        if env.get("JAX_PLATFORMS"):
            # config-API forcing: on TPU-pod images the site setup pins the
            # platform via jax.config in every interpreter, which overrides
            # the env var we just set (see util.force_platform)
            util.force_platform(env["JAX_PLATFORMS"], env.get("TOS_NUM_CPU_DEVICES"))
        # re-connect our own IPC channel from inside the child
        addr, authkey = error_queue_spec
        ctx.mgr = TFManager.connect(addr, authkey)
        _start_heartbeat(ctx.mgr, ctx.executor_id)
        if not cluster_meta.get("obs", True):
            obs_registry.set_enabled(False)
        # the long-lived child owns this executor's obs_snapshot lane: its
        # cumulative registry is overwritten on the channel every interval
        publisher = obs_aggregate.SnapshotPublisher(ctx.mgr).start()
        # from here a preemption warning (SIGTERM, driver preempt key, or
        # the node.preempt chaos site) drains instead of dying abruptly
        _arm_preemption(ctx.mgr, ctx, publisher)
        if cluster_meta.get("jax_distributed", True):
            ctx.initialize_distributed()
        try:
            import jax

            tpu_info.validate_against_runtime(jax.local_device_count())
        except Exception:  # validation is advisory
            pass
        if cluster_meta.get("log_dir") and ctx.process_id == 0:
            try:
                import jax

                profiler_port = util.find_free_port()
                jax.profiler.start_server(profiler_port)
                logger.info("jax profiler server on port %d", profiler_port)
            except Exception as e:  # profiling is best-effort
                logger.warning("could not start jax profiler server: %s", e)
        with obs_trace.span("node_main", job=ctx.job_name, task_index=ctx.task_index):
            fn(tf_args, ctx)
        _drain_checkpoints()
        publisher.stop()  # final flush: short runs publish at least once
        ctx.mgr.set("child_status", "done")
    except BaseException as child_exc:
        tb = traceback.format_exc()
        logger.error("user main_fun failed:\n%s", tb)
        # black-box moment: an unhandled child exit stamps the trace and
        # flushes this process's flight shard so the post-mortem merge shows
        # the child's final spans even when the process is about to die
        try:
            obs_tracing.event(
                "child_failed",
                job=ctx.job_name, task_index=ctx.task_index,
                executor_id=ctx.executor_id, error=type(child_exc).__name__,
            )
            obs_flight.dump("child_failed:{}".format(type(child_exc).__name__))
        except Exception:
            pass
        # land any in-flight async checkpoint BEFORE reporting the failure:
        # the relaunched attempt resumes from the newest committed one
        _drain_checkpoints()
        try:
            if publisher is not None:
                publisher.stop()  # flush so the failed node's metrics survive
        except Exception:
            pass
        try:
            addr, authkey = error_queue_spec
            mgr = TFManager.connect(addr, authkey)
            mgr.get_queue("error").put(tb)
            mgr.set("child_status", "failed")
        except Exception:
            pass
        raise SystemExit(1)


#: seconds the exiting jax child waits for in-flight async checkpoint
#: commits to land (drain-on-exit: an accepted snapshot should become a
#: resume point, not die with the process)
CHECKPOINT_DRAIN_TIMEOUT = float(os.environ.get("TOS_CKPT_DRAIN_TIMEOUT", "120"))


def _drain_checkpoints():
    """Drain every live async checkpoint engine in this child — bounded and
    best-effort: a wedged storage backend must not turn child exit into a
    hang, and a drain failure must not mask the user fn's own outcome."""
    try:
        from tensorflowonspark_tpu import ckpt

        if not ckpt.drain_all(timeout=CHECKPOINT_DRAIN_TIMEOUT):
            logger.warning(
                "async checkpoint drain timed out after %ss on child exit: %s",
                CHECKPOINT_DRAIN_TIMEOUT,
                "; ".join(ckpt.busy_descriptions()) or "engine list changed",
            )
    except Exception:
        logger.exception("async checkpoint drain failed on child exit")


#: seconds between child heartbeats on the IPC channel (the driver-side
#: monitor flags a node whose beat stops without a final child_status —
#: e.g. a SIGKILLed jax child that could post no traceback)
HEARTBEAT_INTERVAL = float(os.environ.get("TOS_HEARTBEAT_INTERVAL", "2"))


# -- preemption-aware drain ---------------------------------------------------
#
# A preemption *warning* (the platform's SIGTERM grace window, the
# ``node.preempt`` chaos site, or the driver posting ``preempt`` on the
# channel for a regrow restart) reaches the jax child while it can still
# act. The warned path turns an abrupt kill into a clean handoff: land every
# in-flight async checkpoint, flush this node's metrics, commit a
# ``preempted`` parting status on the channel (the driver's watchdog turns
# that into a durable registry ``leave``), and exit before the kill lands.
# The recovery ladder classifies the resulting loss as a first-class
# ``preemption``: no blacklist entry, no restart-budget charge.

_preempt_lock = threading.Lock()
_preempt = {
    "fired": False, "mgr": None, "publisher": None,
    "executor_id": None, "job_name": None, "task_index": None,
}


def _arm_preemption(mgr, ctx, publisher):
    """Hand the warned-shutdown path its channel/publisher handles and
    install the real SIGTERM handler (jax-child main thread only)."""
    with _preempt_lock:
        _preempt.update(
            mgr=mgr, publisher=publisher, executor_id=ctx.executor_id,
            job_name=ctx.job_name, task_index=ctx.task_index,
        )
    try:
        signal.signal(
            signal.SIGTERM, lambda signum, frame: _preempt_drain("sigterm")
        )
    except (ValueError, OSError):  # not the main thread / exotic platform
        pass


def _preempt_drain(source):
    """Drain and exit under a preemption warning; never returns once it wins
    the once-race (``os._exit`` — unwinding the training stack could
    overwrite the parting status with a spurious ``failed``)."""
    with _preempt_lock:
        if _preempt["fired"]:
            return  # handler/heartbeat race: first caller owns the exit
        _preempt["fired"] = True
    logger.warning(
        "preemption warning (%s): draining checkpoints before the kill lands",
        source,
    )
    try:
        obs_tracing.event(
            "preempt_drain", source=source,
            executor_id=_preempt["executor_id"], job=_preempt["job_name"],
            task_index=_preempt["task_index"],
        )
    except Exception:
        pass
    _drain_checkpoints()
    if _preempt["publisher"] is not None:
        try:  # flush so the drained node's metrics survive it
            _preempt["publisher"].stop()
        except Exception:
            pass
    if _preempt["mgr"] is not None:
        try:  # the parting commit the watchdog journals as a durable leave
            _preempt["mgr"].set("child_status", "preempted")
        except Exception:
            pass
    try:
        obs_flight.dump("preempted:{}".format(source))
    except Exception:
        pass
    os._exit(143)  # 128 + SIGTERM: the conventional warned-termination code


def _latch(path):
    """Create a chaos ``once_path`` latch file; first creator wins."""
    if not path:
        return
    try:
        with open(path, "x") as f:
            f.write(str(os.getpid()))
    except OSError:
        pass


def _start_heartbeat(mgr, executor_id=None):
    """Daemon thread bumping a counter on the channel every
    HEARTBEAT_INTERVAL; exits quietly when the channel goes away.

    ``executor_id`` scopes the ``node.kill`` / ``node.flap`` chaos sites:
    their specs carry a ``victim`` executor id and an ``after_beats`` ramp,
    so a plan can deterministically take down exactly one node mid-training
    (the recovery-ladder e2e depends on this precision — a victimless kill
    site would take out every child, since each spawned process re-installs
    the plan from the env with a fresh budget).
    """
    import threading

    def _chaos_node_fault(beat):
        # gate on the spec params BEFORE rolling the site, so non-victim
        # nodes and early beats consume neither budget nor counters
        p = chaos.plan()
        for site in ("node.kill", "node.flap", "node.preempt"):
            spec = p.sites.get(site) if p else None
            if spec is None:
                continue
            victim = spec.get("victim")
            if victim is not None and victim != executor_id:
                continue
            if beat < spec.get("after_beats", 0):
                continue
            once = spec.get("once_path")
            if once and os.path.exists(once):
                # cross-process one-shot latch: each spawned child re-installs
                # the plan with a fresh budget, so without the latch a victim
                # respawned by the recovery ladder would die on every life
                continue
            if site == "node.kill":
                if chaos.fire("node.kill"):
                    _latch(once)
                    logger.warning("chaos: node.kill — SIGKILLing executor %s child",
                                   executor_id)
                    os.kill(os.getpid(), signal.SIGKILL)
            elif site == "node.preempt":
                if chaos.fire("node.preempt"):
                    _latch(once)
                    logger.warning(
                        "chaos: node.preempt — SIGTERMing executor %s child "
                        "(warned shutdown)", executor_id,
                    )
                    os.kill(os.getpid(), signal.SIGTERM)
            else:
                if chaos.delay("node.flap"):  # paused beats: watchdog gap
                    _latch(once)

    def _beat():
        failures = 0
        # drift-free monotonic schedule with per-beat jitter: N children
        # started out of the same assembly barrier must not beat in
        # lockstep, or the aggregation tree turns the fleet's beats into
        # synchronized channel bursts (seeded by executor id so tests can
        # reproduce a schedule)
        ticker = resilience.Ticker(
            HEARTBEAT_INTERVAL, jitter=0.25, seed=executor_id
        )
        for n in ticker.ticks():
            if chaos.active:
                _chaos_node_fault(n)
            try:
                mgr.set("heartbeat", n)
                if mgr.get("preempt") is not None:
                    # the driver warned us (regrow restart / planned drain):
                    # same clean-handoff path as a platform SIGTERM
                    _preempt_drain("driver")
                failures = 0
            except Exception:
                # transient proxy hiccups must not kill the beat (the
                # watchdog would then fail a healthy node); only a channel
                # that stays dead ends the thread
                failures += 1
                if failures >= 5:
                    return

    threading.Thread(target=_beat, name="tos-heartbeat", daemon=True).start()


class _NodeLaunchTask:
    """The ``foreachPartition`` closure that boots one cluster node
    (reference ``TFSparkNode.run()._mapfn``, TFSparkNode.py:126-395)."""

    def __init__(self, fn, tf_args, cluster_meta, input_mode, log_dir=None, queues=None):
        self.fn = fn
        self.tf_args = tf_args
        self.cluster_meta = cluster_meta
        self.input_mode = input_mode
        self.log_dir = log_dir
        self.queues = tuple(queues or TFManager.CONTROL_QUEUES)

    def __call__(self, iterator):
        executor_id = None
        for i in iterator:
            executor_id = i
        if executor_id is None:
            return []
        meta = self.cluster_meta
        # PRIVATE registry: the executor process outlives this task, and a
        # relaunch on a reused executor must not double-count the global one
        # (see obs.aggregate docstring)
        reg = obs_registry.Registry(enabled=bool(meta.get("obs", True)))
        states = reg.counter(
            "node_state_transitions_total",
            help="node state-machine transitions driven by the launch task",
        )

        # Detect a live node from a previous (failed or duplicate) launch on
        # this executor: raising forces the scheduler to retry elsewhere
        # (reference TFSparkNode.py:173-179).
        prior = util.read_executor_state()
        if prior is not None:
            try:
                old = TFManager.connect(prior["address"], prior["authkey"])
                if old.get("state") in ("running", "terminating"):
                    raise RuntimeError(
                        "executor already hosts a live node for cluster {} — "
                        "forcing task retry on another executor".format(prior.get("cluster_id"))
                    )
            except RuntimeError:
                raise
            except Exception:
                pass  # stale record from a dead process: overwrite

        template = meta["cluster_template"]
        job_name, task_index = template[executor_id]
        # adopt the driver-minted trace context BEFORE the REG handshake:
        # the node_launch span below carries the cluster trace_id, and the
        # REG round-trip's driver-stamped reply seeds this host's clock
        # offset (obs.tracing.observe_clock) for the trace merger. Folding
        # the meta env lane into os.environ here also means the spawned jax
        # child and anything it forks inherit the context.
        obs_tracing.install_from_env(
            "executor{}".format(executor_id), env=meta.get("env") or {}
        )
        authkey = meta["authkey"]
        # every channel is TCP ('remote'): the driver shuts nodes down by
        # posting end-of-feed directly to each node's queues — deterministic,
        # unlike scattering shutdown tasks and hoping the scheduler spreads
        # them one-per-executor (the reference's approach, TFCluster.py:174).
        mgr = TFManager.start(authkey=authkey, queues=self.queues, mode="remote")
        # at most one live node per executor process (enforced above), so any
        # existing channel — whatever cluster/node id it served — is from a
        # finished run on this reused executor: shut it down, don't leak it
        for key in list(_live_channels):
            _live_channels.pop(key).shutdown()
        _live_channels[executor_id] = mgr  # pin the channel beyond this task
        mgr.set("state", "starting")
        states.inc()

        host = util.get_ip_address()
        port = util.find_free_port()
        is_tb_node = job_name in ("chief", "master") or (
            "chief" not in {j for j, _ in template.values()}
            and "master" not in {j for j, _ in template.values()}
            and job_name == "worker"
            and task_index == 0
        )
        tb_port = None
        if meta.get("tensorboard") and is_tb_node:
            tb_port = self._launch_tensorboard(meta.get("log_dir"))
        client = reservation.Client(meta["server_addr"])
        with obs_trace.span(
            "node_launch", registry=reg,
            executor_id=executor_id, job=job_name, task_index=task_index,
        ):
            client.register(
                {
                    "executor_id": executor_id,
                    "host": host,
                    "job_name": job_name,
                    "task_index": task_index,
                    "port": port,
                    "manager_addr": list(mgr.address),
                    "tb_port": tb_port,
                    "tpu": tpu_info.local_topology(),
                }
            )
            cluster_info = client.await_reservations(
                timeout=meta.get("reservation_timeout", 600)
            )

        # sanity: every executor id distinct (reference TFSparkNode.py:281-289)
        ids = [r["executor_id"] for r in cluster_info]
        if len(set(ids)) != len(ids):
            raise RuntimeError("duplicate executor ids in cluster: {}".format(sorted(ids)))

        self._maybe_start_aggregator(mgr, cluster_info, executor_id, authkey, meta)

        cluster_spec = {}
        for row in sorted(cluster_info, key=lambda r: (_role_rank(r["job_name"]), r["task_index"])):
            cluster_spec.setdefault(row["job_name"], []).append(
                "{}:{}".format(row["host"], row["port"])
            )
        me = {"executor_id": executor_id}
        coord, num_procs, proc_id = _derive_world(cluster_info, me)

        util.write_executor_state(
            {
                "executor_id": executor_id,
                "cluster_id": meta["id"],
                "address": mgr.address,
                "authkey": authkey,
                "job_name": job_name,
                "task_index": task_index,
            }
        )

        ctx = TFNodeContext(
            executor_id=executor_id,
            job_name=job_name,
            task_index=task_index,
            cluster_spec=cluster_spec,
            defaultFS=meta.get("default_fs", "file://"),
            working_dir=os.getcwd(),
            mgr=None,  # child re-connects its own handle
            coordinator_address=coord,
            num_processes=num_procs if meta.get("jax_distributed", False) else 1,
            process_id=proc_id,
            topology=tpu_info.local_topology(),
            cluster_meta={
                k: meta[k]
                for k in ("id", "server_addr", "input_mode", "feed_shm", "obs")
                if k in meta
            },
        )
        mgr.set("state", "running")
        states.inc()
        logger.info(
            "node %s:%d (executor %d) up; world=%s procs=%d id=%d",
            job_name, task_index, executor_id, coord, num_procs, proc_id,
        )

        # spawned, not forked: the executor process carries queue-feeder
        # threads by now, and the child gets a pristine interpreter so the
        # env vars _child_entry sets land before jax is first imported
        import functools

        child = util.spawn_process(
            functools.partial(
                _child_entry, self.fn, self.tf_args, ctx, meta, (mgr.address, authkey)
            ),
            name="jax-node-{}-{}".format(job_name, task_index),
        )
        child.start()
        self._register_child(child)
        self._start_abort_watch(mgr, child, job_name, task_index)

        def _flush_obs():
            # exactly once per return path (accumulate merges, so twice
            # would double-count); channel failure must not fail the node
            try:
                obs_aggregate.accumulate_to_channel(mgr, reg)
            except Exception:
                pass

        if job_name in ("ps", "evaluator"):
            # park until the driver posts a shutdown message on the control
            # queue (reference ps wait loop, TFSparkNode.py:373-390)
            control = mgr.get_queue("control")
            while True:
                msg = control.get(block=True)
                control.task_done()
                if msg is None:
                    break
            child.terminate()
            child.join(timeout=10)
            mgr.set("state", "stopped")
            states.inc()
            _flush_obs()
        elif self.input_mode == "spark":
            # return immediately: this executor's slot is needed for feed tasks
            _flush_obs()
        else:
            # InputMode.TENSORFLOW: the task occupies the slot until training
            # finishes (reference fg-thread dispatch, TFSparkNode.py:391-395)
            child.join()
            mgr.set("state", "stopped")
            states.inc()
            _flush_obs()
            if child.exitcode != 0:
                if mgr.get("abort") is not None:
                    # the driver's abort watcher killed this child on
                    # purpose: returning (not raising) keeps Spark from
                    # retrying the task against a cluster being torn down
                    logger.info(
                        "node %s:%d terminated by driver abort: %s",
                        job_name, task_index, mgr.get("abort"),
                    )
                    return []
                if mgr.get("child_status") == "preempted":
                    # warned shutdown: the child drained and committed its
                    # parting status before exiting — surface a first-class
                    # preemption so the ladder skips the blacklist and the
                    # restart budget (see elastic.classify_failure)
                    raise RuntimeError(
                        "node {}:{} preempted (executor {})".format(
                            job_name, task_index, executor_id
                        )
                    )
                err = None
                try:
                    eq = mgr.get_queue("error")
                    if not eq.empty():
                        err = eq.get(block=False)
                        eq.task_done()
                except Exception:
                    pass
                raise RuntimeError(
                    "node {}:{} failed (exit {}):\n{}".format(
                        job_name, task_index, child.exitcode, err or "<no traceback captured>"
                    )
                )
        return []

    @staticmethod
    def _maybe_start_aggregator(mgr, cluster_info, executor_id, authkey, meta):
        """Start the heartbeat aggregation thread when this executor is an
        elected aggregator for the assembled cluster.

        The election (:func:`registry.plan_aggregation_tree`) is a pure
        function of ``cluster_info``, so every executor and the driver agree
        on the tree without another rendezvous round-trip. The thread is a
        daemon on the *executor* process (which outlives the launch task in
        spark mode via ``_live_channels``), publishing per-window beat
        summaries on this node's own channel; the driver's watchdog reads
        those instead of polling every member directly. Failure to start is
        non-fatal — the driver falls back to direct polls.

        Idempotent per executor process: the aggregator thread also outlives
        the launch task, so a Spark task retry (or a relaunch generation with
        a different tree) first stops the previous aggregator — otherwise two
        threads would interleave independently-numbered windows under
        ``WINDOW_KEY`` and the driver's freshness check would flap."""
        from tensorflowonspark_tpu import registry as registry_mod

        try:
            with _live_aggregators_lock:
                prev = _live_aggregators.pop(executor_id, None)
            if prev is not None:
                prev.stop()
            if not registry_mod.aggregation_enabled(len(cluster_info)):
                return
            tree = registry_mod.plan_aggregation_tree(cluster_info)
            members = tree.get(executor_id)
            if not members:
                return
            rows = {r["executor_id"]: r for r in cluster_info}
            agg = registry_mod.HeartbeatAggregator(
                mgr,
                [rows[m] for m in members if m in rows],
                authkey,
                obs_enabled=bool(meta.get("obs", True)),
            )
            agg.start()
            with _live_aggregators_lock:
                _live_aggregators[executor_id] = agg
            logger.info(
                "executor %d aggregating heartbeats for members %s",
                executor_id, members,
            )
        except Exception:
            logger.exception("heartbeat aggregator failed to start; "
                             "driver will poll members directly")

    @staticmethod
    def _start_abort_watch(mgr, child, job_name, task_index):
        """Executor-side kill switch: a daemon thread that terminates the jax
        child when the driver posts an ``"abort"`` reason on this node's
        channel (:meth:`TFCluster.TFCluster.abort`).

        This is what makes failure *recovery* possible on top of failure
        *detection*: in InputMode.TENSORFLOW the launch task blocks in
        ``child.join()`` holding its executor slot, so after one node dies the
        surviving nodes' tasks would pin their executors until training ended
        naturally — and a relaunch on the same SparkContext would queue behind
        them forever. The reference stopped at detection and SystemExit
        (reference TFCluster.py:178-183); here the driver can reclaim every
        executor deterministically and relaunch (``run_with_recovery``).

        The abort flag is a dedicated kv key, NOT a ``state`` value: the
        state machine's ``"terminating"`` is written by the child to stop the
        feed plane, and an abort arriving mid-terminate must not race it.
        The watcher answers every abort — even for a child that already
        exited on its own (spark-mode tasks return immediately, so nobody
        else would confirm that node down) — and retires only when the node
        reaches ``"stopped"`` or its channel dies."""
        import threading

        def _watch():
            ticker = resilience.Backoff(base=1.0, factor=1.0, max_delay=1.0, jitter=0.0)
            for _ in ticker.attempts():
                try:
                    if mgr.get("abort") is not None:
                        if child.is_alive():
                            logger.warning(
                                "driver abort: terminating jax child %s:%d", job_name, task_index
                            )
                            child.terminate()
                            child.join(timeout=10)
                            if child.is_alive() and hasattr(child, "kill"):
                                child.kill()
                                child.join(timeout=5)
                        mgr.set("state", "stopped")
                        return
                    if mgr.get("state") == "stopped":
                        return  # node retired through a normal shutdown path
                except Exception:
                    return  # channel gone: node already shut down

        threading.Thread(
            target=_watch, name="tos-abort-watch-{}-{}".format(job_name, task_index), daemon=True
        ).start()

    @staticmethod
    def _register_child(proc):
        try:
            from tensorflowonspark_tpu.backends import local as local_backend

            local_backend.register_child_process(proc)
        except Exception:
            pass

    def _launch_tensorboard(self, log_dir):
        """Launch a TensorBoard subprocess on this (chief) executor if the
        binary is available (reference TFSparkNode.py:206-238). Returns the
        port or None. The jax child additionally serves profiler data into
        ``log_dir`` via jax.profiler."""
        import subprocess
        import sys

        port = util.find_free_port()
        cmd = [
            sys.executable, "-m", "tensorboard.main",
            "--logdir", log_dir or os.getcwd(),
            "--host", "0.0.0.0", "--port", str(port),
        ]
        try:
            proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        except OSError as e:
            logger.warning("could not launch tensorboard: %s", e)
            return None
        self._register_child(_PopenAdapter(proc))
        logger.info("tensorboard listening on port %d (logdir=%s)", port, log_dir)
        return port


class _PopenAdapter:
    """Adapts subprocess.Popen to the mp.Process reaping surface the local
    backend expects (is_alive/terminate/join)."""

    def __init__(self, popen):
        self._p = popen

    def is_alive(self):
        return self._p.poll() is None

    def terminate(self):
        self._p.terminate()

    def join(self, timeout=None):
        try:
            self._p.wait(timeout=timeout)
        except Exception:
            pass


def _connect_executor_channel():
    state = util.read_executor_state()
    if state is not None and state.get("executor_id") in _live_channels:
        return state, _live_channels[state["executor_id"]]
    if state is None:
        raise RuntimeError(
            "no cluster node on this executor (missing {} in {}) — was the "
            "cluster started, and is this task on a cluster executor?".format(
                util.EXECUTOR_STATE_FILE, os.getcwd()
            )
        )
    return state, TFManager.connect(state["address"], state["authkey"])


def drain_queue(mgr, qname, max_items=100000):
    """Empty a feed queue at teardown, releasing shared-memory segments the
    consumer never materialized (a dead jax child cannot unlink them; the
    age-gated janitor is a day-scale backstop, not the primary cleanup)."""
    from tensorflowonspark_tpu.shm import ShmChunk

    q = mgr.get_queue(qname)
    drained = 0
    for _ in range(max_items):
        try:
            item = q.get_nowait()
        except Exception:
            break
        if isinstance(item, ShmChunk):
            item.discard()
        q.task_done()
        drained += 1
    if drained:
        logger.info("drained %d unconsumed item(s) from %r at shutdown", drained, qname)
    return drained


def peek_error(mgr):
    """Non-destructively read a traceback from a node's error queue, or None.

    The peek-and-requeue keeps the error visible to later tasks too
    (reference trick, TFSparkNode.py:576-582)."""
    eq = mgr.get_queue("error")
    if eq.empty():
        return None
    try:
        tb = eq.get(block=False)
    except Exception:
        return None
    eq.put(tb)
    eq.task_done()
    return tb


def _raise_if_remote_error(mgr):
    tb = peek_error(mgr)
    if tb is not None:
        raise RuntimeError("error in jax child process:\n{}".format(tb))


def _chaos_trim(buf):
    """Chaos fault ``feed.truncate_chunk``: drop the tail of one train chunk
    (a torn feed message). Train-only — inference feeds keep their 1:1
    row/output contract, so this is called from the train feeder alone."""
    if chaos.fire("feed.truncate_chunk"):
        return buf[: max(1, len(buf) // 2)]
    return buf


class _TrainPartitionTask:
    """Feeds one RDD partition into the executor's input queue
    (reference ``TFSparkNode.train()._train``, TFSparkNode.py:400-467)."""

    def __init__(self, cluster_meta, qname="input", feed_timeout=600, chunk_size=None):
        self.cluster_meta = cluster_meta
        self.qname = qname
        self.feed_timeout = feed_timeout
        self.chunk_size = chunk_size or FEED_CHUNK_SIZE
        # captured at task construction (driver side) so the executor honors
        # the driver's setting regardless of its own env
        self.use_shm = FEED_SHM

    def __call__(self, iterator):
        _state, mgr = _connect_executor_channel()
        if mgr.get("state") == "terminating":
            logger.info("node is terminating; skipping partition")
            for _ in iterator:  # drain so the scheduler sees the task consumed
                pass
            return []
        # private per-task registry, accumulated onto the channel at task end
        # (see obs.aggregate docstring for the double-count rationale)
        reg = obs_registry.Registry(enabled=bool(self.cluster_meta.get("obs", True)))
        rows_c = reg.counter("feed_rows_total", help="rows fed into the input queue")
        chunks_c = reg.counter("feed_chunks_total", help="feed-plane chunk messages enqueued")
        depth_g = reg.gauge(
            "feed_queue_depth", help="unconsumed input-queue items at last sample"
        )
        q = mgr.get_queue(self.qname)
        count = 0
        buf = []
        try:
            with obs_trace.span("feed_wave", registry=reg, qname=self.qname) as sp:
                for item in iterator:
                    buf.append(item)
                    count += 1
                    if len(buf) >= self.chunk_size:
                        if chaos.active:
                            buf = _chaos_trim(buf)
                        _put_rows(q, buf, self.use_shm)
                        rows_c.inc(len(buf))
                        chunks_c.inc()
                        buf = []
                if buf:
                    if chaos.active:
                        buf = _chaos_trim(buf)
                    _put_rows(q, buf, self.use_shm)
                    rows_c.inc(len(buf))
                    chunks_c.inc()
                sp.set(rows=count)
                logger.info(
                    "fed %d items to queue %r; waiting for consumption", count, self.qname
                )
                # fine-grained poll at first (a consumer already caught up
                # finishes the wait in ~ms, which matters for many small
                # partitions), backing off so long waits don't hammer the proxy
                poll = resilience.Backoff(base=0.002, factor=2.0, max_delay=0.1, jitter=0.0)
                pending = 0
                for _ in poll.attempts(deadline=resilience.Deadline(self.feed_timeout)):
                    pending = q.unfinished()
                    depth_g.set(pending)
                    if pending <= 0:
                        break
                    _raise_if_remote_error(mgr)
                    if mgr.get("state") == "terminating":
                        break
                else:
                    raise RuntimeError(
                        "feed timeout: queue {!r} still has {} unconsumed items".format(
                            self.qname, pending
                        )
                    )
        finally:
            try:  # metrics must surface even when the wave times out
                obs_aggregate.accumulate_to_channel(mgr, reg)
            except Exception:
                pass
        _raise_if_remote_error(mgr)
        if mgr.get("state") == "terminating":
            # training said "enough" (e.g. reached target steps): tell the
            # driver so it can stop scheduling feed jobs
            # (reference TFSparkNode.py:451-464)
            try:
                reservation.Client(self.cluster_meta["server_addr"]).request_stop()
            except reservation.ReservationError:
                pass
        return []


class _InferencePartitionTask:
    """Feeds one partition and collects exactly its results
    (reference ``TFSparkNode.inference()._inference``, TFSparkNode.py:470-529).

    REQUIRES one concurrent task per executor (spark.executor.cores=1 or
    spark.task.cpus=executor cores) — the same hard invariant the reference
    held (its TFSparkNode.py:116-119). Two inference tasks interleaving on
    one executor channel could split a result chunk across collectors; the
    collector below detects the resulting over-collection and fails loudly
    rather than starving the peer task into a feed timeout."""

    def __init__(self, cluster_meta, qname_in="input", qname_out="output", feed_timeout=600, chunk_size=None):
        self.cluster_meta = cluster_meta
        self.qname_in = qname_in
        self.qname_out = qname_out
        self.feed_timeout = feed_timeout
        self.chunk_size = chunk_size or FEED_CHUNK_SIZE
        self.use_shm = FEED_SHM

    def __call__(self, iterator):
        _state, mgr = _connect_executor_channel()
        reg = obs_registry.Registry(enabled=bool(self.cluster_meta.get("obs", True)))
        rows_c = reg.counter("feed_rows_total", help="rows fed into the input queue")
        chunks_c = reg.counter("feed_chunks_total", help="feed-plane chunk messages enqueued")
        results_c = reg.counter(
            "inference_results_total", help="inference results collected back from nodes"
        )
        q = mgr.get_queue(self.qname_in)
        count = 0
        buf = []
        try:
            with obs_trace.span("inference_wave", registry=reg, qname=self.qname_in) as sp:
                for item in iterator:
                    buf.append(item)
                    count += 1
                    if len(buf) >= self.chunk_size:
                        _put_rows(q, buf, self.use_shm)
                        rows_c.inc(len(buf))
                        chunks_c.inc()
                        buf = []
                if buf:
                    _put_rows(q, buf, self.use_shm)
                    rows_c.inc(len(buf))
                    chunks_c.inc()
                q.put(EndPartition(), block=True)
                sp.set(rows=count)
                if count == 0:
                    return []
                poll = resilience.Backoff(base=0.002, factor=2.0, max_delay=0.1, jitter=0.0)
                for _ in poll.attempts(deadline=resilience.Deadline(self.feed_timeout)):
                    if q.unfinished() <= 0:
                        break
                    _raise_if_remote_error(mgr)
                else:
                    raise RuntimeError(
                        "inference feed timeout on queue {!r}".format(self.qname_in)
                    )
                from tensorflowonspark_tpu.shm import ShmChunk

                out = mgr.get_queue(self.qname_out)
                results = []
                while len(results) < count:
                    item = out.get(block=True, timeout=self.feed_timeout)
                    out.task_done()
                    if isinstance(item, ShmChunk):
                        results.extend(item.rows())
                    elif isinstance(item, Chunk):
                        results.extend(item.items)
                    else:
                        results.append(item)
                results_c.inc(len(results))
        finally:
            try:
                obs_aggregate.accumulate_to_channel(mgr, reg)
            except Exception:
                pass
        if len(results) > count:
            raise RuntimeError(
                "collected {} inference results for a {}-item partition: "
                "another task is sharing this executor's channel — run "
                "inference with one concurrent task per executor "
                "(spark.executor.cores=1)".format(len(results), count)
            )
        logger.info("collected %d inference results", len(results))
        return results


class _ShutdownPartitionTask:
    """Posts end-of-feed to one worker's queues and confirms the node wound
    down (reference ``TFSparkNode.shutdown()._shutdown``, TFSparkNode.py:534-588)."""

    def __init__(self, cluster_meta, queues=("input",), grace_secs=0):
        self.cluster_meta = cluster_meta
        self.queues = tuple(queues)
        self.grace_secs = grace_secs

    def __call__(self, iterator):
        for _ in iterator:
            pass
        _state, mgr = _connect_executor_channel()
        for qname in self.queues:
            mgr.get_queue(qname).put(None, block=True)
        # give the child time to drain + export (reference grace sleep,
        # TFSparkNode.py:571-574); when we own the child handle (local
        # backend: launch ran in this very process) join it instead.
        joined = False
        try:
            from tensorflowonspark_tpu.backends import local as local_backend

            for proc in local_backend._executor_children:
                proc.join(timeout=max(self.grace_secs, 60))
                joined = True
        except Exception:
            pass
        if not joined and self.grace_secs:
            time.sleep(self.grace_secs)
        _raise_if_remote_error(mgr)
        mgr.set("state", "stopped")
        # janitor: feed segments orphaned by a crashed consumer. The age gate
        # must exceed any plausible feed backlog (feed_timeout defaults to
        # 600 s), so only segments a full day old are presumed dead.
        from tensorflowonspark_tpu import shm

        shm.unlink_leaked(max_age_secs=86400)
        return []


class _PreflightTask:
    """Per-executor health probe run as a short Spark task *between* cluster
    attempts (the recovery ladder's health gate, :mod:`~tensorflowonspark_tpu.elastic`).

    Each partition carries one executor id. The probe checks the three
    resources a relaunch needs from this host — scratch-dir writability,
    a TCP loopback round-trip (the manager-channel transport), and
    accelerator visibility — plus the live manager channel when one survives
    from a previous attempt, and an optional picklable ``extra_probe`` hook.
    Returns one report dict per executor; a failed check is recorded as its
    error string, never raised, so one bad host cannot fail the whole gate.
    """

    def __init__(self, extra_probe=None):
        self.extra_probe = extra_probe

    def __call__(self, iterator):
        executor_id = None
        for i in iterator:
            executor_id = i
        if executor_id is None:
            return []
        checks = {}
        checks["scratch"] = self._check_scratch()
        checks["loopback"] = self._check_loopback()
        checks["devices"] = self._check_devices()
        # the local backend advertises the hosting executor's identity in
        # the process env — a mismatch means the pin was not honored and
        # this report would be attributed to the wrong host
        lane = os.environ.get("TOS_LOCAL_EXECUTOR_ID")
        if lane is not None:
            checks["pinning"] = (
                "ok" if str(executor_id) == lane
                else "partition for executor {} ran on executor {}".format(
                    executor_id, lane
                )
            )
        channel = self._check_channel(executor_id)
        if channel is not None:
            checks["channel"] = channel
        if self.extra_probe is not None:
            try:
                self.extra_probe(executor_id)
                checks["extra"] = "ok"
            except Exception as e:
                checks["extra"] = "{}: {}".format(type(e).__name__, e)
        report = {
            "executor_id": executor_id,
            "ok": all(v == "ok" for v in checks.values()),
            "checks": checks,
        }
        return [report]

    @staticmethod
    def _check_scratch():
        """Write/read/delete a probe file where node scratch state lives."""
        path = os.path.join(os.getcwd(), ".tos_preflight_{}".format(os.getpid()))
        try:
            with open(path, "w") as f:
                f.write("probe")
            with open(path) as f:
                if f.read() != "probe":
                    return "scratch readback mismatch"
            os.remove(path)
            return "ok"
        except OSError as e:
            try:
                os.remove(path)
            except OSError:
                pass
            return "{}: {}".format(type(e).__name__, e)

    @staticmethod
    def _check_loopback():
        """TCP round-trip on loopback — the manager channel's transport."""
        import socket

        try:
            srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            srv.bind(("127.0.0.1", 0))
            srv.listen(1)
            srv.settimeout(5.0)
            cli = socket.create_connection(srv.getsockname(), timeout=5.0)
            conn, _ = srv.accept()
            cli.sendall(b"ping")
            data = conn.recv(4)
            cli.close()
            conn.close()
            srv.close()
            return "ok" if data == b"ping" else "loopback echo mismatch"
        except OSError as e:
            return "{}: {}".format(type(e).__name__, e)

    @staticmethod
    def _check_devices():
        """Accelerator visibility without importing jax in the executor."""
        try:
            topo = tpu_info.local_topology()
            if not topo:
                return "no local topology"
            return "ok"
        except Exception as e:
            return "{}: {}".format(type(e).__name__, e)

    @staticmethod
    def _check_channel(executor_id):
        """Round-trip the live manager channel when a previous attempt left
        one on this executor; None when there is nothing to probe."""
        mgr = _live_channels.get(executor_id)
        if mgr is None:
            state = util.read_executor_state()
            if state is None or state.get("executor_id") != executor_id:
                return None
            try:
                mgr = TFManager.connect(state["address"], state["authkey"])
            except Exception as e:
                return "{}: {}".format(type(e).__name__, e)
        try:
            mgr.set("preflight", executor_id)
            if mgr.get("preflight") != executor_id:
                return "channel readback mismatch"
            return "ok"
        except Exception as e:
            return "{}: {}".format(type(e).__name__, e)


# -- public factory API (names match the reference) ---------------------------


def run(fn, tf_args, cluster_meta, input_mode, log_dir=None, queues=None):
    """Build the node-launch closure for ``nodeRDD.foreachPartition``."""
    return _NodeLaunchTask(fn, tf_args, cluster_meta, input_mode, log_dir, queues)


def train(cluster_info, cluster_meta, feed_timeout=600, qname="input"):
    del cluster_info  # reconnection goes through the executor state file
    return _TrainPartitionTask(cluster_meta, qname=qname, feed_timeout=feed_timeout)


def inference(cluster_info, cluster_meta, feed_timeout=600, qname="input", qname_out="output"):
    del cluster_info
    return _InferencePartitionTask(
        cluster_meta, qname_in=qname, qname_out=qname_out, feed_timeout=feed_timeout
    )


def shutdown(cluster_info, cluster_meta, queues=("input",), grace_secs=0):
    del cluster_info
    return _ShutdownPartitionTask(cluster_meta, queues=queues, grace_secs=grace_secs)


def preflight(extra_probe=None):
    """Build the per-executor health-probe closure for
    ``rdd.mapPartitions(...).collect()`` (see :mod:`~tensorflowonspark_tpu.elastic`)."""
    return _PreflightTask(extra_probe=extra_probe)
