"""Shared crash-consistency primitives for the tmp+fsync+rename idiom.

Every durable commit point in the tree (checkpoint manifests, registry
journal/manifest, slab-cache generations, model pointers, flight-recorder
segments, executor bootstrap state) publishes by renaming a fully-written
staging path onto its final name. The rename makes the publish *atomic*;
it does not make it *durable* — after a power cut the filesystem may
replay the directory without the new entry even though both files'
contents were fsynced. Durability needs the parent directory's entry
fsynced too, which is what these helpers centralize (and what the
``commit-discipline`` rule of ``python -m tosa`` enforces at every
publish site; see the "Durable commit points" table in
docs/architecture.md).

This module is a leaf on purpose: no intra-package imports, so ckpt/,
obs/ and the registry can all use it without cycles.
"""

import errno
import logging
import os

logger = logging.getLogger(__name__)


def fsync_dir(path):
    """fsync a directory's entry table so renames/creates inside it
    survive a power cut. Best-effort: some filesystems (and all of
    Windows) refuse O_RDONLY fsync on directories — losing the *entry*
    durability there is strictly no worse than not trying."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return False
    try:
        os.fsync(fd)
        return True
    except OSError as e:
        if e.errno not in (errno.EINVAL, errno.EBADF, errno.ENOTSUP):
            logger.debug("directory fsync of %s failed: %s", path, e)
        return False
    finally:
        os.close(fd)


def fsync_file(path):
    """fsync an already-written file by path (for writers like np.savez
    that own the file handle internally). Best-effort like fsync_dir."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return False
    try:
        os.fsync(fd)
        return True
    except OSError:
        return False
    finally:
        os.close(fd)
