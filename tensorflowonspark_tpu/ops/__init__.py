"""TPU pallas kernels for the hot ops.

The reference had no kernels of its own — its hot loops were TensorFlow's
CUDA/NCCL internals (SURVEY.md §2.6). Here the compute path is XLA, and pallas
covers the places XLA needs help; kernels ship with an ``interpret`` mode so
numerics are testable on CPU.
"""

_EXPORTS = {
    "flash_attention": "flash_attention",
    "flash_attention_kernel": "flash_attention",
    "fused_batch_norm": "fused_bn",
    "FusedBatchNorm": "fused_bn",
}


def __getattr__(name):
    import importlib

    if name not in _EXPORTS:
        raise AttributeError(name)
    mod = importlib.import_module("tensorflowonspark_tpu.ops." + _EXPORTS[name])
    return getattr(mod, name) if name != _EXPORTS[name] else mod


def __dir__():
    return sorted(_EXPORTS)
