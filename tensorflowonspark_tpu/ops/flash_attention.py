"""Flash attention as pallas TPU kernels, with a full custom VJP.

Blockwise attention that never materializes the [L, L] score matrix: the
forward streams K/V blocks through VMEM accumulating an online softmax
(running max ``m``, denominator ``l``, weighted values ``acc``); the backward
recomputes probabilities per block from the saved log-sum-exp and accumulates
dq / dk / dv — three matmul-dominated kernels that keep the MXU busy while
HBM traffic stays O(L·D).

This is the single-device analogue of
:mod:`tensorflowonspark_tpu.parallel.ring_attention` (same math, blocks
streamed from local HBM instead of rotated over ICI). ``interpret=True`` runs
the kernels on CPU for tests.
"""

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_BIG = -0.7 * float(jnp.finfo(jnp.float32).max)

# tuned on v5e (L=4096, d=64, bf16): 512/512 runs ~1.3x faster than XLA's
# fused attention; 128/128 only ties it
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512

#: row-statistics (lse/delta) are stored [BH, L, _STAT_W]: TPU block shapes
#: need a tileable trailing dim, and a trailing dim equal to the full array
#: dim is allowed, so 8 lanes is the cheapest legal width
_STAT_W = 8


def _causal_mask(s, iq, ik, block_q, block_k):
    q_pos = iq * block_q + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    k_pos = ik * block_k + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(q_pos >= k_pos, s, _NEG_BIG)


def _segment_mask(s, sq_ref, sk_ref):
    """Packed-sequence fence: scores survive only where the query's segment
    id equals the key's. ``sq_ref`` blocks are [block_q, _STAT_W] (the same
    broadcast-lane trick as the row statistics); ``sk_ref`` blocks come from
    the pre-transposed [BH, _STAT_W, L] layout so the kernel reads a
    [1, block_k] row directly — no in-kernel transpose."""
    seg_q = sq_ref[0][:, :1]  # [bq, 1]
    seg_k = sk_ref[0][:1, :]  # [1, bk]
    return jnp.where(seg_q == seg_k, s, _NEG_BIG)


def _fwd_kernel(*refs, scale, causal, segmented, block_q, block_k):
    if segmented:
        q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref, lse_ref, acc, m, l = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc, m, l = refs
        sq_ref = sk_ref = None
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)
        m[:] = jnp.full_like(m, _NEG_BIG)
        l[:] = jnp.zeros_like(l)

    def _block():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = _causal_mask(s, iq, ik, block_q, block_k)
        if segmented:
            s = _segment_mask(s, sq_ref, sk_ref)
        m_new = jnp.maximum(m[:], jnp.max(s, axis=1, keepdims=True))
        corr = jnp.exp(m[:] - m_new)
        p = jnp.exp(s - m_new)
        l[:] = l[:] * corr + jnp.sum(p, axis=1, keepdims=True)
        acc[:] = acc[:] * corr + jax.lax.dot_general(
            p.astype(v_ref.dtype), v_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m[:] = m_new

    if causal:
        # skip blocks strictly above the diagonal
        @pl.when(ik * block_k <= iq * block_q + (block_q - 1))
        def _():
            _block()
    else:
        _block()

    @pl.when(ik == pl.num_programs(2) - 1)
    def _finish():
        denom = jnp.maximum(l[:], 1e-30)
        o_ref[0] = (acc[:] / denom).astype(o_ref.dtype)
        lse_ref[0] = jnp.broadcast_to(m[:] + jnp.log(denom), (l.shape[0], _STAT_W))


def _bwd_dq_kernel(*refs, scale, causal, segmented, block_q, block_k):
    if segmented:
        q_ref, k_ref, v_ref, sq_ref, sk_ref, do_ref, lse_ref, delta_ref, dq_ref, acc = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, acc = refs
        sq_ref = sk_ref = None
    iq, ik = pl.program_id(1), pl.program_id(2)

    @pl.when(ik == 0)
    def _init():
        acc[:] = jnp.zeros_like(acc)

    def _block():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = _causal_mask(s, iq, ik, block_q, block_k)
        if segmented:
            s = _segment_mask(s, sq_ref, sk_ref)
        p = jnp.exp(s - lse_ref[0][:, :1])
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * scale
        acc[:] += jax.lax.dot_general(
            ds.astype(k_ref.dtype), k_ref[0],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        @pl.when(ik * block_k <= iq * block_q + (block_q - 1))
        def _():
            _block()
    else:
        _block()

    @pl.when(ik == pl.num_programs(2) - 1)
    def _finish():
        dq_ref[0] = acc[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(*refs, scale, causal, segmented, block_q, block_k):
    if segmented:
        (q_ref, k_ref, v_ref, sq_ref, sk_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        sq_ref = sk_ref = None
    ik, iq = pl.program_id(1), pl.program_id(2)  # note: kv outer, q inner

    @pl.when(iq == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _block():
        s = jax.lax.dot_general(
            q_ref[0], k_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            s = _causal_mask(s, iq, ik, block_q, block_k)
        if segmented:
            s = _segment_mask(s, sq_ref, sk_ref)
        p = jnp.exp(s - lse_ref[0][:, :1])  # [bq, bk]
        dv_acc[:] += jax.lax.dot_general(
            p.astype(do_ref.dtype), do_ref[0],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do_ref[0], v_ref[0],
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_ref[0][:, :1]) * scale  # [bq, bk]
        dk_acc[:] += jax.lax.dot_general(
            ds.astype(q_ref.dtype), q_ref[0],
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    if causal:
        # q blocks strictly above this kv block contribute nothing
        @pl.when(iq * block_q + (block_q - 1) >= ik * block_k)
        def _():
            _block()
    else:
        _block()

    @pl.when(iq == pl.num_programs(2) - 1)
    def _finish():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _specs(block_rows, head_dim, outer_fixed=True):
    """BlockSpec over [BH, L, D] arrays: (1, block_rows, D) blocks; the row
    index comes from grid dim 1 when ``outer_fixed`` else grid dim 2."""
    if outer_fixed:
        return pl.BlockSpec((1, block_rows, head_dim), lambda b, i, j: (b, i, 0))
    return pl.BlockSpec((1, block_rows, head_dim), lambda b, i, j: (b, j, 0))


def _row_specs(block_rows, outer_fixed=True):
    if outer_fixed:
        return pl.BlockSpec((1, block_rows, _STAT_W), lambda b, i, j: (b, i, 0))
    return pl.BlockSpec((1, block_rows, _STAT_W), lambda b, i, j: (b, j, 0))


def _seg_inputs(seg, bh, l_q, l_k):
    """Segment-id operands for the kernels: query ids broadcast onto the
    [BH, L, _STAT_W] row-statistics layout, key ids pre-transposed to
    [BH, _STAT_W, L] so a kv block is a directly-loadable row vector."""
    seg = seg.astype(jnp.int32)
    seg_q = jnp.broadcast_to(seg[:, :, None], (bh, l_q, _STAT_W))
    seg_k = jnp.broadcast_to(seg[:, None, :], (bh, _STAT_W, l_k))
    return seg_q, seg_k


def _seg_k_spec(block_k, outer_fixed=False):
    """BlockSpec over the transposed [BH, _STAT_W, L] key-segment layout;
    the kv index comes from grid dim 2 unless ``outer_fixed``."""
    if outer_fixed:
        return pl.BlockSpec((1, _STAT_W, block_k), lambda b, i, j: (b, 0, i))
    return pl.BlockSpec((1, _STAT_W, block_k), lambda b, i, j: (b, 0, j))


def _pick_block(seq, preferred):
    """Largest power-of-two block ≤ preferred that divides seq (whole-array
    block for short sequences); pallas pads ragged trailing blocks with
    garbage, so blocks must tile the sequence exactly."""
    if seq <= preferred:
        return seq
    b = preferred
    while b >= 8:  # 8 = minimum sublane tile
        if seq % b == 0:
            return b
        b //= 2
    raise ValueError(
        "sequence length {} has no 8..{} block divisor; pad the sequence "
        "or use plain attention".format(seq, preferred)
    )


def _flash_fwd(q, k, v, seg, scale, causal, block_q, block_k, interpret):
    bh, l_q, d = q.shape
    l_k = k.shape[1]
    block_q = _pick_block(l_q, block_q)
    block_k = _pick_block(l_k, block_k)
    grid = (bh, pl.cdiv(l_q, block_q), pl.cdiv(l_k, block_k))
    segmented = seg is not None
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal, segmented=segmented,
        block_q=block_q, block_k=block_k,
    )
    in_specs = [
        _specs(block_q, d, True),
        _specs(block_k, d, False),
        _specs(block_k, d, False),
    ]
    operands = [q, k, v]
    if segmented:
        seg_q, seg_k = _seg_inputs(seg, bh, l_q, l_k)
        in_specs += [_row_specs(block_q, True), _seg_k_spec(block_k, False)]
        operands += [seg_q, seg_k]
    o, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[_specs(block_q, d, True), _row_specs(block_q, True)],
        out_shape=[
            jax.ShapeDtypeStruct((bh, l_q, d), q.dtype),
            jax.ShapeDtypeStruct((bh, l_q, _STAT_W), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*operands)
    return o, lse


def _compiler_params(interpret):
    """batch/q-block grid dims run in any order; only the kv dim carries the
    accumulator, so mark it 'arbitrary' and the rest 'parallel' for pipelining."""
    if interpret:
        return None
    return pltpu.CompilerParams(
        dimension_semantics=("parallel", "parallel", "arbitrary")
    )


def _flash_bwd(q, k, v, seg, do, o, lse, scale, causal, block_q, block_k, interpret):
    bh, l_q, d = q.shape
    l_k = k.shape[1]
    block_q = _pick_block(l_q, block_q)
    block_k = _pick_block(l_k, block_k)
    segmented = seg is not None
    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    delta = jnp.broadcast_to(delta[:, :, None], (bh, l_q, _STAT_W))
    if segmented:
        seg_q, seg_k = _seg_inputs(seg, bh, l_q, l_k)

    dq_in_specs = [
        _specs(block_q, d, True),
        _specs(block_k, d, False),
        _specs(block_k, d, False),
    ]
    dq_operands = [q, k, v]
    if segmented:
        dq_in_specs += [_row_specs(block_q, True), _seg_k_spec(block_k, False)]
        dq_operands += [seg_q, seg_k]
    dq_in_specs += [
        _specs(block_q, d, True),
        _row_specs(block_q, True),
        _row_specs(block_q, True),
    ]
    dq_operands += [do, lse, delta]
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, scale=scale, causal=causal, segmented=segmented,
            block_q=block_q, block_k=block_k,
        ),
        grid=(bh, pl.cdiv(l_q, block_q), pl.cdiv(l_k, block_k)),
        in_specs=dq_in_specs,
        out_specs=_specs(block_q, d, True),
        out_shape=jax.ShapeDtypeStruct((bh, l_q, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*dq_operands)

    dkv_in_specs = [
        _specs(block_q, d, False),  # q indexed by inner grid dim
        _specs(block_k, d, True),  # k fixed per outer step
        _specs(block_k, d, True),
    ]
    dkv_operands = [q, k, v]
    if segmented:
        dkv_in_specs += [_row_specs(block_q, False), _seg_k_spec(block_k, True)]
        dkv_operands += [seg_q, seg_k]
    dkv_in_specs += [
        _specs(block_q, d, False),
        _row_specs(block_q, False),
        _row_specs(block_q, False),
    ]
    dkv_operands += [do, lse, delta]
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, scale=scale, causal=causal, segmented=segmented,
            block_q=block_q, block_k=block_k,
        ),
        grid=(bh, pl.cdiv(l_k, block_k), pl.cdiv(l_q, block_q)),
        in_specs=dkv_in_specs,
        out_specs=[_specs(block_k, d, True), _specs(block_k, d, True)],
        out_shape=[
            jax.ShapeDtypeStruct((bh, l_k, d), k.dtype),
            jax.ShapeDtypeStruct((bh, l_k, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(*dkv_operands)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash_attention_bhld(q, k, v, seg, scale, causal, block_q, block_k, interpret):
    o, _ = _flash_fwd(q, k, v, seg, scale, causal, block_q, block_k, interpret)
    return o


def _flash_attention_fwd(q, k, v, seg, scale, causal, block_q, block_k, interpret):
    o, lse = _flash_fwd(q, k, v, seg, scale, causal, block_q, block_k, interpret)
    return o, (q, k, v, seg, o, lse)


def _flash_attention_bwd(scale, causal, block_q, block_k, interpret, res, do):
    q, k, v, seg, o, lse = res
    dq, dk, dv = _flash_bwd(
        q, k, v, seg, do, o, lse, scale, causal, block_q, block_k, interpret
    )
    # integer segment ids carry no gradient (None = zero cotangent)
    return dq, dk, dv, None


_flash_attention_bhld.defvjp(_flash_attention_fwd, _flash_attention_bwd)


def flash_attention(
    q, k, v, causal=False, scale=None, segment_ids=None,
    block_q=DEFAULT_BLOCK_Q, block_k=DEFAULT_BLOCK_K, interpret=False,
):
    """Flash attention over ``[batch, heads, seq, head_dim]`` arrays.

    Drop-in replacement for
    :func:`tensorflowonspark_tpu.parallel.ring_attention.plain_attention`
    with O(L·D) memory. Sequence lengths must divide into the block sizes
    (pad upstream; the transformer pads its own inputs).

    ``segment_ids`` (``int32 [batch, seq]``, 0 = padding) fences packed
    sequences: scores between positions with different ids are masked, so
    pack neighbours never cross-attend (the text plane's block-diagonal
    contract). Ids are shared across heads and carry no gradient.
    """
    b, h, l_q, d = q.shape
    if scale is None:
        scale = 1.0 / math.sqrt(d)
    merge = lambda t: t.reshape(b * h, t.shape[2], d)  # noqa: E731
    seg = None
    if segment_ids is not None:
        seg = jnp.broadcast_to(
            segment_ids.astype(jnp.int32)[:, None, :], (b, h, l_q)
        ).reshape(b * h, l_q)
    o = _flash_attention_bhld(
        merge(q), merge(k), merge(v), seg, float(scale), bool(causal),
        int(block_q), int(block_k), bool(interpret),
    )
    return o.reshape(b, h, l_q, d)
