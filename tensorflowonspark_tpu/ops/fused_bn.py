"""Training-mode BatchNorm as fused pallas TPU kernels (+ custom VJP).

The r4 on-chip breakdown (docs/perf.md) charged **28% of the ResNet-50 step
to BatchNorm** — HBM-bound statistics/normalize passes over large activations
that XLA cannot fold into the convs in training mode. This module is the
measured attempt VERDICT r4 asked for: the same trick flash attention plays
(do everything to a VMEM-resident tile in one visit), applied to BN.

HBM traffic per training step over an ``[R, C]`` activation (R = N*H*W):

==============  =============================  ==========================
pass             this module                    naive (unfused) lowering
==============  =============================  ==========================
forward stats    1 read (sum + sumsq fused)     2 reads (mean, then var)
forward norm     1 read + 1 write               1 read + 1 write
backward red.    1 read of (x, dy)              2+ reads (dbeta, dgamma)
backward dx      1 read of (x, dy) + 1 write    1-2 reads + 1 write
==============  =============================  ==========================

XLA already fuses much of the naive column; whether the pallas version wins
on real shapes is exactly the experiment — results live in docs/perf.md
(r5 "BatchNorm attack"). ``interpret=True`` runs the kernels on CPU for
correctness tests.

Semantics notes:

* statistics are computed over the kernel's shard. On a 1-chip run this is
  identical to ``flax.linen.BatchNorm``; under data parallelism it is
  per-replica BN (what the reference's MultiWorkerMirroredStrategy did —
  resnet_imagenet_main.py used per-replica BN), where the flax module under
  pjit computes global sync-BN. The ``FusedBatchNorm`` module documents this.
* the returned ``(mean, var)`` are detached (running-average inputs); the
  VJP flows through ``y`` only.
"""

import functools
import logging

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

logger = logging.getLogger(__name__)

#: default row-block; _pick_block shrinks it to divide R exactly
DEFAULT_BLOCK_R = 512


def _pick_block_or_none(rows, preferred):
    """Largest power-of-two block ≤ preferred dividing rows exactly, or
    None when no 8..preferred divisor exists (pallas pads ragged trailing
    blocks with garbage — same rule as flash attention's ``_pick_block``)."""
    if rows <= preferred:
        return rows
    b = preferred
    while b >= 8:
        if rows % b == 0:
            return b
        b //= 2
    return None


def _pick_block(rows, preferred):
    """Like :func:`_pick_block_or_none` but raising — for direct
    :func:`fused_batch_norm` callers, where silently changing the math
    would be worse than the trace-time error. :class:`FusedBatchNorm`
    instead falls back to the flax-equivalent path."""
    b = _pick_block_or_none(rows, preferred)
    if b is None:
        raise ValueError(
            "row count {} has no 8..{} block divisor; reshape or pad upstream".format(
                rows, preferred
            )
        )
    return b


def _compiler_params(interpret):
    if interpret:
        return None
    # the single grid dim carries the stat accumulators -> 'arbitrary'
    return pltpu.CompilerParams(dimension_semantics=("arbitrary",))


def _stats_kernel(x_ref, mean_ref, var_ref, sum_acc, sq_acc, *, n_rows):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        sum_acc[:] = jnp.zeros_like(sum_acc)
        sq_acc[:] = jnp.zeros_like(sq_acc)

    xb = x_ref[...].astype(jnp.float32)
    # one visit computes BOTH first and second moments (the fusion XLA's
    # mean-then-variance lowering doesn't always get)
    sum_acc[:] += jnp.sum(xb, axis=0, keepdims=True)
    sq_acc[:] += jnp.sum(xb * xb, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _finish():
        m = sum_acc[:] / n_rows
        mean_ref[...] = m
        var_ref[...] = jnp.maximum(sq_acc[:] / n_rows - m * m, 0.0)


def _norm_kernel(x_ref, mean_ref, var_ref, gamma_ref, beta_ref, y_ref, *, eps):
    xb = x_ref[...].astype(jnp.float32)
    inv = jax.lax.rsqrt(var_ref[...] + eps)
    y_ref[...] = (
        (xb - mean_ref[...]) * (inv * gamma_ref[...]) + beta_ref[...]
    ).astype(y_ref.dtype)


def _bwd_reduce_kernel(
    x_ref, dy_ref, mean_ref, var_ref, dgamma_ref, dbeta_ref, dg_acc, db_acc, *, eps
):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_acc[:] = jnp.zeros_like(dg_acc)
        db_acc[:] = jnp.zeros_like(db_acc)

    xb = x_ref[...].astype(jnp.float32)
    dyb = dy_ref[...].astype(jnp.float32)
    xhat = (xb - mean_ref[...]) * jax.lax.rsqrt(var_ref[...] + eps)
    db_acc[:] += jnp.sum(dyb, axis=0, keepdims=True)
    dg_acc[:] += jnp.sum(dyb * xhat, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _finish():
        dgamma_ref[...] = dg_acc[:]
        dbeta_ref[...] = db_acc[:]


def _bwd_dx_kernel(
    x_ref, dy_ref, mean_ref, var_ref, gamma_ref, dgamma_ref, dbeta_ref, dx_ref,
    *, eps, n_rows
):
    xb = x_ref[...].astype(jnp.float32)
    dyb = dy_ref[...].astype(jnp.float32)
    inv = jax.lax.rsqrt(var_ref[...] + eps)
    xhat = (xb - mean_ref[...]) * inv
    # dx = (gamma * inv / N) * (N*dy - dbeta - xhat * dgamma)
    dx = (gamma_ref[...] * inv / n_rows) * (
        n_rows * dyb - dbeta_ref[...] - xhat * dgamma_ref[...]
    )
    dx_ref[...] = dx.astype(dx_ref.dtype)


def _row_spec(block_r, n_ch):
    return pl.BlockSpec((block_r, n_ch), lambda i: (i, 0))


def _ch_spec(n_ch):
    return pl.BlockSpec((1, n_ch), lambda i: (0, 0))


def _bn_stats(x2d, block_r, interpret):
    rows, n_ch = x2d.shape
    grid = (pl.cdiv(rows, block_r),)
    return pl.pallas_call(
        functools.partial(_stats_kernel, n_rows=float(rows)),
        grid=grid,
        in_specs=[_row_spec(block_r, n_ch)],
        out_specs=[_ch_spec(n_ch), _ch_spec(n_ch)],
        out_shape=[
            jax.ShapeDtypeStruct((1, n_ch), jnp.float32),
            jax.ShapeDtypeStruct((1, n_ch), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, n_ch), jnp.float32),
            pltpu.VMEM((1, n_ch), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(x2d)


def _bn_normalize(x2d, mean, var, gamma, beta, eps, block_r, interpret):
    rows, n_ch = x2d.shape
    return pl.pallas_call(
        functools.partial(_norm_kernel, eps=eps),
        grid=(pl.cdiv(rows, block_r),),
        in_specs=[_row_spec(block_r, n_ch)] + [_ch_spec(n_ch)] * 4,
        out_specs=_row_spec(block_r, n_ch),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(x2d, mean, var, gamma, beta)


# the WHOLE train path (stats + normalize) lives inside one custom_vjp:
# pallas_call has no JVP rule, so every kernel invocation must sit behind
# this boundary or jax.grad dies trying to linearize it
@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_bn_2d(x2d, gamma, beta, eps, block_r, interpret):
    y, mean, var = _fused_bn_2d_fwd(x2d, gamma, beta, eps, block_r, interpret)[0]
    return y, mean, var


def _fused_bn_2d_fwd(x2d, gamma, beta, eps, block_r, interpret):
    n_ch = x2d.shape[1]
    mean, var = _bn_stats(x2d, block_r, interpret)
    g2 = gamma.reshape(1, n_ch)
    b2 = beta.reshape(1, n_ch)
    y = _bn_normalize(x2d, mean, var, g2, b2, eps, block_r, interpret)
    return (y, mean, var), (x2d, gamma, mean, var)


def _fused_bn_2d_bwd(eps, block_r, interpret, res, cts):
    # d(mean)/d(var) cotangents are ignored by design: the batch statistics'
    # dependency on x is folded into dx below, and the public wrapper
    # detaches the returned stats (running-average inputs)
    dy, _dmean, _dvar = cts
    x2d, gamma, mean, var = res
    rows, n_ch = x2d.shape
    gamma = gamma.reshape(1, n_ch)
    dgamma, dbeta = pl.pallas_call(
        functools.partial(_bwd_reduce_kernel, eps=eps),
        grid=(pl.cdiv(rows, block_r),),
        in_specs=[_row_spec(block_r, n_ch)] * 2 + [_ch_spec(n_ch)] * 2,
        out_specs=[_ch_spec(n_ch), _ch_spec(n_ch)],
        out_shape=[
            jax.ShapeDtypeStruct((1, n_ch), jnp.float32),
            jax.ShapeDtypeStruct((1, n_ch), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, n_ch), jnp.float32),
            pltpu.VMEM((1, n_ch), jnp.float32),
        ],
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(x2d, dy, mean, var)
    dx = pl.pallas_call(
        functools.partial(_bwd_dx_kernel, eps=eps, n_rows=float(rows)),
        grid=(pl.cdiv(rows, block_r),),
        in_specs=[_row_spec(block_r, n_ch)] * 2 + [_ch_spec(n_ch)] * 5,
        out_specs=_row_spec(block_r, n_ch),
        out_shape=jax.ShapeDtypeStruct(x2d.shape, x2d.dtype),
        compiler_params=_compiler_params(interpret),
        interpret=interpret,
    )(x2d, dy, mean, var, gamma, dgamma, dbeta)
    # gamma/beta grads reshape back to the [C] primal shape
    return dx, dgamma[0], dbeta[0]


_fused_bn_2d.defvjp(_fused_bn_2d_fwd, _fused_bn_2d_bwd)


def fused_batch_norm(x, gamma, beta, eps=1e-5, block_r=DEFAULT_BLOCK_R, interpret=False):
    """Training-mode batch norm over the last axis of ``x`` (channels):
    returns ``(y, mean, var)`` with batch statistics computed in one fused
    HBM pass and a pallas backward.

    ``x`` is ``[..., C]`` (any leading dims — NHWC activations flatten to
    ``[N*H*W, C]``); ``gamma``/``beta`` are ``[C]`` float32. ``mean``/``var``
    are detached ``[C]`` float32 (feed the running-average update; gradients
    flow through ``y`` only, where the batch-stat dependency on ``x`` is
    already folded into the custom VJP's ``dx``).
    """
    n_ch = x.shape[-1]
    x2d = x.reshape(-1, n_ch)
    block = _pick_block(x2d.shape[0], block_r)
    y2d, mean, var = _fused_bn_2d(
        x2d, gamma.astype(jnp.float32), beta.astype(jnp.float32),
        float(eps), int(block), bool(interpret),
    )
    return (
        y2d.reshape(x.shape),
        jax.lax.stop_gradient(mean[0]),
        jax.lax.stop_gradient(var[0]),
    )


class FusedBatchNorm(nn.Module):
    """Drop-in for ``flax.linen.BatchNorm`` (same param/``batch_stats``
    variable names, so checkpoints interchange) whose TRAIN path runs the
    fused pallas kernels. Eval (``use_running_average=True``) is plain
    jax — XLA fuses the affine into neighbors there already.

    Statistics are per-shard (per-replica BN, the reference's
    MultiWorkerMirroredStrategy behavior); the flax module under pjit
    gives global sync-BN instead — see module docstring.
    """

    #: None = decided at call time (exactly flax.linen.BatchNorm's contract:
    #: pass it in the constructor or the call, never both)
    use_running_average: bool = None
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: object = None
    scale_init: object = nn.initializers.ones
    bias_init: object = nn.initializers.zeros
    block_r: int = DEFAULT_BLOCK_R
    interpret: bool = False

    @nn.compact
    def __call__(self, x, use_running_average=None):
        use_ra = nn.merge_param(
            "use_running_average", self.use_running_average, use_running_average
        )
        n_ch = x.shape[-1]
        scale = self.param("scale", self.scale_init, (n_ch,), jnp.float32)
        bias = self.param("bias", self.bias_init, (n_ch,), jnp.float32)
        ra_mean = self.variable(
            "batch_stats", "mean", lambda s: jnp.zeros(s, jnp.float32), (n_ch,)
        )
        ra_var = self.variable(
            "batch_stats", "var", lambda s: jnp.ones(s, jnp.float32), (n_ch,)
        )
        out_dtype = self.dtype or x.dtype
        if use_ra:
            inv = jax.lax.rsqrt(ra_var.value + self.epsilon) * scale
            y = (x.astype(jnp.float32) - ra_mean.value) * inv + bias
            return y.astype(out_dtype)
        rows = 1
        for d in x.shape[:-1]:
            rows *= d
        if _pick_block_or_none(rows, self.block_r) is None:
            # e.g. an odd per-shard batch: no power-of-two row block divides
            # the activation, so the pallas kernels would pad garbage. Fall
            # back to the flax-equivalent jax spelling (ADVICE r5) instead
            # of raising at trace time — same math, XLA's own BN lowering.
            logger.warning(
                "fused BN: %d rows (shape %s) have no 8..%d block divisor; "
                "falling back to the plain XLA batch-norm path",
                rows, x.shape, self.block_r,
            )
            xf = x.astype(jnp.float32)
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(xf, axis=axes)
            var = jnp.maximum(jnp.mean(xf * xf, axis=axes) - mean * mean, 0.0)
            y = (xf - mean) * jax.lax.rsqrt(var + self.epsilon) * scale + bias
            mean = jax.lax.stop_gradient(mean)
            var = jax.lax.stop_gradient(var)
        else:
            y, mean, var = fused_batch_norm(
                x, scale, bias, eps=self.epsilon,
                block_r=self.block_r, interpret=self.interpret,
            )
        if not self.is_initializing():
            m = self.momentum
            ra_mean.value = m * ra_mean.value + (1.0 - m) * mean
            ra_var.value = m * ra_var.value + (1.0 - m) * var
        return y.astype(out_dtype)
