"""Model-bundle inference server + batch-inference CLI — the JVM-inference
equivalent.

The reference shipped a Scala/JNI stack so JVM Spark jobs could run batch
inference without Python (/root/reference/src/main/scala/com/yahoo/
tensorflowonspark/Inference.scala:17, TFModel.scala:38 — SavedModelBundle via
libtensorflow). A jax model has no JNI runtime to embed, so the TPU-native
equivalent is a host RPC: this server owns the model bundle (and the TPU
chips) in a Python process, and any JVM executor talks to it over a tiny
length-prefixed protocol (``jvm/`` ships a dependency-free Java client for
Spark mapPartitions; the wire format is specified in jvm/README.md).

Protocol (4-byte big-endian length + UTF-8 JSON, same framing as the
reservation control plane):

* ``{"type": "ping"}`` → ``{"type": "pong"}``
* ``{"type": "info"}`` → ``{"type": "info", "export_dir": ..., "ready": true}``
* ``{"type": "predict", "inputs": {name: nested-lists, ...}}`` →
  ``{"type": "result", "outputs": {name: nested-lists, ...}}``
* ``{"type": "predict_binary", "columns": [{"name","dtype","shape"},...]}``
  followed by ONE raw frame (4-byte BE length + the columns' C-contiguous
  little-endian buffers concatenated in order) →
  ``{"type": "result_binary", "columns": [...]}`` + one raw frame — the
  native-buffer lane matching the class of the reference's JVM tensor path
  (TFModel.scala:121-244 moved tensors as nio buffers, not text).
* anything else / failure → ``{"type": "error", "message": ...}`` (an error
  reply is NEVER followed by a raw frame).

**Trust boundary**: a model bundle contains pickled CODE
(``predict_builder.pkl``), executed when the bundle loads — the jax analogue
of a SavedModel executing its graph, but with Python's full power. Serve
only bundles you produced or vetted. For bundles from untrusted storage use
``--trusted_builder MODULE:ATTR``: the builder comes from your own code and
weights load from ``weights.npz`` with ``allow_pickle=False``, so nothing in
``--export_dir`` is unpickled (details: train/export.py docstring).

Batch CLI (the reference's ``Inference.scala:52-79`` analogue — TFRecords
in, predictions out as files, no server involved):

    python -m tensorflowonspark_tpu.serving infer \
        --tfrecords /data/shards --export_dir /models/bundle \
        --output /data/preds [--format json|tfrecord] [--batch_size 128] \
        [--input_mapping feature=tensor ...] [--output_mapping tensor=col ...]

Start the server standalone:  ``python -m tensorflowonspark_tpu.serving
serve --export_dir /path/bundle --port 8500`` (bare ``--export_dir ...``
still serves, for round-2 compat).
"""

import argparse
import json
import logging
import os
import queue
import socket
import threading

from tensorflowonspark_tpu import chaos, obs, resilience
from tensorflowonspark_tpu.reservation import MessageSocket

logger = logging.getLogger(__name__)

#: binary tensor frames can be big (a 128-row ResNet batch is ~77 MB f32);
#: framing itself lives on MessageSocket (send_raw/recv_raw) so one
#: implementation owns the wire format
MAX_BINARY_FRAME = int(os.environ.get("TOS_SERVING_MAX_FRAME", str(512 << 20)))


def _columns_to_arrays(columns, payload):
    """Decode the binary-lane column descriptors + concatenated payload."""
    import numpy as np

    arrays = {}
    offset = 0
    for col in columns:
        dtype = np.dtype(col["dtype"])
        shape = tuple(int(d) for d in col["shape"])
        nbytes = int(dtype.itemsize * int(np.prod(shape, dtype=np.int64)))
        if offset + nbytes > len(payload):
            raise ValueError("binary payload shorter than declared columns")
        arrays[col["name"]] = np.frombuffer(
            payload, dtype=dtype, count=int(np.prod(shape, dtype=np.int64)), offset=offset
        ).reshape(shape)
        offset += nbytes
    return arrays


def _arrays_to_columns(arrays):
    import numpy as np

    columns, parts = [], []
    for name, arr in arrays.items():
        arr = np.ascontiguousarray(arr)
        if arr.dtype.byteorder == ">":  # ship little-endian on the wire
            arr = arr.astype(arr.dtype.newbyteorder("<"))
        columns.append({"name": name, "dtype": arr.dtype.str, "shape": list(arr.shape)})
        parts.append(arr.tobytes())
    return columns, b"".join(parts)


class Overloaded(RuntimeError):
    """Request shed because the predictor's pending queue is full."""


class DeadlineExceeded(RuntimeError):
    """Request shed because it waited in queue past its deadline."""


class _Predictor:
    """Single predictor thread owning the chips: requests queue up, and
    same-signature requests that are waiting together coalesce into ONE
    model invocation (split back row-wise) — the replacement for round 2's
    global lock, which serialized N clients into N dispatches.

    A signature is (sorted column names, per-column dtype + trailing shape);
    only axis-0 (batch) concatenation is ever performed, so results are
    bit-identical to individual runs for row-wise models.

    Tail-latency policy (VERDICT r4): the pending queue is BOUNDED
    (``max_pending`` requests, default ``TOS_SERVING_MAX_PENDING`` = 256) —
    a full queue sheds new requests with :class:`Overloaded` instead of
    growing an unbounded backlog behind a slow model; and each request may
    carry a deadline (``deadline_ms``, default ``TOS_SERVING_DEADLINE_MS``,
    0 = off) — a request still queued when its deadline passes is failed
    with :class:`DeadlineExceeded` rather than served arbitrarily late.
    Both surface to clients as the protocol's error reply.
    """

    def __init__(self, predict_fn, params, model_state, max_rows=None,
                 max_pending=None, deadline_ms=None):
        import collections

        self._predict_fn = predict_fn
        self._params = params
        self._model_state = model_state
        self._max_rows = max_rows or int(os.environ.get("TOS_SERVING_COALESCE_ROWS", "1024"))
        self._max_pending = max_pending or int(os.environ.get("TOS_SERVING_MAX_PENDING", "256"))
        self._deadline_secs = (
            deadline_ms if deadline_ms is not None
            else int(os.environ.get("TOS_SERVING_DEADLINE_MS", "0"))
        ) / 1000.0
        # +1 slot so stop()'s sentinel can always enqueue behind a full load
        self._q = queue.Queue(maxsize=self._max_pending + 1)
        self._stop = object()
        #: exact pending count: incremented in submit, decremented when the
        #: request's future resolves — unlike qsize()+backlog it also covers
        #: the batch in flight inside _run, so the Overloaded gate is a hard
        #: bound (ADVICE r5)
        self._pending = 0
        #: deferred non-matching requests, served FIRST next cycle — keeps
        #: FIFO so a minority-signature request can't be starved by sustained
        #: majority-signature load
        self._backlog = collections.deque()
        self._stopped = False
        #: newest request's column signature — what a hot-swap warm-up
        #: predict should look like (serving_mesh warms the new compile off
        #: the request path before flipping)
        self._last_spec = None
        self._submit_lock = threading.Lock()
        self._requests_c = obs.counter(
            "serving_requests_total", help="predict requests submitted (shed ones included)"
        )
        self._shed_over_c = obs.counter(
            "serving_shed_overloaded_total", help="requests shed: pending queue full"
        )
        self._shed_deadline_c = obs.counter(
            "serving_shed_deadline_total", help="requests shed: queued past their deadline"
        )
        self._pending_g = obs.gauge(
            "serving_pending_depth", help="requests pending (queue + deferred backlog)"
        )
        self._latency_h = obs.histogram(
            "serving_request_seconds", help="end-to-end predict latency, submit to result"
        )
        self._thread = threading.Thread(target=self._run, name="tos-predictor", daemon=True)
        self._thread.start()

    def submit(self, arrays):
        """Blocking predict; thread-safe. Returns the outputs dict.

        Rejects malformed requests HERE (0-d arrays, mismatched leading
        dims, empty input dict) so a bad request becomes the caller's error
        reply, never a predictor-thread crash. Sheds with
        :class:`Overloaded` when ``max_pending`` requests are queued."""
        import time as _time

        import numpy as np
        from concurrent.futures import Future

        self._requests_c.inc()
        if not arrays:
            raise ValueError("predict requires at least one input column")
        lead = set()
        spec = []
        for name, arr in arrays.items():
            arr = np.asarray(arr)
            if arr.ndim == 0:
                raise ValueError(
                    "input {!r} is a scalar; batch inputs need a leading "
                    "(row) dimension".format(name)
                )
            lead.add(arr.shape[0])
            spec.append((name, arr.dtype.str, tuple(arr.shape[1:])))
        if len(lead) != 1:
            raise ValueError("input columns disagree on row count: {}".format(sorted(lead)))

        deadline = (
            _time.monotonic() + self._deadline_secs if self._deadline_secs > 0 else None
        )
        if chaos.active and chaos.fire("serving.overload"):
            self._shed_over_c.inc()
            raise Overloaded("chaos: injected transient overload; request shed")
        fut = Future()
        # the lock orders every put against stop()'s sentinel: a submit that
        # wins the race enqueues BEFORE the sentinel (the run thread serves
        # it), one that loses raises — no future can be orphaned
        with self._submit_lock:
            self._last_spec = tuple(sorted(spec))
            if self._stopped:
                raise RuntimeError("predictor stopped")
            # _pending counts every unresolved request — queued, parked in
            # the backlog, AND coalesced into the batch _run is currently
            # dispatching — so max_pending is exact: the old
            # qsize()+backlog read went soft by one in-flight batch
            self._pending_g.set(self._pending)
            if self._pending >= self._max_pending:
                self._shed_over_c.inc()
                raise Overloaded(
                    "server overloaded: {} requests pending; request shed".format(
                        self._max_pending
                    )
                )
            self._pending += 1
            # registered before the put: the consumer cannot resolve a
            # future it has not yet been handed
            fut.add_done_callback(self._release_pending)
            self._q.put((arrays, fut, deadline))
        with self._latency_h.time():
            return fut.result()

    def _release_pending(self, _fut):
        with self._submit_lock:
            self._pending -= 1
            self._pending_g.set(self._pending)

    def warm_spec(self):
        """Column signature of the newest submitted request — sorted
        ``(name, dtype, trailing shape)`` triples, or None before the first
        request."""
        with self._submit_lock:
            return self._last_spec

    def stop(self):
        with self._submit_lock:
            if not self._stopped:
                # first stop only: the bounded queue holds at most
                # max_pending requests (submit gates on that), so the +1
                # slot guarantees this put never blocks — but a SECOND
                # sentinel would fill the queue and block forever while
                # holding _submit_lock. stop() must stay idempotent
                # (server shutdown paths can reach it more than once).
                self._stopped = True
                self._q.put(self._stop)
        self._thread.join(timeout=60)
        if self._thread.is_alive():
            # an in-flight predict (e.g. a first-call XLA compile) outlived
            # the join: the thread still owns the queue/backlog and will
            # serve everything up to the sentinel, then exit. Draining here
            # would steal the sentinel and race its Future operations.
            logger.warning("predictor still busy at stop(); it will drain and exit")
            return
        # thread exited: fail anything still queued so no caller blocks
        # forever on a future that will never resolve
        leftovers = list(self._backlog)
        self._backlog.clear()
        while True:
            try:
                leftovers.append(self._q.get_nowait())
            except queue.Empty:
                break
        for item in leftovers:
            if item is not self._stop:
                item[1].set_exception(RuntimeError("predictor stopped"))

    # -- internals ----------------------------------------------------------

    @staticmethod
    def _signature(arrays):
        return tuple(
            (name, arrays[name].dtype.str, arrays[name].shape[1:])
            for name in sorted(arrays)
        )

    def _expired(self, item):
        """Fail a queued request whose deadline passed; True if it was."""
        import time as _time

        if item[2] is not None and _time.monotonic() > item[2]:
            self._shed_deadline_c.inc()
            item[1].set_exception(
                DeadlineExceeded(
                    "request shed: queued past its {:.0f} ms deadline".format(
                        self._deadline_secs * 1000
                    )
                )
            )
            return True
        return False

    def _run(self):
        import numpy as np

        while True:
            item = self._backlog.popleft() if self._backlog else self._q.get()
            if item is self._stop:
                # drain anything that raced in behind the sentinel
                for pending in self._backlog:
                    pending[1].set_exception(RuntimeError("predictor stopped"))
                self._backlog.clear()
                return
            if self._expired(item):
                continue
            batch = [item]
            try:
                sig = self._signature(item[0])
                rows = next(iter(item[0].values())).shape[0]
            except Exception as e:  # malformed request that slipped validation
                item[1].set_exception(e)
                continue
            # coalesce same-signature requests: deferred (older) ones first,
            # then whatever is already waiting on the queue. Non-matching
            # requests keep FIFO order in the backlog, whose head seeds the
            # next cycle — mixed-signature load batches per signature instead
            # of degrading to one request per dispatch. A request that would
            # push the batch past max_rows is DEFERRED, not appended
            # (ADVICE r4): the dispatch shape stays within the operator's
            # bound, so the power-of-two padding below keeps its shape-reuse
            # guarantee under sustained load.
            deferred = []

            def _admit(nxt):
                """Coalesce nxt into the batch, defer it, or expire it —
                one admission policy shared by both scan loops below."""
                nonlocal rows
                if self._expired(nxt):
                    return
                if nxt[0] and self._signature(nxt[0]) == sig:
                    nxt_rows = next(iter(nxt[0].values())).shape[0]
                    if rows + nxt_rows <= self._max_rows:
                        batch.append(nxt)
                        rows += nxt_rows
                        return
                deferred.append(nxt)

            saw_stop = False
            while self._backlog and rows < self._max_rows:
                nxt = self._backlog.popleft()
                if nxt is self._stop:
                    deferred.append(nxt)
                    saw_stop = True
                    break
                _admit(nxt)
            while not saw_stop and rows < self._max_rows:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                if nxt is self._stop:
                    deferred.append(nxt)
                    break
                _admit(nxt)
            # deferred items are older than anything left in the backlog
            # (the pending gauge is driven by _release_pending)
            self._backlog.extendleft(reversed(deferred))

            if chaos.active:
                chaos.delay("serving.latency")
            try:
                if len(batch) == 1:
                    arrays = batch[0][0]
                else:
                    arrays = {
                        name: np.concatenate([req[0][name] for req in batch])
                        for name in batch[0][0]
                    }
                    # pad coalesced batches up to a power-of-two bucket:
                    # arbitrary concatenated row counts would make every
                    # distinct total a fresh XLA compile (seconds-long on
                    # TPU), serializing the very requests coalescing exists
                    # to speed up. Single requests keep their exact shape —
                    # the client's batch size is the client's contract.
                    # Row-wise semantics make the padding rows inert; the
                    # per-request split below never reads them. Coalesced
                    # rows never exceed _max_rows (overshooters are
                    # deferred above), so the cap only canonicalizes the
                    # top bucket when _max_rows is not a power of two.
                    bucket = min(1 << (rows - 1).bit_length(), self._max_rows)
                    if bucket > rows:
                        arrays = {
                            name: np.concatenate(
                                [a, np.zeros((bucket - rows,) + a.shape[1:], a.dtype)]
                            )
                            for name, a in arrays.items()
                        }
                outputs = self._predict_fn(self._params, self._model_state, arrays)
                if not isinstance(outputs, dict):
                    outputs = {"output": outputs}
                outputs = {name: np.asarray(v) for name, v in outputs.items()}
            except Exception as e:
                for _arrays, fut, _deadline in batch:
                    fut.set_exception(e)
                continue
            if len(batch) == 1:
                batch[0][1].set_result(outputs)
            else:
                start = 0
                for req_arrays, fut, _deadline in batch:
                    n = next(iter(req_arrays.values())).shape[0]
                    fut.set_result(
                        {name: v[start : start + n] for name, v in outputs.items()}
                    )
                    start += n


class ProtocolServer:
    """Socket/accept/connection machinery for the wire protocol in the
    module docstring, decoupled from where predictions actually run.
    Subclasses supply ``_submit(arrays) -> outputs`` (dict of numpy arrays
    in and out) and ``_info() -> dict``: :class:`InferenceServer` plugs in
    a local :class:`_Predictor`; the mesh frontend
    (:class:`~tensorflowonspark_tpu.serving_mesh.MeshFrontend`) plugs in a
    replica router.

    Connections are handled by a bounded thread pool
    (``TOS_SERVING_THREADS``, default 32) instead of round 2's unbounded
    thread-per-connection."""

    def __init__(self, host="", port=0, max_threads=None, name="tos-serving"):
        self._max_threads = max_threads or int(os.environ.get("TOS_SERVING_THREADS", "32"))
        self._name = name
        self._pool = None
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(64)
        self.address = self._sock.getsockname()
        self._shutdown = threading.Event()
        self._thread = None
        #: live client connections — closed on stop() so pool threads blocked
        #: in recv() unblock (pool threads are non-daemon; without this an
        #: idle persistent client would hang interpreter shutdown)
        self._conns = set()
        self._conns_lock = threading.Lock()

    def start(self):
        from concurrent.futures import ThreadPoolExecutor

        self._pool = ThreadPoolExecutor(
            max_workers=self._max_threads, thread_name_prefix=self._name
        )
        self._thread = threading.Thread(
            target=self._serve, name=self._name + "-accept", daemon=True
        )
        self._thread.start()
        logger.info("%s listening at %s", self._name, self.address)
        return self.address

    def stop(self):
        self._shutdown.set()
        try:
            with socket.create_connection(("127.0.0.1", self.address[1]), timeout=1):
                pass
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=10)
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        self._stop_workload()
        if self._pool is not None:
            self._pool.shutdown(wait=True)
        try:
            self._sock.close()
        except OSError:
            pass

    def kill(self):
        """SIGKILL-shaped death for chaos tests: close the listening socket
        and every live connection with no drain — in-flight requests see a
        connection reset, exactly what a killed process produces.
        :meth:`stop` may still be called afterwards to reap threads."""
        self._shutdown.set()
        try:
            self._sock.close()
        except OSError:
            pass
        with self._conns_lock:
            conns = list(self._conns)
        for conn in conns:
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    # -- subclass surface ------------------------------------------------------

    def _submit(self, arrays):
        """Run one predict (dict of numpy arrays -> dict of numpy arrays)."""
        raise NotImplementedError

    def _info(self):
        return {"type": "info", "ready": True}

    def _stop_workload(self):
        """Hook: drain subclass-owned work after connections close and
        before the handler pool shuts down."""

    # -- internals ------------------------------------------------------------

    def _serve(self):
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            if self._shutdown.is_set():
                conn.close()
                return
            self._pool.submit(self._handle_conn, conn)

    def _handle_conn(self, conn):
        with self._conns_lock:
            self._conns.add(conn)
        # close the race with stop(): registration above + this check means
        # any connection either appears in stop()'s snapshot or observes the
        # shutdown flag here — no handler can survive blocked in recv()
        if self._shutdown.is_set():
            try:
                conn.close()
            finally:
                with self._conns_lock:
                    self._conns.discard(conn)
            return
        msock = MessageSocket(conn)
        try:
            while True:
                try:
                    msg = msock.recv()
                except (OSError, ValueError):
                    return
                if msg is None:
                    return
                if chaos.active and chaos.fire("serving.conn_drop"):
                    return  # close the connection mid-request
                try:
                    if isinstance(msg, dict) and msg.get("type") == "predict_binary":
                        self._handle_binary(msock, msg)
                    else:
                        msock.send(self._handle(msg))
                except (OSError, ConnectionError):
                    return
        finally:
            msock.close()
            with self._conns_lock:
                self._conns.discard(conn)

    def _handle_binary(self, msock, msg):
        # recv_raw consumes oversize frames before raising, so an error
        # reply always leaves the stream positioned at the next message
        # (the documented lone-JSON-frame error contract)
        try:
            payload = msock.recv_raw(MAX_BINARY_FRAME)
        except ValueError as e:
            msock.send({"type": "error", "message": str(e)})
            return
        if payload is None:
            raise ConnectionError("client closed mid-request")
        try:
            arrays = _columns_to_arrays(msg.get("columns") or [], payload)
            outputs = self._submit(arrays)
            columns, out_payload = _arrays_to_columns(outputs)
        except (Overloaded, DeadlineExceeded) as e:
            # expected under load-shedding policy: no traceback spam
            logger.warning("binary predict shed: %s", e)
            msock.send({"type": "error", "message": "{}: {}".format(type(e).__name__, e)})
            return
        except Exception as e:
            logger.exception("binary predict failed")
            msock.send({"type": "error", "message": "{}: {}".format(type(e).__name__, e)})
            return
        msock.send({"type": "result_binary", "columns": columns})
        msock.send_raw(out_payload)

    def _handle(self, msg):
        kind = msg.get("type") if isinstance(msg, dict) else None
        if kind == "ping":
            return {"type": "pong"}
        if kind == "info":
            return self._info()
        if kind == "predict":
            try:
                return {"type": "result", "outputs": self._predict(msg.get("inputs") or {})}
            except (Overloaded, DeadlineExceeded) as e:
                logger.warning("predict shed: %s", e)
                return {"type": "error", "message": "{}: {}".format(type(e).__name__, e)}
            except Exception as e:
                logger.exception("predict failed")
                return {"type": "error", "message": "{}: {}".format(type(e).__name__, e)}
        return {"type": "error", "message": "unknown message type {!r}".format(kind)}

    def _predict(self, inputs):
        import numpy as np

        arrays = {name: np.asarray(vals) for name, vals in inputs.items()}
        outputs = self._submit(arrays)
        return {name: np.asarray(v).tolist() for name, v in outputs.items()}


class InferenceServer(ProtocolServer):
    """Serve one exported model bundle over TCP.

    Predictions funnel through the coalescing :class:`_Predictor`. The
    predictor slot is hot-swappable: :meth:`swap_predictor` installs a new
    one atomically (the serving mesh's zero-downtime model swap) while
    requests already dispatched drain on the old one."""

    def __init__(self, export_dir, host="", port=0, max_threads=None, trusted_builder=None):
        from tensorflowonspark_tpu.train import export

        self.export_dir = export_dir
        predict_fn, params, model_state = export.load_model(
            export_dir, trusted_builder=trusted_builder
        )
        self._pred_lock = threading.Lock()
        self._predictor = _Predictor(predict_fn, params, model_state)
        ProtocolServer.__init__(self, host=host, port=port, max_threads=max_threads)

    def swap_predictor(self, predictor, export_dir=None):
        """Atomically install ``predictor`` (zero-downtime hot swap) and
        return the old one. Requests already dispatched keep draining on
        the old predictor; the caller stops it after the flip."""
        with self._pred_lock:
            old = self._predictor
            self._predictor = predictor
            if export_dir is not None:
                self.export_dir = export_dir
        return old

    def warm_spec(self):
        """Column signature of the newest request seen by the current
        predictor — what a hot-swap warm-up predict should look like."""
        with self._pred_lock:
            predictor = self._predictor
        return predictor.warm_spec()

    def _submit(self, arrays):
        with self._pred_lock:
            predictor = self._predictor
        return predictor.submit(arrays)

    def _info(self):
        return {"type": "info", "export_dir": self.export_dir, "ready": True}

    def _stop_workload(self):
        with self._pred_lock:
            predictor = self._predictor
        predictor.stop()


class InferenceClient:
    """Python twin of the JVM client (jvm/.../InferenceClient.java).

    Transient failures are absorbed by a shared
    :class:`~tensorflowonspark_tpu.resilience.RetryPolicy`: a dropped
    connection is re-dialed and the request re-sent (prediction is
    stateless, so replay is safe), and an ``Overloaded`` shed reply is
    retried after backoff — the client half of the server's load-shedding
    contract. Pass ``retry=RetryPolicy(max_attempts=1)`` for the old
    fail-fast behavior. Non-transient error replies (bad inputs, model
    failures) raise immediately."""

    def __init__(self, address, timeout=120, retry=None):
        self.address = (address[0], int(address[1]))
        self.timeout = timeout
        self._sock = None
        self._msock = None
        self._policy = retry if retry is not None else resilience.RetryPolicy(
            max_attempts=3,
            backoff=resilience.Backoff(base=0.2, factor=2.0, max_delay=2.0, jitter=0.5),
            retry_on=(OSError, Overloaded),
            name="inference-client",
        )
        self._connect()

    def _connect(self):
        self._sock = socket.create_connection(self.address, timeout=self.timeout)
        self._msock = MessageSocket(self._sock)

    def _reset(self):
        if self._msock is not None:
            self._msock.close()
        self._sock = None
        self._msock = None

    @staticmethod
    def _check_reply(reply):
        if reply.get("type") == "error":
            message = str(reply.get("message") or "")
            if message.startswith("Overloaded"):
                raise Overloaded(message)  # transient shed: retryable
            raise RuntimeError(message)
        return reply

    def _roundtrip(self, msg):
        if self._msock is None:
            self._connect()
        try:
            self._msock.send(msg)
            reply = self._msock.recv()
        except OSError:
            self._reset()
            raise
        if reply is None:
            self._reset()
            raise ConnectionError("inference server closed the connection")
        return self._check_reply(reply)

    def _call(self, fn, *args):
        """Run a protocol roundtrip under the retry policy. When the budget
        is exhausted, the final error NAMES the server address, attempt
        count, and elapsed budget (the contract the reservation client's
        driver-restart path set) instead of surfacing the bare last error."""
        import time as _time

        started = _time.monotonic()
        try:
            return self._policy.call(fn, *args)
        except Overloaded as e:
            elapsed = _time.monotonic() - started
            raise Overloaded(
                "Overloaded: inference server at {}:{} kept shedding after {} "
                "attempt(s) over {:.1f}s: {}".format(
                    self.address[0] or "127.0.0.1", self.address[1],
                    self._policy.max_attempts, elapsed, e,
                )
            ) from e
        except (OSError, resilience.DeadlineExceeded) as e:
            elapsed = _time.monotonic() - started
            raise ConnectionError(
                "inference server at {}:{} unreachable after {} attempt(s) "
                "over {:.1f}s: {}".format(
                    self.address[0] or "127.0.0.1", self.address[1],
                    self._policy.max_attempts, elapsed, e,
                )
            ) from e

    def _request(self, msg):
        return self._call(self._roundtrip, msg)

    def ping(self):
        return self._request({"type": "ping"})["type"] == "pong"

    def info(self):
        return self._request({"type": "info"})

    def predict(self, **inputs):
        """Column name → nested lists / numpy arrays; returns dict of lists."""
        inputs = {
            k: v.tolist() if hasattr(v, "tolist") else v for k, v in inputs.items()
        }
        return self._request({"type": "predict", "inputs": inputs})["outputs"]

    def predict_binary(self, **inputs):
        """Binary tensor lane: numpy arrays in, numpy arrays out — no JSON
        text encoding of the payloads (see module docstring)."""
        import numpy as np

        arrays = {k: np.asarray(v) for k, v in inputs.items()}
        columns, payload = _arrays_to_columns(arrays)

        def _round():
            if self._msock is None:
                self._connect()
            try:
                self._msock.send({"type": "predict_binary", "columns": columns})
                self._msock.send_raw(payload)
                reply = self._msock.recv()
                if reply is None:
                    self._reset()
                    raise ConnectionError("inference server closed the connection")
                self._check_reply(reply)  # error replies carry no raw frame
                out_payload = self._msock.recv_raw(MAX_BINARY_FRAME)
                if out_payload is None:
                    self._reset()
                    raise ConnectionError("inference server closed mid-reply")
            except OSError:
                self._reset()
                raise
            return _columns_to_arrays(reply["columns"], out_payload)

        return self._call(_round)

    def close(self):
        self._reset()


# -- batch inference CLI (Inference.scala analogue) ----------------------------


def _parse_mapping(pairs):
    out = {}
    for pair in pairs or []:
        if "=" not in pair:
            raise ValueError("mapping must be key=value, got {!r}".format(pair))
        k, v = pair.split("=", 1)
        out[k] = v
    return out


def run_batch_inference(
    tfrecords_dir,
    export_dir,
    output_dir,
    batch_size=128,
    input_mapping=None,
    output_mapping=None,
    out_format="json",
    server=None,
    trusted_builder=None,
):
    """TFRecord shards → bundle predictions → output shards (one output shard
    per input shard; ``json`` = one JSON object per record per line,
    ``tfrecord`` = serialized Examples). Reference ``Inference.scala:52-79``:
    loadTFRecords → TFModel.transform → write.json.

    ``input_mapping``: feature name → model input name (default: every
    non-bytes feature feeds an input of the same name). ``output_mapping``:
    model output name → output column name (default: keep names).
    ``server``: ``(host, port)`` of a running :class:`InferenceServer` —
    batches go over the binary tensor lane instead of loading the bundle
    in-process (what a JVM executor does; ``export_dir`` may be None then).
    """
    import numpy as np

    from tensorflowonspark_tpu import tfrecord

    if server is not None:
        client = InferenceClient(server)
        predictor = None

        def _submit(arrays):
            return client.predict_binary(**arrays)

        def _stop():
            client.close()
    else:
        from tensorflowonspark_tpu.train import export

        predict_fn, params, model_state = export.load_model(
            export_dir, trusted_builder=trusted_builder
        )
        predictor = _Predictor(predict_fn, params, model_state)
        _submit = predictor.submit
        _stop = predictor.stop
    shards = tfrecord.list_shards(tfrecords_dir)
    if not shards:
        raise FileNotFoundError("no TFRecord shards under {}".format(tfrecords_dir))
    os.makedirs(output_dir, exist_ok=True)
    in_map = dict(input_mapping or {})
    out_map = dict(output_mapping or {})
    total = 0

    def _rows_to_arrays(rows):
        cols = {}
        for name in rows[0]:
            if in_map and name not in in_map:
                continue
            vals = [r[name] for r in rows]
            if any(isinstance(v, (bytes, bytearray)) for v in vals[0]):
                continue  # bytes features are not numeric model inputs
            arr = np.asarray(vals)
            if arr.shape[-1] == 1:  # scalar features decode as length-1 lists
                arr = arr.reshape(arr.shape[:-1])
            cols[in_map.get(name, name)] = arr
        if not cols:
            raise ValueError(
                "no numeric input features in records (features: {})".format(sorted(rows[0]))
            )
        return cols

    def _emit(outputs, n):
        renamed = {out_map.get(name, name): np.asarray(v) for name, v in outputs.items()}
        for i in range(n):
            yield {name: np.asarray(v[i]).tolist() for name, v in renamed.items()}

    try:
        for shard in shards:
            rows = [
                {name: vals for name, (_kind, vals) in tfrecord.decode_example(rec).items()}
                for rec in tfrecord.read_records(shard)
            ]
            base = os.path.basename(shard)
            out_path = os.path.join(
                output_dir, base + (".jsonl" if out_format == "json" else "")
            )
            records_out = []
            for start in range(0, len(rows), batch_size):
                chunk = rows[start : start + batch_size]
                outputs = _submit(_rows_to_arrays(chunk))
                records_out.extend(_emit(outputs, len(chunk)))
            if out_format == "json":
                with open(out_path, "w") as f:
                    for rec in records_out:
                        f.write(json.dumps(rec) + "\n")
            else:
                with tfrecord.TFRecordWriter(out_path) as w:
                    for rec in records_out:
                        w.write(
                            tfrecord.encode_example(
                                {
                                    k: v if isinstance(v, list) else [v]
                                    for k, v in rec.items()
                                }
                            )
                        )
            total += len(records_out)
            logger.info("wrote %d predictions to %s", len(records_out), out_path)
    finally:
        _stop()
    return total


#: set by :func:`_wait_for_exit` while a blocking ``main()`` is serving;
#: tests set it to shut the CLI down as cleanly as a Ctrl-C would
_exit_event = None


def _wait_for_exit():
    global _exit_event
    _exit_event = threading.Event()
    try:
        _exit_event.wait()
    except KeyboardInterrupt:
        pass
    finally:
        _exit_event = None


def main(argv=None):
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    # round-2 compat: bare `--export_dir ...` means `serve` — but top-level
    # --help must still show BOTH subcommands
    if not argv:
        argv = ["serve"]
    elif argv[0].startswith("-") and argv[0] not in ("-h", "--help"):
        argv = ["serve"] + argv

    parser = argparse.ArgumentParser(description=__doc__)
    sub = parser.add_subparsers(dest="command", required=True)

    serve_p = sub.add_parser("serve", help="serve a bundle over TCP")
    serve_p.add_argument("--export_dir", required=True)
    serve_p.add_argument("--host", default="")
    serve_p.add_argument("--port", type=int, default=8500)
    serve_p.add_argument(
        "--metrics_port", type=int, default=0, metavar="PORT",
        help="serve Prometheus metrics (GET /metrics) and the raw snapshot "
             "(GET /metrics.json) on this port; 0 (default) disables the endpoint")
    serve_p.add_argument(
        "--trusted_builder", default=None, metavar="MODULE:ATTR",
        help="take the predict-fn builder from your own code instead of the "
             "bundle's pickle; with npz weights, nothing from --export_dir "
             "is unpickled (safe for untrusted storage). Without this flag "
             "the bundle is TRUSTED: loading it executes its pickled code.")

    mesh_p = sub.add_parser(
        "mesh", help="serve N replicas behind one routed, hedging endpoint"
    )
    mesh_p.add_argument("--export_dir", required=True,
                        help="bundle dir or serving_mesh generation-pointer dir")
    mesh_p.add_argument("--replicas", type=int, default=3)
    mesh_p.add_argument("--host", default="")
    mesh_p.add_argument("--port", type=int, default=8500,
                        help="the routed frontend's port (replicas bind ephemeral ports)")
    mesh_p.add_argument(
        "--metrics_port", type=int, default=0, metavar="PORT",
        help="serve Prometheus metrics on this port; the snapshot includes "
             "the mesh gauges (serving_replicas_active etc.), so scraping "
             "any mesh process shows replica health; 0 disables")
    mesh_p.add_argument("--hedge_ms", type=float, default=0.0,
                        help="hedge a request to a second replica when the first "
                             "has not answered within this many ms; 0 disables")
    mesh_p.add_argument("--trusted_builder", default=None, metavar="MODULE:ATTR",
                        help="safe-load lane for --export_dir (see serve --help)")

    infer_p = sub.add_parser("infer", help="batch inference: TFRecords -> prediction shards")
    infer_p.add_argument("--tfrecords", required=True, help="input TFRecord shard dir")
    infer_p.add_argument("--export_dir", default=None,
                         help="bundle dir (in-process inference; omit with --server)")
    infer_p.add_argument("--output", required=True, help="output dir for prediction shards")
    infer_p.add_argument("--batch_size", type=int, default=128)
    infer_p.add_argument("--format", choices=["json", "tfrecord"], default="json")
    infer_p.add_argument("--input_mapping", nargs="*", default=None, metavar="FEATURE=TENSOR")
    infer_p.add_argument("--output_mapping", nargs="*", default=None, metavar="TENSOR=COLUMN")
    infer_p.add_argument("--server", default=None, metavar="HOST:PORT",
                         help="route batches to a running InferenceServer over "
                              "the binary tensor lane instead of loading the bundle")
    infer_p.add_argument("--trusted_builder", default=None, metavar="MODULE:ATTR",
                         help="safe-load lane for --export_dir (see serve --help)")

    args = parser.parse_args(argv)
    from tensorflowonspark_tpu import util

    util.setup_logging()

    if args.command == "infer":
        if args.server is None and args.export_dir is None:
            infer_p.error("one of --export_dir / --server is required")
        server_addr = None
        if args.server is not None:
            host, _, port = args.server.rpartition(":")
            if not port.isdigit():
                infer_p.error("--server must be HOST:PORT, got {!r}".format(args.server))
            server_addr = (host or "127.0.0.1", int(port))
        total = run_batch_inference(
            args.tfrecords, args.export_dir, args.output,
            batch_size=args.batch_size,
            input_mapping=_parse_mapping(args.input_mapping),
            output_mapping=_parse_mapping(args.output_mapping),
            out_format=args.format,
            server=server_addr,
            trusted_builder=args.trusted_builder,
        )
        print(json.dumps({"inferred": total, "output": args.output}), flush=True)
        return

    if args.command == "mesh":
        from tensorflowonspark_tpu import serving_mesh

        mesh = serving_mesh.ServingMesh(
            args.export_dir, replicas=args.replicas,
            trusted_builder=args.trusted_builder,
        )
        mesh.start()
        router = mesh.router(
            hedge_after=args.hedge_ms / 1000.0 if args.hedge_ms > 0 else None
        )
        front = serving_mesh.MeshFrontend(router, host=args.host, port=args.port)
        host, port = front.start()
        metrics_server = None
        if args.metrics_port:
            from tensorflowonspark_tpu.obs import exporter

            # the process-global snapshot carries the mesh gauges
            # (serving_replicas_active, failover/hedge/swap counters), so a
            # scrape of this endpoint shows mesh health, not just one replica
            metrics_server = exporter.MetricsHTTPServer(
                obs.snapshot, host=args.host, port=args.metrics_port
            ).start()
        print(
            json.dumps(
                {
                    "serving": args.export_dir,
                    "mesh": True,
                    "replicas": args.replicas,
                    "host": host or "0.0.0.0",
                    "port": port,
                    "metrics_port": metrics_server.address[1] if metrics_server else None,
                }
            ),
            flush=True,
        )
        _wait_for_exit()
        if metrics_server is not None:
            metrics_server.stop()
        front.stop()
        router.close()
        mesh.stop()
        return

    server = InferenceServer(
        args.export_dir, args.host, args.port, trusted_builder=args.trusted_builder
    )
    host, port = server.start()
    metrics_server = None
    if args.metrics_port:
        from tensorflowonspark_tpu.obs import exporter

        metrics_server = exporter.MetricsHTTPServer(
            obs.snapshot, host=args.host, port=args.metrics_port
        ).start()
    print(
        json.dumps(
            {
                "serving": args.export_dir,
                "host": host or "0.0.0.0",
                "port": port,
                "metrics_port": metrics_server.address[1] if metrics_server else None,
            }
        ),
        flush=True,
    )
    _wait_for_exit()
    if metrics_server is not None:
        metrics_server.stop()
    server.stop()


if __name__ == "__main__":
    main()
