"""Model-bundle inference server — the JVM-inference equivalent.

The reference shipped a Scala/JNI stack so JVM Spark jobs could run batch
inference without Python (/root/reference/src/main/scala/com/yahoo/
tensorflowonspark/Inference.scala:17, TFModel.scala:38 — SavedModelBundle via
libtensorflow). A jax model has no JNI runtime to embed, so the TPU-native
equivalent is a host RPC: this server owns the model bundle (and the TPU
chips) in a Python process, and any JVM executor talks to it over a tiny
length-prefixed JSON protocol (``jvm/`` ships a dependency-free Java client
for Spark mapPartitions; the wire format is specified in jvm/README.md).

Protocol (4-byte big-endian length + UTF-8 JSON, same framing as the
reservation control plane):

* ``{"type": "ping"}`` → ``{"type": "pong"}``
* ``{"type": "info"}`` → ``{"type": "info", "export_dir": ..., "ready": true}``
* ``{"type": "predict", "inputs": {name: nested-lists, ...}}`` →
  ``{"type": "result", "outputs": {name: nested-lists, ...}}``
* anything else / failure → ``{"type": "error", "message": ...}``

Start standalone:  ``python -m tensorflowonspark_tpu.serving --export_dir
/path/bundle --port 8500``
"""

import argparse
import json
import logging
import socket
import threading

from tensorflowonspark_tpu.reservation import MessageSocket

logger = logging.getLogger(__name__)


class InferenceServer:
    """Serve one exported model bundle over TCP (thread per connection)."""

    def __init__(self, export_dir, host="", port=0):
        from tensorflowonspark_tpu.train import export

        self.export_dir = export_dir
        predict_fn, params, model_state = export.load_model(export_dir)
        self._predict_fn = predict_fn
        self._params = params
        self._model_state = model_state
        self._lock = threading.Lock()  # predictions serialized onto the chips
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        self.address = self._sock.getsockname()
        self._shutdown = threading.Event()
        self._thread = None

    def start(self):
        self._thread = threading.Thread(target=self._serve, name="tos-serving", daemon=True)
        self._thread.start()
        logger.info("inference server for %s at %s", self.export_dir, self.address)
        return self.address

    def stop(self):
        self._shutdown.set()
        try:
            with socket.create_connection(("127.0.0.1", self.address[1]), timeout=1):
                pass
        except OSError:
            pass
        if self._thread is not None:
            self._thread.join(timeout=10)
        try:
            self._sock.close()
        except OSError:
            pass

    # -- internals ------------------------------------------------------------

    def _serve(self):
        while not self._shutdown.is_set():
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                return
            if self._shutdown.is_set():
                conn.close()
                return
            threading.Thread(
                target=self._handle_conn, args=(conn,), daemon=True
            ).start()

    def _handle_conn(self, conn):
        msock = MessageSocket(conn)
        try:
            while True:
                try:
                    msg = msock.recv()
                except (OSError, ValueError):
                    return
                if msg is None:
                    return
                try:
                    msock.send(self._handle(msg))
                except OSError:
                    return
        finally:
            msock.close()

    def _handle(self, msg):
        kind = msg.get("type") if isinstance(msg, dict) else None
        if kind == "ping":
            return {"type": "pong"}
        if kind == "info":
            return {"type": "info", "export_dir": self.export_dir, "ready": True}
        if kind == "predict":
            try:
                return {"type": "result", "outputs": self._predict(msg.get("inputs") or {})}
            except Exception as e:
                logger.exception("predict failed")
                return {"type": "error", "message": "{}: {}".format(type(e).__name__, e)}
        return {"type": "error", "message": "unknown message type {!r}".format(kind)}

    def _predict(self, inputs):
        import numpy as np

        arrays = {name: np.asarray(vals) for name, vals in inputs.items()}
        with self._lock:
            outputs = self._predict_fn(self._params, self._model_state, arrays)
        if not isinstance(outputs, dict):
            outputs = {"output": outputs}
        return {name: np.asarray(v).tolist() for name, v in outputs.items()}


class InferenceClient:
    """Python twin of the JVM client (jvm/.../InferenceClient.java)."""

    def __init__(self, address, timeout=120):
        self.address = (address[0], int(address[1]))
        self.timeout = timeout
        self._sock = socket.create_connection(self.address, timeout=timeout)
        self._msock = MessageSocket(self._sock)

    def _request(self, msg):
        self._msock.send(msg)
        reply = self._msock.recv()
        if reply is None:
            raise ConnectionError("inference server closed the connection")
        if reply.get("type") == "error":
            raise RuntimeError(reply.get("message"))
        return reply

    def ping(self):
        return self._request({"type": "ping"})["type"] == "pong"

    def info(self):
        return self._request({"type": "info"})

    def predict(self, **inputs):
        """Column name → nested lists / numpy arrays; returns dict of lists."""
        inputs = {
            k: v.tolist() if hasattr(v, "tolist") else v for k, v in inputs.items()
        }
        return self._request({"type": "predict", "inputs": inputs})["outputs"]

    def close(self):
        self._msock.close()


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--export_dir", required=True)
    parser.add_argument("--host", default="")
    parser.add_argument("--port", type=int, default=8500)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)
    server = InferenceServer(args.export_dir, args.host, args.port)
    host, port = server.start()
    print(json.dumps({"serving": args.export_dir, "host": host or "0.0.0.0", "port": port}), flush=True)
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        server.stop()


if __name__ == "__main__":
    main()
